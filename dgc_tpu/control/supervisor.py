"""Restart supervisor as a library (docs/RESILIENCE.md §"Elastic restart",
docs/TELEMETRY.md §"Control plane").

The launch / exponential-backoff / progress-watch loop that used to live
inside ``scripts/supervise.py`` — extracted so the control plane
(:mod:`dgc_tpu.control.plane`) can own N of them concurrently, one thread
each. ``scripts/supervise.py`` remains the thin single-run CLI over this
class with its flag surface and event schema unchanged.

Mechanics (shared by CLI and control plane):

* ``env_file`` is re-read before EVERY launch and its ``KEY=VALUE`` lines
  override the child environment — the cluster manager's (and the control
  plane's) hook for publishing a new cohort spec
  (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
  ``JAX_PROCESS_ID``) after a slice comes back with a different shape.
* a child exit code in ``success_codes`` (default ``0``) ends the loop
  successfully; a code in ``quarantine_codes`` (default ``70``,
  EX_SOFTWARE — the nonfinite-streak abort in train.py) quarantines the
  run: no relaunch, artifacts kept for post-mortem. Exit code 75
  (EX_TEMPFAIL) is the convention for "preempted after a clean emergency
  save — relaunch me"; a code in ``surgery_codes`` (default ``76``,
  cohort surgery — docs/RESILIENCE.md §"Cohort surgery") applies the
  workers' ``surgery_exit.json`` record (publish the shrunk cohort spec,
  remap this survivor's ``JAX_PROCESS_ID`` around the excised slot, or
  self-quarantine when THIS worker is the one cut out) and relaunches
  immediately with the retry budget reset; anything else relaunches
  against the retry budget.
* retries are budgeted against *progress*: when ``watch`` names the
  checkpoint directory and its ``latest.json`` changed since the last
  launch (an emergency save counts), the failure counter resets.
* every event is stamped with a per-supervisor ``run_id`` and the cohort
  spec from the latest env read, flushed per event; the same ``run_id``
  is exported to the child as ``DGC_RUN_ID`` so its telemetry header and
  the supervise stream agree on which run this is.

Library extensions on top of the CLI behavior — all host-only, called
from the control plane's thread:

* ``on_event`` — callback receiving every event record (the plane's
  fleet-wide stream re-stamps and merges them).
* ``request_restart()`` — SIGTERM the child *without* stopping the loop:
  the child takes its emergency-save path, exits 75, and the loop
  relaunches it (with whatever cohort spec the env-file now publishes).
* ``request_stop()`` — SIGTERM the child and stop relaunching (the CLI's
  signal handler routes here).
* ``quarantine(reason)`` — stop relaunching but keep artifacts; also
  entered automatically on a ``quarantine_codes`` exit.
* ``request_kill()`` — SIGKILL the child (the watchdog escalation tier:
  a SIGTERM assumes a responsive process; a hung one gets no courtesy).
* ``hang_timeout``/``heartbeat`` — supervisor-side hang escalation: the
  child's :class:`~dgc_tpu.resilience.preempt.Watchdog` refreshes the
  heartbeat file's mtime each step (the path is exported to the child as
  ``DGC_HEARTBEAT``); a monitor thread SIGKILLs + quarantines the child
  once the mtime goes stale past ``hang_timeout`` seconds. The
  survivors' blocked agreement collective then errors out and they take
  the exit-76 surgery path.
"""

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

from dgc_tpu.telemetry.sink import JsonlAppender

__all__ = ["parse_env_file", "checkpoint_progress", "COHORT_KEYS",
           "default_events_path", "Supervisor", "main"]


def parse_env_file(path):
    """KEY=VALUE lines (blank lines and ``#`` comments ignored)."""
    out = {}
    if not path or not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def checkpoint_progress(watch_dir):
    """(epoch, mtime) of ``latest.json``; None when absent/unreadable."""
    if not watch_dir:
        return None
    path = os.path.join(watch_dir, "latest.json")
    try:
        with open(path) as f:
            epoch = json.load(f).get("epoch")
        return (epoch, os.path.getmtime(path))
    except (OSError, ValueError):
        return None


#: cohort-spec env keys stamped into every event (the monitor's view of
#: the world shape each launch ran under)
COHORT_KEYS = ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
               "JAX_COORDINATOR_ADDRESS")


def default_events_path(watch):
    """``supervise_events.jsonl`` next to the watched checkpoint dir —
    i.e. under the run dir, where the live monitor looks for it."""
    if not watch:
        return None
    return os.path.join(os.path.dirname(os.path.abspath(watch)),
                        "supervise_events.jsonl")


class Supervisor:
    """Bounded-retry relaunch loop for one training run.

    ``run()`` blocks until the run ends (done / stopped / gave up /
    quarantined) and returns the final child exit code (0 on success) —
    run it on a dedicated thread when supervising a fleet. All the
    ``request_*`` methods are safe to call from another thread.
    """

    def __init__(self, cmd, retries=5, backoff=5.0, backoff_max=300.0,
                 env_file=None, watch=None, events=None,
                 success_codes=(0,), quarantine_codes=(70,),
                 surgery_codes=(76,), hang_timeout=None, heartbeat=None,
                 name=None, extra_env=None, on_event=None):
        self.cmd = list(cmd)
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.backoff_max = float(backoff_max)
        self.env_file = env_file
        self.watch = watch
        self.events_path = events
        self.success_codes = set(success_codes)
        self.quarantine_codes = set(quarantine_codes or ())
        self.surgery_codes = set(surgery_codes or ())
        self.hang_timeout = (float(hang_timeout)
                             if hang_timeout else None)
        self.heartbeat = heartbeat
        if self.hang_timeout and not self.heartbeat and watch:
            self.heartbeat = os.path.join(
                os.path.dirname(os.path.abspath(watch)), "heartbeat")
        self.name = name
        self.extra_env = dict(extra_env or {})
        self.on_event = on_event
        self.child = None
        self.shutting_down = False
        self.quarantined = None     # reason string once quarantined
        self.launches = 0
        self.last_rc = None
        self._surgery_applied_t = None   # dedup: apply each record once
        self.state = "idle"         # running|done|stopped|gave_up|quarantined
        # one id per supervisor lifetime: every relaunch of this run
        # shares it, a fresh supervisor gets a fresh one
        stamp = time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}"
        self.run_id = f"{name}-{stamp}" if name else stamp
        self.cohort = {k: os.environ.get(k) for k in COHORT_KEYS
                       if os.environ.get(k) is not None}
        self._events = JsonlAppender(events) if events else None
        # decorrelated-jitter backoff state: the previous delay seeds the
        # next draw's upper bound. Per-instance RNG so tests can seed it
        # and a fleet of supervisors never shares a stream.
        self._last_delay = 0.0
        self._rng = random.Random()
        self._wake = threading.Event()
        # guards child/quarantined/shutting_down/launches/cohort — shared
        # between run(), the hang-watch thread, and cross-thread
        # request_*() callers. Never held across Popen/wait/event I/O.
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ #
    # events                                                             #
    # ------------------------------------------------------------------ #

    def event(self, kind, **fields):
        with self._lock:
            launches, cohort = self.launches, dict(self.cohort)
        rec = dict(fields, event=kind, t=time.time(),
                   launches=launches, run_id=self.run_id,
                   cohort=cohort)
        tag = f"[supervise:{self.name}]" if self.name else "[supervise]"
        line = json.dumps(rec)
        print(f"{tag} {line}", flush=True)
        if self._events is not None:
            # persistent handle, flushed per event: a tailing monitor
            # sees every launch/relaunch as it happens, and relaunch
            # churn doesn't reopen the file hundreds of times
            self._events.write(rec)
        if self.on_event is not None:
            try:
                self.on_event(dict(rec))
            except Exception as e:  # a broken stream must not kill the run
                print(f"{tag} on_event failed: {e!r}", flush=True)

    # ------------------------------------------------------------------ #
    # cross-thread controls                                              #
    # ------------------------------------------------------------------ #

    def _signal_child(self, signum=signal.SIGTERM):
        with self._lock:
            child = self.child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
                return True
            except OSError:
                pass
        return False

    def request_restart(self, reason=None):
        """SIGTERM the child WITHOUT stopping the loop: it emergency-saves,
        exits 75, and relaunches under the current env-file cohort spec.
        Returns True when the signal was delivered to a live child."""
        delivered = self._signal_child(signal.SIGTERM)
        self.event("restart_request", reason=reason, delivered=delivered)
        return delivered

    def request_kill(self, reason="hang"):
        """SIGKILL the child — the watchdog escalation tier for a hung
        process (SIGTERM would route to a signal handler the process may
        never service again). Quarantines the run first so the loop
        holds the corpse for post-mortem instead of relaunching it."""
        with self._lock:
            if self.quarantined is None:
                self.quarantined = f"hang:{reason}"
        delivered = self._signal_child(signal.SIGKILL)
        self.event("hang_kill", reason=reason, delivered=delivered)
        return delivered

    def request_stop(self, reason="signal"):
        """Stop relaunching and pass SIGTERM through so the child takes
        its emergency-save path (the CLI signal handler routes here)."""
        with self._lock:
            self.shutting_down = True
        self._signal_child(signal.SIGTERM)
        self._wake.set()

    def quarantine(self, reason):
        """Stop relaunching but keep every artifact (telemetry, flight
        dump, checkpoints) for post-mortem. Does NOT kill a live child —
        a run is quarantined for what it did, not executed for it."""
        with self._lock:
            if self.quarantined is None:
                self.quarantined = str(reason)
        self._wake.set()

    def _forward(self, signum, frame):
        # the scheduler is tearing US down: stop relaunching, pass the
        # signal through so the child takes its emergency-save path
        with self._lock:
            self.shutting_down = True
        self._signal_child(signum)
        self._wake.set()

    # ------------------------------------------------------------------ #
    # hang escalation + surgery (docs/RESILIENCE.md §"Cohort surgery")   #
    # ------------------------------------------------------------------ #

    def _watch_hang(self, child, launched_at):
        """Monitor thread, one per launch: SIGKILL + quarantine the
        child once the heartbeat file's mtime goes stale past
        ``hang_timeout`` (startup counts from launch time, so a long
        first compile needs a budget to match)."""
        poll = max(0.05, min(1.0, self.hang_timeout / 4.0))
        while child.poll() is None:
            time.sleep(poll)
            with self._lock:
                current = self.child
            if child.poll() is not None or current is not child:
                return
            try:
                last = os.path.getmtime(self.heartbeat)
            except OSError:
                last = None
            ref = max(launched_at, last) if last is not None else launched_at
            stale = time.time() - ref
            if stale > self.hang_timeout:
                self.request_kill(reason=f"no heartbeat for {stale:.1f}s "
                                         f"(budget {self.hang_timeout}s)")
                return

    def _apply_surgery(self, rc):
        """Exit-76 bookkeeping, applied once per exit record: publish
        the shrunk cohort spec (idempotent — derived from the record's
        FROM-world, so every survivor's supervisor computes the same
        value and racing publishes agree), remap this run's
        ``JAX_PROCESS_ID`` around the excised slot, and detect
        self-excision (this run IS the target → quarantine, the cohort
        spec no longer has a seat for it)."""
        from dgc_tpu.resilience import surgery as _surgery
        info = {}
        rec = None
        if self.watch:
            rec = _surgery.read_exit_record(
                os.path.join(self.watch, _surgery.EXIT_RECORD))
        if not rec or rec.get("t") == self._surgery_applied_t:
            return info
        self._surgery_applied_t = rec.get("t")
        target = int(rec.get("target", -1))
        info.update(verdict=rec.get("verdict"), target=target,
                    lost=bool(rec.get("lost")))
        try:
            world = int(rec.get("world") or 0)
        except (TypeError, ValueError):
            world = 0
        updates = _surgery.shrink_updates(world, target)
        if updates:
            info["world"] = int(updates["JAX_NUM_PROCESSES"])
            if self.env_file:
                from dgc_tpu.control.actions import publish_env
                publish_env(self.env_file, updates)
                info["published"] = updates
        pid = self.extra_env.get("JAX_PROCESS_ID",
                                 os.environ.get("JAX_PROCESS_ID"))
        if pid is not None and target >= 0:
            new_pid = _surgery.remap_process_id(pid, target)
            if new_pid is None:
                info["excised"] = True
            elif new_pid != int(pid):
                self.extra_env["JAX_PROCESS_ID"] = str(new_pid)
                info["process_id"] = new_pid
        return info

    # ------------------------------------------------------------------ #
    # the loop                                                           #
    # ------------------------------------------------------------------ #

    def _next_delay(self, failures):
        """Decorrelated-jitter backoff: the first retry waits exactly
        ``backoff``; each later delay draws uniformly from
        ``[backoff, min(3 * previous, backoff_max)]``. A correlated fleet
        failure (one bad switch kills every child at once) then spreads
        its relaunch storm out instead of hammering the coordinator in
        exponential lockstep — same expected growth as doubling, none of
        the synchronization. Checkpoint progress resets ``failures`` and
        with it the spread."""
        if failures <= 1:
            self._last_delay = 0.0
        lo = min(self.backoff, self.backoff_max)
        hi = min(max(3.0 * self._last_delay, lo), self.backoff_max)
        delay = self._rng.uniform(lo, hi) if hi > lo else lo
        self._last_delay = delay
        return delay

    def run(self, install_signals=None):
        """Supervise until the run ends; returns the final exit code.
        ``install_signals`` defaults to True only on the main thread
        (signal.signal is main-thread-only; plane threads skip it)."""
        if install_signals is None:
            install_signals = (threading.current_thread()
                               is threading.main_thread())
        if install_signals:
            for s in (signal.SIGTERM, signal.SIGINT):
                signal.signal(s, self._forward)
        self.state = "running"
        failures = 0
        while True:
            env = dict(os.environ)
            env.update(self.extra_env)      # the run's baseline env ...
            overrides = parse_env_file(self.env_file)
            env.update(overrides)           # ... under the LIVE cohort spec
            # the child's telemetry header and this event stream must
            # agree on which run this is
            env["DGC_RUN_ID"] = self.run_id
            # latest cohort spec (the env-file may have re-shaped the
            # world since the last launch) rides every event from here on
            cohort = {k: env.get(k) for k in COHORT_KEYS
                      if env.get(k) is not None}
            with self._lock:
                self.cohort = cohort
            if self.heartbeat:
                # the child's Watchdog refreshes this file's mtime; the
                # hang monitor below is its supervisor-side consumer
                env["DGC_HEARTBEAT"] = self.heartbeat
            before = checkpoint_progress(self.watch)
            with self._lock:
                self.launches += 1
            self.event("launch", cmd=self.cmd,
                       world=env.get("JAX_NUM_PROCESSES"),
                       env_overrides=sorted(overrides))
            t0 = time.time()
            child = subprocess.Popen(self.cmd, env=env)
            with self._lock:
                self.child = child
            if self.hang_timeout and self.heartbeat:
                threading.Thread(target=self._watch_hang,
                                 args=(child, t0),
                                 name="dgc-hang-watch", daemon=True).start()
            rc = child.wait()
            with self._lock:
                self.child = None
            self.last_rc = rc
            elapsed = time.time() - t0
            if rc in self.success_codes:
                self.state = "done"
                self.event("done", rc=rc, elapsed=elapsed)
                return 0
            after = checkpoint_progress(self.watch)
            progressed = after is not None and after != before
            if progressed:
                # visible checkpoint progress (a preemption's emergency
                # save included) is not a failure: the retry budget
                # guards against crash loops, not against preemptions
                failures = 0
            else:
                failures += 1
            with self._lock:
                surgery_due = (rc in self.surgery_codes
                               and self.quarantined is None
                               and not self.shutting_down)
            if surgery_due:
                info = self._apply_surgery(rc)
                if info.pop("excised", False):
                    # the shrunk spec has no seat for this worker: it is
                    # the one being cut out — hold it for the readmit
                    # probe instead of relaunching into a dead slot
                    with self._lock:
                        self.quarantined = \
                            f"excised:{info.get('verdict') or rc}"
                else:
                    failures = 0    # a deliberate transition, not a crash
                    self.event("surgery", rc=rc, elapsed=elapsed, **info)
                    continue
            with self._lock:
                if (rc in self.quarantine_codes
                        and self.quarantined is None):
                    self.quarantined = f"exit:{rc}"
                quarantined = self.quarantined
                stopping = self.shutting_down
            if quarantined is not None:
                self.state = "quarantined"
                self.event("quarantined", rc=rc, reason=quarantined)
                return rc
            if stopping:
                self.state = "stopped"
                self.event("stopped", rc=rc, reason="signal")
                return rc
            if failures > self.retries:
                self.state = "gave_up"
                self.event("giveup", rc=rc, failures=failures,
                           retries=self.retries)
                return rc
            delay = self._next_delay(failures)
            self.event("relaunch", rc=rc, elapsed=elapsed,
                       failures=failures, delay=delay,
                       progressed=progressed)
            # interruptible backoff: a stop/quarantine lands immediately
            # instead of after the full delay
            self._wake.wait(delay)
            self._wake.clear()
            with self._lock:
                quarantined = self.quarantined
                stopping = self.shutting_down
            if quarantined is not None:
                self.state = "quarantined"
                self.event("quarantined", rc=rc, reason=quarantined)
                return rc
            if stopping:
                self.state = "stopped"
                self.event("stopped", rc=rc, reason="signal")
                return rc


def main(argv=None):
    """The ``scripts/supervise.py`` CLI: one run, this process's signals."""
    import argparse
    parser = argparse.ArgumentParser(
        description="Restart supervisor for elastic training "
                    "(docs/RESILIENCE.md §\"Elastic restart\").",
        usage="supervise.py [options] -- <training command ...>")
    parser.add_argument("--retries", type=int, default=5,
                        help="consecutive no-progress failures before "
                             "giving up (progress resets the count)")
    parser.add_argument("--backoff", type=float, default=5.0,
                        help="initial relaunch delay, doubled per "
                             "consecutive failure")
    parser.add_argument("--backoff-max", type=float, default=300.0)
    parser.add_argument("--env-file", default=None,
                        help="KEY=VALUE file re-read before every launch; "
                             "overrides the child environment (new cohort "
                             "spec goes here)")
    parser.add_argument("--watch", default=None,
                        help="checkpoint directory; progress in its "
                             "latest.json resets the retry budget")
    parser.add_argument("--events-out", default=None,
                        help="append one JSON line per supervisor event; "
                             "defaults to supervise_events.jsonl next to "
                             "the --watch dir (under the run dir)")
    parser.add_argument("--events", default=None,
                        help="legacy alias for --events-out (takes "
                             "precedence when both are given)")
    parser.add_argument("--success-codes", default="0",
                        help="comma-separated child exit codes that end "
                             "the loop successfully")
    parser.add_argument("--surgery-codes", default="76",
                        help="comma-separated child exit codes treated "
                             "as cohort surgery: apply surgery_exit.json "
                             "(shrunk spec + process-id remap) and "
                             "relaunch immediately (docs/RESILIENCE.md "
                             "§\"Cohort surgery\"); empty disables")
    parser.add_argument("--hang-timeout", type=float, default=None,
                        help="SIGKILL + quarantine the child when its "
                             "heartbeat file goes stale for this many "
                             "seconds (the watchdog escalation tier)")
    parser.add_argument("--heartbeat", default=None,
                        help="heartbeat file path (exported to the child "
                             "as DGC_HEARTBEAT; defaults to 'heartbeat' "
                             "next to the --watch dir)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- then the training command")
    args = parser.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("no training command given (put it after --)")
    events = (args.events or args.events_out
              or default_events_path(args.watch))
    sup = Supervisor(
        cmd, retries=args.retries, backoff=args.backoff,
        backoff_max=args.backoff_max, env_file=args.env_file,
        watch=args.watch, events=events,
        success_codes={int(c) for c in args.success_codes.split(",")},
        surgery_codes={int(c) for c in args.surgery_codes.split(",")
                       if c.strip()},
        hang_timeout=args.hang_timeout, heartbeat=args.heartbeat)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
