"""Declarative alert → remediation rules for the control plane.

A :class:`Rule` binds a *detector* — a pure function over one run's
monitor snapshot (:func:`dgc_tpu.telemetry.monitor.collect`) returning
evidence or ``None`` — to a named remediation from
:data:`dgc_tpu.telemetry.registry.CONTROL_ACTIONS`. The
:class:`RuleEngine` adds the operational hygiene every auto-remediation
needs:

* **persistence** (``min_hits``) — the detector must fire on that many
  *consecutive* ticks before the rule does; one noisy snapshot never
  restarts a run.
* **debounce** (``debounce_s``) — after firing, the rule stays quiet for
  a window so the remediation has time to take effect before the same
  evidence (which may persist through a restart) can fire it again.
* **budget** (``budget``) — a hard per-(run, rule) cap on firings for
  the plane's lifetime; a remediation that doesn't stick escalates to a
  human instead of flapping forever.

Suppressed firings (debounced or over budget) are counted and visible
via ``engine.suppressed`` — silence must be attributable too. The engine
takes ``now`` explicitly so tests drive it with a fake clock.

The table itself can come from a ``rules.toml`` file
(:func:`load_rules`) so an operator retunes thresholds or wires the
``adapt`` remediation without touching code; the code table
(:func:`default_rules`) stays the default.
"""

import math
from typing import Callable, Dict, NamedTuple, Optional, Tuple

__all__ = ["Rule", "RuleEngine", "default_rules", "load_rules",
           "DETECTORS", "detect_desync", "detect_straggler",
           "detect_quarantine", "detect_cohort_shrink", "detect_excise",
           "detect_readmit", "detect_stale_replica", "detect_autoscale"]


class Rule(NamedTuple):
    """One row of the remediation table."""
    name: str
    detect: Callable[[Dict], Optional[Dict]]
    action: str                 # a registry.CONTROL_ACTIONS name
    min_hits: int = 2           # consecutive detecting ticks before firing
    debounce_s: float = 60.0    # quiet window after a firing
    budget: int = 2             # lifetime firings per (run, rule)


# ---------------------------------------------------------------------- #
# detectors — tolerant by design: a half-collected snapshot (young run,  #
# torn shard, no supervise stream yet) must read as "no evidence", never #
# raise                                                                  #
# ---------------------------------------------------------------------- #

def detect_desync(snap: Dict) -> Optional[Dict]:
    """A worker's residual walked out of the cohort's rolling band
    (:func:`dgc_tpu.telemetry.fleet.detect_desync` verdict in the
    snapshot summary) — the silent-corruption signature. Remediation:
    restart the run so it restores from the last good checkpoint."""
    s = snap.get("summary") or {}
    alerts = s.get("desync_alerts") or 0
    workers = s.get("desync_workers") or []
    if alerts and workers:
        return {"kind": "desync", "alerts": int(alerts),
                "workers": list(workers), "first": s.get("desync_first")}
    return None


def detect_straggler(snap: Dict, min_share: float = 1.5,
                     min_gap_ms: float = 20.0) -> Optional[Dict]:
    """One worker persistently slower than the cohort mean by
    ``min_share`` (and trailing by at least ``min_gap_ms``) — the whole
    cohort runs at its pace. Remediation: publish a smaller cohort spec
    and elastically relaunch without it."""
    s = snap.get("summary") or {}
    share = s.get("straggler_share")
    gap = s.get("straggler_gap")
    worker = s.get("straggler")
    if (share is not None and gap is not None and worker is not None
            and math.isfinite(share) and share >= min_share
            and gap >= min_gap_ms):
        return {"kind": "straggler", "worker": int(worker),
                "share": float(share), "gap_ms": float(gap)}
    return None


def detect_quarantine(snap: Dict, max_nonfinite_rate: float = 0.5) \
        -> Optional[Dict]:
    """The run is numerically dead or crashed hard: a flight-recorder
    dump on disk, a nonfinite-streak abort (exit 70), or a saturated
    nonfinite guard rate. Remediation: quarantine — relaunching a run
    that diverges deterministically just burns the retry budget and
    overwrites the evidence."""
    flight = snap.get("flight") or {}
    if flight.get("reason"):
        return {"kind": "flight_dump", "reason": flight["reason"],
                "t_dump": flight.get("t_dump"),
                "records": flight.get("records")}
    last = snap.get("last_supervise") or {}
    if last.get("event") in ("relaunch", "quarantined", "giveup") \
            and last.get("rc") == 70:
        return {"kind": "nonfinite_abort", "rc": 70,
                "supervise_event": last.get("event")}
    guards = snap.get("guards") or {}
    rate = guards.get("nonfinite_rate")
    if rate is not None and rate > max_nonfinite_rate:
        return {"kind": "nonfinite_rate", "nonfinite_rate": float(rate),
                "skipped_steps": guards.get("skipped_steps")}
    return None


def detect_cohort_shrink(snap: Dict) -> Optional[Dict]:
    """Fewer hosts writing telemetry than the run's recorded cohort spec
    — a process died without its supervisor noticing (the others block in
    collectives at the next exchange). Remediation: publish the shrunken
    cohort through the env-file and elastically relaunch at W' = live."""
    static = snap.get("static") or {}
    want = static.get("num_processes")
    have = snap.get("num_hosts")
    try:
        want = int(want) if want is not None else None
    except (TypeError, ValueError):
        want = None
    if want and have and int(have) < want:
        return {"kind": "cohort_shrink", "live_hosts": int(have),
                "spec_processes": want}
    return None


def detect_excise(snap: Dict) -> Optional[Dict]:
    """A worker was SIGKILLed by the supervisor's hang-escalation tier
    (``hang_kill`` event, or the quarantine it left behind) — the
    survivors are already taking the exit-76 path. Remediation:
    ``excise`` — publish the order + shrunk cohort spec so the whole
    fleet's record of the surgery is explicit and audited
    (docs/RESILIENCE.md §"Cohort surgery")."""
    last = snap.get("last_supervise") or {}
    hang = last.get("event") == "hang_kill" or (
        last.get("event") == "quarantined"
        and str(last.get("reason", "")).startswith("hang:"))
    if not hang:
        return None
    ev: Dict = {"kind": "hang", "reason": last.get("reason")}
    cohort = last.get("cohort") or {}
    try:
        ev["worker"] = int(cohort.get("JAX_PROCESS_ID"))
    except (TypeError, ValueError):
        pass
    # FROM-world: the spec the hung child LAUNCHED under (the event's
    # cohort stamp) — by audit time the survivors' supervisors have
    # already shrunk the live env-file, and deriving from that would
    # shrink the cohort twice
    try:
        ev["world"] = int(cohort.get("JAX_NUM_PROCESSES"))
    except (TypeError, ValueError):
        plane_cohort = snap.get("cohort") or {}
        if plane_cohort.get("spec_world"):
            ev["world"] = int(plane_cohort["spec_world"])
    return ev


def detect_readmit(snap: Dict) -> Optional[Dict]:
    """A quarantined worker passed its re-init probe and the device-pool
    ledger holds freed capacity (``snap["cohort"]`` is the control
    plane's injected ledger view). Remediation: ``readmit`` — publish
    the grown cohort spec and relaunch the worker; the elastic 1:k
    split reshard deals it back into the error-feedback state."""
    cohort = snap.get("cohort") or {}
    probe = cohort.get("probe") or {}
    if not probe.get("passed") or not cohort.get("pool_free"):
        return None
    ev: Dict = {"kind": "readmit", "pool_free": int(cohort["pool_free"]),
                "probe_rc": probe.get("rc")}
    if probe.get("checksum"):
        ev["checksum"] = probe["checksum"]
    if cohort.get("spec_world"):
        ev["target_world"] = int(cohort["spec_world"]) + 1
    return ev


def detect_stale_replica(snap: Dict) -> Optional[Dict]:
    """A serving replica is unhealthy or past the stream's pinned
    ``max_lag`` bound (the monitor's serving lane,
    :func:`dgc_tpu.telemetry.fleet.serving_summary`) — it is serving a
    model the trainer has moved past, or it hit a gap/divergence the
    in-place delta path cannot repair. Remediation: ``resync`` — ask the
    exporter to rebase so the replica reloads a fresh full snapshot."""
    serving = snap.get("serving") or {}
    stale = serving.get("stale_replicas") or []
    if not stale:
        return None
    head = serving.get("head") or {}
    ev: Dict = {"kind": "stale_replica", "replicas": list(stale),
                "head": f"v{head.get('base_version')}:"
                        f"{head.get('latest_seq')}",
                "max_lag": head.get("max_lag")}
    recs = serving.get("replicas") or {}
    healths = {n: recs[n].get("health") for n in stale if n in recs}
    if healths:
        ev["health"] = healths
    if "max_staleness" in serving:
        ev["max_staleness"] = serving["max_staleness"]
    return ev


def detect_autoscale(snap: Dict, max_straggler_share: float = 1.5) \
        -> Optional[Dict]:
    """A healthy run with headroom (the gang scheduler's injected
    ``snap["sched"]`` view shows ``slots < slots_max``) that is making
    throughput (the summary's rate lane) and is NOT straggler-bound —
    giving a straggler-limited cohort another worker just adds another
    waiter. Remediation: ``admit`` a one-seat grow request; the
    scheduler grants it when slots free (preempting a lower-priority
    gang if the priority gap says so)."""
    sched = snap.get("sched") or {}
    slots = sched.get("slots")
    slots_max = sched.get("slots_max")
    try:
        slots, slots_max = int(slots), int(slots_max)
    except (TypeError, ValueError):
        return None
    if slots < 1 or slots >= slots_max:
        return None
    rate = snap.get("steps_per_s")
    try:
        rate = float(rate)
    except (TypeError, ValueError):
        return None
    if not math.isfinite(rate) or rate <= 0:
        return None    # no throughput signal: don't scale blind
    s = snap.get("summary") or {}
    share = s.get("straggler_share")
    if share is not None and math.isfinite(float(share)) \
            and float(share) >= max_straggler_share:
        return None    # straggler-bound: a new seat would just wait too
    return {"kind": "autoscale", "slots": slots, "slots_max": slots_max,
            "target_slots": slots + 1, "rate": rate}


def default_rules() -> Tuple[Rule, ...]:
    """The shipped remediation table (docs/TELEMETRY.md §"Control plane").
    Order matters: quarantine outranks everything — a numerically dead
    run must never be "fixed" by a restart rule on the same tick."""
    return (
        Rule("nonfinite-quarantine", detect_quarantine, "quarantine",
             min_hits=1, debounce_s=0.0, budget=1),
        Rule("desync-restart", detect_desync, "restart",
             min_hits=2, debounce_s=60.0, budget=2),
        Rule("straggler-relaunch", detect_straggler, "elastic_relaunch",
             min_hits=3, debounce_s=120.0, budget=1),
        Rule("cohort-shrink-relaunch", detect_cohort_shrink,
             "elastic_relaunch", min_hits=2, debounce_s=120.0, budget=2),
        Rule("hang-excise", detect_excise, "excise",
             min_hits=1, debounce_s=60.0, budget=2),
        Rule("probe-readmit", detect_readmit, "readmit",
             min_hits=1, debounce_s=60.0, budget=2),
        Rule("stale-replica-resync", detect_stale_replica, "resync",
             min_hits=2, debounce_s=30.0, budget=4),
        Rule("autoscale-admit", detect_autoscale, "admit",
             min_hits=3, debounce_s=300.0, budget=2),
    )


#: detector names usable from a ``rules.toml`` rule table
DETECTORS: Dict[str, Callable[[Dict], Optional[Dict]]] = {
    "desync": detect_desync,
    "straggler": detect_straggler,
    "quarantine": detect_quarantine,
    "cohort_shrink": detect_cohort_shrink,
    "excise": detect_excise,
    "readmit": detect_readmit,
    "stale_replica": detect_stale_replica,
    "autoscale": detect_autoscale,
}

#: the Rule fields a ``rules.toml`` table may set
_RULE_KEYS = {"name", "detector", "action", "min_hits", "debounce_s",
              "budget"}


def _toml_scalar(raw: str, path: str, lineno: int):
    """One TOML scalar: quoted string, int, or float."""
    if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "\"'":
        return raw[1:-1]
    for conv in (int, float):
        try:
            return conv(raw)
        except ValueError:
            pass
    raise ValueError(
        f"{path}:{lineno}: unsupported TOML value {raw!r} (the rule-table "
        "subset takes quoted strings, ints, and floats)")


def load_rules(path: str) -> Tuple[Rule, ...]:
    """Rule table from a ``rules.toml`` file — ``[[rule]]`` array-of-
    tables, one per row, e.g.::

        [[rule]]
        name = "straggler-adapt"
        detector = "straggler"     # a DETECTORS name
        action = "adapt"           # a registry.CONTROL_ACTIONS name
        min_hits = 3
        debounce_s = 120.0
        budget = 1

    Validated loudly: unknown detectors, actions, or keys raise — a
    typo'd table silently reverting to defaults would make the operator's
    intent a no-op. (Hand-rolled subset parser — ``[[rule]]`` headers and
    scalar ``key = value`` lines — because the pinned Python predates
    ``tomllib`` and the repo vendors no TOML library.)"""
    from dgc_tpu.telemetry import registry
    tables: list = []
    current: Optional[Dict] = None
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[rule]]":
                current = {}
                tables.append(current)
                continue
            if line.startswith("["):
                raise ValueError(
                    f"{path}:{lineno}: only [[rule]] tables are "
                    f"supported, got {line!r}")
            if current is None:
                raise ValueError(
                    f"{path}:{lineno}: key outside a [[rule]] table")
            key, sep, raw = (p.strip() for p in line.partition("="))
            if not sep or not key:
                raise ValueError(
                    f"{path}:{lineno}: expected key = value, got {line!r}")
            if raw[:1] not in "\"'" and "#" in raw:
                raw = raw.split("#", 1)[0].strip()
            current[key] = _toml_scalar(raw, path, lineno)
    if not tables:
        raise ValueError(f"{path}: no [[rule]] tables")
    rules = []
    for i, t in enumerate(tables, 1):
        missing = [k for k in ("name", "detector", "action") if k not in t]
        if missing:
            raise ValueError(f"{path}: rule #{i} missing keys {missing}")
        unknown = sorted(set(t) - _RULE_KEYS)
        if unknown:
            raise ValueError(
                f"{path}: rule {t['name']!r} has unknown keys {unknown} "
                f"(known: {sorted(_RULE_KEYS)})")
        det = t["detector"]
        if det not in DETECTORS:
            raise ValueError(
                f"{path}: rule {t['name']!r}: unknown detector {det!r} "
                f"(known: {sorted(DETECTORS)})")
        if t["action"] not in registry.control_action_names():
            raise ValueError(
                f"{path}: rule {t['name']!r}: unknown action "
                f"{t['action']!r} "
                f"(known: {list(registry.control_action_names())})")
        rules.append(Rule(
            name=str(t["name"]), detect=DETECTORS[det],
            action=str(t["action"]),
            min_hits=int(t.get("min_hits", 2)),
            debounce_s=float(t.get("debounce_s", 60.0)),
            budget=int(t.get("budget", 2))))
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate rule names in {names}")
    return tuple(rules)


class RuleEngine:
    """Stateful evaluator: consecutive-hit counting, debounce, budget."""

    def __init__(self, rules: Optional[Tuple[Rule, ...]] = None):
        self.rules = tuple(default_rules() if rules is None else rules)
        self._hits: Dict[Tuple[str, str], int] = {}
        self._fired_t: Dict[Tuple[str, str], float] = {}
        self._fired_n: Dict[Tuple[str, str], int] = {}
        #: (run, rule) -> count of firings suppressed by debounce/budget
        self.suppressed: Dict[Tuple[str, str], int] = {}

    def evaluate(self, run: str, snap: Dict, now: float):
        """One tick for one run: returns ``[(rule, evidence), ...]`` for
        every rule that fires now. Evidence is the detector's dict plus
        ``hits`` (consecutive detecting ticks) and ``firing`` (1-based
        count against the budget)."""
        fired = []
        for rule in self.rules:
            key = (run, rule.name)
            try:
                evidence = rule.detect(snap)
            except Exception:
                evidence = None     # a detector crash is not evidence
            if not evidence:
                self._hits[key] = 0
                continue
            self._hits[key] = self._hits.get(key, 0) + 1
            if self._hits[key] < rule.min_hits:
                continue
            last = self._fired_t.get(key)
            if ((last is not None and now - last < rule.debounce_s)
                    or self._fired_n.get(key, 0) >= rule.budget):
                self.suppressed[key] = self.suppressed.get(key, 0) + 1
                continue
            self._fired_t[key] = now
            self._fired_n[key] = self._fired_n.get(key, 0) + 1
            fired.append((rule, dict(evidence, hits=self._hits[key],
                                     firing=self._fired_n[key])))
        return fired
