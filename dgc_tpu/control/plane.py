"""``ControlPlane`` — N supervised runs, one tick loop, audited actions.

One :class:`~dgc_tpu.control.supervisor.Supervisor` per run, each on its
own thread (the child is a subprocess group of its own; the supervisor
thread just launches, waits, and backs off). Every supervisor event is
re-stamped with the run's fleet name and merged into one fleet-wide JSONL
stream (``<fleet_root>/control_events.jsonl``) next to the plane's own
events — ``plane_start``, per-rule ``control_action`` records (schema
checked by :func:`dgc_tpu.telemetry.registry.validate_control_action`),
``plane_stop``.

The tick loop closes the observe → decide → act cycle:

1. **observe** — :func:`dgc_tpu.telemetry.monitor.collect` on each run
   dir (tolerant: a young or torn run yields no evidence, not an error),
2. **decide** — :class:`dgc_tpu.control.rules.RuleEngine` applies the
   declarative rule table with persistence/debounce/budget hygiene,
3. **act** — :mod:`dgc_tpu.control.actions` executes the remediation
   through the run's supervisor and the result is appended to the audit
   stream with the triggering evidence attached.

Quarantined runs are excluded from further rule evaluation: the plane
stops reasoning about a run it has deliberately stopped healing.
"""

import os
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

from dgc_tpu.control import actions as _actions
from dgc_tpu.control.rules import Rule, RuleEngine
from dgc_tpu.control.supervisor import Supervisor
from dgc_tpu.telemetry import registry
from dgc_tpu.telemetry.sink import JsonlAppender

__all__ = ["RunSpec", "ControlPlane", "CONTROL_EVENTS"]

#: fleet-wide event stream file name under the fleet root
CONTROL_EVENTS = "control_events.jsonl"


class RunSpec(NamedTuple):
    """One run the plane supervises. ``name`` doubles as the fleet label
    on every merged event and metric; ``run_dir`` is where the run's
    telemetry / flight / supervise artifacts land (the monitor's view)."""
    name: str
    cmd: Sequence[str]
    run_dir: str
    watch: Optional[str] = None       # default: <run_dir>/checkpoints
    env_file: Optional[str] = None    # cohort-spec publish target
    env: Optional[Dict[str, str]] = None
    retries: int = 5
    backoff: float = 5.0
    backoff_max: float = 300.0
    success_codes: Tuple[int, ...] = (0,)


class ControlPlane:
    """Supervise a fleet of runs and remediate per the rule table."""

    def __init__(self, specs: Sequence[RunSpec], fleet_root: str,
                 rules: Optional[Sequence[Rule]] = None,
                 interval: float = 5.0, events_out: Optional[str] = None,
                 cohort_planner: Optional[Callable] = None,
                 collect: Optional[Callable] = None):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate run names in fleet: {names}")
        self.fleet_root = os.path.abspath(fleet_root)
        os.makedirs(self.fleet_root, exist_ok=True)
        self.interval = float(interval)
        self.stream = JsonlAppender(
            events_out or os.path.join(self.fleet_root, CONTROL_EVENTS))
        self.engine = RuleEngine(rules)
        self._planner = cohort_planner or _actions.default_cohort_planner
        if collect is None:
            from dgc_tpu.telemetry import monitor as _monitor
            collect = _monitor.collect
        self._collect = collect
        self.specs: Dict[str, RunSpec] = {}
        self.supervisors: Dict[str, Supervisor] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._rcs: Dict[str, Optional[int]] = {}
        self.actions: List[Dict] = []   # the in-memory audit trail
        self._quarantine_audited: set = set()
        self.ticks = 0
        self._started = False
        self._sleep = threading.Event()
        for spec in specs:
            os.makedirs(spec.run_dir, exist_ok=True)
            sup = Supervisor(
                spec.cmd,
                retries=spec.retries, backoff=spec.backoff,
                backoff_max=spec.backoff_max, env_file=spec.env_file,
                watch=spec.watch or os.path.join(spec.run_dir, "checkpoints"),
                events=os.path.join(spec.run_dir, "supervise_events.jsonl"),
                success_codes=spec.success_codes, name=spec.name,
                extra_env=spec.env,
                on_event=lambda rec, _n=spec.name: self._merge(_n, rec))
            self.specs[spec.name] = spec
            self.supervisors[spec.name] = sup
            self._rcs[spec.name] = None

    # ------------------------------------------------------------------ #
    # event stream                                                       #
    # ------------------------------------------------------------------ #

    def _merge(self, name: str, rec: Dict) -> None:
        """Supervisor event -> fleet stream, stamped with the run name."""
        self.stream.write(dict(rec, run=name))

    def _plane_event(self, kind: str, **fields) -> None:
        self.stream.write(dict(fields, event=kind, t=time.time()))

    # ------------------------------------------------------------------ #
    # lifecycle                                                          #
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._plane_event(
            "plane_start", fleet_root=self.fleet_root,
            runs={n: {"cmd": list(s.cmd), "run_dir": s.run_dir}
                  for n, s in self.specs.items()},
            rules=[r.name for r in self.engine.rules])
        for name, sup in self.supervisors.items():
            t = threading.Thread(
                target=self._supervise, args=(name, sup),
                name=f"dgc-control-{name}", daemon=True)
            self._threads[name] = t
            t.start()

    def _supervise(self, name: str, sup: Supervisor) -> None:
        # plane threads must not touch signal handlers (main-thread-only)
        self._rcs[name] = sup.run(install_signals=False)

    def alive(self) -> bool:
        return any(t.is_alive() for t in self._threads.values())

    def poll(self) -> Dict[str, Dict]:
        """Per-run view: supervisor state, launches, last rc."""
        return {
            name: {"state": sup.state, "launches": sup.launches,
                   "last_rc": sup.last_rc, "rc": self._rcs[name],
                   "run_id": sup.run_id, "quarantined": sup.quarantined}
            for name, sup in self.supervisors.items()
        }

    def stop(self) -> None:
        """Stop every run (SIGTERM through the supervisors) and wake the
        tick loop; the supervisors stop relaunching."""
        for sup in self.supervisors.values():
            sup.request_stop()
        self._sleep.set()

    # ------------------------------------------------------------------ #
    # observe -> decide -> act                                           #
    # ------------------------------------------------------------------ #

    def tick(self, now: Optional[float] = None) -> List[Dict]:
        """One control cycle over every live run; returns the
        ``control_action`` records fired this tick."""
        now = time.monotonic() if now is None else now
        self.ticks += 1
        fired: List[Dict] = []
        for name, sup in self.supervisors.items():
            if sup.quarantined is not None:
                # a self-quarantine (exit 70) still gets ONE audited pass
                # so the evidence lands in the action trail; after that
                # the plane stops reasoning about the run
                if name in self._quarantine_audited:
                    continue
            try:
                snap = self._collect(self.specs[name].run_dir)
            except Exception:
                continue    # young/torn/missing run: no evidence yet
            for rule, evidence in self.engine.evaluate(name, snap, now):
                kw = {}
                if rule.action == "elastic_relaunch":
                    kw["env_updates"] = self._planner(snap, evidence)
                result = _actions.execute(rule.action, sup, evidence, **kw)
                rec = {"event": "control_action", "run": name,
                       "run_id": sup.run_id, "rule": rule.name,
                       "action": rule.action, "evidence": evidence,
                       "result": result, "t": time.time()}
                registry.validate_control_action(rec)
                self.stream.write(rec)
                self.actions.append(rec)
                fired.append(rec)
                if rule.action == "quarantine":
                    self._quarantine_audited.add(name)
                    break   # no further reasoning about this run
        return fired

    def run(self, max_ticks: Optional[int] = None) -> Dict[str, Dict]:
        """Start the fleet and tick until every run ends (or ``max_ticks``
        control cycles pass — then the fleet is stopped). Returns the
        final :meth:`poll` view."""
        self.start()
        while self.alive():
            if max_ticks is not None and self.ticks >= max_ticks:
                self.stop()
                break
            self._sleep.wait(self.interval)
            self._sleep.clear()
            self.tick()
        for t in self._threads.values():
            t.join(timeout=max(30.0, 2 * self.interval))
        self.tick()     # final pass: audit anything the exits revealed
        final = self.poll()
        self._plane_event("plane_stop", ticks=self.ticks,
                          actions=len(self.actions), runs=final)
        return final
