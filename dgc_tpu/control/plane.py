"""``ControlPlane`` — N supervised runs, one tick loop, audited actions.

One :class:`~dgc_tpu.control.supervisor.Supervisor` per run, each on its
own thread (the child is a subprocess group of its own; the supervisor
thread just launches, waits, and backs off). Every supervisor event is
re-stamped with the run's fleet name and merged into one fleet-wide JSONL
stream (``<fleet_root>/control_events.jsonl``) next to the plane's own
events — ``plane_start``, per-rule ``control_action`` records (schema
checked by :func:`dgc_tpu.telemetry.registry.validate_control_action`),
``plane_stop``.

The tick loop closes the observe → decide → act cycle:

1. **observe** — :func:`dgc_tpu.telemetry.monitor.collect` on each run
   dir (tolerant: a young or torn run yields no evidence, not an error),
2. **decide** — :class:`dgc_tpu.control.rules.RuleEngine` applies the
   declarative rule table with persistence/debounce/budget hygiene,
3. **act** — :mod:`dgc_tpu.control.actions` executes the remediation
   through the run's supervisor and the result is appended to the audit
   stream with the triggering evidence attached.

Quarantined runs are excluded from further rule evaluation — with ONE
exception (docs/RESILIENCE.md §"Cohort surgery"): a quarantined run with
a ``probe_cmd`` keeps being probed, and once the probe passes, the
``readmit`` rule may fire on it. The :class:`DevicePool` ledger tracks
where every run's device slots are (active → quarantined → freed →
active), so capacity freed by quarantines flows back through readmits
instead of leaking; the ledger is published as ``cohort.json`` under
each run dir and the fleet root for the monitor's COHORT line and the
``dgc_cohort_size`` / ``dgc_pool_free`` gauges.
"""

import collections
import json
import os
import subprocess
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

from dgc_tpu.control import actions as _actions
from dgc_tpu.control.rules import Rule, RuleEngine
from dgc_tpu.control.scheduler import GangScheduler
from dgc_tpu.control.supervisor import Supervisor, parse_env_file
from dgc_tpu.telemetry import registry
from dgc_tpu.telemetry.sink import JsonlAppender

__all__ = ["RunSpec", "DevicePool", "ControlPlane", "CONTROL_EVENTS",
           "COHORT_FILE"]

#: fleet-wide event stream file name under the fleet root
CONTROL_EVENTS = "control_events.jsonl"

#: ledger snapshot file name, written under each run dir and the fleet
#: root every tick (the monitor's COHORT line reads it)
COHORT_FILE = "cohort.json"


class RunSpec(NamedTuple):
    """One run the plane supervises. ``name`` doubles as the fleet label
    on every merged event and metric; ``run_dir`` is where the run's
    telemetry / flight / supervise artifacts land (the monitor's view)."""
    name: str
    cmd: Sequence[str]
    run_dir: str
    watch: Optional[str] = None       # default: <run_dir>/checkpoints
    env_file: Optional[str] = None    # cohort-spec publish target
    env: Optional[Dict[str, str]] = None
    retries: int = 5
    backoff: float = 5.0
    backoff_max: float = 300.0
    success_codes: Tuple[int, ...] = (0,)
    #: re-init probe for readmission: exit 0 = the quarantined worker may
    #: rejoin (clean init + checksum over a held-out batch; a
    #: ``CHECKSUM:<hex>`` stdout line is recorded as probe evidence)
    probe_cmd: Optional[Sequence[str]] = None
    #: device slots this run holds in the :class:`DevicePool` ledger
    slots: int = 1
    #: supervisor-side hang escalation (SIGKILL past a stale heartbeat)
    hang_timeout: Optional[float] = None
    heartbeat: Optional[str] = None
    #: gang-scheduler priority (higher grants first; ties FIFO by admit
    #: time) — only read when the plane has a GangScheduler wired
    priority: int = 0


class DevicePool:
    """Backpressure ledger: where each run's device slots are.

    ``active`` — serving the run. ``quarantined`` — held with the
    quarantined run for post-mortem (not schedulable). ``freed`` — the
    readmit probe passed; capacity is back on the market and
    ``dgc_pool_free`` counts it. A readmit moves the slots back to
    ``active``. All transitions are one-way per call and idempotent, so
    racing ticks cannot double-count a slot."""

    def __init__(self, slots: Dict[str, int]):
        self.slots = {n: int(c) for n, c in slots.items()}
        self.state: Dict[str, str] = {n: "active" for n in self.slots}

    def add(self, name: str, slots: int = 1) -> None:
        """Register (or grow) a run's holding as active — the gang
        scheduler deals seats in as grants execute."""
        self.slots[name] = self.slots.get(name, 0) + int(slots)
        self.state[name] = "active"

    def quarantine(self, name: str) -> None:
        if self.state.get(name) == "active":
            self.state[name] = "quarantined"

    def release(self, name: str) -> None:
        if self.state.get(name) == "quarantined":
            self.state[name] = "freed"

    def activate(self, name: str) -> None:
        if name in self.state:
            self.state[name] = "active"

    def _count(self, want: str) -> int:
        return sum(self.slots[n] for n, s in self.state.items()
                   if s == want)

    @property
    def free(self) -> int:
        return self._count("freed")

    def snapshot(self) -> Dict:
        return {"total": sum(self.slots.values()),
                "active": self._count("active"),
                "free": self.free,
                "quarantined": sorted(n for n, s in self.state.items()
                                      if s == "quarantined"),
                "freed": sorted(n for n, s in self.state.items()
                                if s == "freed")}


class ControlPlane:
    """Supervise a fleet of runs and remediate per the rule table."""

    def __init__(self, specs: Sequence[RunSpec], fleet_root: str,
                 rules: Optional[Sequence[Rule]] = None,
                 interval: float = 5.0, events_out: Optional[str] = None,
                 cohort_planner: Optional[Callable] = None,
                 collect: Optional[Callable] = None,
                 scheduler: Optional[GangScheduler] = None):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate run names in fleet: {names}")
        self.fleet_root = os.path.abspath(fleet_root)
        os.makedirs(self.fleet_root, exist_ok=True)
        self.interval = float(interval)
        self.stream = JsonlAppender(
            events_out or os.path.join(self.fleet_root, CONTROL_EVENTS))
        self.engine = RuleEngine(rules)
        self._planner = cohort_planner or _actions.default_cohort_planner
        if collect is None:
            from dgc_tpu.telemetry import monitor as _monitor
            collect = _monitor.collect
        self._collect = collect
        self.specs: Dict[str, RunSpec] = {}
        self.supervisors: Dict[str, Supervisor] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._rcs: Dict[str, Optional[int]] = {}
        self.actions: List[Dict] = []   # the in-memory audit trail
        self._quarantine_audited: set = set()
        self.pool = DevicePool({s.name: s.slots for s in specs})
        self._probe: Dict[str, Dict] = {}   # run -> last probe result
        self.ticks = 0
        self._started = False
        self._sleep = threading.Event()
        # gang scheduling (docs/RESILIENCE.md §Scheduler): the scheduler
        # loop thread only *decides* (appends to the deque); every
        # mutation of supervisors/pool/stream happens on the tick thread
        # when the decisions drain — one writer, no cross-thread races
        self.scheduler = scheduler
        self._gangs: Dict[str, Dict] = {}        # gang -> meta
        self._gang_specs: Dict[str, List[RunSpec]] = {}
        self._gang_of: Dict[str, str] = {}       # member run -> gang
        self._gang_completed: set = set()
        self._preempt_watch: Dict[str, str] = {}  # victim gang -> seat
        self._sched_decisions: "collections.deque" = collections.deque()
        self._sched_stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None
        for spec in specs:
            os.makedirs(spec.run_dir, exist_ok=True)
            self.specs[spec.name] = spec
            self.supervisors[spec.name] = self._make_supervisor(spec)
            self._rcs[spec.name] = None

    def _make_supervisor(self, spec: RunSpec) -> Supervisor:
        return Supervisor(
            spec.cmd,
            retries=spec.retries, backoff=spec.backoff,
            backoff_max=spec.backoff_max, env_file=spec.env_file,
            watch=spec.watch or os.path.join(spec.run_dir, "checkpoints"),
            events=os.path.join(spec.run_dir, "supervise_events.jsonl"),
            success_codes=spec.success_codes, name=spec.name,
            hang_timeout=spec.hang_timeout, heartbeat=spec.heartbeat,
            extra_env=spec.env,
            on_event=lambda rec, _n=spec.name: self._merge(_n, rec))

    # ------------------------------------------------------------------ #
    # event stream                                                       #
    # ------------------------------------------------------------------ #

    def _merge(self, name: str, rec: Dict) -> None:
        """Supervisor event -> fleet stream, stamped with the run name."""
        self.stream.write(dict(rec, run=name))

    def _plane_event(self, kind: str, **fields) -> None:
        self.stream.write(dict(fields, event=kind, t=time.time()))

    def _audit(self, run: str, run_id: str, rule: str, action: str,
               evidence: Dict, result: Dict) -> Dict:
        """One schema-checked ``control_action`` record onto the fleet
        stream + the in-memory trail. EVERY mutation the plane makes —
        rule-fired remediations and scheduler transitions alike — funnels
        through here, so the audit trail is the whole story."""
        rec = {"event": "control_action", "run": run, "run_id": run_id,
               "rule": rule, "action": action, "evidence": evidence,
               "result": result, "t": time.time()}
        registry.validate_control_action(rec)
        self.stream.write(rec)
        self.actions.append(rec)
        return rec

    # ------------------------------------------------------------------ #
    # lifecycle                                                          #
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._plane_event(
            "plane_start", fleet_root=self.fleet_root,
            runs={n: {"cmd": list(s.cmd), "run_dir": s.run_dir}
                  for n, s in self.specs.items()},
            rules=[r.name for r in self.engine.rules])
        for name, sup in self.supervisors.items():
            t = threading.Thread(
                target=self._supervise, args=(name, sup),
                name=f"dgc-control-{name}", daemon=True)
            self._threads[name] = t
            t.start()
        if self.scheduler is not None and self._sched_thread is None:
            t = threading.Thread(target=self._sched_loop,
                                 name="dgc-sched", daemon=True)
            self._sched_thread = t
            t.start()

    def _supervise(self, name: str, sup: Supervisor) -> None:
        # plane threads must not touch signal handlers (main-thread-only)
        self._rcs[name] = sup.run(install_signals=False)

    def alive(self) -> bool:
        return any(t.is_alive() for t in self._threads.values())

    def _sched_live(self) -> bool:
        """The fleet isn't done while grantable work is queued or a
        decision is waiting to execute — :meth:`run` keeps ticking even
        when no supervisor thread is up yet (a freshly-submitted fleet
        has zero running members until its first grant)."""
        return (self.scheduler is not None
                and not self._sched_stop.is_set()
                and (self.scheduler.pending() > 0
                     or bool(self._sched_decisions)
                     or bool(self._preempt_watch)))

    def poll(self) -> Dict[str, Dict]:
        """Per-run view: supervisor state, launches, last rc."""
        return {
            name: {"state": sup.state, "launches": sup.launches,
                   "last_rc": sup.last_rc, "rc": self._rcs[name],
                   "run_id": sup.run_id, "quarantined": sup.quarantined}
            for name, sup in self.supervisors.items()
        }

    def stop(self) -> None:
        """Stop every run (SIGTERM through the supervisors), stop the
        scheduler pump, and wake the tick loop; the supervisors stop
        relaunching and queued grants stop executing."""
        self._sched_stop.set()
        for sup in list(self.supervisors.values()):
            sup.request_stop()
        self._sleep.set()

    # ------------------------------------------------------------------ #
    # cohort surgery machinery (docs/RESILIENCE.md §"Cohort surgery")    #
    # ------------------------------------------------------------------ #

    def _spec_world(self, name: str) -> Optional[int]:
        """The published cohort-spec world for this run's env-file."""
        spec = self.specs[name]
        try:
            w = parse_env_file(spec.env_file).get("JAX_NUM_PROCESSES")
            return int(w) if w is not None else None
        except (OSError, ValueError):
            return None

    def _run_probe(self, name: str) -> Dict:
        """Re-init probe for a quarantined run: bounded subprocess; exit
        0 passes, a ``CHECKSUM:<hex>`` stdout line rides the evidence.
        Probed once per quarantine episode — a failing worker stays
        quarantined (its slot never frees) until an operator intervenes."""
        spec = self.specs[name]
        result: Dict = {"t": time.time()}
        try:
            proc = subprocess.run(list(spec.probe_cmd), timeout=120.0,
                                  capture_output=True, text=True)
            result["rc"] = proc.returncode
            result["passed"] = proc.returncode == 0
            for line in (proc.stdout or "").splitlines():
                if line.startswith("CHECKSUM:"):
                    result["checksum"] = line.split(":", 1)[1].strip()
        except (OSError, subprocess.TimeoutExpired) as e:
            result.update(rc=None, passed=False, error=repr(e))
        self._probe[name] = result
        self._plane_event("probe", run=name, **result)
        if result["passed"]:
            self.pool.release(name)
        return result

    def _cohort_state(self, name: str) -> Dict:
        """The ledger view injected into each snapshot (``snap["cohort"]``)
        for the excise/readmit detectors and written to ``cohort.json``."""
        state = dict(self.pool.snapshot())
        state["pool_free"] = state.pop("free")
        sw = self._spec_world(name)
        if sw is not None:
            state["spec_world"] = sw
        probe = self._probe.get(name)
        if probe is not None:
            state["probe"] = dict(probe)
        return state

    def _relaunch(self, name: str) -> bool:
        """Fresh supervisor + thread for a readmitted run (the old one
        returned when it quarantined; a supervisor loop is one life)."""
        old = self.supervisors.get(name)
        if old is not None and old.state == "running":
            return False
        sup = self._make_supervisor(self.specs[name])
        self.supervisors[name] = sup
        self._rcs[name] = None
        self._quarantine_audited.discard(name)
        self._probe.pop(name, None)
        self.pool.activate(name)
        t = threading.Thread(target=self._supervise, args=(name, sup),
                             name=f"dgc-control-{name}", daemon=True)
        self._threads[name] = t
        if self._started:
            t.start()
        return True

    def _restart_cohort(self, readmitted: str) -> List[str]:
        """SIGTERM the readmitted run's still-running cohort peers (the
        runs sharing its env-file) so the grown spec takes effect at the
        next restart boundary."""
        env_file = self.specs[readmitted].env_file
        restarted = []
        for other, osup in self.supervisors.items():
            if other == readmitted or osup.quarantined is not None:
                continue
            if self.specs[other].env_file != env_file:
                continue
            if osup.request_restart(reason="readmit"):
                restarted.append(other)
        return restarted

    def _write_cohort_files(self) -> None:
        """Atomic ``cohort.json`` under each run dir + the fleet root:
        the monitor's COHORT line and the ``dgc_cohort_size`` /
        ``dgc_pool_free`` gauges read these."""
        # lazy import: serving.__init__ pulls jax via the exporter
        from dgc_tpu.serving import protocol as _sproto
        per_run = {n: self._cohort_state(n) for n in self.specs}
        fleet = dict(self.pool.snapshot(), t=time.time(),
                     runs={n: self.pool.state.get(n) for n in self.specs})
        for payload, path in (
                [(dict(per_run[n], t=time.time()),
                  os.path.join(self.specs[n].run_dir, COHORT_FILE))
                 for n in self.specs]
                + [(fleet, os.path.join(self.fleet_root, COHORT_FILE))]):
            try:
                _sproto.write_json_atomic(path, payload)
            except OSError:
                pass    # a full disk must not stop the control loop

    # ------------------------------------------------------------------ #
    # gang scheduling (docs/RESILIENCE.md §Scheduler)                    #
    # ------------------------------------------------------------------ #

    def submit(self, name: str, specs: Sequence[RunSpec],
               priority: int = 0, slots_max: Optional[int] = None,
               grow_spec: Optional[Callable[[int], RunSpec]] = None) -> Dict:
        """Queue a gang for admission: the member RunSpecs launch together
        when the scheduler grants their slots (and not before). ``specs``
        is ordered — member *i* is cohort seat *i*. ``grow_spec(seat)``
        (optional) mints the RunSpec for an elastic-grow seat; without it
        the gang never grows past its submitted size. ``slots_max`` caps
        autoscale growth (default: the submitted size, i.e. no growth).
        The admission itself is an audited ``control_action``."""
        if self.scheduler is None:
            raise RuntimeError("ControlPlane has no GangScheduler wired")
        specs = list(specs)
        if not specs:
            raise ValueError(f"gang {name!r} has no member specs")
        for s in specs:
            if s.name in self.specs or s.name in self._gang_of:
                raise ValueError(f"duplicate run name {s.name!r}")
        if name in self._gangs:
            raise ValueError(f"duplicate gang name {name!r}")
        slots = sum(s.slots for s in specs)
        self._gangs[name] = {
            "members": [s.name for s in specs], "priority": int(priority),
            "slots_max": int(slots_max) if slots_max is not None else slots,
            "grow_spec": grow_spec}
        self._gang_specs[name] = specs
        for s in specs:
            self._gang_of[s.name] = name
        evidence = {"kind": "submit", "gang": name, "slots": slots,
                    "priority": int(priority),
                    "members": [s.name for s in specs]}
        result = _actions.execute(
            "admit", None, evidence,
            enqueue=lambda: self.scheduler.admit(
                name, slots=slots, priority=int(priority), kind="launch"))
        return self._audit(name, f"queued:{name}", "scheduler-admit",
                           "admit", evidence, result)

    def _admit_grow(self, member: str) -> Dict:
        """The autoscale rule's enqueue hook: map the healthy run back to
        its gang and queue ONE extra seat at the gang's priority. The
        scheduler's duplicate check keeps a flapping rule from stacking
        requests; ``slots_max`` is enforced both here and (cheaper) in
        the detector's evidence gate."""
        gang = self._gang_of.get(member)
        meta = self._gangs.get(gang) if gang else None
        if meta is None:
            return {"duplicate": True, "error": "not a gang member"}
        if meta.get("grow_spec") is None:
            return {"duplicate": True, "error": "gang has no grow_spec"}
        holding = self.scheduler.holding(gang) or {}
        if int(holding.get("slots", 0)) >= meta["slots_max"]:
            return {"duplicate": True, "error": "gang at slots_max"}
        return self.scheduler.admit(gang, slots=1,
                                    priority=meta["priority"], kind="grow")

    def _register_and_start(self, spec: RunSpec) -> None:
        """Late-bound run registration: a granted gang member gets its
        supervisor + thread only when the grant executes."""
        os.makedirs(spec.run_dir, exist_ok=True)
        self.specs[spec.name] = spec
        sup = self._make_supervisor(spec)
        self.supervisors[spec.name] = sup
        self._rcs[spec.name] = None
        t = threading.Thread(target=self._supervise, args=(spec.name, sup),
                             name=f"dgc-control-{spec.name}", daemon=True)
        self._threads[spec.name] = t
        if self._started:
            t.start()

    def _sched_loop(self) -> None:
        """Scheduler pump thread ("dgc-sched"): periodically tick the
        gang scheduler and queue its decisions. It NEVER executes them —
        launches, order files, and env publishes all happen on the tick
        thread when :meth:`_drain_sched_decisions` pops the deque, so
        supervisor/pool/stream state keeps a single writer."""
        while not self._sched_stop.wait(self.interval):
            try:
                self._sched_decisions.extend(self.scheduler.tick())
            except Exception:
                pass    # a scheduler hiccup must not kill the pump

    def _drain_sched_decisions(self) -> List[Dict]:
        """Execute every queued scheduler decision (plus a synchronous
        scheduler tick, so a plane tick never waits a pump period for an
        obvious grant). Returns the audited ``control_action`` records."""
        if self._sched_stop.is_set():
            self._sched_decisions.clear()   # no launches after stop
            return []
        try:
            self._sched_decisions.extend(self.scheduler.tick())
        except Exception:
            pass
        fired: List[Dict] = []
        while self._sched_decisions:
            d = self._sched_decisions.popleft()
            try:
                rec = self._exec_decision(d)
            except Exception as e:
                self._plane_event("sched_decision_error", decision=dict(d),
                                  error=repr(e))
                continue
            if rec is not None:
                fired.append(rec)
        return fired

    def _exec_decision(self, d: Dict) -> Optional[Dict]:
        if d.get("decision") == "grant":
            if d.get("kind") == "grow":
                return self._exec_grant_grow(d)
            return self._exec_grant_launch(d)
        if d.get("decision") == "preempt_to_grant":
            return self._exec_preempt(d)
        return None

    def _exec_grant_launch(self, d: Dict) -> Optional[Dict]:
        """A queued gang got its slots: boot every member's supervisor
        and deal their seats into the pool ledger as active."""
        gang = d["name"]
        specs = self._gang_specs.get(gang)
        if specs is None:
            return None

        def launcher() -> List[str]:
            launched = []
            for spec in specs:
                if spec.name in self.supervisors:
                    continue    # idempotent: a replayed grant is a no-op
                self._register_and_start(spec)
                self.pool.add(spec.name, spec.slots)
                launched.append(spec.name)
            return launched

        evidence = dict(d, kind="grant_launch", gang=gang)
        result = _actions.execute("grant", None, evidence,
                                  launcher=launcher)
        sup = self.supervisors.get(self._gangs[gang]["members"][0])
        run_id = sup.run_id if sup is not None else f"gang:{gang}"
        return self._audit(gang, run_id, "scheduler-grant", "grant",
                           evidence, result)

    def _exec_grant_grow(self, d: Dict) -> Optional[Dict]:
        """A granted grow seat: mint the seat's RunSpec, publish the
        grown cohort spec, boot the seat, and restart the running members
        so the 1:k split reshard deals the error-feedback state onto the
        new worker (the ``grow`` action does the surgery-order hygiene)."""
        gang = d["name"]
        meta = self._gangs.get(gang)
        if meta is None or meta.get("grow_spec") is None:
            return None
        sup = self.supervisors.get(meta["members"][0])
        if sup is None:
            return None
        world = self._spec_world(meta["members"][0])
        if world is None:
            world = len(meta["members"])
        seat = world
        spec = meta["grow_spec"](seat)

        def relauncher() -> List[str]:
            meta["members"].append(spec.name)
            self._gang_specs[gang].append(spec)
            self._gang_of[spec.name] = gang
            self._register_and_start(spec)
            self.pool.add(spec.name, spec.slots)
            return [spec.name]

        evidence = dict(d, kind="grant_grow", gang=gang, seat=seat,
                        world=world + 1)
        result = _actions.execute(
            "grow", sup, evidence,
            env_updates={"JAX_NUM_PROCESSES": str(world + 1)},
            relauncher=relauncher,
            cohort_restart=lambda: self._restart_cohort(spec.name))
        return self._audit(gang, sup.run_id, "scheduler-grow", "grow",
                           evidence, result)

    def _exec_preempt(self, d: Dict) -> Optional[Dict]:
        """Shrink the victim gang by one seat through the cohort-surgery
        excise path: the order file lands in EVERY member's watch dir,
        the target seat exits 76 and self-excises, survivors relaunch
        under the shrunk spec, and the elastic merge folds the excised
        seat's residual into a survivor — zero mass lost. The freed seat
        grants to the beneficiary at a later tick (see
        :meth:`_sched_bookkeeping`)."""
        from dgc_tpu.resilience import surgery as _surgery
        victim = d.get("victim")
        vmeta = self._gangs.get(victim)
        if vmeta is None:
            return None
        sup = self.supervisors.get(vmeta["members"][0])
        if sup is None:
            return None
        world = self._spec_world(vmeta["members"][0])
        if world is None:
            world = len(vmeta["members"])
        if world < 2:
            return None     # the elastic merge needs a survivor
        target = world - 1
        seat_name = vmeta["members"][target] \
            if target < len(vmeta["members"]) else vmeta["members"][-1]
        order_paths = []
        for m in vmeta["members"]:
            msup = self.supervisors.get(m)
            if msup is not None and msup.watch:
                order_paths.append(
                    os.path.join(msup.watch, _surgery.ORDER_FILE))
        evidence = dict(d, kind="preempt", gang=victim, worker=target,
                        world=world, beneficiary=d.get("name"))
        result = _actions.execute(
            "preempt_to_grant", sup, evidence,
            env_updates={"JAX_NUM_PROCESSES": str(world - 1)},
            order_paths=order_paths)
        self._preempt_watch[victim] = seat_name
        return self._audit(victim, sup.run_id, "scheduler-preempt",
                           "preempt_to_grant", evidence, result)

    def _sched_bookkeeping(self) -> None:
        """Close the scheduler's feedback loops on the tick thread:
        an excised preempt target frees its seat (``shrunk``), a gang
        with a member winding down stops being a preemption target
        (``mark_exiting``), and a fully-terminal gang returns all its
        seats (``completed``)."""
        for victim, seat in list(self._preempt_watch.items()):
            sup = self.supervisors.get(seat)
            if sup is None:
                continue
            if (sup.quarantined or "").startswith("excised:"):
                self.scheduler.shrunk(
                    victim, by=self.specs[seat].slots)
                self._preempt_watch.pop(victim, None)
                self._plane_event("sched_slot_freed", run=victim,
                                  seat=seat, reason=sup.quarantined)
        for gang, meta in self._gangs.items():
            if gang in self._gang_completed:
                continue
            members = meta["members"]
            if not all(m in self.supervisors for m in members):
                continue    # not granted yet (or grow seat mid-boot)
            if gang in self._preempt_watch:
                continue    # shrink in flight; judge after it lands
            def terminal(m: str) -> bool:
                t = self._threads.get(m)
                return (self._rcs.get(m) is not None
                        and (t is None or not t.is_alive()))
            if all(terminal(m) for m in members):
                self.scheduler.completed(gang)
                self._gang_completed.add(gang)
            elif any(terminal(m) for m in members):
                self.scheduler.mark_exiting(gang)

    def _sched_snap(self, name: str, sched_state: Dict) -> Optional[Dict]:
        """The per-run scheduler view injected as ``snap["sched"]`` for
        the autoscale detector (rules.detect_autoscale)."""
        gang = self._gang_of.get(name)
        meta = self._gangs.get(gang) if gang else None
        if meta is None:
            return None
        holding = self.scheduler.holding(gang) or {}
        return {"gang": gang, "slots": int(holding.get("slots", 0)),
                "slots_max": meta["slots_max"],
                "free": sched_state.get("free", 0),
                "pending": self.scheduler.pending()}

    # ------------------------------------------------------------------ #
    # observe -> decide -> act                                           #
    # ------------------------------------------------------------------ #

    def tick(self, now: Optional[float] = None) -> List[Dict]:
        """One control cycle over every live run; returns the
        ``control_action`` records fired this tick."""
        now = time.monotonic() if now is None else now
        self.ticks += 1
        fired: List[Dict] = []
        sched_state: Optional[Dict] = None
        if self.scheduler is not None:
            # execute queued scheduler decisions FIRST (they mutate the
            # supervisor table; the per-run loop below must see a stable
            # view), then close the shrink/exit feedback loops
            fired.extend(self._drain_sched_decisions())
            self._sched_bookkeeping()
            sched_state = self.scheduler.snapshot()
        for name, sup in list(self.supervisors.items()):
            quarantined = sup.quarantined is not None
            if quarantined:
                # ledger: a quarantined run holds its slots until the
                # readmit probe frees them
                self.pool.quarantine(name)
                spec = self.specs[name]
                if (spec.probe_cmd
                        and self.pool.state.get(name) == "quarantined"
                        and name not in self._probe):
                    self._run_probe(name)
            if quarantined and name in self._quarantine_audited:
                # a self-quarantine still got its ONE audited pass; after
                # that only the readmit path may keep reasoning about the
                # run — capacity freed by its probe must flow back
                if not (self._probe.get(name) or {}).get("passed"):
                    continue
            try:
                snap = self._collect(self.specs[name].run_dir)
            except Exception:
                continue    # young/torn/missing run: no evidence yet
            snap = dict(snap, cohort=self._cohort_state(name))
            if sched_state is not None:
                sched_view = self._sched_snap(name, sched_state)
                if sched_view is not None:
                    snap["sched"] = sched_view
            for rule, evidence in self.engine.evaluate(name, snap, now):
                if (quarantined and name in self._quarantine_audited
                        and rule.action != "readmit"):
                    continue
                kw = {}
                if rule.action in ("elastic_relaunch", "excise",
                                   "readmit"):
                    kw["env_updates"] = self._planner(snap, evidence)
                if rule.action == "readmit":
                    kw["relauncher"] = \
                        lambda _n=name: self._relaunch(_n)
                    kw["cohort_restart"] = \
                        lambda _n=name: self._restart_cohort(_n)
                if rule.action == "admit":
                    kw["enqueue"] = \
                        lambda _n=name: self._admit_grow(_n)
                result = _actions.execute(rule.action, sup, evidence, **kw)
                fired.append(self._audit(name, sup.run_id, rule.name,
                                         rule.action, evidence, result))
                if rule.action in ("quarantine", "excise"):
                    if self.supervisors[name].quarantined is not None:
                        self._quarantine_audited.add(name)
                        self.pool.quarantine(name)
                    break   # no further reasoning about this run now
                if rule.action == "readmit":
                    break   # the old supervisor object is gone
        self._write_cohort_files()
        return fired

    def run(self, max_ticks: Optional[int] = None) -> Dict[str, Dict]:
        """Start the fleet and tick until every run ends (or ``max_ticks``
        control cycles pass — then the fleet is stopped). Returns the
        final :meth:`poll` view."""
        self.start()
        while self.alive() or self._sched_live():
            if max_ticks is not None and self.ticks >= max_ticks:
                self.stop()
                break
            self._sleep.wait(self.interval)
            self._sleep.clear()
            self.tick()
        for t in list(self._threads.values()):
            t.join(timeout=max(30.0, 2 * self.interval))
        self.tick()     # final pass: audit anything the exits revealed
        if self._sched_thread is not None:
            self._sched_stop.set()
            self._sched_thread.join(timeout=max(30.0, 2 * self.interval))
        final = self.poll()
        self._plane_event("plane_stop", ticks=self.ticks,
                          actions=len(self.actions), runs=final)
        return final
