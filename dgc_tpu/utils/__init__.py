from dgc_tpu.utils.pytree import (
    named_flatten,
    named_leaves,
    named_unflatten,
    tree_names,
)

__all__ = ["named_flatten", "named_leaves", "named_unflatten", "tree_names"]
