"""Version-adaptive JAX API surface.

``shard_map`` moved from ``jax.experimental.shard_map`` (kwarg
``check_rep``) to ``jax.shard_map`` (kwarg ``check_vma``) across the
0.4.x -> 0.5+ series. The engine only ever disables the replication
check (collectives inside the worker are explicit), so the shim maps
``check_vma=False`` onto whichever spelling this JAX provides. Import
``shard_map`` from here instead of from ``jax`` directly.

``enable_x64`` similarly graduated from ``jax.experimental`` to the
``jax`` top level; the shim re-exports whichever exists.
"""

import jax

__all__ = ["enable_x64", "shard_map"]

if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:
    from jax.experimental import enable_x64


if hasattr(jax, "shard_map"):

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=False):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)

else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
