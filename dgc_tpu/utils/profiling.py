"""Tracing / profiling helpers (SURVEY.md §5 "Tracing / profiling").

The reference ships no profiler; its only performance artifact is the
wall-clock speedup figure (README.md:24-25). The TPU build does better:

* :func:`trace` — context manager around ``jax.profiler`` writing a
  TensorBoard-loadable device trace (XPlane) for any code region; the
  harness exposes it as ``--profile`` (traces land under
  ``<save_path>/profile``).
* :func:`step_timer` — wall-clock step statistics with device sync, used by
  ``bench.py``.
* :func:`exchange_report` — the north-star observable: gradient-exchange
  cost of a (dist_opt, engine) pair measured by differencing full steps
  against a no-exchange variant on the same inputs.
"""

import contextlib
import time
from typing import Callable, Dict

import jax
import numpy as np

__all__ = ["trace", "step_timer", "annotate", "exchange_report"]


@contextlib.contextmanager
def trace(logdir: str, enabled: bool = True):
    """Device-level profiler trace (view in TensorBoard / Perfetto)."""
    if not enabled:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named sub-region inside an active trace (shows as a track event)."""
    return jax.profiler.TraceAnnotation(name)


def step_timer(step_fn: Callable, *args, warmup: int = 3, iters: int = 20,
               sync: Callable = None) -> Dict[str, float]:
    """median/p10/p90 wall-clock ms of ``step_fn(*args)``; ``sync`` extracts
    a value to block on (defaults to the whole output)."""
    out = None
    for _ in range(warmup):
        out = step_fn(*args)
    jax.block_until_ready(sync(out) if sync else out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = step_fn(*args)
        jax.block_until_ready(sync(out) if sync else out)
        times.append((time.perf_counter() - t0) * 1000)
    t = np.asarray(times)
    return {"median_ms": float(np.median(t)),
            "p10_ms": float(np.percentile(t, 10)),
            "p90_ms": float(np.percentile(t, 90))}


def exchange_report(dgc_ms: float, dense_ms: float, payload_elems: int,
                    num_params: int, workers: int,
                    fabric_gbps: float) -> Dict[str, float]:
    """Grad-exchange accounting used by bench.py: measured on-device
    overhead plus a stated wire model (ring allreduce vs sparse allgather,
    f32 values + int32 indices)."""
    dense_wire_ms = (2 * 4 * num_params * (workers - 1) / workers) / (
        fabric_gbps * 1e9) * 1e3
    dgc_wire_ms = ((workers - 1) * payload_elems * 8) / (
        fabric_gbps * 1e9) * 1e3
    overhead = max(dgc_ms - dense_ms, 0.0)
    return {
        "dense_exchange_ms": dense_wire_ms,
        "dgc_exchange_ms": overhead + dgc_wire_ms,
        "dgc_wire_ms": dgc_wire_ms,
        "dgc_compute_overhead_ms": overhead,
        "speedup": dense_wire_ms / max(overhead + dgc_wire_ms, 1e-12),
        "wire_reduction": (2 * 4 * num_params * (workers - 1) / workers) /
                          max((workers - 1) * payload_elems * 8, 1),
    }
