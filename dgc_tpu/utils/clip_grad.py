"""Gradient clipping utilities (C7 parity, /root/reference/dgc/clip_grad.py).

Local variants are pure per-tensor functions; *global* variants reduce the
squared sum across the mesh axis with ``psum`` (the XLA equivalent of the
reference's ``hvd.allreduce_``, clip_grad.py:29-42) and are meant to run
inside ``shard_map``. All are pluggable into ``DGCSGDMemory`` via its
``gradient_clipping`` argument (reference memory.py:34,52-53) — bind the
axis name with ``functools.partial`` first.
"""

import functools

import jax
import jax.numpy as jnp

__all__ = ["clip_grad_norm", "clip_grad_value",
           "clip_grad_value_by_global_norm", "clip_grad_norm_2_by_global",
           "global_norm_clipper"]


def clip_grad_norm(grad, max_norm, norm_type=2):
    """Scale ``grad`` so its norm is at most ``max_norm``
    (reference clip_grad.py:10-20)."""
    max_norm = float(max_norm)
    if norm_type == float("inf"):
        total_norm = jnp.max(jnp.abs(grad))
    else:
        total_norm = jnp.sum(jnp.abs(grad) ** norm_type) ** (1.0 / norm_type)
    clip_coef = max_norm / (total_norm + 1e-6)
    return jnp.where(clip_coef < 1, grad * clip_coef, grad)


def clip_grad_value(grad, clip_value):
    """Clamp elementwise to [-clip_value, clip_value] (clip_grad.py:23-25)."""
    clip_value = float(clip_value)
    return jnp.clip(grad, -clip_value, clip_value)


def clip_grad_value_by_global_norm(grad, axis_name=None):
    """Clamp elementwise to ±sqrt(mean over workers of sum(grad²))
    (clip_grad.py:29-32)."""
    sq = jnp.sum(jnp.square(grad))
    if axis_name is not None:
        sq = jax.lax.pmean(sq, axis_name)
    clip_value = jnp.sqrt(sq)
    return jnp.clip(grad, -clip_value, clip_value)


def clip_grad_norm_2_by_global(grad, max_norm, axis_name=None):
    """Scale by max_norm / global 2-norm (clip_grad.py:35-42)."""
    max_norm = float(max_norm)
    sq = jnp.sum(jnp.square(grad))
    if axis_name is not None:
        sq = jax.lax.pmean(sq, axis_name)
    total_norm = jnp.sqrt(sq)
    clip_coef = max_norm / (total_norm + 1e-6)
    return jnp.where(clip_coef < 1, grad * clip_coef, grad)


def global_norm_clipper(max_norm, axis_name="data"):
    """Partial form ready to plug into ``DGCSGDMemory(gradient_clipping=...)``."""
    return functools.partial(clip_grad_norm_2_by_global, max_norm=max_norm,
                             axis_name=axis_name)
