"""Metric meters — TopKClassMeter parity.

Parity target: ``torchpack.mtpack.meters.TopKClassMeter`` with the
update/data/set/compute protocol the reference harness drives
(/root/reference/train.py:306-327): per-batch ``update(outputs, targets)``,
``data()`` returning reducible scalars, cross-worker Sum reduction, ``set``
with the reduced values, ``compute`` → accuracy %.

In the TPU harness the per-batch top-k counts are usually computed on device
and psum-reduced inside the eval step; ``set``/``compute`` then consume the
reduced counts. ``update`` is kept for host-side/API-compatible use.
"""

import numpy as np

__all__ = ["TopKClassMeter"]


class TopKClassMeter:
    def __init__(self, k: int = 1):
        self.k = k
        self.reset()

    def reset(self):
        self.num_correct = 0
        self.num_examples = 0

    def update(self, outputs, targets):
        """outputs: [N, C] scores; targets: [N] integer labels."""
        outputs = np.asarray(outputs)
        targets = np.asarray(targets)
        k = min(self.k, outputs.shape[-1])
        topk = np.argpartition(-outputs, k - 1, axis=-1)[:, :k]
        correct = (topk == targets[:, None]).any(axis=-1)
        self.num_correct += int(correct.sum())
        self.num_examples += int(targets.shape[0])

    def update_counts(self, num_correct: int, num_examples: int):
        self.num_correct += int(num_correct)
        self.num_examples += int(num_examples)

    def data(self):
        return {"num_correct": self.num_correct,
                "num_examples": self.num_examples}

    def set(self, data):
        self.num_correct = int(data["num_correct"])
        self.num_examples = int(data["num_examples"])

    def compute(self) -> float:
        if self.num_examples == 0:
            return 0.0
        return 100.0 * self.num_correct / self.num_examples
