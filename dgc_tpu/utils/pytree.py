"""Named-pytree helpers.

The compression engine is keyed by parameter *names* (the reference keys its
per-tensor attributes and memory buffers by ``named_parameters()`` names,
/root/reference/dgc/compression.py:56-89, /root/reference/dgc/memory.py:43-48).
In JAX, parameters are nested dict pytrees; these helpers give every leaf a
stable ``a/b/c`` path name and convert between the nested tree and a flat
``{name: leaf}`` ordered dict.
"""

from typing import Any, Dict, List, Tuple

import jax


def _key_str(k) -> str:
    if isinstance(k, jax.tree_util.DictKey):
        return str(k.key)
    if isinstance(k, jax.tree_util.SequenceKey):
        return str(k.idx)
    if isinstance(k, jax.tree_util.GetAttrKey):
        return str(k.name)
    if isinstance(k, jax.tree_util.FlattenedIndexKey):
        return str(k.key)
    return str(k)


def path_name(path: Tuple) -> str:
    return "/".join(_key_str(k) for k in path)


def named_leaves(tree: Any) -> List[Tuple[str, Any]]:
    """Flatten ``tree`` to an ordered list of (path-name, leaf)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(path_name(path), leaf) for path, leaf in flat]


def named_flatten(tree: Any) -> Tuple[Dict[str, Any], Any]:
    """Flatten ``tree`` to ({name: leaf}, treedef) for later unflattening."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return {path_name(path): leaf for path, leaf in flat}, treedef


def named_unflatten(named: Dict[str, Any], treedef: Any) -> Any:
    """Inverse of :func:`named_flatten` (relies on insertion order)."""
    return jax.tree_util.tree_unflatten(treedef, list(named.values()))


def tree_names(tree: Any) -> List[str]:
    return [name for name, _ in named_leaves(tree)]
