"""Metrics logging — rank-0 console + JSONL scalar stream.

The reference logs per-step train loss and per-epoch meters to tensorboardX
with x-axis = cumulative samples seen (/root/reference/train.py:197-201,
235-242,299-301) and prints through a rank-0-only ``printr``
(train.py:406-408). tensorboardX is not available in this environment, so the
scalar stream is JSONL (one ``{"tag", "value", "step"}`` object per line) —
trivially convertible; if tensorboardX is importable it is used additionally.
"""

import json
import os

__all__ = ["MetricWriter", "printr"]


def printr(*args, **kwargs):
    """Process-0-only print. Single-controller JAX: always prints; kept for
    API parity and multi-process deployments."""
    import jax
    if jax.process_index() == 0:
        print(*args, **kwargs)


class MetricWriter:
    """Coordinator-only writer: on non-zero processes every method is a
    no-op (the reference's SummaryWriter lives on rank 0 only,
    train.py:197-201 — multiple processes appending to one JSONL file would
    interleave corruptly on a shared filesystem)."""

    def __init__(self, logdir: str):
        import jax
        self.logdir = logdir
        self._f = None
        self._tb = None
        if jax.process_index() != 0:
            return
        os.makedirs(logdir, exist_ok=True)
        self._f = open(os.path.join(logdir, "metrics.jsonl"), "a")
        try:
            from tensorboardX import SummaryWriter  # optional
            self._tb = SummaryWriter(logdir)
        except ImportError:
            pass

    def add_scalar(self, tag: str, value: float, step: int):
        if self._f is None:
            return
        self._f.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step)}) + "\n")
        self._f.flush()
        if self._tb is not None:
            self._tb.add_scalar(tag, value, step)

    def close(self):
        if self._f is not None:
            self._f.close()
        if self._tb is not None:
            self._tb.close()
