"""Composable config system — mini-torchpack ``Config`` parity.

Replicates the de-facto API surface the reference harness builds on
(``torchpack.mtpack.utils.config.{Config, configs}``, /root/reference/
train.py:15,34-35 and every file under /root/reference/configs/):

* ``configs`` is a global tree-of-dicts namespace mutated by config modules;
* a config *module* is an ordinary Python file executed in CLI order, later
  files overriding earlier ones (``Config.update_from_modules``);
* dotted CLI overrides: ``--train.num_epochs 500``
  (``Config.update_from_arguments``);
* ``Config(callable)`` nodes instantiate their callable on call, passing the
  stored fields as keyword arguments plus any call-time args/kwargs
  (reference usage: ``configs.model()``, ``configs.train.optimizer(params)``,
  train.py:81,111,127).
"""

import ast
import os
import runpy
from typing import Any, Callable, Optional

__all__ = ["Config", "configs"]

_FN_KEY = "__fn__"


class Config(dict):
    """Attribute-accessible dict; optionally wraps a callable."""

    def __init__(self, fn: Optional[Callable] = None, **kwargs):
        super().__init__()
        if fn is not None:
            if not callable(fn):
                raise TypeError(f"Config callable must be callable, got {fn!r}")
            dict.__setitem__(self, _FN_KEY, fn)
        for k, v in kwargs.items():
            self[k] = v

    # ---- attribute protocol ------------------------------------------- #

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def __delattr__(self, name: str) -> None:
        try:
            del self[name]
        except KeyError:
            raise AttributeError(name) from None

    # ---- dict cosmetics ------------------------------------------------ #

    def keys(self):
        return (k for k in super().keys() if k != _FN_KEY)

    def items(self):
        return ((k, v) for k, v in super().items() if k != _FN_KEY)

    def values(self):
        return (v for k, v in super().items() if k != _FN_KEY)

    def __iter__(self):
        return iter(list(self.keys()))

    def __len__(self):
        return sum(1 for _ in self.keys())

    def __contains__(self, key):
        return key != _FN_KEY and super().__contains__(key)

    # ---- callable-node protocol ---------------------------------------- #

    @property
    def callable(self) -> Optional[Callable]:
        return super().get(_FN_KEY)

    def __call__(self, *args, **overrides):
        fn = self.callable
        if fn is None:
            raise TypeError("this Config node has no callable to instantiate")
        kwargs = {k: v for k, v in self.items()}
        kwargs.update(overrides)
        return fn(*args, **kwargs)

    # ---- pretty print --------------------------------------------------- #

    def _format(self, indent: int = 0) -> str:
        pad = "  " * indent
        lines = []
        fn = self.callable
        if fn is not None:
            name = getattr(fn, "__name__", repr(fn))
            lines.append(f"{pad}[callable] {name}")
        for k, v in self.items():
            if isinstance(v, Config):
                lines.append(f"{pad}{k}:")
                lines.append(v._format(indent + 1))
            else:
                lines.append(f"{pad}{k}: {v!r}")
        return "\n".join(lines)

    def __str__(self) -> str:
        return self._format()

    def __repr__(self) -> str:
        fn = self.callable
        inner = ", ".join(f"{k}={v!r}" for k, v in self.items())
        if fn is not None:
            inner = f"{getattr(fn, '__name__', fn)!s}" + (
                ", " + inner if inner else "")
        return f"Config({inner})"

    # ---- module / CLI composition --------------------------------------- #

    @staticmethod
    def update_from_modules(*paths: str) -> None:
        """Execute config .py files in order; they mutate the global
        ``configs`` (reference train.py:34).

        For each path like ``configs/cifar/resnet20.py`` the package
        ``__init__.py`` files along the way (``configs/__init__.py``,
        ``configs/cifar/__init__.py``) run first, each at most once per call
        — so ``--configs configs/cifar/resnet20.py configs/dgc/wm5.py``
        composes base + dataset group + model + dgc group + flag, exactly
        like the reference CLI.
        """
        seen = set()

        def run_once(p):
            p = os.path.normpath(p)
            if p not in seen and os.path.isfile(p):
                seen.add(p)
                runpy.run_path(p)

        for path in paths:
            if not path.endswith(".py"):
                path = path + ".py"
            if not os.path.isfile(path):
                raise FileNotFoundError(f"config module not found: {path}")
            # package chain: every ancestor dir holding an __init__.py,
            # outermost first (works for absolute paths and any cwd)
            chain = []
            d = os.path.dirname(os.path.abspath(path))
            while os.path.isfile(os.path.join(d, "__init__.py")):
                chain.append(os.path.join(d, "__init__.py"))
                parent = os.path.dirname(d)
                if parent == d:
                    break
                d = parent
            for init in reversed(chain):
                run_once(init)
            run_once(path)

    @staticmethod
    def update_from_arguments(*opts: str) -> None:
        """Apply dotted overrides: ``--a.b.c value`` pairs
        (reference train.py:35)."""
        i = 0
        while i < len(opts):
            opt = opts[i]
            if not opt.startswith("--"):
                raise ValueError(f"expected --dotted.key, got {opt!r}")
            keys = opt[2:].split(".")
            if i + 1 >= len(opts):
                raise ValueError(f"missing value for {opt}")
            raw = opts[i + 1]
            try:
                value = ast.literal_eval(raw)
            except (ValueError, SyntaxError):
                value = raw
            node = configs
            for k in keys[:-1]:
                if k not in node:
                    node[k] = Config()
                node = node[k]
            node[keys[-1]] = value
            i += 2

    @staticmethod
    def reset() -> None:
        """Clear the global namespace (between runs / in tests)."""
        configs.clear()


#: the global config namespace, mirroring torchpack's module-level singleton
configs = Config()
