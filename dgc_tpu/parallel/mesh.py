"""Device mesh construction for data-parallel DGC training.

Replaces the reference's process-per-GPU Horovod world (``hvd.init/size/rank``,
/root/reference/train.py:412, dgc/compression.py:23) with a
``jax.sharding.Mesh``. The reference system is data-parallel only (SURVEY.md
§2 parallelism inventory); the mesh is therefore 1-D over a ``data`` axis, but
constructed through this helper so future model-sharding axes compose without
touching call sites.

Parameter broadcast at init (train.py:167-173) is unnecessary: parameters are
initialized from the same PRNG key on every worker, so replication holds by
construction.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_sharding", "replicated_sharding", "DATA_AXIS"]

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    """1-D data-parallel mesh over local (or provided) devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def data_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard leading axis over the data axis (batches, per-worker state)."""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, optimizer state)."""
    return NamedSharding(mesh, P())
