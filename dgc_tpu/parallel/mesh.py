"""Device mesh construction for data-parallel DGC training.

Replaces the reference's process-per-GPU Horovod world (``hvd.init/size/rank``,
/root/reference/train.py:412, dgc/compression.py:23) with a
``jax.sharding.Mesh``. The reference system is data-parallel only (SURVEY.md
§2 parallelism inventory); the mesh is therefore 1-D over a ``data`` axis, but
constructed through this helper so future model-sharding axes compose without
touching call sites.

Parameter broadcast at init (train.py:167-173) is unnecessary: parameters are
initialized from the same PRNG key on every worker, so replication holds by
construction.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "make_two_tier_mesh", "data_sharding",
           "replicated_sharding", "DATA_AXIS", "HOST_AXIS", "LOCAL_AXIS"]

DATA_AXIS = "data"
HOST_AXIS = "hosts"
LOCAL_AXIS = "local"


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    """1-D data-parallel mesh over local (or provided) devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def make_two_tier_mesh(num_hosts: int, local_size: int,
                       devices: Optional[Sequence] = None,
                       host_axis: str = HOST_AXIS,
                       local_axis: str = LOCAL_AXIS) -> Mesh:
    """2-D ``(hosts, local)`` mesh for the hierarchical two-tier exchange
    (dense over ICI within a host, sparse DGC over DCN across hosts — the
    real form of the reference's "#Sparsified Nodes < #GPUs" regime, which
    it can only *simulate* via ``num_batches_per_step``,
    /root/reference/README.md:126-128,133-134).

    Devices are grouped by process so each mesh row is one host's chips:
    collectives over ``local_axis`` then ride ICI, collectives over
    ``host_axis`` cross DCN. On a single process the grouping is the
    device order (rows are ICI-adjacent on one slice; on the fake CPU mesh
    the split is purely logical).
    """
    if devices is None:
        devices = sorted(jax.devices(),
                         key=lambda d: (d.process_index, d.id))
    need = num_hosts * local_size
    if len(devices) < need:
        raise ValueError(
            f"two-tier mesh needs {num_hosts}x{local_size}={need} devices, "
            f"have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(num_hosts, local_size)
    return Mesh(grid, (host_axis, local_axis))


def data_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard leading axis over the data axis (batches, per-worker state)."""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (parameters, optimizer state)."""
    return NamedSharding(mesh, P())
