"""Multi-host initialization — the launcher-side counterpart of the
reference's ``hvd.init()`` over OpenMPI (/root/reference/train.py:412,
README.md:89-104).

On TPU pods there is no mpirun: every host runs the SAME program,
``jax.distributed.initialize()`` wires the hosts together over DCN (reading
the TPU metadata or the coordinator address from the environment), and
``jax.devices()`` then spans the whole pod. The data mesh covers all chips;
collectives ride ICI within a host/slice and DCN across — exactly where the
reference's "intra-machine dense, inter-machine sparse" simulation
(README.md:133-134) becomes a real two-tier fabric.

Launchers in ``script/`` show the three standard entries: single host,
``gcloud ... tpu-vm ssh --worker=all`` pods, and Slurm
(``sample_slurm.sh`` parity).
"""

import inspect
import os
import time
from typing import Optional

import jax

__all__ = ["initialize_multihost", "is_coordinator", "local_batch_slice"]

#: the env triple the launcher scripts export — set all three or none
_ENV_TRIPLE = ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
               "JAX_PROCESS_ID")


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         init_retries: int = 3,
                         init_backoff: float = 1.0,
                         **timeouts) -> bool:
    """Call ``jax.distributed.initialize`` when running multi-host.

    With no arguments, TPU pod environments are auto-detected (the TPU
    metadata service supplies coordinator/worker ids). For CPU/GPU clusters
    (e.g. under Slurm) pass the coordinator explicitly or export
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``
    — the same triple the launcher scripts derive from Slurm variables
    (reference sample_slurm.sh:36-52 builds the equivalent -H list).

    ``timeouts`` forwards ``initialization_timeout`` /
    ``heartbeat_timeout_seconds`` / ``shutdown_timeout_seconds`` to
    ``jax.distributed.initialize`` (keywords this JAX doesn't accept are
    dropped — the older releases hard-code those two timeouts server
    side). The shutdown timeout matters on cold
    machines: processes reach the coordination service's shutdown barrier
    skewed by however much their compile times diverge, and the 300 s
    default is shorter than a cold multi-minute XLA compile — the barrier
    then kills the healthy process with DEADLINE_EXCEEDED.

    ``init_retries`` bounds retry of a failed
    ``jax.distributed.initialize`` (coordinator not up yet — the common
    race when workers of a pod/Slurm job start skewed), with exponential
    backoff starting at ``init_backoff`` seconds. The last attempt's
    error propagates.

    Returns True when distributed init ran, False for single-process runs.

    **Fail-fast on a partial env triple**: exporting only some of
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` is always a launcher bug — half-configured, a run
    would either hang waiting for processes that never dial in or
    silently come up single-process. Raise immediately with the missing
    names instead.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    # Slurm: per-task variables are only visible inside the srun task, so
    # read them here rather than exporting from the sbatch batch step
    # (where SLURM_PROCID is always 0)
    if num_processes is None and "SLURM_NTASKS" in os.environ:
        num_processes = int(os.environ["SLURM_NTASKS"])
    if process_id is None and "SLURM_PROCID" in os.environ:
        process_id = int(os.environ["SLURM_PROCID"])

    # fail-fast on a half-wired coordinator setup: once ANY of the triple
    # is supplied (args, env, or Slurm) the other two must resolve too —
    # a partial triple either hangs the job waiting for workers that
    # never dial in, or (num/id without a coordinator) silently comes up
    # single-process and trains on a fraction of the data
    resolved = {"JAX_COORDINATOR_ADDRESS": coordinator_address,
                "JAX_NUM_PROCESSES": num_processes,
                "JAX_PROCESS_ID": process_id}
    missing = [k for k, v in resolved.items() if v is None]
    if missing and len(missing) < len(resolved):
        raise RuntimeError(
            "partial multihost configuration: "
            f"{sorted(set(resolved) - set(missing))} resolved but "
            f"{missing} missing — export the full JAX_COORDINATOR_ADDRESS/"
            "JAX_NUM_PROCESSES/JAX_PROCESS_ID triple (or none of it for "
            "TPU-pod autodetection)")

    # TPU_WORKER_HOSTNAMES lists every host of a pod slice; a single entry
    # (no comma) is a one-host environment — nothing to wire up
    pod_hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    multi = (coordinator_address is not None
             or "," in pod_hosts
             or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if not multi:
        return False
    accepted = inspect.signature(jax.distributed.initialize).parameters
    kwargs = {k: v for k, v in timeouts.items() if k in accepted}
    # bounded retry around the coordination-service dial-in: worker
    # processes of a pod/Slurm job start skewed, and a worker that dials
    # in before the coordinator is listening gets a connection error it
    # should wait out, not die from. The fault-injection hook
    # (DGC_FAULTS="init_fail@N") exercises exactly this path in tests.
    from dgc_tpu.resilience import faults as _faults
    last_err = None
    for attempt in range(max(1, int(init_retries))):
        try:
            if _faults.should_fail_init(attempt):
                raise RuntimeError(
                    f"injected init failure (attempt {attempt})")
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs)
            return True
        except Exception as e:
            last_err = e
            if attempt + 1 >= max(1, int(init_retries)):
                raise
            delay = init_backoff * (2 ** attempt)
            print(f"[multihost] initialize attempt {attempt + 1} failed "
                  f"({type(e).__name__}: {e}); retrying in {delay:.1f}s")
            time.sleep(delay)
    raise last_err  # unreachable; keeps the control flow explicit


def is_coordinator() -> bool:
    """Rank-0 check (the reference's ``hvd.rank() == 0`` gating for logging
    and checkpoint bookkeeping, train.py:406-408)."""
    return jax.process_index() == 0


def local_batch_slice(global_batch: int, num_processes: int = None,
                      process_id: int = None):
    """The slice of a [global_batch, ...] host array this process should
    feed. Data loading is per-host: each process materializes only its
    shard (the DistributedSampler role, reference train.py:99-100).

    Fails fast on a non-divisible batch: flooring it here would make
    every host silently feed fewer samples — the effective global batch
    (and with it the LR scaling story) shrinks with no error anywhere
    downstream, since each host only ever sees its own shard.
    ``num_processes``/``process_id`` default to the live ``jax``
    values; tests pass them explicitly."""
    n = jax.process_count() if num_processes is None else int(num_processes)
    i = jax.process_index() if process_id is None else int(process_id)
    per, rem = divmod(int(global_batch), n)
    if rem:
        raise ValueError(
            f"global batch {global_batch} does not split evenly over {n} "
            f"processes (remainder {rem}): each host would silently feed "
            f"{per} samples and the effective global batch would shrink "
            f"to {per * n}. Use a global batch divisible by {n} (e.g. "
            f"{per * n} or {(per + 1) * n}) — adjust train.batch_size or "
            "train.num_batches_per_step")
    return slice(i * per, (i + 1) * per)


def host_local_to_global(arr, mesh, axis=None):
    """Host batch array -> global ``jax.Array`` sharded on the data axis.

    ``axis`` defaults to ALL the mesh's axis names — on the 1-D data mesh
    that is ``('data',)``, on the two-tier ``('hosts', 'local')`` mesh the
    batch shards over both tiers (process h's devices hold the h-th
    contiguous block, matching :func:`local_batch_slice`).

    Single process: a sharded device_put. Multi-process: a jit over a
    pod-spanning mesh cannot take process-local arrays — each host keeps
    only its :func:`local_batch_slice` and the global array is assembled
    with ``jax.make_array_from_process_local_data`` (the input-pipeline
    contract of multi-host JAX; this is the harness's replacement for the
    reference's DistributedSampler, train.py:99-100)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if axis is None:
        axis = tuple(mesh.axis_names)
    sharding = NamedSharding(mesh, P(axis))
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    local = arr[local_batch_slice(arr.shape[0])]
    return jax.make_array_from_process_local_data(sharding, local,
                                                  arr.shape)
