from dgc_tpu.parallel.mesh import (
    DATA_AXIS,
    HOST_AXIS,
    LOCAL_AXIS,
    data_sharding,
    make_mesh,
    make_two_tier_mesh,
    replicated_sharding,
)

__all__ = ["DATA_AXIS", "HOST_AXIS", "LOCAL_AXIS", "data_sharding",
           "make_mesh", "make_two_tier_mesh", "replicated_sharding"]
