from dgc_tpu.parallel.mesh import (
    DATA_AXIS,
    data_sharding,
    make_mesh,
    replicated_sharding,
)

__all__ = ["DATA_AXIS", "data_sharding", "make_mesh", "replicated_sharding"]
