from dgc_tpu.interop.torch_bridge import TorchDGCBridge

__all__ = ["TorchDGCBridge"]
