"""DLPack bridge: route PyTorch gradients through the JAX/TPU compressor.

BASELINE.json's north star includes a compatibility path where "train.py
keeps its PyTorch model/data path but routes gradients through the JAX
compressor via DLPack when --device tpu is set" — this module is that shim.
A torch training loop keeps its model, autograd, and data pipeline; after
``loss.backward()`` it hands the named gradients to :class:`TorchDGCBridge`,
which moves them zero-copy (DLPack) into the flat engine, runs the full
momentum-corrected sparsify + exchange + decompress on the JAX device mesh,
and returns exchanged torch gradients to drop into ``p.grad`` before
``optimizer.step()`` — the same position the reference's hooked
``synchronize()`` writes decompressed grads (dgc/horovod/optimizer.py:
141-157).

Zero-copy holds CPU<->CPU; on TPU the transfer is a host->device copy (there
is no shared memory), which is still the reference's own data path (its GPU
grads go through Horovod's CPU/MPI staging for large payloads).
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from dgc_tpu.utils.compat import shard_map

__all__ = ["TorchDGCBridge"]


class TorchDGCBridge:
    """Wraps a (DistributedOptimizer, params-template) pair for torch
    callers.

    Usage::

        bridge = TorchDGCBridge(dist_opt, named_shapes)   # once
        new_grads = bridge.exchange({name: p.grad for ...})  # per step
        for name, p in model.named_parameters():
            p.grad.copy_(new_grads[name])

    The bridge owns the DGC memory state (momentum correction / error
    feedback) across steps, like the reference's ``DGCSGDMemory`` object.
    """

    def __init__(self, dist_opt, named_shapes: Dict[str, Tuple[int, ...]],
                 mesh=None, seed: int = 0):
        import torch  # local import: torch is optional for the core package

        self._torch = torch
        self.dist = dist_opt
        template = {name: jax.ShapeDtypeStruct(tuple(shape), jnp.float32)
                    for name, shape in named_shapes.items()}
        zeros = {name: jnp.zeros(s.shape, s.dtype)
                 for name, s in template.items()}
        self.layout, self.engine = dist_opt.make_flat(zeros)
        self.mem = self.engine.init_memory()
        self.mesh = mesh
        self._key = jax.random.PRNGKey(seed)
        self._step = 0

        axis = dist_opt.axis_name
        world = dist_opt.world_size
        if self.mesh is None:
            from dgc_tpu.parallel import make_mesh
            self.mesh = make_mesh(world)
        assert self.mesh.devices.size == world, (
            f"mesh has {self.mesh.devices.size} devices, world_size="
            f"{world}; with world_size > 1 pass per-worker gradients with "
            f"a leading [world] axis")
        self.world = world

        def _exchange(flat_w, mem_w, key):
            # flat_w: [W, P] per-worker gradients sharded on the data axis;
            # mem_w: per-worker memory [W, ...]. Replicating one gradient to
            # W workers would make the exchange a no-op at W-times the cost,
            # so distinct per-worker inputs are the only multi-worker form.
            from jax.sharding import PartitionSpec as P

            def worker(fg, m, k):
                fg = fg[0]
                m = jax.tree.map(lambda x: x[0], m)
                k = jax.random.fold_in(k, jax.lax.axis_index(axis))
                out, m = self.engine.exchange(fg, m, k, axis, world)
                return out, jax.tree.map(lambda x: x[None], m)

            return shard_map(
                worker, mesh=self.mesh,
                in_specs=(P(axis), P(axis), P()),
                out_specs=(P(), P(axis)),
                check_vma=False)(flat_w, mem_w, key)

        # mem is dead after each call (exchange() rebinds self.mem to the
        # returned tree), so donating it halves the bridge's resident
        # DGC-state HBM (flagged by the dgcver donation-liveness pass)
        self._exchange = jax.jit(_exchange, donate_argnums=(1,))
        from jax.sharding import NamedSharding, PartitionSpec as P
        self._data_sharding = NamedSharding(self.mesh, P(axis))
        self._repl_sharding = NamedSharding(self.mesh, P())
        self.mem = jax.tree.map(
            lambda x: jax.device_put(
                jnp.broadcast_to(x[None], (world,) + x.shape),
                self._data_sharding),
            self.mem)

    def _to_jax(self, t):
        """torch tensor -> jax array (DLPack when possible)."""
        try:
            return jnp.from_dlpack(t.detach().contiguous())
        except Exception:
            return jnp.asarray(t.detach().cpu().numpy())

    def _to_torch(self, a):
        try:
            return self._torch.from_dlpack(a)
        except Exception:
            # np.array (not asarray): jax buffers are read-only through
            # numpy, and torch.from_numpy on a non-writable array is UB
            return self._torch.from_numpy(np.array(a))

    def exchange(self, named_grads: Dict) -> Dict:
        """Run compress -> exchange -> decompress on the device mesh.

        ``named_grads`` values are torch tensors of the declared shapes
        (world_size == 1) or with a leading ``[world]`` axis of per-worker
        gradients. Returns {name: torch tensor} of exchanged gradients
        (without the world axis — the result is identical on every worker).
        """
        from dgc_tpu.utils.pytree import named_unflatten
        W = self.world

        # convert each tensor ONCE to [W, shape], then one vmapped flatten
        def grab(n):
            if n not in named_grads:
                return jnp.zeros((W,) + self.layout.shapes[n], jnp.float32)
            g = self._to_jax(named_grads[n]).astype(jnp.float32)
            return g.reshape((W,) + self.layout.shapes[n])

        tree_w = named_unflatten({n: grab(n)
                                  for n in self.layout._tree_order},
                                 self.layout.treedef)
        flat_w = jax.vmap(self.layout.flatten)(tree_w)
        flat_w = jax.device_put(flat_w, self._data_sharding)
        key = jax.device_put(jax.random.fold_in(self._key, self._step),
                             self._repl_sharding)
        self._step += 1
        out, self.mem = self._exchange(flat_w, self.mem, key)
        named_out = self.layout.unflatten_named(out)
        # DLPack hand-off (zero-copy CPU<->CPU); numpy fallback inside
        return {n: self._to_torch(named_out[n]) for n in named_grads}

    # checkpoint protocol (reference memory.py:79-88); per-worker buffers
    # keep their leading [world] axis, matching the reference's per-rank
    # checkpoint files (train.py:60-68). Delegates to the engine's
    # per-name slice/merge helpers — one worker row at a time.
    def state_dict(self):
        if not self.mem:
            return None
        rows = [self.engine.memory_state_dict(
            {k: v[w] for k, v in self.mem.items()})
            for w in range(self.world)]
        return {k: {n: np.stack([np.asarray(r[k][n]) for r in rows])
                    for n in rows[0][k]} for k in rows[0]}

    def load_state_dict(self, saved):
        if not self.mem or saved is None:
            return
        merged = []
        for w in range(self.world):
            saved_w = {k: {n: np.asarray(v)[w] for n, v in d.items()}
                       for k, d in saved.items()}
            merged.append(self.engine.load_memory_state_dict(
                {k: v[w] for k, v in self.mem.items()}, saved_w))
        self.mem = {k: jax.device_put(
            jnp.stack([m[k] for m in merged]), self._data_sharding)
            for k in merged[0]}
