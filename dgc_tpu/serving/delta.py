"""The serving delta format: bucketed top-k sparse param deltas on the
training stack's wire codecs.

A :class:`DeltaSpec` is the static contract both ends of the stream agree
on. It is built from nothing but the parameter ``{name: shape}`` map and
the serving ratio, so a replica reconstructs the identical spec from the
manifest without ever seeing the trainer's process:

* **bucketing** — :class:`~dgc_tpu.compression.flat.ParamLayout` over the
  WHOLE tree (every tensor is delta-compressed, down to scalars; the
  layout's size-bucket DP and row-aligned tiles are reused unchanged),
  then one :func:`~dgc_tpu.compression.flat._bucket_from_rows` bucket per
  layout tile with per-row quotas ``k_r = max(1, round(numel_r * ratio))``.
* **indices** — :class:`~dgc_tpu.compression.wirecodec.DeltaIndexCodec`
  (Elias-Fano over the canonically sorted stream). Selection is emitted
  sorted ascending per row with the pad tail clipped in-row, which
  satisfies the codec's sorted-per-bucket contract by construction.
* **values** — int4 nibbles (:func:`~dgc_tpu.compression.wirecodec.pack_int4`)
  against one f32 scale per bucket row (``scale_r = max|v| / 7``); padded
  slots quantize to exactly 0 and scatter as no-ops anywhere, the same
  zero-contribution contract the training scatter sentinel rides.

**Bitwise apply parity**: :meth:`DeltaSpec.apply` is a deterministic
host-side ``decode -> dequantize -> np.add.at`` over the flat f32 buffer.
The exporter advances its published state by applying its own DECODED
artifacts — never the raw delta — so a replica that applied the same
artifact stream holds the byte-identical flat buffer, checkable by
digest at any ``(base_version, delta_seq)``. Quantization error and the
unsent tail are *not* lost: they stay in the live-params-minus-published
difference and ride the next delta (the serving analogue of DGC's error
feedback).
"""

import hashlib
import json
from typing import Dict, List, Sequence, Tuple

import numpy as np

from dgc_tpu.compression.flat import ParamLayout, _bucket_from_rows
from dgc_tpu.compression.wirecodec import (
    DeltaIndexCodec, pack_int4, unpack_int4)

__all__ = ["DeltaSpec"]

#: artifact format tag, bumped on any incompatible wire-layout change
FORMAT = "dgc-serving-delta"
FORMAT_VERSION = 1


def _named_arrays(params) -> Dict[str, np.ndarray]:
    """Any param container (pytree, flax variables dict, {name: array})
    -> an ordered {name: f32 ndarray} map."""
    from dgc_tpu.utils.pytree import named_flatten
    named, _ = named_flatten(params)
    return {n: np.asarray(a, np.float32) for n, a in named.items()}


class DeltaSpec:
    """Static codec + layout for one parameter set at one serving ratio."""

    def __init__(self, shapes: Dict[str, Sequence[int]], ratio: float):
        if not shapes:
            raise ValueError("DeltaSpec needs at least one parameter")
        if not (0.0 < float(ratio) <= 1.0):
            raise ValueError(f"serving ratio must be in (0, 1], got {ratio}")
        self.ratio = float(ratio)
        self.shapes = {str(n): tuple(int(d) for d in shapes[n])
                       for n in shapes}
        elems = sum(int(np.prod(np.asarray(s, np.int64)))
                    for s in self.shapes.values())
        if elems >= 2 ** 31:
            # cheap pre-check before materializing the layout template;
            # the layout.total guard below covers padding-driven overflow
            raise ValueError(
                f"serving layout spans {elems} >= 2^31 slots — "
                "shard the stream per parameter group")
        template = {n: np.zeros(s, np.float32)
                    for n, s in self.shapes.items()}
        #: the flat-engine layout, every tensor in the compressed block
        self.layout = ParamLayout(template, compressed_names=list(template))
        if self.layout.total >= 2 ** 31:
            # index traffic rides int32 (the codecs' own decode bound);
            # a >2^31-slot serving state needs per-shard streams anyway
            raise ValueError(
                f"serving layout spans {self.layout.total} >= 2^31 slots — "
                "shard the stream per parameter group")
        self.buckets = []
        for g in self.layout.buckets:
            rows = []
            for n in g.names:
                numel = self.layout.sizes[n]
                k = max(1, min(numel, int(round(numel * self.ratio))))
                # stride/sample/topk attrs are selection-pipeline fields
                # the wire codecs never read; fill with the exact-sampling
                # identity so the bucket is self-consistent
                rows.append((self.layout.offsets[n], numel, 1, numel, k, k))
            self.buckets.append(_bucket_from_rows(g.base, g.cols, rows))
        self.codec = DeltaIndexCodec(self.buckets)
        self.payload = self.codec.payload
        #: per payload slot: index of its owning row in the concatenated
        #: per-row scale vector (bucket-major, row-minor)
        slot_scale, self.num_rows = [], 0
        for b in self.buckets:
            rows = np.asarray(b.tight) // b.max_sel
            slot_scale.append(self.num_rows + rows.astype(np.int64))
            self.num_rows += b.rows
        self._slot_scale = np.concatenate(slot_scale)
        self._slot_off = np.asarray(self.codec.slot_off, np.int64)
        self._slot_numel = np.asarray(self.codec.slot_numel, np.int64)

    # ------------------------------------------------------------------ #

    @classmethod
    def from_params(cls, params, ratio: float) -> "DeltaSpec":
        return cls({n: a.shape for n, a in _named_arrays(params).items()},
                   ratio)

    def meta(self) -> Dict:
        """The JSON-able spec record a manifest carries; feeding it back
        through :meth:`from_meta` reconstructs the identical spec."""
        return {"format": FORMAT, "format_version": FORMAT_VERSION,
                "ratio": self.ratio,
                "shapes": {n: list(s) for n, s in self.shapes.items()},
                "key": self.key()}

    @classmethod
    def from_meta(cls, meta: Dict) -> "DeltaSpec":
        if meta.get("format") != FORMAT:
            raise ValueError(f"not a serving delta spec: "
                             f"format={meta.get('format')!r}")
        if int(meta.get("format_version", -1)) != FORMAT_VERSION:
            raise ValueError(
                f"serving delta format version {meta.get('format_version')} "
                f"!= supported {FORMAT_VERSION} — resync from a full "
                "checkpoint written by a matching tree")
        spec = cls(meta["shapes"], float(meta["ratio"]))
        if meta.get("key") and meta["key"] != spec.key():
            raise ValueError("serving spec key mismatch: the manifest was "
                             "published by a different layout/codec build")
        return spec

    def key(self) -> str:
        """Content hash of everything the wire layout depends on — the
        lineage anchor's compatibility check."""
        h = hashlib.sha256()
        h.update(json.dumps(
            {"format": FORMAT, "v": FORMAT_VERSION, "ratio": self.ratio,
             "shapes": {n: list(s) for n, s in sorted(self.shapes.items())}},
            sort_keys=True).encode())
        return h.hexdigest()[:16]

    # ------------------------------------------------------------------ #

    def flatten(self, params) -> np.ndarray:
        """Params -> flat f32 [total] in layout order (host-side numpy;
        structural zeros in row tails / gaps, like ``ParamLayout.flatten``)."""
        named = _named_arrays(params)
        got = {n: tuple(a.shape) for n, a in named.items()}
        if got != self.shapes:
            raise ValueError(
                f"params do not match the serving spec: spec shapes "
                f"{self.shapes} vs got {got}")
        flat = np.zeros((self.layout.total,), np.float32)
        for n, a in named.items():
            off = self.layout.offsets[n]
            flat[off:off + self.layout.sizes[n]] = np.ravel(a)
        return flat

    def unflatten(self, flat: np.ndarray) -> Dict[str, np.ndarray]:
        """Flat [total] -> {name: array} (the replica's serving view)."""
        out = {}
        for n, shape in self.shapes.items():
            off = self.layout.offsets[n]
            out[n] = np.asarray(flat[off:off + self.layout.sizes[n]]
                                ).reshape(shape)
        return out

    # ------------------------------------------------------------------ #

    def encode(self, delta: np.ndarray) -> Dict[str, np.ndarray]:
        """Flat f32 delta [total] -> wire artifact arrays.

        Per bucket row: top-``k_r`` by |delta|, indices sorted ascending,
        pad tail clipped to the row's last element with value exactly 0.0
        (the codec's canonical form), then int4 quantize against the
        row's scale. Returns ``{"scales" f32 [num_rows], "values" int8
        [ceil(payload/2)], "words" uint32 [nwords]}``.
        """
        delta = np.asarray(delta, np.float32)
        if delta.shape != (self.layout.total,):
            raise ValueError(f"delta shape {delta.shape} != "
                             f"({self.layout.total},)")
        values = np.zeros((self.payload,), np.float32)
        indices = np.zeros((self.payload,), np.int64)
        scales = np.ones((self.num_rows,), np.float32)
        p0 = row0 = 0
        for b in self.buckets:
            grid_v = np.zeros((b.rows, b.max_sel), np.float32)
            # pad slots carry the row's last element (in-row, ascending
            # after any real selection) with value 0.0 — decodes as a
            # zero-contribution scatter, same envelope as the sentinel
            grid_i = np.repeat((np.asarray(b.row_offsets, np.int64)
                                + np.asarray(b.numels, np.int64) - 1)
                               [:, None], b.max_sel, axis=1)
            for r in range(b.rows):
                off = int(b.row_offsets[r])
                numel = int(b.numels[r])
                k = int(b.num_selects[r])
                x = delta[off:off + numel]
                if k < numel:
                    sel = np.argpartition(np.abs(x), numel - k)[numel - k:]
                else:
                    sel = np.arange(numel)
                sel = np.sort(sel)
                grid_v[r, :k] = x[sel]
                grid_i[r, :k] = off + sel
            tight = np.asarray(b.tight)
            values[p0:p0 + b.payload] = grid_v.reshape(-1)[tight]
            indices[p0:p0 + b.payload] = grid_i.reshape(-1)[tight]
            amax = np.max(np.abs(grid_v), axis=1, initial=0.0)
            scales[row0:row0 + b.rows] = np.where(amax > 0, amax / 7.0, 1.0)
            p0 += b.payload
            row0 += b.rows
        q = np.clip(np.rint(values / scales[self._slot_scale]), -8, 7
                    ).astype(np.int32)
        packed = np.asarray(pack_int4(q))
        # int32 keeps the codec on its native width (no x64 round-trip);
        # the constructor guards total < 2^31
        words = np.asarray(self.codec.encode(indices.astype(np.int32)))
        return {"scales": scales, "values": packed, "words": words}

    def decode(self, artifact: Dict[str, np.ndarray]
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Wire artifact -> (values f32 [payload], indices int64 [payload])
        — the canonical stream every receiver reconstructs."""
        q = np.asarray(unpack_int4(
            np.asarray(artifact["values"], np.int8), self.payload))
        scales = np.asarray(artifact["scales"], np.float32)
        if scales.shape != (self.num_rows,):
            raise ValueError(f"scale lane shape {scales.shape} != "
                             f"({self.num_rows},)")
        values = q.astype(np.float32) * scales[self._slot_scale]
        idx = np.asarray(self.codec.decode(
            np.asarray(artifact["words"], np.uint32),
            out_dtype=np.int32)).astype(np.int64)
        # receiver-side row clamp: a corrupted word decodes in-row, the
        # same containment the training wire relies on
        idx = self._slot_off + np.clip(idx - self._slot_off, 0,
                                       self._slot_numel - 1)
        return values, idx

    def apply(self, flat: np.ndarray,
              artifact: Dict[str, np.ndarray]) -> np.ndarray:
        """One deterministic in-place delta application: scatter-ADD the
        decoded values at the decoded coordinates. Both ends run exactly
        this, which is what makes apply parity bitwise."""
        values, idx = self.decode(artifact)
        out = np.array(flat, np.float32, copy=True)
        np.add.at(out, idx, values)
        return out

    # ------------------------------------------------------------------ #

    def wire_bytes_per_update(self) -> int:
        """Exact artifact payload bytes of one delta update (scale lane +
        packed int4 values + Elias-Fano index words)."""
        return int(4 * self.num_rows + (self.payload + 1) // 2
                   + 4 * self.codec.nwords)

    def full_checkpoint_bytes(self) -> int:
        """f32 bytes of a full parameter snapshot — the shipping cost the
        delta stream replaces."""
        return int(4 * self.layout.num_params)

    @staticmethod
    def digest(flat: np.ndarray) -> str:
        """Content digest of a flat param state — the apply-parity check
        between the exporter's published state and a replica."""
        return hashlib.sha256(
            np.ascontiguousarray(np.asarray(flat, np.float32)).tobytes()
        ).hexdigest()[:16]

    def describe(self) -> Dict:
        """Static accounting for logs/bench: payload, rows, wire bytes,
        bits/index, and the delta:checkpoint byte ratio."""
        wire = self.wire_bytes_per_update()
        full = self.full_checkpoint_bytes()
        return {
            "num_params": int(self.layout.num_params),
            "payload": int(self.payload),
            "num_rows": int(self.num_rows),
            "num_buckets": len(self.buckets),
            "bits_per_index": round(self.codec.bits_per_index, 3),
            "wire_bytes_per_update": wire,
            "full_checkpoint_bytes": full,
            "wire_frac": round(wire / full, 6) if full else 0.0,
        }
