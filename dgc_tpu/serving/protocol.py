"""File protocol of the serving stream: atomic artifacts + JSON control.

A serving directory is a single flat namespace both ends rendezvous on
(local disk in the drills; the same layout works on any
``os.replace``-atomic store):

* ``manifest.json`` — the stream head: spec meta, ``base_version``,
  ``latest_seq``, checkpoint lineage anchor, and trailing per-update
  digests. Readers poll it; it is the ONLY file whose content changes.
* ``base_v{V}.npz`` — full f32 flat snapshot for base version ``V``.
* ``delta_v{V}_{S}.npz`` — delta artifact ``S`` (1-based) on base ``V``.
* ``resync.json`` — a pending resync request (replica- or control-plane
  written); the exporter consumes it at the next publish and rebases.

Every write is ``tempfile.mkstemp`` + ``os.replace`` in the target
directory — the checkpoint manager's publish idiom — so a reader never
observes a torn file and a crashed writer leaves only ``*.tmp`` litter.
"""

import json
import os
import tempfile
import zipfile
from typing import Dict, Optional

import numpy as np

__all__ = [
    "MANIFEST", "RESYNC_REQUEST", "base_path", "delta_path",
    "write_json_atomic", "write_text_atomic", "read_json", "read_manifest",
    "save_npz_atomic", "load_npz", "request_resync",
    "read_resync_request", "clear_resync_request",
]

MANIFEST = "manifest.json"
RESYNC_REQUEST = "resync.json"


def base_path(serving_dir: str, version: int) -> str:
    return os.path.join(serving_dir, f"base_v{int(version)}.npz")


def delta_path(serving_dir: str, version: int, seq: int) -> str:
    return os.path.join(serving_dir, f"delta_v{int(version)}_{int(seq)}.npz")


def write_json_atomic(path: str, obj: Dict) -> None:
    """Publish a JSON document atomically (mkstemp + os.replace in the
    destination directory, so the rename never crosses filesystems)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_text_atomic(path: str, text: str, prefix: str = ".atomic.",
                      suffix: str = ".tmp") -> None:
    """Publish a text file with the same mkstemp+fsync+replace discipline
    as :func:`write_json_atomic` — the one choke point for every
    non-JSON publish (the supervisor env-file) so the model checker
    verifies a single idiom."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=prefix, suffix=suffix)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_json(path: str) -> Optional[Dict]:
    """Read a JSON document; None when absent or torn mid-replace (the
    caller polls, so transient unreadability is just 'not yet')."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def read_manifest(serving_dir: str) -> Optional[Dict]:
    return read_json(os.path.join(serving_dir, MANIFEST))


def save_npz_atomic(path: str, arrays: Dict[str, np.ndarray]) -> None:
    """np.savez to an explicit tmp path in the destination directory,
    then os.replace — same publish idiom as the JSON side."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    os.close(fd)
    try:
        # savez appends .npz unless the name already ends with it; give
        # it an exact .npz path so the replace source is deterministic
        tmp_npz = tmp[:-4]
        os.replace(tmp, tmp_npz)
        with open(tmp_npz, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_npz, path)
    except BaseException:
        for t in (tmp, tmp[:-4]):
            try:
                os.unlink(t)
            except OSError:
                pass
        raise


def load_npz(path: str) -> Optional[Dict[str, np.ndarray]]:
    """Load an artifact; None when absent (a gap) or unreadable. The
    catch set covers every shape a truncated zip container takes:
    np.load raises BadZipFile/EOFError/KeyError (not just OSError/
    ValueError) depending on WHERE the byte boundary falls."""
    try:
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    except (OSError, ValueError, KeyError, EOFError,
            zipfile.BadZipFile):
        return None


def request_resync(serving_dir: str, reason: str, **fields) -> Dict:
    """Ask the exporter to rebase: publish ``resync.json``. Idempotent —
    concurrent requesters just overwrite each other's identical ask; the
    exporter consumes whichever it sees at its next publish."""
    req = {"event": "resync_request", "reason": str(reason), **fields}
    write_json_atomic(os.path.join(serving_dir, RESYNC_REQUEST), req)
    return req


def read_resync_request(serving_dir: str) -> Optional[Dict]:
    return read_json(os.path.join(serving_dir, RESYNC_REQUEST))


def clear_resync_request(serving_dir: str) -> None:
    try:
        os.unlink(os.path.join(serving_dir, RESYNC_REQUEST))
    except OSError:
        pass
