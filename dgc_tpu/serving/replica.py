"""Serving-side replica: applies the delta stream in place.

``Replica.poll()`` is the serving loop's tick: read the manifest, apply
every delta artifact between the local ``(base_version, delta_seq)`` and
the stream head, and report status. Three fallbacks guard the in-place
path, all ending in a full-snapshot resync:

* **base change** — the manifest's ``base_version`` moved (exporter
  rebased): reload ``base_v{V}.npz`` and replay from seq 0.
* **gap** — the next delta artifact is missing while the head is
  already past it (a dropped update): the in-place state can never
  catch up, so the replica requests a resync (``auto_resync=True``
  writes ``resync.json`` itself; otherwise it reports ``gap`` health
  and waits for the control plane's ``stale_replica -> resync``).
* **staleness breach** — ``latest_seq - delta_seq`` exceeded the
  manifest's pinned ``max_lag`` bound: same resync path.

Status records validate against
:func:`dgc_tpu.telemetry.registry.validate_replica_status` and are what
the fleet monitor's per-replica ``{replica=…}`` gauges scrape.
"""

import os
import time
from typing import Dict, Optional

import numpy as np

from dgc_tpu.serving import protocol
from dgc_tpu.serving.delta import DeltaSpec

__all__ = ["Replica"]


class Replica:
    """One serving replica following a stream in ``serving_dir``."""

    def __init__(self, serving_dir: str, name: str = "replica0",
                 auto_resync: bool = True):
        self.serving_dir = str(serving_dir)
        self.name = str(name)
        self.auto_resync = bool(auto_resync)
        self.spec: Optional[DeltaSpec] = None
        self.flat: Optional[np.ndarray] = None
        self.base_version = 0
        self.delta_seq = -1          # -1: no base loaded yet
        self.applied_deltas = 0
        self.resyncs = 0
        self.gaps = 0
        self._health = "init"

    # ------------------------------------------------------------------ #

    @property
    def ready(self) -> bool:
        return self.flat is not None

    def params(self) -> Dict[str, np.ndarray]:
        """The served parameter view at the current (version, seq)."""
        if not self.ready:
            raise RuntimeError(f"replica {self.name} has no base loaded")
        return self.spec.unflatten(self.flat)

    def digest(self) -> str:
        if not self.ready:
            raise RuntimeError(f"replica {self.name} has no base loaded")
        return DeltaSpec.digest(self.flat)

    # ------------------------------------------------------------------ #

    def _load_base(self, manifest: Dict) -> bool:
        v = int(manifest["base_version"])
        arrays = protocol.load_npz(protocol.base_path(self.serving_dir, v))
        if arrays is None:
            self._health = "no_base"
            return False
        spec = DeltaSpec.from_meta(manifest["spec"])
        flat = np.asarray(arrays["flat"], np.float32)
        if flat.shape != (spec.layout.total,):
            self._health = "bad_base"
            return False
        if self.ready:
            self.resyncs += 1
        self.spec, self.flat = spec, flat
        self.base_version, self.delta_seq = v, 0
        self._health = "ok"
        return True

    def _request_resync(self, reason: str) -> None:
        if self.auto_resync:
            protocol.request_resync(self.serving_dir, reason,
                                    replica=self.name,
                                    base_version=self.base_version,
                                    delta_seq=self.delta_seq)

    # ------------------------------------------------------------------ #

    def poll(self) -> Dict:
        """One serving tick: catch up to the stream head, return status."""
        manifest = protocol.read_manifest(self.serving_dir)
        if manifest is None:
            self._health = "no_manifest"
            return self.status(latest_seq=-1, max_lag=0)
        head_v = int(manifest["base_version"])
        head_s = int(manifest["latest_seq"])
        max_lag = int(manifest.get("max_lag", 8))

        if not self.ready or head_v != self.base_version:
            if not self._load_base(manifest):
                return self.status(latest_seq=head_s, max_lag=max_lag)

        while self.delta_seq < head_s:
            nxt = self.delta_seq + 1
            arrays = protocol.load_npz(protocol.delta_path(
                self.serving_dir, self.base_version, nxt))
            if arrays is None:
                # missing artifact below the head — a real gap, not a
                # not-yet-published tail (the manifest IS the head)
                self.gaps += 1
                self._health = "gap"
                self._request_resync(f"gap at {self.base_version}:{nxt}")
                break
            self.flat = self.spec.apply(self.flat, arrays)
            self.delta_seq = nxt
            self.applied_deltas += 1
            self._health = "ok"

        if (self._health == "ok"
                and head_s - self.delta_seq > max_lag):
            self._health = "stale"
            self._request_resync(
                f"staleness {head_s - self.delta_seq} > max_lag {max_lag}")

        # bitwise apply-parity check against the manifest's digest trail
        key = f"{self.base_version}:{self.delta_seq}"
        want = manifest.get("digests", {}).get(key)
        if want is not None and self._health in ("ok", "stale"):
            if self.digest() != want:
                self._health = "divergent"
                self._request_resync(f"digest mismatch at {key}")
        return self.status(latest_seq=head_s, max_lag=max_lag)

    def status(self, latest_seq: int, max_lag: int) -> Dict:
        """The replica_status record the fleet monitor scrapes (schema:
        ``telemetry.registry.validate_replica_status``)."""
        staleness = (max(0, latest_seq - self.delta_seq)
                     if self.ready and latest_seq >= 0 else -1)
        return {
            "event": "replica_status",
            "replica": self.name,
            "base_version": self.base_version,
            "delta_seq": self.delta_seq,
            "latest_seq": int(latest_seq),
            "staleness": staleness,
            "max_lag": int(max_lag),
            "health": self._health,
            "applied_deltas": self.applied_deltas,
            "resyncs": self.resyncs,
            "gaps": self.gaps,
            "t": time.time(),
        }

    def write_status(self, status_dir: str, latest_seq: int,
                     max_lag: int) -> str:
        """Publish this replica's status file for the fleet monitor
        (``status_dir/replica_{name}.json``, atomic)."""
        path = os.path.join(status_dir, f"replica_{self.name}.json")
        protocol.write_json_atomic(
            path, self.status(latest_seq=latest_seq, max_lag=max_lag))
        return path
