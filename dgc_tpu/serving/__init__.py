"""dgc_tpu.serving — sparse model-delta streaming from trainer to replicas.

Under DGC the per-N-step parameter delta is top-k sparse by construction,
so the training stack's wire codecs (int4 values, Elias-Fano delta
indices — :mod:`dgc_tpu.compression.wirecodec`) ship model updates to a
serving fleet at a tiny fraction of full-checkpoint bytes. The subsystem
has three parts (docs/SERVING.md):

* :class:`~dgc_tpu.serving.delta.DeltaSpec` — the static delta format:
  flat-engine bucketing (:class:`~dgc_tpu.compression.flat.ParamLayout`)
  over the WHOLE param tree, per-row top-k quotas, int4 values + per-row
  f32 scales + Elias-Fano indices, and the deterministic scatter apply
  both ends share (bitwise apply parity).
* :class:`~dgc_tpu.serving.exporter.Exporter` — trainer side: every N
  steps, diff current params against the last *published* (decoded)
  state, encode, publish a versioned delta artifact; full base snapshots
  carry the checkpoint-lineage anchor; rebases answer resync requests.
* :class:`~dgc_tpu.serving.replica.Replica` — serving side: applies
  deltas in place, tracks ``(base_version, delta_seq)``, reports
  staleness/gap health the fleet monitor scrapes, and falls back to
  full-snapshot resync on a gap or a staleness-bound breach (self-driven
  with ``auto_resync=True``, else via the control plane's
  ``stale_replica -> resync`` rule).

Everything here is host-side file-protocol code (atomic publishes, JSON
manifests) — nothing imports into the train step, and the codecs reuse
the exact compression-stack implementations.
"""

from dgc_tpu.serving.delta import DeltaSpec
from dgc_tpu.serving.exporter import Exporter
from dgc_tpu.serving.protocol import (
    MANIFEST, RESYNC_REQUEST, clear_resync_request, read_manifest,
    read_resync_request, request_resync, write_json_atomic,
)
from dgc_tpu.serving.replica import Replica

__all__ = [
    "DeltaSpec", "Exporter", "Replica", "MANIFEST", "RESYNC_REQUEST",
    "read_manifest", "read_resync_request", "request_resync",
    "clear_resync_request", "write_json_atomic",
]
