"""Trainer-side delta exporter.

``Exporter.publish(params, step)`` is called from the training loop every
N steps (or every epoch). It diffs the live params against the last
*published* state, encodes the top-k sparse delta with the serving wire
codecs, and publishes ``delta_v{V}_{S}.npz`` + an updated manifest.

The published state is advanced by applying the exporter's own DECODED
artifact — the exact bytes a replica will apply — never the raw delta.
Two things follow:

* **bitwise apply parity** — a replica that has applied the same
  ``(base_version, delta_seq)`` stream holds the byte-identical flat
  buffer, and the manifest's trailing digests make that checkable.
* **error feedback** — whatever the top-k selection did not send, plus
  all int4 quantization error, remains in ``live - published`` and is
  a candidate for the next delta. Nothing is ever dropped, only
  deferred (the serving analogue of DGC's residual accumulation).

A pending ``resync.json`` (from a replica or the control plane's
``stale_replica -> resync`` action) is consumed at the next publish: the
exporter REBASES — bumps ``base_version``, writes a fresh full
``base_v{V}.npz`` of the live params, resets ``delta_seq`` to 0 — and
replicas reload from the newer base. The base snapshot carries the
checkpoint lineage anchor (``lineage={"epoch": …, "step": …}``) naming
the training checkpoint the stream is certified against.
"""

import os
import time
from typing import Dict, Optional

import numpy as np

from dgc_tpu.serving import protocol
from dgc_tpu.serving.delta import DeltaSpec

__all__ = ["Exporter"]

#: trailing (version:seq -> digest) entries kept in the manifest
DIGEST_TRAIL = 32


class Exporter:
    """Publishes one serving stream into ``serving_dir``.

    Single-writer by contract (one exporter per stream — the trainer's
    process 0); replicas and the control plane only read, except for the
    ``resync.json`` request file.
    """

    def __init__(self, serving_dir: str, params, ratio: float = 0.001,
                 max_lag: int = 8, lineage: Optional[Dict] = None):
        self.serving_dir = str(serving_dir)
        os.makedirs(self.serving_dir, exist_ok=True)
        self.spec = DeltaSpec.from_params(params, ratio)
        self.max_lag = int(max_lag)
        self.base_version = 0
        self.delta_seq = 0
        self.digests: Dict[str, str] = {}
        self.published: Optional[np.ndarray] = None
        self.wire_bytes_total = 0
        self._rebase(params, lineage=lineage, reason="initial")

    # ------------------------------------------------------------------ #

    def _manifest(self, lineage: Optional[Dict]) -> Dict:
        return {
            "spec": self.spec.meta(),
            "base_version": self.base_version,
            "latest_seq": self.delta_seq,
            "max_lag": self.max_lag,
            "lineage": dict(lineage) if lineage else {},
            "digests": dict(self.digests),
            "wire_bytes_per_update": self.spec.wire_bytes_per_update(),
            "full_checkpoint_bytes": self.spec.full_checkpoint_bytes(),
            "published_at": time.time(),
        }

    def _record_digest(self) -> str:
        d = DeltaSpec.digest(self.published)
        self.digests[f"{self.base_version}:{self.delta_seq}"] = d
        while len(self.digests) > DIGEST_TRAIL:
            self.digests.pop(next(iter(self.digests)))
        return d

    def _rebase(self, params, lineage: Optional[Dict],
                reason: str) -> Dict:
        """Publish a fresh full base snapshot as version+1, seq 0."""
        self.base_version += 1
        self.delta_seq = 0
        self.digests = {}
        self.published = self.spec.flatten(params)
        self._lineage = dict(lineage) if lineage else {}
        self._lineage.setdefault("reason", reason)
        self._record_digest()
        protocol.save_npz_atomic(
            protocol.base_path(self.serving_dir, self.base_version),
            {"flat": self.published})
        protocol.write_json_atomic(
            os.path.join(self.serving_dir, protocol.MANIFEST),
            self._manifest(self._lineage))
        protocol.clear_resync_request(self.serving_dir)
        return {"kind": "base", "base_version": self.base_version,
                "delta_seq": 0, "reason": reason,
                "bytes": self.spec.full_checkpoint_bytes()}

    # ------------------------------------------------------------------ #

    def publish(self, params, step: Optional[int] = None,
                lineage: Optional[Dict] = None) -> Dict:
        """One publish tick. Rebases if a resync request is pending,
        otherwise emits the next delta artifact. Returns an audit record
        ``{"kind": "base"|"delta", ...}``."""
        req = protocol.read_resync_request(self.serving_dir)
        if req is not None:
            lin = dict(lineage) if lineage else dict(self._lineage)
            if step is not None:
                lin["step"] = int(step)
            out = self._rebase(params, lineage=lin,
                               reason=req.get("reason", "requested"))
            out["request"] = req
            return out

        flat = self.spec.flatten(params)
        artifact = self.spec.encode(flat - self.published)
        self.delta_seq += 1
        # advance by the DECODED artifact — the bytes replicas apply —
        # so parity is bitwise and the unsent remainder carries over
        self.published = self.spec.apply(self.published, artifact)
        self._record_digest()
        if lineage:
            self._lineage = dict(lineage)
        if step is not None:
            self._lineage["step"] = int(step)
        # fault injection for drills: DGC_SERVE_DROP="S" skips writing
        # delta S of every base; "V:S" skips it on base V only (so a
        # post-resync stream does not re-hit the same injected gap)
        drop = os.environ.get("DGC_SERVE_DROP", "")
        if ":" in drop:
            v, s = drop.split(":", 1)
            dropped = (self.base_version == int(v)
                       and self.delta_seq == int(s))
        else:
            dropped = bool(drop) and self.delta_seq == int(drop)
        if not dropped:
            protocol.save_npz_atomic(
                protocol.delta_path(self.serving_dir, self.base_version,
                                    self.delta_seq),
                artifact)
        # the manifest advances either way: a skipped artifact is a GAP
        # replicas (and the control plane) must detect, the injected
        # fault of the serving drill
        protocol.write_json_atomic(
            os.path.join(self.serving_dir, protocol.MANIFEST),
            self._manifest(self._lineage))
        wire = self.spec.wire_bytes_per_update()
        self.wire_bytes_total += 0 if dropped else wire
        return {"kind": "delta", "base_version": self.base_version,
                "delta_seq": self.delta_seq, "bytes": wire,
                "dropped": dropped,
                "digest": self.digests[
                    f"{self.base_version}:{self.delta_seq}"]}
