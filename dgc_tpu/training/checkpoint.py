"""Checkpoint save/resume/rotate — parity with the reference subsystem
(SURVEY.md §3.4, /root/reference/train.py:152-173,244-264).

Replicated facts: checkpoints save every epoch and include the DGC
compression memory (momentums + velocities) as part of training state
(train.py:249-250); a ``latest`` pointer and a ``best`` copy are maintained;
only the last 3 epoch checkpoints are kept (train.py:260-263). Differences by
design: one checkpoint holds the whole sharded state (the per-worker memory
and BN stats carry their leading ``[world]`` axis) instead of one file per
Horovod rank, and restore re-places arrays on the mesh — so resume works
across different worker counts only if the mesh size matches, like the
reference.

Arrays are materialized to host numpy before saving (single-host orbax
PyTree checkpointing); restore hands back numpy pytrees which the caller
re-shards via ``shard_state``.
"""

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

__all__ = ["CheckpointManager"]


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()

    # ------------------------------------------------------------------ #

    def _epoch_dir(self, epoch: int) -> str:
        return os.path.join(self.directory, f"e{epoch}")

    def _meta_path(self) -> str:
        return os.path.join(self.directory, "latest.json")

    def save(self, epoch: int, state: Any, meters: Dict[str, float],
             best: bool = False,
             topology: Optional[Dict[str, int]] = None) -> str:
        """Save epoch checkpoint, update latest pointer, rotate, track best.

        Multi-process (``jax.process_count() > 1``): EVERY process must
        call this with the same global (sharded) state — orbax coordinates
        the distributed array write itself (the directory must be a shared
        filesystem, as on TPU pods) — while all the filesystem bookkeeping
        (meters/latest files, best copy, rotation) happens on the
        coordinator only, fenced by barriers so no process races a
        directory that is being rotated. Single-process keeps the simple
        host-materialized write."""
        multi = jax.process_count() > 1
        coord = jax.process_index() == 0
        path = self._epoch_dir(epoch)
        if multi:
            from jax.experimental import multihost_utils
            if coord and os.path.exists(path):
                shutil.rmtree(path)
            multihost_utils.sync_global_devices(f"ckpt_pre_save_e{epoch}")
            self._ckptr.save(path, state)      # collective: global arrays
            self._ckptr.wait_until_finished()
            multihost_utils.sync_global_devices(f"ckpt_post_save_e{epoch}")
            if not coord:
                return path
        else:
            host_state = jax.tree.map(np.asarray, jax.device_get(state))
            if os.path.exists(path):
                shutil.rmtree(path)
            self._ckptr.save(path, host_state)
            self._ckptr.wait_until_finished()
        with open(os.path.join(path, "meters.json"), "w") as f:
            payload = {k: float(v) for k, v in meters.items()}
            payload["epoch"] = epoch
            if topology:
                # process/mesh topology the state was written under —
                # restoring under a different one would otherwise fail deep
                # in orbax/XLA with an opaque sharding error (or silently
                # reinterpret per-worker error-feedback state)
                payload["_topology"] = dict(topology)
            json.dump(payload, f)
        with open(self._meta_path(), "w") as f:
            json.dump({"epoch": epoch}, f)
        if best:
            best_path = os.path.join(self.directory, "best")
            if os.path.exists(best_path):
                shutil.rmtree(best_path)
            shutil.copytree(path, best_path)
        # rotate: keep the last `keep` epoch dirs (reference keeps 3)
        old = epoch - self.keep
        old_path = self._epoch_dir(old)
        if old >= 0 and os.path.exists(old_path):
            shutil.rmtree(old_path)
        return path

    # ------------------------------------------------------------------ #

    @staticmethod
    def _legacy_keep_template(template):
        """Template with the flat engine's 'sent_c' memory key renamed to
        the v0.2 'keep_c' — None when the state carries no such key (the
        migration only applies to flat-engine DGC states)."""
        mem = getattr(template, "memory", None)
        if not (isinstance(mem, dict) and "sent_c" in mem):
            return None
        legacy = dict(mem)
        legacy["keep_c"] = legacy.pop("sent_c")
        return template.replace(memory=legacy)

    def latest_epoch(self) -> Optional[int]:
        if not os.path.exists(self._meta_path()):
            return None
        with open(self._meta_path()) as f:
            return int(json.load(f)["epoch"])

    def restore(self, template: Any, epoch: Optional[int] = None,
                best: bool = False,
                topology: Optional[Dict[str, int]] = None
                ) -> Optional[Tuple[Any, int, Dict[str, float]]]:
        """Restore (state, epoch, meters); None when nothing to resume.

        ``template`` is a freshly-initialized state pytree providing
        structure/shape/dtype targets. When both the checkpoint and the
        caller carry a ``topology`` record (process count / mesh shape /
        tier config), a mismatch raises an explicit error BEFORE the
        restore instead of failing deep inside orbax/XLA with an opaque
        sharding message.
        """
        if best:
            path = os.path.join(self.directory, "best")
            if not os.path.exists(path):
                return None
            epoch = -1
        else:
            if epoch is None:
                epoch = self.latest_epoch()
            if epoch is None:
                return None
            path = self._epoch_dir(epoch)
            if not os.path.exists(path):
                return None
        saved_topology = None
        meters_path = os.path.join(path, "meters.json")
        if os.path.exists(meters_path):
            with open(meters_path) as f:
                saved_topology = json.load(f).get("_topology")
        if topology is not None and saved_topology is not None \
                and dict(saved_topology) != dict(topology):
            raise RuntimeError(
                f"checkpoint at {path} was written under topology "
                f"{saved_topology} but this run has {dict(topology)} — "
                "resume with the same process/mesh/tier configuration, or "
                "start a fresh experiment directory")
        if jax.process_count() > 1:
            # restore straight into the live sharded layout: global arrays
            # cannot be host-materialized per process, and the sharding on
            # the abstract template tells orbax how to place each shard
            host_template = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    np.shape(x), x.dtype,
                    sharding=getattr(x, "sharding", None)), template)
        else:
            host_template = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), template)
        def _restore_checked(tmpl):
            state = self._ckptr.restore(path, tmpl)
            # orbax only validates tree STRUCTURE; stale checkpoints from a
            # different flat layout restore silently with on-disk shapes —
            # reject those too
            mismatch = jax.tree.map(
                lambda a, b: np.shape(a) != np.shape(b), state, tmpl)
            if any(jax.tree.leaves(mismatch)):
                raise ValueError("leaf shapes differ from the current "
                                 "state layout")
            return state

        try:
            try:
                state = _restore_checked(host_template)
            except ValueError:
                # v0.2 -> v0.3 engine-memory migration: the deferred-mask
                # state was a keep MASK ('keep_c', 1.0 = keep); it is now a
                # transmit COUNT ('sent_c', 0.0 = keep). Retry with the
                # legacy key and convert (sent = 1 - keep) so old runs
                # resume instead of silently restarting — pending deferred
                # masks survive the conversion exactly.
                legacy = self._legacy_keep_template(host_template)
                if legacy is None:
                    raise
                state = _restore_checked(legacy)
                mem = dict(state.memory)
                keep = mem.pop("keep_c")
                mem["sent_c"] = jax.tree.map(lambda k: 1.0 - k, keep)
                state = state.replace(memory=mem)
                print(f"[checkpoint] migrated legacy keep_c mask at {path}")
        except ValueError as e:
            # on-disk structure from an older/incompatible state layout
            # (e.g. per-tensor vs flat buffers): train from scratch rather
            # than crash — the reference likewise starts fresh when resume
            # files are absent (train.py:154-165)
            print(f"[checkpoint] incompatible checkpoint at {path}, "
                  f"ignoring: {str(e).splitlines()[0]}")
            return None
        meters = {}
        if os.path.exists(meters_path):
            with open(meters_path) as f:
                meters = json.load(f)
        meters.pop("_topology", None)
        if best:
            epoch = int(meters.pop("epoch", epoch))
        else:
            meters.pop("epoch", None)
        return state, epoch, meters
