"""Checkpoint save/resume/rotate — parity with the reference subsystem
(SURVEY.md §3.4, /root/reference/train.py:152-173,244-264).

Replicated facts: checkpoints save every epoch and include the DGC
compression memory (momentums + velocities) as part of training state
(train.py:249-250); a ``latest`` pointer and a ``best`` copy are maintained;
only the last 3 epoch checkpoints are kept (train.py:260-263). Differences by
design: one checkpoint holds the whole sharded state (the per-worker memory
and BN stats carry their leading ``[world]`` axis) instead of one file per
Horovod rank, and restore re-places arrays on the mesh — so resume works
across different worker counts only if the mesh size matches, like the
reference.

Arrays are materialized to host numpy before saving (single-host orbax
PyTree checkpointing); restore hands back numpy pytrees which the caller
re-shards via ``shard_state``.
"""

import json
import os
import shutil
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from dgc_tpu.serving import protocol as serving_protocol

__all__ = ["CheckpointManager"]


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)
        self._ckptr = ocp.StandardCheckpointer()

    # ------------------------------------------------------------------ #

    def _epoch_dir(self, epoch: int) -> str:
        return os.path.join(self.directory, f"e{epoch}")

    def _meta_path(self) -> str:
        return os.path.join(self.directory, "latest.json")

    def save(self, epoch: int, state: Any, meters: Dict[str, float],
             best: bool = False,
             topology: Optional[Dict[str, int]] = None) -> str:
        """Save epoch checkpoint, update latest pointer, rotate, track best.

        **Atomic**: the state AND its meters.json are written to
        ``e<N>.tmp`` and published with one ``os.replace`` — a crash or
        preemption mid-write leaves only a ``.tmp`` directory that the
        next run ignores (and ``restore`` falls back to the previous kept
        epoch), never a half-written ``e<N>`` that latest.json points at.

        Multi-process (``jax.process_count() > 1``): EVERY process must
        call this with the same global (sharded) state — orbax coordinates
        the distributed array write itself (the directory must be a shared
        filesystem, as on TPU pods) — while all the filesystem bookkeeping
        (rename, meters/latest files, best copy, rotation) happens on the
        coordinator only, fenced by barriers so no process races a
        directory that is being rotated. Single-process keeps the simple
        host-materialized write."""
        if getattr(state, "adaptive", None) is not None:
            # the straggler-adaptive policy state is memoryless (one
            # step's verdict, recomputed every step) and deliberately NOT
            # checkpointed: stripping it keeps old checkpoints and elastic
            # world-size changes restore-compatible — restore re-seeds a
            # fresh full-send verdict from the caller's template
            state = state.replace(adaptive=None)
        multi = jax.process_count() > 1
        coord = jax.process_index() == 0
        path = self._epoch_dir(epoch)
        tmp = path + ".tmp"
        if multi:
            from jax.experimental import multihost_utils
            if coord and os.path.exists(tmp):   # stale from a crashed run
                shutil.rmtree(tmp)
            multihost_utils.sync_global_devices(f"ckpt_pre_save_e{epoch}")
            self._ckptr.save(tmp, state)       # collective: global arrays
            self._ckptr.wait_until_finished()
            multihost_utils.sync_global_devices(f"ckpt_post_save_e{epoch}")
        else:
            host_state = jax.tree.map(np.asarray, jax.device_get(state))
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            self._ckptr.save(tmp, host_state)
            self._ckptr.wait_until_finished()
        if coord:
            # meters.json goes INTO the tmp dir before the rename, so the
            # published checkpoint is complete the instant it exists
            with open(os.path.join(tmp, "meters.json"), "w") as f:
                payload = {k: float(v) for k, v in meters.items()}
                payload["epoch"] = epoch
                if topology:
                    # process/mesh topology the state was written under —
                    # restoring under a different one would otherwise fail
                    # deep in orbax/XLA with an opaque sharding error (or
                    # silently reinterpret per-worker error-feedback state)
                    payload["_topology"] = dict(topology)
                json.dump(payload, f)
            if os.path.exists(path):           # same-epoch overwrite
                shutil.rmtree(path)
            os.replace(tmp, path)
            # the blessed rename-atomic idiom (and the model checker's
            # choke point): a crash between the epoch publish and this
            # pointer update leaves the OLD complete latest.json, and
            # restore's kept-epoch scan still finds the new epoch
            serving_protocol.write_json_atomic(self._meta_path(),
                                               {"epoch": epoch})
            if best:
                best_path = os.path.join(self.directory, "best")
                if os.path.exists(best_path):
                    shutil.rmtree(best_path)
                shutil.copytree(path, best_path)
            # rotate: keep the last `keep` epoch dirs (reference keeps 3)
            old = epoch - self.keep
            old_path = self._epoch_dir(old)
            if old >= 0 and os.path.exists(old_path):
                shutil.rmtree(old_path)
        if multi:
            # a process must not leave save() (and possibly restore
            # straight away) before the coordinator has written the
            # latest/best pointers and finished rotating — without this
            # fence a non-coordinator's immediate restore() can read a
            # missing/stale latest.json and silently report "nothing to
            # resume" (observed as a test flake under cold-compile skew)
            multihost_utils.sync_global_devices(f"ckpt_meta_e{epoch}")
        return path

    # ------------------------------------------------------------------ #

    @staticmethod
    def _legacy_sent_template(template, key: str):
        """Template with the flat engine's v0.4 'sent_bits' packed record
        (int32 words) replaced by the legacy full-[T] f32 vector under
        ``key`` — 'sent_c' (v0.3 transmit counts) or 'keep_c' (v0.2 keep
        mask). None when the state carries no packed record (the
        migration only applies to flat-engine DGC states). T comes from
        the momentum buffer (the word count is not invertible when
        T % 4096 == 2048)."""
        mem = getattr(template, "memory", None)
        if not (isinstance(mem, dict) and "sent_bits" in mem
                and "momentums_c" in mem):
            return None
        legacy = dict(mem)
        bits = legacy.pop("sent_bits")
        mc = legacy["momentums_c"]
        shape = tuple(np.shape(bits)[:-1]) + (np.shape(mc)[-1],)
        legacy[key] = np.zeros(shape, np.float32)
        return template.replace(memory=legacy)

    @staticmethod
    def _pack_transmitted_np(transmitted: np.ndarray) -> np.ndarray:
        """Bool [..., T] transmitted map -> the engine's packed int32 word
        record [..., W] (kernels.pack_sent_bits layout): word
        (a, l) of each trailing [A, 128] word view holds rows
        a*32 .. a*32+31 of lane l of the [T // 128, 128] row view."""
        T = transmitted.shape[-1]
        pad = (-T) % 4096
        if pad:
            z = np.zeros(transmitted.shape[:-1] + (pad,), bool)
            transmitted = np.concatenate([transmitted, z], axis=-1)
        s3 = transmitted.reshape(transmitted.shape[:-1] + (-1, 32, 128))
        m = np.arange(32, dtype=np.int64)[:, None]
        words = (s3.astype(np.int64) << m).sum(axis=-2)
        # fold into int32 range (bit 31 is the sign bit)
        words = np.where(words >= 2 ** 31, words - 2 ** 32, words)
        return np.ascontiguousarray(
            words.reshape(words.shape[:-2] + (-1,)).astype(np.int32))

    def saved_topology(self) -> Optional[Dict[str, int]]:
        """The ``_topology`` record of the newest restorable checkpoint
        (latest pointer first, then kept epochs, mirroring ``restore``'s
        walk), or None when there is nothing to resume or the checkpoint
        predates topology records. ``train.py`` reads this BEFORE
        building the step so an elastic restart can resolve its batch
        geometry (``resilience.elastic.resolve_batch_geometry``) against
        the world size the state was actually written under."""
        latest = self.latest_epoch()
        candidates = self._kept_epochs()
        if latest is not None:
            candidates = [latest] + [e for e in candidates if e != latest]
        for ep in candidates:
            meters_path = os.path.join(self._epoch_dir(ep), "meters.json")
            if not os.path.exists(meters_path):
                continue
            try:
                with open(meters_path) as f:
                    topo = json.load(f).get("_topology")
            except (ValueError, OSError):
                continue        # torn meters: restore() will skip it too
            return dict(topo) if topo else None
        return None

    def latest_epoch(self) -> Optional[int]:
        if not os.path.exists(self._meta_path()):
            return None
        try:
            with open(self._meta_path()) as f:
                return int(json.load(f)["epoch"])
        except (ValueError, KeyError, OSError):
            # torn/corrupt pointer (crash mid-write): restore() falls back
            # to scanning the kept epoch directories
            return None

    def _kept_epochs(self) -> list:
        """Epoch numbers of the on-disk ``e<N>`` checkpoint dirs, newest
        first (``.tmp`` staging dirs and ``best`` excluded)."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("e") and name[1:].isdigit() \
                    and os.path.isdir(os.path.join(self.directory, name)):
                out.append(int(name[1:]))
        return sorted(out, reverse=True)

    def restore(self, template: Any, epoch: Optional[int] = None,
                best: bool = False,
                topology: Optional[Dict[str, int]] = None,
                elastic: bool = False,
                elastic_opts: Optional[Dict[str, Any]] = None
                ) -> Optional[Tuple[Any, int, Dict[str, float]]]:
        """Restore (state, epoch, meters); None when nothing to resume.

        ``template`` is a freshly-initialized state pytree providing
        structure/shape/dtype targets. When both the checkpoint and the
        caller carry a ``topology`` record (process count / mesh shape /
        tier config), a mismatch raises an explicit error BEFORE the
        restore instead of failing deep inside orbax/XLA with an opaque
        sharding message.

        ``elastic=True`` (opt-in; the default stays fail-fast) turns a
        *world-size* mismatch into a host-side reshard instead: the
        state is restored to host numpy under the checkpoint's recorded
        world, run through ``resilience.elastic.reshard_state`` (error
        feedback merged/split with exact mass conservation), and handed
        back as a HOST pytree the caller must re-shard; the returned
        meters carry an ``_elastic`` record describing the conversion.
        ``elastic_opts`` forwards compressor-memory semantics
        (``DGCCompressor.elastic_reshard_opts()``) plus
        ``per_worker_opt`` for the Adasum scheme (refused). Checkpoints
        that predate ``_topology`` records restore as "written under the
        current topology, non-elastic" with a logged warning.

        When no explicit ``epoch`` is given and the newest checkpoint is
        corrupt (crash mid-write before atomic saves, truncated array
        files, unreadable meters), restore **falls back** to the previous
        kept epochs, newest first, instead of silently training from
        scratch while good checkpoints sit on disk. A topology mismatch is
        a configuration error, not corruption — it raises immediately.
        """
        if best:
            path = os.path.join(self.directory, "best")
            if not os.path.exists(path):
                return None
            try:
                return self._restore_one(path, -1, template, topology,
                                         best=True, elastic=elastic,
                                         elastic_opts=elastic_opts)
            except RuntimeError:
                raise
            except Exception as e:
                print(f"[checkpoint] incompatible checkpoint at {path}, "
                      f"ignoring: {self._errline(e)}")
                return None
        if epoch is not None:
            candidates = [epoch]
        else:
            latest = self.latest_epoch()
            candidates = self._kept_epochs()
            if latest is not None:
                candidates = [latest] + [e for e in candidates if e != latest]
        for i, ep in enumerate(candidates):
            path = self._epoch_dir(ep)
            if not os.path.exists(path):
                continue
            try:
                return self._restore_one(path, ep, template, topology,
                                         best=False, elastic=elastic,
                                         elastic_opts=elastic_opts)
            except RuntimeError:
                raise                     # topology mismatch: config error
            except Exception as e:
                more = any(os.path.exists(self._epoch_dir(x))
                           for x in candidates[i + 1:])
                print(f"[checkpoint] incompatible checkpoint at {path}, "
                      f"ignoring: {self._errline(e)}"
                      + (" — falling back to the previous kept epoch"
                         if more else ""))
        return None

    @staticmethod
    def _errline(e: Exception) -> str:
        s = str(e).splitlines()
        return s[0] if s else type(e).__name__

    def _restore_one(self, path: str, epoch: int, template: Any,
                     topology: Optional[Dict[str, int]], best: bool,
                     elastic: bool = False,
                     elastic_opts: Optional[Dict[str, Any]] = None
                     ) -> Tuple[Any, int, Dict[str, float]]:
        """Restore one checkpoint directory or raise (the public
        ``restore`` turns failures into kept-epoch fallback)."""
        saved_topology = None
        meters_path = os.path.join(path, "meters.json")
        if os.path.exists(meters_path):
            with open(meters_path) as f:
                saved_topology = json.load(f).get("_topology")
        if topology is not None and saved_topology is None:
            # pre-_topology checkpoint (PR-3-era and earlier): there is
            # nothing to compare or reshard against — treat it as written
            # under the current topology and restore non-elastically
            print(f"[checkpoint] {path} has no _topology record "
                  "(pre-elastic checkpoint): assuming it was written "
                  f"under the current topology {dict(topology)}; elastic "
                  "resharding is unavailable for it")
        mismatch = (topology is not None and saved_topology is not None
                    and dict(saved_topology) != dict(topology))
        elastic_info = None
        if mismatch and elastic:
            # opt-in elastic path: restore to host numpy under the world
            # the checkpoint was written at, then merge/split the
            # per-worker [world] axis (resilience/elastic.py) — the
            # caller re-shards the returned HOST state onto its mesh
            from dgc_tpu.resilience import elastic as _elastic
            opts = dict(elastic_opts or {})
            per_worker_opt = bool(opts.pop("per_worker_opt", False))
            old = _elastic.with_world(template,
                                      int(saved_topology["world"]),
                                      per_worker_opt=per_worker_opt)
            state = self._restore_guarded(path, old, force_host=True)
            state = _elastic.reshard_state(
                state, saved_topology, topology,
                per_worker_opt=per_worker_opt, **opts)
            elastic_info = {
                "from_world": int(saved_topology["world"]),
                "to_world": int(topology["world"]),
                "from_process_count":
                    int(saved_topology.get("process_count", 1)),
                "to_process_count": int(topology.get("process_count", 1)),
            }
        elif mismatch:
            raise RuntimeError(
                f"checkpoint at {path} was written under topology "
                f"{saved_topology} but this run has {dict(topology)} — "
                "resume with the same process/mesh/tier configuration, "
                "pass elastic=True (--elastic) to reshard the per-worker "
                "state across the world-size change, or start a fresh "
                "experiment directory")
        else:
            state = self._restore_guarded(path, template)
        meters: Dict[str, float] = {}
        if os.path.exists(meters_path):
            with open(meters_path) as f:
                meters = json.load(f)
        meters.pop("_topology", None)
        if elastic_info is not None:
            meters["_elastic"] = elastic_info
        if best:
            epoch = int(meters.pop("epoch", epoch))
        else:
            meters.pop("epoch", None)
        return state, epoch, meters

    def _restore_guarded(self, path: str, template: Any,
                         force_host: bool = False) -> Any:
        """``_restore_state`` with the pre-resilience fallback: a
        checkpoint without the guard-counter subtree retries without it
        (the caller re-seeds fresh guard state rather than discarding an
        otherwise-good checkpoint). The adaptive policy field is never
        saved (see :meth:`save`), so the restore always runs against the
        adaptive-stripped template and the template's fresh verdict is
        re-attached after — which also makes elastic world-size changes
        immune to the [world]-shaped ``w_frac`` leaf."""
        adaptive = getattr(template, "adaptive", None)
        if adaptive is not None:
            template = template.replace(adaptive=None)
        try:
            state = self._restore_state(path, template,
                                        force_host=force_host)
        except Exception:
            if getattr(template, "guards", None) is None:
                raise
            state = self._restore_state(path,
                                        template.replace(guards=None),
                                        force_host=force_host)
            print(f"[checkpoint] {path} predates the resilience guard "
                  "counters — they start fresh")
        if adaptive is not None:
            state = state.replace(adaptive=adaptive)
        return state

    def _restore_state(self, path: str, template: Any,
                       force_host: bool = False) -> Any:
        if jax.process_count() > 1 and not force_host:
            # restore straight into the live sharded layout: global arrays
            # cannot be host-materialized per process, and the sharding on
            # the abstract template tells orbax how to place each shard
            host_template = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    np.shape(x), x.dtype,
                    sharding=getattr(x, "sharding", None)), template)
        else:
            host_template = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), template)
        def _restore_checked(tmpl):
            state = self._ckptr.restore(path, tmpl)
            # orbax only validates tree STRUCTURE; stale checkpoints from a
            # different flat layout restore silently with on-disk shapes —
            # reject those too
            mismatch = jax.tree.map(
                lambda a, b: np.shape(a) != np.shape(b), state, tmpl)
            if any(jax.tree.leaves(mismatch)):
                raise ValueError("leaf shapes differ from the current "
                                 "state layout")
            return state

        try:
            state = _restore_checked(host_template)
        except ValueError:
            # legacy engine-memory migrations, newest first. The
            # deferred-mask state was a full-[T] f32 keep MASK in v0.2
            # ('keep_c', 1.0 = keep) and a transmit COUNT in v0.3
            # ('sent_c', 0.0 = keep); v0.4 packs it into int32 words
            # ('sent_bits', kernels.pack_sent_bits). Retry with each
            # legacy key and convert, so old runs resume instead of
            # silently restarting — pending deferred masks survive the
            # conversion exactly. (Multi-process restores skip the
            # shape-changing migrations: the legacy leaf would need a
            # sharding the template cannot supply.)
            if jax.process_count() > 1:
                if self._legacy_sent_template(host_template,
                                              "sent_c") is not None:
                    # don't leave only the generic "incompatible,
                    # ignoring" line: a legacy checkpoint IS
                    # recoverable, just not from here — the operator
                    # should migrate it before the multi-process run
                    # silently restarts from scratch
                    print("[checkpoint] NOTE: this may be a legacy "
                          "(v0.2/v0.3) memory layout, which cannot be "
                          "migrated under multi-process restore; run a "
                          "single-process restore+save once to migrate "
                          "it, then resume multi-process")
                raise
            state = None
            for key, to_transmitted in (
                    ("sent_c", lambda s: np.asarray(s) != 0.0),
                    ("keep_c", lambda k: np.asarray(k) == 0.0)):
                legacy = self._legacy_sent_template(host_template, key)
                if legacy is None:
                    raise
                try:
                    state = _restore_checked(legacy)
                except ValueError:
                    continue
                mem = dict(state.memory)
                bits = self._pack_transmitted_np(
                    to_transmitted(mem.pop(key)))
                mem["sent_bits"] = bits
                state = state.replace(memory=mem)
                print(f"[checkpoint] migrated legacy {key} record at "
                      f"{path}")
                break
            if state is None:
                raise ValueError("no legacy memory layout matched")
        return state
