"""Learning-rate schedules replicating the reference recipe (SURVEY.md §2.10).

The reference scales the configured LR by ``num_batches_per_step · world_size``
(/root/reference/train.py:115-118), warms it up linearly from ``base_lr`` to
the scaled LR over ``warmup_lr_epochs`` (fractional per step, train.py:335-343,
per arXiv:1706.02677), then hands over to a per-epoch scheduler — cosine
(CIFAR, configs/cifar/__init__.py:22-23) or MultiStep with milestones shifted
by the warm-up epochs (ImageNet, configs/imagenet/__init__.py:23-26).

Here the whole thing is one pure ``step_count -> lr`` function consumed by the
optimizer transformation, so per-step warm-up needs no host-side mutation of
optimizer state.
"""

from typing import Callable, Optional, Sequence

import jax.numpy as jnp

__all__ = ["warmup_factor", "cosine_schedule", "multistep_schedule",
           "make_lr_schedule"]


def warmup_factor(epoch_f, world_size: int, warmup_epochs: float):
    """Linear 1/size → 1 ramp of the *scaled* LR (train.py:337-343):
    ``factor = (epoch_f·(size-1)/warmup + 1)/size``."""
    return (epoch_f * (world_size - 1) / warmup_epochs + 1) / world_size


def cosine_schedule(t_max: float, eta_min_fraction: float = 0.0) -> Callable:
    """Cosine annealing over epochs-after-warmup, as a multiplicative factor.

    Matches torch CosineAnnealingLR's curve with ``eta_min = eta_min_fraction
    · scaled_lr`` — NOTE the floor is a *fraction of the scaled LR*, not an
    absolute LR (the factor is applied to ``scaled_lr`` by
    :func:`make_lr_schedule`). The reference configs use eta_min = 0, where
    the two parameterizations coincide.
    """
    def fn(t):
        return (eta_min_fraction + (1 - eta_min_fraction)
                * 0.5 * (1 + jnp.cos(jnp.pi * t / t_max)))
    return fn


def multistep_schedule(milestones: Sequence[float], gamma: float = 0.1
                       ) -> Callable:
    """torch.optim.lr_scheduler.MultiStepLR (milestones in epochs-after-warmup)."""
    ms = jnp.asarray(sorted(milestones), jnp.float32)

    def fn(t):
        passed = jnp.sum(t >= ms)
        return gamma ** passed
    return fn


def make_lr_schedule(scaled_lr: float, world_size: int,
                     num_steps_per_epoch: int,
                     warmup_lr_epochs: float = 0,
                     decay: Optional[Callable] = None,
                     schedule_lr_per_epoch: bool = True) -> Callable:
    """Compose warm-up + decay into one ``step_count -> lr`` function.

    ``decay`` maps epochs-after-warmup (fractional if
    ``schedule_lr_per_epoch=False``) to a multiplicative factor in (0, 1].
    """

    def schedule(count):
        count = jnp.asarray(count, jnp.float32)
        epoch_f = count / num_steps_per_epoch
        in_warmup = epoch_f < warmup_lr_epochs

        wf = (warmup_factor(epoch_f, world_size, warmup_lr_epochs)
              if warmup_lr_epochs > 0 else 1.0)

        t = epoch_f - warmup_lr_epochs
        if schedule_lr_per_epoch:
            t = jnp.floor(t)
        t = jnp.maximum(t, 0.0)
        df = decay(t) if decay is not None else 1.0

        factor = jnp.where(in_warmup, wf, df) if warmup_lr_epochs > 0 else df
        return scaled_lr * factor

    return schedule
