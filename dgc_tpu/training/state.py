"""Training state pytree and its mesh placement.

All training state is explicit (the functional re-design of the reference's
scattered mutable objects — model buffers, optimizer state, compression
memory, /root/reference/train.py:244-251):

* ``params`` / ``opt_state`` — replicated across the mesh (identical update
  computed everywhere from the gathered gradients, so no broadcast is needed).
* ``memory`` — the DGC error-feedback buffers are **per-worker** state
  (each worker accumulates its own untransmitted residual); stored with a
  leading ``[world]`` axis sharded over the data axis.
* ``batch_stats`` — BatchNorm running stats are likewise per-worker, matching
  the reference where each Horovod process keeps local BN stats and
  checkpoints them per rank (train.py:60-68).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["TrainState", "shard_state", "state_specs", "with_leading_axis",
           "map_per_worker"]


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    memory: Any
    batch_stats: Any
    #: step-guard counters/window (dgc_tpu.resilience.guard), replicated;
    #: None (the default) is an EMPTY pytree — a guards-off state has
    #: exactly the pre-resilience leaf structure, so old checkpoints
    #: restore unchanged and the guards-off step compiles byte-identically
    guards: Any = None
    #: straggler-adaptive exchange policy state
    #: (dgc_tpu.resilience.adaptive: {"w_frac": [world] f32}), replicated;
    #: same None-is-empty doctrine as ``guards``. Deliberately NOT
    #: checkpointed — the policy is memoryless, and stripping it keeps
    #: old checkpoints AND elastic world-size changes restore-compatible
    #: (training/checkpoint.py strips on save, re-seeds on restore)
    adaptive: Any = None


def with_leading_axis(tree: Any, world_size: int) -> Any:
    """Tile per-worker state to a leading [world] axis (identical initial
    contents on every worker — zeros for memory, init stats for BN)."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (world_size,) + x.shape)
        if hasattr(x, "shape") else x, tree)


def map_per_worker(state: TrainState, fn,
                   per_worker_opt: bool = False) -> TrainState:
    """Apply ``fn`` to each PER-WORKER field subtree — exactly the fields
    :func:`state_specs` shards on the data axis (memory, batch_stats,
    and opt_state under the Adasum per-worker scheme) — leaving the
    replicated fields untouched. The single place that knows which state
    carries a leading ``[world]`` axis; elastic resharding
    (``dgc_tpu.resilience.elastic``) retiles through it so it cannot
    drift from the sharding rules below."""
    out = state.replace(memory=fn(state.memory),
                        batch_stats=fn(state.batch_stats))
    if per_worker_opt:
        out = out.replace(opt_state=fn(state.opt_state))
    return out


def state_specs(state: TrainState, axis="data",
                per_worker_opt: bool = False) -> TrainState:
    """PartitionSpec pytree for shard_map in/out_specs.

    ``axis`` is a mesh-axis name or a tuple of names (the two-tier
    ``('hosts', 'local')`` mesh): per-worker state shards its leading
    [world] axis over all of them.

    ``per_worker_opt``: the Adasum delta-optimizer scheme steps the base
    optimizer on LOCAL gradients, so its state is genuinely per-worker
    (leading [world] axis, like the memory) — declaring it replicated would
    silently keep only shard 0 on any host materialization."""
    return TrainState(
        step=P(),
        params=jax.tree.map(lambda _: P(), state.params),
        opt_state=jax.tree.map(lambda _: P(axis) if per_worker_opt else P(),
                               state.opt_state),
        memory=jax.tree.map(lambda _: P(axis), state.memory),
        batch_stats=jax.tree.map(lambda _: P(axis), state.batch_stats),
        guards=jax.tree.map(lambda _: P(), state.guards),
        adaptive=jax.tree.map(lambda _: P(), state.adaptive),
    )


def shard_state(state: TrainState, mesh: Mesh, axis="data",
                per_worker_opt: Optional[bool] = None,
                dist_opt=None) -> TrainState:
    """Place state on the mesh with the canonical shardings. ``axis``
    accepts a tuple of mesh-axis names for the two-tier mesh.

    Pass the ``DistributedOptimizer`` as ``dist_opt`` and the per-worker
    opt-state flag is derived from it (``per_worker_opt_state``, the Adasum
    scheme) — callers then cannot go out of sync with the step builder.
    Supplying BOTH is rejected rather than silently resolved."""
    if dist_opt is not None:
        if per_worker_opt is not None:
            raise ValueError(
                "pass either dist_opt (flag derived) or per_worker_opt, "
                "not both")
        per_worker_opt = getattr(dist_opt, "per_worker_opt_state", False)
    specs = state_specs(state, axis, bool(per_worker_opt))
    if jax.process_count() > 1:
        # device_put onto a pod-spanning sharding routes every leaf
        # through multihost_utils.assert_equal — one gloo broadcast per
        # leaf to check the hosts agree. Initial state is deterministic
        # and identical on every process (same seed, same code), so the
        # check buys nothing, and its broadcasts can interleave with a
        # previous step's still-draining collectives on the shared gloo
        # communicator, aborting the run with
        # "op.preamble.length <= op.nbytes". Assemble the global arrays
        # collective-free from process-local shards instead — the same
        # contract host_local_to_global uses for batch assembly.
        def place(x, sp):
            host = np.asarray(jax.device_get(x))
            return jax.make_array_from_callback(
                host.shape, NamedSharding(mesh, sp),
                lambda idx, h=host: h[idx])
        return jax.tree.map(place, state, specs)
    return jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        state, specs)
