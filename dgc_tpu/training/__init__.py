from dgc_tpu.training.state import (
    TrainState,
    shard_state,
    state_specs,
    with_leading_axis,
)
from dgc_tpu.training.step import build_eval_step, build_train_step
from dgc_tpu.training.lr import (
    cosine_schedule,
    make_lr_schedule,
    multistep_schedule,
)

__all__ = [
    "TrainState", "shard_state", "state_specs", "with_leading_axis",
    "build_eval_step", "build_train_step",
    "cosine_schedule", "make_lr_schedule", "multistep_schedule",
]
