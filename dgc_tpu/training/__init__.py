from dgc_tpu.training.state import (
    TrainState,
    shard_state,
    state_specs,
    with_leading_axis,
)
from dgc_tpu.training.step import (
    FlatSetup,
    build_eval_step,
    build_train_step,
    make_flat_setup,
    make_flat_state,
    make_loss_fn,
)
from dgc_tpu.training.lr import (
    cosine_schedule,
    make_lr_schedule,
    multistep_schedule,
)

__all__ = [
    "TrainState", "shard_state", "state_specs", "with_leading_axis",
    "build_eval_step", "build_train_step", "make_loss_fn",
    "FlatSetup", "make_flat_setup", "make_flat_state",
    "cosine_schedule", "make_lr_schedule", "multistep_schedule",
]
