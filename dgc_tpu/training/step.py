"""Jitted train/eval steps over the device mesh.

The reference's hot loop (/root/reference/train.py:267-301 + the hook machinery
in dgc/horovod/optimizer.py:105-194) — micro-batch forward/backward, per-tensor
async compress+allgather during backward, drain + decompress + SGD in
``optimizer.step()`` — collapses here into ONE jitted XLA program per step:

    shard_map over mesh('data'):
        scan over micro-batches: forward + backward (grad accumulation)
        compress (momentum-corrected sampled top-k, per worker)
        all_gather (values, indices) over the data axis   [ICI]
        scatter-add + average; dense psum fallback for 1-D params
        DGCSGD update (replicated)

XLA's latency-hiding scheduler overlaps the collectives with independent
compute, replacing the reference's Python-managed async handles; there is no
``synchronize()`` because the dataflow graph *is* the synchronization.

Only parameters with ndim > 1 are compressed (reference train.py:136-140);
biases and BatchNorm fall through to dense psum.
"""

from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from dgc_tpu.ops import kernels
from dgc_tpu.optim.distributed import DistributedOptimizer
from dgc_tpu.resilience import faults as _faults
from dgc_tpu.telemetry import trace as _trace
from dgc_tpu.training.state import TrainState, state_specs, with_leading_axis
from dgc_tpu.utils.compat import shard_map

__all__ = ["build_train_step", "build_eval_step", "make_loss_fn",
           "FlatSetup", "make_flat_setup", "make_flat_state"]


class FlatSetup(NamedTuple):
    """Static layouts + engine for the flat-buffer step (see
    ``dgc_tpu.compression.flat``): parameters, optimizer state, and memory
    cross the jit boundary as a handful of flat [P]-sized HBM buffers instead
    of hundreds of per-tensor arrays — per-buffer dispatch overhead dominates
    small-model steps, and all unflattening fuses away inside the program."""
    layout: Any          # ParamLayout over params
    stats_layout: Any    # ParamLayout over batch_stats
    engine: Any          # compressor flat-exchange engine


def make_flat_setup(variables, dist_opt: DistributedOptimizer,
                    plan=None) -> FlatSetup:
    """Build layouts + engine from initialized model variables. Rebuild after
    a warm-up compress-ratio change (the engine holds ratio-derived attrs).

    ``plan`` — optional per-bucket exchange plan
    (``dgc_tpu.compression.planner``); a ``Plan`` is re-fit to the fresh
    bucket geometry on every rebuild, so the warmup loop can pass the
    same object each time and only recompiles when ``plan.key()``
    actually changes."""
    from dgc_tpu.compression.flat import ParamLayout
    layout, engine = dist_opt.make_flat(variables["params"], plan=plan)
    stats_layout = ParamLayout(variables.get("batch_stats", {}))
    return FlatSetup(layout, stats_layout, engine)


def make_flat_state(variables, dist_opt: DistributedOptimizer,
                    setup: FlatSetup, world_size: int,
                    guards=None, adaptive=None) -> TrainState:
    """Initial flat TrainState (params/opt replicated; memory and BN stats
    per-worker with a leading [world] axis, as in ``dgc_tpu.training.state``).

    ``guards`` — a ``resilience.guard.GuardConfig`` to carry guard
    counters in the state (pass the SAME config to
    :func:`build_train_step`); None keeps the pre-resilience pytree.

    ``adaptive`` — a ``resilience.adaptive.AdaptiveConfig`` to carry the
    straggler-adaptive send-fraction verdict in the state (again pass the
    SAME config to :func:`build_train_step`); None keeps the field an
    empty pytree, so the off-path state is structurally unchanged."""
    flat_params = setup.layout.flatten(variables["params"])
    flat_stats = setup.stats_layout.flatten(variables.get("batch_stats", {}))
    opt_state = dist_opt.init(flat_params)
    if dist_opt.per_worker_opt_state:
        opt_state = with_leading_axis(opt_state, world_size)
    if guards is not None:
        from dgc_tpu.resilience import guard as _guard
        gstate = _guard.init_state(guards)
    else:
        gstate = None
    if adaptive is not None:
        from dgc_tpu.resilience import adaptive as _adaptive
        astate = _adaptive.init_state(world_size)
    else:
        astate = None
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=flat_params,
        opt_state=opt_state,
        memory=with_leading_axis(setup.engine.init_memory(), world_size),
        batch_stats=with_leading_axis(flat_stats, world_size),
        guards=gstate,
        adaptive=astate)


def _squeeze0(tree):
    return jax.tree.map(lambda x: x[0], tree)


def _expand0(tree):
    return jax.tree.map(lambda x: x[None], tree)


def make_loss_fn(apply_fn: Callable) -> Callable:
    """Cross-entropy loss closure over a flax apply_fn with BN mutation
    (the reference criterion is CrossEntropyLoss, configs/__init__.py:17)."""

    def loss_fn(params, batch_stats, images, labels, scale, dropout_key):
        variables = {"params": params}
        rngs = None
        if batch_stats:
            variables["batch_stats"] = batch_stats
        if dropout_key is not None:
            rngs = {"dropout": dropout_key}
        if batch_stats:
            logits, updated = apply_fn(variables, images, train=True,
                                       mutable=["batch_stats"], rngs=rngs)
            new_stats = updated["batch_stats"]
        else:
            logits = apply_fn(variables, images, train=True, rngs=rngs)
            new_stats = batch_stats
        # loss math in f32 regardless of the model compute dtype (the
        # standard mixed-precision recipe; a no-op for f32 models)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels).mean() * scale
        return loss, new_stats

    return loss_fn


def build_train_step(apply_fn: Callable, dist_opt: DistributedOptimizer,
                     mesh: Mesh, num_batches_per_step: int = 1,
                     use_dropout: bool = False, donate: bool = True,
                     flat: Optional[FlatSetup] = None,
                     model_dtype=None, telemetry: bool = False,
                     guards=None, fleet: bool = False, adaptive=None):
    """Build the jitted data-parallel DGC train step.

    Returns ``step_fn(state, images, labels, key) -> (state, metrics)`` where
    ``images`` is ``[world·nbps·bs, H, W, C]`` sharded on axis 0 and metrics
    holds the psum-averaged loss (reference train.py:298). ``nbps`` micro-batch
    gradient accumulation follows train.py:287-294: each micro-loss is scaled
    by 1/nbps and gradients sum before a single exchange+update.

    With ``flat`` (a :class:`FlatSetup`), the state must come from
    :func:`make_flat_state` and the whole pipeline runs over flat HBM buffers
    (fused exchange, two collectives per step) — the default fast path.

    ``model_dtype`` (flat path only): explicit mixed precision — the
    model must be constructed with the same narrow ``dtype`` (e.g.
    ``vgg16_bn(dtype=jnp.bfloat16)``, configs/bf16.py); the step then
    casts the flat f32 parameter buffer to it ONCE inside the
    differentiated function and the model consumes narrow views, so XLA
    has no per-consumer weight conversions to materialize (its auto-bf16
    conv precision was measured materializing THREE whole-[P] converted
    copies per DGC step at VGG — ~3.5 ms — while fusing them away in the
    dense build). Parameters, gradients, the optimizer, and the whole
    compression pipeline stay f32: the cast's vjp converts the narrow
    cotangent back to one f32 [P] buffer.

    Both paths share ONE worker implementation, parameterized only on how
    params/grads/stats are represented and which update entrypoint runs —
    so their numerics cannot drift apart.

    ``telemetry=True`` (flat path only): the metrics dict gains a
    ``"telemetry"`` pytree of per-step compression-health scalars
    (``dgc_tpu.telemetry.registry.STEP_METRICS``, pmean'd over the mesh) as
    an aux output of the SAME jitted program — zero extra host syncs or
    dispatches; feed it to :class:`dgc_tpu.telemetry.sink.TelemetrySink`.
    The default ``False`` traces none of it, leaving the compiled step
    byte-identical to the pre-telemetry program.

    ``guards`` (flat path only): a ``resilience.guard.GuardConfig``
    enabling the in-graph step guards — nonfinite-grad/loss detection and
    the loss-spike circuit breaker, both skipping the WHOLE update
    atomically (params, optimizer state, DGC momentum + residual, and BN
    stats revert; only the step counter advances). The state must carry
    guard counters (``make_flat_state(..., guards=cfg)``) and the metrics
    dict gains a ``"guards"`` pytree
    (``telemetry.registry.GUARD_METRICS``). Zero extra collectives: the
    per-worker badness flag rides the existing loss psum as a stacked
    ``[2]`` vector, and the skip is a traced select — no host syncs. The
    default None compiles the guards away byte-identically (contract-
    pinned in ``dgc_tpu.analysis.suite``).

    ``fleet=True`` (requires ``telemetry=True``): cross-worker dispersion
    taps (``dgc_tpu.telemetry.fleet``, ISSUE 10). The step signature
    gains a fifth argument — ``step_fn(state, images, labels, key,
    clock)`` where ``clock`` is the host-stamped [world] f32 dispatch-
    interval input (``fleet.make_clock``) — and the metrics dict gains a
    ``"fleet"`` pytree (``registry.FLEET_METRICS``: per-worker clock/
    grad-norm/residual-mass/sent-ratio columns + straggler/skew scalars).
    The telemetry pmean is REPLACED by one packed all_gather that yields
    both the telemetry means and the fleet columns, so the fleet build
    costs at most ONE packed collective over the plain step and zero
    host syncs (contract-pinned). ``fleet=False`` traces none of it:
    byte-identical to the pre-fleet program.

    ``adaptive`` (requires ``fleet=True``): a
    ``resilience.adaptive.AdaptiveConfig`` enabling the straggler-
    adaptive exchange — each worker reads last step's replicated policy
    verdict (``state.adaptive["w_frac"][widx]``) and transmits that
    fraction of its per-bucket quota (the tail of the fixed payload is
    masked to the structural sentinel pad, so wire shapes never change);
    the next verdict is recomputed in-graph from the gathered ``w_clock``
    column the fleet taps already carry. Zero extra collectives, zero
    recompiles, and the withheld mass stays in the error-feedback
    residual (all contract-pinned in ``dgc_tpu.analysis.suite``). The
    state must carry the policy field (``make_flat_state(...,
    adaptive=cfg)``) and the fleet metrics gain a real ``w_eff_ratio``
    column. The default None compiles it all away byte-identically.
    """
    if fleet and not telemetry:
        raise ValueError("fleet dispersion taps require telemetry=True "
                         "(they extend the telemetry lane)")
    if adaptive is not None and not fleet:
        raise ValueError("adaptive straggler exchange requires fleet=True "
                         "(the policy reads the gathered w_clock lane)")
    if telemetry and flat is None:
        raise ValueError("telemetry taps require the flat engine path "
                         "(pass flat=make_flat_setup(...))")
    if guards is not None and flat is None:
        raise ValueError("step guards require the flat engine path "
                         "(pass flat=make_flat_setup(...))")
    if (flat is not None and getattr(flat.engine, "checksum", False)
            and guards is None):
        raise ValueError(
            "DGCCompressor(checksum=True) needs guards= on the step "
            "builder — the mismatch counter travels in the guard metrics")
    if guards is not None:
        from dgc_tpu.resilience import guard as _guard
    if adaptive is not None:
        from dgc_tpu.resilience import adaptive as _adaptive
    loss_fn = make_loss_fn(apply_fn)
    world = dist_opt.world_size
    axes = dist_opt.data_axes      # (axis,) flat, (hosts, local) two-tier
    local_size = dist_opt.local_size
    nbps = num_batches_per_step
    r_nbps = 1.0 / nbps
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    if flat is not None:
        layout, stats_layout, engine = flat
        unpack_params = layout.unflatten
        unpack_stats = stats_layout.unflatten   # empty layout -> {} and back
        pack_grads = layout.flatten
        pack_stats = stats_layout.flatten

        want_health = (guards is not None
                       and getattr(engine, "checksum", False))

        def do_update(grads, params, opt_state, memory, key,
                      send_frac=None):
            health = {} if want_health else None
            if telemetry:
                upd, opt_state, memory, tstats = dist_opt.update_flat(
                    grads, opt_state, params, memory, key, engine,
                    telemetry=True, health_out=health,
                    send_frac=send_frac)
                return params + upd, opt_state, memory, tstats, health
            upd, opt_state, memory = dist_opt.update_flat(
                grads, opt_state, params, memory, key, engine,
                health_out=health, send_frac=send_frac)
            return params + upd, opt_state, memory, None, health
    else:
        unpack_params = unpack_stats = pack_grads = pack_stats = (
            lambda x: x)

        def do_update(grads, params, opt_state, memory, key,
                      send_frac=None):
            del send_frac   # per-tensor path: adaptive requires flat
            upd, opt_state, memory = dist_opt.update(
                grads, opt_state, params, memory, key)
            return (optax.apply_updates(params, upd), opt_state, memory,
                    None, None)

    per_worker_opt = dist_opt.per_worker_opt_state

    def worker(state: TrainState, images, labels, key, clock=None):
        if (flat is not None and model_dtype is None
                and getattr(dist_opt.compressor, "attributes", None)):
            # break XLA's view of the per-tensor params as one [P]
            # source: its auto-bf16 conv precision hoists the weight
            # conversions into whole-buffer converted copies in the DGC
            # build (~2.9 ms/step at VGG, r5 device profile + optimized
            # HLO) while fusing them per-conv in the dense build. Views
            # the simplifier can rewrite as slice(reshape(P)) get a real
            # custom-call boundary (opaque_view — barriers are stripped
            # before the late pass that forms the whole-buffer
            # converts); the rest keep the cheaper optimization_barrier,
            # which recovers a further ~0.4 ms by itself. The
            # model_dtype path does its own single cast and never reads
            # this tree.
            lay = flat.layout
            risky = lay.convert_hoist_risky()

            def guard(n, a, fp=state.params):
                if n not in risky:
                    return jax.lax.optimization_barrier(a)
                base, size = lay.offsets[n], lay.sizes[n]
                if kernels.opaque_view_eligible(lay.total, base, size):
                    # streamed straight from the flat buffer — the
                    # sliced operand form pays a second materialized
                    # tensor-sized copy
                    return kernels.opaque_view_from(
                        fp, base, size).reshape(lay.shapes[n])
                return kernels.opaque_view(a)

            params = lay.unflatten(state.params, transform=guard)
        else:
            params = unpack_params(state.params)
        memory = _squeeze0(state.memory)
        packed_stats = _squeeze0(state.batch_stats)

        if len(axes) == 1:
            widx = jax.lax.axis_index(axes[0])
            key = jax.random.fold_in(key, widx)
            dropout_key, sparsify_key = jax.random.split(key)
        else:
            # two-tier: dropout differs per worker; the SPARSIFY key is
            # shared within a local group — every worker of a node holds the
            # identical node-aggregated gradient and must make the identical
            # selection, or the replicated (P()) outputs would diverge
            nidx = jax.lax.axis_index(axes[0])
            widx = nidx * local_size + jax.lax.axis_index(axes[1])
            dropout_key = jax.random.split(
                jax.random.fold_in(key, widx))[0]
            sparsify_key = jax.random.split(
                jax.random.fold_in(key, world + nidx))[1]

        if adaptive is not None:
            # this worker's send fraction: LAST step's replicated policy
            # verdict, carried in the donated state (one-step feedback —
            # no extra collective; the verdict below refreshes it)
            frac = state.adaptive["w_frac"][widx]
        else:
            frac = None

        mb_images = images.reshape((nbps, -1) + images.shape[1:])
        mb_labels = labels.reshape((nbps, -1))

        if flat is not None and model_dtype is not None:
            # mixed precision over the flat buffer: differentiate w.r.t.
            # the f32 [P] buffer with the narrow cast inside — gradients
            # arrive as ONE flat f32 buffer (no per-tensor pack concat)
            def micro(carry, mb):
                gsum, pstats, losssum, i = carry
                imgs, lbls = mb
                dk = (jax.random.fold_in(dropout_key, i) if use_dropout
                      else None)

                def loss_flat(fp):
                    return loss_fn(unpack_params(fp.astype(model_dtype)),
                                   unpack_stats(pstats), imgs, lbls,
                                   r_nbps, dk)

                (lval, new_stats), gflat = jax.value_and_grad(
                    loss_flat, has_aux=True)(state.params)
                return (gsum + gflat, pack_stats(new_stats),
                        losssum + lval, i + 1), None
        else:
            def micro(carry, mb):
                gsum, pstats, losssum, i = carry
                imgs, lbls = mb
                dk = (jax.random.fold_in(dropout_key, i) if use_dropout
                      else None)
                (lval, new_stats), grads = grad_fn(
                    params, unpack_stats(pstats), imgs, lbls, r_nbps, dk)
                gsum = jax.tree.map(jnp.add, gsum, pack_grads(grads))
                return (gsum, pack_stats(new_stats), losssum + lval,
                        i + 1), None

        stats0, memory0 = packed_stats, memory
        zeros = jax.tree.map(jnp.zeros_like, state.params)
        with _trace.phase("fwd_bwd"):
            (grads, packed_stats, loss, _), _ = jax.lax.scan(
                micro, (zeros, packed_stats, jnp.zeros((), jnp.float32),
                        jnp.zeros((), jnp.int32)),
                (mb_images, mb_labels))
        if _faults.armed():
            # deterministic NaN injection at the armed step (tests only;
            # identity — zero ops — when DGC_FAULTS is unset)
            grads = _faults.inject_nan_grads(grads, state.step)

        opt_state0 = (_squeeze0(state.opt_state) if per_worker_opt
                      else state.opt_state)
        with _trace.phase("update"):
            new_params, opt_state, memory, tstats, health = do_update(
                grads, state.params, opt_state0, memory, sparsify_key,
                send_frac=frac)

        # dgcver dtype-flow anchor (analysis/verify.py): the loss lane is
        # an f32 source — zero HLO ops, contracts unchanged
        loss = kernels.vtag(loss, "dgcver.src.loss")
        if guards is not None:
            # the per-worker badness flag rides the loss all-reduce as a
            # stacked [2] vector — same collective count as unguarded,
            # and every worker computes the identical verdict
            with _trace.phase("loss"):
                bad_local = _guard.nonfinite_flag(grads, loss)
                packed = jax.lax.psum(jnp.stack([loss, bad_local]), axes)
                mean_loss = packed[0] / world
            bad_count = packed[1]
        else:
            with _trace.phase("loss"):
                mean_loss = jax.lax.psum(loss, axes) / world
        metrics = {"loss": mean_loss}
        if fleet:
            # ONE packed all_gather yields the telemetry means AND the
            # per-worker dispersion columns — the pmean below is subsumed
            # (a gather strictly dominates a mean), so the fleet build
            # costs at most one packed collective over the plain step
            from dgc_tpu.telemetry import fleet as _fleet
            if isinstance(memory, dict) and "gossip_age" in memory:  # dgclint: ok[tracer-branch] — pytree-key membership is trace-static, not a tracer test
                # gossip on: the age vector is replicated by construction,
                # so indexing this worker's entry costs zero collectives
                g_stale = memory["gossip_age"][widx]
                g_forced = memory["gossip_forced"]
            else:
                g_stale = g_forced = None
            metrics["telemetry"], metrics["fleet"] = _fleet.gather_stats(
                tstats, axes, clock=clock, total_elems=layout.total,
                eff_ratio=frac, staleness=g_stale, forced=g_forced)
        elif telemetry:
            # per-worker stats -> replicated (mesh mean), matching the
            # loss: the collective rides the same program (no dispatch)
            from dgc_tpu.telemetry import taps
            metrics["telemetry"] = taps.pmean_stats(tstats, axes)

        if adaptive is not None:
            # next step's verdict from THIS step's gathered clock column.
            # Pure function of replicated values -> every worker computes
            # the identical [W] vector with no new exchange; memoryless,
            # so no guard revert is needed (a skipped step's clock is as
            # real a straggler signal as an applied one)
            new_adaptive = {"w_frac": _adaptive.update_policy(
                adaptive, metrics["fleet"]["w_clock"])}
        else:
            new_adaptive = state.adaptive

        if guards is not None:
            # dgcver anchor: guard counters are f32 sources too (tagged
            # only on guarded builds, so guards-off stays untouched)
            skip, gstate, gmetrics = _guard.apply(
                guards, kernels.vtag(state.guards, "dgcver.src.guards"),
                bad_count=bad_count,
                mean_loss=mean_loss,
                checksum_failures=(health or {}).get("checksum_failures"))
            # ATOMIC skip: every piece of the update reverts together —
            # params, optimizer state, DGC momentum + residual (the
            # exchange's memory write included), and BN stats. A partial
            # revert would silently desynchronize the error-feedback
            # residual from the transmit record. Step counter advances.
            new_params = _guard.tree_select(skip, state.params, new_params)
            opt_state = _guard.tree_select(skip, opt_state0, opt_state)
            memory = _guard.tree_select(skip, memory0, memory)
            packed_stats = _guard.tree_select(skip, stats0, packed_stats)
            metrics["guards"] = gmetrics
        else:
            gstate = state.guards

        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=(_expand0(opt_state) if per_worker_opt
                       else opt_state),
            memory=_expand0(memory),
            batch_stats=_expand0(packed_stats),
            guards=gstate,
            adaptive=new_adaptive,
        )
        return new_state, metrics

    metric_specs = {"loss": P()}
    if telemetry:
        from dgc_tpu.telemetry import registry
        metric_specs["telemetry"] = registry.step_out_specs(P)
    if guards is not None:
        from dgc_tpu.telemetry import registry
        metric_specs["guards"] = registry.guard_out_specs(P)
    if fleet:
        from dgc_tpu.telemetry import registry
        metric_specs["fleet"] = registry.fleet_out_specs(P)

        @partial(jax.jit, donate_argnums=(0,) if donate else ())
        def step_fn(state, images, labels, key, clock):
            specs = state_specs(state, axes, per_worker_opt)
            sharded = shard_map(
                worker, mesh=mesh,
                in_specs=(specs, P(axes), P(axes), P(), P(axes)),
                out_specs=(specs, metric_specs),
                check_vma=False)
            return sharded(state, images, labels, key, clock)

        return step_fn

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step_fn(state, images, labels, key):
        specs = state_specs(state, axes, per_worker_opt)
        sharded = shard_map(
            worker, mesh=mesh,
            in_specs=(specs, P(axes), P(axes), P()),
            out_specs=(specs, metric_specs),
            check_vma=False)
        return sharded(state, images, labels, key)

    return step_fn


def build_eval_step(apply_fn: Callable, mesh: Mesh, world_size: int,
                    axis="data", topk: Tuple[int, ...] = (1, 5),
                    flat: Optional[FlatSetup] = None):
    """Jitted eval step: per-worker inference with local BN stats, top-k
    correct counts Sum-reduced over the mesh (reference train.py:304-328).
    With ``flat``, params/batch_stats are the flat buffers from the flat
    train state. ``axis`` accepts a tuple of mesh-axis names (two-tier
    mesh); counts reduce over all of them."""

    def worker(params, batch_stats, images, labels):
        batch_stats = _squeeze0(batch_stats)
        if flat is not None:
            params = flat.layout.unflatten(params)
            batch_stats = (flat.stats_layout.unflatten(batch_stats)
                           if flat.stats_layout.total > 0 else {})
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits = apply_fn(variables, images, train=False)
        counts = {}
        for k in topk:
            kk = min(k, logits.shape[-1])
            _, pred = jax.lax.top_k(logits, kk)
            correct = jnp.any(pred == labels[:, None], axis=-1)
            counts[f"top{k}"] = jax.lax.psum(
                jnp.sum(correct.astype(jnp.int32)), axis)
        counts["count"] = jax.lax.psum(
            jnp.asarray(labels.shape[0], jnp.int32), axis)
        return counts

    @jax.jit
    def eval_fn(params, batch_stats, images, labels):
        out_specs = {f"top{k}": P() for k in topk}
        out_specs["count"] = P()
        sharded = shard_map(
            worker, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(), params),
                      jax.tree.map(lambda _: P(axis), batch_stats),
                      P(axis), P(axis)),
            out_specs=out_specs,
            check_vma=False)
        return sharded(params, batch_stats, images, labels)

    return eval_fn
