"""dgc_tpu — a TPU-native Deep Gradient Compression training framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of the reference
PyTorch/Horovod DGC system (Lin et al., ICLR 2018). The reference's hook-driven
architecture (per-parameter autograd hooks launching async Horovod collectives)
is re-designed as a single jitted, functional train step over an explicit state
pytree, sharded with `jax.shard_map` over a `jax.sharding.Mesh`; the XLA
latency-hiding scheduler overlaps compression+collectives with backward compute
instead of Python-managed handles.

The reference's plugin boundary survives as typed interfaces (see
`dgc_tpu.compression.base.Compressor` and `dgc_tpu.compression.memory.Memory`):
compressors expose compress/decompress/communicate, memories expose
compensate/update, and the distributed optimizer is generic over both.

Top-level names resolve LAZILY (PEP 562): importing the package does not pull
jax/flax/optax. That keeps light consumers light — in particular the spawned
image-decode pool workers (`dgc_tpu.data.datasets._decode_one`) import only
PIL+numpy instead of paying seconds of jax import and hundreds of MB of RSS
per worker.
"""

__version__ = "0.3.0"

_EXPORTS = {
    "DGCCompressor": "dgc_tpu.compression.dgc",
    "Memory": "dgc_tpu.compression.memory",
    "DGCSGDMemory": "dgc_tpu.compression.memory",
    "Compressor": "dgc_tpu.compression.base",
    "NoneCompressor": "dgc_tpu.compression.base",
    "FP16Compressor": "dgc_tpu.compression.base",
    "Compression": "dgc_tpu.compression.base",
    "dgc_sgd": "dgc_tpu.optim.sgd",
    "sgd": "dgc_tpu.optim.sgd",
    "DistributedOptimizer": "dgc_tpu.optim.distributed",
    "AdasumDistributedOptimizer": "dgc_tpu.optim.adasum",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib
        value = getattr(importlib.import_module(_EXPORTS[name]), name)
        globals()[name] = value        # cache: resolve once
        return value
    raise AttributeError(f"module 'dgc_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(__all__))
