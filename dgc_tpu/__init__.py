"""dgc_tpu — a TPU-native Deep Gradient Compression training framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of the reference
PyTorch/Horovod DGC system (Lin et al., ICLR 2018). The reference's hook-driven
architecture (per-parameter autograd hooks launching async Horovod collectives)
is re-designed as a single jitted, functional train step over an explicit state
pytree, sharded with `jax.shard_map` over a `jax.sharding.Mesh`; the XLA
latency-hiding scheduler overlaps compression+collectives with backward compute
instead of Python-managed handles.

The reference's plugin boundary survives as typed interfaces (see
`dgc_tpu.compression.base.Compressor` and `dgc_tpu.compression.memory.Memory`):
compressors expose compress/decompress/communicate, memories expose
compensate/update, and the distributed optimizer is generic over both.
"""

__version__ = "0.1.0"

from dgc_tpu.compression.dgc import DGCCompressor
from dgc_tpu.compression.memory import Memory, DGCSGDMemory
from dgc_tpu.compression.base import Compressor, NoneCompressor, FP16Compressor, Compression
from dgc_tpu.optim.sgd import dgc_sgd, sgd
from dgc_tpu.optim.distributed import DistributedOptimizer
from dgc_tpu.optim.adasum import AdasumDistributedOptimizer

__all__ = [
    "DGCCompressor",
    "Memory",
    "DGCSGDMemory",
    "Compressor",
    "NoneCompressor",
    "FP16Compressor",
    "Compression",
    "dgc_sgd",
    "sgd",
    "DistributedOptimizer",
    "AdasumDistributedOptimizer",
]
