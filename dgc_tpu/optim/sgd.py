"""SGD optimizers as optax-style gradient transformations.

``dgc_sgd`` replicates the reference's ``DGCSGD`` (/root/reference/dgc/optim/
sgd.py:30-70) — the critical *optimizer split* (SURVEY.md §2.9): gradient
momentum is applied **pre-compression** inside the DGC memory, so the optimizer
must NOT re-apply momentum to the gradient. It applies momentum + nesterov only
to the weight-decay term: ``d_p = wd·p`` runs through the momentum buffer, then
the (already momentum-corrected, decompressed) gradient is added raw, and the
parameter moves by ``-lr · d_p``.

``sgd`` replicates stock ``torch.optim.SGD`` (momentum buffer over
``grad + wd·p``) for the dense/no-DGC baseline, so compressed and dense runs
differ only in the gradient path.

Both take ``lr`` as a float or a ``step -> lr`` schedule (the harness drives
per-step warm-up through it, SURVEY.md §2.10) and an optional
``weight_decay_mask`` pytree/callable marking which parameters receive weight
decay (the reference's ``optimize_bn_separately`` puts BN params in a wd=0
group, train.py:121-125). Mask leaves may be booleans (whole-tensor groups,
like the reference's param groups) or 0/1 *arrays* — the latter supports the
flat-buffer path where all parameters live in one [P] array and the BN split
becomes a per-coordinate mask (``ParamLayout.mask_vector``).
"""

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["dgc_sgd", "sgd", "SGDState"]

ScalarOrSchedule = Union[float, Callable[[jax.Array], jax.Array]]


class SGDState(NamedTuple):
    count: jax.Array          # int32 step counter
    momentum_buffer: Any      # pytree like params (None when unused)


def _lr_at(lr: ScalarOrSchedule, count):
    return lr(count) if callable(lr) else lr


def _wd_mask_flat(weight_decay_mask, params, treedef):
    if weight_decay_mask is None:
        return [True] * treedef.num_leaves
    mask = (weight_decay_mask(params) if callable(weight_decay_mask)
            else weight_decay_mask)
    return jax.tree.leaves(mask)


def _make_sgd(per_param_fn, lr, weight_decay_mask, use_buf):
    """Shared scaffolding: flatten, apply per_param_fn per leaf, unflatten."""

    def init(params):
        buf = jax.tree.map(jnp.zeros_like, params) if use_buf else None
        return SGDState(count=jnp.zeros((), jnp.int32), momentum_buffer=buf)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("this transformation requires params")
        lr_t = _lr_at(lr, state.count)
        first = state.count == 0
        flat_g, treedef = jax.tree.flatten(grads)
        flat_p = treedef.flatten_up_to(params)
        flat_buf = (treedef.flatten_up_to(state.momentum_buffer)
                    if use_buf else [None] * len(flat_g))
        flat_mask = _wd_mask_flat(weight_decay_mask, params, treedef)

        flat_updates, flat_new_buf = [], []
        for g, p, buf, m_wd in zip(flat_g, flat_p, flat_buf, flat_mask):
            upd, new_buf = per_param_fn(g, p, buf, m_wd, lr_t, first)
            flat_updates.append(upd)
            flat_new_buf.append(new_buf)

        updates = jax.tree.unflatten(treedef, flat_updates)
        new_buf = (jax.tree.unflatten(treedef, flat_new_buf)
                   if use_buf else None)
        return updates, SGDState(count=state.count + 1,
                                 momentum_buffer=new_buf)

    return optax.GradientTransformation(init, update)


def dgc_sgd(lr: ScalarOrSchedule, momentum: float = 0.9,
            dampening: float = 0.0, weight_decay: float = 0.0,
            nesterov: bool = False,
            weight_decay_mask=None) -> optax.GradientTransformation:
    """DGC-split SGD (reference sgd.py:30-70).

    Per parameter: ``d_p = wd·p``; momentum buffer ``buf = m·buf +
    (1-dampening)·d_p`` (first step: ``buf = d_p`` exactly, matching torch's
    clone-init); ``d_p += m·buf`` (nesterov) or ``d_p = buf``; then
    ``p ← p - lr·(d_p + grad)`` — the gradient bypasses the momentum buffer.
    """
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    use_buf = weight_decay != 0 and momentum != 0

    def per_param(g, p, buf, m_wd, lr_t, first):
        if not isinstance(m_wd, (bool, int)):
            # per-coordinate 0/1 mask (flat-buffer path)
            mv = jnp.asarray(m_wd, p.dtype)
            d_p = weight_decay * mv * p
            if momentum != 0 and weight_decay != 0:
                new_buf = jnp.where(first, d_p,
                                    momentum * buf + (1 - dampening) * d_p)
                # a wd=0 coordinate never touches its buffer (sgd.py:51)
                new_buf = mv * new_buf + (1 - mv) * buf
                d_p = d_p + momentum * new_buf if nesterov else new_buf
            else:
                new_buf = buf
            return -lr_t * (mv * d_p + g), new_buf
        wd = weight_decay if m_wd else 0.0
        if wd != 0:
            d_p = wd * p
            if momentum != 0:
                new_buf = jnp.where(first, d_p,
                                    momentum * buf + (1 - dampening) * d_p)
                d_p = d_p + momentum * new_buf if nesterov else new_buf
            else:
                new_buf = buf
            d_p = d_p + g
        else:
            # buffer still advances on wd-masked params? No: reference keeps
            # per-group wd; a wd=0 group never touches its buffer (sgd.py:51).
            d_p = g
            new_buf = buf
        return -lr_t * d_p, new_buf

    return _make_sgd(per_param, lr, weight_decay_mask, use_buf)


def sgd(lr: ScalarOrSchedule, momentum: float = 0.0, dampening: float = 0.0,
        weight_decay: float = 0.0, nesterov: bool = False,
        weight_decay_mask=None) -> optax.GradientTransformation:
    """Stock torch-semantics SGD for the dense baseline: ``d_p = g + wd·p``;
    ``buf = m·buf + (1-dampening)·d_p`` (first step ``buf = d_p``); nesterov
    ``d_p += m·buf`` else ``d_p = buf``; ``p ← p - lr·d_p``."""
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError("Nesterov momentum requires a momentum and zero dampening")

    use_buf = momentum != 0

    def per_param(g, p, buf, m_wd, lr_t, first):
        if not isinstance(m_wd, (bool, int)):
            # per-coordinate 0/1 mask gates only the wd term; momentum
            # applies to every coordinate (stock torch SGD group semantics)
            d_p = g + weight_decay * jnp.asarray(m_wd, p.dtype) * p
        else:
            d_p = g + (weight_decay * p
                       if (weight_decay != 0 and m_wd) else 0.0)
        if momentum != 0:
            new_buf = jnp.where(first, d_p,
                                momentum * buf + (1 - dampening) * d_p)
            d_p = d_p + momentum * new_buf if nesterov else new_buf
        else:
            new_buf = buf
        return -lr_t * d_p, new_buf

    return _make_sgd(per_param, lr, weight_decay_mask, use_buf)
