from dgc_tpu.optim.sgd import SGDState, dgc_sgd, sgd
from dgc_tpu.optim.distributed import DistributedOptimizer
from dgc_tpu.optim.adasum import AdasumDistributedOptimizer, adasum_allreduce

__all__ = ["SGDState", "dgc_sgd", "sgd", "DistributedOptimizer",
           "AdasumDistributedOptimizer", "adasum_allreduce"]
