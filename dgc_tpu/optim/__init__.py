from dgc_tpu.optim.sgd import SGDState, dgc_sgd, sgd
from dgc_tpu.optim.distributed import DistributedOptimizer

__all__ = ["SGDState", "dgc_sgd", "sgd", "DistributedOptimizer"]
