"""Adasum delta-optimizer variant (C5 parity).

TPU-native equivalent of the reference's ``_DistributedAdasumOptimizer``
(/root/reference/dgc/horovod/optimizer.py:197-367, selected by its factory
when ``op == Adasum``, :407-417; library-only — the harness always passes
``op=Average``, train.py:149). The scheme: apply the base optimizer LOCALLY
first, treat the resulting parameter delta as the quantity to exchange, and
combine deltas across workers with the Adasum operator instead of averaging —
Adasum scales each contribution by ``1 - <a,b>/(2|a|^2)`` so aligned deltas
average while orthogonal deltas add, making the effective step robust to
large worker counts.

Mapping to the functional design: the reference stashes ``p_start``, steps
the wrapped optimizer in place, sends ``delta = p - p_start`` through
``compression.compress -> communicate(op=Adasum)``, and in ``step()``
decompresses and applies the reduced delta to the stashed start
(optimizer.py:267-310, 337-360). Here the base optax transformation already
returns the delta (``updates``), so the flow is one line of dataflow:
``updates -> engine.exchange(op='adasum') -> apply``. Compressed payloads are
scatter-add SUMMED (the reference's decompress skips the ``/world_size`` for
any op other than Average, compression.py:192-193); the dense block is
combined with the true pairwise-recursive Adasum operator.
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from dgc_tpu.optim.distributed import DistributedOptimizer
from dgc_tpu.utils.pytree import named_flatten, named_unflatten

__all__ = ["adasum_pair", "adasum_reduce", "adasum_allreduce",
           "AdasumDistributedOptimizer"]


def adasum_pair(a: jax.Array, b: jax.Array) -> jax.Array:
    """Combine two delta vectors: ``(1 - <a,b>/2|a|^2) a +
    (1 - <a,b>/2|b|^2) b`` (the Adasum operator; identical vectors give the
    vector back, orthogonal vectors add)."""
    dot = jnp.sum(a * b)
    asq = jnp.sum(a * a)
    bsq = jnp.sum(b * b)
    fa = jnp.where(asq > 0, 1.0 - dot / (2 * asq), 1.0)
    fb = jnp.where(bsq > 0, 1.0 - dot / (2 * bsq), 1.0)
    return fa * a + fb * b


def adasum_reduce(gathered: jax.Array) -> jax.Array:
    """Pairwise-recursive Adasum over a [W, P] stack (Horovod's recursive
    halving order: neighbors first, then pairs of pairs)."""
    vecs = [gathered[w] for w in range(gathered.shape[0])]
    while len(vecs) > 1:
        nxt = [adasum_pair(vecs[i], vecs[i + 1])
               for i in range(0, len(vecs) - 1, 2)]
        if len(vecs) % 2:
            nxt.append(vecs[-1])
        vecs = nxt
    return vecs[0]


def adasum_allreduce(x: jax.Array, axis_name: str,
                     world_size: int) -> jax.Array:
    """Adasum-combine ``x`` across the mesh axis (replaces the reference's
    ``hvd.allreduce_(op=Adasum)``).

    Power-of-two worlds run recursive doubling over ``ppermute``: log2(W)
    rounds, O(P) memory per device, the same binary combine tree as
    :func:`adasum_reduce`. ``adasum_pair`` is symmetric mathematically but
    NOT bitwise under compilation (XLA fuses ``fa*a + fb*b`` into an FMA
    whose rounding depends on operand order), so each pair's two members
    must evaluate the combine with the IDENTICAL operand order: the
    lower-indexed member's value always goes first. That determinism is
    what makes every device converge to the bitwise-identical result — the
    replication invariant the reference gets from its single collective
    (/root/reference/dgc/horovod/optimizer.py:283-310). Other world sizes
    fall back to a gathered reduce (O(W*P) memory), which is replicated by
    construction (every device reduces the same [W, P] stack in the same
    order)."""
    if world_size == 1:
        return x
    if world_size & (world_size - 1) == 0:
        idx = jax.lax.axis_index(axis_name)
        d = 1
        while d < world_size:
            perm = [(i, i ^ d) for i in range(world_size)]
            other = jax.lax.ppermute(x, axis_name, perm)
            # bit d of idx decides which pair member we are; order the
            # operands so both members compute adasum_pair(lo, hi)
            is_lo = (idx & d) == 0
            lo = jnp.where(is_lo, x, other)
            hi = jnp.where(is_lo, other, x)
            x = adasum_pair(lo, hi)
            d *= 2
        return x
    return adasum_reduce(jax.lax.all_gather(x, axis_name))


class AdasumDistributedOptimizer(DistributedOptimizer):
    """Delta-optimizer composition: local base-optimizer step, compressed
    Adasum exchange of the delta. Flat-engine path only (the per-tensor
    oracle path exchanges gradients, not deltas — use the default
    ``DistributedOptimizer`` there, as the reference harness does).

    The base optimizer steps on LOCAL gradients (reference
    optimizer.py:267-275), so its state is per-worker — the train step
    stores it with a leading [world] axis like the DGC memory.

    **Two-tier composition** (``local_axis_name`` set): the node-aggregated
    Adasum — per-worker deltas are dense-MEANED over the near-free ICI
    axis first, then each node acts as ONE Adasum participant across the
    host/DCN axis (sparse payloads scatter-add summed, the dense tail
    pairwise-Adasum-combined). This is Horovod's own hierarchical Adasum
    recipe (in-node reduce + normalize, Adasum across nodes) applied to
    the reference's "sparsified nodes" regime
    (/root/reference/README.md:126-128): mathematically the reference's
    Adasum (optimizer.py:197-367) with the node mean as each worker's
    delta."""

    per_worker_opt_state = True

    def update(self, grads, opt_state, params, mem_state, key=None):
        """Per-tensor Adasum delta exchange (reference
        _DistributedAdasumOptimizer, optimizer.py:197-367): the base
        optimizer steps on LOCAL gradients first (:267-275), then each
        tensor's delta goes through the compressor — sparse payloads
        allgather + scatter-add SUM (the reference's decompress divides
        only under Average, compression.py:192-193), dense-fallback
        deltas combine with the true pairwise Adasum operator
        (:283-310's ``op=Adasum`` allreduce) and take the
        non-accumulating momentum correction like any fallback tensor
        (compression.py:198). Parity path, not a performance one — the
        flat-engine :meth:`update_flat` is the fast route.

        Two-tier (``local_axis_name`` set): the node-aggregated Adasum,
        mirroring :meth:`update_flat`/the flat engine — per-worker deltas
        are dense-MEANED over the local (ICI) axis first, then each node is
        ONE Adasum participant across ``axis_name`` (``num_nodes``
        participants, not ``world_size``)."""
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        if self.local_axis_name is not None:
            # the node-mean delta is the Adasum participant (same recipe
            # as FlatDGCEngine.exchange's op="adasum" two-tier branch)
            updates = jax.tree.map(
                lambda u: jax.lax.psum(u, self.local_axis_name)
                / self.local_size, updates)
        named, treedef = named_flatten(updates)
        comp = self.compressor
        out = {}
        for i, (name, delta) in enumerate(named.items()):
            k = jax.random.fold_in(key, i) if key is not None else None
            payload, ctx, mem_state = comp.compress(mem_state, name, delta,
                                                    k)
            if getattr(ctx, "compressed", False):
                gathered = comp.communicate(payload, ctx, self.axis_name,
                                            self.num_nodes)
                out[name], mem_state = comp.decompress(
                    gathered, ctx, mem_state, self.num_nodes, op="adasum")
            else:
                red = adasum_allreduce(delta, self.axis_name,
                                       self.num_nodes)
                corrected, mem_state = comp.memory.compensate(
                    mem_state, name, red.reshape(-1), accumulate=False)
                out[name] = corrected.reshape(delta.shape)
        return named_unflatten(out, treedef), opt_state, mem_state

    def update_flat(self, flat_grads, opt_state, flat_params, mem_state,
                    key, engine, telemetry: bool = False,
                    health_out=None,
                    send_frac=None) -> Tuple[jax.Array, object, dict]:
        if telemetry:
            raise NotImplementedError(
                "telemetry taps are not wired through the Adasum flat path")
        if send_frac is not None:
            raise NotImplementedError(
                "straggler-adaptive send fractions are not wired through "
                "the Adasum flat path")
        # local step FIRST (reference optimizer.py:267-275: the wrapped
        # optimizer advances on local gradients, producing the delta)
        updates, opt_state = self.optimizer.update(flat_grads, opt_state,
                                                   flat_params)
        reduced, mem_state = engine.exchange(
            updates, mem_state, key, self.axis_name, self.num_nodes,
            op="adasum", local_axis=self.local_axis_name,
            local_size=self.local_size, health_out=health_out)
        return reduced, opt_state, mem_state
