"""Distributed optimizer — the composition point generic over compressors.

TPU-native equivalent of the reference's patched Horovod
``_DistributedOptimizer`` (/root/reference/dgc/horovod/optimizer.py:105-194).
The reference registers per-parameter autograd hooks that launch async
collectives during backward and drains them in ``step()``; here the exchange
is ordinary dataflow inside the jitted step — XLA's latency-hiding scheduler
overlaps the collectives with the remaining backward compute, which is the
compiler-managed version of the reference's hook overlap (SURVEY.md §2
"Async overlap" row).

The plugin boundary survives intact (optimizer.py:39-40): for every gradient
the optimizer calls ``compressor.compress → communicate → decompress`` and is
otherwise generic over the compressor/memory pair. ``NoneCompressor`` yields
plain dense psum-averaging, ``DGCCompressor`` the sparse allgather path.

Payload fusion: with ``fuse_payloads=True`` (default) all sparse (values,
indices) payloads are concatenated into two arrays and exchanged with exactly
two ``all_gather`` calls per step instead of 2·T — the TPU answer to the
reference's per-tensor named-handle fusion and to its stated thresholding
overhead caveat (README.md:130-138).
"""

from typing import Any, Dict, Optional, Tuple

import jax
import optax

from dgc_tpu.compression.base import Compressor
from dgc_tpu.utils.pytree import named_flatten, named_unflatten

__all__ = ["DistributedOptimizer"]


class DistributedOptimizer:
    """Wraps a gradient transformation with compressed gradient exchange.

    Args:
      optimizer: base optax-style transformation (e.g. ``dgc_sgd``).
      compressor: the compression plugin (``DGCCompressor``,
        ``NoneCompressor``, ...). Its ``memory`` handles error feedback.
      axis_name: mesh axis over which gradients are exchanged (the
        host/DCN axis in two-tier mode).
      world_size: static TOTAL number of workers (across all axes).
      fuse_payloads: concatenate sparse payloads into one exchange.
      local_axis_name: set to enable the **two-tier hierarchical
        exchange** (the real form of the reference's "#Sparsified Nodes <
        #GPUs" regime, /root/reference/README.md:126-128,133-134): the
        gradient is first dense-aggregated over this mesh axis (intra-host
        ICI, near-free), then the sparse DGC exchange runs over
        ``axis_name`` only (cross-host DCN) among ``world_size //
        local_size`` sparsified nodes.
      local_size: workers per node on ``local_axis_name``; must divide
        ``world_size``.
    """

    #: True when the wrapped optimizer steps on LOCAL (pre-exchange)
    #: gradients and its state is therefore per-worker (Adasum scheme) —
    #: the train step then stores it with a leading [world] axis
    per_worker_opt_state = False

    def __init__(self, optimizer: optax.GradientTransformation,
                 compressor: Compressor, axis_name: str = "data",
                 world_size: int = 1, fuse_payloads: bool = True,
                 local_axis_name: Optional[str] = None,
                 local_size: int = 1):
        self.optimizer = optimizer
        self.compressor = compressor
        self.axis_name = axis_name
        self.world_size = world_size
        self.fuse_payloads = fuse_payloads
        if local_axis_name is not None:
            if local_size <= 1:
                raise ValueError(
                    "two-tier mode needs local_size > 1 (got "
                    f"{local_size}); omit local_axis_name for flat DP")
            if world_size % local_size:
                raise ValueError(
                    f"local_size {local_size} must divide world_size "
                    f"{world_size}")
        elif local_size > 1:
            raise ValueError(
                f"local_size {local_size} given without local_axis_name — "
                "name the mesh axis for the dense (ICI) tier to enable the "
                "two-tier exchange")
        self.local_axis_name = local_axis_name
        self.local_size = local_size if local_axis_name is not None else 1
        #: number of sparse-exchange participants on ``axis_name``
        #: (sparsified nodes in two-tier mode; all workers otherwise)
        self.num_nodes = world_size // self.local_size

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Mesh axes the data batch (and per-worker state) shards over —
        ``(axis_name,)`` flat, ``(axis_name, local_axis_name)`` two-tier."""
        if self.local_axis_name is not None:
            return (self.axis_name, self.local_axis_name)
        return (self.axis_name,)

    # ------------------------------------------------------------------ #

    def init(self, params) -> Any:
        return self.optimizer.init(params)

    def init_memory(self, params) -> Dict:
        named, _ = named_flatten(params)
        return self.compressor.memory.init(named.items())

    # ------------------------------------------------------------------ #
    # flat-buffer path (see dgc_tpu.compression.flat)                    #
    # ------------------------------------------------------------------ #

    def make_flat(self, params, plan=None):
        """Build the (ParamLayout, engine) pair for the fused flat-buffer
        pipeline. Compressed names are the compressor's initialized
        attributes (the dim>1 selection, reference train.py:136-140).
        Call again after ``warmup_compress_ratio`` changes the ratio.

        ``plan`` — optional per-bucket exchange plan
        (``compression.planner``); a ``Plan`` instance is re-fit to the
        rebuilt geometry via ``Plan.replan``, so warmup rebuilds keep the
        planner's fabric/cost context without the caller re-planning by
        hand."""
        from dgc_tpu.compression.flat import ParamLayout
        layout = ParamLayout.for_compressor(params, self.compressor)
        if plan is not None and hasattr(plan, "replan"):
            # re-fit to THIS layout's bucket geometry (ratio-dependent):
            # same fabric/cost/candidates, fresh payload sizes. A probe
            # engine supplies the buckets — host-side numpy bookkeeping
            # only, nothing is traced or compiled.
            probe = self.compressor.make_flat_exchange(layout)
            plan = plan.replan(probe)
        engine = self.compressor.make_flat_exchange(layout, plan=plan)
        return layout, engine

    def update_flat(self, flat_grads, opt_state, flat_params, mem_state,
                    key, engine, telemetry: bool = False,
                    health_out: Optional[Dict] = None,
                    send_frac=None):
        """Flat-path analogue of :meth:`update`: fused exchange over the [P]
        buffer, then the wrapped optimizer on the same buffer.

        ``telemetry=True`` returns a fourth element — the engine's per-step
        stat pytree (``dgc_tpu.telemetry``); the default traces nothing
        extra. ``health_out`` forwards to the engine's exchange (payload-
        checksum mismatch counter, see ``resilience.integrity``);
        ``send_frac`` forwards this worker's adaptive send fraction
        (``resilience.adaptive``; None is Python-static off)."""
        if telemetry:
            exchanged, mem_state, tstats = engine.exchange(
                flat_grads, mem_state, key, self.axis_name, self.num_nodes,
                local_axis=self.local_axis_name, local_size=self.local_size,
                telemetry=True, health_out=health_out,
                send_frac=send_frac)
        else:
            exchanged, mem_state = engine.exchange(
                flat_grads, mem_state, key, self.axis_name, self.num_nodes,
                local_axis=self.local_axis_name, local_size=self.local_size,
                health_out=health_out, send_frac=send_frac)
        updates, opt_state = self.optimizer.update(exchanged, opt_state,
                                                   flat_params)
        if telemetry:
            return updates, opt_state, mem_state, tstats
        return updates, opt_state, mem_state

    # ------------------------------------------------------------------ #

    def exchange(self, grads, mem_state, key: Optional[jax.Array]
                 ) -> Tuple[Any, Dict]:
        """Compress + communicate + decompress every gradient leaf.

        ``grads`` is a (nested) pytree; returns the exchanged pytree of the
        same structure plus the updated memory state.

        In two-tier mode the gradients are first dense-averaged over the
        local (ICI) axis; the compress/communicate/decompress pipeline then
        runs among the ``num_nodes`` sparsified nodes on ``axis_name``
        exactly as in flat DP.
        """
        if self.local_axis_name is not None:
            grads = jax.tree.map(
                lambda g: jax.lax.psum(g, self.local_axis_name)
                / self.local_size, grads)
        named, treedef = named_flatten(grads)
        comp = self.compressor

        compressed = {}       # name -> (payload, ctx)
        dense = {}            # name -> (payload, ctx)
        for i, (name, g) in enumerate(named.items()):
            k = jax.random.fold_in(key, i) if key is not None else None
            payload, ctx, mem_state = comp.compress(mem_state, name, g, k)
            (compressed if ctx.compressed else dense)[name] = (payload, ctx)

        out: Dict[str, jax.Array] = {}

        # --- dense fallback path: psum + average (+ memory correction) ---
        for name, (payload, ctx) in dense.items():
            gathered = comp.communicate(payload, ctx, self.axis_name,
                                        self.num_nodes)
            out[name], mem_state = comp.decompress(gathered, ctx, mem_state,
                                                   self.num_nodes)

        # --- sparse path --- (fusion is a compressor capability discovered
        # by duck typing, like the reference's communicate/synchronize
        # dispatch, optimizer.py:39-40)
        if compressed:
            fused = getattr(comp, "exchange_fused", None)
            if self.fuse_payloads and fused is not None and len(compressed) > 1:
                fused_out, mem_state = fused(compressed, self.axis_name,
                                             self.num_nodes, mem_state)
                out.update(fused_out)
            else:
                for name, (payload, ctx) in compressed.items():
                    gathered = comp.communicate(payload, ctx, self.axis_name,
                                                self.num_nodes)
                    out[name], mem_state = comp.decompress(
                        gathered, ctx, mem_state, self.num_nodes)

        ordered = {name: out[name] for name in named}
        return named_unflatten(ordered, treedef), mem_state

    # ------------------------------------------------------------------ #

    def update(self, grads, opt_state, params, mem_state,
               key: Optional[jax.Array] = None):
        """Full distributed update: exchange, then the wrapped optimizer
        (the reference's ``step()`` = synchronize + base step,
        optimizer.py:176-187)."""
        exchanged, mem_state = self.exchange(grads, mem_state, key)
        updates, opt_state = self.optimizer.update(exchanged, opt_state,
                                                   params)
        return updates, opt_state, mem_state
