"""Real-file CIFAR loader path (dgc_tpu/data/datasets.py::CIFAR) against
synthesized pickle-batch trees — the torchpack CIFAR role the reference
configs use (/root/reference/configs/cifar/__init__.py:3). Every other test
and experiment in this zero-egress environment runs the synthetic fallback;
these fixtures cover the pickle parsing, the NCHW->NHWC transpose, the
CIFAR-100 fine_labels key, and the flat base-dir fallback."""

import pickle

import numpy as np
import pytest

from dgc_tpu.data.datasets import CIFAR, SyntheticSplit


def _write_batch(path, images_nchw_flat, labels, label_key=b"labels"):
    with open(path, "wb") as f:
        pickle.dump({b"data": images_nchw_flat, label_key: labels}, f)


def _make_images(n, seed):
    """uint8 [n, 3072] in the CIFAR wire layout (channel-major planes) with
    a per-channel signature so the transpose is verifiable: channel c of
    image i is filled with (i * 3 + c) % 251."""
    rng = np.random.RandomState(seed)
    x = np.zeros((n, 3, 32, 32), np.uint8)
    for i in range(n):
        for c in range(3):
            x[i, c] = (i * 3 + c) % 251
    # sprinkle noise in one corner so accidental equality can't pass
    x[:, :, 0, 0] = rng.randint(0, 255, (n, 3))
    return x.reshape(n, -1)


@pytest.fixture
def cifar10_tree(tmp_path):
    base = tmp_path / "cifar-10-batches-py"
    base.mkdir()
    for b in range(1, 6):
        _write_batch(base / f"data_batch_{b}", _make_images(4, b),
                     [(b + j) % 2 for j in range(4)])
    _write_batch(base / "test_batch", _make_images(4, 99), [0, 1, 0, 1])
    return tmp_path


def test_cifar10_pickle_tree_shapes_and_transpose(cifar10_tree):
    ds = CIFAR(str(cifar10_tree), num_classes=10, synthetic_fallback=False)
    train, test = ds["train"], ds["test"]
    assert len(train) == 20 and len(test) == 4
    assert train.images.shape == (20, 32, 32, 3)
    assert train.images.dtype == np.uint8
    # NCHW plane -> NHWC pixel transpose: channel signature must land on
    # the LAST axis (a missing/wrong transpose would interleave planes)
    for i in (0, 7, 19):
        for c in range(3):
            plane = train.images[i, :, :, c]
            assert plane[1, 1] == (i % 4 * 3 + c) % 251, (i, c)
    # labels concatenated in batch order
    expect = [(b + j) % 2 for b in range(1, 6) for j in range(4)]
    np.testing.assert_array_equal(train.labels, expect)
    # get_batch returns normalized float batches + int labels
    imgs, labels = test.get_batch(np.array([0, 3]))
    assert imgs.shape == (2, 32, 32, 3) and imgs.dtype == np.float32
    np.testing.assert_array_equal(labels, [0, 1])
    # eval path is deterministic (no augmentation)
    imgs2, _ = test.get_batch(np.array([0, 3]))
    np.testing.assert_array_equal(imgs, imgs2)


def test_cifar10_base_dir_fallback(cifar10_tree):
    """Batches sitting directly under root (no cifar-10-batches-py/
    subdir) load through the `base` fallback."""
    flat = cifar10_tree / "cifar-10-batches-py"
    ds = CIFAR(str(flat), num_classes=10, synthetic_fallback=False)
    assert len(ds["train"]) == 20


def test_cifar100_fine_labels(tmp_path):
    base = tmp_path / "cifar-100-python"
    base.mkdir()
    _write_batch(base / "train", _make_images(6, 1),
                 list(range(6)), label_key=b"fine_labels")
    _write_batch(base / "test", _make_images(3, 2),
                 [5, 4, 3], label_key=b"fine_labels")
    ds = CIFAR(str(tmp_path), num_classes=100, synthetic_fallback=False)
    assert len(ds["train"]) == 6 and len(ds["test"]) == 3
    np.testing.assert_array_equal(ds["train"].labels, range(6))
    np.testing.assert_array_equal(ds["test"].labels, [5, 4, 3])


def test_cifar_missing_raises_without_fallback(tmp_path):
    with pytest.raises(FileNotFoundError):
        CIFAR(str(tmp_path / "nope"), synthetic_fallback=False)


def test_cifar_missing_falls_back_to_synthetic(tmp_path):
    ds = CIFAR(str(tmp_path / "nope"), synthetic_fallback=True,
               synthetic_size=64)
    assert isinstance(ds["train"], SyntheticSplit)
    assert len(ds["train"]) == 64
