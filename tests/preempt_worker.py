"""Worker program for the 2-process kill-and-resume drill
(tests/test_multiprocess.py::test_kill_and_resume_bitwise_memory).

Three phases, each a separate 2-process ``jax.distributed`` launch over the
same checkpoint directory:

* ``baseline`` — train TOTAL_STEPS uninterrupted; record per-step losses
  and a per-process sha256 fingerprint of the compressor memory after
  KILL_STEP steps and at the end.
* ``run`` — train with a :class:`PreemptionHandler` installed; the parent
  arms ``DGC_FAULTS=kill@3`` on process 1 only, so that process SIGTERMs
  itself after step 3. :func:`agree_preempt` spreads the verdict, both
  processes break on the SAME step boundary, write one collective
  emergency checkpoint (atomic tmp+rename) with the batch cursor, and exit
  0 through :func:`clean_shutdown`.
* ``resume`` — restore the emergency checkpoint, fingerprint the restored
  memory (must be bitwise the baseline's at the kill point), and train the
  remaining steps — losses must match the baseline trajectory exactly.

Prints one RESULT: JSON line per process for the parent to compare.
"""

import hashlib
import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")
if "jax_cpu_collectives_implementation" in jax.config.values:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TOTAL_STEPS = 6
KILL_STEP = 3          # completed steps before the injected SIGTERM


def main():
    proc_id = int(sys.argv[1])
    num_procs = int(sys.argv[2])
    coord = sys.argv[3]
    workdir = sys.argv[4]
    phase = sys.argv[5]
    assert phase in ("baseline", "run", "resume"), phase

    from dgc_tpu.parallel.multihost import (host_local_to_global,
                                            initialize_multihost)

    # same shared persistent compile cache as multiproc_worker.py (this
    # worker's step function is built identically, so it reuses the entry)
    import getpass
    import tempfile
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(tempfile.gettempdir(),
                                   f"dgc_tpu_test_jax_cache_"
                                   f"{getpass.getuser()}"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    os.environ["JAX_COORDINATOR_ADDRESS"] = coord
    os.environ["JAX_NUM_PROCESSES"] = str(num_procs)
    os.environ["JAX_PROCESS_ID"] = str(proc_id)
    assert initialize_multihost(initialization_timeout=600,
                                heartbeat_timeout_seconds=600,
                                shutdown_timeout_seconds=1200) is True
    assert jax.process_count() == num_procs

    import jax.numpy as jnp  # noqa: F401  (kept for parity with sibling)
    import numpy as np
    from flax import linen as nn
    from jax.sharding import Mesh

    from dgc_tpu import (DGCCompressor, DGCSGDMemory, DistributedOptimizer,
                         dgc_sgd)
    from dgc_tpu.resilience import faults, preempt
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)
    from dgc_tpu.training.checkpoint import CheckpointManager
    from dgc_tpu.utils.pytree import named_flatten

    W = len(jax.devices())
    assert W == 2 * 4
    mesh = Mesh(np.array(jax.devices()), ("data",))

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x.mean(axis=(1, 2)))

    model = M()
    v = dict(model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3))))

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        if mutable:
            return model.apply(variables, x, train=train, mutable=mutable,
                               rngs=rngs)
        return model.apply(variables, x, train=train)

    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh,
                        dist_opt=dist)
    step_fn = build_train_step(apply_fn, dist, mesh, donate=False,
                               flat=setup)

    bs = 4

    def batch(i):
        """Deterministic per-step global batch — identical in every phase,
        so an uninterrupted run and a kill+resume run see the same data."""
        rng = np.random.RandomState(1000 + i)
        im = rng.randn(W * bs, 16, 16, 3).astype(np.float32)
        lb = rng.randint(0, 10, W * bs).astype(np.int32)
        return (host_local_to_global(im, mesh),
                host_local_to_global(lb, mesh))

    def fingerprint(tree):
        """sha256 over this process's addressable shard bytes, in a
        deterministic (path, shard-index) order — equal fingerprints mean
        bitwise-equal per-worker state on this process."""
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        h = hashlib.sha256()
        for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
            if not hasattr(leaf, "addressable_shards"):
                h.update(np.asarray(leaf).tobytes())
                continue
            for s in sorted(leaf.addressable_shards,
                            key=lambda s: str(s.index)):
                h.update(np.asarray(s.data).tobytes())
        return h.hexdigest()

    ckpt = CheckpointManager(os.path.join(workdir, "ckpt_preempt"), keep=3)
    out = {"proc": proc_id, "phase": phase}

    if phase == "baseline":
        losses = []
        for i in range(TOTAL_STEPS):
            im, lb = batch(i)
            state, m = step_fn(state, im, lb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
            jax.block_until_ready(state)
            if i + 1 == KILL_STEP:
                out["mem_at_kill"] = fingerprint(state.memory)
        out.update(losses=losses, mem_final=fingerprint(state.memory))

    elif phase == "run":
        handler = preempt.PreemptionHandler()
        losses, preempt_at = [], None
        for i in range(TOTAL_STEPS):
            # step-boundary agreement: the killed process's local flag
            # becomes everyone's verdict, so both enter the collective
            # emergency save on the same step
            if preempt.agree_preempt(handler.requested):
                preempt_at = i - 1
                break
            im, lb = batch(i)
            state, m = step_fn(state, im, lb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
            jax.block_until_ready(state)
            faults.maybe_kill(i + 1)     # SIGTERM self at the armed step
        assert preempt_at == KILL_STEP - 1, \
            f"expected preemption after step {KILL_STEP}, got {preempt_at}"
        preempt.emergency_save(ckpt, 0, state,
                               {"preempt_batch": preempt_at})
        out.update(losses=losses, preempt_at=preempt_at,
                   mem_saved=fingerprint(state.memory),
                   signum=handler.signum)
        handler.uninstall()

    else:  # resume
        restored = ckpt.restore(state)
        assert restored is not None, "emergency checkpoint must restore"
        r_state, r_epoch, meters = restored
        assert r_epoch == 0
        start = int(meters["preempt_batch"]) + 1
        out["mem_restored"] = fingerprint(r_state.memory)
        losses = []
        for i in range(start, TOTAL_STEPS):
            im, lb = batch(i)
            r_state, m = step_fn(r_state, im, lb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
            jax.block_until_ready(r_state)
        out.update(losses=losses, start=start,
                   mem_final=fingerprint(r_state.memory))

    print("RESULT:" + json.dumps(out), flush=True)

    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(f"preempt_{phase}_done")
    if phase == "run":
        preempt.clean_shutdown()     # the path a preempted trainer takes
    else:
        jax.distributed.shutdown()


if __name__ == "__main__":
    main()
