"""Two-tier hierarchical exchange (dense over the local/ICI axis, sparse DGC
over the host/DCN axis) on the 8-device CPU mesh reshaped (2 hosts x 4 local).

This is the real form of the reference's "#Sparsified Nodes < #GPUs" regime,
which it can only simulate via ``num_batches_per_step`` micro-batching
(/root/reference/README.md:126-128,133-134, dgc/horovod/optimizer.py:70-72).

Oracle strategy: after the local psum-average, every worker of a node holds
the node-aggregated gradient — so the two-tier exchange over (H, L) must
equal the FLAT 1-D exchange over H workers fed the node gradients. Gradients
are quantized to multiples of 2^-12 (|g| < 4) so sums of 4 and /4 are exact
in f32: node aggregation is then bitwise reproducible on the host and the
assertions can be exact.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dgc_tpu import (
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    dgc_sgd,
)
from dgc_tpu.parallel import make_mesh, make_two_tier_mesh
from dgc_tpu.training import with_leading_axis
from dgc_tpu.utils.pytree import named_flatten
from dgc_tpu.utils.compat import shard_map

H, L, W = 2, 4, 8


@pytest.fixture(scope="module")
def mesh2x4():
    assert len(jax.devices()) >= 8
    return make_two_tier_mesh(H, L)


def _params():
    rng = np.random.RandomState(0)
    return {
        "conv1": {"kernel": jnp.asarray(rng.randn(3, 3, 4, 8), jnp.float32)},
        "conv2": {"kernel": jnp.asarray(rng.randn(3, 3, 8, 8), jnp.float32)},
        "dense": {"kernel": jnp.asarray(rng.randn(32, 10), jnp.float32),
                  "bias": jnp.asarray(rng.randn(10), jnp.float32)},
    }


def _quantized(rng, shape):
    """randn quantized to multiples of 2^-12, |x| <= 4: any sum of <= 4 such
    values (and its /4) is exact in f32, making node aggregation bitwise
    reproducible on the host."""
    x = np.clip(rng.randn(*shape), -4, 4)
    return (np.round(x * 4096) / 4096).astype(np.float32)


def _make_engine(params, ratio=0.05):
    named, _ = named_flatten(params)
    comp = DGCCompressor(ratio, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W, local_axis_name="local",
                                local_size=L, axis_name="hosts")
    layout, engine = dist.make_flat(params)
    return comp, dist, layout, engine


def _two_tier_fn(engine, mesh):
    axes = ("hosts", "local")

    def worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        # sparsify key folds the HOST index only: workers of one node must
        # make the identical selection (they hold the same node gradient)
        key = jax.random.fold_in(key, jax.lax.axis_index("hosts"))
        out, mem = engine.exchange(fg, mem, key, "hosts", H,
                                   local_axis="local", local_size=L)
        return out[None], jax.tree.map(lambda x: x[None], mem)

    return jax.jit(shard_map(
        worker, mesh=mesh, in_specs=(P(axes), P(axes), P()),
        out_specs=(P(axes), P(axes)), check_vma=False))


def _flat_fn(engine, mesh, world):
    def worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = engine.exchange(fg, mem, key, "data", world)
        return out[None], jax.tree.map(lambda x: x[None], mem)

    return jax.jit(shard_map(
        worker, mesh=mesh, in_specs=(P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))


def test_two_tier_matches_flat_oracle_on_node_grads(mesh2x4):
    """Distinct per-worker grads: the (2 hosts x 4 local) two-tier exchange
    must equal the flat 2-worker exchange fed the exact node-mean gradients
    — bitwise, across steps (memory/error-feedback included)."""
    params = _params()
    comp, dist, layout, engine = _make_engine(params)
    rng = np.random.RandomState(1)
    g_w = _quantized(rng, (W, layout.total))
    # zero the structural-pad slots so flatten() semantics hold
    data = np.zeros((W, layout.total), np.float32)
    for n in layout.names:
        o, s = layout.offsets[n], layout.sizes[n]
        data[:, o:o + s] = g_w[:, o:o + s]
    g_w = data
    # node means are exact (sums of 4 quantized values, /4)
    g_nodes = g_w.reshape(H, L, -1).sum(1) / L

    mesh2 = make_mesh(H)
    two_tier = _two_tier_fn(engine, mesh2x4)
    flat = _flat_fn(engine, mesh2, H)

    mem_t = with_leading_axis(engine.init_memory(), W)
    mem_f = with_leading_axis(engine.init_memory(), H)
    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_t, mem_t = two_tier(jnp.asarray(g_w), mem_t, key)
        out_f, mem_f = flat(jnp.asarray(g_nodes), mem_f, key)
        out_t, out_f = np.asarray(out_t), np.asarray(out_f)
        # every worker decompresses the identical gradient
        for w in range(1, W):
            np.testing.assert_array_equal(out_t[0], out_t[w])
        np.testing.assert_array_equal(out_t[0], out_f[0],
                                      err_msg=f"step {step}")
        # per-node memory equals the flat oracle's per-worker memory
        for h in range(H):
            for k in mem_t:
                np.testing.assert_array_equal(
                    np.asarray(mem_t[k][h * L]), np.asarray(mem_f[k][h]),
                    err_msg=f"memory {k} node {h} step {step}")
        # and is identical across a node's workers
        for w in range(W):
            for k in mem_t:
                np.testing.assert_array_equal(
                    np.asarray(mem_t[k][w]),
                    np.asarray(mem_t[k][(w // L) * L]))


def test_two_tier_dense_tail_and_sum_op(mesh2x4):
    """The dense-fallback tail averages over ALL workers (both tiers), and
    op='sum' skips every divide."""
    params = _params()
    comp, dist, layout, engine = _make_engine(params)
    rng = np.random.RandomState(2)
    g_w = _quantized(rng, (W, layout.total))
    bias_off = layout.offsets["dense/bias"]
    bias_sz = layout.sizes["dense/bias"]

    two_tier = _two_tier_fn(engine, mesh2x4)
    mem = with_leading_axis(engine.init_memory(), W)
    out, _ = two_tier(jnp.asarray(g_w), mem, jax.random.PRNGKey(0))
    # dense tail (zero-initialized memory): first step output == mean over
    # all 8 workers
    np.testing.assert_allclose(
        np.asarray(out[0][bias_off:bias_off + bias_sz]),
        g_w[:, bias_off:bias_off + bias_sz].mean(0), rtol=1e-6, atol=1e-7)

    # op='sum': node tier still psums (no local divide), sparse gather does
    # not divide either -> transmitted coordinates carry the full sum
    def worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("hosts"))
        out, mem = engine.exchange(fg, mem, key, "hosts", H, op="sum",
                                   local_axis="local", local_size=L)
        return out[None], jax.tree.map(lambda x: x[None], mem)

    f = jax.jit(shard_map(
        worker, mesh=mesh2x4,
        in_specs=(P(("hosts", "local")), P(("hosts", "local")), P()),
        out_specs=(P(("hosts", "local")), P(("hosts", "local"))),
        check_vma=False))
    mem = with_leading_axis(engine.init_memory(), W)
    out_sum, _ = f(jnp.asarray(g_w), mem, jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(out_sum[0][bias_off:bias_off + bias_sz]),
        g_w[:, bias_off:bias_off + bias_sz].sum(0), rtol=1e-6, atol=1e-6)


def test_two_tier_per_tensor_path_matches_flat_engine(mesh2x4):
    """The unfused per-tensor path (DistributedOptimizer.exchange) under
    two-tier mode agrees with the flat engine's two-tier exchange."""
    params = _params()
    named, _ = named_flatten(params)
    comp, dist, layout, engine = _make_engine(params)
    rng = np.random.RandomState(3)
    grads_w = {n: jnp.asarray(_quantized(rng, (W,) + tuple(p.shape)))
               for n, p in named.items()}

    def pt_worker(grads, mem, key):
        grads = jax.tree.map(lambda x: x[0], grads)
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("hosts"))
        out, mem = dist.exchange(grads, mem, key)
        return (jax.tree.map(lambda x: x[None], out),
                jax.tree.map(lambda x: x[None], mem))

    axes = ("hosts", "local")
    pt = jax.jit(shard_map(
        pt_worker, mesh=mesh2x4, in_specs=(P(axes), P(axes), P()),
        out_specs=(P(axes), P(axes)), check_vma=False))
    two_tier = _two_tier_fn(engine, mesh2x4)

    mem_p = with_leading_axis(dist.init_memory(params), W)
    mem_f = with_leading_axis(engine.init_memory(), W)
    from dgc_tpu.utils.pytree import named_unflatten
    treedef = named_flatten(params)[1]
    flat_g = jnp.stack([
        engine.layout.flatten(named_unflatten(
            {n: grads_w[n][w] for n in named}, treedef))
        for w in range(W)])

    key = jax.random.PRNGKey(0)
    out_p, _ = pt(named_unflatten(grads_w, treedef), mem_p, key)
    out_f, _ = two_tier(flat_g, mem_f, key)
    named_p, _ = named_flatten(out_p)
    named_f = layout.unflatten_named(np.asarray(out_f)[0])
    for n in layout.names:
        np.testing.assert_allclose(
            np.asarray(named_p[n][0]).reshape(-1),
            np.asarray(named_f[n]).reshape(-1), rtol=1e-5, atol=1e-6,
            err_msg=n)


class _TinyNet(nn.Module):
    """BN-free tiny net (BN running stats update per micro-batch in the nbps
    oracle, a deliberate state-only difference; keep it out of the loss)."""
    @nn.compact
    def __call__(self, x, train=True):
        x = nn.Conv(8, (3, 3))(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(10)(x)


def test_two_tier_train_step_matches_nbps_simulation(mesh2x4):
    """Full train step: two-tier over (2 hosts x 4 local) must track the
    reference's SIMULATED form — flat DP over 2 workers with
    num_batches_per_step=4 on the same data (README.md:133-134) — since both
    compute DGC over the same two node gradients. Losses agree to float
    tolerance (aggregation order differs: psum/4 vs scan of 1/4-scaled)."""
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)

    model = _TinyNet()
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)),
                   train=True)
    named, _ = named_flatten(v["params"])

    def build(two_tier: bool):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        if two_tier:
            dist = DistributedOptimizer(
                dgc_sgd(0.1, momentum=0.9), comp, axis_name="hosts",
                world_size=W, local_axis_name="local", local_size=L)
            mesh = mesh2x4
            nbps = 1
        else:
            dist = DistributedOptimizer(
                dgc_sgd(0.1, momentum=0.9), comp, axis_name="data",
                world_size=H)
            mesh = make_mesh(H)
            nbps = L
        setup = make_flat_setup(v, dist)
        state = shard_state(
            make_flat_state(v, dist, setup, dist.world_size), mesh,
            dist.data_axes if two_tier else "data", dist_opt=dist)
        step = build_train_step(model.apply, dist, mesh,
                                num_batches_per_step=nbps, donate=False,
                                flat=setup)
        return step, state, setup

    step_t, state_t, setup_t = build(True)
    step_f, state_f, _ = build(False)

    rng = np.random.RandomState(7)
    bs = 4
    images = jnp.asarray(rng.randn(W * bs, 16, 16, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, W * bs), jnp.int32)

    losses_t, losses_f = [], []
    for step in range(3):
        key = jax.random.PRNGKey(100 + step)
        state_t, mt = step_t(state_t, images, labels, key)
        state_f, mf = step_f(state_f, images, labels, key)
        losses_t.append(float(mt["loss"]))
        losses_f.append(float(mf["loss"]))
    np.testing.assert_allclose(losses_t, losses_f, rtol=1e-4)
    # parameters track too (same selections + same node grads modulo fp)
    np.testing.assert_allclose(np.asarray(state_t.params),
                               np.asarray(state_f.params),
                               rtol=1e-4, atol=1e-5)


def test_two_tier_dense_fp16_wire_divides_before_cast(mesh2x4):
    """FlatDenseExchange two-tier: the average divide happens BEFORE the
    fp16 wire cast — an undivided node sum would overflow fp16 local_size x
    earlier than flat mode does."""
    from dgc_tpu import Compression
    from dgc_tpu.compression.flat import FlatDenseExchange, ParamLayout

    params = _params()
    layout = ParamLayout(params)           # no compressed names: all dense
    engine = FlatDenseExchange(Compression.fp16(), layout)
    # per-worker 30000: node SUM 120000 overflows fp16 (max 65504); the
    # node AVERAGE 30000 is representable and so is the 2-host wire sum
    g = np.full((W, layout.total), 30000.0, np.float32)

    def worker(fg, key):
        out, _ = engine.exchange(fg[0], {}, key, "hosts", H,
                                 local_axis="local", local_size=L)
        return out[None]

    axes = ("hosts", "local")
    f = jax.jit(shard_map(
        worker, mesh=mesh2x4, in_specs=(P(axes), P()),
        out_specs=P(axes), check_vma=False))
    out = np.asarray(f(jnp.asarray(g), jax.random.PRNGKey(0)))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out[0], 30000.0)


def test_two_tier_validation(mesh2x4):
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    with pytest.raises(ValueError, match="local_size"):
        DistributedOptimizer(dgc_sgd(0.1), comp, world_size=8,
                             local_axis_name="local", local_size=3)
    with pytest.raises(ValueError, match="local_size"):
        DistributedOptimizer(dgc_sgd(0.1), comp, world_size=8,
                             local_axis_name="local", local_size=1)
    with pytest.raises(ValueError, match="local_axis_name"):
        DistributedOptimizer(dgc_sgd(0.1), comp, world_size=8, local_size=4)


def test_two_tier_adasum_matches_flat_oracle(mesh2x4):
    """Adasum x two-tier (node-aggregated Adasum): the (2 hosts x 4 local)
    exchange with op='adasum' must equal the flat 2-participant Adasum
    exchange fed the exact node-mean deltas — each node is one Adasum
    participant (Horovod's hierarchical Adasum recipe applied to the
    reference's sparsified-nodes regime, optimizer.py:197-367). Covers the
    compressed block (scatter-add sum), the dense tail (pairwise Adasum),
    and the error-feedback memory."""
    params = _params()
    comp, dist, layout, engine = _make_engine(params)
    rng = np.random.RandomState(7)
    g_w = _quantized(rng, (W, layout.total))
    data = np.zeros((W, layout.total), np.float32)
    for n in layout.names:
        o, s = layout.offsets[n], layout.sizes[n]
        data[:, o:o + s] = g_w[:, o:o + s]
    g_w = data
    g_nodes = g_w.reshape(H, L, -1).sum(1) / L   # exact node means

    mesh2 = make_mesh(H)
    axes = ("hosts", "local")

    def tt_worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("hosts"))
        out, mem = engine.exchange(fg, mem, key, "hosts", H, op="adasum",
                                   local_axis="local", local_size=L)
        return out[None], jax.tree.map(lambda x: x[None], mem)

    two_tier = jax.jit(shard_map(
        tt_worker, mesh=mesh2x4, in_specs=(P(axes), P(axes), P()),
        out_specs=(P(axes), P(axes)), check_vma=False))

    def flat_worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = engine.exchange(fg, mem, key, "data", H, op="adasum")
        return out[None], jax.tree.map(lambda x: x[None], mem)

    flat = jax.jit(shard_map(
        flat_worker, mesh=mesh2, in_specs=(P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))

    mem_t = with_leading_axis(engine.init_memory(), W)
    mem_f = with_leading_axis(engine.init_memory(), H)
    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_t, mem_t = two_tier(jnp.asarray(g_w), mem_t, key)
        out_f, mem_f = flat(jnp.asarray(g_nodes), mem_f, key)
        out_t, out_f = np.asarray(out_t), np.asarray(out_f)
        for w in range(1, W):
            np.testing.assert_array_equal(out_t[0], out_t[w])
        np.testing.assert_allclose(out_t[0], out_f[0], rtol=1e-6,
                                   atol=1e-7, err_msg=f"step {step}")
        for h in range(H):
            for k in mem_t:
                np.testing.assert_allclose(
                    np.asarray(mem_t[k][h * L]), np.asarray(mem_f[k][h]),
                    rtol=1e-6, atol=1e-7,
                    err_msg=f"memory {k} node {h} step {step}")
    # the dense tail actually took the Adasum combine, not an average:
    # feed ORTHOGONAL node deltas on the dense block — Adasum of
    # orthogonal vectors is their SUM (fa = fb = 1), distinct from the
    # mean. (Collinear probes cannot distinguish the two: for b = c*a the
    # Adasum operator gives (1+c)/2 * a, identically the arithmetic mean.)
    db = layout.offsets[layout.dense_names[0]]
    probe = np.zeros((W, layout.total), np.float32)
    probe[:L, db] = 1.0          # node 0's delta: e_db
    probe[L:, db + 1] = 1.0      # node 1's delta: e_{db+1}, orthogonal
    out_p, _ = two_tier(jnp.asarray(probe),
                        with_leading_axis(engine.init_memory(), W),
                        jax.random.PRNGKey(9))
    out_p = np.asarray(out_p)
    assert out_p[0, db] == pytest.approx(1.0, rel=1e-6)       # sum, not 0.5
    assert out_p[0, db + 1] == pytest.approx(1.0, rel=1e-6)


def test_two_tier_adasum_distributed_optimizer_constructs():
    """AdasumDistributedOptimizer now composes with the two-tier config
    (the round-3 NotImplementedError guard is gone)."""
    from dgc_tpu.optim.adasum import AdasumDistributedOptimizer
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    opt = AdasumDistributedOptimizer(dgc_sgd(0.1), comp, axis_name="hosts",
                                     world_size=8, local_axis_name="local",
                                     local_size=4)
    assert opt.num_nodes == 2 and opt.per_worker_opt_state


def test_two_tier_adasum_per_tensor_update_matches_flat(mesh2x4):
    """The PER-TENSOR AdasumDistributedOptimizer.update() under a two-tier
    config (the advisor-flagged branch): per-worker deltas are node-meaned
    over the local axis, then ``num_nodes`` (not world_size) participants
    exchange over the host axis — numerically equal to the flat
    2-participant per-tensor update fed the node-mean gradients (sgd(0.1)
    is linear, so mean-of-deltas == delta-of-mean), and replicated across
    every worker."""
    from dgc_tpu.optim.adasum import AdasumDistributedOptimizer

    params = _params()
    named, _ = named_flatten(params)

    def make(two_tier):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        from dgc_tpu import sgd
        if two_tier:
            return AdasumDistributedOptimizer(
                sgd(0.1), comp, axis_name="hosts", world_size=W,
                local_axis_name="local", local_size=L)
        return AdasumDistributedOptimizer(sgd(0.1), comp,
                                          axis_name="data", world_size=H)

    dist_t = make(True)
    assert dist_t.num_nodes == H
    dist_f = make(False)
    opt_state = dist_t.init(params)

    rng = np.random.RandomState(17)
    g_w = {n: jnp.asarray(
        np.round(rng.randn(W, *p.shape) * 4096) / 4096, jnp.float32)
        for n, p in named.items()}
    g_nodes = {n: g_w[n].reshape(H, L, *g_w[n].shape[1:]).sum(1) / L
               for n in named}
    from dgc_tpu.utils.pytree import named_unflatten

    def tt_worker(gw, mem, key):
        g = named_unflatten(
            {n: gw[n][0] for n in named}, named_flatten(params)[1])
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("hosts"))
        upd, _, mem = dist_t.update(g, opt_state, params, mem, key)
        upd_named, _ = named_flatten(upd)
        return ({n: upd_named[n][None] for n in named},
                jax.tree.map(lambda x: x[None], mem))

    axes = ("hosts", "local")
    tt = jax.jit(shard_map(
        tt_worker, mesh=mesh2x4,
        in_specs=({n: P(axes) for n in named}, P(axes), P()),
        out_specs=({n: P(axes) for n in named}, P(axes)),
        check_vma=False))

    def flat_worker(gw, mem, key):
        g = named_unflatten(
            {n: gw[n][0] for n in named}, named_flatten(params)[1])
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        upd, _, mem = dist_f.update(g, opt_state, params, mem, key)
        upd_named, _ = named_flatten(upd)
        return ({n: upd_named[n][None] for n in named},
                jax.tree.map(lambda x: x[None], mem))

    mesh2 = make_mesh(H)
    fl = jax.jit(shard_map(
        flat_worker, mesh=mesh2,
        in_specs=({n: P("data") for n in named}, P("data"), P()),
        out_specs=({n: P("data") for n in named}, P("data")),
        check_vma=False))

    mem_t = with_leading_axis(dist_t.init_memory(params), W)
    mem_f = with_leading_axis(dist_f.init_memory(params), H)
    key = jax.random.PRNGKey(0)
    out_t, mem_t = tt(g_w, mem_t, key)
    out_f, mem_f = fl(g_nodes, mem_f, key)
    for n in named:
        ot = np.asarray(out_t[n])
        for w in range(1, W):
            np.testing.assert_array_equal(ot[0], ot[w], err_msg=n)
        np.testing.assert_allclose(ot[0], np.asarray(out_f[n][0]),
                                   rtol=1e-5, atol=1e-7, err_msg=n)
