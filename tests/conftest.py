"""Test configuration: force an 8-fake-device CPU platform.

Multi-worker semantics (shard_map, all_gather, psum) are exercised exactly on
fake CPU devices (SURVEY.md §4 test strategy). NOTE: this environment's
sitecustomize force-registers a TPU plugin and overrides JAX_PLATFORMS, so the
platform must be re-set via jax.config *after* importing jax.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from dgc_tpu.parallel import make_mesh
    assert len(jax.devices()) >= 8, "conftest failed to create 8 CPU devices"
    return make_mesh(8)
