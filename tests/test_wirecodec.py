"""Wire-codec round-trip properties on the edge cases the serving path
hits (ISSUE 17 satellites): empty selection, single-element bucket,
all-indices-selected, the max-bucket-size boundary, and odd-length int4
packing."""

import types

import jax
import numpy as np
import pytest

from dgc_tpu.compression.flat import _bucket_from_rows
from dgc_tpu.compression.wirecodec import (
    DeltaIndexCodec,
    IndexCodec,
    pack_int4,
    unpack_int4,
)

pytestmark = pytest.mark.fast     # all offline codec math: SERVE_SMOKE

CODECS = [IndexCodec, DeltaIndexCodec]


def _bucket(rows, cols=128, base=0):
    """[(numel, k), ...] -> one exact-selection _Bucket."""
    specs, off = [], base
    for numel, k in rows:
        specs.append((off, numel, 1, numel, k, k))
        off += cols
    return _bucket_from_rows(base, cols, specs)


def _canonical_selection(bucket, rng):
    """A valid per-slot index stream: per row, k sorted random in-row
    picks, pad tail clipped to the row's last element (ascending per
    bucket by construction — legal for BOTH codecs)."""
    grid = np.repeat((np.asarray(bucket.row_offsets, np.int64)
                      + np.asarray(bucket.numels, np.int64) - 1)[:, None],
                     bucket.max_sel, axis=1)
    for r in range(bucket.rows):
        numel = int(bucket.numels[r])
        k = int(bucket.num_selects[r])
        sel = np.sort(rng.choice(numel, size=k, replace=False))
        grid[r, :k] = int(bucket.row_offsets[r]) + sel
    return grid.reshape(-1)[np.asarray(bucket.tight)]


# --------------------------------------------------------------------- #
# round-trip properties                                                  #
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("codec_cls", CODECS)
def test_empty_selection_round_trip(codec_cls):
    codec = codec_cls([])
    assert codec.payload == 0
    assert codec.nwords == 0
    assert codec.bits_per_index == 0.0
    words = codec.encode(np.zeros((0,), np.int32))
    assert np.asarray(words).shape == (0,)
    out = codec.decode(words, out_dtype=np.int32)
    assert np.asarray(out).shape == (0,)


@pytest.mark.parametrize("codec_cls", CODECS)
def test_single_element_bucket_round_trip(codec_cls):
    b = _bucket([(1, 1)])
    codec = codec_cls([b])
    assert codec.payload == 1
    idx = np.asarray([0], np.int32)
    got = np.asarray(codec.decode(codec.encode(idx), out_dtype=np.int32))
    np.testing.assert_array_equal(got, idx)


@pytest.mark.parametrize("codec_cls", CODECS)
def test_all_indices_selected_round_trip(codec_cls):
    # k == numel on every row: the densest stream the serving path emits
    b = _bucket([(7, 7), (13, 13), (1, 1)])
    codec = codec_cls([b])
    idx = _canonical_selection(b, np.random.RandomState(0))
    got = np.asarray(codec.decode(codec.encode(idx.astype(np.int32)),
                                  out_dtype=np.int32))
    np.testing.assert_array_equal(got, idx)


@pytest.mark.parametrize("codec_cls", CODECS)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_canonical_round_trip(codec_cls, seed):
    rng = np.random.RandomState(seed)
    buckets = [_bucket([(37, 5), (128, 17), (1, 1), (64, 64)]),
               _bucket([(200, 3)], cols=256, base=1024)]
    codec = codec_cls(buckets)
    idx = np.concatenate([_canonical_selection(b, rng) for b in buckets])
    got = np.asarray(codec.decode(codec.encode(idx.astype(np.int32)),
                                  out_dtype=np.int32))
    np.testing.assert_array_equal(got, idx)
    # canonical() is the decode(encode(x)) fixed point
    np.testing.assert_array_equal(
        np.asarray(codec.canonical(idx.astype(np.int32))), idx)


@pytest.mark.parametrize("codec_cls", CODECS)
def test_decode_vectorizes_over_leading_axes(codec_cls):
    # the gathered [W, nwords] wire decodes row-wise identically
    rng = np.random.RandomState(3)
    b = _bucket([(50, 9), (33, 4)])
    codec = codec_cls([b])
    streams = [_canonical_selection(b, rng) for _ in range(3)]
    words = np.stack([np.asarray(codec.encode(s.astype(np.int32)))
                      for s in streams])
    got = np.asarray(codec.decode(words, out_dtype=np.int32))
    np.testing.assert_array_equal(got, np.stack(streams))


# --------------------------------------------------------------------- #
# max-bucket-size boundary                                               #
# --------------------------------------------------------------------- #

def test_delta_codec_boundary_just_below_2_31():
    # largest legal universe: one row spanning just under 2^31 slots —
    # boundary indices survive the Elias-Fano round trip exactly
    cols = 2 ** 30
    numel = cols - 1
    b = _bucket_from_rows(0, cols, [(0, numel, 1, numel, 4, 4)])
    codec = DeltaIndexCodec([b])
    idx = np.asarray([0, 1, numel - 2, numel - 1], np.int32)
    got = np.asarray(codec.decode(codec.encode(idx), out_dtype=np.int32))
    np.testing.assert_array_equal(got, idx)


def test_delta_codec_refuses_2_31_universe():
    # a >= 2^31-slot grid exceeds the int32 Elias-Fano decode: loud error
    b = _bucket_from_rows(0, 2 ** 31, [(0, 10, 1, 10, 2, 2)])
    with pytest.raises(ValueError, match="2\\^31"):
        DeltaIndexCodec([b])


def test_index_codec_refuses_widths_over_32_bits():
    # numel > 2^32 would need >32-bit locals; _bucket_from_rows casts
    # numels to int32 so the only road here is a corrupt bucket — the
    # codec must still refuse rather than silently truncate
    fake = types.SimpleNamespace(
        tight=np.arange(2), max_sel=2,
        row_offsets=np.asarray([0], np.int64),
        numels=np.asarray([2 ** 33], np.int64))
    with pytest.raises(ValueError, match="32-bit"):
        IndexCodec([fake])


# --------------------------------------------------------------------- #
# int4 nibble packing                                                    #
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("n", [1, 2, 3, 7, 8, 9, 255])
def test_pack_int4_round_trip_all_lengths(n):
    rng = np.random.RandomState(n)
    q = rng.randint(-8, 8, size=n).astype(np.int32)
    packed = np.asarray(pack_int4(q))
    assert packed.shape == ((n + 1) // 2,)
    got = np.asarray(unpack_int4(packed, n))
    np.testing.assert_array_equal(got, q)


def test_pack_int4_odd_trailing_negative():
    # odd n with a negative final nibble: the sign-extension of the last
    # REAL nibble must not leak into (or from) the zero pad nibble
    q = np.asarray([-8, 7, -1], np.int32)
    got = np.asarray(unpack_int4(pack_int4(q), 3))
    np.testing.assert_array_equal(got, q)
    full = np.asarray(unpack_int4(pack_int4(q), 4))
    assert full[3] == 0     # the pad nibble decodes to exactly 0


def test_pack_int4_extremes():
    q = np.asarray([-8, -8, 7, 7, -8], np.int32)
    got = np.asarray(unpack_int4(pack_int4(q), 5))
    np.testing.assert_array_equal(got, q)


def test_unpack_int4_vectorized_leading_axes():
    rng = np.random.RandomState(9)
    q = rng.randint(-8, 8, size=(4, 11)).astype(np.int32)
    packed = np.stack([np.asarray(pack_int4(row)) for row in q])
    got = np.asarray(unpack_int4(jax.numpy.asarray(packed), 11))
    np.testing.assert_array_equal(got, q)
