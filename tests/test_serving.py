"""Train-to-serve delta streaming (dgc_tpu.serving, docs/SERVING.md).

Unit layer: DeltaSpec meta/key pinning, flatten round-trip, the
encode/decode/apply wire path with its error-feedback carryover, the
exporter/replica protocol over real files (gap -> auto resync -> rebase),
the fleet serving lane, the ``stale_replica -> resync`` control rule, and
the regress-gate extraction of ``wire_bytes_per_update``.

Drill layer: a real 1-trainer / 2-replica multiprocess drill
(tests/serving_worker.py, file-logged subprocesses in the
tests/test_multiprocess.py pattern) with an injected dropped delta; the
PARENT runs the control plane — monitor.collect over the run dir,
RuleEngine with the shipped rules, audited ``resync`` execution — and the
drill passes only if both replicas end bitwise-identical to the trainer's
published head after the control-driven rebase.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dgc_tpu.control import actions as ctl_actions
from dgc_tpu.control import rules as ctl_rules
from dgc_tpu.serving import (
    DeltaSpec,
    Exporter,
    Replica,
    protocol,
    read_manifest,
    read_resync_request,
    request_resync,
)
from dgc_tpu.telemetry import fleet as tfleet
from dgc_tpu.telemetry import monitor as tmonitor
from dgc_tpu.telemetry import registry
from dgc_tpu.telemetry import regress


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": rng.randn(24, 16).astype(np.float32),
            "b": rng.randn(24).astype(np.float32),
            "s": np.float32(0.5)}


# --------------------------------------------------------------------- #
# DeltaSpec: meta/key, flatten, wire path                                #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_spec_meta_round_trip_and_key_pinning():
    spec = DeltaSpec.from_params(_params(), 0.05)
    meta = spec.meta()
    again = DeltaSpec.from_meta(meta)
    assert again.key() == spec.key()
    assert again.shapes == spec.shapes

    bad = dict(meta, format="not-a-delta-stream")
    with pytest.raises(ValueError, match="format"):
        DeltaSpec.from_meta(bad)
    newer = dict(meta, format_version=999)
    with pytest.raises(ValueError, match="resync"):
        DeltaSpec.from_meta(newer)
    # a tampered key (ratio drift between ends) is a loud error, not a
    # silent mis-apply
    drift = dict(meta, ratio=0.5)
    with pytest.raises(ValueError, match="key"):
        DeltaSpec.from_meta(drift)


@pytest.mark.fast
def test_flatten_unflatten_bitwise():
    p = _params(1)
    spec = DeltaSpec.from_params(p, 0.05)
    flat = spec.flatten(p)
    assert flat.dtype == np.float32 and flat.ndim == 1
    back = spec.unflatten(flat)
    assert sorted(back) == sorted(p)
    for n in p:
        np.testing.assert_array_equal(back[n],
                                      np.asarray(p[n], np.float32))
    with pytest.raises(ValueError, match="shape"):
        spec.flatten({"w": p["w"], "b": p["b"], "s": np.zeros(3)})


@pytest.mark.fast
def test_encode_decode_apply_deterministic():
    p = _params(2)
    spec = DeltaSpec.from_params(p, 0.1)
    rng = np.random.RandomState(3)
    delta = rng.randn(spec.layout.total).astype(np.float32) * 0.01
    art1 = spec.encode(delta)
    art2 = spec.encode(delta)
    for k in ("scales", "values", "words"):
        np.testing.assert_array_equal(art1[k], art2[k])
    values, idx = spec.decode(art1)
    assert values.shape == idx.shape == (spec.payload,)
    # decoded coordinates stay inside the flat state (receiver row clamp)
    assert int(idx.min()) >= 0 and int(idx.max()) < spec.layout.total
    base = np.zeros(spec.layout.total, np.float32)
    out1 = spec.apply(base, art1)
    out2 = spec.apply(base, art1)
    np.testing.assert_array_equal(out1, out2)
    assert 0 < int(np.count_nonzero(out1)) <= spec.payload


@pytest.mark.fast
def test_error_feedback_converges_on_static_target():
    """What top-k + int4 does not send stays in live - published and
    rides later deltas: repeated publishes of one fixed target drive the
    published state toward it (the serving analogue of DGC residual
    accumulation)."""
    p0 = _params(4)
    spec = DeltaSpec.from_params(p0, 0.05)
    rng = np.random.RandomState(5)
    # perturb the PARAMS (not the flat buffer: layout padding slots are
    # structurally unaddressable by the wire, by design)
    pt = {n: np.asarray(v, np.float32)
          + np.asarray(rng.randn(*np.shape(v)), np.float32) * 0.1
          for n, v in p0.items()}
    target = spec.flatten(pt)
    published = spec.flatten(p0)
    errs = []
    for _ in range(60):
        published = spec.apply(published, spec.encode(target - published))
        errs.append(float(np.max(np.abs(target - published))))
    assert errs[-1] < errs[0] * 0.05, errs[::8]


@pytest.mark.fast
def test_wire_accounting_and_describe():
    p = _params(6)
    spec = DeltaSpec.from_params(p, 0.05)
    d = spec.describe()
    wire = spec.wire_bytes_per_update()
    full = spec.full_checkpoint_bytes()
    assert d["wire_bytes_per_update"] == wire
    assert d["full_checkpoint_bytes"] == full == 4 * spec.layout.num_params
    # the acceptance bound the ResNet-20 bench row is gated on, scaled
    # here to the toy model at 5% density
    assert wire <= 0.10 * full
    assert d["wire_frac"] == pytest.approx(wire / full, abs=1e-6)


@pytest.mark.fast
def test_spec_refuses_unshardable_streams():
    with pytest.raises(ValueError, match="shard"):
        DeltaSpec({"huge": [2 ** 16, 2 ** 15]}, 0.001)


# --------------------------------------------------------------------- #
# protocol: atomic files, tolerant reads                                 #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_protocol_tolerant_reads_and_resync_files(tmp_path):
    d = str(tmp_path)
    assert read_manifest(d) is None
    assert protocol.load_npz(protocol.base_path(d, 1)) is None
    # a torn manifest reads as absent, never raises
    with open(os.path.join(d, protocol.MANIFEST), "w") as f:
        f.write('{"base_version": 1, "latest')
    assert read_manifest(d) is None

    assert read_resync_request(d) is None
    req = request_resync(d, "stale_replica", replicas=["r1"])
    got = read_resync_request(d)
    assert got["event"] == "resync_request"
    assert got["reason"] == "stale_replica" == req["reason"]
    assert got["replicas"] == ["r1"]
    protocol.clear_resync_request(d)
    assert read_resync_request(d) is None
    protocol.clear_resync_request(d)        # idempotent


# --------------------------------------------------------------------- #
# exporter <-> replica over real files                                   #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_exporter_replica_parity_gap_resync(tmp_path, monkeypatch):
    monkeypatch.delenv("DGC_SERVE_DROP", raising=False)
    d = str(tmp_path / "serving")
    p = _params(7)
    exp = Exporter(d, p, ratio=0.1, max_lag=3,
                   lineage={"epoch": 1, "step": 100})
    man = read_manifest(d)
    assert man["base_version"] == 1 and man["latest_seq"] == 0
    assert man["lineage"]["epoch"] == 1

    rep = Replica(d, name="r0", auto_resync=True)
    st = rep.poll()
    registry.validate_replica_status(st)
    assert st["health"] == "ok" and st["staleness"] == 0
    assert rep.digest() == exp.digests["1:0"]

    # several delta ticks: bitwise parity at every head
    rng = np.random.RandomState(8)
    for i in range(4):
        p = {n: np.asarray(v, np.float32)
             + np.asarray(rng.randn(*np.shape(v)), np.float32) * 0.01
             for n, v in p.items()}
        rec = exp.publish(p, step=101 + i)
        assert rec["kind"] == "delta" and not rec["dropped"]
        st = rep.poll()
        assert st["health"] == "ok" and st["delta_seq"] == i + 1
        assert rep.digest() == rec["digest"]
    assert rep.applied_deltas == 4

    # inject a dropped artifact: gap -> auto resync request -> rebase
    monkeypatch.setenv("DGC_SERVE_DROP", "5")
    rec = exp.publish(p, step=105)
    assert rec["dropped"]
    monkeypatch.delenv("DGC_SERVE_DROP")
    st = rep.poll()
    assert st["health"] == "gap" and rep.gaps == 1
    assert read_resync_request(d) is not None
    rec = exp.publish(p, step=106)
    assert rec["kind"] == "base" and rec["base_version"] == 2
    assert rec["request"]["reason"].startswith("gap at 1:5")
    st = rep.poll()
    assert st["health"] == "ok"
    assert st["base_version"] == 2 and st["delta_seq"] == 0
    assert rep.resyncs == 1
    assert rep.digest() == exp.digests["2:0"]
    # served params reshape losslessly
    assert sorted(rep.params()) == sorted(p)


@pytest.mark.fast
def test_replica_without_auto_resync_waits_for_control(tmp_path,
                                                       monkeypatch):
    d = str(tmp_path / "serving")
    p = _params(9)
    exp = Exporter(d, p, ratio=0.1, max_lag=2)
    rep = Replica(d, name="r1", auto_resync=False)
    rep.poll()
    monkeypatch.setenv("DGC_SERVE_DROP", "1")
    exp.publish(p)
    monkeypatch.delenv("DGC_SERVE_DROP")
    st = rep.poll()
    assert st["health"] == "gap"
    # no self-service: the request file is the control plane's to write
    assert read_resync_request(d) is None


# --------------------------------------------------------------------- #
# telemetry: registry schema, fleet lane, monitor gauges                 #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_registry_serving_schema():
    assert "resync" in registry.control_action_names()
    assert set(registry.serving_stat_names()) >= {
        "staleness", "base_version", "delta_seq", "gaps"}
    # the actions table and the registry must agree (audit requirement)
    assert set(ctl_actions.ACTIONS) <= set(registry.control_action_names())
    assert "wire_bytes_per_update" in {
        s.name for s in registry.RUN_METRICS}

    rec = Replica("/nonexistent", name="rX").status(latest_seq=-1,
                                                    max_lag=0)
    registry.validate_replica_status(rec)
    with pytest.raises(ValueError, match="replica_status"):
        registry.validate_replica_status(dict(rec, event="nope"))
    bad = dict(rec)
    del bad["staleness"]
    with pytest.raises(ValueError, match="staleness"):
        registry.validate_replica_status(bad)
    with pytest.raises(ValueError, match="replica"):
        registry.validate_replica_status(dict(rec, replica=""))


def _drill_dir(tmp_path, *, stale=False):
    """A run dir with a live stream and two replica status files."""
    run = tmp_path / "run"
    d = str(run / "serving")
    p = _params(10)
    exp = Exporter(d, p, ratio=0.1, max_lag=2)
    for _ in range(3):
        exp.publish(p)
    r0 = Replica(d, name="r0")
    r0.poll()
    r0.write_status(d, latest_seq=3, max_lag=2)
    r1 = Replica(d, name="r1", auto_resync=False)
    if stale:
        # r1 never applied past the base: staleness 3 > max_lag 2
        r1.poll()
        r1.delta_seq = 0
        r1._health = "gap"
        r1.gaps = 1
    else:
        r1.poll()
    r1.write_status(d, latest_seq=3, max_lag=2)
    return str(run), d


@pytest.mark.fast
def test_fleet_serving_summary(tmp_path):
    run, d = _drill_dir(tmp_path, stale=True)
    assert tfleet.discover_serving(run) == d
    s = tfleet.serving_summary(d)
    assert s["head"]["base_version"] == 1
    assert s["head"]["latest_seq"] == 3
    assert s["num_replicas"] == 2
    assert s["stale_replicas"] == ["r1"]
    assert s["replicas"]["r0"]["health"] == "ok"
    assert s["max_staleness"] == 3
    # a corrupt status file is counted, not trusted
    with open(os.path.join(d, "replica_zz.json"), "w") as f:
        f.write("{broken")
    s = tfleet.serving_summary(d)
    assert s["bad_status"] == 1 and s["num_replicas"] == 2


@pytest.mark.fast
def test_monitor_serving_lane(tmp_path):
    run, _ = _drill_dir(tmp_path, stale=True)
    # serving-only run dirs are monitorable (no trainer telemetry here)
    snap = tmonitor.collect(run)
    assert snap["serving"]["stale_replicas"] == ["r1"]
    om = tmonitor.render_openmetrics(snap)
    assert "dgc_serving_latest_seq" in om
    assert 'dgc_replica_staleness{' in om
    assert 'replica="r0"' in om and 'replica="r1"' in om
    assert 'dgc_replica_healthy' in om
    status = tmonitor.render_status(snap)
    assert "SERVING: head v1:3" in status
    assert "STALE=[r1]" in status
    ranked = tmonitor.rank_runs({"runs": {run: snap}})
    assert any("stale-replicas [r1]" in n for n in ranked[0]["notes"])


# --------------------------------------------------------------------- #
# control plane: stale_replica -> resync                                 #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_stale_replica_rule_fires_and_resyncs(tmp_path):
    run, d = _drill_dir(tmp_path, stale=True)
    snap = tmonitor.collect(run)
    ev = ctl_rules.detect_stale_replica(snap)
    assert ev["kind"] == "stale_replica"
    assert ev["replicas"] == ["r1"]
    assert ev["head"] == "v1:3" and ev["max_lag"] == 2
    assert ev["health"] == {"r1": "gap"}

    eng = ctl_rules.RuleEngine()      # shipped rules, min_hits=2
    assert eng.evaluate(run, snap, now=0.0) == []
    fired = eng.evaluate(run, snap, now=1.0)
    assert [(r.name, e["kind"]) for r, e in fired] == [
        ("stale-replica-resync", "stale_replica")]
    rule, evidence = fired[0]
    assert rule.action == "resync" and evidence["hits"] == 2

    res = ctl_actions.execute("resync", None, evidence, serving_dir=d)
    assert res["requested"]
    req = read_resync_request(d)
    assert req["reason"] == "stale_replica"
    assert req["fired_by"] == "control_plane"
    # the audit record every firing must produce validates
    registry.validate_control_action({
        "event": "control_action", "run": run, "run_id": "drill",
        "rule": rule.name, "action": rule.action, "evidence": evidence,
        "t": time.time()})
    # healthy fleet: no evidence, no firing
    run2, _ = _drill_dir(tmp_path / "healthy", stale=False)
    assert ctl_rules.detect_stale_replica(tmonitor.collect(run2)) is None


@pytest.mark.fast
def test_regress_gate_reads_serving_wire_bytes():
    obj = {"serving": {"wire_bytes_per_update": 925,
                       "full_checkpoint_bytes": 1089896}}
    out = regress._from_bench_obj(obj)
    assert out == {"wire_bytes_per_update": 925.0}
    rows = regress.compare({"wire_bytes_per_update": 925.0},
                           {"wire_bytes_per_update": 1200.0}, tol=0.10)
    assert rows[0]["regressed"]
    rows = regress.compare({"wire_bytes_per_update": 925.0},
                           {"wire_bytes_per_update": 900.0}, tol=0.10)
    assert not rows[0]["regressed"]


# --------------------------------------------------------------------- #
# the multiprocess drill                                                 #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_serve_drill_one_trainer_two_replicas(tmp_path):
    """1 trainer + 2 replicas as real subprocesses; delta (1, 5) is
    dropped on the wire; the PARENT is the control plane. Passes when:

    * both replicas end bitwise-identical to the trainer's published
      head (v2:6) — apply parity across process boundaries,
    * while healthy, observed staleness stayed within the pinned
      ``max_lag`` bound,
    * the injected gap produced an AUDITED ``stale-replica-resync``
      firing (min_hits respected) whose rebase both replicas followed.
    """
    worker = os.path.join(os.path.dirname(__file__), "serving_worker.py")
    run_dir = str(tmp_path)
    serving_dir = os.path.join(run_dir, "serving")
    os.makedirs(serving_dir, exist_ok=True)
    target_v, target_s = 2, 6

    env = {k: v for k, v in os.environ.items() if k != "DGC_SERVE_DROP"}
    tenv = dict(env, DGC_SERVE_DROP="1:5", JAX_PLATFORMS="cpu")
    renv = dict(env, JAX_PLATFORMS="cpu")
    # file logs, not pipes (tests/test_multiprocess.py pattern)
    logs = {n: open(tmp_path / f"{n}.log", "w+")
            for n in ("trainer", "r0", "r1")}
    procs = {
        "trainer": subprocess.Popen(
            [sys.executable, worker, "trainer", serving_dir,
             str(target_v), str(target_s)],
            stdout=logs["trainer"], stderr=subprocess.STDOUT, text=True,
            env=tenv),
    }
    for name in ("r0", "r1"):
        procs[name] = subprocess.Popen(
            [sys.executable, worker, "replica", serving_dir, name,
             str(target_v), str(target_s)],
            stdout=logs[name], stderr=subprocess.STDOUT, text=True,
            env=renv)

    # the parent IS the control plane: monitor -> rules -> audited resync
    engine = ctl_rules.RuleEngine()
    audit_path = os.path.join(run_dir, "control_events.jsonl")
    actions_fired = []
    deadline = time.monotonic() + 120.0
    while (any(p.poll() is None for p in procs.values())
           and time.monotonic() < deadline):
        try:
            snap = tmonitor.collect(run_dir)
        except FileNotFoundError:
            time.sleep(0.2)
            continue
        for rule, evidence in engine.evaluate(run_dir, snap,
                                              now=time.time()):
            res = ctl_actions.execute(rule.action, None, evidence,
                                      serving_dir=serving_dir)
            rec = {"event": "control_action", "run": run_dir,
                   "run_id": "serve-drill", "rule": rule.name,
                   "action": rule.action, "evidence": evidence,
                   "result": res, "t": time.time()}
            registry.validate_control_action(rec)
            with open(audit_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
            actions_fired.append(rec)
        time.sleep(0.2)

    outs = {}
    for name, p in procs.items():
        try:
            p.wait(timeout=30)
        finally:
            if p.poll() is None:
                p.kill()
        lf = logs[name]
        lf.seek(0)
        outs[name] = lf.read()
        lf.close()
    for name, p in procs.items():
        assert p.returncode == 0, f"{name} failed:\n{outs[name][-4000:]}"

    results = {}
    for name, out in outs.items():
        for line in out.splitlines():
            if line.startswith("RESULT:"):
                results[name] = json.loads(line[len("RESULT:"):])
    assert set(results) == {"trainer", "r0", "r1"}, outs

    tr = results["trainer"]
    assert tr["base_version"] == target_v, tr   # exactly one rebase
    assert tr["latest_seq"] >= target_s
    # the drill's wire-volume bound, same shape as the bench acceptance
    assert tr["wire_bytes_per_update"] <= 0.10 * tr["full_checkpoint_bytes"]

    for name in ("r0", "r1"):
        r = results[name]
        assert r["health"] == "ok", r
        assert r["base_version"] == target_v
        assert r["delta_seq"] == tr["latest_seq"]
        # bitwise apply parity across the process boundary
        assert r["digest"] == tr["digest"], (name, r, tr)
        # the dropped artifact was SEEN as a gap...
        assert r["gaps"] >= 1, r
        # ...and the control-driven rebase was followed
        assert r["resyncs"] >= 1, r
        # staleness while healthy stayed within the pinned bound
        assert r["max_ok_staleness"] <= 3, r
        assert r["param_names"] == ["b", "s", "w"]

    # the resync was control-plane-driven and audited
    assert len(actions_fired) >= 1
    assert all(a["rule"] == "stale-replica-resync" and
               a["action"] == "resync" for a in actions_fired)
    with open(audit_path) as f:
        logged = [json.loads(l) for l in f if l.strip()]
    assert len(logged) == len(actions_fired)
    for rec in logged:
        registry.validate_control_action(rec)
        assert rec["evidence"]["hits"] >= 2   # min_hits respected
