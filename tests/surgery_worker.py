"""Fake cohort member for the surgery drill (tests/test_surgery.py).

Three of these form a W=3 cohort under one ControlPlane, lock-stepped
through a file barrier in a shared ``--cohort`` dir — no jax, no real
collective, millisecond steps — so the full excise/readmit cycle of
docs/RESILIENCE.md §"Cohort surgery" runs in seconds:

* every step: touch the supervisor's heartbeat (``DGC_HEARTBEAT``), run
  the REAL fault plan (``DGC_FAULTS=hang@5-5`` stalls exactly like
  train.py's injector), then write a barrier marker and wait for all
  ``JAX_NUM_PROCESSES`` peers' markers;
* a peer that never reaches the barrier (hung → SIGKILLed by its
  supervisor) times the barrier out: the survivors take the exit-76
  path — one atomic ``latest.json`` save (the drill's stand-in for the
  emergency checkpoint), a ``surgery_exit.json`` record naming the
  missing member, ``os._exit(76)``;
* progress is shared (``progress.json`` in the cohort dir) and barrier
  markers persist, so a relaunch under a re-published spec — survivors
  at W=2, the readmitted worker back at W=3 — resumes at the cohort's
  step and fast-forwards through markers already on disk;
* SIGTERM (the readmit cohort restart) takes the emergency-save path:
  bump ``latest.json``, exit 75;
* ``--probe`` is the re-init probe: deterministic checksum over a
  held-out array, ``CHECKSUM:<hex>`` on stdout, exit 0.

Telemetry is the fleet schema (like tests/control_worker.py) so the
plane's monitor.collect sees a real-looking run every tick.
"""

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.resilience import faults, surgery  # noqa: E402
from dgc_tpu.telemetry import registry  # noqa: E402


def _atomic_json(path, payload):
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _read_step(path, default=0):
    try:
        with open(path) as f:
            return int(json.load(f).get("step", default))
    except (OSError, ValueError):
        return default


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir")
    ap.add_argument("--cohort", required=True,
                    help="shared dir: barrier markers + progress.json")
    ap.add_argument("--steps", type=int, default=140)
    ap.add_argument("--step-ms", type=float, default=30.0)
    ap.add_argument("--world", type=int, default=3,
                    help="telemetry lane width (fixed across phases)")
    ap.add_argument("--probe", action="store_true",
                    help="re-init probe mode: print CHECKSUM:<hex>, exit 0")
    args = ap.parse_args(argv)

    if args.probe:
        import numpy as np
        arr = np.arange(256, dtype=np.float32)
        print("CHECKSUM:" + surgery.probe_checksum([arr]), flush=True)
        return 0

    run_dir = os.path.abspath(args.run_dir)
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    cohort_dir = os.path.abspath(args.cohort)
    bar_dir = os.path.join(cohort_dir, "barriers")
    for d in (ckpt_dir, bar_dir):
        os.makedirs(d, exist_ok=True)
    shard_dir = os.path.join(run_dir, "telemetry", "host0")
    os.makedirs(shard_dir, exist_ok=True)

    W = int(os.environ.get("JAX_NUM_PROCESSES") or 1)
    pid = int(os.environ.get("JAX_PROCESS_ID") or 0)
    hb_path = os.environ.get("DGC_HEARTBEAT")
    boundary_timeout = float(os.environ.get("DGC_BOUNDARY_TIMEOUT") or 10.0)
    progress_path = os.path.join(cohort_dir, "progress.json")

    static = {"world": args.world, "num_params": 1000, "payload_elems": 50,
              "num_processes": W, "process_id": pid}
    run_id = os.environ.get("DGC_RUN_ID")
    if run_id:
        static["run_id"] = run_id

    def beat():
        if not hb_path:
            return
        try:
            with open(hb_path, "a"):
                pass
            os.utime(hb_path, None)
        except OSError:
            pass

    def save(completed):
        _atomic_json(os.path.join(ckpt_dir, "latest.json"),
                     {"epoch": int(completed)})

    fh = open(os.path.join(shard_dir, "telemetry.jsonl"), "w")

    def emit(rec):
        fh.write(json.dumps(rec) + "\n")
        fh.flush()

    emit(registry.make_header(static, guards=True, fleet=True))

    # cohort-wide resume point: all members of a (re)formed cohort start
    # at the same shared step, whatever their own run lived through
    step = max(_read_step(progress_path),
               _read_step(os.path.join(ckpt_dir, "latest.json"), 0))
    state = {"step": step}

    def on_term(signum, frame):
        # emergency-save path: visible progress, exit 75 so the
        # supervisor relaunches under the currently published spec
        save(state["step"])
        fh.flush()
        os._exit(75)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    def barrier(s):
        """Write own marker, wait for all W peers'. Markers persist, so
        a resuming member fast-forwards through past steps. Returns the
        missing member ids on deadline (the hang signature)."""
        own = os.path.join(bar_dir, "b%d.%d" % (s, pid))
        with open(own, "w") as f:
            f.write(str(time.time()))
        deadline = time.time() + boundary_timeout
        while True:
            missing = [q for q in range(W)
                       if not os.path.exists(
                           os.path.join(bar_dir, "b%d.%d" % (s, q)))]
            if not missing:
                return []
            beat()      # a member BLOCKED at the boundary is not hung
            if time.time() > deadline:
                return missing
            time.sleep(0.015)

    while state["step"] < args.steps:
        s = state["step"]
        beat()
        faults.maybe_hang(s)        # the real injector train.py uses
        faults.maybe_exit(s)
        missing = barrier(s)
        if missing:
            # cohort lost at the step boundary: atomic emergency save,
            # exit record naming the missing member, exit 76 — the
            # supervisor applies the record and relaunches survivors
            # under the shrunk published spec
            save(s)
            ag = surgery.Agreement(excise=True, target=max(missing),
                                   verdict="hang", lost=True)
            surgery.write_exit_record(
                os.path.join(ckpt_dir, surgery.EXIT_RECORD), ag,
                world=W, process_index=pid, step=s)
            emit({"event": "surgery_exit", "t_host": round(time.time(), 3),
                  "step": s, "missing": missing})
            fh.flush()
            os._exit(surgery.EXIT_SURGERY)
        time.sleep(args.step_ms / 1000.0)
        state["step"] = s + 1
        save(s + 1)
        _atomic_json(progress_path, {"step": s + 1})
        emit({
            "step": s, "t_host": round(time.time(), 3),
            "loss": round(2.0 - 0.01 * s, 4),
            "grad_norm": 1.0, "payload_elems": 50.0,
            "w_clock": [10.0] * args.world,
            "w_grad_norm": [1.0] * args.world,
            "w_residual_mass": [100.0] * args.world,
            "w_sent_ratio": [0.05] * args.world,
            "straggler": 0.0, "straggler_gap": 0.0, "worker_skew": 0.1,
        })

    emit({"event": "run_done", "t_host": round(time.time(), 3),
          "steps": args.steps, "world": W})
    fh.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
