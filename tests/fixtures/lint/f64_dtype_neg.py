"""Clean twin: f32/bf16 stay f32/bf16."""
import jax.numpy as jnp


def make_table(n):
    base = jnp.zeros((n,), dtype=jnp.float32)
    narrow = base.astype(jnp.bfloat16)
    return base, narrow.astype(jnp.float32)
