"""Clean twin: branches on static config, shapes, and dtypes only."""
import jax
import jax.numpy as jnp


@jax.jit
def select(x, use_abs: bool = False, mode: str = "mean"):
    if use_abs:
        x = jnp.abs(x)
    if mode == "mean":
        r = x.mean()
    else:
        r = x.sum()
    if x.shape[0] > 4:
        r = r / 2.0
    if x is not None and jnp.issubdtype(x.dtype, jnp.floating):
        r = r + 1.0
    return jnp.where(r > 0, r, -r)
