"""Seeded violations: host syncs inside jitted scope.

`# LINT: <rule-id>` marks the lines tests expect the linter to flag."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_loss(params, batch):
    loss = jnp.mean(params * batch)
    print("loss is", loss)  # LINT: host-sync
    scale = float(loss)  # LINT: host-sync
    host = np.asarray(loss)  # LINT: host-sync
    fetched = jax.device_get(loss)  # LINT: host-sync
    item = loss.item()  # LINT: host-sync
    return loss * scale + host + fetched + item
