"""Seeded violation: jitted state-threading step without donation."""
import jax


@jax.jit
def train_step(state, batch):  # LINT: missing-donate
    return state, batch
