"""Seeded violations: per-iteration host conversions in driver loops."""


def train(step_fn, state, batches, writer):
    for batch in batches:
        state, metrics = step_fn(state, batch)
        writer.log(float(metrics["loss"]))  # LINT: sync-in-loop
    return state


def evaluate(eval_fn, state, batches):
    total = 0.0
    for batch in batches:
        counts = eval_fn(state, batch)
        total += counts.item()  # LINT: sync-in-loop
    return total
