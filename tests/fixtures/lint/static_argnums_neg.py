"""Clean twin: hashable tuple/int/str static specs."""
import jax


def build(fn):
    return jax.jit(fn, static_argnums=(0, 1))


def build_one(fn):
    return jax.jit(fn, static_argnums=2, static_argnames="mode")
