"""DGC108 negative: the flag reaches traced scope as a static argument
(retrace per value — correct), the host-side reader is never traced,
and a local binding shadowing the module name is not a closure read."""

from functools import partial

import jax
import jax.numpy as jnp

_FAST_MATH = False


def set_fast_math(on):
    global _FAST_MATH
    _FAST_MATH = on


@partial(jax.jit, static_argnames=("fast",))
def scale(x, fast: bool = False):
    factor = 2.0 if fast else 1.0
    return x * jnp.float32(factor)


@jax.jit
def scale_local(x):
    _FAST_MATH = True           # local shadow, not the module flag
    return x * jnp.float32(2.0 if _FAST_MATH else 1.0)


def current_mode():
    # host-side read: nothing is traced here, mutation is visible
    return "fast" if _FAST_MATH else "exact"
