"""Clean twin: donation declared, or no state threading at all."""
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    return state, batch


@jax.jit
def eval_step(params, batch):
    return params, batch
