"""Clean twin: jax.random with threaded keys; host timing stays host."""
import time

import jax


@jax.jit
def seeded(x, key):
    return x + jax.random.normal(key, x.shape)


def host_timer():
    return time.time()
