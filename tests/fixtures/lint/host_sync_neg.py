"""Clean twin of host_sync_pos: no host syncs in traced scope."""
import jax
import jax.numpy as jnp


@jax.jit
def good_loss(params, batch):
    if params is None:
        return jnp.zeros(())
    rank = len(batch.shape)
    return jnp.mean(params * batch) * rank


def host_driver(results):
    # untraced host function: converting fetched values is the job
    return [float(r) for r in results]
