"""Seeded violations: Python control flow on tracer values."""
import jax
import jax.numpy as jnp


@jax.jit
def select(x, threshold):
    if jnp.any(x > threshold):  # LINT: tracer-branch
        x = x * 2.0
    while x.sum() > 1.0:  # LINT: tracer-branch
        x = x * 0.5
    assert x[0] > 0  # LINT: tracer-branch
    y = x if x.mean() > 0 else -x  # LINT: tracer-branch
    return y
