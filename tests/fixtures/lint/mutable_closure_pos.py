"""DGC108 positive: jitted scope reads a module flag that another
function mutates via ``global`` — the PR-6 "fresh-closure jaxpr-cache"
hazard. The first trace bakes ``_FAST_MATH``'s value into the cached
program; ``set_fast_math(True)`` afterwards changes nothing."""

import jax
import jax.numpy as jnp

_FAST_MATH = False


def set_fast_math(on):
    global _FAST_MATH
    _FAST_MATH = on


@jax.jit
def scale(x):
    factor = 2.0 if _FAST_MATH else 1.0  # LINT: mutable-closure
    return x * jnp.float32(factor)
