"""Seeded violations: host time/RNG frozen into a traced program."""
import random
import time

import jax
import numpy as np


@jax.jit
def noisy(x):
    t = time.time()  # LINT: host-entropy
    r = np.random.rand()  # LINT: host-entropy
    s = random.random()  # LINT: host-entropy
    return x * t * r * s
