"""Seeded violations: float64 literals / dtype drift."""
import numpy as np
import jax.numpy as jnp


def make_table(n):
    scale = np.float64(1.5)  # LINT: f64-dtype
    base = jnp.zeros((n,), dtype="float64")  # LINT: f64-dtype
    wide = base.astype(float)  # LINT: f64-dtype
    return scale, wide
