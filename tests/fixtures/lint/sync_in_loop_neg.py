"""Clean twin: device values collected in the loop, converted after."""


def train(step_fn, state, batches, writer):
    log = []
    for batch in batches:
        state, metrics = step_fn(state, batch)
        log.append(metrics["loss"])
    for loss in log:
        writer.log(float(loss))
    return state
