"""Seeded violations: unhashable static_argnums/static_argnames."""
import jax


def build(fn):
    return jax.jit(fn, static_argnums=[0, 1])  # LINT: static-argnums


def build_named(fn):
    return jax.jit(fn, static_argnames=["mode"])  # LINT: static-argnums
