"""Seeded violation: mutable state shared across threads, no lock.

`# LINT: <rule-id>` marks the lines tests expect the race linter to
flag (the emit site is the first unlocked write)."""
import threading


class Counter:
    def __init__(self):
        self._n = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        for _ in range(100):
            self._n = self._n + 1  # LINT: thread-shared-state

    def snapshot(self):
        # main-thread read races the worker's increment: += is
        # read-modify-write, so updates are lost and reads tear
        return self._n
