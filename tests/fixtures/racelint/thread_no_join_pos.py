"""Seeded violation: a non-daemon thread nothing ever joins —
interpreter shutdown blocks on it forever."""
import threading


def _worker(q):
    while True:
        q.get()


def start_worker(q):
    t = threading.Thread(target=_worker, args=(q,))  # LINT: thread-no-join
    t.start()
    return t
