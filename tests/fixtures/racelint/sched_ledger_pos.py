"""Seeded violation: a gang scheduler whose pump thread and tick-side
callers mutate the queue/holdings ledger WITHOUT a lock — the hazard
control.scheduler.GangScheduler is built to avoid (one lock around all
ledger state; decisions cross threads in a deque).

`# LINT: <rule-id>` marks the lines tests expect the race linter to
flag (the emit site is the first unlocked write)."""
import threading


class UnlockedScheduler:
    def __init__(self, total):
        self.total = total
        self._queue = []
        self._held = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        # scheduler loop thread: grants mutate the ledger while admit()
        # appends from the tick thread — a torn read double-grants a slot
        while not self._stop.wait(0.01):
            for entry in list(self._queue):
                slots = entry["slots"]
                if slots <= self.total - self._held:
                    self._queue.remove(entry)
                    self._held = self._held + slots  # LINT: thread-shared-state

    def admit(self, name, slots):
        self._queue.append({"name": name, "slots": slots})

    def completed(self, slots):
        self._held = self._held - slots
