"""Seeded violation: a worker thread and a signal handler write the
same file — a crash mid-write interleaves the two writers."""
import signal
import threading


class Dumper:
    def __init__(self, path):
        self.path = path
        signal.signal(signal.SIGTERM, self._on_term)
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            with open(self.path, "w") as f:  # LINT: thread-crash-file
                f.write("tick")

    def _on_term(self, signum, frame):
        # fires at ANY point of _run's write, including mid-line
        with open(self.path, "w") as f:
            f.write("final")
