"""Seeded violation: a thread mutates state a traced function reads.

The lock makes every access consistent — and still loses: the first
trace bakes ``self.scale`` into the compiled step, so the thread's
updates are silently ignored (cf. dgclint DGC108)."""
import threading

import jax


class Stepper:
    def __init__(self):
        self.scale = 1.0
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    @jax.jit
    def step(self, x):
        with self._lock:
            return x * self.scale

    def _run(self):
        while True:
            with self._lock:
                self.scale = self.scale * 0.5  # LINT: thread-traced-state
