"""Clean twin: the mutable value rides the step as an ARGUMENT, so
every trace sees the current value instead of the baked-in first one."""
import threading

import jax


class Stepper:
    def __init__(self):
        self.scale = 1.0
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    @jax.jit
    def step(self, x, scale):
        return x * scale

    def snapshot(self):
        with self._lock:
            return self.scale

    def _run(self):
        while True:
            with self._lock:
                self.scale = self.scale * 0.5
