"""Clean twin: the handler publishes to its own path, so the crash
path never interleaves with the worker's stream."""
import signal
import threading


class Dumper:
    def __init__(self, path):
        self.path = path
        signal.signal(signal.SIGTERM, self._on_term)
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            with open(self.path, "w") as f:
                f.write("tick")

    def _on_term(self, signum, frame):
        with open(self.path + ".final", "w") as f:
            f.write("final")
