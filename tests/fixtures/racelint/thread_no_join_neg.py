"""Clean twin: the module joins the worker (bounded), so shutdown has
an exit path."""
import threading


def _worker(q):
    while True:
        q.get()


def start_worker(q):
    t = threading.Thread(target=_worker, args=(q,))
    t.start()
    return t


def stop_worker(t):
    t.join(timeout=5)
