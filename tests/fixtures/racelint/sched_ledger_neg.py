"""Clean twin: the real GangScheduler shape — one lock guards every
piece of ledger state the pump thread and the tick-side callers share;
decisions cross threads through a deque (its appends are atomic)."""
import collections
import threading


class LockedScheduler:
    def __init__(self, total):
        self.total = total
        self._lock = threading.Lock()
        self._queue = []
        self._held = 0
        self._decisions = collections.deque()
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        while not self._stop.wait(0.01):
            with self._lock:
                for entry in list(self._queue):
                    slots = entry["slots"]
                    if slots <= self.total - self._held:
                        self._queue.remove(entry)
                        self._held = self._held + slots
                        self._decisions.append(entry)

    def admit(self, name, slots):
        with self._lock:
            self._queue.append({"name": name, "slots": slots})

    def completed(self, slots):
        with self._lock:
            self._held = self._held - slots
