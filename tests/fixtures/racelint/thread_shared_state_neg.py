"""Clean twin: every access to the shared counter holds one lock."""
import threading


class Counter:
    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        for _ in range(100):
            with self._lock:
                self._n = self._n + 1

    def snapshot(self):
        with self._lock:
            return self._n
