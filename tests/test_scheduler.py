"""Tests for the gang scheduler (ISSUE 19; docs/RESILIENCE.md
§Scheduler): starvation/fairness edges on a fake clock — a
never-grantable gang is parked without head-of-line blocking, priority
ties grant FIFO by admit time, an exiting gang is never a preemption
target — the persisted scheduler-ledger protocol (conservation on every
intact record, seq monotone across restarts, tolerant readers), the
plane-level gang lifecycle, and the 3-run priority-inversion drill:
a low-priority 2-seat gang and a high-priority 1-seat gang fill the
pool, a third gang queues behind them, the autoscale rule admits a grow
seat for the high-priority gang, and the scheduler resolves the
starvation through an audited admit → preempt_to_grant → grant → grow
chain — the victim shrinks through the cohort-surgery excise path and
the excised seat's residual mass survives the fold (NumPy oracle,
≤ 1e-6).

The unit tests and the plane lifecycle are host-only and fast; the
subprocess drill is ``slow``-marked (scripts/t1.sh runs a bounded
fake-clock smoke instead).
"""

import json
import os
import sys

import numpy as np
import pytest

from dgc_tpu.control import rules
from dgc_tpu.control.plane import ControlPlane, RunSpec
from dgc_tpu.control.rules import Rule
from dgc_tpu.control.scheduler import (GangScheduler, SCHED_GRANTS,
                                       SCHED_QUEUE, grant_latency_summary,
                                       read_grant_ledger, read_queue)
from dgc_tpu.control.supervisor import parse_env_file
from dgc_tpu.resilience import surgery
from dgc_tpu.telemetry import registry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "sched_worker.py")


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt
        return self.t


# --------------------------------------------------------------------- #
# grant policy: priorities, FIFO ties, starvation edges                  #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_priority_then_fifo_by_admit_time():
    clk = FakeClock()
    s = GangScheduler(4, clock=clk)
    s.admit("a", 1, priority=0)
    clk.tick()
    s.admit("b", 1, priority=0)     # same priority, later admit
    clk.tick()
    s.admit("c", 1, priority=5)     # higher priority, latest admit
    granted = [d["name"] for d in s.tick()]
    assert granted == ["c", "a", "b"]     # priority first, then FIFO
    assert s.snapshot()["free"] == 1


@pytest.mark.fast
def test_same_instant_ties_break_by_admission_seq():
    # a fake clock can admit two gangs at the same instant: the
    # admission sequence keeps the order deterministic
    s = GangScheduler(2, clock=FakeClock())
    s.admit("x", 1, priority=1, now=100.0)
    s.admit("y", 1, priority=1, now=100.0)
    assert [d["name"] for d in s.tick()] == ["x", "y"]


@pytest.mark.fast
def test_never_grantable_gang_is_parked_not_blocking():
    clk = FakeClock()
    s = GangScheduler(3, clock=clk)
    s.admit("whale", 5, priority=9)       # demand exceeds the whole pool
    assert s.tick() == []
    assert s.pending() == 0               # parked: no control loop spin
    assert s.snapshot()["unschedulable"] == ["whale"]
    # surfaced ONCE, then silent
    s.tick(), s.tick()
    # ... and smaller work behind it is never head-of-line blocked
    s.admit("minnow", 1, priority=0)
    granted = [d["name"] for d in s.tick()]
    assert granted == ["minnow"]
    snap = s.snapshot()
    assert snap["free"] == 2 and snap["holdings"]["minnow"]["slots"] == 1


@pytest.mark.fast
def test_no_backfill_past_a_starved_schedulable_head():
    clk = FakeClock()
    s = GangScheduler(3, clock=clk)
    s.admit("big", 2, priority=5)
    clk.tick()
    s.admit("small", 1, priority=0)
    assert [d["name"] for d in s.tick()] == ["big", "small"]
    # pool now full; an equal-priority 2-seat gang is starved with no
    # STRICTLY-lower victim holding >= 2 seats ("small" has 1 — a shrink
    # would leave no survivor for the elastic merge)
    clk.tick()
    s.admit("urgent", 2, priority=5)
    assert s.tick() == []
    # the lower-priority 1-seat entry behind the starved head must NOT
    # jump it (that is exactly the starvation the scheduler exists to
    # prevent)
    clk.tick()
    s.admit("sneak", 1, priority=0)
    assert s.tick() == []
    assert s.pending() == 2


@pytest.mark.fast
def test_duplicate_admit_rejected_and_cancel():
    s = GangScheduler(2, clock=FakeClock())
    rec = s.admit("g", 1)
    assert rec["event"] == "admit" and rec["queue_depth"] == 1
    assert s.admit("g", 1) == {"duplicate": True, "name": "g",
                               "kind": "launch"}
    # a different kind for the same name is NOT a duplicate
    assert s.admit("g", 1, kind="grow")["event"] == "admit"
    assert s.cancel("g", kind="grow") is True
    assert s.cancel("g") is True
    assert s.cancel("g") is False         # nothing left to drop
    assert s.pending() == 0
    with pytest.raises(ValueError):
        s.admit("g", 1, kind="resize")
    with pytest.raises(ValueError):
        GangScheduler(0)


# --------------------------------------------------------------------- #
# preempt-to-grant: victim choice                                        #
# --------------------------------------------------------------------- #

def _pool_with(s, *gangs):
    """Admit + grant (name, slots, priority) gangs into holdings."""
    for name, slots, pri in gangs:
        s.admit(name, slots, priority=pri)
    granted = {d["name"] for d in s.tick()}
    assert granted == {g[0] for g in gangs}
    return s


@pytest.mark.fast
def test_preempt_picks_lowest_priority_active_victim():
    clk = FakeClock()
    s = _pool_with(GangScheduler(5, clock=clk),
                   ("low", 2, 0), ("mid", 2, 1), ("hi", 1, 3))
    clk.tick()
    s.admit("urgent", 1, priority=9)
    (d,) = s.tick()
    assert d["decision"] == "preempt_to_grant"
    assert d["victim"] == "low" and d["victim_priority"] == 0
    assert d["name"] == "urgent" and d["short"] == 1
    # in flight: a second tick must not stack another preemption
    assert s.tick() == []
    assert s.snapshot()["preempt_inflight"] == {"low": "urgent"}
    # the shrink lands -> the freed seat grants the starved head
    s.shrunk("low")
    (g,) = s.tick()
    assert g["decision"] == "grant" and g["name"] == "urgent"
    assert s.snapshot()["preempt_inflight"] == {}
    assert s.holding("low") == {"slots": 1, "priority": 0,
                                "state": "active"}


@pytest.mark.fast
def test_preempt_skips_exiting_and_single_seat_gangs():
    clk = FakeClock()
    s = _pool_with(GangScheduler(4, clock=clk),
                   ("low", 2, 0), ("mid", 2, 1))
    s.mark_exiting("low")
    clk.tick()
    s.admit("urgent", 1, priority=9)
    # "low" is winding down (its seats free on their own) -> the victim
    # is the next-lowest ACTIVE gang
    (d,) = s.tick()
    assert d["victim"] == "mid"
    # once mid is in flight too, nothing else qualifies
    assert s.tick() == []


@pytest.mark.fast
def test_preempt_requires_strictly_lower_priority():
    clk = FakeClock()
    s = _pool_with(GangScheduler(2, clock=clk), ("peer", 2, 3))
    clk.tick()
    s.admit("rival", 1, priority=3)       # equal priority: no preemption
    assert s.tick() == []
    clk.tick()
    s.admit("boss", 1, priority=4)
    (d,) = s.tick()
    assert d["victim"] == "peer" and d["name"] == "boss"


# --------------------------------------------------------------------- #
# the persisted ledger (the "scheduler-ledger" protocol)                 #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_ledger_conservation_and_seq_monotone(tmp_path):
    root = str(tmp_path)
    clk = FakeClock()
    s = GangScheduler(4, root=root, clock=clk)
    s.admit("a", 2, priority=1)
    clk.tick()
    s.admit("b", 1, priority=0)
    clk.tick()
    s.tick()
    s.shrunk("a")
    s.mark_exiting("a")
    s.completed("b")
    s.close()

    records, skipped = read_grant_ledger(root)
    assert skipped == 0
    events = [r["event"] for r in records]
    assert events == ["admit", "admit", "grant", "grant", "shrunk",
                      "exiting", "completed"]
    seqs = [r["seq"] for r in records]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # EVERY intact record carries the conservation check
    for r in records:
        assert r["held"] + r["free"] == r["total"] == 4, r

    snap = read_queue(root)
    assert snap is not None and snap["queue"] == []
    assert snap["free"] == 3 and snap["holdings"]["a"]["slots"] == 1
    assert snap["seq"] == seqs[-1]

    lat = grant_latency_summary(records)
    assert lat["n"] == 2 and lat["max_s"] >= lat["median_s"] >= 0.0


@pytest.mark.fast
def test_seq_resumes_across_scheduler_restart(tmp_path):
    root = str(tmp_path)
    s = GangScheduler(2, root=root, clock=FakeClock())
    s.admit("a", 1)
    s.tick()
    s.close()
    last = read_grant_ledger(root)[0][-1]["seq"]

    s2 = GangScheduler(2, root=root, clock=FakeClock(200.0))
    rec = s2.admit("b", 1)
    s2.close()
    # the new incarnation resumed PAST everything durable: the ledger's
    # surviving prefix stays the true, strictly-monotone history
    assert rec["seq"] == last + 1


@pytest.mark.fast
def test_readers_tolerate_torn_and_absent_files(tmp_path):
    root = str(tmp_path)
    assert read_queue(root) is None
    assert read_grant_ledger(root) == ([], 0)
    with open(os.path.join(root, SCHED_QUEUE), "w") as f:
        f.write('{"total": 3, "que')                  # torn snapshot
    assert read_queue(root) is None
    with open(os.path.join(root, SCHED_QUEUE), "w") as f:
        json.dump(["not", "a", "snapshot"], f)
    assert read_queue(root) is None
    with open(os.path.join(root, SCHED_GRANTS), "w") as f:
        f.write('{"event": "admit", "seq": 1, "total": 3, "held": 0, '
                '"free": 3}\n')
        f.write('{"event": "grant", "se')             # torn tail
    records, skipped = read_grant_ledger(root)
    assert len(records) == 1 and skipped == 1
    assert grant_latency_summary(records) is None     # no intact grant


# --------------------------------------------------------------------- #
# the monitor's SCHED lane                                               #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_monitor_sched_lane(tmp_path):
    from dgc_tpu.telemetry import monitor
    root = str(tmp_path)
    assert monitor.collect_sched(root) is None      # no scheduler ran

    clk = FakeClock()
    s = GangScheduler(4, root=root, clock=clk)
    s.admit("train", 3, priority=1)
    s.admit("whale", 9, priority=0)
    clk.tick(2.0)
    s.tick()
    s.admit("batch", 2, priority=0)
    s.close()

    lane = monitor.collect_sched(root)
    assert lane["total"] == 4 and lane["free"] == 1
    assert lane["queue_depth"] == 1                 # batch (whale parked)
    assert lane["holdings"] == {"train": 3}
    assert lane["unschedulable"] == ["whale"]
    assert lane["grant_latency"]["n"] == 1
    assert lane["ledger_skipped"] == 0

    fsnap = monitor.collect_fleet(root)
    assert fsnap["sched"]["holdings"] == {"train": 3}
    status = monitor.render_fleet_status(fsnap)
    assert "SCHED:" in status and "1/4 free" in status
    assert "train:3" in status and "UNSCHEDULABLE [whale]" in status
    om = monitor.render_openmetrics_fleet(fsnap)
    assert "dgc_sched_slots_total 4" in om
    assert "dgc_sched_slots_free 1" in om
    assert "dgc_sched_queue_depth 1" in om
    assert 'dgc_sched_held_slots{run="train"} 3' in om
    assert "dgc_sched_grant_latency_seconds" in om


# --------------------------------------------------------------------- #
# plane-level gang lifecycle (fast: trivial member commands)             #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_plane_gang_grant_queue_and_complete(tmp_path):
    root = str(tmp_path)

    def gang(name, n, secs=0.4):
        return [RunSpec(
            f"{name}{i}",
            [sys.executable, "-c", f"import time; time.sleep({secs})"],
            run_dir=os.path.join(root, f"{name}{i}"), backoff=0.1)
            for i in range(n)]

    sched = GangScheduler(2, root=root)
    plane = ControlPlane([], root, rules=(), interval=0.05,
                         scheduler=sched)
    with pytest.raises(ValueError):
        plane.submit("empty", [])
    plane.submit("alpha", gang("alpha", 2), priority=0)
    plane.submit("beta", gang("beta", 1, secs=0.2), priority=1)
    with pytest.raises(ValueError):
        plane.submit("alpha", gang("dup", 1))
    final = plane.run(max_ticks=400)

    # beta (higher priority) granted first; alpha (2 seats) had to wait
    # for beta's slot to free — and everything completed
    for name in ("alpha0", "alpha1", "beta0"):
        assert final[name]["rc"] == 0 and final[name]["state"] == "done"
    chain = [(a["action"], a["run"]) for a in plane.actions]
    assert chain[:2] == [("admit", "alpha"), ("admit", "beta")]
    grants = [a for a in plane.actions if a["action"] == "grant"]
    assert [g["run"] for g in grants] == ["beta", "alpha"]
    assert set(grants[1]["result"]["launched"]) == {"alpha0", "alpha1"}
    for a in plane.actions:
        registry.validate_control_action(a)

    # pool ledger saw every granted member; scheduler returned all seats
    assert plane.pool.slots == {"alpha0": 1, "alpha1": 1, "beta0": 1}
    snap = sched.snapshot()
    assert snap["free"] == snap["total"] == 2 and snap["holdings"] == {}
    records, skipped = read_grant_ledger(root)
    assert skipped == 0
    # a tick can land between alpha0's and alpha1's exits, in which case
    # the partially-done gang is marked exiting (preemption shield)
    # before it completes — tolerate that optional record
    events = [r["event"] for r in records]
    assert [e for e in events if e != "exiting"] == [
        "admit", "admit", "grant", "completed", "grant", "completed"]
    assert all(r["name"] == "alpha" for r in records
               if r["event"] == "exiting")
    for r in records:
        assert r["held"] + r["free"] == r["total"] == 2


@pytest.mark.fast
def test_submit_without_scheduler_raises(tmp_path):
    plane = ControlPlane([], str(tmp_path), rules=())
    with pytest.raises(RuntimeError):
        plane.submit("g", [RunSpec("g0", ["true"],
                                   run_dir=str(tmp_path / "g0"))])


# --------------------------------------------------------------------- #
# the 3-run priority-inversion drill                                     #
# --------------------------------------------------------------------- #

def _drill_rules():
    # the shipped autoscale detector, tuned tick-fast: two consecutive
    # healthy ticks with headroom admit ONE grow seat
    return (
        Rule("autoscale-admit", rules.detect_autoscale, "admit",
             min_hits=2, debounce_s=5.0, budget=1),
    )


def _member(root, gang, i, env_file, world, steps, priority=0):
    run_dir = os.path.join(root, f"{gang}{i}")
    return RunSpec(
        f"{gang}{i}",
        [sys.executable, WORKER, run_dir,
         "--cohort", os.path.join(root, f"cohort_{gang}"),
         "--steps", str(steps), "--step-ms", "25", "--world", str(world)],
        run_dir=run_dir,
        env_file=env_file,
        env={"JAX_PROCESS_ID": str(i), "DGC_BOUNDARY_TIMEOUT": "3.5"},
        backoff=0.1, priority=priority)


@pytest.mark.slow
def test_priority_inversion_drill(tmp_path):
    root = str(tmp_path)
    envs = {}
    for gang, world in (("low", 2), ("hi", 1), ("bat", 1)):
        envs[gang] = os.path.join(root, f"{gang}.env")
        with open(envs[gang], "w") as f:
            f.write(f"JAX_NUM_PROCESSES={world}\n")

    sched = GangScheduler(3, root=root)
    plane = ControlPlane([], root, rules=_drill_rules(), interval=0.25,
                         scheduler=sched)
    # step counts keep every phase overlapped: hi (120 steps, ~3 s) is
    # still mid-run when the autoscale admit -> preempt -> grow chain
    # lands (~1.5 s); low (100 steps) is still mid-run at the preempt
    plane.submit("low", [_member(root, "low", i, envs["low"], 2, 100)
                         for i in range(2)], priority=0)
    plane.submit(
        "hi", [_member(root, "hi", 0, envs["hi"], 2, 120)],
        priority=2, slots_max=2,
        grow_spec=lambda seat: _member(root, "hi", seat, envs["hi"], 2,
                                       120))
    plane.submit("bat", [_member(root, "bat", 0, envs["bat"], 1, 10)],
                 priority=0)
    final = plane.run(max_ticks=400)

    # ---- outcomes: hi grew, low shrank (one seat excised), bat ran ----
    for name in ("low0", "hi0", "hi1", "bat0"):
        assert final[name]["rc"] == 0, (name, final[name])
        assert final[name]["state"] == "done"
    assert final["low1"]["rc"] == surgery.EXIT_SURGERY
    assert final["low1"]["state"] == "quarantined"
    assert final["low1"]["quarantined"] == "excised:manual"
    assert parse_env_file(envs["low"]) == {"JAX_NUM_PROCESSES": "1"}
    assert parse_env_file(envs["hi"]) == {"JAX_NUM_PROCESSES": "2"}

    # ---- the audited chain: admit -> grant -> preempt -> grow --------
    for a in plane.actions:
        registry.validate_control_action(a)
    chain = [(a["action"], a["run"]) for a in plane.actions]
    assert chain[:3] == [("admit", "low"), ("admit", "hi"),
                         ("admit", "bat")]
    grants = [a for a in plane.actions if a["action"] == "grant"]
    # priority order: hi first, then low (FIFO ahead of bat); bat only
    # after low's surviving seat finished and freed the pool
    assert [g["run"] for g in grants] == ["hi", "low", "bat"]

    scale = [a for a in plane.actions
             if a["action"] == "admit" and a["run"] == "hi0"]
    assert scale and scale[0]["rule"] == "autoscale-admit"
    assert scale[0]["evidence"]["kind"] == "autoscale"
    assert scale[0]["evidence"]["target_slots"] == 2
    assert scale[0]["result"]["admitted"] is True

    (pre,) = [a for a in plane.actions
              if a["action"] == "preempt_to_grant"]
    assert pre["run"] == "low" and pre["rule"] == "scheduler-preempt"
    assert pre["evidence"]["victim"] == "low"
    assert pre["evidence"]["beneficiary"] == "hi"
    assert pre["evidence"]["worker"] == 1 and pre["evidence"]["world"] == 2
    assert pre["result"]["published"] == {"JAX_NUM_PROCESSES": "1"}
    assert pre["result"]["order"]["verdict"] == "manual"
    assert len(pre["result"]["order"]["paths"]) == 2   # EVERY member

    (grow,) = [a for a in plane.actions if a["action"] == "grow"]
    assert grow["run"] == "hi" and grow["rule"] == "scheduler-grow"
    assert grow["evidence"]["seat"] == 1
    assert grow["evidence"]["world"] == 2
    assert grow["result"]["published"] == {"JAX_NUM_PROCESSES": "2"}
    assert grow["result"]["launched"] == ["hi1"]
    assert grow["result"]["cohort_restarted"] == ["hi0"]
    # the preemption freed the seat BEFORE the grow granted it
    order = [a["action"] for a in plane.actions]
    assert order.index("preempt_to_grant") < order.index("grow")

    # ---- the scheduler ledger tells the same story -------------------
    records, skipped = read_grant_ledger(root)
    assert skipped == 0
    for r in records:
        assert r["held"] + r["free"] == r["total"] == 3, r
    events = [(r["event"], r["name"]) for r in records]
    assert events.index(("preempt", "low")) \
        < events.index(("shrunk", "low")) \
        < [i for i, e in enumerate(events)
           if e == ("grant", "hi")][1]                # the grow grant
    shrunk = next(r for r in records if r["event"] == "shrunk")
    assert shrunk["beneficiary"] == "hi"
    grow_grant = [r for r in records if r["event"] == "grant"
                  and r["kind"] == "grow"]
    assert len(grow_grant) == 1 and grow_grant[0]["name"] == "hi"
    completed = [r["name"] for r in records if r["event"] == "completed"]
    assert set(completed) == {"low", "hi", "bat"}
    snap = read_queue(root)
    assert snap["free"] == 3 and snap["holdings"] == {}
    assert grant_latency_summary(records)["n"] == 4

    # ---- mass oracle: the excised seat's residual survived the fold --
    for gang, seats in (("low", (0, 1)), ("hi", (0, 1)), ("bat", (0,))):
        cohort = os.path.join(root, f"cohort_{gang}")
        recs = []
        for j in seats:
            with open(os.path.join(cohort, f"res.{j}.json")) as f:
                recs.append(json.load(f))
        actual = float(np.sum(np.asarray([r["res"] for r in recs],
                                         dtype=np.float64)))
        oracle = float(np.sum(np.asarray([r["mass_in"] for r in recs],
                                         dtype=np.float64)))
        assert oracle > 0.0, gang
        assert abs(actual - oracle) <= 1e-6, (gang, actual, oracle)
    # low1's final residual was folded into the survivor and zeroed
    with open(os.path.join(root, "cohort_low", "res.1.json")) as f:
        orphan = json.load(f)
    assert orphan["final"] is True and orphan["folded_into"] == 0
    assert orphan["res"] == 0.0 and orphan["mass_in"] > 0.0
    with open(os.path.join(root, "cohort_low", "res.0.json")) as f:
        assert 1 in json.load(f)["folded"]

    # ---- cohort walks: low 2 -> 1, hi 1 -> 2 -------------------------
    evs = [json.loads(l) for l in open(
        os.path.join(root, "low0", "supervise_events.jsonl"))]
    worlds = [e["cohort"].get("JAX_NUM_PROCESSES") for e in evs
              if e["event"] == "launch"]
    assert worlds[0] == "2" and worlds[-1] == "1"
    rec = surgery.read_exit_record(
        os.path.join(root, "low1", "checkpoints", surgery.EXIT_RECORD))
    assert rec["target"] == 1 and rec["world"] == 2
    assert rec["verdict"] == "manual"
    evs = [json.loads(l) for l in open(
        os.path.join(root, "hi0", "supervise_events.jsonl"))]
    worlds = [e["cohort"].get("JAX_NUM_PROCESSES") for e in evs
              if e["event"] == "launch"]
    assert worlds[0] == "1" and worlds[-1] == "2"

    # every completed member finished its steps; progress is cohort-wide
    with open(os.path.join(root, "cohort_low", "progress.json")) as f:
        assert json.load(f)["step"] == 100
    with open(os.path.join(root, "cohort_hi", "progress.json")) as f:
        assert json.load(f)["step"] == 120

    # the fleet stream carries the full audit trail + the freed-slot event
    events = [json.loads(l) for l in open(
        os.path.join(root, "control_events.jsonl"))]
    freed = [e for e in events if e["event"] == "sched_slot_freed"]
    assert freed and freed[0]["run"] == "low" and freed[0]["seat"] == "low1"
    action_evs = [e for e in events if e["event"] == "control_action"]
    assert len(action_evs) == len(plane.actions)
