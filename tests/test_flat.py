"""Flat-buffer engine (dgc_tpu.compression.flat): layout roundtrips, flat-vs-
per-tensor equivalence, vector weight-decay masks, and the flat train step on
the fake 8-device CPU mesh.

Equivalence strategy: with ``sample_ratio=1.0`` the sampled threshold is the
exact k-th largest importance and no RNG enters selection, so the flat and
per-tensor paths must produce identical exchanged gradients and memory state
(modulo float op order)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dgc_tpu import (
    Compression,
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    dgc_sgd,
    sgd,
)
from dgc_tpu.compression.flat import ParamLayout
from dgc_tpu.utils.pytree import named_flatten
from dgc_tpu.utils.compat import enable_x64, shard_map

W = 8


def _params():
    rng = np.random.RandomState(0)
    return {
        "conv1": {"kernel": jnp.asarray(rng.randn(3, 3, 4, 8), jnp.float32)},
        "conv2": {"kernel": jnp.asarray(rng.randn(3, 3, 8, 8), jnp.float32)},
        "dense": {"kernel": jnp.asarray(rng.randn(32, 10), jnp.float32),
                  "bias": jnp.asarray(rng.randn(10), jnp.float32)},
        "bn": {"scale": jnp.asarray(rng.randn(8), jnp.float32)},
    }


def _make_dist(sample_ratio=1.0, ratio=0.05, **kw):
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(ratio, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=sample_ratio, **kw)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4),
                                comp, world_size=W)
    return params, comp, dist


def test_layout_roundtrip():
    params = _params()
    named, _ = named_flatten(params)
    compressed = [n for n, p in named.items() if p.ndim > 1]
    layout = ParamLayout(params, compressed)
    flat = layout.flatten(params)
    assert flat.shape == (layout.total,)
    assert layout.num_params == sum(p.size for p in named.values())
    # compressed block is the row-aligned prefix; the gap holds the sentinel
    t_real = sum(named[n].size for n in compressed)
    assert layout.t_data >= t_real          # row tails are structural pads
    assert layout.sentinel == layout.t_data
    assert layout.t_compressed >= layout.t_data + 1
    assert layout.t_compressed % 1024 == 0 and layout.total % 1024 == 0
    # every compressed tensor sits inside exactly one bucket row
    for g in layout.buckets:
        for r, n in enumerate(g.names):
            assert layout.offsets[n] == g.base + r * g.cols
            assert layout.sizes[n] <= g.cols
    # every slot not covered by a real tensor is a structural zero
    fl = np.asarray(flat)
    covered = np.zeros((layout.total,), bool)
    for n in layout.names:
        covered[layout.offsets[n]:layout.offsets[n] + layout.sizes[n]] = True
    assert (fl[~covered] == 0).all()
    back = layout.unflatten(flat)
    for n, p in named_flatten(back)[0].items():
        np.testing.assert_array_equal(np.asarray(p), np.asarray(named[n]))


def test_int64_index_wire_path():
    """A flat buffer at/above 2**31 slots forces the int64 index wire
    format (BASELINE 'int64 idx' row): the layout reports index_dtype
    int64, the engine refuses to build without jax x64 mode (clear error,
    not a silent wrap), and under x64 the traced sparsify emits int64
    indices with the exact per-tensor payload. Shape-only structs +
    eval_shape keep the test allocation-free."""
    from dgc_tpu.compression.flat import FlatDGCEngine

    huge = {"w": jax.ShapeDtypeStruct((2 ** 31 + 128,), jnp.float32)}
    layout = ParamLayout(huge, ["w"])
    assert layout.index_dtype == np.int64
    numel = 2 ** 31 + 128
    comp = DGCCompressor(1e-6, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize([("w", (numel, (numel,)))])
    with pytest.raises(RuntimeError, match="x64"):
        FlatDGCEngine(comp, layout)
    with enable_x64(True):
        engine = FlatDGCEngine(comp, layout)
        assert engine.index_dtype == jnp.int64
        assert engine.payload_size == comp.attributes["w"].num_selects
        out = jax.eval_shape(
            engine.sparsify,
            jax.ShapeDtypeStruct((layout.t_compressed,), jnp.float32),
            jax.random.PRNGKey(0))
        assert out[1].dtype == jnp.int64
        assert out[0].shape == out[1].shape == (engine.payload_size,)
    # small layouts keep the int32 wire unless explicitly asked otherwise
    ok = {"w": jax.ShapeDtypeStruct((2 ** 20,), jnp.float32)}
    small = ParamLayout(ok, ["w"])
    assert small.index_dtype == np.int32
    comp2 = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9),
                          int32_indices=False)
    comp2.initialize([("w", (2 ** 20, (2 ** 20,)))])
    # int64-by-config also requires x64 (same clear error)
    with pytest.raises(RuntimeError, match="x64"):
        FlatDGCEngine(comp2, small)
    with enable_x64(True):
        assert FlatDGCEngine(comp2, small).index_dtype == jnp.int64


def test_int64_wire_exchange_runs(mesh8):
    """int32_indices=False on a small model under x64: the WHOLE exchange
    (compensate, sparsify, gather, scatter-add, sent-count record) runs
    with int64 wire indices and matches the int32 engine's output exactly
    (same selections — the index dtype is representation only)."""
    from dgc_tpu.utils.pytree import named_unflatten

    params = _params()
    named, treedef = named_flatten(params)
    rng = np.random.RandomState(21)
    grads_w = {n: rng.randn(W, *p.shape).astype(np.float32)
               for n, p in named.items()}

    def build(int32_indices):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, int32_indices=int32_indices)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        layout, engine = dist.make_flat(params)
        flat_g = jnp.stack([layout.flatten(named_unflatten(
            {n: jnp.asarray(grads_w[n][w]) for n in named}, treedef))
            for w in range(W)])
        mem = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
            engine.init_memory())
        f = _flat_exchange_fn(None, engine, mesh8)
        return engine, f(flat_g, mem, jax.random.PRNGKey(0))[0]

    with enable_x64(True):
        engine64, out64 = build(False)
        assert engine64.index_dtype == jnp.int64
        out64 = np.asarray(out64[0])
    engine32, out32 = build(True)
    assert engine32.index_dtype == jnp.int32
    assert np.isfinite(out64).all()
    np.testing.assert_allclose(out64, np.asarray(out32[0]),
                               rtol=1e-6, atol=1e-7)


def test_flat_engine_without_error_feedback(mesh8):
    """DGCCompressor with the no-op base Memory (memory=None): the engine
    runs sparsify+exchange with NO compensate/masking state (mem == {}),
    like the reference compressor when paired with the base Memory —
    output is the scatter-add average of each worker's raw top-k."""
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(0.05, sample_ratio=1.0)   # memory=None -> Memory()
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=W)
    layout, engine = dist.make_flat(params)
    assert engine.init_memory() == {}
    rng = np.random.RandomState(23)
    g = np.zeros((W, layout.total), np.float32)
    for n in layout.names:
        o, s = layout.offsets[n], layout.sizes[n]
        g[:, o:o + s] = rng.randn(W, s)

    def worker(fg, key):
        out, mem = engine.exchange(fg[0], {}, key, "data", W)
        assert mem == {}
        return out[None]

    f = jax.jit(shard_map(
        worker, mesh=mesh8, in_specs=(P("data"), P()),
        out_specs=P("data"), check_vma=False))
    out = np.asarray(f(jnp.asarray(g), jax.random.PRNGKey(0)))[0]
    assert np.isfinite(out).all()
    # each worker's top-num_selects contribution averaged; a coordinate
    # every worker selects equals the plain mean there
    name = layout.compressed_names[0]
    o, s = layout.offsets[name], layout.sizes[name]
    a = comp.attributes[name]
    per_worker_tops = [set(np.argsort(-np.abs(g[w, o:o + s]))
                           [:a.num_selects]) for w in range(W)]
    common = set.intersection(*per_worker_tops)
    for c in list(common)[:5]:
        np.testing.assert_allclose(out[o + c], g[:, o + c].mean(),
                                   rtol=1e-5, atol=1e-6)


def test_layout_mask_vector():
    params = _params()
    layout = ParamLayout(params, [])
    mask = np.asarray(layout.mask_vector(lambda n: "bn" not in n))
    named, _ = named_flatten(params)
    assert mask.sum() == sum(p.size for n, p in named.items() if "bn" not in n)
    off, sz = layout.offsets["bn/scale"], layout.sizes["bn/scale"]
    assert (mask[off:off + sz] == 0).all()


def _mem_full(engine, mem, w=None):
    """Split flat memory -> canonical {momentums, velocities} [P] numpy
    view via the engine (materializes any pending deferred mask),
    optionally selecting worker w from a [W]-leading-axis tree."""
    if w is not None:
        mem = jax.tree.map(lambda x: x[w], mem)
    return {k: np.asarray(v) for k, v in engine.memory_full(mem).items()}


def _flat_exchange_fn(dist, engine, mesh):
    def worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = engine.exchange(fg, mem, key, "data", W)
        return out[None], jax.tree.map(lambda x: x[None], mem)

    return jax.jit(shard_map(
        worker, mesh=mesh, in_specs=(P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))


def _pt_exchange_fn(dist, mesh):
    def worker(grads, mem, key):
        grads = jax.tree.map(lambda x: x[0], grads)
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = dist.exchange(grads, mem, key)
        return (jax.tree.map(lambda x: x[None], out),
                jax.tree.map(lambda x: x[None], mem))

    return jax.jit(shard_map(
        worker, mesh=mesh, in_specs=(P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))


@pytest.mark.parametrize("nesterov", [False, True])
@pytest.mark.parametrize("momentum_masking", [False, True])
def test_flat_matches_per_tensor_exchange(mesh8, nesterov, momentum_masking):
    """Same grads, deterministic selection -> identical exchanged gradients
    and memory on both paths, including over multiple steps (error feedback
    accumulates differently if masking or compensation diverges)."""
    params = _params()
    named, _ = named_flatten(params)

    def make(dist_cls=None):
        comp = DGCCompressor(
            0.05, memory=DGCSGDMemory(momentum=0.9, nesterov=nesterov,
                                      momentum_masking=momentum_masking),
            sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        return comp, DistributedOptimizer(
            dgc_sgd(0.1, momentum=0.9), comp, world_size=W)

    comp_f, dist_f = make()
    comp_p, dist_p = make()
    layout, engine = dist_f.make_flat(params)

    rng = np.random.RandomState(1)
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}

    flat_fn = _flat_exchange_fn(dist_f, engine, mesh8)
    pt_fn = _pt_exchange_fn(dist_p, mesh8)

    mem_f = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine.init_memory())
    mem_p = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         dist_p.init_memory(params))

    from dgc_tpu.utils.pytree import named_unflatten

    def worker_tree(w):
        return named_unflatten({n: grads_w[n][w] for n in named},
                               named_flatten(params)[1])

    flat_grads_w = jnp.stack(
        [layout.flatten(worker_tree(w)) for w in range(W)])

    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_f, mem_f = flat_fn(flat_grads_w, mem_f, key)
        out_p, mem_p = pt_fn(grads_w, mem_p, key)
        named_out_p, _ = named_flatten(out_p)
        named_out_f = layout.unflatten_named(out_f[0])
        for n in layout.names:
            np.testing.assert_allclose(
                np.asarray(named_out_f[n]).reshape(-1),
                np.asarray(named_out_p[n][0]).reshape(-1),
                rtol=1e-5, atol=1e-6,
                err_msg=f"exchanged grads step {step} {n}")
        # memory equivalence (flat stores split buffers; compare per name
        # through the full view)
        full_f = _mem_full(engine, mem_f, w=0)
        for mkey in ("momentums", "velocities"):
            named_m_f = layout.unflatten_named(full_f[mkey], keep_1d=True)
            for n in layout.names:
                np.testing.assert_allclose(
                    np.asarray(named_m_f[n]),
                    np.asarray(mem_p[mkey][n][0]).reshape(-1),
                    rtol=1e-5, atol=1e-6,
                    err_msg=f"{mkey} step {step} {n}")


def test_flat_matches_per_tensor_exchange_bf16_memory(mesh8):
    """The opt-in bf16 error-feedback state (DGCSGDMemory(dtype='bfloat16'),
    configs/dgc/bf16mem.py): flat and per-tensor paths round at the same
    points (f32 math, one round per stored value), so with deterministic
    selection they must still agree — at bf16 resolution — on exchanged
    gradients and memory state across steps, and every state buffer must
    actually BE bf16 on both paths."""
    params = _params()
    named, _ = named_flatten(params)

    def make():
        comp = DGCCompressor(
            0.05, memory=DGCSGDMemory(momentum=0.9, dtype="bfloat16"),
            sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        return comp, DistributedOptimizer(
            dgc_sgd(0.1, momentum=0.9), comp, world_size=W)

    comp_f, dist_f = make()
    comp_p, dist_p = make()
    layout, engine = dist_f.make_flat(params)

    mem0 = engine.init_memory()
    assert mem0["momentums_c"].dtype == jnp.bfloat16
    assert mem0["velocities_d"].dtype == jnp.bfloat16
    # the packed transmit record stays int32 words regardless of the
    # narrow state dtype (word-wide scatter, bit-expansion on read)
    assert mem0["sent_bits"].dtype == jnp.int32
    mem_p0 = dist_p.init_memory(params)
    assert all(v.dtype == jnp.bfloat16 for v in mem_p0["momentums"].values())

    rng = np.random.RandomState(3)
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}

    flat_fn = _flat_exchange_fn(dist_f, engine, mesh8)
    pt_fn = _pt_exchange_fn(dist_p, mesh8)

    mem_f = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         mem0)
    mem_p = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         mem_p0)

    from dgc_tpu.utils.pytree import named_unflatten

    def worker_tree(w):
        return named_unflatten({n: grads_w[n][w] for n in named},
                               named_flatten(params)[1])

    flat_grads_w = jnp.stack(
        [layout.flatten(worker_tree(w)) for w in range(W)])

    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_f, mem_f = flat_fn(flat_grads_w, mem_f, key)
        out_p, mem_p = pt_fn(grads_w, mem_p, key)
        named_out_p, _ = named_flatten(out_p)
        named_out_f = layout.unflatten_named(out_f[0])
        for n in layout.names:
            np.testing.assert_allclose(
                np.asarray(named_out_f[n], np.float32).reshape(-1),
                np.asarray(named_out_p[n][0], np.float32).reshape(-1),
                rtol=1e-2, atol=1e-2,
                err_msg=f"exchanged grads step {step} {n}")
        full_f = _mem_full(engine, jax.tree.map(lambda x: x[0], mem_f))
        for mkey in ("momentums", "velocities"):
            assert full_f[mkey].dtype == jnp.bfloat16
            named_m_f = layout.unflatten_named(
                jnp.asarray(full_f[mkey]), keep_1d=True)
            for n in layout.names:
                np.testing.assert_allclose(
                    np.asarray(named_m_f[n], np.float32),
                    np.asarray(mem_p[mkey][n][0], np.float32).reshape(-1),
                    rtol=1e-2, atol=1e-2,
                    err_msg=f"{mkey} step {step} {n}")


def test_flat_matches_per_tensor_exchange_int8_wire(mesh8):
    """int8 wire values (DGCCompressor(int8_values=True),
    configs/dgc/int8.py): both paths quantize per tensor with the same
    symmetric scale (max|payload|/127, round-to-nearest), so flat and
    per-tensor exchanges must produce identical dequantized gradients,
    and the dequantization error of each transmitted value is bounded by
    scale/2."""
    params = _params()
    named, _ = named_flatten(params)

    def make():
        comp = DGCCompressor(
            0.05, memory=DGCSGDMemory(momentum=0.9), sample_ratio=1.0,
            int8_values=True)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        return comp, DistributedOptimizer(
            dgc_sgd(0.1, momentum=0.9), comp, world_size=W)

    comp_f, dist_f = make()
    comp_p, dist_p = make()
    layout, engine = dist_f.make_flat(params)
    assert engine._row_map is not None
    assert int(engine._row_map.shape[0]) == engine.payload_size

    rng = np.random.RandomState(5)
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}

    flat_fn = _flat_exchange_fn(dist_f, engine, mesh8)
    pt_fn = _pt_exchange_fn(dist_p, mesh8)
    mem_f = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine.init_memory())
    mem_p = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         dist_p.init_memory(params))

    from dgc_tpu.utils.pytree import named_unflatten

    def worker_tree(w):
        return named_unflatten({n: grads_w[n][w] for n in named},
                               named_flatten(params)[1])

    flat_grads_w = jnp.stack(
        [layout.flatten(worker_tree(w)) for w in range(W)])

    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_f, mem_f = flat_fn(flat_grads_w, mem_f, key)
        out_p, mem_p = pt_fn(grads_w, mem_p, key)
        named_out_p, _ = named_flatten(out_p)
        named_out_f = layout.unflatten_named(out_f[0])
        for n in layout.names:
            np.testing.assert_allclose(
                np.asarray(named_out_f[n]).reshape(-1),
                np.asarray(named_out_p[n][0]).reshape(-1),
                rtol=1e-5, atol=1e-6,
                err_msg=f"exchanged grads step {step} {n}")
        # memory equivalence: the error-feedback residual (int8 EF) must
        # land identically on both paths
        full_f = _mem_full(engine, mem_f, w=0)
        for mkey in ("momentums", "velocities"):
            named_m_f = layout.unflatten_named(full_f[mkey], keep_1d=True)
            for n in layout.names:
                np.testing.assert_allclose(
                    np.asarray(named_m_f[n]),
                    np.asarray(mem_p[mkey][n][0]).reshape(-1),
                    rtol=1e-5, atol=1e-6,
                    err_msg=f"{mkey} step {step} {n}")


def test_int8_error_feedback_residual_semantics(mesh8):
    """int8 wire + error feedback (the default): after one exchange, the
    velocity at every transmitted coordinate holds exactly the
    quantization residual ``v - q*scale`` (NOT zero), the momentum is
    still masked, and with ``int8_error_feedback=False`` the round-3
    zeroing behavior returns."""
    params = _params()
    named, _ = named_flatten(params)

    def run(ef):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, int8_values=True,
                             int8_error_feedback=ef)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        layout, engine = dist.make_flat(params)
        rng = np.random.RandomState(2)
        from dgc_tpu.utils.pytree import named_unflatten
        grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
                   for n, p in named.items()}
        flat_grads_w = jnp.stack([
            layout.flatten(named_unflatten(
                {n: grads_w[n][w] for n in named},
                named_flatten(params)[1])) for w in range(W)])
        fn = _flat_exchange_fn(dist, engine, mesh8)
        mem = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
            engine.init_memory())
        out, mem = fn(flat_grads_w, mem, jax.random.PRNGKey(0))
        return layout, engine, flat_grads_w, mem

    layout, engine, fg, mem = run(ef=True)
    # recompute worker 0's selection to find its transmitted coordinates:
    # first step => velocity == momentum-corrected grad == grad (momentum
    # buffers start at zero, vec = 0 + (0*m + g))
    vec0 = np.asarray(fg[0][:layout.t_compressed])
    vals, idx = jax.jit(engine.sparsify)(jnp.asarray(vec0),
                                         jax.random.fold_in(
                                             jax.random.PRNGKey(0), 0))
    vals, idx = np.asarray(vals), np.asarray(idx)
    real = idx != layout.sentinel
    full = _mem_full(engine, mem, w=0)
    vel, mmt = full["velocities"], full["momentums"]
    # per-tensor symmetric scales over the payload rows
    rm = np.asarray(engine._row_map)
    scales = np.zeros(rm.max() + 1, np.float32)
    for rr in np.unique(rm):
        scales[rr] = np.abs(vals[rm == rr]).max() / 127.0
    safe = np.where(scales > 0, scales, 1.0)
    q = np.clip(np.round(vals / safe[rm]), -127, 127)
    resid = vals - q * scales[rm]
    np.testing.assert_allclose(vel[idx[real]], resid[real],
                               rtol=1e-5, atol=1e-7)
    assert np.abs(resid[real]).max() > 0          # feedback is non-trivial
    assert (mmt[idx[real]] == 0).all()            # momentum masked eagerly
    # transmit record stays empty (no deferred zeroing may kill residuals)
    assert not np.asarray(mem["sent_bits"]).any()

    layout0, engine0, _, mem0 = run(ef=False)
    full0 = _mem_full(engine0, mem0, w=0)
    np.testing.assert_array_equal(full0["velocities"][idx[real]], 0.0)


def test_int8_quantization_roundtrip_bound():
    """quantize_int8: dequantized values are within scale/2 of the
    original, zero maps to zero, and an all-zero vector survives."""
    from dgc_tpu.compression.dgc import quantize_int8
    rng = np.random.RandomState(0)
    v = jnp.asarray(rng.randn(1000) * np.exp(rng.randn(1000) * 3),
                    jnp.float32)
    q, scale = quantize_int8(v)
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32
    deq = np.asarray(q, np.float32) * float(scale)
    err = np.abs(deq - np.asarray(v))
    assert err.max() <= float(scale) / 2 + 1e-7
    assert float(scale) == pytest.approx(
        float(jnp.max(jnp.abs(v))) / 127.0)
    qz, sz = quantize_int8(jnp.zeros((16,), jnp.float32))
    assert float(sz) == 0.0 and not np.asarray(qz).any()


def test_warmup_ratio_rebuild_equivalence(mesh8):
    """The full wm5 warm-up schedule (6 ratio changes, reference
    compression.py:91-107) driven through the FLAT ENGINE REBUILD path:
    each ratio change rebuilds the engine (new static attrs, re-jit) while
    the memory buffers — including a pending deferred transmit mask from
    the previous ratio's last step — carry over untouched. The flat path
    must stay step-for-step identical to the per-tensor oracle across
    every transition (sample_ratio=1.0 makes selection deterministic)."""
    params = _params()
    named, _ = named_flatten(params)

    def mk():
        comp = DGCCompressor(0.001, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, warmup_epochs=5)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        return comp, DistributedOptimizer(
            dgc_sgd(0.1, momentum=0.9), comp, world_size=W)

    comp_f, dist_f = mk()
    comp_p, dist_p = mk()

    rng = np.random.RandomState(3)
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}
    from dgc_tpu.utils.pytree import named_unflatten

    def worker_tree(w):
        return named_unflatten({n: grads_w[n][w] for n in named},
                               named_flatten(params)[1])

    mem_f = mem_p = None
    layout0 = None
    ratios, payloads = [], []
    for epoch in range(7):
        ch_f = comp_f.warmup_compress_ratio(epoch)
        assert ch_f == comp_p.warmup_compress_ratio(epoch)
        assert ch_f == (epoch <= 5)
        layout, engine = dist_f.make_flat(params)   # the rebuild
        if layout0 is None:
            layout0 = layout
            flat_grads_w = jnp.stack(
                [layout.flatten(worker_tree(w)) for w in range(W)])
            mem_f = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                engine.init_memory())
            mem_p = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                dist_p.init_memory(params))
        # memory shapes are ratio-independent: the rebuilt engine adopts
        # the carried buffers with no conversion
        ratios.append(round(comp_f.compress_ratio, 4))
        payloads.append(engine.payload_size)
        flat_fn = _flat_exchange_fn(dist_f, engine, mesh8)
        pt_fn = _pt_exchange_fn(dist_p, mesh8)
        for s in range(2):
            key = jax.random.PRNGKey(epoch * 10 + s)
            out_f, mem_f = flat_fn(flat_grads_w, mem_f, key)
            out_p, mem_p = pt_fn(grads_w, mem_p, key)
            assert np.isfinite(np.asarray(out_f)).all()
            named_out_p, _ = named_flatten(out_p)
            named_out_f = layout.unflatten_named(out_f[0])
            for n in layout.names:
                np.testing.assert_allclose(
                    np.asarray(named_out_f[n]).reshape(-1),
                    np.asarray(named_out_p[n][0]).reshape(-1),
                    rtol=1e-5, atol=1e-6,
                    err_msg=f"epoch {epoch} step {s} {n}")
        full_f = _mem_full(engine, mem_f, w=0)
        for mkey in ("momentums", "velocities"):
            named_m_f = layout.unflatten_named(full_f[mkey], keep_1d=True)
            for n in layout.names:
                np.testing.assert_allclose(
                    np.asarray(named_m_f[n]),
                    np.asarray(mem_p[mkey][n][0]).reshape(-1),
                    rtol=1e-5, atol=1e-6,
                    err_msg=f"{mkey} epoch {epoch} {n}")
    assert ratios == [0.3162, 0.1, 0.0316, 0.01, 0.0032, 0.001, 0.001]
    # payload shrinks with the ratio and is constant once warm-up ends
    assert payloads == sorted(payloads, reverse=True)
    assert payloads[-1] == payloads[-2]
    # error feedback survived to the end: residuals accumulated
    assert np.abs(full_f["velocities"]).sum() > 0


def test_flat_payload_matches_reference_wire_volume():
    """The tight payload is exactly sum(num_selects) — the reference's wire
    size (compression.py:151), no padding inflation."""
    params, comp, dist = _make_dist(sample_ratio=0.25, ratio=0.01)
    layout, engine = dist.make_flat(params)
    expected = sum(a.num_selects for a in comp.attributes.values())
    assert engine.payload_size == expected


def test_flat_sparsify_selects_topk(mesh8):
    """With deterministic sampling, the flat engine selects exactly the
    num_selects largest-|.| coordinates of each tensor."""
    params, comp, dist = _make_dist(sample_ratio=1.0, ratio=0.05)
    layout, engine = dist.make_flat(params)
    rng = np.random.RandomState(2)
    vec = np.zeros((layout.t_compressed,), np.float32)
    vec[:layout.t_data] = rng.randn(layout.t_data).astype(np.float32)
    vals, idx = jax.jit(engine.sparsify)(jnp.asarray(vec),
                                         jax.random.PRNGKey(0))
    vals, idx = np.asarray(vals), np.asarray(idx)
    for name in layout.compressed_names:
        a = comp.attributes[name]
        off = layout.offsets[name]
        seg = vec[off:off + a.numel]
        expect = set(off + np.argsort(-np.abs(seg))[:a.num_selects])
        got = {int(i) for i in idx if off <= i < off + a.numel}
        assert got == expect, name
        for i in idx:
            if off <= i < off + a.numel:
                assert vals[list(idx).index(i)] == seg[i - off]


def test_ladder_from_topk_matches_full_scan():
    """The hot path derives the resample ladder from the selection top-k
    (flat._ladder_adapt_from_topk); it must choose the IDENTICAL adapted
    threshold as the full [R, cols] ladder scan (flat._ladder_adapt) for
    exact top-k — across descending, immediately-passing, and saturated
    count regimes."""
    from dgc_tpu.compression.flat import _ladder_adapt, _ladder_adapt_from_topk

    rng = np.random.RandomState(11)
    R, cols, k = 6, 4096, 64
    imp = jnp.asarray(np.abs(rng.randn(R, cols)).astype(np.float32))
    num_selects = jnp.asarray(
        rng.randint(8, k + 1, R).astype(np.float32))
    adapt = jnp.asarray(np.array([True] * (R - 1) + [False]))
    top_scores = jax.lax.top_k(imp, k)[0]
    for scale in (8.0, 1.0, 0.05):  # high thr (descends) .. low (saturates)
        # per-row thresholds near the selection quantile, scaled
        thr = top_scores[:, k // 2] * scale
        a = _ladder_adapt(imp, thr, num_selects, adapt, 0.8, 10)
        b = _ladder_adapt_from_topk(top_scores, thr, num_selects, adapt,
                                    0.8, 10)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"scale {scale}")


def _sampling_test_data(kind, numel, rng):
    """Gradient distributions the threshold estimator must survive:
    well-behaved Gaussian; heavy-tailed Student-t3 (fc-layer gradients —
    rare huge entries dominate the top-k); low-rank rank-1 + noise
    (structured gradients whose contiguous elements — and hence whole
    128-lane sample blocks — are strongly correlated, the adversarial
    case for lane-block sampling's effective sample size)."""
    if kind == "gauss":
        return rng.randn(numel).astype(np.float32)
    if kind == "t3":
        return rng.standard_t(3, numel).astype(np.float32)
    u = rng.randn(300, 1)
    v = rng.randn(1, 400)
    return (u @ v + 0.05 * rng.randn(300, 400)).astype(np.float32).ravel()


@pytest.mark.parametrize("kind", ["gauss", "t3", "lowrank"])
def test_lane_block_sampling_quantile(kind):
    """Lane-block strided sampling, across gradient distributions: (a)
    the drawn sample count tracks the geometry's num_samples (the old
    nb = n // 128 truncation drew as little as half the budget), and (b)
    the sampled threshold estimates the target quantile — the fraction of
    elements above the raw (pre-adaptation) threshold stays within a
    constant factor of the compress ratio across random phases, at a
    moderate stride. The threshold is an order statistic, so the band is
    distribution-free; within-block correlation (lowrank) widens the
    estimator's variance, which is what the band budgets for."""
    ratio, numel = 0.01, 120_000
    comp = DGCCompressor(ratio, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=0.05, max_adaptation_iters=0)
    comp.initialize([("w", (numel, (300, 400)))])
    a = comp.attributes["w"]
    assert a.sample_stride > 1  # genuinely strided
    params = {"w": jnp.zeros((300, 400), jnp.float32)}
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    [b] = engine.buckets

    data = _sampling_test_data(kind, numel, np.random.RandomState(5))
    vec = np.zeros((layout.t_compressed,), np.float32)
    vec[:numel] = data
    block = jnp.asarray(vec[:b.rows * b.cols]).reshape(b.rows, b.cols)
    col = jnp.arange(b.cols)[None, :]
    imp_rows = jnp.where(col < int(a.numel), jnp.abs(block), -1.0)

    sample_fn = jax.jit(lambda k: engine._sample_rows(b, imp_rows, k))
    fractions = []
    for seed in range(30):
        smp = np.asarray(sample_fn(jax.random.PRNGKey(seed)))
        drawn = int((smp >= 0).sum())
        # (a) budget: within [1.0, 1.0 + lane-block rounding slack]
        assert a.num_samples <= drawn <= a.num_samples + 128, drawn
        # (b) threshold = top_k_samples-th largest sample (engine rule)
        thr = np.sort(smp[smp >= 0])[-a.top_k_samples]
        fractions.append((np.abs(data) >= thr).sum() / numel)
    med = float(np.median(fractions))
    # quantile error bounded: the ladder's one-sided correction (x0.8 per
    # level) easily covers a [0.4, 2.5]x band
    assert 0.4 * ratio <= med <= 2.5 * ratio, (kind, med)


@pytest.mark.parametrize("kind", ["gauss", "t3", "lowrank"])
def test_selection_count_within_adaptation_bounds(kind):
    """End-to-end selection counts under the full pipeline (sampling +
    ladder adaptation + top-k cap): for every distribution and every
    random phase, the number of REAL transmitted coordinates stays inside
    the adaptation contract [lower_bound * num_selects, num_selects] —
    the ladder must recover whatever bias/variance the distribution
    induces in the raw threshold estimate (reference
    compression.py:128-151 semantics)."""
    ratio, numel = 0.01, 120_000
    comp = DGCCompressor(ratio, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=0.05)       # default ladder (10 iters)
    comp.initialize([("w", (numel, (300, 400)))])
    a = comp.attributes["w"]
    params = {"w": jnp.zeros((300, 400), jnp.float32)}
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    sp = jax.jit(engine.sparsify)
    ns = int(a.num_selects)
    floor = int(comp.compress_lower_bound * ns)
    for seed in range(20):
        data = _sampling_test_data(kind, numel,
                                   np.random.RandomState(100 + seed))
        vec = np.zeros((layout.t_compressed,), np.float32)
        vec[:numel] = data
        _, idx = sp(jnp.asarray(vec), jax.random.PRNGKey(seed))
        real = int((np.asarray(idx) != layout.sentinel).sum())
        assert floor <= real <= ns, (kind, seed, real, floor, ns)


def test_split_bucket_stratified_selection(monkeypatch):
    """Giant single-tensor rows split into segments (flat._SPLIT_COLS):
    the per-tensor quota distributes exactly across segments and, with
    deterministic sampling, each segment selects exactly its top-quota
    coordinates (stratified selection; payload/wire volume unchanged)."""
    import dgc_tpu.compression.flat as flat

    monkeypatch.setattr(flat, "_SPLIT_COLS", 1024)
    monkeypatch.setattr(flat, "_SPLIT_TARGET", 1024)
    params = {"w": {"kernel": jnp.zeros((64, 128), jnp.float32)}}
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize([("w/kernel", (8192, (64, 128)))])
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    a = comp.attributes["w/kernel"]
    [b] = engine.buckets
    assert b.rows > 1 and b.rows * b.cols == 8192
    assert int(b.num_selects.sum()) == a.num_selects  # exact quota total
    # the wire payload may be the padded [R, max_sel] grid when the
    # inflation stays under flat._PAD_PAYLOAD_MAX_FRAC (identity tight
    # map, no compaction gather) — real transmitted elements stay
    # exactly the per-segment quotas (checked below)
    assert (a.num_selects <= engine.payload_size
            <= (1 + flat._PAD_PAYLOAD_MAX_FRAC) * a.num_selects + 1)

    rng = np.random.RandomState(3)
    vec = np.zeros((layout.t_compressed,), np.float32)
    vec[:8192] = rng.randn(8192).astype(np.float32)
    vals, idx = jax.jit(engine.sparsify)(jnp.asarray(vec),
                                         jax.random.PRNGKey(0))
    idx = np.asarray(idx)
    got = set(int(i) for i in idx if i < 8192)
    expect = set()
    for s in range(b.rows):
        seg = vec[s * b.cols:(s + 1) * b.cols]
        ns = int(b.num_selects[s])
        expect.update(s * b.cols + np.argsort(-np.abs(seg))[:ns])
    assert got == expect


@pytest.mark.parametrize("kw", [
    dict(),                                    # sampled + ladder adaptation
    dict(sample_ratio=1.0),                    # exact (sample-everything)
    dict(strided_sample=False),                # uniform resample
    dict(resample=False),                      # two-sided batched adaptation
])
def test_payload_indices_unique(mesh8, kw):
    """The engine's payload must never contain duplicate non-sentinel
    indices: ``kernels.pack_sent_bits`` scatters single bits ADDITIVELY
    (a repeated index would carry into a neighboring coordinate's bit and
    silently corrupt its error-feedback mask), so uniqueness is a hard
    precondition of the transmit record, not a style point. This pins it
    at the payload level for every selection path — a future selection
    change that emits duplicates fails here loudly."""
    params, comp, dist = _make_dist(ratio=0.05, **kw)
    layout, engine = dist.make_flat(params)
    rng = np.random.RandomState(11)
    vec = np.zeros((layout.t_compressed,), np.float32)
    for n in layout.compressed_names:
        o, s = layout.offsets[n], layout.sizes[n]
        vec[o:o + s] = rng.randn(s).astype(np.float32)
    for step in range(3):
        _, idx = jax.jit(engine.sparsify)(jnp.asarray(vec),
                                          jax.random.PRNGKey(step))
        idx = np.asarray(idx)
        real = idx[idx != layout.sentinel]
        assert len(np.unique(real)) == len(real), kw


def test_payload_indices_unique_split_bucket(monkeypatch):
    """Same duplicate-free guarantee through the segment-split (giant row)
    path: segments partition the tensor, so cross-segment duplicates are
    structurally impossible — assert it anyway at the payload level."""
    import dgc_tpu.compression.flat as flat

    monkeypatch.setattr(flat, "_SPLIT_COLS", 1024)
    monkeypatch.setattr(flat, "_SPLIT_TARGET", 1024)
    params = {"w": {"kernel": jnp.zeros((64, 128), jnp.float32)}}
    comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=0.05)
    comp.initialize([("w/kernel", (8192, (64, 128)))])
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    assert engine.buckets[0].rows > 1
    rng = np.random.RandomState(5)
    vec = np.zeros((layout.t_compressed,), np.float32)
    vec[:8192] = rng.randn(8192).astype(np.float32)
    _, idx = jax.jit(engine.sparsify)(jnp.asarray(vec),
                                      jax.random.PRNGKey(2))
    idx = np.asarray(idx)
    real = idx[idx != layout.sentinel]
    assert len(np.unique(real)) == len(real)


def test_3d_layout_free_selection_path(monkeypatch):
    """Wide buckets (cols >= SEL3D_MIN_COLS) select through the layout-free
    3-D path (lane-stratified candidates + small final top-k, no 2-D
    relayout). On CPU both approx stages lower to exact, so the selection
    must recover nearly all of the exact top-num_selects (lane caps at
    SEL3D_MARGIN x the mean bind with negligible probability) and the
    payload invariants hold: indices in-tensor, values = vec[idx], valid
    count ladder-bounded. The gate is lowered so a CI-sized tensor takes
    the path (production gates at 3M cols, where the paired A/B says the
    3-D form wins)."""
    from dgc_tpu.compression.flat import FlatDGCEngine

    monkeypatch.setattr(FlatDGCEngine, "SEL3D_MIN_COLS", 1024 * 1024)
    numel = 1_200_000
    comp = DGCCompressor(0.005, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=0.01)
    comp.initialize([("w", (numel, (numel,)))])
    params = {"w": jax.ShapeDtypeStruct((numel,), jnp.float32)}
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    [b] = engine.buckets
    assert engine._use_3d(b), (b.cols, b.strides, b.num_samples)

    a = comp.attributes["w"]
    rng = np.random.RandomState(17)
    vec = np.zeros((layout.t_compressed,), np.float32)
    vec[:numel] = rng.randn(numel).astype(np.float32)
    vals, idx = jax.jit(engine.sparsify)(jnp.asarray(vec),
                                         jax.random.PRNGKey(0))
    vals, idx = np.asarray(vals), np.asarray(idx)
    real = idx != layout.sentinel
    count = int(real.sum())
    # ladder adaptation guarantees at least lower_bound * num_selects pass
    # (and the slot cap bounds above)
    assert 0.8 * a.num_selects * 0.9 <= count <= a.num_selects
    assert (idx[real] < numel).all() and (idx[real] >= 0).all()
    np.testing.assert_array_equal(vals[real], vec[idx[real]])
    assert len(np.unique(idx[real])) == count  # no duplicate coordinates
    # near-exact recall on CPU (both approx stages lower to exact sorts)
    exact = set(np.argsort(-np.abs(vec[:numel]))[:count])
    recall = len(exact & set(idx[real].tolist())) / count
    assert recall >= 0.95, recall


def test_flat_dense_exchange_psum(mesh8):
    params = _params()
    dist = DistributedOptimizer(sgd(0.1), Compression.none(), world_size=W)
    layout, engine = dist.make_flat(params)
    rng = np.random.RandomState(3)
    g = rng.randn(W, layout.total).astype(np.float32)
    f = _flat_exchange_fn(dist, engine, mesh8)
    out, _ = f(jnp.asarray(g), {}, jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(out[0]), g.mean(0), rtol=1e-5)


def test_vector_wd_mask_matches_tree_mask():
    """dgc_sgd over one flat buffer with a 0/1 mask vector == dgc_sgd over
    the pytree with per-leaf boolean masks."""
    params = _params()
    named, _ = named_flatten(params)
    layout = ParamLayout(params, [])
    rng = np.random.RandomState(4)
    grads = {n: jnp.asarray(rng.randn(*p.shape), jnp.float32)
             for n, p in named.items()}

    pred = lambda n: "bn" not in n and "bias" not in n
    tree_mask = jax.tree_util.tree_map_with_path(
        lambda path, _: pred("/".join(str(getattr(k, 'key', k))
                                      for k in path)), params)
    opt_tree = dgc_sgd(0.1, momentum=0.9, weight_decay=1e-2,
                       weight_decay_mask=tree_mask)
    opt_flat = dgc_sgd(0.1, momentum=0.9, weight_decay=1e-2,
                       weight_decay_mask=layout.mask_vector(pred))

    from dgc_tpu.utils.pytree import named_unflatten
    st_t = opt_tree.init(params)
    flat_p = layout.flatten(params)
    st_f = opt_flat.init(flat_p)
    flat_g = layout.flatten(
        named_unflatten(dict(grads), named_flatten(params)[1]))

    p_t, p_f = params, flat_p
    g_named = grads
    for _ in range(3):
        upd_t, st_t = opt_tree.update(
            jax.tree_util.tree_unflatten(
                named_flatten(params)[1], [g_named[n] for n in named]),
            st_t, p_t)
        upd_f, st_f = opt_flat.update(flat_g, st_f, p_f)
        p_t = jax.tree.map(lambda a, b: a + b, p_t, upd_t)
        p_f = p_f + upd_f
        named_t, _ = named_flatten(p_t)
        named_f = layout.unflatten_named(p_f)
        for n in layout.names:
            np.testing.assert_allclose(np.asarray(named_f[n]).reshape(-1),
                                       np.asarray(named_t[n]).reshape(-1),
                                       rtol=1e-6, atol=1e-7)


def test_flat_train_step_smoke(mesh8):
    """Full flat train step on the CPU mesh: runs, loss finite, params move,
    and a compress-ratio change rebuild keeps working."""
    from dgc_tpu.models import resnet20
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)

    model = resnet20(num_classes=10)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                   train=True)
    named, _ = named_flatten(v["params"])
    comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9),
                         warmup_epochs=2)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    comp.warmup_compress_ratio(0)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4),
                                comp, world_size=W)
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh8,
                        dist_opt=dist)
    step = build_train_step(model.apply, dist, mesh8, flat=setup)

    rng = np.random.RandomState(5)
    images = jnp.asarray(rng.randn(W * 4, 32, 32, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, W * 4), jnp.int32)
    p0 = np.asarray(state.params)
    state, m = step(state, images, labels, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))
    assert int(state.step) == 1
    assert not np.allclose(p0, np.asarray(state.params))

    # ratio change -> rebuild engine + step, state carries over
    changed = comp.warmup_compress_ratio(5)
    assert changed
    setup2 = make_flat_setup(v, dist)
    step2 = build_train_step(model.apply, dist, mesh8, flat=setup2)
    state, m = step2(state, images, labels, jax.random.PRNGKey(1))
    assert np.isfinite(float(m["loss"]))


def test_flat_uninitialized_compressor_degrades_to_dense(mesh8):
    """A DGCCompressor whose initialize() was never called has no attributes:
    every parameter must fall through to the dense psum block (the per-tensor
    path's `name in attributes` guard, dgc.py compress)."""
    params = _params()
    comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9))
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    layout, engine = dist.make_flat(params)
    assert layout.t_compressed == 0 and engine.payload_size == 0
    rng = np.random.RandomState(7)
    g = rng.randn(W, layout.total).astype(np.float32)
    f = _flat_exchange_fn(dist, engine, mesh8)
    mem = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                       engine.init_memory())
    out, _ = f(jnp.asarray(g), mem, jax.random.PRNGKey(0))
    # dense block applies non-accumulating momentum correction to the average;
    # on zero-initialized memory step 1 output == the plain average
    np.testing.assert_allclose(np.asarray(out[0]), g.mean(0), rtol=1e-5)


def test_flat_uniform_sampling_exact_for_tiny_tensors():
    """strided_sample=False with tensors whose numel <= 2/ratio (the
    sample-everything path): the threshold must come from the exact
    importance vector, not a with-replacement draw."""
    params = {"w": jnp.asarray(np.arange(1, 41, dtype=np.float32)
                               .reshape(5, 8))}
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         strided_sample=False)
    comp.initialize([("w", params["w"])])
    a = comp.attributes["w"]
    assert a.num_samples == a.numel  # degenerate sample-everything geometry
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=W)
    layout, engine = dist.make_flat(params)
    vec = np.zeros((layout.t_compressed,), np.float32)
    vec[:40] = np.arange(1, 41, dtype=np.float32)
    vals, idx = jax.jit(engine.sparsify)(jnp.asarray(vec),
                                         jax.random.PRNGKey(3))
    got = {int(i) for v, i in zip(np.asarray(vals), np.asarray(idx))
           if i < layout.t_data}
    expect = set(np.argsort(-vec)[:a.num_selects])
    assert got == expect


def test_flat_ratio_one_routes_dense(mesh8):
    """compress_ratio == 1.0 must transmit everything dense with the
    per-tensor path's non-accumulating correction (dgc.py's
    `compress_ratio < 1.0` guard) — no sparse payload at all."""
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(1.0, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=W)
    layout, engine = dist.make_flat(params)
    assert engine.payload_size == 0
    rng = np.random.RandomState(11)
    g = rng.randn(W, layout.total).astype(np.float32)
    f = _flat_exchange_fn(dist, engine, mesh8)
    mem = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                       engine.init_memory())
    out, mem2 = f(jnp.asarray(g), mem, jax.random.PRNGKey(0))
    # zero-initialized memory, step 1: out == momentum-corrected average
    # == 0.9*0 + mean(g)
    np.testing.assert_allclose(np.asarray(out[0]), g.mean(0), rtol=1e-5)
    # velocities untouched on the dense path (memory.py:64-70)
    np.testing.assert_array_equal(
        _mem_full(engine, mem2, w=0)["velocities"], 0)


def test_flat_memory_state_dict_roundtrip():
    params, comp, dist = _make_dist(sample_ratio=1.0, ratio=0.05)
    layout, engine = dist.make_flat(params)
    mem = engine.init_memory()
    mem = {k: v if k == "sent_bits"
           else v + (1.0 if k.startswith("momentums") else 2.0)
           for k, v in mem.items()}
    sd = engine.memory_state_dict(mem)
    assert set(sd) == {"momentums", "velocities"}
    assert set(sd["momentums"]) == set(layout.names)
    back = _mem_full(
        engine, engine.load_memory_state_dict(engine.init_memory(), sd))
    # per-name contents round-trip; gap slots stay structurally zero
    for mkey, val in (("momentums", 1.0), ("velocities", 2.0)):
        named_b = layout.unflatten_named(back[mkey], keep_1d=True)
        for n in layout.names:
            np.testing.assert_allclose(np.asarray(named_b[n]), val)
        b = np.asarray(back[mkey])
        assert (b[layout.t_data:layout.t_compressed] == 0).all()


def test_shard_state_rejects_conflicting_flags():
    from dgc_tpu.parallel import make_mesh
    from dgc_tpu.training import TrainState, shard_state

    state = TrainState(step=jnp.zeros((), jnp.int32), params=jnp.zeros((4,)),
                       opt_state=None, memory={}, batch_stats={})
    dist = DistributedOptimizer(sgd(0.1), Compression.none(), world_size=1)
    with pytest.raises(ValueError, match="not both"):
        shard_state(state, make_mesh(1), per_worker_opt=True, dist_opt=dist)


@pytest.mark.parametrize("global_clip", [False, True])
def test_flat_gradient_clipping_matches_per_tensor(mesh8, global_clip):
    """A gradient_clipping hook plugged into DGCSGDMemory (reference
    memory.py:34,52-53) must behave identically on the flat engine and the
    per-tensor oracle: clip the LOCAL grad inside the accumulating
    compensate and the AVERAGED grad on the dense fallback. Covers both a
    local clip and the psum-backed global-norm clip (clip_grad.py:35-42)."""
    import functools

    from dgc_tpu.utils.clip_grad import (clip_grad_norm,
                                         clip_grad_norm_2_by_global)

    params = _params()
    named, _ = named_flatten(params)
    if global_clip:
        clip = functools.partial(clip_grad_norm_2_by_global, max_norm=0.05,
                                 axis_name="data")
    else:
        clip = functools.partial(clip_grad_norm, max_norm=0.05)

    def make():
        comp = DGCCompressor(
            0.05, memory=DGCSGDMemory(momentum=0.9, gradient_clipping=clip),
            sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        return comp, DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                          world_size=W)

    _, dist_f = make()
    _, dist_p = make()
    layout, engine = dist_f.make_flat(params)

    rng = np.random.RandomState(21)
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}
    from dgc_tpu.utils.pytree import named_unflatten
    flat_grads_w = jnp.stack([
        layout.flatten(named_unflatten({n: grads_w[n][w] for n in named},
                                       named_flatten(params)[1]))
        for w in range(W)])

    flat_fn = _flat_exchange_fn(dist_f, engine, mesh8)
    pt_fn = _pt_exchange_fn(dist_p, mesh8)
    mem_f = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine.init_memory())
    mem_p = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         dist_p.init_memory(params))

    clipped_any = False
    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_f, mem_f = flat_fn(flat_grads_w, mem_f, key)
        out_p, mem_p = pt_fn(grads_w, mem_p, key)
        named_out_p, _ = named_flatten(out_p)
        named_out_f = layout.unflatten_named(out_f[0])
        for n in layout.names:
            np.testing.assert_allclose(
                np.asarray(named_out_f[n]).reshape(-1),
                np.asarray(named_out_p[n][0]).reshape(-1),
                rtol=1e-5, atol=1e-6,
                err_msg=f"exchanged grads step {step} {n}")
        full_f = _mem_full(engine, mem_f, w=0)
        for mkey in ("momentums", "velocities"):
            named_m_f = layout.unflatten_named(full_f[mkey], keep_1d=True)
            for n in layout.names:
                np.testing.assert_allclose(
                    np.asarray(named_m_f[n]),
                    np.asarray(mem_p[mkey][n][0]).reshape(-1),
                    rtol=1e-5, atol=1e-6,
                    err_msg=f"{mkey} step {step} {n}")
        # the clip must actually engage: raw grads have norm >> 0.05
        for n in layout.compressed_names:
            seg = full_f["momentums"][
                layout.offsets[n]:layout.offsets[n] + layout.sizes[n]]
            if np.linalg.norm(seg) < 1.0:
                clipped_any = True
    assert clipped_any


# ------------------------------------------------------------------ #
# bit-packed index wire (compression/wirecodec.py, configs/dgc/packidx)
# ------------------------------------------------------------------ #


def test_index_codec_roundtrip():
    """IndexCodec: every in-row index decodes to exactly itself, for
    random payloads across rows of mixed sizes (widths are per-tensor,
    offsets straddle word boundaries)."""
    from dgc_tpu.compression.wirecodec import IndexCodec

    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=W)
    layout, engine = dist.make_flat(params)
    codec = IndexCodec(engine.buckets)
    assert codec.payload == engine.payload_size
    # variable widths: the big conv rows need more bits than tiny rows
    assert codec.widths.min() >= 1
    assert codec.bits_per_index < 32

    rng = np.random.RandomState(0)
    for trial in range(5):
        local = (rng.rand(codec.payload)
                 * codec.slot_numel).astype(np.int64)
        gidx = codec.slot_off + local
        words = jax.jit(codec.encode)(jnp.asarray(gidx, jnp.int32))
        assert words.dtype == jnp.uint32
        assert words.shape == (codec.nwords,)
        back = np.asarray(jax.jit(codec.decode)(words))
        np.testing.assert_array_equal(back, gidx)
    # batched decode (the gathered [W, nwords] wire)
    local = (rng.rand(W, codec.payload) * codec.slot_numel).astype(np.int64)
    gidx = codec.slot_off[None] + local
    words = jnp.stack([codec.encode(jnp.asarray(gidx[w], jnp.int32))
                       for w in range(W)])
    back = np.asarray(jax.jit(codec.decode)(words))
    np.testing.assert_array_equal(back, gidx)


def test_index_codec_boundary_values():
    """Word-straddling widths: rows whose numel is one under/over a power
    of two, locals at 0 and numel-1 (all-ones bit patterns)."""
    from dgc_tpu.compression.wirecodec import IndexCodec

    class B:
        pass

    b = B()
    b.rows = 3
    b.row_offsets = np.array([0, 4096, 8192], np.int64)
    b.numels = np.array([4095, 4097, 7], np.int64)
    b.num_selects = np.array([5, 5, 3], np.int32)
    b.max_sel = 5
    # tight payload layout (what _bucket_from_rows builds for these
    # uneven quotas): rows 0-1 full, row 2 takes 3 of 5 grid slots
    b.tight = np.array([0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12],
                       np.int64)
    codec = IndexCodec([b])
    assert list(codec.widths[:5]) == [12] * 5          # 4095 -> 12 bits
    assert list(codec.widths[5:10]) == [13] * 5        # 4097 -> 13 bits
    assert list(codec.widths[10:]) == [3] * 3          # 7 -> 3 bits
    idx = np.array([0, 4094, 1, 4093, 2,
                    4096, 4096 + 4096, 4096 + 1, 4096 + 4095, 4096,
                    8192, 8192 + 6, 8192 + 3], np.int64)
    words = codec.encode(jnp.asarray(idx, jnp.int32))
    back = np.asarray(codec.decode(words))
    np.testing.assert_array_equal(back, idx)


# ------------------------------------------------------------------ #
# delta-coded (Elias-Fano) index wire + int4 nibble packing          #
# (compression/wirecodec.py, the int8_delta_idx/int4_packed regimes) #
# ------------------------------------------------------------------ #


def _fake_bucket(base, cols, numels, num_selects):
    """A bucket-shaped object with the flat engine's grid invariants:
    row r spans [base + r*cols, base + r*cols + numel_r), numel_r <=
    cols, tight payload layout over per-row quotas."""

    class B:
        pass

    b = B()
    b.base = int(base)
    b.cols = int(cols)
    b.rows = len(numels)
    b.row_offsets = base + np.arange(b.rows, dtype=np.int64) * cols
    b.numels = np.asarray(numels, np.int64)
    ns = np.asarray(num_selects, np.int32)
    b.num_selects = ns
    b.max_sel = int(ns.max())
    b.payload = int(ns.sum())
    tight = [r * b.max_sel + k for r, n in enumerate(ns) for k in range(n)]
    b.tight = np.asarray(tight, np.int64)
    return b


def _ef_encode_oracle(codec, gidx):
    """Bit-by-bit NumPy Elias-Fano encoder: per bucket, slot j's low
    ``s`` bits at bit offset ``j*s`` of the low region, high bit at
    position ``high_j + j`` of the high region."""
    words = np.zeros(codec.nwords, np.uint32)

    def set_bit(t):
        words[t >> 5] |= np.uint32(1) << np.uint32(t & 31)

    canon = np.asarray(codec.canonical(jnp.asarray(gidx, jnp.int32)))
    p0 = 0
    for m in codec.meta:
        p, s = m["p"], m["s"]
        for j in range(p):
            g = int(canon[p0 + j]) - m["base"]
            for k in range(s):
                if (g >> k) & 1:
                    set_bit(m["low_w0"] * 32 + j * s + k)
            set_bit(m["high_w0"] * 32 + (g >> s) + j)
        p0 += p
    return words


def _sorted_bucket_indices(rng, bucket):
    """Random in-row indices, sorted within the bucket — the engine's
    pre-encode contract (``_sort_delta_payload``). Rows occupy disjoint
    ascending flat ranges, so the global sort lands each row's indices
    exactly on that row's payload slots."""
    rows = np.asarray(bucket.tight) // bucket.max_sel
    out = [int(bucket.row_offsets[r]) + rng.randint(0, int(bucket.numels[r]))
           for r in rows]
    return np.sort(np.asarray(out, np.int64))


def test_delta_index_codec_roundtrip_edges():
    """Elias-Fano round-trip at the edge geometries: a 1-row bucket, a
    payload == full-grid bucket (s == 0, high-bits-only), a deep-s
    sparse bucket, and a multi-bucket stream with offset bases and
    ragged numels. Indices are bitwise-exact against the input and the
    packed words bitwise-exact against a NumPy bit-by-bit oracle."""
    from dgc_tpu.compression.wirecodec import DeltaIndexCodec

    geometries = [
        # one row, modest sparsity
        [_fake_bucket(0, 64, [50], [5])],
        # max_sel == cols: every grid slot selected, p == U forces s=0
        [_fake_bucket(0, 4, [4, 4], [4, 4])],
        # deep s: 300k-slot grid, 21 selected -> 13 low bits per index
        [_fake_bucket(0, 100_000, [99_997, 100_000, 12_345], [7, 5, 9])],
        # two buckets, second base far from zero, ragged numels
        [_fake_bucket(0, 128, [100, 128, 3], [6, 6, 2]),
         _fake_bucket(4096, 512, [500], [17])],
    ]
    rng = np.random.RandomState(7)
    for buckets in geometries:
        codec = DeltaIndexCodec(buckets)
        assert codec.payload == sum(b.payload for b in buckets)
        assert codec.nwords == sum(codec.bucket_words)
        for _ in range(3):
            gidx = np.concatenate([_sorted_bucket_indices(rng, b)
                                   for b in buckets])
            words = np.asarray(
                jax.jit(codec.encode)(jnp.asarray(gidx, jnp.int32)))
            assert words.dtype == np.uint32
            assert words.shape == (codec.nwords,)
            np.testing.assert_array_equal(
                words, _ef_encode_oracle(codec, gidx),
                err_msg="wire words differ from the NumPy oracle")
            back = np.asarray(jax.jit(codec.decode)(
                jnp.asarray(words, jnp.uint32)))
            np.testing.assert_array_equal(back, gidx)
        # batched decode (the gathered [W, nwords] wire)
        gidx_w = np.stack([np.concatenate(
            [_sorted_bucket_indices(rng, b) for b in buckets])
            for _ in range(W)])
        words_w = jnp.stack([codec.encode(jnp.asarray(gidx_w[w], jnp.int32))
                             for w in range(W)])
        back_w = np.asarray(jax.jit(codec.decode)(words_w))
        np.testing.assert_array_equal(back_w, gidx_w)


def test_delta_index_codec_all_pad_bucket():
    """All-structural-pad bucket: every payload slot carries the global
    scatter sentinel (no threshold passers). The wire must decode to
    the CANONICAL stream — each sentinel clipped to its row's last
    element — which is the decode(encode(x)) fixed point the engine's
    0.0-valued pad slots ride safely."""
    from dgc_tpu.compression.wirecodec import DeltaIndexCodec

    b = _fake_bucket(256, 32, [20, 7, 32], [4, 4, 4])
    codec = DeltaIndexCodec([b])
    sentinel = 10 ** 6  # far outside every row
    gidx = np.full(b.payload, sentinel, np.int64)
    canon = np.asarray(codec.canonical(jnp.asarray(gidx, jnp.int32)))
    # clipped-to-row-end positions are nondecreasing across the tight
    # layout, so the sorted-input contract already holds
    assert np.all(np.diff(canon) >= 0)
    words = codec.encode(jnp.asarray(gidx, jnp.int32))
    back = np.asarray(codec.decode(words))
    np.testing.assert_array_equal(back, canon)
    np.testing.assert_array_equal(
        np.asarray(words), _ef_encode_oracle(codec, gidx))


def test_delta_index_codec_rejects_oversized_universe():
    from dgc_tpu.compression.wirecodec import DeltaIndexCodec

    b = _fake_bucket(0, 2 ** 30, [2 ** 30, 2 ** 30], [1, 1])
    with pytest.raises(ValueError, match="2\\^31"):
        DeltaIndexCodec([b])


def test_int4_pack_unpack_oracle():
    """Two-nibbles-per-byte packing round-trips every value in [-8, 7]
    at odd and even lengths, matches a NumPy byte oracle, and unpacks
    batched (the gathered [W, nbytes] wire)."""
    from dgc_tpu.compression.wirecodec import pack_int4, unpack_int4

    rng = np.random.RandomState(3)
    for n in (1, 2, 7, 8, 33):
        q = rng.randint(-8, 8, size=n).astype(np.int32)
        packed = np.asarray(jax.jit(pack_int4)(jnp.asarray(q)))
        assert packed.dtype == np.int8
        assert packed.shape == ((n + 1) // 2,)
        # byte oracle: even slot = low nibble, odd = high, zero pad
        qp = np.concatenate([q, np.zeros(n % 2, np.int32)])
        oracle = ((qp[0::2] & 15) | ((qp[1::2] & 15) << 4)).astype(
            np.uint8).view(np.int8)
        np.testing.assert_array_equal(packed, oracle)
        back = np.asarray(unpack_int4(jnp.asarray(packed), n))
        np.testing.assert_array_equal(back, q)
    # full nibble range survives sign-extension
    q = np.arange(-8, 8, dtype=np.int32)
    np.testing.assert_array_equal(
        np.asarray(unpack_int4(pack_int4(jnp.asarray(q)), 16)), q)
    # batched leading axis
    qw = rng.randint(-8, 8, size=(W, 9)).astype(np.int32)
    pw = jnp.stack([pack_int4(jnp.asarray(qw[w])) for w in range(W)])
    np.testing.assert_array_equal(np.asarray(unpack_int4(pw, 9)), qw)


def test_flat_delta_idx_bitwise_matches_int8(mesh8):
    """int8_delta_idx is int8 plus a different index wire: the decoded
    exchange and memory state must equal the int8 plan's BITWISE —
    the per-bucket payload sort permutes (value, index) pairs together
    and scatter-add is order-invariant over disjoint canonical slots."""
    from dgc_tpu.compression.flat import FlatDGCEngine, ParamLayout
    from dgc_tpu.compression.planner import BUILTIN_FABRICS, Plan

    params = _params()
    named, _ = named_flatten(params)
    compressed = [n for n, p in named.items() if p.ndim > 1]
    layout = ParamLayout(params, compressed)
    fab = BUILTIN_FABRICS["32x25GbE"]

    def build(regime):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        nb = len(FlatDGCEngine(comp, layout).buckets)
        engine = FlatDGCEngine(comp, layout,
                               plan=Plan((regime,) * nb, fab, W))
        return engine, _flat_exchange_fn(dist, engine, mesh8)

    eng_d, fn_d = build("int8_delta_idx")
    eng_8, fn_8 = build("int8")
    # the delta wire must actually be smaller than the int32-index int8
    # wire (that is the whole point of the regime)
    assert eng_d.wire_bytes_per_worker() < eng_8.wire_bytes_per_worker()
    # and lane-exact per bucket: the per-bucket split sums to the total
    assert sum(eng_d.bucket_wire_bytes()) == eng_d.wire_bytes_per_worker()

    rng = np.random.RandomState(5)
    g = rng.randn(W, layout.total).astype(np.float32)
    covered = np.zeros((layout.total,), bool)
    for n in layout.names:
        covered[layout.offsets[n]:layout.offsets[n] + layout.sizes[n]] = True
    g[:, ~covered] = 0.0
    fg = jnp.asarray(g)

    def init_mem(engine):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
            engine.init_memory())

    mem_d, mem_8 = init_mem(eng_d), init_mem(eng_8)
    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_d, mem_d = fn_d(fg, mem_d, key)
        out_8, mem_8 = fn_8(fg, mem_8, key)
        np.testing.assert_array_equal(np.asarray(out_d[0]),
                                      np.asarray(out_8[0]),
                                      err_msg=f"step {step}")
        fd = _mem_full(eng_d, mem_d, w=0)
        f8 = _mem_full(eng_8, mem_8, w=0)
        for mk in ("momentums", "velocities"):
            np.testing.assert_array_equal(fd[mk], f8[mk],
                                          err_msg=f"{mk} step {step}")


def test_flat_int4_plan_tracks_fp32(mesh8):
    """int4_packed: per-bucket scale/7 quantization bounds each
    worker's per-value error by scale/2, so the W-worker sum stays
    within W/14 of the fp32 exchange's dynamic range — and the wire is
    smaller than the int8 regime's."""
    from dgc_tpu.compression.flat import FlatDGCEngine, ParamLayout
    from dgc_tpu.compression.planner import BUILTIN_FABRICS, Plan

    params = _params()
    named, _ = named_flatten(params)
    compressed = [n for n, p in named.items() if p.ndim > 1]
    layout = ParamLayout(params, compressed)
    fab = BUILTIN_FABRICS["32x25GbE"]

    def build(regime):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        nb = len(FlatDGCEngine(comp, layout).buckets)
        engine = FlatDGCEngine(comp, layout,
                               plan=Plan((regime,) * nb, fab, W))
        return engine, _flat_exchange_fn(dist, engine, mesh8)

    eng_4, fn_4 = build("int4_packed")
    eng_f, fn_f = build("fp32")
    eng_8, _ = build("int8")
    assert eng_4.wire_bytes_per_worker() < eng_8.wire_bytes_per_worker()
    assert sum(eng_4.bucket_wire_bytes()) == eng_4.wire_bytes_per_worker()

    rng = np.random.RandomState(9)
    g = rng.randn(W, layout.total).astype(np.float32)
    covered = np.zeros((layout.total,), bool)
    for n in layout.names:
        covered[layout.offsets[n]:layout.offsets[n] + layout.sizes[n]] = True
    g[:, ~covered] = 0.0
    fg = jnp.asarray(g)

    def init_mem(engine):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
            engine.init_memory())

    mem_4, mem_f = init_mem(eng_4), init_mem(eng_f)
    for step in range(2):
        key = jax.random.PRNGKey(step)
        out_4, mem_4 = fn_4(fg, mem_4, key)
        out_f, mem_f = fn_f(fg, mem_f, key)
        o4 = np.asarray(out_4[0])
        of = np.asarray(out_f[0])
        scale = np.abs(of).max()
        d = np.abs(o4 - of)
        # guaranteed per-value bound: W workers x scale/14 each
        assert d.max() <= W / 14 * scale + 1e-6, (d.max(), scale)
        # and quantization noise, not bias: tiny RMS over the buffer
        assert np.sqrt(np.mean(d ** 2)) <= 0.05 * scale


def test_flat_packed_indices_matches_unpacked(mesh8):
    """packed_indices=True (configs/dgc/packidx.py): the exchange result
    and memory state equal the int32-index wire's exactly — decoded
    indices are bit-exact for real slots, and padded slots contribute
    value 0.0 wherever they land."""
    params = _params()
    named, _ = named_flatten(params)

    def make(packed):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, packed_indices=packed)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        layout, engine = dist.make_flat(params)
        return dist, layout, engine

    dist_u, layout, engine_u = make(False)
    dist_p, _, engine_p = make(True)
    assert engine_u._codec is None and engine_p._codec is not None

    rng = np.random.RandomState(11)
    from dgc_tpu.utils.pytree import named_unflatten
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}
    flat_grads_w = jnp.stack([
        layout.flatten(named_unflatten({n: grads_w[n][w] for n in named},
                                       named_flatten(params)[1]))
        for w in range(W)])

    fn_u = _flat_exchange_fn(dist_u, engine_u, mesh8)
    fn_p = _flat_exchange_fn(dist_p, engine_p, mesh8)
    mem_u = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine_u.init_memory())
    mem_p = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine_p.init_memory())
    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_u, mem_u = fn_u(flat_grads_w, mem_u, key)
        out_p, mem_p = fn_p(flat_grads_w, mem_p, key)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_u),
                                   rtol=1e-6, atol=1e-7,
                                   err_msg=f"step {step}")
        fu = _mem_full(engine_u, mem_u, w=0)
        fp = _mem_full(engine_p, mem_p, w=0)
        for mkey in ("momentums", "velocities"):
            np.testing.assert_allclose(fp[mkey], fu[mkey],
                                       rtol=1e-6, atol=1e-7,
                                       err_msg=f"{mkey} step {step}")


def test_flat_packed_indices_with_int8(mesh8):
    """packed indices compose with the int8 value wire: combined wire
    matches the unpacked int8 exchange."""
    params = _params()
    named, _ = named_flatten(params)

    def make(packed):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, int8_values=True,
                             packed_indices=packed)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        layout, engine = dist.make_flat(params)
        return dist, layout, engine

    dist_u, layout, engine_u = make(False)
    dist_p, _, engine_p = make(True)
    rng = np.random.RandomState(13)
    from dgc_tpu.utils.pytree import named_unflatten
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}
    flat_grads_w = jnp.stack([
        layout.flatten(named_unflatten({n: grads_w[n][w] for n in named},
                                       named_flatten(params)[1]))
        for w in range(W)])
    fn_u = _flat_exchange_fn(dist_u, engine_u, mesh8)
    fn_p = _flat_exchange_fn(dist_p, engine_p, mesh8)
    mem_u = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine_u.init_memory())
    mem_p = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine_p.init_memory())
    for step in range(2):
        key = jax.random.PRNGKey(step)
        out_u, mem_u = fn_u(flat_grads_w, mem_u, key)
        out_p, mem_p = fn_p(flat_grads_w, mem_p, key)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_u),
                                   rtol=1e-6, atol=1e-7,
                                   err_msg=f"step {step}")


def test_exchange_fused_apply_matches_fallback(mesh8):
    """CPU-oracle parity of the fused apply epilogue
    (``DGCCompressor(fused_apply=True)`` ->
    ``kernels.payload_apply_bits`` in interpret mode) against the XLA
    scatter fallback: per-worker selection keys differ, so cross-worker
    duplicate coordinates exist and the scatter-add order genuinely
    matters — the staging sort is stable, so duplicate contributions
    keep payload order and the comparison is EXACT, transmit record
    included."""
    params = _params()
    named, _ = named_flatten(params)

    def make(fused):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, fused_apply=fused)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        layout, engine = dist.make_flat(params)
        return dist, layout, engine

    dist_u, layout, engine_u = make(False)
    dist_f, _, engine_f = make(True)
    # the routing gate itself: the fused engine must actually take the
    # fused path (flag + memory + f32 wire + aligned T)
    assert not engine_u._use_fused_apply(engine_u._mem, False, jnp.float32)
    assert engine_f._use_fused_apply(engine_f._mem, False, jnp.float32)

    rng = np.random.RandomState(17)
    from dgc_tpu.utils.pytree import named_unflatten
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}
    flat_grads_w = jnp.stack([
        layout.flatten(named_unflatten({n: grads_w[n][w] for n in named},
                                       named_flatten(params)[1]))
        for w in range(W)])
    fn_u = _flat_exchange_fn(dist_u, engine_u, mesh8)
    fn_f = _flat_exchange_fn(dist_f, engine_f, mesh8)
    mem_u = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine_u.init_memory())
    mem_f = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         engine_f.init_memory())
    for step in range(3):
        key = jax.random.PRNGKey(step)
        out_u, mem_u = fn_u(flat_grads_w, mem_u, key)
        out_f, mem_f = fn_f(flat_grads_w, mem_f, key)
        np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_u),
                                      err_msg=f"step {step}")
        np.testing.assert_array_equal(np.asarray(mem_f["sent_bits"]),
                                      np.asarray(mem_u["sent_bits"]),
                                      err_msg=f"bits step {step}")
        fu = _mem_full(engine_u, mem_u, w=0)
        ff = _mem_full(engine_f, mem_f, w=0)
        for mkey in ("momentums", "velocities"):
            np.testing.assert_array_equal(ff[mkey], fu[mkey],
                                          err_msg=f"{mkey} step {step}")


def test_sparsify_with_fused_candidates_matches_standalone(monkeypatch):
    """The fused compensate+candidates path: ``sparsify(x, key,
    seg_cands=...)`` with candidates from
    ``kernels.fused_compensate_bits_cands`` must be BITWISE the
    standalone seg-kernel path ``sparsify(x, key)`` — the engine swaps
    where candidates come from, never what they are. Candidates for an
    arbitrary x are obtained by feeding the fused kernel zero state and
    zero bits (then ov == x exactly: m = momentum*0 + x, v = 0 + m)."""
    from dgc_tpu.compression.flat import FlatDGCEngine
    from dgc_tpu.ops import kernels

    monkeypatch.setattr(FlatDGCEngine, "SEL3D_MIN_COLS", 1024 * 1024)
    numel = 1_200_000
    comp = DGCCompressor(0.001, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=0.01)
    comp.initialize([("w", (numel, (numel,)))])
    params = {"w": jax.ShapeDtypeStruct((numel,), jnp.float32)}
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    [b] = engine.buckets
    assert engine._use_seg_kernel(b) and engine._seg_fused

    T = layout.t_compressed
    rng = np.random.RandomState(31)
    x = np.zeros((T,), np.float32)
    x[:numel] = rng.randn(numel).astype(np.float32)
    xj = jnp.asarray(x)
    z = jnp.zeros((T,), jnp.float32)
    bits = jnp.zeros((kernels.num_sent_words(T),), jnp.int32)
    _, ov, cv, ci = kernels.fused_compensate_bits_cands(
        xj, z, z, bits, 0.9, False, True)
    np.testing.assert_array_equal(np.asarray(ov), x)
    key = jax.random.PRNGKey(5)
    v0, i0 = jax.jit(engine.sparsify)(xj, key)
    v1, i1 = jax.jit(lambda a, k, c: engine.sparsify(a, k, seg_cands=c))(
        xj, key, (cv, ci))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


def test_sparsify_with_fused_candidates_multi_bucket(monkeypatch):
    """Same bitwise contract as
    test_sparsify_with_fused_candidates_matches_standalone, but across
    MULTIPLE seg-kernel buckets with R>1: two same-size tensors share a
    bucket (R=2) that sits at a nonzero ``b.base``, behind a single-row
    giant bucket at base 0. This exercises the candidate-stream slice
    ``cv_all[sb:sb + R*nsr]`` with a nonzero segment offset ``sb`` and
    the ``sb + r*nsr + s`` segment ordering end to end — a stream
    off-by-one would scramble bucket 1's candidates, not bucket 0's."""
    from dgc_tpu.compression.flat import FlatDGCEngine
    from dgc_tpu.ops import kernels

    monkeypatch.setattr(FlatDGCEngine, "SEL3D_MIN_COLS", 1024 * 1024)
    numels = {"a": 1_200_000, "b": 1_200_000, "c": 2_400_000}
    comp = DGCCompressor(0.001, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=0.01)
    comp.initialize([(n, (sz, (sz,))) for n, sz in numels.items()])
    params = {n: jax.ShapeDtypeStruct((sz,), jnp.float32)
              for n, sz in numels.items()}
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    # the geometry this test exists for: 2 buckets, all on the kernel
    # path, one multi-row, one at a nonzero segment-aligned base
    assert len(engine.buckets) == 2
    assert all(engine._use_seg_kernel(b) for b in engine.buckets)
    assert engine._seg_fused
    assert any(b.rows > 1 for b in engine.buckets)
    assert any(b.base > 0 for b in engine.buckets)
    span = kernels._SEG_BLOCKS * 128
    assert all(b.base % span == 0 for b in engine.buckets)

    rng = np.random.RandomState(37)
    arrs = {n: jnp.asarray(rng.randn(sz).astype(np.float32))
            for n, sz in numels.items()}
    T = layout.t_compressed
    xj = layout.flatten(arrs)[:T]
    z = jnp.zeros((T,), jnp.float32)
    bits = jnp.zeros((kernels.num_sent_words(T),), jnp.int32)
    _, ov, cv, ci = kernels.fused_compensate_bits_cands(
        xj, z, z, bits, 0.9, False, True)
    np.testing.assert_array_equal(np.asarray(ov), np.asarray(xj))
    key = jax.random.PRNGKey(9)
    v0, i0 = jax.jit(engine.sparsify)(xj, key)
    v1, i1 = jax.jit(lambda a, k, c: engine.sparsify(a, k, seg_cands=c))(
        xj, key, (cv, ci))
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    # both buckets actually transmitted: payload indices land in each
    # bucket's extent (a silent one-bucket selection would still pass
    # the bitwise checks above)
    i0 = np.asarray(i0)
    real = i0[i0 != layout.sentinel]
    for b in engine.buckets:
        hits = ((real >= b.base) & (real < b.base + b.rows * b.cols)).sum()
        assert hits > 0, (b.base, b.rows, b.cols)


@pytest.mark.parametrize("state_dtype", [None, "bfloat16"])
def test_3d_seg_top2_kernel_selection_path(monkeypatch, state_dtype):
    """The segment-top-2 candidates kernel path (cells >= 3*num_selects):
    same payload invariants and near-exact CPU recall as the approx 3-D
    path, with values taken from the kernel's candidate stream instead
    of a payload gather. Parameterized over the narrow (bf16)
    error-feedback state: the kernel up-casts in VMEM and the engine
    casts back, so the vals == vec[idx] round-trip must stay exact."""
    from dgc_tpu.compression.flat import FlatDGCEngine
    from dgc_tpu.ops import kernels

    monkeypatch.setattr(FlatDGCEngine, "SEL3D_MIN_COLS", 1024 * 1024)
    numel = 1_200_000
    comp = DGCCompressor(0.001, memory=DGCSGDMemory(momentum=0.9,
                                                    dtype=state_dtype),
                         sample_ratio=0.01)
    comp.initialize([("w", (numel, (numel,)))])
    params = {"w": jax.ShapeDtypeStruct((numel,), jnp.float32)}
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    [b] = engine.buckets
    assert engine._use_3d(b)
    cells = (b.cols // 128 // kernels._SEG_BLOCKS) * 128
    assert cells >= 3 * b.max_sel
    assert kernels.seg_top2_eligible(layout.t_compressed // 128, b.base,
                                     b.cols)
    # the ROUTING gate itself — sparsify must actually take the kernel
    # path, not silently fall back to the approx 3-D form
    assert engine._use_seg_kernel(b)

    a = comp.attributes["w"]
    rng = np.random.RandomState(23)
    vdt = jnp.bfloat16 if state_dtype else jnp.float32
    vec = np.zeros((layout.t_compressed,), np.float32)
    vec[:numel] = rng.randn(numel).astype(np.float32)
    vec = np.asarray(jnp.asarray(vec, vdt).astype(jnp.float32))
    vals, idx = jax.jit(engine.sparsify)(jnp.asarray(vec, vdt),
                                         jax.random.PRNGKey(0))
    assert vals.dtype == vdt
    vals = np.asarray(vals.astype(jnp.float32))
    idx = np.asarray(idx)
    real = idx != layout.sentinel
    count = int(real.sum())
    assert 0.8 * a.num_selects * 0.9 <= count <= a.num_selects
    assert (idx[real] < numel).all() and (idx[real] >= 0).all()
    np.testing.assert_array_equal(vals[real], vec[idx[real]])
    assert len(np.unique(idx[real])) == count
    exact = np.argsort(-np.abs(vec[:numel]))[:count]
    recall = len(set(exact.tolist()) & set(idx[real].tolist())) / count
    assert recall >= 0.93 if state_dtype else recall >= 0.95, recall


@pytest.mark.parametrize("sparse_regime", ["fp32", "int8_packed"])
def test_flat_mixed_plan_matches_uniform_mixture(mesh8, sparse_regime):
    """A mixed exchange plan (sparse bucket 0 + dense-planned bucket 1)
    must produce, slab for slab, EXACTLY what the uniform engines
    produce: bucket 0's output and memory match the uniform sparse
    engine, bucket 1's and the dense tail's match the all-dense plan —
    the planner changes the wire, never the math."""
    from dgc_tpu.compression.flat import FlatDGCEngine, ParamLayout
    from dgc_tpu.compression.planner import BUILTIN_FABRICS, Plan

    rng = np.random.RandomState(0)
    params = {
        "big": {"kernel": jnp.asarray(rng.randn(600, 600), jnp.float32)},
        "small": {"kernel": jnp.asarray(rng.randn(40, 50), jnp.float32)},
        "bias": {"b": jnp.asarray(rng.randn(16), jnp.float32)},
    }
    named, _ = named_flatten(params)
    compressed = [n for n, p in named.items() if p.ndim > 1]
    layout = ParamLayout(params, compressed)
    fab = BUILTIN_FABRICS["32x25GbE"]

    def build(regimes):
        comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        engine = FlatDGCEngine(comp, layout, plan=Plan(regimes, fab, W))
        return engine, _flat_exchange_fn(dist, engine, mesh8)

    eng_mix, fn_mix = build((sparse_regime, "dense"))
    eng_sp, fn_sp = build((sparse_regime, sparse_regime))
    eng_dn, fn_dn = build(("dense", "dense"))
    assert len(eng_mix.buckets) == 2
    assert eng_mix.regimes == (sparse_regime, "dense")
    assert eng_dn.plan.all_dense

    g = rng.randn(W, layout.total).astype(np.float32)
    # zero the structural-pad slots so flat buffers are well-formed
    covered = np.zeros((layout.total,), bool)
    for n in layout.names:
        covered[layout.offsets[n]:layout.offsets[n] + layout.sizes[n]] = True
    g[:, ~covered] = 0.0
    fg = jnp.asarray(g)

    def init_mem(engine):
        return jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
            engine.init_memory())

    mems = [init_mem(e) for e in (eng_mix, eng_sp, eng_dn)]
    b0, b1 = eng_mix.buckets
    s0 = slice(b0.base, b0.base + b0.rows * b0.cols)
    s1 = slice(b1.base, b1.base + b1.rows * b1.cols)
    tail = slice(layout.t_compressed, layout.total)

    for step in range(2):
        key = jax.random.PRNGKey(step)
        (o_mix, mems[0]), (o_sp, mems[1]), (o_dn, mems[2]) = (
            fn(fg, m, key) for fn, m in zip((fn_mix, fn_sp, fn_dn), mems))
        o_mix, o_sp, o_dn = (np.asarray(o[0]) for o in (o_mix, o_sp, o_dn))
        # sparse-planned slab == uniform sparse engine, bitwise (the
        # allgather wire carries identical payloads in both builds)
        np.testing.assert_array_equal(o_mix[s0], o_sp[s0],
                                      err_msg=f"step {step} bucket0")
        # dense-planned slab + tail == all-dense plan to 1 ULP: the psum
        # covers a differently-offset buffer (concat wire vs whole [P]),
        # so the ring reduction may associate additions differently
        np.testing.assert_allclose(o_mix[s1], o_dn[s1], rtol=2e-7,
                                   atol=1e-7,
                                   err_msg=f"step {step} bucket1")
        np.testing.assert_allclose(o_mix[tail], o_dn[tail], rtol=2e-7,
                                   atol=1e-7, err_msg=f"step {step} tail")
        full_mix = _mem_full(eng_mix, mems[0], w=0)
        full_sp = _mem_full(eng_sp, mems[1], w=0)
        full_dn = _mem_full(eng_dn, mems[2], w=0)
        for mk in ("momentums", "velocities"):
            np.testing.assert_array_equal(
                full_mix[mk][s0], full_sp[mk][s0],
                err_msg=f"step {step} {mk} bucket0")
            np.testing.assert_allclose(
                full_mix[mk][s1], full_dn[mk][s1], rtol=2e-7, atol=1e-7,
                err_msg=f"step {step} {mk} bucket1")
