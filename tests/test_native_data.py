"""Native input-pipeline kernels (dgc_tpu.data.native): the C kernel and the
vectorized-numpy fallback must both match the per-image oracle; the
prefetcher must preserve order and surface worker errors."""

import numpy as np
import pytest

from dgc_tpu.data import native
from dgc_tpu.data.datasets import (
    CIFAR_MEAN,
    CIFAR_STD,
    _normalize,
    _random_crop_flip_reference,
)


def _case(n=16, h=32, w=32, pad=4, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 256, (n, h, w, 3), dtype=np.uint8)
    ys = rng.randint(0, 2 * pad + 1, size=n)
    xs = rng.randint(0, 2 * pad + 1, size=n)
    flips = rng.randint(0, 2, size=n).astype(np.uint8)
    return imgs, ys, xs, flips, pad


def _oracle(imgs, ys, xs, flips, pad):
    out = _random_crop_flip_reference(imgs, ys, xs, flips.astype(bool), pad)
    return _normalize(out, CIFAR_MEAN, CIFAR_STD)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_numpy_fallback_matches_oracle(seed):
    imgs, ys, xs, flips, pad = _case(seed=seed)
    scale = (1.0 / (255.0 * CIFAR_STD)).astype(np.float32)
    bias = (-CIFAR_MEAN / CIFAR_STD).astype(np.float32)
    got = native._numpy_path(imgs, ys, xs, flips, pad, scale, bias)
    np.testing.assert_allclose(got, _oracle(imgs, ys, xs, flips, pad),
                               rtol=1e-5, atol=1e-5)


def test_native_kernel_matches_oracle():
    if not native.native_available():
        pytest.skip("no C toolchain on this machine")
    imgs, ys, xs, flips, pad = _case(n=32)
    got = native.crop_flip_normalize(imgs, ys, xs, flips, pad,
                                     CIFAR_MEAN, CIFAR_STD)
    np.testing.assert_allclose(got, _oracle(imgs, ys, xs, flips, pad),
                               rtol=1e-5, atol=1e-5)


def test_native_kernel_extreme_offsets():
    """Corners: offset 0 (top-left of padding) and 2*pad (bottom-right),
    flip on/off — implicit zero padding must match the padded oracle."""
    if not native.native_available():
        pytest.skip("no C toolchain on this machine")
    imgs = np.full((4, 8, 8, 3), 200, np.uint8)
    ys = np.array([0, 0, 8, 8])
    xs = np.array([0, 8, 0, 8])
    flips = np.array([0, 1, 0, 1], np.uint8)
    got = native.crop_flip_normalize(imgs, ys, xs, flips, 4,
                                     CIFAR_MEAN, CIFAR_STD)
    np.testing.assert_allclose(got, _oracle(imgs, ys, xs, flips, 4),
                               rtol=1e-5, atol=1e-5)


def test_array_split_uses_fused_path():
    from dgc_tpu.data.datasets import ArraySplit
    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 256, (64, 32, 32, 3), dtype=np.uint8)
    labels = rng.randint(0, 10, 64)
    split = ArraySplit(imgs, labels, CIFAR_MEAN, CIFAR_STD, train=True,
                       seed=5)
    x, y = split.get_batch(np.arange(32))
    assert x.shape == (32, 32, 32, 3) and x.dtype == np.float32
    assert np.isfinite(x).all()
    # eval split: pure normalization, deterministic
    ev = ArraySplit(imgs, labels, CIFAR_MEAN, CIFAR_STD, train=False)
    x1, _ = ev.get_batch(np.arange(8))
    x2, _ = ev.get_batch(np.arange(8))
    np.testing.assert_array_equal(x1, x2)


def test_prefetcher_order_and_errors():
    class Split:
        def get_batch(self, idx):
            if int(idx) == 3:
                raise RuntimeError("boom")
            return np.full((2,), int(idx)), np.full((2,), int(idx))

    pf = native.Prefetcher(Split(), iter(np.arange(3)))
    got = [int(x[0][0]) for x in pf]
    assert got == [0, 1, 2]

    pf = native.Prefetcher(Split(), iter(np.arange(5)))
    with pytest.raises(RuntimeError, match="boom"):
        list(pf)


def test_prefetcher_close_releases_worker():
    """Abandoning iteration early + close(): the fill thread must exit even
    though the bounded queue is full."""
    class Split:
        def get_batch(self, idx):
            return np.zeros((2,)), np.zeros((2,))

    pf = native.Prefetcher(Split(), iter(np.arange(100)), depth=2)
    it = iter(pf)
    next(it)          # consume one, abandon the rest
    pf.close()
    assert not pf._thread.is_alive()
