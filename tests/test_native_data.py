"""Native input-pipeline kernels (dgc_tpu.data.native): the C kernel and the
vectorized-numpy fallback must both match the per-image oracle; the
prefetcher must preserve order and surface worker errors."""

import os
import numpy as np
import pytest

from dgc_tpu.data import native
from dgc_tpu.data.datasets import (
    CIFAR_MEAN,
    CIFAR_STD,
    _normalize,
    _random_crop_flip_reference,
)


def _case(n=16, h=32, w=32, pad=4, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 256, (n, h, w, 3), dtype=np.uint8)
    ys = rng.randint(0, 2 * pad + 1, size=n)
    xs = rng.randint(0, 2 * pad + 1, size=n)
    flips = rng.randint(0, 2, size=n).astype(np.uint8)
    return imgs, ys, xs, flips, pad


def _oracle(imgs, ys, xs, flips, pad):
    out = _random_crop_flip_reference(imgs, ys, xs, flips.astype(bool), pad)
    return _normalize(out, CIFAR_MEAN, CIFAR_STD)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_numpy_fallback_matches_oracle(seed):
    imgs, ys, xs, flips, pad = _case(seed=seed)
    scale = (1.0 / (255.0 * CIFAR_STD)).astype(np.float32)
    bias = (-CIFAR_MEAN / CIFAR_STD).astype(np.float32)
    got = native._numpy_path(imgs, ys, xs, flips, pad, scale, bias)
    np.testing.assert_allclose(got, _oracle(imgs, ys, xs, flips, pad),
                               rtol=1e-5, atol=1e-5)


def test_native_kernel_matches_oracle():
    if not native.native_available():
        pytest.skip("no C toolchain on this machine")
    imgs, ys, xs, flips, pad = _case(n=32)
    got = native.crop_flip_normalize(imgs, ys, xs, flips, pad,
                                     CIFAR_MEAN, CIFAR_STD)
    np.testing.assert_allclose(got, _oracle(imgs, ys, xs, flips, pad),
                               rtol=1e-5, atol=1e-5)


def test_native_kernel_extreme_offsets():
    """Corners: offset 0 (top-left of padding) and 2*pad (bottom-right),
    flip on/off — implicit zero padding must match the padded oracle."""
    if not native.native_available():
        pytest.skip("no C toolchain on this machine")
    imgs = np.full((4, 8, 8, 3), 200, np.uint8)
    ys = np.array([0, 0, 8, 8])
    xs = np.array([0, 8, 0, 8])
    flips = np.array([0, 1, 0, 1], np.uint8)
    got = native.crop_flip_normalize(imgs, ys, xs, flips, 4,
                                     CIFAR_MEAN, CIFAR_STD)
    np.testing.assert_allclose(got, _oracle(imgs, ys, xs, flips, 4),
                               rtol=1e-5, atol=1e-5)


def test_array_split_uses_fused_path():
    from dgc_tpu.data.datasets import ArraySplit
    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 256, (64, 32, 32, 3), dtype=np.uint8)
    labels = rng.randint(0, 10, 64)
    split = ArraySplit(imgs, labels, CIFAR_MEAN, CIFAR_STD, train=True,
                       seed=5)
    x, y = split.get_batch(np.arange(32))
    assert x.shape == (32, 32, 32, 3) and x.dtype == np.float32
    assert np.isfinite(x).all()
    # eval split: pure normalization, deterministic
    ev = ArraySplit(imgs, labels, CIFAR_MEAN, CIFAR_STD, train=False)
    x1, _ = ev.get_batch(np.arange(8))
    x2, _ = ev.get_batch(np.arange(8))
    np.testing.assert_array_equal(x1, x2)


def test_prefetcher_order_and_errors():
    class Split:
        def get_batch(self, idx):
            if int(idx) == 3:
                raise RuntimeError("boom")
            return np.full((2,), int(idx)), np.full((2,), int(idx))

    pf = native.Prefetcher(Split(), iter(np.arange(3)))
    got = [int(x[0][0]) for x in pf]
    assert got == [0, 1, 2]

    pf = native.Prefetcher(Split(), iter(np.arange(5)))
    with pytest.raises(RuntimeError, match="boom"):
        list(pf)


def test_prefetcher_close_releases_worker():
    """Abandoning iteration early + close(): the fill thread must exit even
    though the bounded queue is full."""
    class Split:
        def get_batch(self, idx):
            return np.zeros((2,)), np.zeros((2,))

    pf = native.Prefetcher(Split(), iter(np.arange(100)), depth=2)
    it = iter(pf)
    next(it)          # consume one, abandon the rest
    pf.close()
    assert not pf._thread.is_alive()


def _make_image_folder(root, classes=2, per_class=3):
    from PIL import Image
    rng = np.random.RandomState(0)
    for c in range(classes):
        d = os.path.join(root, f"n{c:03d}")
        os.makedirs(d)
        for i in range(per_class):
            arr = rng.randint(0, 255, (48, 40, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"i{i}.png"))


def test_image_folder_pool_matches_sequential(tmp_path):
    """Pool decode (the DataLoader num_workers role, reference
    train.py:96-107) must be bitwise identical to sequential decode:
    per-image seeds make augmentation independent of worker count and
    completion order."""
    pytest.importorskip("PIL")
    from dgc_tpu.data.datasets import _ImageFolderSplit

    _make_image_folder(str(tmp_path))
    idx = np.arange(6)
    seq = _ImageFolderSplit(str(tmp_path), 32, train=True, seed=3,
                            workers=1)
    x1, y1 = seq.get_batch(idx)
    pool = _ImageFolderSplit(str(tmp_path), 32, train=True, seed=3,
                             workers=2)
    x2, y2 = pool.get_batch(idx)
    pool.close()
    np.testing.assert_array_equal(y1, y2)
    np.testing.assert_array_equal(x1, x2)
    # eval path too (deterministic center crop)
    ev1 = _ImageFolderSplit(str(tmp_path), 32, train=False, workers=1)
    ev2 = _ImageFolderSplit(str(tmp_path), 32, train=False, workers=2)
    a1, _ = ev1.get_batch(idx)
    a2, _ = ev2.get_batch(idx)
    ev2.close()
    np.testing.assert_array_equal(a1, a2)


def test_image_folder_batch_stream_deterministic(tmp_path):
    """Two splits with the same seed produce the same augmented batches in
    sequence (the master RNG draws one seed block per batch)."""
    pytest.importorskip("PIL")
    from dgc_tpu.data.datasets import _ImageFolderSplit

    _make_image_folder(str(tmp_path))
    a = _ImageFolderSplit(str(tmp_path), 32, train=True, seed=9, workers=1)
    b = _ImageFolderSplit(str(tmp_path), 32, train=True, seed=9, workers=1)
    for _ in range(2):
        xa, _ = a.get_batch(np.arange(4))
        xb, _ = b.get_batch(np.arange(4))
        np.testing.assert_array_equal(xa, xb)
