"""dgclint layer 1: fixture-seeded rule coverage + allowlist machinery.

Every rule has a ``<rule>_pos.py`` / ``<rule>_neg.py`` pair under
tests/fixtures/lint/. Positive fixtures mark each expected violation line
with ``# LINT: <rule-id>``; the test asserts the linter finds exactly the
marked (rule, line) set — both missed violations and false positives on
the clean twins fail here."""

import os
import re
from pathlib import Path

import pytest

from dgc_tpu.analysis.astlint import DEFAULT_ROOTS, lint_paths, lint_source
from dgc_tpu.analysis.rules import (RULES, RULES_BY_ID, Allowlist, Finding,
                                    load_allowlist)

FIXDIR = Path(__file__).parent / "fixtures" / "lint"
REPO_ROOT = Path(__file__).parents[1]
_MARK = re.compile(r"#\s*LINT:\s*([a-z0-9\-]+)")

POS = sorted(FIXDIR.glob("*_pos.py"))
NEG = sorted(FIXDIR.glob("*_neg.py"))


def _expected(src: str):
    return {(m.group(1), i + 1)
            for i, line in enumerate(src.splitlines())
            for m in [_MARK.search(line)] if m}


@pytest.mark.parametrize("path", POS, ids=lambda p: p.stem)
def test_positive_fixture_flags_marked_lines(path):
    src = path.read_text()
    want = _expected(src)
    assert want, f"{path.name} has no LINT markers"
    got = {(f.rule, f.line) for f in lint_source(src, str(path))}
    assert got == want


@pytest.mark.parametrize("path", NEG, ids=lambda p: p.stem)
def test_negative_fixture_is_clean(path):
    findings = lint_source(path.read_text(), str(path))
    assert findings == [], [f.format() for f in findings]


def test_every_rule_has_fixture_pair():
    stems = {p.stem for p in POS} | {p.stem for p in NEG}
    for rule in RULES:
        base = rule.id.replace("-", "_")
        assert f"{base}_pos" in stems, f"no positive fixture for {rule.id}"
        assert f"{base}_neg" in stems, f"no negative fixture for {rule.id}"


# --------------------------------------------------------------------- #
# CLI gate exit codes                                                    #
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("path", POS, ids=lambda p: p.stem)
def test_cli_exits_nonzero_on_seeded_violation(path, capsys):
    from dgc_tpu.analysis.__main__ import main
    rc = main([str(path), "--root", str(REPO_ROOT)])
    capsys.readouterr()
    assert rc == 1


def test_cli_exits_zero_on_clean_fixtures(capsys):
    from dgc_tpu.analysis.__main__ import main
    rc = main([str(p) for p in NEG] + ["--root", str(REPO_ROOT)])
    capsys.readouterr()
    assert rc == 0


def test_cli_gate_clean_on_repo_tree(capsys):
    # the acceptance bar: the shipped tree lints clean (lint layer of
    # --gate; the contract layer has its own test module)
    from dgc_tpu.analysis.__main__ import main
    rc = main(["--root", str(REPO_ROOT)])
    out = capsys.readouterr().out
    assert rc == 0, out


def test_repo_tree_has_no_unallowed_findings():
    findings = lint_paths(DEFAULT_ROOTS, root=str(REPO_ROOT))
    bad = [f.format() for f in findings if not f.allowed]
    assert bad == []
    # the audited exceptions are real: the allowlist is exercised
    assert any(f.allowed for f in findings)


# --------------------------------------------------------------------- #
# allowlist machinery                                                    #
# --------------------------------------------------------------------- #

def test_inline_waiver_suppresses_named_rule():
    src = ("import jax\n"
           "import numpy as np\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return np.asarray(x)  # dgclint: ok[host-sync]\n")
    assert lint_source(src) == []
    # a waiver for a different rule does not suppress
    other = src.replace("ok[host-sync]", "ok[f64-dtype]")
    assert [f.rule for f in lint_source(other)] == ["host-sync"]
    # bare ok waives any rule
    bare = src.replace("ok[host-sync]", "ok")
    assert lint_source(bare) == []


def test_allowlist_matches_rule_glob_and_substring():
    fd = Finding(rule="host-sync", path="dgc_tpu/utils/meters.py", line=3,
                 col=0, snippet="x = np.asarray(outputs)", message="m")
    allow = Allowlist([{"rule": "host-sync", "file": "dgc_tpu/utils/*",
                        "contains": "np.asarray", "reason": "host meter"}])
    assert allow.match(fd) == "host meter"
    assert allow.match(
        Finding(rule="tracer-branch", path=fd.path, line=3, col=0,
                snippet=fd.snippet, message="m")) is None
    assert allow.match(
        Finding(rule="host-sync", path="train.py", line=3, col=0,
                snippet=fd.snippet, message="m")) is None
    assert allow.match(
        Finding(rule="host-sync", path=fd.path, line=3, col=0,
                snippet="y = int(z)", message="m")) is None


def test_load_allowlist_rejects_missing_reason(tmp_path):
    p = tmp_path / "a.toml"
    p.write_text('[[allow]]\nrule = "host-sync"\nfile = "x.py"\n')
    with pytest.raises(ValueError, match="reason"):
        load_allowlist(str(p))


def test_load_allowlist_rejects_unknown_rule(tmp_path):
    p = tmp_path / "a.toml"
    p.write_text('[[allow]]\nrule = "no-such-rule"\nreason = "r"\n')
    with pytest.raises(ValueError, match="unknown rule"):
        load_allowlist(str(p))


def test_repo_allowlist_parses_and_names_known_rules():
    allow = load_allowlist()
    assert allow.entries, "repo allowlist should carry audited exceptions"
    for e in allow.entries:
        assert e["rule"] in RULES_BY_ID
        assert e["reason"].strip()


def test_rule_codes_are_unique():
    codes = [r.code for r in RULES]
    assert len(codes) == len(set(codes))


def test_allowlisted_finding_format_shows_reason():
    fd = Finding(rule="host-sync", path="a.py", line=1, col=0,
                 snippet="s", message="m", allowed=True, allowed_by="why")
    assert "[allowed: why]" in fd.format()
    assert "DGC101" in fd.format()


def test_syntax_error_reported_as_finding(tmp_path):
    assert [f.message for f in lint_source("def broken(:\n")][0].startswith(
        "syntax error")
