"""Fake trainer for the control-plane drill (tests/test_control.py).

Writes a fleet-schema telemetry run — ``<run_dir>/telemetry/host0/
telemetry.jsonl`` flushed per record, checkpoint progress in
``<run_dir>/checkpoints/latest.json`` — at millisecond cost, no jax, so
a ControlPlane can supervise several of these concurrently and the rule
engine sees exactly the signals a real ``train.py`` run emits:

* ``DGC_RUN_ID`` (set by the Supervisor) lands in the header static,
* ``JAX_NUM_PROCESSES`` (spec env / republished cohort file) lands in
  ``static.num_processes`` — the cohort spec the relaunch picked up,
* ``DGC_FAULTS=slow[:ms=M]`` stretches the LAST worker's ``w_clock``
  lane by M ms (the straggler signature the fleet taps would record),
* ``DGC_FAKE_DESYNC=<worker>`` walks that worker's ``w_residual_mass``
  away from the cohort band after a third of the run (offline residual
  corruption),
* ``DGC_FAKE_NONFINITE=<step>`` aborts the nonfinite way at that step:
  guard counters in the record, a ``dgc-flight`` dump, exit 70,
* SIGTERM takes the emergency-save path: bump ``latest.json``, exit 75.

Exit codes mirror train.py's conventions (docs/TELEMETRY.md §"Control
plane"): 0 done, 75 preempted-after-save, 70 nonfinite abort.
"""

import argparse
import json
import os
import random
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dgc_tpu.telemetry import registry  # noqa: E402


def parse_slow_ms(tokens):
    """The ``slow[:ms=M]`` token of DGC_FAULTS (default 100ms)."""
    for tok in (tokens or "").split(","):
        tok = tok.strip()
        if not tok.startswith("slow"):
            continue
        ms = 100.0
        for part in tok.split(":")[1:]:
            if part.startswith("ms="):
                ms = float(part[3:])
        return ms
    return None


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir")
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--step-ms", type=float, default=20.0)
    ap.add_argument("--world", type=int, default=4)
    args = ap.parse_args(argv)

    run_dir = os.path.abspath(args.run_dir)
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    os.makedirs(ckpt_dir, exist_ok=True)
    shard_dir = os.path.join(run_dir, "telemetry", "host0")
    os.makedirs(shard_dir, exist_ok=True)

    num_processes = int(os.environ.get("JAX_NUM_PROCESSES") or 1)
    static = {"world": args.world, "num_params": 1000, "payload_elems": 50,
              "num_processes": num_processes}
    run_id = os.environ.get("DGC_RUN_ID")
    if run_id:
        static["run_id"] = run_id

    slow_ms = parse_slow_ms(os.environ.get("DGC_FAULTS"))
    desync = os.environ.get("DGC_FAKE_DESYNC")
    desync_w = int(desync) if desync else None
    nonfinite = os.environ.get("DGC_FAKE_NONFINITE")
    nonfinite_at = int(nonfinite) if nonfinite else None
    desync_at = max(10, args.steps // 3)

    try:
        with open(os.path.join(ckpt_dir, "latest.json")) as f:
            epoch = int(json.load(f).get("epoch", 0))
    except (OSError, ValueError):
        epoch = 0

    def save(next_epoch):
        tmp = os.path.join(ckpt_dir, ".latest.tmp")
        with open(tmp, "w") as f:
            json.dump({"epoch": next_epoch}, f)
        os.replace(tmp, os.path.join(ckpt_dir, "latest.json"))

    fh = open(os.path.join(shard_dir, "telemetry.jsonl"), "w")

    def emit(rec):
        fh.write(json.dumps(rec) + "\n")
        fh.flush()

    emit(registry.make_header(static, guards=True, fleet=True))

    def on_term(signum, frame):
        # the emergency-save path: visible progress, then exit 75 so the
        # supervisor relaunches without burning its retry budget
        save(epoch + 1)
        fh.flush()
        os._exit(75)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    rng = random.Random(0)
    for i in range(args.steps):
        time.sleep(args.step_ms / 1000.0)
        clock = [10.0 + rng.random() for _ in range(args.world)]
        if slow_ms is not None:
            clock[args.world - 1] += slow_ms
        mass = [100.0 * (1.0 + 0.02 * rng.gauss(0, 1))
                for _ in range(args.world)]
        if desync_w is not None and i >= desync_at:
            mass[desync_w] *= 1.0 + 0.6 * (i - desync_at + 1)
        rec = {
            "step": i, "t_host": round(time.time(), 3),
            "loss": round(2.0 - 0.01 * i, 4),
            "grad_norm": 1.0, "payload_elems": 50.0,
            "w_clock": [round(c, 3) for c in clock],
            "w_grad_norm": [1.0] * args.world,
            "w_residual_mass": [round(m, 4) for m in mass],
            "w_sent_ratio": [0.05] * args.world,
            "straggler": float(max(range(args.world),
                                   key=lambda w: clock[w])),
            "straggler_gap": round(max(clock) - min(clock), 3),
            "worker_skew": 0.1,
        }
        if nonfinite_at is not None and i >= nonfinite_at:
            rec.update(skipped_steps=3.0, nonfinite_rate=1.0,
                       checksum_failures=0.0, loss=None)
            emit(rec)
            from dgc_tpu.telemetry.flight import FlightRecorder
            fl = FlightRecorder(capacity=16, static=static)
            fl.record(step=i, loss=float("nan"))
            fl.dump(os.path.join(run_dir, "flight.json"),
                    reason=f"nonfinite-streak x3 at step {i}")
            fh.flush()
            return 70
        emit(rec)
        if i and i % 5 == 0:
            epoch += 1
            save(epoch)
    save(epoch + 1)
    emit({"event": "run_done", "t_host": round(time.time(), 3),
          "steps": args.steps})
    fh.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
