"""Tests for dgc_tpu.telemetry: registry schema, in-graph taps, async sink,
and the regression gate (ISSUE 2 tentpole acceptance):

* telemetry=True must not perturb training — bitwise state equality vs
  telemetry=False on the same inputs;
* telemetry=False must compile away entirely — the lowered step contains no
  telemetry ops;
* the emitted stats must match the engine's static geometry (payload_elems,
  wire_bytes, selected_frac ~ ratio);
* regress exits 0 on self-compare and nonzero on a degraded run.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.telemetry import registry, taps
from dgc_tpu.telemetry.sink import TelemetrySink, read_run, summarize, to_csv
from dgc_tpu.telemetry import regress


# --------------------------------------------------------------------- #
# registry                                                               #
# --------------------------------------------------------------------- #

def test_registry_names_unique_and_kinds_known():
    names = registry.step_stat_names()
    assert len(names) == len(set(names))
    for s in registry.STEP_METRICS + registry.RUN_METRICS:
        assert s.kind in ("scalar", "per_bucket")
        assert s.better in ("", "lower", "higher")


def test_validate_step_stats_catches_drift():
    good = {n: 0.0 for n in registry.step_stat_names()}
    registry.validate_step_stats(good)  # no raise
    with pytest.raises(ValueError, match="missing"):
        bad = dict(good)
        del bad["grad_norm"]
        registry.validate_step_stats(bad)
    with pytest.raises(ValueError, match="extra"):
        registry.validate_step_stats(dict(good, bogus=1.0))


def test_step_out_specs_matches_stat_dict_structure():
    specs = registry.step_out_specs(lambda: "P()")
    assert set(specs) == set(registry.step_stat_names())


def test_make_header_versioned():
    h = registry.make_header({"engine": "test"})
    assert h["schema"] == registry.SCHEMA
    assert h["version"] == registry.SCHEMA_VERSION
    assert h["static"] == {"engine": "test"}
    assert {m["name"] for m in h["metrics"]} == set(
        registry.step_stat_names())


# --------------------------------------------------------------------- #
# taps                                                                   #
# --------------------------------------------------------------------- #

def test_l2_basic_and_degenerate():
    assert float(taps.l2(None)) == 0.0
    assert float(taps.l2(jnp.zeros((0,)))) == 0.0
    x = jnp.asarray([3.0, 4.0])
    assert float(taps.l2(x)) == pytest.approx(5.0)
    # bf16 input still reduces in f32
    assert taps.l2(x.astype(jnp.bfloat16)).dtype == jnp.float32


def test_bucket_payload_stats_counts_and_threshold():
    S = 999
    vals = jnp.asarray([0.5, -2.0, 0.0, 1.5])
    gidx = jnp.asarray([3, 7, S, 12])
    count, thr = taps.bucket_payload_stats(vals, gidx, S)
    assert float(count) == 3.0
    # min |value| over REAL slots only — the 0.0 sits in a sentinel slot
    assert float(thr) == pytest.approx(0.5)


def test_bucket_payload_stats_all_sentinel_is_zero_threshold():
    S = 4
    count, thr = taps.bucket_payload_stats(
        jnp.zeros((3,)), jnp.full((3,), S), S)
    assert float(count) == 0.0
    assert float(thr) == 0.0  # not inf


def test_empty_bucket_stats_shapes():
    e = taps.empty_bucket_stats(3)
    assert e["selected_frac"].shape == (3,)
    assert e["threshold"].shape == (3,)
    assert e["payload_elems"].shape == ()


def test_assemble_step_stats_schema_and_dtype():
    stats = taps.assemble_step_stats(
        grad_norm=1.0, momentum_norm=2.0, residual_norm=3.0,
        residual_mass=4.0, clip_delta=0.0, payload_elems=10, wire_bytes=80,
        selected_frac=jnp.asarray([0.1]), threshold=jnp.asarray([0.5]))
    assert set(stats) == set(registry.step_stat_names())
    assert all(v.dtype == jnp.float32 for v in stats.values())


def test_pmean_stats_single_collective_round_trip():
    # per-device stats with distinct values; pmean over the axis must
    # average every leaf and preserve shapes through the pack/unpack
    n = 8
    assert len(jax.devices()) >= n

    def per_device(i):
        stats = {
            "a": i.astype(jnp.float32),
            "b": jnp.stack([i, 2 * i]).astype(jnp.float32),
        }
        return taps.pmean_stats(stats, ("d",))

    out = jax.pmap(per_device, axis_name="d")(jnp.arange(n))
    mean = (n - 1) / 2
    np.testing.assert_allclose(np.asarray(out["a"])[0], mean)
    np.testing.assert_allclose(np.asarray(out["b"])[0], [mean, 2 * mean])
    # replicated across devices
    np.testing.assert_allclose(np.asarray(out["a"]),
                               np.full((n,), mean))


# --------------------------------------------------------------------- #
# sink                                                                   #
# --------------------------------------------------------------------- #

def test_sink_write_read_round_trip(tmp_path):
    p = str(tmp_path / "run.jsonl")
    with TelemetrySink(p, static={"engine": "t"}) as sk:
        sk.write(0, {"grad_norm": jnp.asarray(1.5),
                     "selected_frac": jnp.asarray([0.1, 0.2])})
        sk.write(1, {"grad_norm": jnp.asarray(2.5),
                     "selected_frac": jnp.asarray([0.3, 0.4])})
        sk.write_record({"event": "engine_rebuild", "epoch": 3})
        sk.flush()
    header, records = read_run(p)
    assert header["static"] == {"engine": "t"}
    steps = [r for r in records if "step" in r]
    assert [r["step"] for r in steps] == [0, 1]
    assert steps[0]["grad_norm"] == 1.5
    assert steps[1]["selected_frac"] == [pytest.approx(0.3),
                                         pytest.approx(0.4)]
    events = [r for r in records if r.get("event") == "engine_rebuild"]
    assert events and events[0]["epoch"] == 3


def test_sink_directory_path_and_disabled(tmp_path):
    d = str(tmp_path / "telem")
    sk = TelemetrySink(d)
    assert sk.path == os.path.join(d, "telemetry.jsonl")
    sk.close()

    off = TelemetrySink(str(tmp_path / "nope"), enabled=False)
    off.write(0, {"grad_norm": 1.0})
    off.flush()
    off.close()
    assert off.path is None
    assert not (tmp_path / "nope").exists()


def test_sink_rotation_rewrites_header(tmp_path):
    p = str(tmp_path / "rot.jsonl")
    with TelemetrySink(p, rotate_bytes=600) as sk:
        for i in range(40):
            sk.write(i, {"grad_norm": jnp.asarray(float(i))})
        sk.flush()
    rotated = sorted(f for f in os.listdir(tmp_path) if f.endswith(".jsonl"))
    assert len(rotated) > 1, "rotation never triggered"
    total = 0
    for f in rotated:
        header, records = read_run(str(tmp_path / f))  # every file parses
        assert header["version"] == registry.SCHEMA_VERSION
        total += len(records)
    assert total == 40  # no record lost across rotation


def test_read_run_rejects_foreign_and_wrong_version(tmp_path):
    foreign = tmp_path / "foreign.jsonl"
    foreign.write_text('{"hello": 1}\n')
    with pytest.raises(ValueError, match="not a dgc-telemetry"):
        read_run(str(foreign))
    futur = tmp_path / "future.jsonl"
    futur.write_text(json.dumps({"schema": registry.SCHEMA,
                                 "version": registry.SCHEMA_VERSION + 1})
                     + "\n")
    with pytest.raises(ValueError, match="version"):
        read_run(str(futur))


def test_summarize_and_csv(tmp_path):
    recs = [{"step": i, "grad_norm": float(i),
             "selected_frac": [0.1, 0.2]} for i in range(5)]
    s = summarize(recs)
    assert s["grad_norm"]["median"] == 2.0
    assert s["grad_norm"]["n"] == 5
    # per-bucket lists summarize their sum
    assert s["selected_frac"]["mean"] == pytest.approx(0.3)
    assert "step" not in s

    p = str(tmp_path / "c.jsonl")
    with TelemetrySink(p) as sk:
        for r in recs:
            sk.write(r["step"], {"grad_norm": jnp.asarray(r["grad_norm"])})
        sk.flush()
    out = str(tmp_path / "c.csv")
    to_csv(p, out)
    lines = open(out).read().strip().splitlines()
    assert len(lines) == 6  # header + 5 rows
    assert "grad_norm" in lines[0]


# --------------------------------------------------------------------- #
# regress gate                                                           #
# --------------------------------------------------------------------- #

def _write_summary_run(path, **metrics):
    with TelemetrySink(str(path)) as sk:
        sk.write_record(dict({"event": "run_summary"}, **metrics))
        sk.flush()
    return str(path)


def test_regress_self_compare_exits_zero(tmp_path, capsys):
    run = _write_summary_run(tmp_path / "a.jsonl", step_time_ms=10.0,
                             overhead_ms=1.0, wire_bytes=2264)
    assert regress.main([run, run, "--tol", "0.10"]) == 0
    assert "PASS" in capsys.readouterr().out


def test_regress_degraded_run_exits_nonzero(tmp_path, capsys):
    base = _write_summary_run(tmp_path / "b.jsonl", step_time_ms=10.0,
                              overhead_ms=1.0, wire_bytes=2264)
    worse = _write_summary_run(tmp_path / "w.jsonl", step_time_ms=12.0,
                               overhead_ms=1.0, wire_bytes=2264)
    rc = regress.main([base, worse, "--tol", "0.10"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "FAIL" in out


def test_regress_improvement_always_passes(tmp_path):
    base = _write_summary_run(tmp_path / "b.jsonl", step_time_ms=10.0)
    better = _write_summary_run(tmp_path / "g.jsonl", step_time_ms=5.0)
    assert regress.main([base, better, "--tol", "0.10"]) == 0


def test_regress_reads_bench_wrapper_format(tmp_path):
    # the driver's BENCH_r*.json wraps bench.py's JSON under "parsed"
    wrapper = tmp_path / "BENCH.json"
    wrapper.write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 0,
         "parsed": {"metric": "exchange_ms", "value": 3.0,
                    "overhead_ms": 1.0, "wire_bytes": 2264}}))
    run = _write_summary_run(tmp_path / "r.jsonl", exchange_ms=3.1,
                             overhead_ms=1.05, wire_bytes=2264)
    assert regress.main([str(wrapper), str(run), "--tol", "0.10"]) == 0
    bad = _write_summary_run(tmp_path / "bad.jsonl", exchange_ms=4.0,
                             overhead_ms=1.0, wire_bytes=2264)
    assert regress.main([str(wrapper), str(bad), "--tol", "0.10"]) == 1


def test_regress_gates_scheduler_service_metrics(tmp_path):
    # the gang scheduler's service metrics (grant wait + schedulable
    # backlog, both lower-is-better) ride the bench-object "scheduler"
    # block and regress like any other run metric
    base = tmp_path / "sched_base.json"
    base.write_text(json.dumps(
        {"scheduler": {"grant_latency_s": 0.5, "sched_queue_depth": 2}}))
    ok = _write_summary_run(tmp_path / "ok.jsonl", grant_latency_s=0.52,
                            sched_queue_depth=2.0)
    assert regress.main([str(base), ok, "--tol", "0.10"]) == 0
    slow = _write_summary_run(tmp_path / "slow.jsonl",
                              grant_latency_s=0.9, sched_queue_depth=2.0)
    assert regress.main([str(base), slow, "--tol", "0.10"]) == 1
    backlog = _write_summary_run(tmp_path / "backlog.jsonl",
                                 grant_latency_s=0.5,
                                 sched_queue_depth=5.0)
    assert regress.main([str(base), backlog, "--tol", "0.10"]) == 1


def test_regress_usage_error_exit_two(tmp_path):
    empty = tmp_path / "garbage.txt"
    empty.write_text("not json at all\n")
    assert regress.main([str(empty), str(empty)]) == 2


def test_compare_direction_handling():
    rows = regress.compare({"step_time_ms": 10.0}, {"step_time_ms": 10.5},
                           tol=0.10)
    assert rows[0]["regressed"] is False      # +5% within 10%
    rows = regress.compare({"step_time_ms": 10.0}, {"step_time_ms": 11.5},
                           tol=0.10)
    assert rows[0]["regressed"] is True       # +15% over 10%
    # zero baseline compares absolutely, no division blowup
    rows = regress.compare({"overhead_ms": 0.0}, {"overhead_ms": 0.05},
                           tol=0.10)
    assert rows[0]["regressed"] is False
    rows = regress.compare({"overhead_ms": 0.0}, {"overhead_ms": 0.5},
                           tol=0.10)
    assert rows[0]["regressed"] is True


# --------------------------------------------------------------------- #
# end-to-end: taps inside the real flat train step                       #
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def flat_step_pair(mesh8):
    """(state, step_telemetry, step_plain, setup, inputs) on a tiny model
    over the 8 fake devices — built once for the whole module."""
    from flax import linen as nn
    from dgc_tpu import DGCCompressor, DGCSGDMemory, DistributedOptimizer
    from dgc_tpu import dgc_sgd
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)
    from dgc_tpu.utils.pytree import named_flatten

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x.mean(axis=(1, 2)))

    model = M()
    v = dict(model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3))))

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        if mutable:
            return model.apply(variables, x, train=train, mutable=mutable,
                               rngs=rngs)
        return model.apply(variables, x, train=train)

    W = 8
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh8,
                        dist_opt=dist)
    step_t = build_train_step(apply_fn, dist, mesh8, donate=False,
                              flat=setup, telemetry=True)
    step_p = build_train_step(apply_fn, dist, mesh8, donate=False,
                              flat=setup, telemetry=False)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(W * 4, 16, 16, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, W * 4), jnp.int32)
    return state, step_t, step_p, setup, (images, labels)


def test_step_telemetry_stats_match_engine_geometry(flat_step_pair):
    state, step_t, _, setup, (images, labels) = flat_step_pair
    _, m = step_t(state, images, labels, jax.random.PRNGKey(1))
    t = {k: np.asarray(v) for k, v in m["telemetry"].items()}
    assert set(t) == set(registry.step_stat_names())
    eng = setup.engine
    assert t["payload_elems"] == pytest.approx(eng.payload_size)
    assert t["wire_bytes"] == pytest.approx(eng.wire_bytes_per_worker())
    assert t["grad_norm"] > 0
    assert t["momentum_norm"] > 0
    assert t["selected_frac"].shape == (len(eng.buckets),)
    # warm-up-free run at ratio 0.05: selection tracks the ratio closely
    np.testing.assert_allclose(t["selected_frac"], 0.05, atol=0.02)
    assert (t["threshold"] >= 0).all()


def test_step_telemetry_does_not_perturb_training(flat_step_pair):
    state, step_t, step_p, _, (images, labels) = flat_step_pair
    s1, m1 = step_t(state, images, labels, jax.random.PRNGKey(1))
    s2, m2 = step_p(state, images, labels, jax.random.PRNGKey(1))
    assert float(m1["loss"]) == float(m2["loss"])
    for (pa, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(s1),
                               jax.tree_util.tree_leaves_with_path(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))


def test_step_telemetry_off_compiles_away(flat_step_pair):
    # pinned through the standing contract mechanism (dgc_tpu.analysis);
    # the full suite also checks byte-identity against a build that never
    # names telemetry= (tests/test_analysis_contracts.py)
    from dgc_tpu.analysis import Contract
    state, _, step_p, _, (images, labels) = flat_step_pair
    Contract("telemetry-off-compiles-away", step_p,
             args=(state, images, labels, jax.random.PRNGKey(1))).expects(
        forbid_substrings=["telemetry"]).enforce()


def test_step_telemetry_residual_energy_identity(flat_step_pair):
    # deferred masking: ||residual||^2 + sum(transmitted^2) == ||vc||^2,
    # so residual_norm must sit strictly between 0 and grad-scale values
    state, step_t, _, _, (images, labels) = flat_step_pair
    _, m = step_t(state, images, labels, jax.random.PRNGKey(1))
    t = {k: float(np.asarray(v)) for k, v in m["telemetry"].items()
         if np.asarray(v).ndim == 0}
    assert 0 <= t["residual_norm"] <= t["momentum_norm"] + t["grad_norm"]


@pytest.mark.fast
def test_telemetry_smoke_step_sink_regress(flat_step_pair, tmp_path):
    """The scripts/t1.sh telemetry smoke (-m fast): one telemetry step
    through the sink, then regress must pass on self-compare."""
    state, step_t, _, setup, (images, labels) = flat_step_pair
    _, m = step_t(state, images, labels, jax.random.PRNGKey(1))
    p = str(tmp_path / "smoke.jsonl")
    with TelemetrySink(p, static=setup.engine.telemetry_static()) as sk:
        sk.write(0, m["telemetry"])
        sk.write_record({
            "event": "run_summary",
            "wire_bytes": setup.engine.wire_bytes_per_worker(),
            "payload_elems": setup.engine.payload_size})
        sk.flush()
    assert regress.main([p, p, "--tol", "0.10"]) == 0


def test_dense_engine_telemetry_has_empty_buckets(mesh8):
    # the dense baseline path still emits the schema (zeros / empty
    # per-bucket arrays) so sinks and specs never branch
    from dgc_tpu.compression.flat import FlatDenseExchange
    e = taps.empty_bucket_stats(0)
    assert e["selected_frac"].shape == (0,)
    assert hasattr(FlatDenseExchange, "exchange")
