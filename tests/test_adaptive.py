"""Straggler-adaptive exchange (ISSUE 13, dgc_tpu.resilience.adaptive).

Covers the policy function, the engine-level masked exchange (mass
conservation vs a NumPy error-feedback oracle over real multi-step
exchanges), the full fleet train step (verdict feed-forward, the
w_eff_ratio lane, engage/release), checkpoint semantics (the policy
state is never saved; restore re-seeds the template's fresh verdict —
including across an elastic world-size change), the windowed ``slow``
fault schedule, and the control-plane pieces that deliver the mode
(``rules.toml`` loading, the ``adapt`` remediation). The 2-process
injected-straggler drill lives in tests/test_multiprocess.py.
"""

import json
import os
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu import (DGCCompressor, DGCSGDMemory, DistributedOptimizer,
                     dgc_sgd)
from dgc_tpu.ops import kernels
from dgc_tpu.resilience import adaptive
from dgc_tpu.resilience.adaptive import AdaptiveConfig
from dgc_tpu.training import TrainState
from dgc_tpu.training.checkpoint import CheckpointManager
from dgc_tpu.utils.compat import shard_map
from dgc_tpu.utils.pytree import named_flatten

W = 8


# --------------------------------------------------------------------- #
# policy units                                                           #
# --------------------------------------------------------------------- #

def _frac(cfg, clock):
    return np.asarray(adaptive.update_policy(
        cfg, jnp.asarray(clock, jnp.float32)))


@pytest.mark.fast
def test_policy_disengaged_below_gap():
    cfg = AdaptiveConfig()
    # a healthy cohort (gap < engage_gap_ms) sends everything
    np.testing.assert_array_equal(
        _frac(cfg, [10.0] * W), np.ones(W, np.float32))
    np.testing.assert_array_equal(
        _frac(cfg, [10, 10, 10, 10 + cfg.engage_gap_ms * 0.9,
                    10, 10, 10, 10]), np.ones(W, np.float32))


@pytest.mark.fast
def test_policy_ramp_tier():
    cfg = AdaptiveConfig()          # engage 100, min 0.25, ramp 500
    clock = [200.0] * 7 + [350.0]   # lag 150 past the median
    f = _frac(cfg, clock)
    # healthy workers keep full quota, the straggler ramps down
    np.testing.assert_array_equal(f[:7], 1.0)
    assert f[7] == pytest.approx(1.0 - 0.75 * 150.0 / 500.0)
    # monotone: a worse lag degrades further, floored at min_frac
    worse = _frac(cfg, [200.0] * 7 + [500.0])
    assert worse[7] < f[7]
    floored = _frac(cfg, [200.0] * 7 + [5000.0])
    # (5000 > 4x median also trips the partial tier — pin the pure ramp
    # floor with the deadline pushed out of reach)
    far = AdaptiveConfig(deadline_factor=1e9)
    assert _frac(far, [200.0] * 7 + [5000.0])[7] == pytest.approx(
        far.min_frac)
    assert floored[7] <= far.min_frac


@pytest.mark.fast
def test_policy_partial_exchange_tier():
    cfg = AdaptiveConfig()
    # past deadline_factor x median: near-empty payload, not the ramp
    f = _frac(cfg, [10.0] * 7 + [200.0])    # 200 > 4 * 10
    assert f[7] == pytest.approx(cfg.partial_frac)
    np.testing.assert_array_equal(f[:7], 1.0)
    # warmup guard: ~0 stamps everywhere must not trip the deadline
    np.testing.assert_array_equal(
        _frac(cfg, [0.0] * W), np.ones(W, np.float32))


@pytest.mark.fast
def test_policy_release_is_immediate():
    cfg = AdaptiveConfig()
    assert _frac(cfg, [10.0] * 7 + [400.0])[7] < 1.0
    # memoryless: the very next healthy clock restores full send
    np.testing.assert_array_equal(
        _frac(cfg, [10.0] * W), np.ones(W, np.float32))
    st = adaptive.init_state(W)
    np.testing.assert_array_equal(np.asarray(st["w_frac"]), 1.0)


# --------------------------------------------------------------------- #
# engine: masked exchange vs the NumPy error-feedback oracle             #
# --------------------------------------------------------------------- #

def _params():
    rng = np.random.RandomState(0)
    return {
        "conv1": {"kernel": jnp.asarray(rng.randn(3, 3, 4, 8), jnp.float32)},
        "conv2": {"kernel": jnp.asarray(rng.randn(3, 3, 8, 8), jnp.float32)},
        "dense": {"kernel": jnp.asarray(rng.randn(32, 10), jnp.float32),
                  "bias": jnp.asarray(rng.randn(10), jnp.float32)},
    }


def _engine():
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    layout, engine = dist.make_flat(params)
    return comp, layout, engine


def _grads(layout, rng):
    g = np.zeros((W, layout.total), np.float32)
    for n in layout.names:
        o, s = layout.offsets[n], layout.sizes[n]
        g[:, o:o + s] = rng.randn(W, s)
    return g


def _exchange_fn(engine, mesh, with_frac):
    def worker(fg, mem, key, frac):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = engine.exchange(
            fg, mem, key, "data", W, op="sum",
            send_frac=frac[0] if with_frac else None)
        return out[None], jax.tree.map(lambda x: x[None], mem)

    return jax.jit(shard_map(
        worker, mesh=mesh,
        in_specs=(P("data"), P("data"), P(), P("data")),
        out_specs=(P("data"), P("data")), check_vma=False))


def _init_mem(engine):
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
        engine.init_memory())


def test_adaptive_full_frac_is_bitwise_identity(mesh8):
    """send_frac == 1.0 on every worker: the masked exchange is bitwise
    the unmasked exchange — outputs AND memory (incl. the transmit
    record), over multiple steps. The runtime complement of the
    adaptive-off-compiles-away HLO contract."""
    comp, layout, engine = _engine()
    f_on = _exchange_fn(engine, mesh8, with_frac=True)
    f_off = _exchange_fn(engine, mesh8, with_frac=False)
    ones = jnp.ones((W,), jnp.float32)
    mem_a, mem_b = _init_mem(engine), _init_mem(engine)
    rng = np.random.RandomState(7)
    for step in range(3):
        g = jnp.asarray(_grads(layout, rng))
        key = jax.random.PRNGKey(step)
        out_a, mem_a = f_on(g, mem_a, key, ones)
        out_b, mem_b = f_off(g, mem_b, key, ones)
        np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))
        for k in mem_a:
            np.testing.assert_array_equal(np.asarray(mem_a[k]),
                                          np.asarray(mem_b[k]))


def test_adaptive_mass_conservation_oracle(mesh8):
    """Real multi-step exchange with the policy engaged on two workers:
    the wire carries exactly the transmitted slice of the velocity
    buffer, the residual keeps the rest (deferred mask), and per-tensor
    mass is conserved vs an independent NumPy error-feedback oracle —
    |transmitted| + |residual| == |accumulated| to 1e-6 relative."""
    comp, layout, engine = _engine()
    T = engine.T
    f = _exchange_fn(engine, mesh8, with_frac=True)
    fracs = np.array([1, 1, 1, 0.3, 1, 1, 1, 0.65], np.float32)
    mem = _init_mem(engine)
    rng = np.random.RandomState(3)

    # NumPy oracle of the accumulating compensate (memory.py:
    # mmt = m*mmt + g; vec += mmt, both masked on read by the PREVIOUS
    # step's transmit record — momentum_masking defaults True)
    mom = comp.memory.momentum
    v_np = np.zeros((W, T), np.float32)
    m_np = np.zeros((W, T), np.float32)
    keep_prev = np.ones((W, T), np.float32)
    quotas = {n: comp.attributes[n].num_selects
              for n in layout.names if n in comp.attributes}

    sent_counts_seen = []
    for step in range(4):
        g = _grads(layout, rng)
        out, mem = f(jnp.asarray(g), mem, jax.random.PRNGKey(step),
                     jnp.asarray(fracs))
        out0 = np.asarray(out)[0]
        bits = np.asarray(mem["sent_bits"])
        keep_new = np.stack([
            np.asarray(kernels.keep_from_bits(jnp.asarray(bits[w]), T))
            for w in range(W)])
        sent_new = 1.0 - keep_new

        # oracle recurrence (f32, mirroring the engine's elementwise ops)
        m_np = mom * (m_np * keep_prev) + g[:, :T]
        v_np = v_np * keep_prev + m_np

        vc = np.asarray(mem["velocities_c"])          # post-step, unmasked
        np.testing.assert_allclose(vc, v_np, rtol=1e-5, atol=1e-5)

        # the wire (op="sum") is exactly the per-worker transmitted slices
        transmitted = vc * sent_new
        np.testing.assert_allclose(out0[:T], transmitted.sum(axis=0),
                                   rtol=1e-5, atol=1e-5)

        # residual view (memory_full materializes the pending mask)
        full = engine.memory_full(
            jax.tree.map(lambda x: jnp.asarray(x[0]), mem))
        resid0 = np.asarray(full["velocities"])[:T]
        np.testing.assert_allclose(resid0, vc[0] * keep_new[0],
                                   rtol=1e-6, atol=1e-6)

        # per-tensor mass conservation vs the oracle, every worker
        for n, quota in quotas.items():
            o, s = layout.offsets[n], layout.sizes[n]
            for w in range(W):
                raw = np.abs(v_np[w, o:o + s].astype(np.float64)).sum()
                split = (np.abs((vc[w] * sent_new[w])[o:o + s]
                                .astype(np.float64)).sum()
                         + np.abs((vc[w] * keep_new[w])[o:o + s]
                                  .astype(np.float64)).sum())
                assert abs(split - raw) <= 1e-6 * max(raw, 1e-12), \
                    (n, w, step)

        # degraded workers transmit a visibly smaller payload, capped by
        # ceil(quota * frac) per row; healthy workers keep theirs
        sent_counts = sent_new.sum(axis=1)
        cap3 = sum(int(np.ceil(q * 0.3)) for q in quotas.values())
        assert 0 < sent_counts[3] <= cap3
        assert sent_counts[3] < sent_counts[0]
        assert sent_counts[4] == sent_counts[0]
        sent_counts_seen.append(sent_counts)
        keep_prev = keep_new

    # the policy engaged on every step (not a warmup accident)
    assert all(s[3] < s[0] for s in sent_counts_seen)


# --------------------------------------------------------------------- #
# full train step: verdict feed-forward + the w_eff_ratio lane           #
# --------------------------------------------------------------------- #

def test_step_adaptive_engages_and_releases(mesh8):
    """The fleet step with adaptive on: step N's gathered clock sets
    step N+1's send fraction (one-step feedback through the donated
    state), the fleet metrics grow a real w_eff_ratio column +
    adaptive_engaged scalar, and a recovered clock releases the worker
    back to full send."""
    from dgc_tpu.analysis.suite import build_fixture

    cfg = AdaptiveConfig()
    state, step, _, (images, labels, key) = build_fixture(
        mesh8, donate=False, telemetry=True, fleet=True, adaptive=cfg)
    sh = NamedSharding(mesh8, P(tuple(mesh8.axis_names)))

    def clock(vals):
        return jax.device_put(np.asarray(vals, np.float32), sh)

    # step 1: fresh verdict (full send), worker 6 straggles 150ms past
    # the 200ms cohort median — ramp tier, below the partial deadline
    skewed = clock([200.0] * 6 + [350.0, 200.0])
    state, metrics = step(state, images, labels, key, skewed)
    flt = metrics["fleet"]
    np.testing.assert_allclose(np.asarray(flt["w_eff_ratio"]), 1.0)
    assert float(flt["adaptive_engaged"]) == 0.0
    want = 1.0 - (1.0 - cfg.min_frac) * 150.0 / cfg.ramp_ms
    frac = np.asarray(state.adaptive["w_frac"])
    assert frac[6] == pytest.approx(want, rel=1e-5)
    np.testing.assert_allclose(np.delete(frac, 6), 1.0)

    # step 2: the degraded fraction reaches the wire AND the telemetry
    state, metrics = step(state, images, labels, key, skewed)
    eff = np.asarray(metrics["fleet"]["w_eff_ratio"])
    assert eff[6] == pytest.approx(want, rel=1e-5)
    np.testing.assert_allclose(np.delete(eff, 6), 1.0)
    assert float(metrics["fleet"]["adaptive_engaged"]) == 1.0

    # step 3 with a recovered clock: immediate release (memoryless)
    state, _ = step(state, images, labels, key, clock([200.0] * 8))
    np.testing.assert_allclose(np.asarray(state.adaptive["w_frac"]), 1.0)


# --------------------------------------------------------------------- #
# checkpoint: the policy state is never saved, always re-seeded          #
# --------------------------------------------------------------------- #

def _ckpt_state(value, adaptive_state=None):
    rng = np.random.RandomState(11)
    return TrainState(
        step=jnp.asarray(int(value), jnp.int32),
        params={"w": jnp.full((4,), float(value))},
        opt_state=(jnp.zeros(()),),
        memory={"momentums_c": jnp.asarray(rng.randn(8), jnp.float32),
                "velocities_c": jnp.asarray(rng.randn(8), jnp.float32),
                "sent_bits": jnp.asarray(rng.randint(0, 2 ** 10, 128),
                                         jnp.int32)},
        batch_stats={},
        adaptive=adaptive_state)


def test_checkpoint_strips_and_reseeds_adaptive(tmp_path):
    """An emergency save taken WHILE the policy is engaged: the
    compressor memory (incl. the packed transmit record — the conserved
    mass) restores bitwise, the degraded verdict is NOT persisted, and
    restore re-seeds the template's fresh full-send verdict."""
    engaged = {"w_frac": jnp.asarray([1.0, 0.3], jnp.float32)}
    saved = _ckpt_state(5.0, adaptive_state=engaged)
    mgr = CheckpointManager(str(tmp_path), keep=2)
    mgr.save(0, saved, {"m": 1.0})

    template = _ckpt_state(0.0, adaptive_state=adaptive.init_state(2))
    state, epoch, _ = mgr.restore(template)
    assert epoch == 0
    for k in ("momentums_c", "velocities_c", "sent_bits"):
        np.testing.assert_array_equal(np.asarray(state.memory[k]),
                                      np.asarray(saved.memory[k]))
    # the restored verdict is the template's fresh one, not the saved 0.3
    np.testing.assert_array_equal(np.asarray(state.adaptive["w_frac"]),
                                  [1.0, 1.0])

    # an adaptive-off template restores the same checkpoint unchanged
    off = mgr.restore(_ckpt_state(0.0))[0]
    assert off.adaptive is None
    np.testing.assert_array_equal(np.asarray(off.params["w"]), 5.0)


def test_checkpoint_adaptive_elastic_world_change(tmp_path):
    """Save at W=2 with the policy engaged, resume at W=1: the [world]-
    shaped w_frac leaf must never enter the restore (it is stripped on
    save and re-attached from the template), so the world-size change
    cannot shape-mismatch."""
    mgr = CheckpointManager(str(tmp_path), keep=2)
    mgr.save(0, _ckpt_state(
        2.0, adaptive_state={"w_frac": jnp.asarray([0.25, 1.0])}), {})
    template = _ckpt_state(0.0, adaptive_state=adaptive.init_state(1))
    state, _, _ = mgr.restore(template)
    assert np.asarray(state.adaptive["w_frac"]).shape == (1,)
    np.testing.assert_array_equal(np.asarray(state.adaptive["w_frac"]),
                                  [1.0])
    np.testing.assert_array_equal(np.asarray(state.params["w"]), 2.0)


# --------------------------------------------------------------------- #
# windowed slow fault (the transient-straggler drill's schedule)         #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_faults_slow_window_parsing():
    from dgc_tpu.resilience import faults
    p = faults.plan("slow:ms=40@10-20")
    assert p.slow_ms == 40 and p.slow_window == (10, 20)
    assert faults.plan("slow@7-9:ms=25").slow_window == (7, 9)
    assert faults.plan("slow@15").slow_window == (15, None)
    assert faults.plan("slow:ms=40").slow_window is None
    assert faults.plan("slow:ms=40").slow_ms == 40


@pytest.mark.fast
def test_faults_slow_window_gating(monkeypatch):
    import time

    from dgc_tpu.resilience import faults
    monkeypatch.setenv(faults.ENV, "slow:ms=30@5-6")

    def took(step):
        t0 = time.perf_counter()
        faults.maybe_slow(step)
        return time.perf_counter() - t0

    assert took(4) < 0.02           # before the window
    assert took(5) >= 0.025         # inside
    assert took(6) >= 0.025         # inclusive upper bound
    assert took(7) < 0.02           # after
    # a windowed plan with no step supplied must never fire
    assert took(None) < 0.02

    # open-ended @K: from K onward
    monkeypatch.setenv(faults.ENV, "slow:ms=30@5")
    assert took(4) < 0.02 and took(50) >= 0.025
    # un-windowed plans keep the old any-step behavior (byte-compatible)
    monkeypatch.setenv(faults.ENV, "slow:ms=30")
    assert took(None) >= 0.025


# --------------------------------------------------------------------- #
# control plane: rules.toml + the adapt remediation                      #
# --------------------------------------------------------------------- #

RULES_TOML = """\
# operator-tuned remediation table
[[rule]]
name = "straggler-adapt"
detector = "straggler"
action = "adapt"
min_hits = 3
debounce_s = 120.0   # let the relaunch settle
budget = 1

[[rule]]
name = "desync-restart"
detector = "desync"
action = "restart"
"""


@pytest.mark.fast
def test_load_rules_toml(tmp_path):
    from dgc_tpu.control import rules as rules_mod
    path = tmp_path / "rules.toml"
    path.write_text(RULES_TOML)
    rules = rules_mod.load_rules(str(path))
    assert [r.name for r in rules] == ["straggler-adapt", "desync-restart"]
    r0 = rules[0]
    assert r0.action == "adapt" and r0.min_hits == 3
    assert r0.debounce_s == 120.0 and r0.budget == 1
    assert r0.detect is rules_mod.detect_straggler
    # unset keys take the Rule defaults
    assert rules[1].min_hits == 2 and rules[1].budget == 2


@pytest.mark.fast
def test_load_rules_validates_loudly(tmp_path):
    from dgc_tpu.control.rules import load_rules

    def write(text):
        p = tmp_path / "r.toml"
        p.write_text(text)
        return str(p)

    with pytest.raises(ValueError, match="unknown detector"):
        load_rules(write('[[rule]]\nname = "x"\n'
                         'detector = "nope"\naction = "adapt"\n'))
    with pytest.raises(ValueError, match="unknown action"):
        load_rules(write('[[rule]]\nname = "x"\n'
                         'detector = "straggler"\naction = "nope"\n'))
    with pytest.raises(ValueError, match="unknown keys"):
        load_rules(write('[[rule]]\nname = "x"\ndetector = "straggler"\n'
                         'action = "adapt"\ntypo_key = 1\n'))
    with pytest.raises(ValueError, match="missing keys"):
        load_rules(write('[[rule]]\nname = "x"\naction = "adapt"\n'))
    with pytest.raises(ValueError, match="duplicate"):
        load_rules(write('[[rule]]\nname = "x"\ndetector = "straggler"\n'
                         'action = "adapt"\n'
                         '[[rule]]\nname = "x"\ndetector = "desync"\n'
                         'action = "restart"\n'))
    with pytest.raises(ValueError, match="outside"):
        load_rules(write('name = "x"\n'))
    with pytest.raises(ValueError, match="no \\[\\[rule\\]\\]"):
        load_rules(write("# empty\n"))


@pytest.mark.fast
def test_act_adapt_publishes_env_flag(tmp_path):
    from dgc_tpu.control import actions
    env_file = str(tmp_path / "cohort.env")
    restarts = []
    sup = types.SimpleNamespace(
        env_file=env_file,
        request_restart=lambda reason=None: restarts.append(reason) or True)
    result = actions.act_adapt(sup, {"kind": "straggler"})
    assert result["published"] == {"DGC_ADAPTIVE": "1"}
    assert result["delivered"] is True
    assert restarts == ["straggler"]
    assert actions.parse_env_file(env_file)["DGC_ADAPTIVE"] == "1"
    # existing cohort keys survive the merge
    actions.publish_env(env_file, {"JAX_NUM_PROCESSES": "2"})
    merged = actions.parse_env_file(env_file)
    assert merged == {"DGC_ADAPTIVE": "1", "JAX_NUM_PROCESSES": "2"}

    # no env-file wired: still restarts, audit says degraded
    sup2 = types.SimpleNamespace(
        env_file=None, request_restart=lambda reason=None: False)
    result2 = actions.act_adapt(sup2, {"kind": "straggler"})
    assert result2["degraded_to"] == "restart"
    assert result2["published"] == {}


@pytest.mark.fast
def test_monitor_renders_adaptive_line():
    from dgc_tpu.telemetry import monitor
    snap = {"run": "r", "step": 9, "num_steps": 10, "world": 4,
            "num_hosts": 1, "summary": {},
            "last": {"adaptive_engaged": 1.0,
                     "w_eff_ratio": [1.0, 1.0, 0.55, 1.0]}}
    status = monitor.render_status(snap)
    assert "ADAPTIVE: straggler send fraction degraded" in status
    assert "w2=0.55" in status and "w0" not in status
    # disengaged: the line disappears
    snap["last"] = {"adaptive_engaged": 0.0,
                    "w_eff_ratio": [1.0, 1.0, 1.0, 1.0]}
    assert "ADAPTIVE" not in monitor.render_status(snap)


@pytest.mark.fast
def test_adapt_action_registered():
    from dgc_tpu.control.actions import ACTIONS
    from dgc_tpu.telemetry import registry
    assert "adapt" in ACTIONS
    assert "adapt" in registry.control_action_names()
    # the fleet schema carries the adaptive lanes the monitor renders
    names = registry.fleet_stat_names()
    assert "w_eff_ratio" in names and "adaptive_engaged" in names
