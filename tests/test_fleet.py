"""Tests for the fleet observability layer (ISSUE 10): registry fleet
schema, the in-graph packed gather, tolerant shard readers + multi-host
merge, the straggler table, the rolling-band desync detector, the live
monitor's OpenMetrics/status renderers + HTTP endpoint, the supervisor's
event stamping, and the ``slow`` fault token.

All host-side pieces run against synthetic JSONL runs — no training, so
the whole file is ``fast``-marked (scripts/t1.sh MONITOR_SMOKE). The
in-graph gather runs once on the 8-fake-device mesh; the cross-process
drill lives in tests/test_multiprocess.py.
"""

import importlib.util
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from dgc_tpu.telemetry import fleet, monitor, registry
from dgc_tpu.telemetry import sink as sink_mod

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------- #
# synthetic runs                                                         #
# --------------------------------------------------------------------- #

def _write_run(root, hosts=2, world=4, steps=40, straggler=None,
               torn=False, rotate=False):
    """A fleet-shaped run dir: ``<root>/telemetry/host<i>/*.jsonl`` with
    replicated per-worker columns, an event row on host0, optionally a
    torn tail on the last host and a rotated shard on host0."""
    header = registry.make_header(
        {"world": world, "num_params": 1000, "payload_elems": 50},
        fleet=True)
    rng = np.random.RandomState(0)
    for h in range(hosts):
        hd = os.path.join(root, "telemetry", f"host{h}")
        os.makedirs(hd, exist_ok=True)
        lines = [json.dumps(header)]
        if h == 0:
            lines.append(json.dumps(
                {"event": "engine_rebuild", "epoch": 0, "t_host": 99.0}))
        recs = []
        for i in range(steps):
            clock = 10.0 + rng.rand(world)
            if straggler is not None:
                clock[straggler] += 80.0
            mass = 100.0 * (1.0 + 0.02 * rng.randn(world))
            recs.append({
                "step": i, "t_host": 100.0 + 0.5 * i,
                "loss": 2.0 - 0.01 * i,
                "grad_norm": 1.0, "payload_elems": 50.0,
                "w_clock": [round(float(x), 3) for x in clock],
                "w_grad_norm": [1.0] * world,
                "w_residual_mass": [round(float(x), 4) for x in mass],
                "w_sent_ratio": [0.05] * world,
                "straggler": float(int(np.argmax(clock))),
                "straggler_gap": round(float(clock.max() - clock.min()), 3),
                "worker_skew": 0.1,
            })
        if rotate and h == 0:
            cut = steps // 2
            open(os.path.join(hd, "telemetry.jsonl"), "w").write(
                "\n".join(lines + [json.dumps(r) for r in recs[:cut]])
                + "\n")
            open(os.path.join(hd, "telemetry.1.jsonl"), "w").write(
                "\n".join([json.dumps(header)]
                          + [json.dumps(r) for r in recs[cut:]]) + "\n")
            continue
        text = "\n".join(lines + [json.dumps(r) for r in recs]) + "\n"
        if torn and h == hosts - 1:
            text += '{"step": 999, "w_clock": [1'     # live-writer tear
        open(os.path.join(hd, "telemetry.jsonl"), "w").write(text)
    return root


# --------------------------------------------------------------------- #
# registry: fleet schema                                                 #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_registry_fleet_schema():
    names = registry.fleet_stat_names()
    assert len(names) == len(set(names))
    kinds = {s.name: s.kind for s in registry.FLEET_METRICS}
    for lane in ("w_clock", "w_grad_norm", "w_residual_mass",
                 "w_sent_ratio"):
        assert kinds[lane] == "per_worker"
    for scalar in ("straggler", "straggler_gap", "worker_skew"):
        assert kinds[scalar] == "scalar"
    # the gate-able dispersion metrics are registered lower-is-better
    by_name = registry.spec_by_name()
    assert by_name["worker_skew"].better == "lower"
    assert by_name["straggler_gap"].better == "lower"
    run_names = {s.name for s in registry.RUN_METRICS}
    assert {"worker_skew", "straggler_gap"} <= run_names

    h = registry.make_header({"world": 8}, fleet=True)
    assert {m["name"] for m in h["fleet_metrics"]} == set(names)
    assert "fleet_metrics" not in registry.make_header({})
    # additive keys: no version bump
    assert h["version"] == registry.SCHEMA_VERSION

    good = {n: 0.0 for n in names}
    registry.validate_fleet_stats(good)
    with pytest.raises(ValueError, match="missing"):
        registry.validate_fleet_stats(
            {k: v for k, v in good.items() if k != "w_clock"})
    assert set(registry.fleet_out_specs(lambda: "P()")) == set(names)


# --------------------------------------------------------------------- #
# tolerant reader                                                        #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_read_run_tolerant_truncated_shard(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    header = registry.make_header({"world": 2}, fleet=True)
    lines = [json.dumps(header)] + [
        json.dumps({"step": i, "grad_norm": 1.0}) for i in range(3)]
    path.write_text("\n".join(lines) + "\n"
                    + '{"step": 3, "grad_norm": 0.')  # torn mid-write
    h, recs, skipped = sink_mod.read_run_tolerant(str(path))
    assert h["schema"] == registry.SCHEMA
    assert [r["step"] for r in recs] == [0, 1, 2]
    assert skipped == 1
    # the strict reader refuses the same file
    with pytest.raises(json.JSONDecodeError):
        sink_mod.read_run(str(path))

    # a torn HEADER is an unreadable file, not a skippable line
    bad = tmp_path / "torn_header.jsonl"
    bad.write_text('{"schema": "dgc-telem')
    with pytest.raises(ValueError, match="unreadable telemetry header"):
        sink_mod.read_run_tolerant(str(bad))

    # a readable but future-versioned header still raises loudly
    fut = tmp_path / "future.jsonl"
    fut.write_text(json.dumps(dict(header, version=999)) + "\n")
    with pytest.raises(sink_mod.SchemaMismatchError):
        sink_mod.read_run_tolerant(str(fut))


# --------------------------------------------------------------------- #
# shard discovery + merge                                                #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_load_view_merges_hosts_and_rotations(tmp_path):
    run = _write_run(str(tmp_path), hosts=2, steps=20, torn=True,
                     rotate=True)
    shards = fleet.discover_shards(run)
    assert sorted(shards) == ["host0", "host1"]
    # rotation order: base shard before .1
    assert [os.path.basename(p) for p in shards["host0"]] == \
        ["telemetry.jsonl", "telemetry.1.jsonl"]

    view = fleet.load_view(run)
    assert sorted(view.hosts) == ["host0", "host1"]
    assert view.world == 4
    assert view.skipped == 1                      # host1's torn tail
    # host0's records span both rotated shards, in step order
    assert [r["step"] for r in view.steps] == list(range(20))
    assert [e["event"] for e in view.events] == ["engine_rebuild"]
    assert view.events[0]["host"] == "host0"

    with pytest.raises(FileNotFoundError):
        fleet.load_view(str(tmp_path / "nope"))


@pytest.mark.fast
def test_worker_series_prefers_columns_then_falls_back(tmp_path):
    run = _write_run(str(tmp_path), hosts=2, world=4, steps=5)
    series = fleet.worker_series(fleet.load_view(run), "w_clock")
    assert len(series) == 5 and len(series[0][1]) == 4

    # pre-fleet layout: per-host scalar columns only -> host-aligned
    old = tmp_path / "old"
    for h in range(2):
        hd = old / "telemetry" / f"host{h}"
        hd.mkdir(parents=True)
        lines = [json.dumps(registry.make_header({}))]
        for i in range(4):
            lines.append(json.dumps(
                {"step": i, "residual_mass": 100.0 + h}))
        (hd / "telemetry.jsonl").write_text("\n".join(lines) + "\n")
    series = fleet.worker_series(fleet.load_view(str(old)),
                                 "w_residual_mass")
    assert len(series) == 4
    assert series[0][1] == [100.0, 101.0]         # one value per host


# --------------------------------------------------------------------- #
# detectors                                                              #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_desync_detector_quiet_then_fires():
    rng = np.random.RandomState(7)
    healthy = [(i, list(100.0 * (1 + 0.03 * rng.randn(4))))
               for i in range(60)]
    assert fleet.detect_desync(healthy) == []

    # worker 2 walks away from the cohort mid-run
    bad = []
    for i, vals in healthy:
        vals = list(vals)
        if i >= 30:
            vals[2] *= 1.0 + 0.8 * (i - 29)
        bad.append((i, vals))
    alerts = fleet.detect_desync(bad)
    assert alerts and {a.worker for a in alerts} == {2}
    assert alerts[0].step >= 30 + 2               # min_hits consecutive
    assert alerts[0].deviation > alerts[0].band
    # the band is learned from history only: the diverging worker's own
    # huge deviations must not have inflated the band it tripped
    assert alerts[0].band < 1.0


@pytest.mark.fast
def test_straggler_table_and_summary(tmp_path):
    run = _write_run(str(tmp_path), hosts=2, world=4, steps=30,
                     straggler=3)
    view = fleet.load_view(run)
    table = fleet.straggler_table(view)
    assert [r["worker"] for r in table][0] == 3
    assert table[0]["share"] > 1.5                # 90ms vs ~10ms cohort
    assert all(set(r) == {"worker", "mean_ms", "max_ms", "last_ms",
                          "share"} for r in table)
    summary = fleet.fleet_summary(view)
    assert summary["straggler"] == 3
    assert summary["straggler_gap"] > 50.0
    assert summary["desync_alerts"] == 0
    assert summary["num_hosts"] == 2 and summary["world"] == 4


# --------------------------------------------------------------------- #
# monitor                                                                #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_monitor_collect_and_renderers(tmp_path):
    run = _write_run(str(tmp_path), hosts=2, world=4, steps=30,
                     straggler=1)
    # a supervisor event stream under the run dir, as supervise.py
    # defaults it (--watch <run>/checkpoints)
    (tmp_path / "supervise_events.jsonl").write_text(
        json.dumps({"event": "launch", "t": 1.0, "launches": 1,
                    "run_id": "x", "cohort": {}}) + "\n"
        + json.dumps({"event": "relaunch", "t": 2.0, "launches": 2,
                      "rc": 75, "run_id": "x", "cohort": {}}) + "\n")

    snap = monitor.collect(run)
    assert snap["step"] == 29 and snap["world"] == 4
    assert snap["steps_per_s"] == pytest.approx(2.0)   # 0.5s t_host grid
    assert snap["compression_ratio"] == pytest.approx(20.0)  # 1000/50
    assert snap["supervise_launches"] == 2
    assert snap["last_supervise"]["event"] == "relaunch"
    assert snap["last_event"]["event"] == "engine_rebuild"

    om = monitor.render_openmetrics(snap)
    assert om.endswith("# EOF\n")
    # every gauge carries the run label (the supervise stream's run_id),
    # per-worker series add worker="i" alongside it
    for needle in ('dgc_worker_clock_ms{run="x",worker="0"}',
                   'dgc_worker_residual_mass{run="x",worker="3"}',
                   'dgc_step{run="x"}',
                   "dgc_straggler_gap_ms", "dgc_worker_skew",
                   "dgc_compression_ratio", "dgc_supervise_launches"):
        assert needle in om, needle
    assert snap["run_label"] == "x"
    # every family is HELP/TYPE'd exactly once
    helps = [l.split()[2] for l in om.splitlines()
             if l.startswith("# HELP")]
    assert len(helps) == len(set(helps))

    status = monitor.render_status(snap)
    assert "<- straggler" in status
    assert "worker  mean_ms" in status            # table header rendered
    assert "desync: quiet" in status
    assert "last supervise" in status


@pytest.mark.fast
def test_monitor_http_endpoint(tmp_path):
    run = _write_run(str(tmp_path), hosts=1, world=4, steps=10)
    server = monitor.ThreadingHTTPServer(
        ("127.0.0.1", 0), monitor._make_handler(monitor._Cache(run, 1.0)))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            body = r.read().decode()
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text")
        assert body.endswith("# EOF\n") and "dgc_step{" in body
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10) as r:
            assert "dgc fleet monitor" in r.read().decode()
    finally:
        server.shutdown()


@pytest.mark.fast
def test_monitor_once_cli(tmp_path, capsys):
    run = _write_run(str(tmp_path), hosts=1, world=4, steps=10)
    assert monitor._main([run, "--once"]) == 0
    assert "dgc fleet monitor" in capsys.readouterr().out
    assert monitor._main([run, "--once", "--openmetrics"]) == 0
    assert capsys.readouterr().out.endswith("# EOF\n")
    assert monitor._main([str(tmp_path / "gone"), "--once"]) == 1


# --------------------------------------------------------------------- #
# supervisor event stamping                                              #
# --------------------------------------------------------------------- #

def _load_supervise():
    spec = importlib.util.spec_from_file_location(
        "supervise", os.path.join(ROOT, "scripts", "supervise.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.fast
def test_supervise_event_stamping_and_flush(tmp_path, monkeypatch):
    sup_mod = _load_supervise()
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    events = tmp_path / "run" / "supervise_events.jsonl"
    sup = sup_mod.Supervisor(["true"], events=str(events))
    sup.event("launch", cmd=["true"])
    sup.launches = 1
    sup.event("relaunch", rc=75)
    # flushed per event: readable NOW, without any close/flush call
    recs = [json.loads(l) for l in events.read_text().splitlines()]
    assert [r["event"] for r in recs] == ["launch", "relaunch"]
    for r in recs:
        assert r["run_id"] == sup.run_id
        assert r["cohort"]["JAX_NUM_PROCESSES"] == "2"
    assert recs[1]["launches"] == 1

    # default stream location: next to the --watch dir, under the run dir
    assert sup_mod.default_events_path("/runs/exp/checkpoints") == \
        "/runs/exp/supervise_events.jsonl"
    assert sup_mod.default_events_path(None) is None
    # the monitor finds the same default
    assert monitor.supervise_events_path(str(tmp_path / "run")) == \
        str(events)


# --------------------------------------------------------------------- #
# slow fault token                                                       #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_faults_slow_token(monkeypatch):
    from dgc_tpu.resilience import faults
    assert faults.plan("slow:ms=40").slow_ms == 40
    assert faults.plan("slow").slow_ms == 100
    assert faults.plan("").slow_ms is None
    with pytest.raises(ValueError):
        faults.plan("sloow")
    monkeypatch.setenv(faults.ENV, "slow:ms=30")
    assert faults.armed()
    t0 = time.perf_counter()
    faults.maybe_slow()
    assert time.perf_counter() - t0 >= 0.025
    monkeypatch.setenv(faults.ENV, "")
    t0 = time.perf_counter()
    faults.maybe_slow()                           # unarmed: no sleep
    assert time.perf_counter() - t0 < 0.02


# --------------------------------------------------------------------- #
# in-graph: the packed gather on the 8-device mesh                       #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_gather_stats_identifies_straggler(mesh8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dgc_tpu.utils.compat import shard_map

    axes = tuple(mesh8.axis_names)
    clock_np = np.array([5, 5, 5, 260, 5, 5, 5, 5], np.float32)
    gn_np = np.arange(1, 9, dtype=np.float32)
    sh = NamedSharding(mesh8, P(axes))
    clock = jax.device_put(clock_np, sh)
    gnorm = jax.device_put(gn_np, sh)

    def worker(c, g):
        g = g.reshape(())
        stats = {"grad_norm": g, "residual_mass": 2.0 * g,
                 "payload_elems": jnp.float32(50.0)}
        return fleet.gather_stats(stats, axes, clock=c, total_elems=1000)

    telem_specs = {k: P() for k in ("grad_norm", "residual_mass",
                                    "payload_elems")}
    fleet_specs = {k: P() for k in registry.fleet_stat_names()}
    fn = jax.jit(shard_map(worker, mesh=mesh8, in_specs=(P(axes), P(axes)),
                           out_specs=(telem_specs, fleet_specs)))
    telem, flt = fn(clock, gnorm)

    # telemetry means replace the pmean exactly
    assert float(telem["grad_norm"]) == pytest.approx(float(gn_np.mean()))
    assert float(telem["residual_mass"]) == pytest.approx(
        2.0 * float(gn_np.mean()))
    # per-worker columns come back verbatim, every stat f32
    np.testing.assert_allclose(np.asarray(flt["w_clock"]), clock_np)
    np.testing.assert_allclose(np.asarray(flt["w_grad_norm"]), gn_np)
    assert all(np.asarray(v).dtype == np.float32 for v in flt.values())
    # straggler verdict + dispersion scalars
    assert int(flt["straggler"]) == 3
    assert float(flt["straggler_gap"]) == pytest.approx(255.0)
    assert np.asarray(flt["w_sent_ratio"]) == pytest.approx(0.05)
    clock_skew = 255.0 / clock_np.mean()
    assert float(flt["worker_skew"]) == pytest.approx(clock_skew, rel=1e-5)


@pytest.mark.fast
def test_make_clock_single_process(mesh8):
    import jax
    clk = fleet.make_clock(12.5, mesh8, 8)
    assert clk.shape == (8,) and clk.dtype == jax.numpy.float32
    np.testing.assert_allclose(np.asarray(clk), 12.5)
