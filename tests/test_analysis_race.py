"""dgcrace (layer 4, static half): DGC201-204 fixture coverage, the
audited-allowlist tree gate, and the red-to-green demo on the real
concurrency fixes this layer motivated.

Every race rule has a ``<rule>_pos.py`` / ``<rule>_neg.py`` pair under
tests/fixtures/racelint/, same convention as the dgclint layer:
positive fixtures mark each expected violation line with
``# LINT: <rule-id>`` and the test asserts marker-exact agreement."""

import re
from pathlib import Path

import pytest

from dgc_tpu.analysis.racelint import race_lint_paths, race_lint_source
from dgc_tpu.analysis.rules import (RACE_RULES, RULES_BY_ID, Allowlist,
                                    load_allowlist)

FIXDIR = Path(__file__).parent / "fixtures" / "racelint"
REPO_ROOT = Path(__file__).parents[1]
_MARK = re.compile(r"#\s*LINT:\s*([a-z0-9\-]+)")

POS = sorted(FIXDIR.glob("*_pos.py"))
NEG = sorted(FIXDIR.glob("*_neg.py"))


def _expected(src: str):
    return {(m.group(1), i + 1)
            for i, line in enumerate(src.splitlines())
            for m in [_MARK.search(line)] if m}


@pytest.mark.parametrize("path", POS, ids=lambda p: p.stem)
def test_positive_fixture_flags_marked_lines(path):
    src = path.read_text()
    want = _expected(src)
    assert want, f"{path.name} has no LINT markers"
    got = {(f.rule, f.line) for f in race_lint_source(src, str(path))}
    assert got == want


@pytest.mark.parametrize("path", NEG, ids=lambda p: p.stem)
def test_negative_fixture_is_clean(path):
    findings = race_lint_source(path.read_text(), str(path))
    assert findings == [], [f.format() for f in findings]


def test_every_race_rule_has_fixture_pair():
    stems = {p.stem for p in POS} | {p.stem for p in NEG}
    for rule in RACE_RULES:
        base = rule.id.replace("-", "_")
        assert f"{base}_pos" in stems, f"no positive fixture for {rule.id}"
        assert f"{base}_neg" in stems, f"no negative fixture for {rule.id}"


def test_race_rules_registered_with_codes():
    for rule in RACE_RULES:
        assert RULES_BY_ID[rule.id] is rule
        assert rule.code.startswith("DGC2")


# --------------------------------------------------------------------- #
# the tree gate: HEAD is clean modulo the audited allowlist              #
# --------------------------------------------------------------------- #

def test_repo_tree_has_no_unallowed_race_findings():
    findings = race_lint_paths(root=str(REPO_ROOT))
    bad = [f.format() for f in findings if not f.allowed]
    assert bad == []
    # the audited exceptions are real: the allowlist is exercised
    assert any(f.allowed for f in findings)


def test_race_allowlist_entries_name_race_rules():
    allow = load_allowlist()
    race_ids = {r.id for r in RACE_RULES}
    audited = [e for e in allow.entries if e["rule"] in race_ids]
    assert audited, "expected audited DGC2xx allowlist entries"
    for e in audited:
        assert e["reason"].strip()


# --------------------------------------------------------------------- #
# red -> green: the pre-fix Supervisor shape vs HEAD                     #
# --------------------------------------------------------------------- #

# Distilled from dgc_tpu/control/supervisor.py BEFORE this layer's fix:
# the run loop (main thread) and the hang watchdog + control-plane
# callers (other threads) touched child/quarantined/launches with no
# lock. The linter finds every one of them.
_PRE_FIX_SUPERVISOR = '''
import subprocess
import threading


class Supervisor:
    def __init__(self, cmd):
        self.cmd = cmd
        self.child = None
        self.quarantined = None
        self.launches = 0

    def quarantine(self, reason):
        if self.quarantined is None:      # check-then-set, no lock
            self.quarantined = reason

    def _watch_hang(self, child):
        current = self.child              # torn read vs run()'s store
        if current is child and self.launches > 3:
            child.kill()
            if self.quarantined is None:  # check-then-set across threads
                self.quarantined = "hang"

    def run(self):
        while self.quarantined is None:
            self.launches += 1
            self.child = subprocess.Popen(self.cmd)
            child = self.child
            t = threading.Thread(target=self._watch_hang, args=(child,),
                                 daemon=True)
            t.start()
            child.wait()
            self.child = None
'''


def test_pre_fix_supervisor_shape_is_red():
    findings = race_lint_source(_PRE_FIX_SUPERVISOR, "pre_fix.py")
    rules = {f.rule for f in findings}
    assert "thread-shared-state" in rules
    shared = {
        f.message.split(" is shared")[0] for f in findings
        if f.rule == "thread-shared-state"}
    # every unlocked cross-thread field is caught
    assert {"Supervisor.child", "Supervisor.quarantined",
            "Supervisor.launches"} <= shared


@pytest.mark.parametrize("rel", [
    "dgc_tpu/control/supervisor.py",
    "dgc_tpu/resilience/preempt.py",
    "dgc_tpu/telemetry/sink.py",
])
def test_fixed_modules_are_green_at_head(rel):
    findings = race_lint_paths([rel], root=str(REPO_ROOT))
    bad = [f.format() for f in findings if not f.allowed]
    assert bad == [], bad


# --------------------------------------------------------------------- #
# CLI gate exit codes                                                    #
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("path", POS, ids=lambda p: p.stem)
def test_cli_race_exits_nonzero_on_seeded_violation(path, capsys):
    from dgc_tpu.analysis.__main__ import main
    rc = main(["--race", str(path), "--root", str(REPO_ROOT)])
    capsys.readouterr()
    assert rc == 1


def test_cli_race_exits_zero_on_clean_fixtures(capsys):
    from dgc_tpu.analysis.__main__ import main
    rc = main(["--race"] + [str(p) for p in NEG]
              + ["--root", str(REPO_ROOT)])
    capsys.readouterr()
    assert rc == 0


def test_cli_race_clean_on_repo_tree(capsys):
    from dgc_tpu.analysis.__main__ import main
    rc = main(["--race", "--root", str(REPO_ROOT)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "dgcrace:" in out


# --------------------------------------------------------------------- #
# waiver machinery rides along unchanged                                 #
# --------------------------------------------------------------------- #

def test_inline_waiver_suppresses_race_rule():
    src = _PRE_FIX_SUPERVISOR.replace(
        "self.launches += 1",
        "self.launches += 1  # dgclint: ok[thread-shared-state]")
    findings = race_lint_source(src, "waived.py")
    assert not any(f.rule == "thread-shared-state"
                   and "launches" in f.message for f in findings)


def test_allowlist_matches_race_finding():
    findings = race_lint_source(_PRE_FIX_SUPERVISOR, "pre_fix.py",
                                allowlist=Allowlist([{
                                    "rule": "thread-shared-state",
                                    "file": "pre_fix.py",
                                    "contains": "self.launches",
                                    "reason": "test"}]))
    waived = [f for f in findings if f.allowed]
    assert waived and all("launches" in f.snippet for f in waived)
