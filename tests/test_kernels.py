"""Pallas kernels (dgc_tpu.ops.kernels) must match their jnp reference
implementations (SURVEY.md §7 item 6 contract; elementwise kernels to one
ULP — FMA contraction — and integer counts exactly). On CPU the kernels run
in interpreter mode — same program the TPU compiles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.ops import kernels


@pytest.mark.parametrize("nesterov", [False, True])
@pytest.mark.parametrize("n", [1, 127, 128, 1024, 65536 + 3, 272474])
def test_fused_compensate_matches_reference(n, nesterov):
    rng = np.random.RandomState(n)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    m = jnp.asarray(rng.randn(n), jnp.float32)
    v = jnp.asarray(rng.randn(n), jnp.float32)
    om, ov = kernels.fused_compensate(g, m, v, 0.9, nesterov)
    rm, rv = kernels.fused_compensate_reference(g, m, v, 0.9, nesterov)
    # FMA contraction in the kernel differs by ~1 ULP of the input
    # scale; vec+mmt can cancel, so absolute tolerance covers that scale
    np.testing.assert_allclose(np.asarray(om), np.asarray(rm),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ov), np.asarray(rv),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("nesterov", [False, True])
@pytest.mark.parametrize("n", [127, 2048, 65536 + 3])
def test_fused_compensate_bf16_state(n, nesterov):
    """bf16 error-feedback state: kernel output must match the jnp
    reference BITWISE (one f32-math pass, one round-to-nearest per stored
    value — no FMA ambiguity survives the bf16 rounding at these
    magnitudes), and must equal the all-f32 result after rounding the
    inputs up/down at the same points."""
    rng = np.random.RandomState(n)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    m = jnp.asarray(rng.randn(n), jnp.bfloat16)
    v = jnp.asarray(rng.randn(n), jnp.bfloat16)
    om, ov = kernels.fused_compensate(g, m, v, 0.9, nesterov)
    rm, rv = kernels.fused_compensate_reference(g, m, v, 0.9, nesterov)
    assert om.dtype == jnp.bfloat16 and ov.dtype == jnp.bfloat16
    f32 = lambda x: np.asarray(x, np.float32)
    np.testing.assert_allclose(f32(om), f32(rm), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(f32(ov), f32(rv), rtol=1e-2, atol=1e-2)
    # the f32-math contract: compute in f32 from the upcast state, round
    # the outputs once
    em, ev = kernels.fused_compensate_reference(
        g, m.astype(jnp.float32), v.astype(jnp.float32), 0.9, nesterov)
    np.testing.assert_array_equal(f32(rm), f32(em.astype(jnp.bfloat16)))
    np.testing.assert_array_equal(f32(rv), f32(ev.astype(jnp.bfloat16)))


@pytest.mark.parametrize("nesterov", [False, True])
def test_fused_compensate_masked_bf16_state(nesterov):
    """Masked variant with bf16 state: matches its reference and the
    eager mask-then-compensate composition at bf16 precision."""
    n = 2048 + 640
    rng = np.random.RandomState(5)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    m = jnp.asarray(rng.randn(n), jnp.bfloat16)
    v = jnp.asarray(rng.randn(n), jnp.bfloat16)
    sent = jnp.asarray(rng.rand(n) < 0.3, jnp.float32)
    om, ov = kernels.fused_compensate_masked(g, m, v, sent, 0.9, nesterov,
                                             True)
    rm, rv = kernels.fused_compensate_masked_reference(
        g, m, v, sent, 0.9, nesterov, True)
    assert om.dtype == jnp.bfloat16 and ov.dtype == jnp.bfloat16
    f32 = lambda x: np.asarray(x, np.float32)
    np.testing.assert_allclose(f32(om), f32(rm), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(f32(ov), f32(rv), rtol=1e-2, atol=1e-2)
    keep = kernels.keep_from_sent(sent).astype(jnp.bfloat16)
    em, ev = kernels.fused_compensate_reference(g, m * keep, v * keep,
                                                0.9, nesterov)
    np.testing.assert_array_equal(f32(rm), f32(em))
    np.testing.assert_array_equal(f32(rv), f32(ev))


@pytest.mark.parametrize("momentum_masking", [False, True])
@pytest.mark.parametrize("nesterov", [False, True])
@pytest.mark.parametrize("n", [127, 1024, 65536 + 3])
def test_fused_compensate_masked_matches_reference(n, nesterov,
                                                   momentum_masking):
    """The mask-on-read kernel body must run (interpret mode) and match its
    reference across all nesterov/momentum_masking combinations, and the
    combined op must equal eager mask-then-compensate."""
    rng = np.random.RandomState(n + 7)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    m = jnp.asarray(rng.randn(n), jnp.float32)
    v = jnp.asarray(rng.randn(n), jnp.float32)
    # sent = transmit counts (0 = keep); keep = (sent == 0)
    sent = jnp.asarray(rng.rand(n) < 0.3, jnp.float32)
    keep = kernels.keep_from_sent(sent)
    om, ov = kernels.fused_compensate_masked(g, m, v, sent, 0.9, nesterov,
                                             momentum_masking)
    rm, rv = kernels.fused_compensate_masked_reference(
        g, m, v, sent, 0.9, nesterov, momentum_masking)
    np.testing.assert_allclose(np.asarray(om), np.asarray(rm),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ov), np.asarray(rv),
                               rtol=1e-6, atol=1e-6)
    # deferred == eager: masking the buffers first then compensating
    em, ev = kernels.fused_compensate_reference(
        g, m * keep if momentum_masking else m, v * keep, 0.9, nesterov)
    np.testing.assert_allclose(np.asarray(om), np.asarray(em),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ov), np.asarray(ev),
                               rtol=1e-6, atol=1e-6)


def _random_indices(rng, n, frac=0.01):
    k = max(1, int(n * frac))
    return jnp.asarray(rng.choice(n, k, replace=False).astype(np.int32))


@pytest.mark.parametrize("n", [4096, 3 * 4096, 65536 + 2048])
def test_pack_sent_bits_roundtrip(n):
    """pack -> unpack must reproduce the transmitted set exactly,
    including the half-aligned tail case (n % 4096 == 2048: phantom rows
    in the last word group never get bits)."""
    rng = np.random.RandomState(n)
    idx = _random_indices(rng, n, 0.03)
    bits = kernels.pack_sent_bits(idx, n)
    assert bits.dtype == jnp.int32
    assert bits.shape == (kernels.num_sent_words(n),)
    keep = np.asarray(kernels.keep_from_bits(bits, n))
    expect = np.ones((n,), np.float32)
    expect[np.asarray(idx)] = 0.0
    np.testing.assert_array_equal(keep, expect)


def test_pack_sent_bits_drops_sentinel():
    """Padded payload slots all carry the sentinel index; repeated
    single-bit adds there would carry into neighboring rows' bits, so
    the sentinel must be dropped outright."""
    n = 4096
    sentinel = 130
    idx = jnp.asarray([5, sentinel, sentinel, sentinel, 700], jnp.int32)
    bits = kernels.pack_sent_bits(idx, n, sentinel=sentinel)
    keep = np.asarray(kernels.keep_from_bits(bits, n))
    assert keep[5] == 0.0 and keep[700] == 0.0
    assert keep[sentinel] == 1.0              # dropped, not recorded
    assert keep.sum() == n - 2


@pytest.mark.parametrize("momentum_masking", [False, True])
@pytest.mark.parametrize("nesterov", [False, True])
@pytest.mark.parametrize("n", [4096, 2 * 4096 + 2048, 65536])
def test_fused_compensate_bits_matches_masked(n, nesterov,
                                              momentum_masking):
    """The bit-packed kernel must equal its jnp reference AND the f32
    count-vector kernel on the same transmitted set (the packed record
    replaces the count vector bitwise)."""
    rng = np.random.RandomState(n + 11)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    m = jnp.asarray(rng.randn(n), jnp.float32)
    v = jnp.asarray(rng.randn(n), jnp.float32)
    idx = _random_indices(rng, n, 0.02)
    sent = jnp.zeros((n,), jnp.float32).at[idx].add(1.0)
    bits = kernels.pack_sent_bits(idx, n)
    om, ov = kernels.fused_compensate_bits(g, m, v, bits, 0.9, nesterov,
                                           momentum_masking)
    rm, rv = kernels.fused_compensate_bits_reference(
        g, m, v, bits, 0.9, nesterov, momentum_masking)
    np.testing.assert_allclose(np.asarray(om), np.asarray(rm),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ov), np.asarray(rv),
                               rtol=1e-6, atol=1e-6)
    em, ev = kernels.fused_compensate_masked_reference(
        g, m, v, sent, 0.9, nesterov, momentum_masking)
    np.testing.assert_array_equal(np.asarray(rm), np.asarray(em))
    np.testing.assert_array_equal(np.asarray(rv), np.asarray(ev))


@pytest.mark.parametrize("nesterov", [False, True])
def test_fused_compensate_bits_bf16_state(nesterov):
    """Bit-packed masking with the narrow bf16 error-feedback state:
    matches its reference bitwise and the count-vector reference."""
    n = 4096 + 2048
    rng = np.random.RandomState(17)
    g = jnp.asarray(rng.randn(n), jnp.float32)
    m = jnp.asarray(rng.randn(n), jnp.bfloat16)
    v = jnp.asarray(rng.randn(n), jnp.bfloat16)
    idx = _random_indices(rng, n, 0.05)
    sent = jnp.zeros((n,), jnp.float32).at[idx].add(1.0)
    bits = kernels.pack_sent_bits(idx, n)
    om, ov = kernels.fused_compensate_bits(g, m, v, bits, 0.9, nesterov,
                                           True)
    rm, rv = kernels.fused_compensate_bits_reference(
        g, m, v, bits, 0.9, nesterov, True)
    assert om.dtype == jnp.bfloat16 and ov.dtype == jnp.bfloat16
    f32 = lambda x: np.asarray(x, np.float32)
    np.testing.assert_allclose(f32(om), f32(rm), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(f32(ov), f32(rv), rtol=1e-2, atol=1e-2)
    em, ev = kernels.fused_compensate_masked_reference(
        g, m, v, sent, 0.9, nesterov, True)
    np.testing.assert_array_equal(f32(rm), f32(em))
    np.testing.assert_array_equal(f32(rv), f32(ev))


@pytest.mark.parametrize("shape", [(1, 64), (3, 128), (5, 1000), (16, 4096)])
def test_ladder_counts_matches_reference(shape):
    rng = np.random.RandomState(shape[1])
    imp = np.abs(rng.randn(*shape)).astype(np.float32)
    # padding slots, as the engine produces them
    imp[:, -3:] = -1.0
    thr = np.abs(rng.randn(shape[0])).astype(np.float32) * 0.5
    got = kernels.ladder_counts(jnp.asarray(imp), jnp.asarray(thr), 0.8, 11)
    ref = kernels.ladder_counts_reference(jnp.asarray(imp), jnp.asarray(thr),
                                          0.8, 11)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_ladder_counts_zero_threshold():
    """All-zero gradients: thr == 0, every non-padded element passes every
    level (imp 0 >= 0), padding (-1) never counts."""
    imp = jnp.concatenate([jnp.zeros((2, 10)), -jnp.ones((2, 2))], axis=1)
    thr = jnp.zeros((2,))
    got = np.asarray(kernels.ladder_counts(imp, thr, 0.8, 5))
    assert (got == 10).all()


def test_ladder_adapt_matches_sequential_oracle():
    """The closed-form ladder pick must equal the reference's sequential
    adaptation loop (ops.adapt_threshold with resample=True) row by row."""
    from dgc_tpu.compression.flat import _ladder_adapt
    from dgc_tpu.ops import sparsify as ops

    rng = np.random.RandomState(7)
    R, N = 6, 2000
    imp = np.abs(rng.randn(R, N)).astype(np.float32)
    num_selects = np.full((R,), 20, np.float32)
    # thresholds engineered too high so adaptation must lower them by
    # varying amounts
    thr0 = np.array([np.sort(imp[r])[-3] for r in range(R)], np.float32)
    max_iters = 10

    got = np.asarray(_ladder_adapt(
        jnp.asarray(imp), jnp.asarray(thr0), jnp.asarray(num_selects),
        jnp.ones((R,), bool), 0.8, max_iters))

    for r in range(R):
        want = np.asarray(ops.adapt_threshold(
            jnp.asarray(imp[r]), jnp.asarray(thr0[r]), 20, 0.8, 1.3,
            max_iters, resample=True))
        # sequential loop multiplies cumulatively; ladder uses lb**i —
        # identical picks, float tolerance on the power
        np.testing.assert_allclose(got[r], want, rtol=1e-5)


def test_flat_sparsify_with_adaptation_transmits_enough():
    """End-to-end through the engine: a distribution that defeats the
    sampled threshold still transmits >= lower_bound * num_selects after
    ladder adaptation (the reference's adaptation goal)."""
    from dgc_tpu import DGCCompressor, DGCSGDMemory, DistributedOptimizer, dgc_sgd

    rng = np.random.RandomState(3)
    # heavy-tailed: strided samples overestimate the top-k threshold
    base = np.abs(rng.randn(64, 64)).astype(np.float32)
    base.reshape(-1)[rng.choice(4096, 40, replace=False)] *= 50.0
    params = {"w": jnp.asarray(base)}
    comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=0.01)
    comp.initialize([("w", params["w"])])
    dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
    layout, engine = dist.make_flat(params)
    a = comp.attributes["w"]
    vec = np.zeros((layout.t_compressed,), np.float32)
    off = layout.offsets["w"]
    vec[off:off + layout.sizes["w"]] = base.reshape(-1)
    vals, idx = jax.jit(engine.sparsify)(jnp.asarray(vec),
                                         jax.random.PRNGKey(0))
    valid = np.asarray(idx) < layout.t_data
    assert valid.sum() >= int(0.8 * a.num_selects) - 1


@pytest.mark.parametrize("shape,k", [((8, 256), 1), ((8, 256), 37),
                                     ((5, 300), 10), ((16, 1024), 40),
                                     ((8, 128), 128)])
def test_topk_rows_matches_lax_top_k(shape, k):
    """topk_rows must equal jax.lax.top_k exactly: descending values, ties
    broken by first occurrence — on aligned and ragged shapes."""
    from dgc_tpu.ops.kernels import topk_rows

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    v, i = topk_rows(x, k)
    v_ref, i_ref = jax.lax.top_k(x, k)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_topk_rows_tie_order():
    """Duplicated values must come out in ascending index order, exactly as
    lax.top_k orders them."""
    from dgc_tpu.ops.kernels import topk_rows

    x = jnp.asarray([[1.0, 3.0, 3.0, 0.0, 3.0, -1.0, 2.0, 2.0]] * 8,
                    jnp.float32)
    v, i = topk_rows(x, 6)
    v_ref, i_ref = jax.lax.top_k(x, 6)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_topk_rows_fallback_large():
    """Rows beyond the VMEM budget (or k > lane width) fall back to
    lax.top_k and stay correct."""
    from dgc_tpu.ops.kernels import topk_rows

    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(2, 2 * 1024 * 1024 // 8), jnp.float32)
    v, i = topk_rows(x, 5)
    v_ref, i_ref = jax.lax.top_k(x, 5)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    x2 = jnp.asarray(rng.randn(4, 512), jnp.float32)
    v2, i2 = topk_rows(x2, 200)       # k > lane width
    v2_ref, i2_ref = jax.lax.top_k(x2, 200)
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(v2_ref))


def test_topk_rows_with_neg_inf_entries():
    """Rows containing real -inf values (and k reaching into them) must
    still match lax.top_k exactly: ascending-index extraction over the
    remaining -inf slots, no duplicate indices."""
    from dgc_tpu.ops.kernels import topk_rows

    ninf = -np.inf
    x = jnp.asarray([[5.0, ninf, 3.0, ninf, 1.0, 0.0, -1.0, 2.0]] * 8,
                    jnp.float32)
    v, i = topk_rows(x, 8)
    v_ref, i_ref = jax.lax.top_k(x, 8)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    assert len(set(np.asarray(i)[0].tolist())) == 8  # no duplicates


def test_topk_rows_k_exceeding_cols_raises_like_lax():
    """cols < k <= lane width must not silently return pad indices — the
    guard delegates to lax.top_k, which raises."""
    from dgc_tpu.ops.kernels import topk_rows

    x = jnp.zeros((8, 100), jnp.float32)
    with pytest.raises(ValueError):
        topk_rows(x, 110)


@pytest.mark.parametrize("shape,k", [((3, 40), 5), ((8, 128), 128),
                                     ((5, 300), 7), ((12, 64), 64),
                                     ((1, 16), 3)])
def test_select_pack_rows_matches_reference(shape, k):
    """The fused threshold->select->pack kernel must match the unfused
    reference (masked |x| top_k + take_along_axis) bitwise: scores,
    signed values, AND column order — the wire format depends on all
    three."""
    from dgc_tpu.ops.kernels import (select_pack_rows,
                                     select_pack_rows_reference)

    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    numels = jnp.asarray(
        rng.randint(max(1, k), shape[1] + 1, shape[0]), jnp.int32)
    s, v, i = select_pack_rows(x, numels, k)
    s_ref, v_ref, i_ref = select_pack_rows_reference(x, numels, k)
    np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_select_pack_rows_ragged_rows_never_select_pad():
    """Slots at/beyond a row's numel are structural zeros: even when every
    real entry is tiny, the kernel must keep selecting real columns (the
    masked importance is -1 there, below any |real| value >= 0)."""
    from dgc_tpu.ops.kernels import select_pack_rows

    x = jnp.full((4, 24), 1e-30, jnp.float32)
    numels = jnp.asarray([5, 24, 1, 8], jnp.int32)
    k = 4
    s, v, i = select_pack_rows(x, numels, k)
    i = np.asarray(i)
    numels_np = np.asarray(numels)
    for r in range(4):
        kr = min(k, int(numels_np[r]))
        assert (i[r, :kr] < numels_np[r]).all()


def test_select_pack_rows_bf16_values():
    """bf16 inputs recurse through the f32 path; returned signed values
    keep the input dtype and equal the gathered originals."""
    from dgc_tpu.ops.kernels import (select_pack_rows,
                                     select_pack_rows_reference)

    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(6, 48), jnp.bfloat16)
    numels = jnp.full((6,), 48, jnp.int32)
    s, v, i = select_pack_rows(x, numels, 9)
    s_ref, v_ref, i_ref = select_pack_rows_reference(x, numels, 9)
    assert v.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(v.astype(jnp.float32)),
                                  np.asarray(v_ref.astype(jnp.float32)))


def test_select_pack_rows_large_k_stays_exact():
    """k past the lane width routes to the chunked multi-round kernel
    (NOT the reference — tests/test_megakernel.py asserts the
    non-delegation); past _MR_MAX_K the reference takes over. Both
    regimes stay exact."""
    from dgc_tpu.ops.kernels import (_MR_MAX_K, select_pack_rows,
                                     select_pack_rows_reference)

    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(2, 2048), jnp.float32)
    numels = jnp.asarray([2048, 1500], jnp.int32)
    for k in (200, _MR_MAX_K + 1):
        s, v, i = select_pack_rows(x, numels, k)
        s_ref, v_ref, i_ref = select_pack_rows_reference(x, numels, k)
        np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))
        np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_seg_top2_kernel_matches_reference(dtype):
    """seg_top2_candidates (interpret mode on CPU) == seg_top2_reference
    bitwise — the same compiled-vs-reference contract every other kernel
    carries (tpu_check.py re-asserts it compiled on the real chip). Runs
    the pallas_call path explicitly, since the engine picks the reference
    off-TPU and would otherwise leave the kernel body unexercised by CI.
    Covers base != 0 (BlockSpec offset arithmetic), multi-row, ties, a
    structural-zero tail, and the narrow (bf16) state input — both ends
    up-cast in the same place, so outputs are f32 and bitwise equal."""
    from dgc_tpu.ops import kernels

    span = kernels._SEG_BLOCKS * 128
    rng = np.random.RandomState(7)
    base, rows, cols = span, 2, 2 * span
    vec = rng.randn(base + rows * cols + span).astype(np.float32)
    vec[base + cols - span // 2:base + cols] = 0.0   # a zero tail region
    # force ties inside one segment: equal |values| at two blocks
    vec[base + 5 * 128 + 3] = 9.0
    vec[base + 9 * 128 + 3] = -9.0
    v2d = jnp.asarray(vec, dtype).reshape(-1, 128)
    cvk, cck = kernels.seg_top2_candidates(v2d, base, rows, cols)
    cvr, ccr = kernels.seg_top2_reference(v2d, base, rows, cols)
    assert cvk.dtype == jnp.float32 and cvr.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(cvk), np.asarray(cvr))
    np.testing.assert_array_equal(np.asarray(cck), np.asarray(ccr))
    # the tie resolved to the FIRST block (lax.top_k order) and the
    # second slot holds the other of the pair
    nseg = cols // span
    cv4 = np.asarray(cvk).reshape(rows, nseg, 2, 128)
    cc4 = np.asarray(cck).reshape(rows, nseg, 2, 128)
    assert cv4[0, 0, 0, 3] == 9.0 and cv4[0, 0, 1, 3] == -9.0
    assert cc4[0, 0, 0, 3] == 5 * 128 + 3
    assert cc4[0, 0, 1, 3] == 9 * 128 + 3


@pytest.mark.parametrize("nesterov,masking", [(False, True), (True, False)])
@pytest.mark.parametrize("sdt", [jnp.float32, jnp.bfloat16])
def test_fused_compensate_bits_cands_matches_composition(nesterov, masking,
                                                         sdt):
    """The fused compensate+candidates kernel (interpret mode on CPU) ==
    (fused_compensate_bits_reference, then seg_top2_reference over the
    stored velocity) bitwise — state updates AND candidates. Covers a
    grad buffer LONGER than the state (the engine passes the whole flat
    [P] so no [:T] operand slice is materialized), the bf16 state
    round-trip, and both compensate variants."""
    from dgc_tpu.ops import kernels

    span = kernels._SEG_BLOCKS * 128
    n = 3 * span                       # 3 complete segments
    rng = np.random.RandomState(11)
    grad = jnp.asarray(rng.randn(n + 2048).astype(np.float32))
    mmt = jnp.asarray(rng.randn(n).astype(np.float32), sdt)
    vec = jnp.asarray(rng.randn(n).astype(np.float32), sdt)
    idx = jnp.asarray(rng.choice(n, 500, replace=False).astype(np.int32))
    bits = kernels.pack_sent_bits(idx, n)

    om, ov, cv, ci = kernels.fused_compensate_bits_cands(
        grad, mmt, vec, bits, 0.9, nesterov, masking)
    # the state contract: bitwise the plain bits KERNEL this fused form
    # replaces (kernel-vs-jnp-reference parity for the compensate math is
    # the plain kernel's own test; at some sizes XLA CPU's fusion of the
    # nesterov multiply-add chain differs by ULPs between the two
    # programs, a pre-existing interpret-mode wobble that the engine
    # never sees: CPU runs the references, TPU runs the kernels and
    # tpu_check pins compiled==interpret)
    omr, ovr = kernels.fused_compensate_bits(
        grad[:n], mmt, vec, bits, 0.9, nesterov, masking)
    np.testing.assert_array_equal(np.asarray(om), np.asarray(omr))
    np.testing.assert_array_equal(np.asarray(ov), np.asarray(ovr))
    # candidates == the standalone kernel's over the STORED velocity,
    # viewed as one row spanning the whole region
    cvr, ccr = kernels.seg_top2_reference(ovr.reshape(-1, 128), 0, 1, n)
    nseg = n // span
    cv_flat = np.asarray(cv[:nseg]).reshape(1, -1)
    np.testing.assert_array_equal(cv_flat, np.asarray(cvr))
    # reference emits bucket-local columns; the fused kernel emits
    # per-segment block indices — recompose and compare
    lane = np.arange(128, dtype=np.int32)
    seg0 = (np.arange(nseg, dtype=np.int32)
            * kernels._SEG_BLOCKS)[None, :, None, None]
    cols = ((np.asarray(ci[:nseg]).reshape(1, nseg, 2, 128) + seg0) * 128
            + lane[None, None, None, :]).reshape(1, -1)
    np.testing.assert_array_equal(cols, np.asarray(ccr))


def test_fused_compensate_bits_cands_ragged_tail():
    """A state length that is NOT a whole number of segments: the state
    update must still be exact over all of [0, n); candidate segments
    fully inside the data must match the standalone reference (the
    straddling tail segment is unspecified and unused by the engine —
    eligible buckets end on segment boundaries)."""
    from dgc_tpu.ops import kernels

    span = kernels._SEG_BLOCKS * 128
    n = span + 16 * 128                # one complete segment + a tail
    rng = np.random.RandomState(3)
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    mmt = jnp.asarray(rng.randn(n).astype(np.float32))
    vec = jnp.asarray(rng.randn(n).astype(np.float32))
    bits = kernels.pack_sent_bits(
        jnp.asarray(rng.choice(n, 64, replace=False).astype(np.int32)), n)
    om, ov, cv, ci = kernels.fused_compensate_bits_cands(
        grad, mmt, vec, bits, 0.9, False, True)
    omr, ovr = kernels.fused_compensate_bits_reference(
        grad, mmt, vec, bits, 0.9, False, True)
    np.testing.assert_array_equal(np.asarray(om), np.asarray(omr))
    np.testing.assert_array_equal(np.asarray(ov), np.asarray(ovr))
    cvr, ccr = kernels.seg_top2_reference(ovr.reshape(-1, 128), 0, 1, span)
    np.testing.assert_array_equal(np.asarray(cv[0]).reshape(1, -1),
                                  np.asarray(cvr))
    lane = np.arange(128, dtype=np.int32)
    cols = (np.asarray(ci[0]).reshape(1, 2, 128) * 128
            + lane[None, None, :]).reshape(1, -1)
    np.testing.assert_array_equal(cols, np.asarray(ccr))


def test_seg_top2_eligible_bounds():
    """Eligibility rejects regions that would read past the buffer end
    (rows > 1 must be accounted for) and misaligned bases/widths."""
    from dgc_tpu.ops import kernels

    span = kernels._SEG_BLOCKS * 128
    blocks = (4 * span) // 128
    assert kernels.seg_top2_eligible(blocks, 0, span, rows=4)
    assert not kernels.seg_top2_eligible(blocks, 0, span, rows=5)
    assert not kernels.seg_top2_eligible(blocks, span + 128, span, rows=1)
    assert not kernels.seg_top2_eligible(blocks, 0, span + 128, rows=1)


def test_opaque_view_identity_and_grad():
    """opaque_view is a bitwise identity with an identity backward —
    the convert-hoisting guard must not change the differentiated
    function (training/step.py's guarded unpack)."""
    from dgc_tpu.ops import kernels

    rng = np.random.RandomState(3)
    for shape in [(3, 3, 64, 64), (13, 7), (1024,)]:
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        np.testing.assert_array_equal(np.asarray(kernels.opaque_view(x)),
                                      np.asarray(x))
        g = jax.grad(lambda a: jnp.sum(kernels.opaque_view(a) ** 2))(x)
        np.testing.assert_array_equal(np.asarray(g), 2 * np.asarray(x))


def test_opaque_view_from_matches_slice():
    """opaque_view_from streams flat[base:base+numel] without an operand
    slice; forward is bitwise the slice, backward is its exact transpose
    (zeros + dynamic_update_slice), including under jit."""
    from dgc_tpu.ops import kernels

    rng = np.random.RandomState(4)
    total = 64 * 1024
    flat = jnp.asarray(rng.randn(total).astype(np.float32))
    for base, numel in [(0, 1024), (2048, 3 * 1024), (31 * 1024, 33 * 1024)]:
        assert kernels.opaque_view_eligible(total, base, numel)
        out = kernels.opaque_view_from(flat, base, numel)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(flat[base:base + numel]))
        g = jax.jit(jax.grad(
            lambda f: jnp.sum(kernels.opaque_view_from(f, base, numel) ** 2)
        ))(flat)
        ref = np.zeros(total, np.float32)
        ref[base:base + numel] = 2 * np.asarray(flat)[base:base + numel]
        np.testing.assert_array_equal(np.asarray(g), ref)
    # misalignment and overrun are rejected
    assert not kernels.opaque_view_eligible(total, 128, 1024)
    assert not kernels.opaque_view_eligible(total, total - 1024, 2048)


@pytest.mark.parametrize("total", [
    45 * 4096 + 2048,                       # single ragged chunk
    2 * 2048 * 128 + 37 * 4096 + 2048,      # multi-chunk, ragged tail
])
def test_payload_apply_bits_matches_reference(total):
    """The fused apply epilogue vs the jnp reference (the engine's XLA
    scatter pair): with unique real indices any scatter order agrees, so
    acc is BITWISE and the transmit record exact — including empty
    chunks, a stale (garbage) donated record buffer, and sentinel-style
    zero-value pad entries."""
    from dgc_tpu.ops import kernels

    rng = np.random.RandomState(11)
    n = 4000
    idx = jnp.asarray(rng.choice(total, n, replace=False).astype(np.int32))
    vals = jnp.asarray(rng.randn(n).astype(np.float32))
    flags = jnp.asarray((rng.rand(n) < 0.4).astype(np.int32))
    acc_r, bits_r = jax.jit(
        lambda v, i, f: kernels.payload_apply_bits_reference(
            v, i, f, total))(vals, idx, flags)
    acc_k, bits_k = jax.jit(
        lambda v, i, f: kernels.payload_apply_bits(
            v, i, f, total))(vals, idx, flags)
    np.testing.assert_array_equal(np.asarray(acc_k), np.asarray(acc_r))
    np.testing.assert_array_equal(np.asarray(bits_k), np.asarray(bits_r))

    # the donated previous-step record must never leak: fill it with
    # garbage and require the identical fresh record
    donor = jnp.asarray(rng.randint(
        -2**31, 2**31 - 1, size=kernels.num_sent_words(total),
        dtype=np.int64).astype(np.int32))
    acc_d, bits_d = jax.jit(
        lambda v, i, f, d: kernels.payload_apply_bits(
            v, i, f, total, bits_donor=d))(vals, idx, flags, donor)
    np.testing.assert_array_equal(np.asarray(acc_d), np.asarray(acc_r))
    np.testing.assert_array_equal(np.asarray(bits_d), np.asarray(bits_r))

    # sentinel-style pads: repeated index, zero value, flag 0 — no-ops
    sent = total - 1
    idx2 = jnp.concatenate([idx, jnp.full((300,), sent, jnp.int32)])
    v2 = jnp.concatenate([vals, jnp.zeros((300,), jnp.float32)])
    f2 = jnp.concatenate([flags, jnp.zeros((300,), jnp.int32)])
    acc_s, bits_s = jax.jit(
        lambda v, i, f: kernels.payload_apply_bits(
            v, i, f, total))(v2, idx2, f2)
    np.testing.assert_array_equal(np.asarray(acc_s), np.asarray(acc_r))
    np.testing.assert_array_equal(np.asarray(bits_s), np.asarray(bits_r))


def test_payload_apply_bits_duplicates_and_empty_chunks():
    """Cross-worker duplicate coordinates: the staged adds run in
    stable sorted order (payload order within a coordinate), summing the
    same contribution sets as the reference scatter — within one f32
    rounding; the record (an OR over unique local coordinates) stays
    exact. A chunk with no payload at all must come back all-zero."""
    from dgc_tpu.ops import kernels

    rng = np.random.RandomState(13)
    total = 3 * 2048 * 128
    base = rng.choice(4096, 500, replace=False)
    # worker-style duplication: same coordinates contributed 3x, plus a
    # block landing only in the LAST chunk, leaving the middle one empty
    idx = np.concatenate([base, base, base,
                          2 * 2048 * 128 + rng.choice(4096, 200,
                                                      replace=False)])
    vals = rng.randn(idx.size).astype(np.float32)
    flags = np.zeros(idx.size, np.int32)
    flags[:500] = 1
    idx, vals, flags = (jnp.asarray(idx.astype(np.int32)),
                        jnp.asarray(vals), jnp.asarray(flags))
    acc_r, bits_r = jax.jit(
        lambda v, i, f: kernels.payload_apply_bits_reference(
            v, i, f, total))(vals, idx, flags)
    acc_k, bits_k = jax.jit(
        lambda v, i, f: kernels.payload_apply_bits(
            v, i, f, total))(vals, idx, flags)
    np.testing.assert_allclose(np.asarray(acc_k), np.asarray(acc_r),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(bits_k), np.asarray(bits_r))
    # empty middle chunk: all-zero despite never receiving an entry
    mid = np.asarray(acc_k[2048 * 128:2 * 2048 * 128])
    assert not mid.any()
