"""Worker program for the 2-process gossip drill
(tests/test_multiprocess.py::test_gossip_two_process_save_resume).

Two phases, each a 2-process ``jax.distributed`` launch over the same
checkpoint directory, both with the SAME ``DGC_FAULTS`` armed (the
``droplink`` injector is traced into the program, so every process must
compile the identical graph):

* ``run`` — build the fleet train step under a ``gossip_ring`` plan
  (``sync_every=4``, ``max_staleness=4``) with
  ``DGC_FAULTS=droplink:peer=3@1-5`` armed: worker 3's contribution is
  suppressed for gossip rounds 1..5, so the staleness bound breaches and
  the engine forces full-sync rounds at exactly clocks 5 and 6 (the
  test_gossip.py step-exact arithmetic, now over a real process
  boundary). Train TOTAL_STEPS steps, write every fleet record —
  including the ``w_staleness`` lane and the forced-sync counter —
  through a per-host :class:`TelemetrySink` shard, and save one
  collective checkpoint after SAVE_STEP steps (mid-drill: the gossip
  clock, ages, forced counter, and in-flight inbox all ride the raw
  memory tree).
* ``resume`` — restore the checkpoint, fingerprint the restored gossip
  round state (must be bitwise the run phase's at the save point), and
  train the remaining steps: the loss trajectory and the final gossip
  fingerprint must match the uninterrupted run exactly.

Prints one RESULT: JSON line per process for the parent to compare.
"""

import hashlib
import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")
if "jax_cpu_collectives_implementation" in jax.config.values:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TOTAL_STEPS = 8
SAVE_STEP = 5          # completed steps before the collective save
GOSSIP_KEYS = ("gossip_clock", "gossip_age", "gossip_forced",
               "gossip_inbox")


def main():
    proc_id = int(sys.argv[1])
    num_procs = int(sys.argv[2])
    coord = sys.argv[3]
    workdir = sys.argv[4]
    phase = sys.argv[5]
    assert phase in ("run", "resume"), phase

    from dgc_tpu.parallel.multihost import (host_local_to_global,
                                            initialize_multihost)

    import getpass
    import tempfile
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(tempfile.gettempdir(),
                                   f"dgc_tpu_test_jax_cache_"
                                   f"{getpass.getuser()}"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    os.environ["JAX_COORDINATOR_ADDRESS"] = coord
    os.environ["JAX_NUM_PROCESSES"] = str(num_procs)
    os.environ["JAX_PROCESS_ID"] = str(proc_id)
    assert initialize_multihost(initialization_timeout=600,
                                heartbeat_timeout_seconds=600,
                                shutdown_timeout_seconds=1200) is True
    assert jax.process_count() == num_procs

    import jax.numpy as jnp
    import numpy as np
    from flax import linen as nn
    from jax.sharding import Mesh

    from dgc_tpu import (DGCCompressor, DGCSGDMemory, DistributedOptimizer,
                         dgc_sgd)
    from dgc_tpu.compression import planner
    from dgc_tpu.telemetry import fleet
    from dgc_tpu.telemetry.sink import TelemetrySink
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)
    from dgc_tpu.training.checkpoint import CheckpointManager
    from dgc_tpu.utils.pytree import named_flatten

    W = len(jax.devices())
    assert W == 2 * 4
    mesh = Mesh(np.array(jax.devices()), ("data",))

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x.mean(axis=(1, 2)))

    model = M()
    v = dict(model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3))))

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        if mutable:
            return model.apply(variables, x, train=train, mutable=mutable,
                               rngs=rngs)
        return model.apply(variables, x, train=train)

    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    # the gossip plan (refit to the real bucket geometry inside
    # make_flat_setup); sync_every == max_staleness == 4 is the step-exact
    # droplink drill from tests/test_gossip.py
    plan = planner.plan_buckets(
        [], fabric="32x25GbE", world=W, candidates=("gossip_ring",),
        gossip_sync_every=4, gossip_max_staleness=4)
    setup = make_flat_setup(v, dist, plan=plan)
    assert setup.engine.plan.gossip is not None
    state = shard_state(make_flat_state(v, dist, setup, W), mesh,
                        dist_opt=dist)
    step_fn = build_train_step(apply_fn, dist, mesh, donate=False,
                               flat=setup, telemetry=True, fleet=True)

    run_dir = os.path.join(workdir, "gossiprun")
    # the resume phase replays steps the run already recorded; one clean
    # shard set keeps the fleet view unambiguous
    sink = None
    if phase == "run":
        sink = TelemetrySink(
            os.path.join(run_dir, "telemetry", f"host{proc_id}"),
            static=dict(setup.engine.telemetry_static(), world=W,
                        process_index=proc_id, num_processes=num_procs),
            fleet=True)

    bs = 4

    def batch(i):
        """Deterministic per-step global batch — identical in both phases,
        so the resumed run sees the uninterrupted run's data."""
        rng = np.random.RandomState(3000 + i)
        im = rng.randn(W * bs, 16, 16, 3).astype(np.float32)
        lb = rng.randint(0, 10, W * bs).astype(np.int32)
        return (host_local_to_global(im, mesh),
                host_local_to_global(lb, mesh))

    def fingerprint(tree):
        """sha256 over this process's addressable shard bytes, in a
        deterministic (path, shard-index) order."""
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        h = hashlib.sha256()
        for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
            if not hasattr(leaf, "addressable_shards"):
                h.update(np.asarray(leaf).tobytes())
                continue
            for s in sorted(leaf.addressable_shards,
                            key=lambda s: str(s.index)):
                h.update(np.asarray(s.data).tobytes())
        return h.hexdigest()

    def gossip_print(st):
        return fingerprint({k: st.memory[k] for k in GOSSIP_KEYS})

    def drive(st, lo, hi):
        """Train steps [lo, hi); return (state, losses, fleet columns).
        The clock input is a deterministic stamp, so both phases trace
        the identical fleet lanes."""
        losses, stale_cols, forced, seen = [], [], [], []
        for i in range(lo, hi):
            im, lb = batch(i)
            st, m = step_fn(st, im, lb, jax.random.PRNGKey(i),
                            fleet.make_clock(10.0 + i, mesh, W))
            losses.append(float(m["loss"]))
            flt = m["fleet"]
            stale_cols.append(
                [float(x) for x in np.asarray(flt["w_staleness"])])
            forced.append(float(flt["gossip_forced_syncs"]))
            seen.append(float(flt["max_staleness_seen"]))
            if sink is not None:
                sink.write(i, {**m["telemetry"], **m["fleet"],
                               "loss": m["loss"]})
            jax.block_until_ready(st)
        return st, losses, stale_cols, forced, seen

    ckpt = CheckpointManager(os.path.join(workdir, "ckpt_gossip"), keep=2)
    out = {"proc": proc_id, "phase": phase}

    if phase == "run":
        state, losses, stale, forced, seen = drive(state, 0, SAVE_STEP)
        out["gossip_saved"] = gossip_print(state)
        ckpt.save(0, state, {"gossip_batch": SAVE_STEP - 1})
        state, l2, s2, f2, m2 = drive(state, SAVE_STEP, TOTAL_STEPS)
        losses += l2
        stale += s2
        forced += f2
        seen += m2
        out.update(losses=losses, w_staleness=stale, forced=forced,
                   max_seen=seen, gossip_final=gossip_print(state),
                   mem_final=fingerprint(state.memory))

    else:  # resume
        restored = ckpt.restore(state)
        assert restored is not None, "gossip checkpoint must restore"
        r_state, r_epoch, meters = restored
        assert r_epoch == 0
        start = int(meters["gossip_batch"]) + 1
        out["gossip_restored"] = gossip_print(r_state)
        r_state, losses, stale, forced, seen = drive(
            r_state, start, TOTAL_STEPS)
        out.update(losses=losses, start=start, w_staleness=stale,
                   forced=forced, max_seen=seen,
                   gossip_final=gossip_print(r_state),
                   mem_final=fingerprint(r_state.memory))

    if sink is not None:
        sink.close()
    print("RESULT:" + json.dumps(out), flush=True)

    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(f"gossip_{phase}_done")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
