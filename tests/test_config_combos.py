"""Every DGC flag module must produce a buildable, runnable configuration:
compose configs exactly as the CLI does, build compressor/optimizer/engine,
and run one flat train step on the 8-way mesh (the reference's flag modules
wm0/wm5/wm5o/fp16/int32/nm/mm, configs/dgc/*.py)."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dgc_tpu.utils.config as cfgmod
from dgc_tpu.optim import DistributedOptimizer
from dgc_tpu.training import (
    build_train_step,
    make_flat_setup,
    make_flat_state,
    shard_state,
)
from dgc_tpu.utils.pytree import named_flatten

W = 8


@pytest.mark.parametrize("flag", ["wm0", "wm5", "wm5o", "fp16", "int32",
                                  "nm", "mm", "twotier", "bf16mem",
                                  "int8", "packidx"])
def test_dgc_flag_combo_runs_a_step(mesh8, flag, monkeypatch):
    # fresh global config tree per combo (the CLI process does this by
    # construction; tests must not leak state between combos)
    fresh = cfgmod.Config()
    monkeypatch.setattr(cfgmod, "configs", fresh)
    cfgmod.Config.update_from_modules(
        "configs/cifar/resnet20.py", f"configs/dgc/{flag}.py")
    configs = cfgmod.configs

    model = configs.model()
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                   train=True)
    named, _ = named_flatten(v["params"])
    memory = configs.train.compression.memory()
    comp = configs.train.compression(memory=memory)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    comp.warmup_compress_ratio(0)
    opt = configs.train.optimizer(lr=0.1)
    dist = DistributedOptimizer(opt, comp, world_size=W)
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh8,
                        dist_opt=dist)
    step = build_train_step(model.apply, dist, mesh8, flat=setup)

    rng = np.random.RandomState(1)
    images = jnp.asarray(rng.randn(W * 2, 32, 32, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, W * 2), jnp.int32)
    state, m = step(state, images, labels, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))

    # flag semantics actually took effect
    if flag == "fp16":
        assert comp.fp16_values
    if flag == "int32":
        # int32_indices is already the compressor default on TPU; assert the
        # flag module's assignment actually landed in the config tree
        assert configs.train.compression.int32_indices is True
        assert comp.int32_indices
    if flag == "nm":
        assert not memory.momentum_masking
    if flag == "mm":
        assert memory.momentum_masking
    if flag == "wm0":
        # no warm-up: the base ratio is in effect from epoch 0
        assert comp.warmup_epochs == 0 and comp.compress_ratio == 0.001
    if flag in ("wm5", "wm5o"):
        assert comp.compress_ratio > 0.001  # warm-up active at epoch 0
    if flag == "packidx":
        assert comp.packed_indices
        assert setup.engine._codec is not None
        assert setup.engine._codec.bits_per_index < 32
    if flag == "twotier":
        # harness-level flag (train.py builds the (hosts, local) mesh and
        # the hierarchical DistributedOptimizer from it; the exchange
        # itself is covered by tests/test_hierarchical.py)
        assert configs.train.num_local_workers == 8
