"""Unit tests for utils/profiling.exchange_report algebra and the
TopKClassMeter update/data/set/compute protocol (ISSUE 2 satellite).

exchange_report is the north-star accounting bench.py prints — its wire
model must obey the ring-allreduce / sparse-allgather identities exactly,
because docs/RESULTS.md quotes its speedup column. TopKClassMeter is the
reference harness's accuracy meter; its data/set round-trip is what the
cross-worker Sum reduction relies on.
"""

import numpy as np
import pytest

from dgc_tpu.utils.meters import TopKClassMeter
from dgc_tpu.utils.profiling import exchange_report


# --------------------------------------------------------------------- #
# exchange_report                                                        #
# --------------------------------------------------------------------- #

def test_exchange_report_wire_model_formulas():
    P, W, gbps = 1_000_000, 8, 100.0
    payload = 1000
    r = exchange_report(dgc_ms=2.0, dense_ms=1.5, payload_elems=payload,
                        num_params=P, workers=W, fabric_gbps=gbps)
    # ring allreduce moves 2*(W-1)/W * 4 bytes per param
    dense_bytes = 2 * 4 * P * (W - 1) / W
    assert r["dense_exchange_ms"] == pytest.approx(
        dense_bytes / (gbps * 1e9) * 1e3)
    # sparse allgather moves (W-1) * payload * (4B value + 4B index)
    sparse_bytes = (W - 1) * payload * 8
    assert r["dgc_wire_ms"] == pytest.approx(
        sparse_bytes / (gbps * 1e9) * 1e3)
    assert r["wire_reduction"] == pytest.approx(dense_bytes / sparse_bytes)


def test_exchange_report_identities():
    r = exchange_report(dgc_ms=3.25, dense_ms=2.0, payload_elems=512,
                        num_params=500_000, workers=4, fabric_gbps=50.0)
    # measured overhead is the paired step-time difference
    assert r["dgc_compute_overhead_ms"] == pytest.approx(3.25 - 2.0)
    # total dgc exchange = compute overhead + modeled wire time
    assert r["dgc_exchange_ms"] == pytest.approx(
        r["dgc_compute_overhead_ms"] + r["dgc_wire_ms"])
    # speedup is defined against that total
    assert r["speedup"] * r["dgc_exchange_ms"] == pytest.approx(
        r["dense_exchange_ms"])


def test_exchange_report_negative_overhead_clamps():
    # DGC arm measured faster than dense (noise): overhead clamps to 0 so
    # the exchange total is pure wire time, never negative.
    r = exchange_report(dgc_ms=1.0, dense_ms=2.0, payload_elems=100,
                        num_params=100_000, workers=8, fabric_gbps=100.0)
    assert r["dgc_compute_overhead_ms"] == 0.0
    assert r["dgc_exchange_ms"] == pytest.approx(r["dgc_wire_ms"])
    assert r["speedup"] > 0


def test_exchange_report_zero_payload_no_div_by_zero():
    r = exchange_report(dgc_ms=1.0, dense_ms=1.0, payload_elems=0,
                        num_params=100_000, workers=8, fabric_gbps=100.0)
    assert np.isfinite(r["wire_reduction"])
    assert r["dgc_wire_ms"] == 0.0


def test_exchange_report_wire_reduction_tracks_ratio():
    # halving the payload doubles the wire reduction (pure algebra)
    kw = dict(dgc_ms=1.0, dense_ms=1.0, num_params=1_000_000, workers=8,
              fabric_gbps=100.0)
    r1 = exchange_report(payload_elems=2000, **kw)
    r2 = exchange_report(payload_elems=1000, **kw)
    assert r2["wire_reduction"] == pytest.approx(2 * r1["wire_reduction"])


# --------------------------------------------------------------------- #
# TopKClassMeter                                                         #
# --------------------------------------------------------------------- #

def test_topk_meter_top1_known_batch():
    m = TopKClassMeter(k=1)
    outputs = np.array([[0.1, 0.9, 0.0],    # pred 1
                        [0.8, 0.1, 0.1],    # pred 0
                        [0.2, 0.3, 0.5]])   # pred 2
    targets = np.array([1, 2, 2])           # correct, wrong, correct
    m.update(outputs, targets)
    assert m.data() == {"num_correct": 2, "num_examples": 3}
    assert m.compute() == pytest.approx(100.0 * 2 / 3)


def test_topk_meter_top2_catches_runner_up():
    m = TopKClassMeter(k=2)
    outputs = np.array([[0.5, 0.4, 0.1],    # top2 {0,1}
                        [0.1, 0.2, 0.7]])   # top2 {1,2}
    targets = np.array([1, 0])              # in top2, not in top2
    m.update(outputs, targets)
    assert m.compute() == pytest.approx(50.0)


def test_topk_meter_k_clamped_to_num_classes():
    m = TopKClassMeter(k=10)
    outputs = np.array([[0.6, 0.4], [0.3, 0.7]])
    m.update(outputs, np.array([0, 0]))
    # k > C degrades to "always correct"
    assert m.compute() == pytest.approx(100.0)


def test_topk_meter_data_set_round_trip_sums_like_workers():
    # the harness reduces data() across workers by Sum, then set()s the
    # reduced values — two local meters must equal one global meter.
    a, b = TopKClassMeter(k=1), TopKClassMeter(k=1)
    rng = np.random.RandomState(0)
    oa, ob = rng.randn(16, 10), rng.randn(16, 10)
    ta, tb = rng.randint(0, 10, 16), rng.randint(0, 10, 16)
    a.update(oa, ta)
    b.update(ob, tb)
    reduced = {k: a.data()[k] + b.data()[k] for k in a.data()}

    world = TopKClassMeter(k=1)
    world.set(reduced)
    ref = TopKClassMeter(k=1)
    ref.update(np.concatenate([oa, ob]), np.concatenate([ta, tb]))
    assert world.data() == ref.data()
    assert world.compute() == pytest.approx(ref.compute())


def test_topk_meter_update_counts_and_reset():
    m = TopKClassMeter(k=1)
    m.update_counts(7, 10)
    m.update_counts(3, 10)
    assert m.compute() == pytest.approx(50.0)
    m.reset()
    assert m.num_examples == 0
    assert m.compute() == 0.0  # no division by zero on empty meter
