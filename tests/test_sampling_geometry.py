"""Sampling-geometry contract (SURVEY.md §2.1, reference compression.py:56-89)."""

import math

import pytest

from dgc_tpu.compression import DGCCompressor, sampling_geometry


def test_known_case_resnet_conv():
    # 3x3x16x16 conv, ratio 0.01, sample 0.01: stride backs off 33→25→17→9
    num_samples, stride = sampling_geometry(2304, 0.01, 0.01)
    assert (num_samples, stride) == (256, 9)


def test_invariants_across_sizes():
    for numel in [100, 1000, 4096, 100000, 2359296, 25557032]:
        for ratio in [0.001, 0.01, 0.05]:
            for sr in [0.01, 0.1]:
                ns, stride = sampling_geometry(numel, sr, ratio)
                pct = math.ceil(numel * sr)
                cpr = math.ceil(2 / ratio)
                if numel <= cpr:
                    assert stride == 1 and ns == numel
                else:
                    # enough samples to estimate the threshold
                    assert ns >= min(max(pct, cpr), numel)
                    assert ns == numel // stride
                    assert stride >= 1


def test_full_sampling():
    ns, stride = sampling_geometry(5000, 1.0, 0.01)
    assert (ns, stride) == (5000, 1)


def test_initialize_attrs():
    comp = DGCCompressor(0.001, sample_ratio=0.01)
    comp.initialize([("w", (2359296, (3, 3, 512, 512)))])
    a = comp.attributes["w"]
    assert a.num_selects == math.ceil(2359296 * 0.001)
    assert a.top_k_samples == math.ceil(a.num_samples * 0.001)
    assert a.numel == 2359296 and a.shape == (3, 3, 512, 512)


def test_ratio_normalization():
    assert DGCCompressor(1000).compress_ratio == 0.001
    assert DGCCompressor(0.25).compress_ratio == 0.25


def test_sample_ratio_clamped():
    # reference clamps sample_ratio to [0.01, 1.0] (compression.py:47)
    assert DGCCompressor(0.01, sample_ratio=0.001).sample_ratio == 0.01
    assert DGCCompressor(0.01, sample_ratio=2.0).sample_ratio == 1.0


def test_warmup_schedule_default_coeff():
    comp = DGCCompressor(0.001, warmup_epochs=5)
    comp.initialize([("w", (100000, (100000,)))])
    ratios = []
    for epoch in range(7):
        comp.warmup_compress_ratio(epoch)
        ratios.append(comp.compress_ratio)
    # warmup_coeff = 0.001**(1/6); ratio_e = coeff**(e+1) clamped at base
    coeff = 0.001 ** (1.0 / 6)
    for e in range(5):
        assert ratios[e] == pytest.approx(max(coeff ** (e + 1), 0.001))
    assert ratios[5] == 0.001 and ratios[6] == 0.001


def test_warmup_schedule_explicit_list():
    comp = DGCCompressor(0.001, warmup_epochs=5,
                         warmup_coeff=[0.25, 0.063, 0.015, 0.004, 0.001])
    comp.initialize([("w", (100000, (100000,)))])
    got = []
    for epoch in range(6):
        comp.warmup_compress_ratio(epoch)
        got.append(comp.compress_ratio)
    assert got == [0.25, 0.063, 0.015, 0.004, 0.001, 0.001]


def test_warmup_changed_flag_and_reinit():
    comp = DGCCompressor(0.001, warmup_epochs=2)
    comp.initialize([("w", (50000, (50000,)))])
    ns0 = comp.attributes["w"].num_selects
    assert comp.warmup_compress_ratio(0) is True
    assert comp.attributes["w"].num_selects > ns0  # looser ratio => more
    assert comp.warmup_compress_ratio(0) is False  # no change => no re-init
