"""Worker program for the 2-process ``jax.distributed`` CPU test
(tests/test_multiprocess.py) — the multi-host execution path the reference
exercised with 8-256 MPI ranks (/root/reference/train.py:99-100,244-264).

Each process: initialize the process group over gRPC, build a mesh spanning
BOTH processes' fake CPU devices, assemble the global batch from its local
shard (``host_local_to_global``), run flat DGC train steps, save a
checkpoint collectively (orbax distributed write, coordinator-only
bookkeeping), restore it, and verify the restored state matches. Prints
one JSON result line prefixed RESULT: for the parent to parse.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")
# pre-0.5 JAX defaults CPU cross-process collectives to "none" ("Multiprocess
# computations aren't implemented on the CPU backend"); newer releases
# default to gloo already
if "jax_cpu_collectives_implementation" in jax.config.values:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    proc_id = int(sys.argv[1])
    num_procs = int(sys.argv[2])
    coord = sys.argv[3]
    workdir = sys.argv[4]

    from dgc_tpu.parallel.multihost import (
        host_local_to_global, initialize_multihost, is_coordinator)

    # persistent compilation cache SHARED by both processes (and across
    # test invocations — a stable tmp location, not the per-test dir): on
    # a small/loaded host, cold-compiling the train step in both processes
    # can outlast the coordination service's 300 s shutdown barrier when
    # one process is starved — the cache removes that variance (warm
    # runs: ~30 s total)
    import getpass
    import tempfile
    # user-scoped: a shared dir would be unwritable for every user but
    # its creator on multi-user hosts, silently disabling the cache
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(tempfile.gettempdir(),
                                   f"dgc_tpu_test_jax_cache_"
                                   f"{getpass.getuser()}"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    os.environ["JAX_COORDINATOR_ADDRESS"] = coord
    os.environ["JAX_NUM_PROCESSES"] = str(num_procs)
    os.environ["JAX_PROCESS_ID"] = str(proc_id)
    # cold-cache runs compile the train steps from scratch (minutes on a
    # loaded 1-core host) and the two processes' compile times diverge;
    # the default 300 s shutdown barrier / 100 s heartbeat then kill the
    # process that finished first while its peer is still compiling
    assert initialize_multihost(initialization_timeout=600,
                                heartbeat_timeout_seconds=600,
                                shutdown_timeout_seconds=1200) is True
    assert jax.process_count() == num_procs
    assert is_coordinator() == (proc_id == 0)

    import jax.numpy as jnp
    import numpy as np
    from flax import linen as nn
    from jax.sharding import Mesh

    from dgc_tpu import (DGCCompressor, DGCSGDMemory, DistributedOptimizer,
                         dgc_sgd)
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)
    from dgc_tpu.training.checkpoint import CheckpointManager
    from dgc_tpu.utils.logging import MetricWriter
    from dgc_tpu.utils.pytree import named_flatten

    W = len(jax.devices())          # 8 global (4 per process)
    assert W == 2 * 4
    mesh = Mesh(np.array(jax.devices()), ("data",))

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x.mean(axis=(1, 2)))

    model = M()
    v = dict(model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3))))

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        if mutable:
            return model.apply(variables, x, train=train, mutable=mutable,
                               rngs=rngs)
        return model.apply(variables, x, train=train)

    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh,
                        dist_opt=dist)
    step_fn = build_train_step(apply_fn, dist, mesh, donate=False,
                               flat=setup)

    # every process materializes the full host batch; host_local_to_global
    # takes each process's local slice (the DistributedSampler role)
    rng = np.random.RandomState(7)
    bs = 4
    images_h = rng.randn(W * bs, 16, 16, 3).astype(np.float32)
    labels_h = rng.randint(0, 10, W * bs).astype(np.int32)
    images = host_local_to_global(images_h, mesh)
    labels = host_local_to_global(labels_h, mesh)

    # NOTE on the block_until_ready calls below: syncing only the loss
    # scalar leaves the step's exchange collectives in flight on the async
    # CPU runtime; if the host then starts a collective sequence of its own
    # (shard_state / checkpoint device_puts issue assert_equal broadcasts),
    # the two processes can issue gloo ops in different orders on the shared
    # communicator and die with "op.preamble.length <= op.nbytes". Fully
    # draining the device stream before every host-driven collective
    # sequence removes that race.
    losses = []
    for i in range(3):
        state, m = step_fn(state, images, labels, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    jax.block_until_ready(state)

    # metric writer: only the coordinator creates files
    writer = MetricWriter(os.path.join(workdir, "logs"))
    writer.add_scalar("loss", losses[-1], 3)
    writer.close()

    ckpt = CheckpointManager(os.path.join(workdir, "ckpt"), keep=3)
    ckpt.save(0, state, {"top1": 12.5}, best=True)

    # one more step so the live state diverges from the saved one
    state2, _ = step_fn(state, images, labels, jax.random.PRNGKey(99))
    jax.block_until_ready(state2)
    restored = ckpt.restore(state2)
    assert restored is not None
    r_state, r_epoch, meters = restored
    assert r_epoch == 0 and abs(meters["top1"] - 12.5) < 1e-6

    # restored params equal the saved (pre-divergence) params, not state2's
    def gather(x):
        # params are replicated: any local shard holds the full value
        return np.asarray(x.addressable_data(0))

    saved_p = gather(state.params)
    rest_p = gather(r_state.params)
    div_p = gather(state2.params)
    np.testing.assert_allclose(rest_p, saved_p, rtol=1e-6)
    assert not np.allclose(rest_p, div_p)

    # resumed state trains on
    state3, m3 = step_fn(r_state, images, labels, jax.random.PRNGKey(5))
    assert np.isfinite(float(m3["loss"]))
    jax.block_until_ready((state3, m3))

    # --- two-tier hierarchical exchange across the REAL process boundary:
    # each process is one "host" row (its 4 local devices form the dense
    # tier); the sparse DGC gather crosses the gRPC/DCN link only ---
    from dgc_tpu.parallel import make_two_tier_mesh
    mesh_tt = make_two_tier_mesh(num_procs, W // num_procs)
    assert [d.process_index for d in mesh_tt.devices[proc_id]] == \
        [proc_id] * (W // num_procs), "mesh rows must align with processes"
    comp_tt = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    comp_tt.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist_tt = DistributedOptimizer(
        dgc_sgd(0.1, momentum=0.9), comp_tt, axis_name="hosts",
        world_size=W, local_axis_name="local", local_size=W // num_procs)
    setup_tt = make_flat_setup(v, dist_tt)
    state_tt = shard_state(make_flat_state(v, dist_tt, setup_tt, W),
                           mesh_tt, dist_tt.data_axes, dist_opt=dist_tt)
    step_tt = build_train_step(apply_fn, dist_tt, mesh_tt, donate=False,
                               flat=setup_tt)
    images_tt = host_local_to_global(images_h, mesh_tt)
    labels_tt = host_local_to_global(labels_h, mesh_tt)
    tt_losses = []
    for i in range(2):
        state_tt, m = step_tt(state_tt, images_tt, labels_tt,
                              jax.random.PRNGKey(i))
        tt_losses.append(float(m["loss"]))
    assert all(np.isfinite(tl) for tl in tt_losses)
    jax.block_until_ready(state_tt)

    # --- 4-host x 2-local two-tier mesh (ISSUE 2 satellite): the hosts
    # (sparse) axis now CROSSES the process boundary — rows 0-1 live in
    # proc 0, rows 2-3 in proc 1 — so the dense local tier stays inside a
    # process while the sparse gather spans both intra- and inter-process
    # "hosts". Per-node memory semantics: the local tier psums the gradient
    # before compression, so the two devices of one row must hold bitwise-
    # identical error-feedback memory at every step, including across
    # save/resume. ---
    hosts4, local2 = 4, 2
    mesh_t4 = make_two_tier_mesh(hosts4, local2)
    rows_per_proc = hosts4 // num_procs
    for r in range(hosts4):
        owner = r // rows_per_proc
        assert [d.process_index for d in mesh_t4.devices[r]] == \
            [owner] * local2, "rows must pack per process in order"
    comp_t4 = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    comp_t4.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist_t4 = DistributedOptimizer(
        dgc_sgd(0.1, momentum=0.9), comp_t4, axis_name="hosts",
        world_size=W, local_axis_name="local", local_size=local2)
    setup_t4 = make_flat_setup(v, dist_t4)
    state_t4 = shard_state(make_flat_state(v, dist_t4, setup_t4, W),
                           mesh_t4, dist_t4.data_axes, dist_opt=dist_t4)
    # telemetry riding the same program across the real process boundary
    step_t4 = build_train_step(apply_fn, dist_t4, mesh_t4, donate=False,
                               flat=setup_t4, telemetry=True)
    images_t4 = host_local_to_global(images_h, mesh_t4)
    labels_t4 = host_local_to_global(labels_h, mesh_t4)

    def mem_pair_dev(mem):
        """Max |memory(row dev 0) - memory(row dev 1)| over all per-worker
        leaves — 0.0 iff every host row's local pair is bitwise equal."""
        leaves = [l for l in jax.tree.leaves(mem)
                  if hasattr(l, "shape") and l.ndim >= 1
                  and l.shape[0] == W]
        assert leaves, "memory has no per-worker leaves"

        def f(*ls):
            d = jnp.zeros((), jnp.float32)
            for l in ls:
                r = l.reshape(hosts4, local2, -1).astype(jnp.float32)
                d = jnp.maximum(d, jnp.max(jnp.abs(r[:, 0] - r[:, 1])))
            return d
        return float(jax.jit(f)(*leaves))

    t4_losses, t4_mem_dev = [], []
    telem = None
    for i in range(2):
        state_t4, m = step_t4(state_t4, images_t4, labels_t4,
                              jax.random.PRNGKey(i))
        jax.block_until_ready(state_t4)
        t4_losses.append(float(m["loss"]))
        t4_mem_dev.append(mem_pair_dev(state_t4.memory))
        telem = m["telemetry"]
    assert all(np.isfinite(tl) for tl in t4_losses)
    t4_payload = float(np.asarray(telem["payload_elems"]))
    assert np.isfinite(float(np.asarray(telem["grad_norm"])))

    # save/resume preserves the per-node memory pairing across the
    # process boundary: save, diverge one step, restore, verify
    ckpt_t4 = CheckpointManager(os.path.join(workdir, "ckpt_tt"), keep=1)
    ckpt_t4.save(0, state_t4, {"top1": 1.0}, best=False)
    state_t4b, _ = step_t4(state_t4, images_t4, labels_t4,
                           jax.random.PRNGKey(77))
    jax.block_until_ready(state_t4b)
    restored_t4 = ckpt_t4.restore(state_t4b)
    assert restored_t4 is not None
    r_state_t4 = restored_t4[0]

    def mem_max_diff(ma, mb):
        la = [l for l in jax.tree.leaves(ma) if hasattr(l, "shape")]
        lb = [l for l in jax.tree.leaves(mb) if hasattr(l, "shape")]

        def f(*ls):
            n = len(ls) // 2
            return jnp.max(jnp.stack([
                jnp.max(jnp.abs(a.astype(jnp.float32) -
                                b.astype(jnp.float32)))
                for a, b in zip(ls[:n], ls[n:])]))
        return float(jax.jit(f)(*(la + lb)))

    t4_restore_diff = mem_max_diff(r_state_t4.memory, state_t4.memory)
    t4_restored_pair_dev = mem_pair_dev(r_state_t4.memory)
    state_t4c, m4c = step_t4(r_state_t4, images_t4, labels_t4,
                             jax.random.PRNGKey(5))
    jax.block_until_ready((state_t4c, m4c))
    t4_resumed_pair_dev = mem_pair_dev(state_t4c.memory)
    assert np.isfinite(float(m4c["loss"]))

    print("RESULT:" + json.dumps({
        "proc": proc_id,
        "losses": losses,
        "tt_losses": tt_losses,
        "resume_loss": float(m3["loss"]),
        "coordinator": is_coordinator(),
        "t4_losses": t4_losses,
        "t4_mem_pair_dev": t4_mem_dev,
        "t4_payload": t4_payload,
        "t4_restore_diff": t4_restore_diff,
        "t4_restored_pair_dev": t4_restored_pair_dev,
        "t4_resumed_pair_dev": t4_resumed_pair_dev,
    }), flush=True)

    # align exits: the coordinator's extra file bookkeeping must not make
    # the other process hit the jax shutdown barrier alone and time out
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("test_done")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
