"""Tests for the structured-tracing stack (docs/TELEMETRY.md §Tracing):

* host spans — nesting/ordering, wrap_iter, step summaries, the
  Chrome-trace export schema, and the sink round-trip;
* device phase markers — phase() is a nullcontext when off, a
  dgcph.<phase>[.b<idx>] named scope when on;
* attrib — op→phase/bucket mapping and the per-bucket table against a
  recorded device-format trace fixture (CPU profiler traces carry no op
  metadata, so the fixture stands in for a TPU trace);
* flight recorder — ring wraparound, raw-value storage, atomic dump +
  load, the nonfinite-streak breaker;
* regress exit codes — 3 (missing artifact) and 4 (schema mismatch)
  stay distinct and actionable.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.telemetry import attrib, regress
from dgc_tpu.telemetry import trace as trace_mod
from dgc_tpu.telemetry.flight import (
    FlightRecorder,
    NonfiniteStreak,
    load_dump,
)
from dgc_tpu.telemetry.trace import (
    NULL_TRACER,
    SpanTracer,
    chrome_trace_from_records,
    validate_chrome_trace,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "device_trace.json")


# --------------------------------------------------------------------- #
# host spans                                                             #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_span_nesting_and_ordering():
    tr = SpanTracer()
    with tr.span("epoch", epoch=0):
        with tr.span("step_dispatch", step=1):
            pass
        with tr.span("step_dispatch", step=2):
            pass
    evs = tr.events()
    # completion order: inner spans close before the outer one
    assert [e["name"] for e in evs] == ["step_dispatch", "step_dispatch",
                                       "epoch"]
    inner1, inner2, outer = evs
    assert inner1["args"]["parent"] == "epoch"
    assert inner2["args"]["parent"] == "epoch"
    assert "parent" not in outer["args"]
    assert inner1["args"]["step"] == 1
    # timestamps are monotonic and the outer span covers the inner ones
    assert inner1["ts"] <= inner2["ts"]
    assert outer["ts"] <= inner1["ts"]
    assert outer["ts"] + outer["dur"] >= inner2["ts"] + inner2["dur"]


@pytest.mark.fast
def test_span_survives_exception():
    tr = SpanTracer()
    with pytest.raises(RuntimeError):
        with tr.span("bad"):
            raise RuntimeError("boom")
    assert [e["name"] for e in tr.events()] == ["bad"]
    # the per-thread stack unwound: a new span has no stale parent
    with tr.span("after"):
        pass
    assert "parent" not in tr.events()[-1]["args"]


@pytest.mark.fast
def test_wrap_iter_spans_each_next():
    tr = SpanTracer()
    out = list(tr.wrap_iter(iter([1, 2, 3]), "data_load"))
    assert out == [1, 2, 3]
    # one span per next() including the final StopIteration probe
    names = [e["name"] for e in tr.events()]
    assert names == ["data_load"] * 4


@pytest.mark.fast
def test_step_summary_accumulates_and_resets():
    tr = SpanTracer()
    with tr.span("step_dispatch"):
        pass
    with tr.span("step_dispatch"):
        pass
    s = tr.step_summary()
    assert set(s) == {"step_dispatch"} and s["step_dispatch"] >= 0
    assert tr.step_summary() == {}          # reset drained it


@pytest.mark.fast
def test_chrome_trace_schema_and_save(tmp_path):
    tr = SpanTracer()
    with tr.span("checkpoint", epoch=3):
        pass
    obj = tr.chrome_trace()
    assert validate_chrome_trace(obj) == []
    assert obj["displayTimeUnit"] == "ms"
    metas = [e for e in obj["traceEvents"] if e["ph"] == "M"]
    assert any(m["name"] == "process_name" for m in metas)
    p = tr.save(str(tmp_path / "trace.json"))
    assert validate_chrome_trace(json.load(open(p))) == []


@pytest.mark.fast
def test_validate_chrome_trace_flags_garbage():
    assert validate_chrome_trace({}) != []
    bad = {"traceEvents": [{"ph": "Z", "name": "x", "pid": 1, "tid": 1},
                           {"ph": "X", "name": 7, "pid": 1, "tid": 1,
                            "ts": -1, "dur": 1}]}
    msgs = validate_chrome_trace(bad)
    assert any("bad ph" in m for m in msgs)
    assert any("ts" in m for m in msgs)


@pytest.mark.fast
def test_sink_roundtrip_rebuilds_chrome_trace():
    records = [
        {"event": "span", "name": "data_load", "ts_us": 10.0,
         "dur_us": 5.0, "tid": 7},
        {"event": "step", "step": 1},                    # non-span: skipped
        {"event": "span", "name": "step_dispatch", "ts_us": 20.0,
         "dur_us": 3.0, "tid": 7, "step": 1, "parent": "epoch"},
    ]
    obj = chrome_trace_from_records(records)
    assert validate_chrome_trace(obj) == []
    xs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["data_load", "step_dispatch"]
    assert xs[1]["args"] == {"step": 1, "parent": "epoch"}


@pytest.mark.fast
def test_null_tracer_is_inert(tmp_path):
    with NULL_TRACER.span("x"):
        pass
    assert list(NULL_TRACER.wrap_iter([1], "y")) == [1]
    assert NULL_TRACER.step_summary() == {}
    assert NULL_TRACER.save(str(tmp_path / "t.json")) is None


# --------------------------------------------------------------------- #
# device phase markers                                                   #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_phase_off_is_nullcontext():
    prev = trace_mod.enable(False)
    try:
        import contextlib
        assert isinstance(trace_mod.phase("select", 3),
                          contextlib.nullcontext)
    finally:
        trace_mod.enable(prev)


@pytest.mark.fast
def test_scope_names():
    assert trace_mod.scope_name("pack") == "dgcph.pack"
    assert trace_mod.scope_name("select", 4) == "dgcph.select.b4"


def test_markers_land_in_compiled_text_only_when_on():
    # a FRESH function per build: jax's jaxpr cache keys on the function
    # object, not the trace flag, so reusing one across enable() flips
    # would leak the first build's markers (the same hazard that keeps
    # module-level jitted kernels undecorated — see ops/kernels.py)
    def make():
        def f(x):
            with trace_mod.phase("select", 2):
                return jnp.sum(x * 2.0)
        return f

    x = jnp.arange(8, dtype=jnp.float32)
    prev = trace_mod.enable(True)
    try:
        on = jax.jit(make()).lower(x).compile().as_text()
    finally:
        trace_mod.enable(prev)
    trace_mod.enable(False)
    off_l = jax.jit(make()).lower(x)
    off = off_l.compile().as_text()
    assert "dgcph.select.b2" in on
    assert "dgcph" not in off
    # and the off build's LOWERED text carries no trace of the marker
    assert "dgcph" not in off_l.as_text()


# --------------------------------------------------------------------- #
# attrib: op -> phase mapping over the recorded fixture                  #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_op_phase_mapping():
    ev = {"args": {"tf_op": "jit(s)/dgcph.select.b2/sort"}}
    assert attrib.op_phase(ev) == ("select", 2)
    ev = {"args": {"tf_op": "jit(s)/dgcph.pack/concat"}}
    assert attrib.op_phase(ev) == ("pack", None)
    # innermost token wins when scopes nest
    ev = {"args": {"tf_op": "jit(s)/dgcph.compensate/dgcph.pack/bitcast"}}
    assert attrib.op_phase(ev) == ("pack", None)
    assert attrib.op_phase({"args": {"tf_op": "jit(s)/mul"}}) == (None, None)
    assert attrib.op_phase({}) == (None, None)


@pytest.mark.fast
def test_device_events_filters_fixture():
    events = attrib.load_trace_events(FIXTURE)
    dev = attrib.device_events(events)
    names = sorted(e["name"] for e in dev)
    # envelope (jit_train_step), no-category (step 42) and host-pid
    # events are all excluded; the 9 leaf device ops remain
    assert len(dev) == 9
    assert "jit_train_step" not in names and "step 42" not in names


@pytest.mark.fast
def test_phase_table_against_fixture():
    dev = attrib.device_events(attrib.load_trace_events(FIXTURE))
    t = attrib.phase_table(dev, steps=1)
    # durations are µs in the fixture -> ms here
    assert t["total_ms"] == pytest.approx(2.39)
    assert t["unattributed_ms"] == pytest.approx(0.5)    # copy.2
    assert t["phases"]["threshold"] == pytest.approx(0.1)
    assert t["phases"]["select"] == pytest.approx(0.2)
    assert t["phases"]["pack"] == pytest.approx(0.09)    # incl. nested win
    assert t["phases"]["allgather"] == pytest.approx(0.3)
    assert t["phases"]["decode"] == pytest.approx(0.08)
    assert t["phases"]["apply"] == pytest.approx(0.12)
    assert t["phases"]["fwd_bwd"] == pytest.approx(1.0)
    # bucket split: b0 carries threshold+select, b1 decode
    assert t["buckets"]["b0"]["threshold"] == pytest.approx(0.1)
    assert t["buckets"]["b0"]["select"] == pytest.approx(0.2)
    assert t["buckets"]["b1"]["decode"] == pytest.approx(0.08)
    # phase keys come out in canonical pipeline order
    order = [p for p in trace_mod.PHASES if p in t["phases"]]
    assert list(t["phases"]) == order


@pytest.mark.fast
def test_profile_json_roundtrip(tmp_path):
    dev = attrib.device_events(attrib.load_trace_events(FIXTURE))
    t = attrib.phase_table(dev, steps=2)
    dense = attrib.phase_table([], steps=2)
    prof = attrib.profile_json(t, dense, static={"world": 8},
                               measured_overhead_ms=0.106)
    assert prof["delta_ms"] == pytest.approx(t["total_ms"])
    # exchange phases exclude fwd_bwd/update/loss
    assert prof["exchange_phase_ms"] == pytest.approx(
        sum(v for p, v in t["phases"].items() if p != "fwd_bwd"))
    p = attrib.write_profile(prof, str(tmp_path / "profile.json"))
    assert attrib.load_profile(p)["measured_overhead_ms"] == 0.106
    with pytest.raises(ValueError):
        attrib.load_profile(FIXTURE)       # wrong schema


@pytest.mark.fast
def test_trace_cli_rebuilds_from_sink_jsonl(tmp_path, capsys):
    from dgc_tpu.telemetry.registry import SCHEMA, SCHEMA_VERSION
    run = tmp_path / "telemetry.jsonl"
    lines = [{"schema": SCHEMA, "version": SCHEMA_VERSION, "static": {}},
             {"event": "span", "name": "eval", "ts_us": 1.0, "dur_us": 2.0,
              "tid": 1}]
    run.write_text("\n".join(json.dumps(x) for x in lines) + "\n")
    out = tmp_path / "trace.json"
    assert trace_mod._main([str(run), "-o", str(out)]) == 0
    obj = json.load(open(out))
    assert validate_chrome_trace(obj) == []
    assert sum(1 for e in obj["traceEvents"] if e["ph"] == "X") == 1


# --------------------------------------------------------------------- #
# flight recorder                                                        #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_flight_ring_wraparound():
    fr = FlightRecorder(capacity=3)
    for s in range(5):
        fr.record(s, loss=float(s))
    assert len(fr) == 3
    assert [r["step"] for r in fr.records()] == [2, 3, 4]


@pytest.mark.fast
def test_flight_dump_atomic_and_loadable(tmp_path):
    fr = FlightRecorder(capacity=4, static={"world": 8})
    # raw device arrays + a nonfinite + an unconvertible value
    fr.record(1, loss=jnp.float32(1.5), spans_ms={"step_dispatch": 2.0})
    fr.record(2, loss=float("nan"), weird=object())
    p = fr.dump(str(tmp_path / "flight.json"), reason="test",
                extra={"note": "x"})
    assert p is not None
    assert os.listdir(tmp_path) == ["flight.json"]     # tmp file renamed
    obj = load_dump(p)
    assert obj["reason"] == "test" and obj["static"] == {"world": 8}
    assert obj["recorded"] == 2 and obj["capacity"] == 4
    r1, r2 = obj["records"]
    assert r1["loss"] == 1.5
    assert r1["spans_ms"] == {"step_dispatch": 2.0}
    assert r2["loss"] == "nan"                          # guarded repr
    assert r2["weird"].startswith("<unconvertible:")
    # dump never raises, even to an unwritable path
    assert fr.dump("/proc/nope/flight.json") is None


@pytest.mark.fast
def test_flight_dump_truncates_arrays(tmp_path):
    fr = FlightRecorder()
    fr.record(1, grad=np.arange(1000, dtype=np.float32))
    obj = load_dump(fr.dump(str(tmp_path / "f.json")))
    assert len(obj["records"][0]["grad"]) == 64


@pytest.mark.fast
def test_nonfinite_streak_breaker():
    ns = NonfiniteStreak(threshold=3)
    assert not ns.update(float("nan"))
    assert not ns.update(float("inf"))
    assert not ns.update(1.0)                 # finite resets
    assert ns.streak == 0
    assert not ns.update(float("nan"))
    assert not ns.update(float("nan"))
    assert ns.update(float("nan"))            # third consecutive trips
    assert ns.update(0.0)                     # tripped stays tripped


@pytest.mark.fast
def test_flight_load_rejects_foreign_schema(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"schema": "other", "version": 1}))
    with pytest.raises(ValueError):
        load_dump(str(p))


# --------------------------------------------------------------------- #
# regress exit codes                                                     #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_regress_exit_3_on_missing_artifact(tmp_path, capsys):
    run = tmp_path / "run.json"
    run.write_text(json.dumps({"metric": "x", "value": 1.0}))
    rc = regress.main([str(tmp_path / "nope.json"), str(run)])
    assert rc == 3
    err = capsys.readouterr().err
    assert "record one first" in err


@pytest.mark.fast
def test_regress_exit_4_on_schema_mismatch(tmp_path, capsys):
    from dgc_tpu.telemetry.registry import SCHEMA
    base = tmp_path / "base.jsonl"
    base.write_text(json.dumps(
        {"schema": SCHEMA, "version": 999, "static": {}}) + "\n")
    run = tmp_path / "run.json"
    run.write_text(json.dumps({"metric": "x", "value": 1.0}))
    rc = regress.main([str(base), str(run)])
    assert rc == 4
    err = capsys.readouterr().err
    assert "schema version" in err and "re-record" in err
