"""Megakernel parity oracles (dgc_tpu.ops.kernels.dgc_forward_rows /
dgc_apply_rows) and the engine-level megakernel path
(``DGCCompressor(megakernel=True)``) on the fake 8-device CPU mesh.

Kernel oracles compare against the JITTED jnp references: XLA CPU
contracts ``momentum * m + g`` into an FMA under jit but not in eager
mode, so the kernel is bitwise the jitted reference in every flag combo
(and the jitted reference is bitwise the jitted engine path — the thing
that actually matters). Engine tests run ``sample_ratio=1.0`` so
selection is deterministic and the megakernel engine must be BITWISE
the default unfused engine, transmit record and error-feedback state
included."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dgc_tpu import (
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    dgc_sgd,
)
from dgc_tpu.ops import kernels
from dgc_tpu.utils.pytree import named_flatten, named_unflatten
from dgc_tpu.utils.compat import shard_map

W = 8

# jitted references — see module docstring for why jit is mandatory here
_ref_forward = jax.jit(
    kernels.dgc_forward_rows_reference,
    static_argnames=("base", "k", "momentum", "nesterov",
                     "momentum_masking"))
_ref_apply = jax.jit(
    kernels.dgc_apply_rows_reference,
    static_argnames=("total", "divisor"))


def _rand_bits(rng, total):
    """An arbitrary packed transmit record covering [0, total): any bit
    pattern is a valid input — realign/expansion only windows it."""
    w = kernels.num_sent_words(total)
    return jnp.asarray(
        rng.randint(-2 ** 31, 2 ** 31, size=w, dtype=np.int64)
        .astype(np.int32))


def _fwd_case(rng, R, cols, base, numels, k, total=None, **flags):
    n = R * cols
    total = total if total is not None else base + n
    g = jnp.asarray(rng.randn(n), jnp.float32)
    m = jnp.asarray(rng.randn(n), jnp.float32)
    v = jnp.asarray(rng.randn(n), jnp.float32)
    bits = _rand_bits(rng, total)
    numels = jnp.asarray(numels, jnp.int32)
    got = kernels.dgc_forward_rows(g, m, v, bits, base, numels, k, 0.9,
                                   **flags)
    want = _ref_forward(g, m, v, bits, base, numels, k, 0.9, **flags)
    for name, a, b in zip(("mmt", "vec", "scores", "values", "cols"),
                          got, want):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=f"{name} R={R} cols={cols} k={k} base={base}")


@pytest.mark.parametrize("R,cols,base,numels,k", [
    (1, 128, 0, [128], 1),                 # minimal geometry
    (2, 256, 640, [256, 100], 16),         # ragged tail + funnel-shift base
    (3, 256, 128, [256, 100, 0], 8),       # an all-structural-pad row
    (1, 512, 0, [512], 129),               # k > 128: no delegate cliff
    (2, 384, 4096, [288, 320], 19),        # the engine's conv bucket shape
])
def test_forward_kernel_matches_jitted_reference(R, cols, base, numels, k):
    rng = np.random.RandomState(3 + R + k)
    _fwd_case(rng, R, cols, base, numels, k, total=base + R * cols + 512)


@pytest.mark.parametrize("nesterov", [False, True])
@pytest.mark.parametrize("momentum_masking", [False, True])
def test_forward_kernel_flag_combos(nesterov, momentum_masking):
    rng = np.random.RandomState(7)
    _fwd_case(rng, 2, 256, 640, [256, 100], 16,
              nesterov=nesterov, momentum_masking=momentum_masking)


def test_forward_kernel_max_multiround_k():
    """k == _MR_MAX_K == 1024: the widest selection the megakernel
    serves — the old ``max_sel <= 128`` reference cliff is 8x past."""
    rng = np.random.RandomState(11)
    _fwd_case(rng, 1, 1024, 0, [1024], kernels._MR_MAX_K)


def test_forward_kernel_refuses_bf16():
    g = jnp.zeros((128,), jnp.bfloat16)
    m = v = jnp.zeros((128,), jnp.float32)
    bits = jnp.zeros((128,), jnp.int32)
    numels = jnp.asarray([128], jnp.int32)
    with pytest.raises(ValueError, match="f32-only"):
        kernels.dgc_forward_rows(g, m, v, bits, 0, numels, 4, 0.9)
    with pytest.raises(ValueError, match="f32-only"):
        kernels.dgc_forward_rows(m, g, v, bits, 0, numels, 4, 0.9)


def _apply_case(rng, total, P_, divisor, donor=False, dupes=False):
    if dupes:
        idx = rng.randint(0, total, size=P_)
        flags = np.zeros(P_, bool)        # dupes may not be flagged
    else:
        idx = rng.choice(total, size=P_, replace=False)
        flags = rng.rand(P_) < 0.5        # pack_sent_bits needs uniqueness
    values = jnp.asarray(rng.randn(P_), jnp.float32)
    indices = jnp.asarray(idx, jnp.int32)
    flags = jnp.asarray(flags)
    bd = _rand_bits(rng, total) if donor else None
    acc, bits = kernels.dgc_apply_rows(values, indices, flags, total,
                                       bits_donor=bd, divisor=divisor)
    want_acc, want_bits = _ref_apply(values, indices, flags, total,
                                     divisor=divisor)
    np.testing.assert_array_equal(np.asarray(acc), np.asarray(want_acc))
    np.testing.assert_array_equal(np.asarray(bits), np.asarray(want_bits))
    return acc, bits


@pytest.mark.parametrize("divisor", [None, 2.0, 8.0])
def test_apply_kernel_matches_jitted_reference(divisor):
    rng = np.random.RandomState(17)
    _apply_case(rng, 12800, 512, divisor)


def test_apply_kernel_donor_never_read():
    """The donated previous-step record only provides the buffer: the
    rebuilt bits equal the fresh-reference bits whatever it held."""
    rng = np.random.RandomState(19)
    _apply_case(rng, 12800, 512, 8.0, donor=True)


def test_apply_kernel_duplicate_indices_stable():
    """Cross-worker duplicate coordinates: the staging argsort is stable,
    so duplicate contributions keep payload order — bitwise the XLA
    scatter-add (which applies updates in order on duplicates)."""
    rng = np.random.RandomState(23)
    _apply_case(rng, 4096, 512, 8.0, dupes=True)


def test_apply_kernel_no_divisor_matches_fused_epilogue():
    """divisor=None is byte-identical semantics to payload_apply_bits —
    the megakernel-off contract at the output level."""
    rng = np.random.RandomState(29)
    total, P_ = 12800, 512
    idx = rng.choice(total, size=P_, replace=False)
    values = jnp.asarray(rng.randn(P_), jnp.float32)
    indices = jnp.asarray(idx, jnp.int32)
    flags = jnp.asarray(rng.rand(P_) < 0.5)
    a1, b1 = kernels.dgc_apply_rows(values, indices, flags, total)
    a2, b2 = kernels.payload_apply_bits(values, indices, flags, total)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))


@pytest.mark.parametrize("k", [257, 1024])
def test_select_pack_rows_no_delegation_past_128(k):
    """The VGG-16 fc regime (k in (128, 1024]) must run the multi-round
    kernel, not the XLA top_k reference — the 11.3 ms/step delegate
    cliff is the megakernel PR's headline kill."""
    rng = np.random.RandomState(31 + k)
    x = jnp.asarray(rng.randn(2, 4096), jnp.float32)
    numels = jnp.asarray([4096, 3000], jnp.int32)
    want = kernels.select_pack_rows_reference(x, numels, k)

    def boom(*a, **kw):
        raise AssertionError("select_pack_rows delegated to the reference")

    orig = kernels.select_pack_rows_reference
    kernels.select_pack_rows_reference = boom
    try:
        got = kernels.select_pack_rows(x, numels, k)
    finally:
        kernels.select_pack_rows_reference = orig
    for name, a, b in zip(("scores", "values", "cols"), got, want):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"{name} k={k}")


# ------------------------------------------------------------------ #
# engine-level parity on the fake 8-device mesh                      #
# ------------------------------------------------------------------ #

def _params():
    rng = np.random.RandomState(0)
    return {
        "conv1": {"kernel": jnp.asarray(rng.randn(3, 3, 4, 8), jnp.float32)},
        "conv2": {"kernel": jnp.asarray(rng.randn(3, 3, 8, 8), jnp.float32)},
        "dense": {"kernel": jnp.asarray(rng.randn(32, 10), jnp.float32),
                  "bias": jnp.asarray(rng.randn(10), jnp.float32)},
        "bn": {"scale": jnp.asarray(rng.randn(8), jnp.float32)},
    }


def _make_engine(params, **kw):
    named, _ = named_flatten(params)
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0, **kw)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    layout, engine = dist.make_flat(params)
    return layout, engine


def _exchange_fn(engine, mesh, send_frac=None):
    def worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = engine.exchange(fg, mem, key, "data", W,
                                   send_frac=send_frac)
        return out[None], jax.tree.map(lambda x: x[None], mem)

    return jax.jit(shard_map(
        worker, mesh=mesh, in_specs=(P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))


def _flat_grads(layout, params, seed):
    named, treedef = named_flatten(params)
    rng = np.random.RandomState(seed)
    grads_w = {n: jnp.asarray(rng.randn(W, *p.shape), jnp.float32)
               for n, p in named.items()}
    return jnp.stack([
        layout.flatten(named_unflatten({n: grads_w[n][w] for n in named},
                                       treedef))
        for w in range(W)])


def _mem0(engine):
    return jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                        engine.init_memory())


def _run_parity(mesh8, steps, mk_kwargs, send_frac=None, seed=37):
    """megakernel engine vs the default unfused engine: bitwise output,
    transmit record, and materialized error-feedback state per step."""
    params = _params()
    _, engine_u = _make_engine(params)
    layout, engine_m = _make_engine(params, **mk_kwargs)

    # the routing gates themselves: the megakernel engine must actually
    # take both fused passes, the default engine neither
    assert engine_u._mk_fwd_ids == ()
    assert engine_m._mk_fwd_ids, "no bucket took the forward megakernel"
    assert engine_m._use_megakernel_apply(engine_m._mem, False, jnp.float32)
    assert not engine_u._use_megakernel_apply(
        engine_u._mem, False, jnp.float32)

    flat_grads_w = _flat_grads(layout, params, seed)
    fn_u = _exchange_fn(engine_u, mesh8, send_frac=send_frac)
    fn_m = _exchange_fn(engine_m, mesh8, send_frac=send_frac)
    mem_u, mem_m = _mem0(engine_u), _mem0(engine_m)
    for step in range(steps):
        key = jax.random.PRNGKey(step)
        out_u, mem_u = fn_u(flat_grads_w, mem_u, key)
        out_m, mem_m = fn_m(flat_grads_w, mem_m, key)
        np.testing.assert_array_equal(np.asarray(out_m), np.asarray(out_u),
                                      err_msg=f"step {step}")
        np.testing.assert_array_equal(np.asarray(mem_m["sent_bits"]),
                                      np.asarray(mem_u["sent_bits"]),
                                      err_msg=f"bits step {step}")
        fu = {k: np.asarray(v) for k, v in engine_u.memory_full(
            jax.tree.map(lambda x: x[0], mem_u)).items()}
        fm = {k: np.asarray(v) for k, v in engine_m.memory_full(
            jax.tree.map(lambda x: x[0], mem_m)).items()}
        for mkey in ("momentums", "velocities"):
            np.testing.assert_array_equal(fm[mkey], fu[mkey],
                                          err_msg=f"{mkey} step {step}")
    return engine_m


def test_exchange_megakernel_matches_default(mesh8):
    """The acceptance pin: DGCCompressor(megakernel=True) over 3 real
    W=8 steps is BITWISE the default engine — exchanged gradient,
    packed transmit record, and folded-back error-feedback state."""
    engine_m = _run_parity(mesh8, 3, dict(megakernel=True))
    # the size DP packs conv1+conv2+dense into ONE multi-row bucket:
    # the megakernel grid covers R > 1 (and a structurally-ragged tail)
    assert any(engine_m.buckets[bi].rows > 1
               for bi in engine_m._mk_fwd_ids)


def test_exchange_megakernel_with_fused_flags(mesh8):
    """megakernel=True composes with (and takes precedence over) the
    standalone fused_select / fused_apply opt-ins: still bitwise the
    plain engine."""
    _run_parity(mesh8, 2, dict(megakernel=True, fused_select=True,
                               fused_apply=True), seed=41)


def test_exchange_megakernel_send_frac(mesh8):
    """Straggler-adaptive masking rides the megakernel selection: the
    post-selection keep mask sees the same (values, indices), so the
    degraded wire stays bitwise the unfused degraded wire."""
    engine_m = _run_parity(mesh8, 2, dict(megakernel=True),
                           send_frac=0.5, seed=43)
    assert engine_m._adaptive_rank is not None


def test_exchange_megakernel_multibucket(mesh8):
    """Two size buckets, each on the megakernel path: a ~328k tensor
    splits off its own bucket under the size DP (its padding would dwarf
    a bucket floor), the small tensors share a second — every bucket
    launches its own forward pass and the reassembled state stays
    bitwise the unfused engine's."""
    rng = np.random.RandomState(5)
    params = {"wide": {"kernel": jnp.asarray(rng.randn(256, 256),
                                             jnp.float32)}}
    for i in range(6):
        params[f"s{i}"] = {
            "kernel": jnp.asarray(rng.randn(16, 20), jnp.float32)}
    named, _ = named_flatten(params)

    def make(mk):
        comp = DGCCompressor(0.001, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, megakernel=mk)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        return dist.make_flat(params)

    layout, engine_m = make(True)
    _, engine_u = make(False)
    assert len(engine_m.buckets) >= 2
    assert len(engine_m._mk_fwd_ids) >= 2
    flat_grads_w = _flat_grads(layout, params, 47)
    fn_u = _exchange_fn(engine_u, mesh8)
    fn_m = _exchange_fn(engine_m, mesh8)
    mem_u, mem_m = _mem0(engine_u), _mem0(engine_m)
    for step in range(2):
        key = jax.random.PRNGKey(step)
        out_u, mem_u = fn_u(flat_grads_w, mem_u, key)
        out_m, mem_m = fn_m(flat_grads_w, mem_m, key)
        np.testing.assert_array_equal(np.asarray(out_m), np.asarray(out_u),
                                      err_msg=f"step {step}")
        np.testing.assert_array_equal(np.asarray(mem_m["sent_bits"]),
                                      np.asarray(mem_u["sent_bits"]),
                                      err_msg=f"bits step {step}")


def test_megakernel_bf16_state_keeps_unfused_path():
    """bf16 error-feedback state: the kernel refuses narrow state, so
    the plan-static gate must route every bucket to the unfused path
    even with megakernel=True."""
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(
        0.05, memory=DGCSGDMemory(momentum=0.9, dtype="bfloat16"),
        sample_ratio=1.0, megakernel=True)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    _, engine = dist.make_flat(params)
    assert engine._megakernel
    assert engine._mk_fwd_ids == ()


def test_megakernel_env_opt_in(monkeypatch):
    """DGC_MEGAKERNEL=1 flips the engine gate without touching the
    compressor ctor — the bench A/B entry point."""
    monkeypatch.setenv("DGC_MEGAKERNEL", "1")
    params = _params()
    _, engine = _make_engine(params)
    assert engine._megakernel
    assert engine._mk_fwd_ids
