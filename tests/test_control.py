"""Tests for the fleet control plane (ISSUE 12): the supervise.py compat
pin, rule-engine debounce/budget hygiene, detector units, fleet-root
discovery with torn shards, the merged per-run-labeled OpenMetrics
exposition, and the multi-run control drill — concurrent fake runs with
an injected straggler, offline residual corruption, and a nonfinite
abort, where the rule engine must remediate exactly the offending runs
with the right evidence and leave the healthy run untouched.

Everything here is host-only (subprocesses + JSONL + threads, no jax),
so the whole file is ``fast``-marked (scripts/t1.sh CONTROL_SMOKE).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from dgc_tpu.control import actions, plane as plane_mod, rules
from dgc_tpu.control.plane import ControlPlane, RunSpec
from dgc_tpu.control.rules import Rule, RuleEngine
from dgc_tpu.telemetry import fleet, monitor, registry

from test_fleet import _write_run

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "control_worker.py")


# --------------------------------------------------------------------- #
# scripts/supervise.py stays a thin CLI: flag surface + event schema     #
# --------------------------------------------------------------------- #

def _load_supervise():
    spec = importlib.util.spec_from_file_location(
        "supervise_compat", os.path.join(ROOT, "scripts", "supervise.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.fast
def test_supervise_cli_compat(tmp_path):
    # the script keeps re-exporting the library surface PR-5 tooling and
    # tests import from its path
    sup_mod = _load_supervise()
    for name in ("parse_env_file", "checkpoint_progress", "COHORT_KEYS",
                 "default_events_path", "Supervisor", "main"):
        assert hasattr(sup_mod, name), name
    from dgc_tpu.control import supervisor as lib
    assert sup_mod.Supervisor is lib.Supervisor

    # pinned flag surface
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "supervise.py"),
         "--help"], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    for flag in ("--retries", "--backoff", "--backoff-max", "--env-file",
                 "--watch", "--events-out", "--events", "--success-codes"):
        assert flag in out.stdout, flag

    # pinned event schema through the real CLI entrypoint (in-process)
    events = tmp_path / "supervise_events.jsonl"
    rc = sup_mod.main(["--retries", "1", "--backoff", "0.05",
                       "--events-out", str(events), "--",
                       sys.executable, "-c", "raise SystemExit(0)"])
    assert rc == 0
    recs = [json.loads(l) for l in events.read_text().splitlines()]
    assert [r["event"] for r in recs] == ["launch", "done"]
    for r in recs:
        assert {"event", "t", "launches", "run_id", "cohort"} <= set(r)
    assert recs[0]["cmd"][-1] == "raise SystemExit(0)"
    assert "env_overrides" in recs[0]
    assert recs[1]["rc"] == 0 and "elapsed" in recs[1]


@pytest.mark.fast
def test_supervisor_quarantines_exit_70(tmp_path):
    # the nonfinite-abort convention: exit 70 must NOT be relaunched
    from dgc_tpu.control.supervisor import Supervisor
    events = tmp_path / "ev.jsonl"
    sup = Supervisor([sys.executable, "-c", "raise SystemExit(70)"],
                     retries=5, backoff=0.05, events=str(events))
    rc = sup.run(install_signals=False)
    assert rc == 70
    assert sup.launches == 1 and sup.state == "quarantined"
    assert sup.quarantined == "exit:70"
    kinds = [json.loads(l)["event"] for l in events.read_text().splitlines()]
    assert kinds == ["launch", "quarantined"]


# --------------------------------------------------------------------- #
# rule engine: persistence, debounce, budget                             #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_rule_engine_debounce_and_budget():
    rule = Rule("r", lambda s: ({"kind": "x"} if s.get("bad") else None),
                "restart", min_hits=2, debounce_s=10.0, budget=2)
    eng = RuleEngine((rule,))
    bad, ok = {"bad": True}, {}

    assert eng.evaluate("a", bad, now=0.0) == []          # 1 hit < min_hits
    fired = eng.evaluate("a", bad, now=1.0)               # persistent: fire
    assert [r.name for r, _ in fired] == ["r"]
    assert fired[0][1] == {"kind": "x", "hits": 2, "firing": 1}
    assert eng.evaluate("a", bad, now=2.0) == []          # debounced
    assert eng.suppressed[("a", "r")] == 1
    fired = eng.evaluate("a", bad, now=12.0)              # debounce expired
    assert fired and fired[0][1]["firing"] == 2
    assert eng.evaluate("a", bad, now=30.0) == []         # budget exhausted
    assert eng.suppressed[("a", "r")] == 2

    # consecutive-hit counting resets on a quiet tick
    assert eng.evaluate("b", bad, now=0.0) == []
    assert eng.evaluate("b", ok, now=1.0) == []
    assert eng.evaluate("b", bad, now=2.0) == []          # back to 1 hit
    fired = eng.evaluate("b", bad, now=3.0)
    assert fired and fired[0][1]["hits"] == 2

    # a crashing detector reads as "no evidence", never raises
    boom = Rule("boom", lambda s: 1 / 0, "restart", min_hits=1)
    assert RuleEngine((boom,)).evaluate("a", bad, now=0.0) == []


@pytest.mark.fast
def test_default_rules_match_registry_and_actions():
    table = rules.default_rules()
    names = [r.name for r in table]
    assert names[0] == "nonfinite-quarantine"   # quarantine outranks all
    for r in table:
        assert r.action in registry.control_action_names(), r.name
        assert r.action in actions.ACTIONS, r.name


@pytest.mark.fast
def test_detectors_on_synthetic_snapshots():
    assert rules.detect_desync({}) is None
    ev = rules.detect_desync({"summary": {
        "desync_alerts": 4, "desync_workers": [2],
        "desync_first": {"step": 30}}})
    assert ev["kind"] == "desync" and ev["workers"] == [2]

    assert rules.detect_straggler({"summary": {
        "straggler_share": 1.1, "straggler_gap": 80.0, "straggler": 3}}) \
        is None                                        # share under floor
    ev = rules.detect_straggler({"summary": {
        "straggler_share": 8.0, "straggler_gap": 80.0, "straggler": 3}})
    assert ev["kind"] == "straggler" and ev["worker"] == 3

    ev = rules.detect_quarantine({"flight": {"reason": "nonfinite-streak",
                                             "records": 16}})
    assert ev["kind"] == "flight_dump"
    ev = rules.detect_quarantine({"last_supervise": {"event": "quarantined",
                                                     "rc": 70}})
    assert ev["kind"] == "nonfinite_abort" and ev["rc"] == 70
    ev = rules.detect_quarantine({"guards": {"nonfinite_rate": 1.0,
                                             "skipped_steps": 3}})
    assert ev["kind"] == "nonfinite_rate"
    assert rules.detect_quarantine({"guards": {"nonfinite_rate": 0.0}}) \
        is None

    ev = rules.detect_cohort_shrink({"num_hosts": 1,
                                     "static": {"num_processes": 2}})
    assert ev == {"kind": "cohort_shrink", "live_hosts": 1,
                  "spec_processes": 2}
    assert rules.detect_cohort_shrink({"num_hosts": 2,
                                       "static": {"num_processes": 2}}) \
        is None


@pytest.mark.fast
def test_publish_env_merges_atomically(tmp_path):
    path = tmp_path / "cohort.env"
    path.write_text("# seed\nJAX_NUM_PROCESSES=2\nJAX_COORDINATOR_ADDRESS"
                    "=h0:1234\n")
    merged = actions.publish_env(str(path),
                                 {"JAX_NUM_PROCESSES": "1"})
    assert merged == {"JAX_NUM_PROCESSES": "1",
                      "JAX_COORDINATOR_ADDRESS": "h0:1234"}
    from dgc_tpu.control.supervisor import parse_env_file
    assert parse_env_file(str(path)) == merged
    assert not [p for p in os.listdir(tmp_path)
                if p.startswith(".cohort.")]     # no temp litter


# --------------------------------------------------------------------- #
# fleet-root discovery + merged exposition                               #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_discover_runs_and_fleet_collect_with_torn_shards(tmp_path):
    root = str(tmp_path)
    _write_run(os.path.join(root, "runA"), hosts=1, world=4, steps=10)
    _write_run(os.path.join(root, "runB"), hosts=2, world=4, steps=10,
               torn=True)
    # a run whose only shard has a torn HEADER: discovered, unreadable
    bad = os.path.join(root, "runC", "telemetry", "host0")
    os.makedirs(bad)
    with open(os.path.join(bad, "telemetry.jsonl"), "w") as f:
        f.write('{"schema": "dgc-telem')
    # event streams and loose files at the root must not become runs
    with open(os.path.join(root, "control_events.jsonl"), "w") as f:
        f.write(json.dumps({"event": "plane_start", "t": 1.0}) + "\n")
    os.makedirs(os.path.join(root, "empty"))

    runs = fleet.discover_runs(root)
    assert sorted(runs) == ["runA", "runB", "runC"]

    # a single run dir degrades to itself; its host*/ shard dirs and
    # telemetry/ subdir are never split into fake "runs"
    assert fleet.discover_runs(os.path.join(root, "runB")) == \
        {"runB": os.path.join(root, "runB")}

    fsnap = monitor.collect_fleet(root)
    assert sorted(fsnap["runs"]) == ["runA", "runB", "runC"]
    assert fsnap["runs"]["runA"]["step"] == 9
    assert fsnap["runs"]["runB"]["skipped_lines"] == 1    # torn tail
    assert "error" in fsnap["runs"]["runC"]
    assert [e["event"] for e in fsnap["control"]] == ["plane_start"]

    om = monitor.render_openmetrics_fleet(fsnap)
    assert om.endswith("# EOF\n")
    assert 'dgc_step{run="runA"}' in om
    assert 'dgc_step{run="runB"}' in om
    assert 'dgc_worker_clock_ms{run="runA",worker="0"}' in om
    assert "dgc_runs 3" in om
    assert "dgc_runs_unreadable 1" in om
    # merged exposition: each family HELP/TYPE'd exactly once
    helps = [l.split()[2] for l in om.splitlines()
             if l.startswith("# HELP")]
    assert len(helps) == len(set(helps))

    ranked = monitor.rank_runs(fsnap)
    assert ranked[0]["name"] == "runC"                    # worst first
    assert ranked[0]["verdict"] == "unreadable"
    status = monitor.render_fleet_status(fsnap)
    assert "dgc fleet control" in status and "runC" in status


# --------------------------------------------------------------------- #
# the multi-run drill                                                    #
# --------------------------------------------------------------------- #

def _worker_cmd(run_dir, steps, step_ms=20):
    return [sys.executable, WORKER, run_dir,
            "--steps", str(steps), "--step-ms", str(step_ms)]


def _drill_rules():
    # the shipped detectors and action mapping, tuned to tick-fast for
    # the drill (production debounce is minutes, not milliseconds)
    return (
        Rule("nonfinite-quarantine", rules.detect_quarantine, "quarantine",
             min_hits=1, debounce_s=0.0, budget=1),
        Rule("desync-restart", rules.detect_desync, "restart",
             min_hits=2, debounce_s=5.0, budget=1),
        Rule("straggler-relaunch", rules.detect_straggler,
             "elastic_relaunch", min_hits=2, debounce_s=5.0, budget=1),
    )


@pytest.mark.fast
def test_control_plane_multi_run_drill(tmp_path):
    root = str(tmp_path)
    specs = [
        # worker 3's clock lane stretched 80ms -> straggler ->
        # elastic relaunch with a shrunken cohort spec
        RunSpec("slowpoke", _worker_cmd(os.path.join(root, "slowpoke"),
                                        steps=150),
                run_dir=os.path.join(root, "slowpoke"),
                env_file=os.path.join(root, "slowpoke", "cohort.env"),
                env={"DGC_FAULTS": "slow:ms=80",
                     "JAX_NUM_PROCESSES": "2"},
                backoff=0.1),
        # worker 2's residual mass walks away -> desync -> restart
        RunSpec("wobbly", _worker_cmd(os.path.join(root, "wobbly"),
                                      steps=150),
                run_dir=os.path.join(root, "wobbly"),
                env={"DGC_FAKE_DESYNC": "2"},
                backoff=0.1),
        # no faults: must complete untouched
        RunSpec("steady", _worker_cmd(os.path.join(root, "steady"),
                                      steps=40),
                run_dir=os.path.join(root, "steady"),
                backoff=0.1),
    ]
    plane = ControlPlane(specs, root, rules=_drill_rules(), interval=0.25)
    final = plane.run(max_ticks=400)

    # every run ended cleanly — the remediations cycled the faulty runs
    # through emergency save (exit 75) + relaunch, not crash loops
    assert final["steady"]["rc"] == 0
    assert final["slowpoke"]["rc"] == 0
    assert final["wobbly"]["rc"] == 0

    by_run = {}
    for a in plane.actions:
        by_run.setdefault(a["run"], []).append(a)

    # the healthy run was untouched: one launch, zero actions
    assert final["steady"]["launches"] == 1
    assert "steady" not in by_run

    # straggler -> elastic relaunch of slowpoke ONLY, with the worker
    # named in the evidence and a shrunken cohort spec published
    acts = by_run["slowpoke"]
    assert [a["action"] for a in acts] == ["elastic_relaunch"]
    ev = acts[0]["evidence"]
    assert ev["kind"] == "straggler" and ev["worker"] == 3
    assert ev["share"] >= 1.5 and ev["hits"] >= 2
    assert acts[0]["result"]["published"] == {"JAX_NUM_PROCESSES": "1"}
    assert acts[0]["result"]["delivered"] is True
    from dgc_tpu.control.supervisor import parse_env_file
    assert parse_env_file(specs[0].env_file) == {"JAX_NUM_PROCESSES": "1"}
    assert final["slowpoke"]["launches"] == 2
    # the relaunch picked the published cohort up: the env-file override
    # beats the spec's baseline env, and the worker recorded it
    snap = monitor.collect(os.path.join(root, "slowpoke"))
    assert snap["static"]["num_processes"] == 1

    # desync -> restart of wobbly ONLY, with the corrupted worker named
    acts = by_run["wobbly"]
    assert [a["action"] for a in acts] == ["restart"]
    ev = acts[0]["evidence"]
    assert ev["kind"] == "desync" and ev["workers"] == [2]
    assert acts[0]["result"]["delivered"] is True
    assert final["wobbly"]["launches"] == 2

    # the fleet event stream is the audit trail: plane lifecycle, every
    # supervisor event re-stamped with its run, every action recorded
    events = [json.loads(l) for l in open(
        os.path.join(root, "control_events.jsonl"))]
    kinds = [e["event"] for e in events]
    assert kinds[0] == "plane_start" and kinds[-1] == "plane_stop"
    assert kinds.count("control_action") == len(plane.actions) >= 2
    launches = [e for e in events if e["event"] == "launch"]
    assert {e["run"] for e in launches} == {"slowpoke", "wobbly", "steady"}
    for e in events:
        if e["event"] == "control_action":
            registry.validate_control_action(e)

    # merged OpenMetrics over the fleet root: every run's gauges under
    # its own run label (the supervisor run_id), plus the action counts
    fsnap = monitor.collect_fleet(root)
    om = monitor.render_openmetrics_fleet(fsnap)
    for name in ("slowpoke", "wobbly", "steady"):
        run_id = plane.supervisors[name].run_id
        assert f'dgc_step{{run="{run_id}"}}' in om, name
    assert "dgc_control_actions{" in om
    assert "dgc_runs 3" in om
    # the fleet status ranks the remediated runs' evidence visibly
    status = monitor.render_fleet_status(fsnap)
    assert "control actions" in status


@pytest.mark.fast
def test_control_plane_quarantines_nonfinite_run(tmp_path):
    root = str(tmp_path)
    run_dir = os.path.join(root, "cursed")
    spec = RunSpec("cursed", _worker_cmd(run_dir, steps=60),
                   run_dir=run_dir,
                   env={"DGC_FAKE_NONFINITE": "12"}, backoff=0.5)
    plane = ControlPlane([spec], root, rules=_drill_rules(), interval=0.2)
    final = plane.run(max_ticks=200)

    # exit 70 -> quarantined: exactly one launch, no relaunch
    assert final["cursed"]["rc"] == 70
    assert final["cursed"]["launches"] == 1
    assert final["cursed"]["state"] == "quarantined"

    # the quarantine is audited with the flight-dump evidence attached
    acts = [a for a in plane.actions if a["run"] == "cursed"]
    assert len(acts) == 1 and acts[0]["action"] == "quarantine"
    assert acts[0]["evidence"]["kind"] == "flight_dump"
    assert "nonfinite-streak" in acts[0]["evidence"]["reason"]

    # artifacts kept for post-mortem, and the monitor surfaces them
    assert os.path.isfile(os.path.join(run_dir, "flight.json"))
    snap = monitor.collect(run_dir)
    assert snap["flight"]["reason"].startswith("nonfinite-streak")
    assert snap["guards"]["nonfinite_rate"] == 1.0
    status = monitor.render_status(snap)
    assert "FLIGHT DUMP" in status and "GUARD TRIPS" in status
    om = monitor.render_openmetrics(snap)
    assert "dgc_flight_dump{" in om
    assert "dgc_guard_nonfinite_rate{" in om


# --------------------------------------------------------------------- #
# decorrelated-jitter backoff (pinned bounds)                           #
# --------------------------------------------------------------------- #

def _jitter_sup(backoff=2.0, backoff_max=30.0, seed=1234):
    from dgc_tpu.control.supervisor import Supervisor
    sup = Supervisor(["true"], backoff=backoff, backoff_max=backoff_max)
    sup._rng.seed(seed)
    return sup


@pytest.mark.fast
def test_backoff_first_retry_is_exactly_base():
    # failures == 1 resets the spread: the first retry after a fresh
    # failure streak waits exactly ``backoff``, deterministically
    sup = _jitter_sup(backoff=2.0, backoff_max=30.0)
    assert sup._next_delay(1) == 2.0
    sup._next_delay(4)              # widen the spread ...
    assert sup._next_delay(1) == 2.0    # ... progress resets it


@pytest.mark.fast
def test_backoff_jitter_bounds_pinned():
    # every delay obeys backoff <= d <= backoff_max, and each draw's
    # envelope is decorrelated: d_n <= min(3 * d_{n-1}, backoff_max)
    for seed in range(20):
        sup = _jitter_sup(backoff=2.0, backoff_max=30.0, seed=seed)
        prev = sup._next_delay(1)
        assert prev == 2.0
        for failures in range(2, 12):
            d = sup._next_delay(failures)
            assert 2.0 <= d <= 30.0, (seed, failures, d)
            assert d <= min(3.0 * prev, 30.0) + 1e-9, (seed, failures, d)
            prev = d


@pytest.mark.fast
def test_backoff_jitter_decorrelates_across_instances():
    # two supervisors born from one correlated failure must not back off
    # in lockstep (per-instance RNG, no shared stream)
    a = _jitter_sup(seed=1)
    b = _jitter_sup(seed=2)
    seq_a = [a._next_delay(f) for f in range(1, 8)]
    seq_b = [b._next_delay(f) for f in range(1, 8)]
    assert seq_a != seq_b
    # and the draws actually spread (not stuck at either bound)
    assert len({round(d, 6) for d in seq_a[1:]}) > 1


@pytest.mark.fast
def test_backoff_jitter_caps_at_backoff_max():
    sup = _jitter_sup(backoff=5.0, backoff_max=8.0, seed=7)
    delays = [sup._next_delay(f) for f in range(1, 10)]
    assert all(5.0 <= d <= 8.0 for d in delays)
    # degenerate config: base above cap clamps to the cap
    tight = _jitter_sup(backoff=10.0, backoff_max=4.0)
    assert tight._next_delay(1) == 4.0
    assert tight._next_delay(2) <= 4.0
