"""End-to-end convergence smoke (SURVEY.md §4 implication): compressed
training must track the dense baseline on a tiny problem."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu import (
    Compression,
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    dgc_sgd,
    sgd,
)
from dgc_tpu.models import resnet20
from dgc_tpu.parallel import make_mesh
from dgc_tpu.training import (
    TrainState,
    build_eval_step,
    build_train_step,
    shard_state,
    with_leading_axis,
)
from dgc_tpu.utils.pytree import named_flatten

W = 8
BS = 2  # per-worker


@pytest.fixture(scope="module")
def setup():
    model = resnet20(num_classes=10)
    v = model.init(jax.random.PRNGKey(42), jnp.zeros((1, 32, 32, 3)),
                   train=True)
    npr = np.random.RandomState(0)
    images = jnp.asarray(npr.randn(W * BS, 32, 32, 3), jnp.float32)
    labels = jnp.asarray(npr.randint(0, 10, W * BS), jnp.int32)
    return model, v, images, labels


def _make_state(dist, params, batch_stats, mesh):
    return shard_state(TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=dist.init(params),
        memory=with_leading_axis(dist.init_memory(params), W),
        batch_stats=with_leading_axis(batch_stats, W)), mesh)


def _train(model, v, images, labels, mesh, dist, steps=6):
    state = _make_state(dist, v["params"], v["batch_stats"], mesh)
    # donate=False: the module-scoped fixture's arrays alias into the state
    step_fn = build_train_step(model.apply, dist, mesh, donate=False)
    losses = []
    for i in range(steps):
        state, m = step_fn(state, images, labels, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    return state, losses


def test_dgc_loss_decreases_and_tracks_dense(mesh8, setup):
    model, v, images, labels = setup

    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dgc_dist = DistributedOptimizer(
        dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4), comp, world_size=W)
    _, dgc_losses = _train(model, v, images, labels, mesh8, dgc_dist)

    v2 = model.init(jax.random.PRNGKey(42), jnp.zeros((1, 32, 32, 3)),
                    train=True)
    dense_dist = DistributedOptimizer(
        sgd(0.1, momentum=0.9, weight_decay=1e-4), Compression.none(),
        world_size=W)
    _, dense_losses = _train(model, v2, images, labels, mesh8, dense_dist)

    assert dgc_losses[-1] < dgc_losses[0], dgc_losses
    assert dense_losses[-1] < dense_losses[0], dense_losses
    # same init, same data: first-step losses identical pre-update
    assert dgc_losses[0] == pytest.approx(dense_losses[0], rel=1e-5)
    # loose tracking on a memorization problem
    assert dgc_losses[-1] < dense_losses[0]


def test_eval_step_counts(mesh8, setup):
    model, v, images, labels = setup
    eval_fn = build_eval_step(model.apply, mesh8, W)
    bstats = with_leading_axis(v["batch_stats"], W)
    counts = eval_fn(v["params"], bstats, images, labels)
    n = int(counts["count"])
    assert n == W * BS
    assert 0 <= int(counts["top1"]) <= int(counts["top5"]) <= n


def test_micro_batch_accumulation_equivalence(mesh8, setup):
    """nbps=2 over a batch must equal nbps=1 over the same concatenated batch
    (grads are averaged identically; BN stats differ only in update order —
    use a BN-free check via loss value at step 1)."""
    model, v, images, labels = setup
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)

    def one(nbps, imgs, lbls):
        dist = DistributedOptimizer(
            dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4), comp,
            world_size=W)
        state = _make_state(dist, v["params"], v["batch_stats"], mesh8)
        fn = build_train_step(model.apply, dist, mesh8,
                              num_batches_per_step=nbps, donate=False)
        _, m = fn(state, imgs, lbls, jax.random.PRNGKey(0))
        return float(m["loss"])

    # nbps=2 needs W*2*bs inputs; duplicate the batch
    imgs2 = jnp.concatenate(
        [images.reshape(W, BS, 32, 32, 3)] * 2, axis=1).reshape(
            W * 2 * BS, 32, 32, 3)
    lbls2 = jnp.concatenate(
        [labels.reshape(W, BS)] * 2, axis=1).reshape(W * 2 * BS)
    l1 = one(1, images, labels)
    l2 = one(2, imgs2, lbls2)
    # duplicated micro-batches: mean loss identical
    assert l1 == pytest.approx(l2, rel=1e-5)


def test_warmup_rebuild_full_flat_train_step(mesh8):
    """train.py's per-epoch rebuild loop (train.py rebuild logic; reference
    compression.py:91-107) at the FULL flat train-step level: the wm5
    schedule's 6 ratio changes each rebuild the engine + re-jit the step
    while the train state (params, optimizer, error-feedback memory with a
    pending deferred mask) carries across; loss must stay finite and the
    memory must visibly survive each re-layout."""
    from flax import linen as nn
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state)

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.relu(x)
            x = nn.Conv(16, (3, 3))(x)
            x = nn.relu(x).mean(axis=(1, 2))
            return nn.Dense(10)(x)

    model = M()
    v = {"params": model.init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 16, 16, 3)))["params"],
         "batch_stats": {}}

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        out = model.apply({"params": variables["params"]}, x, train=train)
        return (out, {"batch_stats": {}}) if mutable else out

    comp = DGCCompressor(0.001, memory=DGCSGDMemory(momentum=0.9),
                         warmup_epochs=5)
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(
        dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4), comp, world_size=W)

    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh8,
                        dist_opt=dist)
    npr = np.random.RandomState(5)
    images = jnp.asarray(npr.randn(W * 4, 16, 16, 3), jnp.float32)
    labels = jnp.asarray(npr.randint(0, 10, W * 4), jnp.int32)

    step_fn = None
    vel_sums = []
    for epoch in range(7):
        if comp.warmup_compress_ratio(epoch) or step_fn is None:
            setup = make_flat_setup(v, dist)
            step_fn = build_train_step(apply_fn, dist, mesh8, donate=False,
                                       flat=setup)
        for s in range(2):
            state, m = step_fn(state, images, labels,
                               jax.random.PRNGKey(epoch * 10 + s))
            assert np.isfinite(float(m["loss"])), (epoch, s)
        vel = np.abs(np.asarray(jax.device_get(
            state.memory["velocities_c"]))).sum()
        vel_sums.append(float(vel))
    assert comp.compress_ratio == 0.001
    # error feedback accumulated and survived every re-layout (a reset
    # buffer would drop back to ~0 right after a rebuild)
    assert all(vs > 0 for vs in vel_sums[1:]), vel_sums


def test_mixed_precision_flat_step_matches_generic(mesh8):
    """build_train_step(model_dtype=bf16) — the flat mixed-precision
    micro branch (one [P] cast inside the differentiated function) —
    must produce the SAME training trajectory as the generic branch
    driving the identical bf16 model (where flax casts per use): the
    cast points are mathematically identical, so params/loss agree to
    f32 op-order tolerance across steps. The dense compressor keeps the
    comparison free of DGC's discrete selection (1-ulp gradient
    differences from the two program structures can flip top-k picks,
    which is a property of top-k, not of this branch)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from dgc_tpu import Compression, DistributedOptimizer, sgd
    from dgc_tpu.models import resnet20
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)

    W = 8
    model = resnet20(num_classes=10, dtype=jnp.bfloat16)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                   train=True)

    def build(model_dtype):
        dist = DistributedOptimizer(sgd(0.1, momentum=0.9),
                                    Compression.none(), world_size=W)
        setup = make_flat_setup(v, dist)
        state = shard_state(make_flat_state(v, dist, setup, W), mesh8,
                            dist_opt=dist)
        step = build_train_step(model.apply, dist, mesh8, flat=setup,
                                model_dtype=model_dtype)
        return step, state

    step_mp, state_mp = build(jnp.bfloat16)
    step_gen, state_gen = build(None)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(W * 2, 32, 32, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, W * 2), jnp.int32)
    # ONE step: the comparison pins the branch's semantics (loss scale,
    # stats packing, the cast-inside-grad structure). Tolerance is
    # bf16-level — the two program structures accumulate the bf16
    # backward in different orders (measured ~6e-5 abs on first-step
    # params), and that noise compounds chaotically through momentum
    # over further steps (a property of bf16 compute, not this branch).
    key = jax.random.PRNGKey(0)
    state_mp, m_mp = step_mp(state_mp, images, labels, key)
    state_gen, m_gen = step_gen(state_gen, images, labels, key)
    assert state_mp.params.dtype == jnp.float32         # f32 master copy
    np.testing.assert_allclose(float(m_mp["loss"]), float(m_gen["loss"]),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(state_mp.params),
                               np.asarray(state_gen.params),
                               rtol=1e-2, atol=1e-3)
    # and the branch actually trains: a second step lowers the loss
    state_mp, m2 = step_mp(state_mp, images, labels, jax.random.PRNGKey(1))
    assert np.isfinite(float(m2["loss"]))
