"""End-to-end convergence smoke (SURVEY.md §4 implication): compressed
training must track the dense baseline on a tiny problem."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu import (
    Compression,
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    dgc_sgd,
    sgd,
)
from dgc_tpu.models import resnet20
from dgc_tpu.parallel import make_mesh
from dgc_tpu.training import (
    TrainState,
    build_eval_step,
    build_train_step,
    shard_state,
    with_leading_axis,
)
from dgc_tpu.utils.pytree import named_flatten

W = 8
BS = 2  # per-worker


@pytest.fixture(scope="module")
def setup():
    model = resnet20(num_classes=10)
    v = model.init(jax.random.PRNGKey(42), jnp.zeros((1, 32, 32, 3)),
                   train=True)
    npr = np.random.RandomState(0)
    images = jnp.asarray(npr.randn(W * BS, 32, 32, 3), jnp.float32)
    labels = jnp.asarray(npr.randint(0, 10, W * BS), jnp.int32)
    return model, v, images, labels


def _make_state(dist, params, batch_stats, mesh):
    return shard_state(TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=dist.init(params),
        memory=with_leading_axis(dist.init_memory(params), W),
        batch_stats=with_leading_axis(batch_stats, W)), mesh)


def _train(model, v, images, labels, mesh, dist, steps=6):
    state = _make_state(dist, v["params"], v["batch_stats"], mesh)
    # donate=False: the module-scoped fixture's arrays alias into the state
    step_fn = build_train_step(model.apply, dist, mesh, donate=False)
    losses = []
    for i in range(steps):
        state, m = step_fn(state, images, labels, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    return state, losses


def test_dgc_loss_decreases_and_tracks_dense(mesh8, setup):
    model, v, images, labels = setup

    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dgc_dist = DistributedOptimizer(
        dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4), comp, world_size=W)
    _, dgc_losses = _train(model, v, images, labels, mesh8, dgc_dist)

    v2 = model.init(jax.random.PRNGKey(42), jnp.zeros((1, 32, 32, 3)),
                    train=True)
    dense_dist = DistributedOptimizer(
        sgd(0.1, momentum=0.9, weight_decay=1e-4), Compression.none(),
        world_size=W)
    _, dense_losses = _train(model, v2, images, labels, mesh8, dense_dist)

    assert dgc_losses[-1] < dgc_losses[0], dgc_losses
    assert dense_losses[-1] < dense_losses[0], dense_losses
    # same init, same data: first-step losses identical pre-update
    assert dgc_losses[0] == pytest.approx(dense_losses[0], rel=1e-5)
    # loose tracking on a memorization problem
    assert dgc_losses[-1] < dense_losses[0]


def test_eval_step_counts(mesh8, setup):
    model, v, images, labels = setup
    eval_fn = build_eval_step(model.apply, mesh8, W)
    bstats = with_leading_axis(v["batch_stats"], W)
    counts = eval_fn(v["params"], bstats, images, labels)
    n = int(counts["count"])
    assert n == W * BS
    assert 0 <= int(counts["top1"]) <= int(counts["top5"]) <= n


def test_micro_batch_accumulation_equivalence(mesh8, setup):
    """nbps=2 over a batch must equal nbps=1 over the same concatenated batch
    (grads are averaged identically; BN stats differ only in update order —
    use a BN-free check via loss value at step 1)."""
    model, v, images, labels = setup
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)

    def one(nbps, imgs, lbls):
        dist = DistributedOptimizer(
            dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4), comp,
            world_size=W)
        state = _make_state(dist, v["params"], v["batch_stats"], mesh8)
        fn = build_train_step(model.apply, dist, mesh8,
                              num_batches_per_step=nbps, donate=False)
        _, m = fn(state, imgs, lbls, jax.random.PRNGKey(0))
        return float(m["loss"])

    # nbps=2 needs W*2*bs inputs; duplicate the batch
    imgs2 = jnp.concatenate(
        [images.reshape(W, BS, 32, 32, 3)] * 2, axis=1).reshape(
            W * 2 * BS, 32, 32, 3)
    lbls2 = jnp.concatenate(
        [labels.reshape(W, BS)] * 2, axis=1).reshape(W * 2 * BS)
    l1 = one(1, images, labels)
    l2 = one(2, imgs2, lbls2)
    # duplicated micro-batches: mean loss identical
    assert l1 == pytest.approx(l2, rel=1e-5)
