"""Model zoo shapes/param-counts and data pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.data import Synthetic, epoch_batches, num_steps_per_epoch
from dgc_tpu.models import resnet20, resnet110, resnet18, resnet50, vgg16_bn


def _count(params):
    return sum(p.size for p in jax.tree.leaves(params))


def test_resnet20_shape_and_params():
    model = resnet20(num_classes=10)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)),
                   train=False)
    out = model.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
    assert out.shape == (2, 10)
    # standard resnet20 ≈ 0.27M (0.272M with option-A, slightly more with
    # projection shortcuts)
    n = _count(v["params"])
    assert 0.25e6 < n < 0.30e6, n


def test_resnet110_params():
    v = resnet110(num_classes=10).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    n = _count(v["params"])
    assert 1.6e6 < n < 1.85e6, n  # standard ≈ 1.7M


@pytest.mark.parametrize("ctor,expected", [
    (resnet18, 11.7e6), (resnet50, 25.6e6)])
def test_imagenet_resnets_params(ctor, expected):
    v = ctor(num_classes=1000).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False)
    n = _count(v["params"])
    assert abs(n - expected) / expected < 0.02, n


def test_resnet50_zero_init_residual():
    v = resnet50(num_classes=10, zero_init_residual=True).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    # find at least one BN scale that is all zeros
    zeros = [p for path, p in
             jax.tree_util.tree_flatten_with_path(v["params"])[0]
             if "scale" in str(path[-1]) and float(jnp.abs(p).sum()) == 0.0]
    assert zeros


def test_vgg16_bn_forward():
    model = vgg16_bn(num_classes=100)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                   train=False)
    out = model.apply(v, jnp.zeros((2, 224, 224, 3)), train=False)
    assert out.shape == (2, 100)
    n = _count(v["params"])
    assert abs(n - 134.7e6) / 134.7e6 < 0.03, n  # torchvision ≈ 134.7M


def test_vgg_dropout_needs_rng():
    model = vgg16_bn(num_classes=10)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                   train=False)
    out = model.apply(v, jnp.zeros((1, 224, 224, 3)), train=True,
                      rngs={"dropout": jax.random.PRNGKey(1)},
                      mutable=["batch_stats"])
    assert out[0].shape == (1, 10)


def test_synthetic_dataset_batches():
    ds = Synthetic(num_classes=10, image_size=32, n_train=100, n_test=20)
    split = ds["train"]
    assert len(split) == 100
    batches = list(epoch_batches(len(split), 32, epoch=0))
    assert all(len(b) == 32 for b in batches)
    assert len(batches) == num_steps_per_epoch(100, 32)
    x, y = split.get_batch(batches[0])
    assert x.shape == (32, 32, 32, 3) and x.dtype == np.float32
    assert y.shape == (32,) and y.dtype == np.int32


def test_epoch_batches_deterministic_per_epoch():
    a = list(epoch_batches(100, 32, epoch=3, seed=5))
    b = list(epoch_batches(100, 32, epoch=3, seed=5))
    c = list(epoch_batches(100, 32, epoch=4, seed=5))
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert not all(np.array_equal(x, y) for x, y in zip(a, c))


def test_epoch_batches_tiny_dataset_pads():
    batches = list(epoch_batches(5, 16, epoch=0))
    assert all(len(b) == 16 for b in batches)


def test_meters():
    from dgc_tpu.utils.meters import TopKClassMeter
    m = TopKClassMeter(k=2)
    outputs = np.asarray([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    m.update(outputs, np.asarray([0, 0]))  # top2 of row0 = {1,0} hit; row1 hit
    assert m.compute() == 100.0
    data = m.data()
    m2 = TopKClassMeter(k=2)
    m2.set({k: v * 4 for k, v in data.items()})  # simulated Sum-allreduce
    assert m2.compute() == 100.0
