"""Model zoo shapes/param-counts and data pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.data import Synthetic, epoch_batches, num_steps_per_epoch
from dgc_tpu.models import resnet20, resnet110, resnet18, resnet50, vgg16_bn


def _count(params):
    return sum(p.size for p in jax.tree.leaves(params))


def test_resnet20_shape_and_params():
    model = resnet20(num_classes=10)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)),
                   train=False)
    out = model.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
    assert out.shape == (2, 10)
    # standard resnet20 ≈ 0.27M (0.272M with option-A, slightly more with
    # projection shortcuts)
    n = _count(v["params"])
    assert 0.25e6 < n < 0.30e6, n


def test_resnet110_params():
    v = resnet110(num_classes=10).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    n = _count(v["params"])
    assert 1.6e6 < n < 1.85e6, n  # standard ≈ 1.7M


@pytest.mark.parametrize("ctor,expected", [
    (resnet18, 11.7e6), (resnet50, 25.6e6)])
def test_imagenet_resnets_params(ctor, expected):
    v = ctor(num_classes=1000).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False)
    n = _count(v["params"])
    assert abs(n - expected) / expected < 0.02, n


def test_resnet50_zero_init_residual():
    v = resnet50(num_classes=10, zero_init_residual=True).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    # find at least one BN scale that is all zeros
    zeros = [p for path, p in
             jax.tree_util.tree_flatten_with_path(v["params"])[0]
             if "scale" in str(path[-1]) and float(jnp.abs(p).sum()) == 0.0]
    assert zeros


def test_vgg16_bn_forward():
    model = vgg16_bn(num_classes=100)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                   train=False)
    out = model.apply(v, jnp.zeros((2, 224, 224, 3)), train=False)
    assert out.shape == (2, 100)
    n = _count(v["params"])
    assert abs(n - 134.7e6) / 134.7e6 < 0.03, n  # torchvision ≈ 134.7M


def test_vgg_dropout_needs_rng():
    model = vgg16_bn(num_classes=10)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                   train=False)
    out = model.apply(v, jnp.zeros((1, 224, 224, 3)), train=True,
                      rngs={"dropout": jax.random.PRNGKey(1)},
                      mutable=["batch_stats"])
    assert out[0].shape == (1, 10)


def test_synthetic_dataset_batches():
    ds = Synthetic(num_classes=10, image_size=32, n_train=100, n_test=20)
    split = ds["train"]
    assert len(split) == 100
    batches = list(epoch_batches(len(split), 32, epoch=0))
    assert all(len(b) == 32 for b in batches)
    assert len(batches) == num_steps_per_epoch(100, 32)
    x, y = split.get_batch(batches[0])
    assert x.shape == (32, 32, 32, 3) and x.dtype == np.float32
    assert y.shape == (32,) and y.dtype == np.int32


def test_epoch_batches_deterministic_per_epoch():
    a = list(epoch_batches(100, 32, epoch=3, seed=5))
    b = list(epoch_batches(100, 32, epoch=3, seed=5))
    c = list(epoch_batches(100, 32, epoch=4, seed=5))
    assert all(np.array_equal(x, y) for x, y in zip(a, b))
    assert not all(np.array_equal(x, y) for x, y in zip(a, c))


def test_epoch_batches_tiny_dataset_pads():
    batches = list(epoch_batches(5, 16, epoch=0))
    assert all(len(b) == 16 for b in batches)


def test_meters():
    from dgc_tpu.utils.meters import TopKClassMeter
    m = TopKClassMeter(k=2)
    outputs = np.asarray([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
    m.update(outputs, np.asarray([0, 0]))  # top2 of row0 = {1,0} hit; row1 hit
    assert m.compute() == 100.0
    data = m.data()
    m2 = TopKClassMeter(k=2)
    m2.set({k: v * 4 for k, v in data.items()})  # simulated Sum-allreduce
    assert m2.compute() == 100.0


@pytest.mark.parametrize("k", [1, 2, 5])
def test_meter_ties_vs_device_topk(k):
    """Tie semantics of the host meter (np.argpartition) vs the on-device
    eval count (jax.lax.top_k membership, build_eval_step). Neither order
    within a tied group is specified, so the contract is set membership:

    - target strictly inside the top k (fewer than k scores >= its own,
      counting itself last): BOTH must count it correct;
    - k scores strictly above the target: BOTH must count it wrong;
    - ties straddling the k-th boundary that include the target: each
      implementation may pick either side — only bounded, not pinned.

    Both counts must land inside the per-row [guaranteed, possible] band;
    on unambiguous rows they must agree exactly."""
    from dgc_tpu.utils.meters import TopKClassMeter

    rng = np.random.RandomState(7 + k)
    N, C = 256, 10
    # tie-heavy scores: small integer support so boundary ties are common
    outputs = rng.randint(0, 4, size=(N, C)).astype(np.float32)
    targets = rng.randint(0, C, size=(N,)).astype(np.int32)

    m = TopKClassMeter(k=k)
    m.update(outputs, targets)
    host = m.num_correct

    # the device-side count, exactly as build_eval_step computes it
    _, pred = jax.lax.top_k(jnp.asarray(outputs), min(k, C))
    dev = int(jnp.sum(jnp.any(
        pred == jnp.asarray(targets)[:, None], axis=-1)))

    ts = outputs[np.arange(N), targets]
    above = (outputs > ts[:, None]).sum(axis=-1)
    at_or_above = (outputs >= ts[:, None]).sum(axis=-1)  # includes target
    must = at_or_above <= k        # any valid top-k set contains the target
    cant = above >= k              # no valid top-k set contains the target
    ambiguous = ~must & ~cant
    lo, hi = int(must.sum()), int((~cant).sum())
    assert lo <= host <= hi, (host, lo, hi)
    assert lo <= dev <= hi, (dev, lo, hi)

    # unambiguous rows: per-row agreement, not just aggregate
    sub = ~ambiguous
    mu = TopKClassMeter(k=k)
    mu.update(outputs[sub], targets[sub])
    _, pu = jax.lax.top_k(jnp.asarray(outputs[sub]), min(k, C))
    du = int(jnp.sum(jnp.any(
        pu == jnp.asarray(targets[sub])[:, None], axis=-1)))
    assert mu.num_correct == du == int(must[sub].sum())


@pytest.mark.parametrize("ctor,shape", [
    (resnet20, (32, 32)), (resnet18, (56, 56)), (vgg16_bn, (224, 224))])
def test_bf16_compute_keeps_f32_params_and_logits(ctor, shape):
    """configs/bf16.py contract: dtype=bfloat16 switches COMPUTE only —
    parameters stay float32 (so the compression pipeline sees f32 grads)
    and logits come back float32."""
    model = ctor(num_classes=10, dtype=jnp.bfloat16)
    x = jnp.zeros((2, *shape, 3), jnp.float32)
    v = model.init(jax.random.PRNGKey(0), x, train=False)
    for leaf in jax.tree.leaves(v["params"]):
        assert leaf.dtype == jnp.float32, leaf.dtype
    out = model.apply(v, x, train=False)
    assert out.dtype == jnp.float32


def test_bf16_dgc_train_step(mesh8):
    """Full DGC train step with a bf16-compute model on the 8-way mesh:
    runs, loss finite, f32 gradients flow through the flat engine."""
    from dgc_tpu import DGCCompressor, DGCSGDMemory, DistributedOptimizer, dgc_sgd
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)
    from dgc_tpu.utils.pytree import named_flatten

    W = 8
    model = resnet20(num_classes=10, dtype=jnp.bfloat16)
    v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                   train=True)
    named, _ = named_flatten(v["params"])
    comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    setup = make_flat_setup(v, dist)
    assert setup.layout.dtype == np.float32
    state = shard_state(make_flat_state(v, dist, setup, W), mesh8,
                        dist_opt=dist)
    step = build_train_step(model.apply, dist, mesh8, flat=setup)
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(W * 2, 32, 32, 3), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 10, W * 2), jnp.int32)
    losses = []
    for i in range(4):
        state, m = step(state, images, labels, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert state.params.dtype == jnp.float32
