"""dgclint layer 2: contract primitives, the HLO parsers, and the
standing suite over the real flat train step.

The suite test here IS the repo's invariant mechanism (ISSUE 3): one
sparse exchange, telemetry compiles away, donation aliases, barrier-free
fused epilogue, trace stability across config variants, collective-free
shard_state."""

import jax
import jax.numpy as jnp
import pytest

from dgc_tpu.analysis import hlo
from dgc_tpu.analysis.contracts import (Contract, ContractViolation,
                                        RecompileGuard, trace_count)

# --------------------------------------------------------------------- #
# hlo text parsers (synthetic inputs)                                    #
# --------------------------------------------------------------------- #

_LOWERED = """\
module @jit_f {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = stablehlo.constant dense<1.0> : tensor<8xf32>
    %1 = "stablehlo.all_gather"(%arg0) : (tensor<8xf32>) -> tensor<8xf32>
    %2 = "stablehlo.all_reduce"(%1) : (tensor<8xf32>) -> tensor<8xf32>
    %3 = "stablehlo.all_reduce"(%2) : (tensor<8xf32>) -> tensor<8xf32>
    %4 = stablehlo.optimization_barrier %3 : tensor<8xf32>
    %5 = stablehlo.add %4, %0 : tensor<8xf32>
    return %5 : tensor<8xf32>
  }
}
"""

_COMPILED_DONATED = (
    "HloModule jit_f, is_scheduled=true, "
    "input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }"
    ", entry_computation_layout={(f32[8]{0})->f32[8]{0}}")

_COMPILED_PLAIN = ("HloModule jit_f, is_scheduled=true, "
                   "entry_computation_layout={(f32[8]{0})->f32[8]{0}}")


def test_op_counts_and_normalization():
    c = hlo.op_counts(_LOWERED)
    assert c["all-gather"] == 1 and c["all-reduce"] == 2
    assert c["optimization-barrier"] == 1 and c["add"] == 1
    assert hlo.count_op(_LOWERED, "all_gather") == 1
    assert hlo.normalize_op("stablehlo.all_gather") == "all-gather"


def test_collective_counts_zero_filled():
    c = hlo.collective_counts(_LOWERED)
    assert c["all-to-all"] == 0 and c["reduce-scatter"] == 0


def test_has_f64():
    assert not hlo.has_f64(_LOWERED)
    assert hlo.has_f64("%0 = stablehlo.constant : tensor<4xf64>")
    assert hlo.has_f64("param = f64[8]{0} parameter(0)")
    assert not hlo.has_f64("bf16[8] and f16[8] are fine")


def test_donated_params_parses_nested_braces():
    assert hlo.donated_params(_COMPILED_DONATED) == [0, 2]
    assert hlo.donated_params(_COMPILED_PLAIN) == []


# --------------------------------------------------------------------- #
# Contract primitives (no lowering: inject texts)                        #
# --------------------------------------------------------------------- #

def _contract(**kw):
    return Contract("t", lowered_text=_LOWERED,
                    compiled_text=_COMPILED_DONATED, **kw)


def test_contract_collectives_pass_and_fail():
    assert _contract().expects(
        collectives={"all-gather": 1, "all_reduce": 2}).check() == []
    bad = _contract().expects(collectives={"all-gather": 3}).check()
    assert len(bad) == 1 and "expected 3" in bad[0]


def test_contract_forbid_and_require_ops():
    assert _contract().expects(require_ops=["all_gather"]).check() == []
    assert "forbidden op" in _contract().expects(
        forbid_ops=["optimization_barrier"]).check()[0]
    assert "required op" in _contract().expects(
        require_ops=["reduce-scatter"]).check()[0]


def test_contract_forbid_substrings_and_f64():
    assert _contract().expects(forbid_substrings=["telemetry"],
                               no_f64=True).check() == []
    assert "forbidden substring" in _contract().expects(
        forbid_substrings=["all_gather"]).check()[0]


def test_contract_donation_expectations():
    assert _contract().expects(donation=[0, 2]).check() == []
    assert "not aliased" in _contract().expects(donation=[1]).check()[0]
    plain = Contract("p", compiled_text=_COMPILED_PLAIN)
    assert plain.expects(donation=[]).check() == []
    assert "silently dropped" in Contract(
        "p2", compiled_text=_COMPILED_PLAIN).expects(
        donation=[0]).check()[0]
    assert "expected no aliasing" in _contract().expects(
        donation=[]).check()[0]


def test_contract_identical_and_delta():
    same = Contract("b", lowered_text=_LOWERED)
    assert _contract().expects(identical_to=same).check() == []
    other = Contract("c", lowered_text=_LOWERED.replace(
        "add", "subtract"))
    bad = _contract().expects(identical_to=other).check()
    assert "byte-identical" in bad[0]
    assert _contract().expects(
        collectives_delta=(other, {"all-reduce": 0})).check() == []
    assert "delta" in _contract().expects(
        collectives_delta=(other, {"all-reduce": 1})).check()[0]


def test_enforce_raises_with_all_violations():
    with pytest.raises(ContractViolation) as ei:
        _contract().expects(collectives={"all-gather": 9},
                            forbid_ops=["add"]).enforce()
    assert len(ei.value.violations) == 2


# --------------------------------------------------------------------- #
# recompile guard on live jits                                           #
# --------------------------------------------------------------------- #

def test_trace_count_requires_jit_wrapper():
    with pytest.raises(TypeError):
        trace_count(lambda x: x)


def test_recompile_guard_passes_on_cache_hits():
    f = jax.jit(lambda x: x * 2)
    with RecompileGuard(f, expect=1):
        f(jnp.ones((4,)))
        f(jnp.zeros((4,)))          # same shape: cache hit


def test_recompile_guard_traps_shape_retrace():
    f = jax.jit(lambda x: x * 2)
    with pytest.raises(ContractViolation, match="cache key"):
        with RecompileGuard(f, expect=1):
            f(jnp.ones((4,)))
            f(jnp.ones((5,)))       # new shape: second trace


# --------------------------------------------------------------------- #
# the standing suite over the real step (ISSUE 3 acceptance pins)        #
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def suite_results(mesh8):
    from dgc_tpu.analysis.suite import run_contract_suite
    return run_contract_suite(mesh8)


def test_contract_suite_all_green(suite_results):
    failed = {n: v for n, v in suite_results if v}
    assert not failed, failed


@pytest.mark.parametrize("pin", [
    "flat-step-one-sparse-exchange",
    "telemetry-on-exactly-one-pmean",
    "telemetry-off-compiles-away",
    "donated-state-aliases-outputs",
    "fused-epilogue-no-opt-barriers",
    "recompile-guard-same-shapes",
    "shard-state-collective-free",
    "control-plane-host-only",
])
def test_suite_covers_named_pin(suite_results, pin):
    assert pin in {n for n, _ in suite_results}


def test_fused_epilogue_contract_standalone():
    from dgc_tpu.analysis.suite import _epilogue_contract
    _epilogue_contract().enforce()


def test_recompile_guard_across_config_variants(mesh8):
    """Flipping donate/use_dropout/telemetry must each build a step that
    traces exactly once for same-shape calls (the flags are Python-static,
    never part of a per-call cache key)."""
    from dgc_tpu.analysis.suite import build_fixture

    for kw in (dict(donate=False, telemetry=False),
               dict(donate=False, telemetry=True),
               dict(donate=False, use_dropout=True),
               dict(donate=True,)):
        state, step, _, (images, labels, key) = build_fixture(mesh8, **kw)
        with RecompileGuard(step, expect=1, name=str(kw)):
            out = step(state, images, labels, key)
            # thread the fresh state through: under donate=True the input
            # buffers are consumed by the first call
            step(out[0], images, labels, jax.random.PRNGKey(3))
