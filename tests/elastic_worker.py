"""Worker program for the elastic-restart drills
(tests/test_multiprocess.py::test_elastic_cross_topology_resume and
tests/test_elastic.py::test_supervisor_relaunch_smoke).

Single-process launches over a configurable fake-device count (the world
size W comes from argv BEFORE jax imports, so each phase can run a
different topology against the same checkpoint directory):

* ``baseline W`` — train TOTAL_STEPS uninterrupted at W workers on a
  learnable synthetic task; record the per-step losses.
* ``save W`` — train SAVE_STEPS at W workers and write a checkpoint with
  the ``_topology`` record.
* ``resume W from_world`` — restore the ``save`` phase's checkpoint at a
  DIFFERENT world size with ``elastic=True``; verify per-parameter
  residual + momentum gradient mass against an independent NumPy oracle
  computed from the RAW old-world state (fold each worker's pending
  transmit record, then sum — exact up to fp addition order); train the
  remaining steps with the SAME global batch.
* ``supervised W`` — one launch of the supervisor smoke child: train
  under a PreemptionHandler with ``DGC_FAULTS=kill@3`` armed by the
  parent; the first launch SIGTERMs itself after step 3, emergency-saves
  (topology stamped), appends a result line, and exits 75 so
  scripts/supervise.py relaunches; the relaunch resumes at step 4 and
  completes.

Each phase prints one ``RESULT:<json>`` line (the ``supervised`` phase
also appends it to ``<workdir>/results.jsonl``, one line per launch).
"""

import json
import os
import sys

NDEV = int(sys.argv[2])
os.environ["XLA_FLAGS"] = (
    f"--xla_force_host_platform_device_count={NDEV}")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TOTAL_STEPS = 24
SAVE_STEPS = 10
SUP_TOTAL = 6
SUP_KILL = 3
GLOBAL_BS = 16          # fixed across world sizes: same data every phase


def main():
    phase = sys.argv[1]
    workdir = sys.argv[3]
    assert phase in ("baseline", "save", "resume", "supervised"), phase

    import getpass
    import tempfile
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(tempfile.gettempdir(),
                                   f"dgc_tpu_test_jax_cache_"
                                   f"{getpass.getuser()}"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import jax.numpy as jnp
    import numpy as np
    from flax import linen as nn
    from jax.sharding import Mesh

    from dgc_tpu import (DGCCompressor, DGCSGDMemory, DistributedOptimizer,
                         dgc_sgd)
    from dgc_tpu.parallel.multihost import host_local_to_global
    from dgc_tpu.resilience import elastic, faults, preempt
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)
    from dgc_tpu.training.checkpoint import CheckpointManager
    from dgc_tpu.utils.pytree import named_flatten

    W = len(jax.devices())
    assert W == NDEV, (W, NDEV)
    mesh = Mesh(np.array(jax.devices()), ("data",))

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x.mean(axis=(1, 2)))

    model = M()
    v = dict(model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3))))

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        if mutable:
            return model.apply(variables, x, train=train, mutable=mutable,
                               rngs=rngs)
        return model.apply(variables, x, train=train)

    comp = DGCCompressor(0.1, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.15, momentum=0.9), comp,
                                world_size=W)
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh,
                        dist_opt=dist)
    step_fn = build_train_step(apply_fn, dist, mesh, donate=False,
                               flat=setup)

    # learnable task (the tests/test_convergence.py pattern): class
    # prototypes + noise, so the loss trajectory genuinely descends and
    # "resumed training still converges" is a meaningful assertion
    protos = np.random.RandomState(7).randn(10, 16, 16, 3) * 1.5

    def batch(i):
        """Deterministic GLOBAL batch for step i — world-size
        independent, so every topology sees the same data sequence."""
        rng = np.random.RandomState(1000 + i)
        lb = rng.randint(0, 10, GLOBAL_BS).astype(np.int32)
        im = (protos[lb] + 0.2 * rng.randn(GLOBAL_BS, 16, 16, 3)
              ).astype(np.float32)
        return (host_local_to_global(im, mesh),
                host_local_to_global(lb, mesh))

    def train_range(state, lo, hi):
        losses = []
        for i in range(lo, hi):
            im, lb = batch(i)
            state, m = step_fn(state, im, lb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
            jax.block_until_ready(state)
        return state, losses

    # ----------------------------------------------------------------- #
    # independent NumPy oracle over the flat engine's memory layout
    # ----------------------------------------------------------------- #

    layout = setup.layout
    T = int(setup.engine.T)

    def oracle_keep(bits, total):
        """Bit-unpack straight from the documented layout (flat position
        p -> word (p // 4096) * 128 + (p % 128), bit (p // 128) % 32),
        written differently from elastic.keep_from_bits_np on purpose."""
        bits = np.asarray(bits).astype(np.uint32)
        p = np.arange(total)
        word = (p // 4096) * 128 + (p % 128)
        bit = (p // 128) % 32
        keep = ((bits[word] >> bit.astype(np.uint32)) & 1) == 0
        return keep

    def masses(mem_workers, momentum_masking=True):
        """Per-parameter momentum/velocity gradient mass summed over
        workers, pending transmit records folded, accumulated in f64."""
        out = {}
        nw = len(mem_workers["momentums_c"])
        folded_m = np.zeros(T, np.float64)
        folded_v = np.zeros(T, np.float64)
        for w in range(nw):
            keep = oracle_keep(mem_workers["sent_bits"][w], T)
            folded_v += np.where(keep,
                                 mem_workers["velocities_c"][w], 0.0)
            mk = keep if momentum_masking else np.ones(T, bool)
            folded_m += np.where(mk, mem_workers["momentums_c"][w], 0.0)
        dense_m = np.asarray(mem_workers["momentums_d"],
                             np.float64).sum(axis=0)
        dense_v = np.asarray(mem_workers["velocities_d"],
                             np.float64).sum(axis=0)
        for n in layout.names:
            off, size = layout.offsets[n], layout.sizes[n]
            if n in layout.compressed_names:
                m, vv = folded_m[off:off + size], folded_v[off:off + size]
            else:
                m = dense_m[off - T:off - T + size]
                vv = dense_v[off - T:off - T + size]
            out[n] = [float(m.sum()), float(vv.sum())]
        return out

    def host_memory(mem):
        return {k: np.asarray(jax.device_get(x)) for k, x in mem.items()}

    ckpt = CheckpointManager(os.path.join(workdir, "ckpt_elastic"), keep=3)
    out = {"phase": phase, "world": W}

    if phase == "baseline":
        state, losses = train_range(state, 0, TOTAL_STEPS)
        out["losses"] = losses

    elif phase == "save":
        state, losses = train_range(state, 0, SAVE_STEPS)
        topo = {"process_count": 1, "world": W, "num_local_workers": 1}
        ckpt.save(0, state, {"saved_steps": SAVE_STEPS}, topology=topo)
        out.update(losses=losses,
                   mass=masses(host_memory(state.memory)))

    elif phase == "resume":
        from_world = int(sys.argv[4])
        topo = {"process_count": 1, "world": W, "num_local_workers": 1}
        # raw restore at the OLD world: the oracle's ground truth
        raw_tmpl = elastic.with_world(state, from_world)
        raw = ckpt.restore(raw_tmpl)
        assert raw is not None, "save-phase checkpoint must restore"
        raw_mass = masses(host_memory(raw[0].memory))
        # the real elastic restore under the NEW topology
        restored = ckpt.restore(state, topology=topo, elastic=True,
                                elastic_opts=comp.elastic_reshard_opts())
        assert restored is not None
        r_state, r_epoch, meters = restored
        assert meters["_elastic"]["from_world"] == from_world
        assert meters["_elastic"]["to_world"] == W
        new_mass = masses(host_memory(r_state.memory))
        # per-parameter gradient mass conserved (exact up to fp addition)
        mass_rel = 0.0
        for n in layout.names:
            for a, b in zip(raw_mass[n], new_mass[n]):
                denom = max(abs(a), abs(b), 1e-6)
                mass_rel = max(mass_rel, abs(a - b) / denom)
        assert mass_rel < 1e-5, f"gradient mass not conserved: {mass_rel}"
        if W > from_world:
            # grow (1:k split): child c%k==0 inherits parent c//k
            # BITWISE (sent_bits included); siblings start zeroed —
            # their residual mass is zero and their keep mask is all-keep
            k = W // from_world
            raw_mem = host_memory(raw[0].memory)
            new_mem = host_memory(r_state.memory)
            for mkey, new_rows in new_mem.items():
                old_rows = raw_mem[mkey]
                for c in range(W):
                    if c % k == 0:
                        np.testing.assert_array_equal(
                            new_rows[c], old_rows[c // k],
                            err_msg=f"{mkey}[{c}] not bitwise-inherited")
                    else:
                        assert not np.any(new_rows[c]), \
                            f"{mkey}[{c}] sibling not zeroed"
            # BN stats: every child copies its parent's row exactly
            for pth, leaf in jax.tree_util.tree_flatten_with_path(
                    raw[0].batch_stats)[0]:
                new_leaf = r_state.batch_stats
                for key in pth:
                    new_leaf = new_leaf[key.key]
                old = np.asarray(jax.device_get(leaf), np.float64)
                new = np.asarray(jax.device_get(new_leaf), np.float64)
                for c in range(W):
                    np.testing.assert_array_equal(new[c], old[c // k])
        else:
            # BN stats: each child row is the mean of its parent group
            k = from_world // W
            for pth, leaf in jax.tree_util.tree_flatten_with_path(
                    raw[0].batch_stats)[0]:
                new_leaf = r_state.batch_stats
                for key in pth:
                    new_leaf = new_leaf[key.key]
                old = np.asarray(jax.device_get(leaf), np.float64)
                new = np.asarray(jax.device_get(new_leaf), np.float64)
                for c in range(W):
                    np.testing.assert_allclose(
                        new[c], old[c * k:(c + 1) * k].mean(axis=0),
                        rtol=1e-5, atol=1e-6)
        r_state = shard_state(jax.tree.map(jnp.asarray, r_state), mesh,
                              dist_opt=dist)
        r_state, losses = train_range(r_state, SAVE_STEPS, TOTAL_STEPS)
        out.update(losses=losses, start=SAVE_STEPS, mass_rel=mass_rel,
                   mass=new_mass)

    else:  # supervised (one launch under scripts/supervise.py)
        results_path = os.path.join(workdir, "results.jsonl")
        topo = {"process_count": 1, "world": W, "num_local_workers": 1}
        sup_ckpt = CheckpointManager(os.path.join(workdir, "ckpt_sup"),
                                     keep=3)
        start = 0
        restored = sup_ckpt.restore(state, topology=topo, elastic=True) \
            if sup_ckpt.latest_epoch() is not None else None
        if restored is not None:
            r_state, _, meters = restored
            state = shard_state(jax.tree.map(jnp.asarray, r_state), mesh,
                                dist_opt=dist)
            start = int(meters["preempt_batch"]) + 1
        handler = preempt.PreemptionHandler()
        losses, preempt_at = [], None
        for i in range(start, SUP_TOTAL):
            if preempt.agree_preempt(handler.requested):
                preempt_at = i - 1
                break
            im, lb = batch(i)
            state, m = step_fn(state, im, lb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
            jax.block_until_ready(state)
            faults.maybe_kill(i + 1)   # global step count: no re-kill
        out.update(losses=losses, start=start)
        if preempt_at is not None:
            preempt.emergency_save(sup_ckpt, 0, state,
                                   {"preempt_batch": preempt_at},
                                   topology=topo)
            out.update(preempt_at=preempt_at, completed=False)
        else:
            out["completed"] = True
        handler.uninstall()
        with open(results_path, "a") as f:
            f.write(json.dumps(out) + "\n")
        print("RESULT:" + json.dumps(out), flush=True)
        sys.exit(75 if preempt_at is not None else 0)

    print("RESULT:" + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
