"""Checkpoint save/resume/rotate (SURVEY.md §3.4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.training import TrainState
from dgc_tpu.training.checkpoint import CheckpointManager


def _state(value: float) -> TrainState:
    return TrainState(
        step=jnp.asarray(int(value), jnp.int32),
        params={"w": jnp.full((4,), value)},
        opt_state=(jnp.zeros(()),),
        memory={"momentums": {"a/b": jnp.full((3,), value)},
                "velocities": {"a/b": jnp.full((3,), value * 2)}},
        batch_stats={"bn": {"mean": jnp.zeros((2, 4))}},
    )


def test_roundtrip_includes_memory(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(0, _state(1.5), {"acc/test_top1": 50.0})
    out = mgr.restore(_state(0.0))
    assert out is not None
    state, epoch, meters = out
    assert epoch == 0
    assert meters["acc/test_top1"] == 50.0
    np.testing.assert_allclose(state.params["w"], 1.5)
    np.testing.assert_allclose(state.memory["momentums"]["a/b"], 1.5)
    np.testing.assert_allclose(state.memory["velocities"]["a/b"], 3.0)
    assert int(state.step) == 1


def test_latest_pointer_and_rotation(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=3)
    for e in range(5):
        mgr.save(e, _state(float(e)), {})
    assert mgr.latest_epoch() == 4
    # keep last 3: e2, e3, e4
    assert not os.path.exists(os.path.join(tmp_path, "e0"))
    assert not os.path.exists(os.path.join(tmp_path, "e1"))
    for e in (2, 3, 4):
        assert os.path.exists(os.path.join(tmp_path, f"e{e}"))
    state, epoch, _ = mgr.restore(_state(0.0))
    assert epoch == 4
    np.testing.assert_allclose(state.params["w"], 4.0)


def test_best_tracking(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2)
    mgr.save(0, _state(10.0), {"m": 1.0}, best=True)
    mgr.save(1, _state(20.0), {"m": 0.5}, best=False)
    out = mgr.restore(_state(0.0), best=True)
    assert out is not None
    state, _, meters = out
    np.testing.assert_allclose(state.params["w"], 10.0)
    assert meters["m"] == 1.0


def test_restore_none_when_empty(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.restore(_state(0.0)) is None
    assert mgr.latest_epoch() is None


def test_overwrite_same_epoch(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(0, _state(1.0), {})
    mgr.save(0, _state(2.0), {})
    state, _, _ = mgr.restore(_state(0.0))
    np.testing.assert_allclose(state.params["w"], 2.0)


def test_topology_mismatch_raises_clearly(tmp_path):
    """A checkpoint written under one process/mesh/tier topology must
    refuse to restore under another with an explicit error (not an opaque
    orbax/XLA sharding failure), while matching or absent topology
    records restore normally."""
    mgr = CheckpointManager(str(tmp_path), keep=3)
    topo = {"process_count": 1, "world": 8, "num_local_workers": 1}
    mgr.save(0, _state(1.0), {"m": 1.0}, topology=topo)
    # same topology: fine, and the record does not leak into meters
    state, epoch, meters = mgr.restore(_state(0.0), topology=topo)
    assert "_topology" not in meters
    # no topology passed (older caller): restores
    assert mgr.restore(_state(0.0)) is not None
    # different topology: explicit refusal
    other = dict(topo, num_local_workers=4)
    with pytest.raises(RuntimeError, match="topology"):
        mgr.restore(_state(0.0), topology=other)


def test_pre_topology_checkpoint_restores_with_warning(tmp_path, capsys):
    """PR-3-era checkpoints have no ``_topology`` key in meters.json:
    they must restore as "current topology, non-elastic" with a logged
    warning — with and without ``elastic=True`` (which has nothing to
    reshard against and must not invent a world size)."""
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(0, _state(3.0), {"m": 1.0})          # note: no topology=
    assert mgr.saved_topology() is None
    topo = {"process_count": 1, "world": 8, "num_local_workers": 1}
    for elastic in (False, True):
        out = mgr.restore(_state(0.0), topology=topo, elastic=elastic)
        assert out is not None
        state, _, meters = out
        np.testing.assert_allclose(state.params["w"], 3.0)
        assert "_elastic" not in meters and "_topology" not in meters
        cap = capsys.readouterr().out
        assert "no _topology record" in cap
        assert "current topology" in cap


def test_legacy_transmit_record_checkpoints_migrate(tmp_path):
    """v0.2 checkpoints carry the deferred-mask state as a full-[T] keep
    MASK ('keep_c', 1.0 = keep); v0.3 as a transmit COUNT ('sent_c',
    0.0 = keep); v0.4 packs it into int32 words ('sent_bits'). Restoring
    either legacy layout into the current template must MIGRATE (pending
    masks preserved exactly as packed bits), not silently restart."""

    def flat_state(mem):
        return TrainState(step=jnp.zeros((), jnp.int32),
                          params=jnp.ones((8,)),
                          opt_state=(jnp.zeros(()),),
                          memory=mem, batch_stats={})

    # transmitted coordinates {1, 4} of T=8
    keep = np.array([1., 0., 1., 1., 0., 1., 1., 1.], np.float32)
    counts = np.array([0., 2., 0., 0., 1., 0., 0., 0.], np.float32)
    expected_bits = CheckpointManager._pack_transmitted_np(keep == 0.0)
    assert expected_bits.shape == (128,)          # ceil(8/4096)*128 words
    # p < 128 lands in word p, bit 0 (row 0 of word group 0)
    assert expected_bits[1] == 1 and expected_bits[4] == 1
    assert expected_bits.sum() == 2

    for key, legacy_vec in (("keep_c", keep), ("sent_c", counts)):
        old = flat_state({"momentums_c": jnp.full((8,), 2.0),
                          "velocities_c": jnp.full((8,), 3.0),
                          key: jnp.asarray(legacy_vec)})
        mgr = CheckpointManager(str(tmp_path / key), keep=3)
        mgr.save(0, old, {"m": 1.0})

        new_template = flat_state({
            "momentums_c": jnp.zeros((8,)),
            "velocities_c": jnp.zeros((8,)),
            "sent_bits": jnp.zeros((128,), jnp.int32)})
        out = mgr.restore(new_template)
        assert out is not None, f"{key} checkpoint must migrate"
        state, epoch, _ = out
        assert key not in state.memory
        np.testing.assert_array_equal(np.asarray(state.memory["sent_bits"]),
                                      expected_bits)
        np.testing.assert_array_equal(
            np.asarray(state.memory["momentums_c"]), 2.0)
