"""Elastic-topology restart (docs/RESILIENCE.md §"Elastic restart"):
unit tests for ``dgc_tpu.resilience.elastic`` (mass-conserving reshard,
pending-mask fold, batch-geometry resolution), the checkpoint-layer
``elastic=True`` wiring, the fail-fast ``local_batch_slice``, and a
supervised relaunch smoke through ``scripts/supervise.py`` (kill@3 ->
emergency save -> exit 75 -> relaunch -> resume mid-run -> complete).

Everything here is marked ``fast``: scripts/t1.sh runs this module as
ELASTIC_SMOKE."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from dgc_tpu.parallel.multihost import local_batch_slice
from dgc_tpu.resilience import elastic
from dgc_tpu.training import TrainState
from dgc_tpu.training.checkpoint import CheckpointManager

pytestmark = pytest.mark.fast

pack_bits = CheckpointManager._pack_transmitted_np


# --------------------------------------------------------------------- #
# transmit-record fold
# --------------------------------------------------------------------- #

@pytest.mark.parametrize("total", [8, 4096, 4096 + 5, 3 * 4096])
def test_keep_from_bits_inverts_pack(total):
    rng = np.random.RandomState(total)
    transmitted = rng.rand(total) < 0.3
    bits = pack_bits(transmitted)
    keep = elastic.keep_from_bits_np(bits, total)
    np.testing.assert_array_equal(keep, ~transmitted)


@pytest.mark.parametrize("momentum_masking", [True, False])
def test_fold_pending_mask(momentum_masking):
    T = 8
    transmitted = np.zeros(T, bool)
    transmitted[[1, 4]] = True
    mem = {"momentums_c": np.arange(1., T + 1, dtype=np.float32),
           "velocities_c": np.arange(10., T + 10, dtype=np.float32),
           "momentums_d": np.full(3, 7., np.float32),
           "velocities_d": np.zeros(3, np.float32),
           "sent_bits": pack_bits(transmitted)}
    out = elastic.fold_pending_mask(mem, momentum_masking)
    # velocities always fold; momentums only under momentum_masking
    want_v = np.where(transmitted, 0., mem["velocities_c"])
    np.testing.assert_array_equal(out["velocities_c"], want_v)
    want_m = np.where(transmitted, 0., mem["momentums_c"]) \
        if momentum_masking else mem["momentums_c"]
    np.testing.assert_array_equal(out["momentums_c"], want_m)
    # the record is consumed, dense tail untouched
    assert out["sent_bits"].sum() == 0
    np.testing.assert_array_equal(out["momentums_d"], mem["momentums_d"])
    # per-tensor memory (no sent_bits) passes through unchanged
    pt = {"momentums": {"a": np.ones(3)}, "velocities": {"a": np.ones(3)}}
    assert elastic.fold_pending_mask(pt) is pt


# --------------------------------------------------------------------- #
# reshard_state on host numpy state
# --------------------------------------------------------------------- #

def _topo(world, nlocal=1):
    return {"process_count": 1, "world": world,
            "num_local_workers": nlocal}


def _worker_state(world, n=6, seed=0):
    """Per-tensor-format state with a leading [world] axis everywhere a
    worker owns state; params/opt replicated."""
    rng = np.random.RandomState(seed)
    return TrainState(
        step=jnp.asarray(5, jnp.int32),
        params={"w": jnp.asarray(rng.randn(4), jnp.float32)},
        opt_state=(jnp.zeros(()),),
        memory={"momentums": {"a": rng.randn(world, n).astype(np.float32)},
                "velocities": {"a": rng.randn(world, n).astype(np.float32)}},
        batch_stats={"bn": {"mean": rng.randn(world, 3).astype(np.float32),
                            "var": rng.rand(world, 3).astype(np.float32)}},
    )


def test_merge_sums_residuals_means_bn():
    s = _worker_state(4)
    out = elastic.reshard_state(s, _topo(4), _topo(2), log=lambda *_: None)
    for key in ("momentums", "velocities"):
        old = np.asarray(s.memory[key]["a"], np.float64)
        new = np.asarray(out.memory[key]["a"], np.float64)
        assert new.shape == (2, 6)
        np.testing.assert_allclose(new[0], old[0] + old[1], rtol=1e-6)
        np.testing.assert_allclose(new[1], old[2] + old[3], rtol=1e-6)
        # total gradient mass conserved
        np.testing.assert_allclose(new.sum(), old.sum(), rtol=1e-5)
    for key in ("mean", "var"):
        old = np.asarray(s.batch_stats["bn"][key], np.float64)
        new = np.asarray(out.batch_stats["bn"][key], np.float64)
        np.testing.assert_allclose(new[0], old[:2].mean(0), rtol=1e-5)
        np.testing.assert_allclose(new[1], old[2:].mean(0), rtol=1e-5)
    # replicated fields pass through untouched
    np.testing.assert_array_equal(out.params["w"], s.params["w"])
    assert int(out.step) == int(s.step)


def test_merge_folds_flat_pending_mask():
    """2 -> 1 on flat-engine memory: worker 1 has a pending transmit
    record; its transmitted coordinates must NOT re-enter the sum."""
    T = 8
    transmitted = np.zeros(T, bool)
    transmitted[2] = True
    mem = {"momentums_c": np.stack([np.full(T, 1., np.float32),
                                    np.full(T, 10., np.float32)]),
           "velocities_c": np.stack([np.full(T, 2., np.float32),
                                     np.full(T, 20., np.float32)]),
           "sent_bits": np.stack([pack_bits(np.zeros(T, bool)),
                                  pack_bits(transmitted)])}
    s = _worker_state(2).replace(memory=mem)
    out = elastic.reshard_state(s, _topo(2), _topo(1),
                                momentum_masking=True, log=lambda *_: None)
    want = np.full(T, 1. + 10., np.float32)
    want[2] = 1.  # worker 1's coordinate 2 was already transmitted
    np.testing.assert_array_equal(out.memory["momentums_c"][0], want)
    want_v = np.full(T, 2. + 20., np.float32)
    want_v[2] = 2.
    np.testing.assert_array_equal(out.memory["velocities_c"][0], want_v)
    assert np.asarray(out.memory["sent_bits"]).sum() == 0
    # momentum_masking=False folds velocities only
    out2 = elastic.reshard_state(s, _topo(2), _topo(1),
                                 momentum_masking=False,
                                 log=lambda *_: None)
    np.testing.assert_array_equal(out2.memory["momentums_c"][0],
                                  np.full(T, 11., np.float32))


def test_split_one_child_inherits_bitwise():
    s = _worker_state(2)
    out = elastic.reshard_state(s, _topo(2), _topo(4), log=lambda *_: None)
    old = np.asarray(s.memory["momentums"]["a"])
    new = np.asarray(out.memory["momentums"]["a"])
    assert new.shape == (4, 6)
    # child c of parent c//2; c%2==0 inherits bitwise, siblings empty
    np.testing.assert_array_equal(new[0], old[0])
    np.testing.assert_array_equal(new[2], old[1])
    assert (new[1] == 0).all() and (new[3] == 0).all()
    np.testing.assert_allclose(new.sum(), old.sum())
    # BN stats are copied to every child, never zeroed
    bn_old = np.asarray(s.batch_stats["bn"]["mean"])
    bn_new = np.asarray(out.batch_stats["bn"]["mean"])
    for c in range(4):
        np.testing.assert_array_equal(bn_new[c], bn_old[c // 2])


def test_collapse_non_divisible():
    s = _worker_state(4)
    out = elastic.reshard_state(s, _topo(4), _topo(3), log=lambda *_: None)
    old = np.asarray(s.memory["velocities"]["a"], np.float64)
    new = np.asarray(out.memory["velocities"]["a"], np.float64)
    assert new.shape == (3, 6)
    np.testing.assert_allclose(new[0], old.sum(0), rtol=1e-5)
    assert (new[1:] == 0).all()
    bn = np.asarray(out.batch_stats["bn"]["mean"], np.float64)
    want = np.asarray(s.batch_stats["bn"]["mean"], np.float64).mean(0)
    for c in range(3):
        np.testing.assert_allclose(bn[c], want, rtol=1e-5)


def test_reshard_refusals():
    s = _worker_state(4)
    # identity is a no-op regardless of memory format
    assert elastic.reshard_state(s, _topo(4), _topo(4)) is s
    with pytest.raises(RuntimeError, match="num_local_workers"):
        elastic.reshard_state(s, _topo(4, nlocal=1), _topo(2, nlocal=2))
    with pytest.raises(NotImplementedError, match="per-worker optimizer"):
        elastic.reshard_state(s, _topo(4), _topo(2), per_worker_opt=True)
    weird = s.replace(memory={"surprise": np.zeros((4, 3), np.float32)})
    with pytest.raises(ValueError, match="ELASTIC_ADDITIVE_PREFIXES"):
        elastic.reshard_state(weird, _topo(4), _topo(2),
                              log=lambda *_: None)
    # a state whose leading axis does not match the recorded topology
    with pytest.raises(ValueError, match="leading"):
        elastic.reshard_state(s, _topo(8), _topo(2), log=lambda *_: None)


def test_with_world_retiles_per_worker_leaves_only():
    s = _worker_state(4)
    t = elastic.with_world(s, 2)
    assert np.shape(t.memory["momentums"]["a"]) == (2, 6)
    assert np.shape(t.batch_stats["bn"]["mean"]) == (2, 3)
    # replicated leaves keep their shape (and values)
    np.testing.assert_array_equal(t.params["w"], s.params["w"])
    assert np.shape(t.opt_state[0]) == ()


# --------------------------------------------------------------------- #
# gossip round state (compression.gossip) across W-changes
# --------------------------------------------------------------------- #

def _gossip_state(world, T=8, seed=0, age=None, clock=None, forced=None):
    """Flat-engine memory carrying the gossip round state: clock /
    forced are replicated per-worker scalars (leading [world] axis), the
    age vector is a replicated [world]-long view, and the in-flight
    inbox is additive mass."""
    rng = np.random.RandomState(seed)
    age = np.asarray(np.arange(world) if age is None else age, np.int32)
    mem = {
        "momentums_c": rng.randn(world, T).astype(np.float32),
        "velocities_c": rng.randn(world, T).astype(np.float32),
        "sent_bits": np.stack([pack_bits(np.zeros(T, bool))] * world),
        "gossip_inbox": rng.randn(world, T).astype(np.float32),
        "gossip_clock": np.asarray([7] * world if clock is None
                                   else clock, np.int32),
        "gossip_age": np.tile(age, (world, 1)),
        "gossip_forced": np.asarray([2] * world if forced is None
                                    else forced, np.int32),
    }
    return _worker_state(world).replace(memory=mem)


def test_gossip_merge_takes_max_staleness():
    """4 -> 2 merge: a merged worker's view is as stale as its stalest
    parent; the clock / forced counters merge by max; the in-flight
    inbox rides the additive path (group-summed, total conserved)."""
    logs = []
    s = _gossip_state(4, age=[0, 3, 1, 2], clock=[6, 7, 7, 5],
                      forced=[2, 5, 2, 2])
    out = elastic.reshard_state(s, _topo(4), _topo(2), log=logs.append)
    mem = out.memory
    assert np.asarray(mem["gossip_age"]).shape == (2, 2)
    np.testing.assert_array_equal(mem["gossip_age"], [[3, 2], [3, 2]])
    np.testing.assert_array_equal(mem["gossip_clock"], [7, 7])
    np.testing.assert_array_equal(mem["gossip_forced"], [5, 5])
    old = np.asarray(s.memory["gossip_inbox"], np.float64)
    new = np.asarray(mem["gossip_inbox"], np.float64)
    np.testing.assert_allclose(new[0], old[0] + old[1], rtol=1e-6)
    np.testing.assert_allclose(new[1], old[2] + old[3], rtol=1e-6)
    np.testing.assert_allclose(new.sum(), old.sum(), rtol=1e-5)
    assert any("gossip round state" in l for l in logs)


def test_gossip_split_inherits_age():
    """2 -> 4 split: every child inherits its parent's staleness view
    and the replicated counters bitwise; the inbox follows the split
    rule (child c%k==0 inherits, siblings start empty)."""
    s = _gossip_state(2, age=[3, 1])
    out = elastic.reshard_state(s, _topo(2), _topo(4),
                                log=lambda *_: None)
    mem = out.memory
    np.testing.assert_array_equal(mem["gossip_age"],
                                  np.tile([3, 3, 1, 1], (4, 1)))
    np.testing.assert_array_equal(mem["gossip_clock"], [7] * 4)
    np.testing.assert_array_equal(mem["gossip_forced"], [2] * 4)
    old = np.asarray(s.memory["gossip_inbox"])
    new = np.asarray(mem["gossip_inbox"])
    np.testing.assert_array_equal(new[0], old[0])
    np.testing.assert_array_equal(new[2], old[1])
    assert (new[1] == 0).all() and (new[3] == 0).all()


def test_gossip_collapse_broadcasts_max():
    """4 -> 3 (non-divisible): worker/data alignment is lost, so every
    child's view starts at the global max age — conservative: the next
    breach check can only over-trigger a full sync, never miss one."""
    s = _gossip_state(4, age=[0, 3, 1, 2])
    out = elastic.reshard_state(s, _topo(4), _topo(3),
                                log=lambda *_: None)
    mem = out.memory
    np.testing.assert_array_equal(mem["gossip_age"],
                                  np.full((3, 3), 3, np.int32))
    np.testing.assert_array_equal(mem["gossip_clock"], [7] * 3)
    inbox = np.asarray(mem["gossip_inbox"], np.float64)
    np.testing.assert_allclose(
        inbox[0], np.asarray(s.memory["gossip_inbox"],
                             np.float64).sum(0), rtol=1e-5)
    assert (inbox[1:] == 0).all()


# --------------------------------------------------------------------- #
# batch geometry + fail-fast batch slicing
# --------------------------------------------------------------------- #

def test_resolve_batch_geometry():
    assert elastic.resolve_batch_geometry(4, 4, 2) == (2, None)
    nbps, note = elastic.resolve_batch_geometry(4, 2, 2)
    assert nbps == 4 and "global batch" in note
    nbps, note = elastic.resolve_batch_geometry(2, 4, 2)
    assert nbps == 1
    # growing beyond the nbps budget cannot preserve the product
    with pytest.raises(RuntimeError, match="preserve_global_batch"):
        elastic.resolve_batch_geometry(2, 8, 2)
    with pytest.raises(RuntimeError, match="preserve_global_batch"):
        elastic.resolve_batch_geometry(4, 3, 1)
    # opting out keeps nbps and warns instead
    nbps, note = elastic.resolve_batch_geometry(4, 3, 1, preserve=False)
    assert nbps == 1 and "preserve_global_batch=False" in note


def test_local_batch_slice_fail_fast():
    assert local_batch_slice(64, num_processes=4, process_id=1) \
        == slice(16, 32)
    assert local_batch_slice(64, num_processes=1, process_id=0) \
        == slice(0, 64)
    with pytest.raises(ValueError) as ei:
        local_batch_slice(65, num_processes=4, process_id=0)
    msg = str(ei.value)
    # actionable: names the remainder and a divisible alternative
    assert "65" in msg and "4" in msg
    assert "64" in msg or "68" in msg


# --------------------------------------------------------------------- #
# checkpoint-layer wiring
# --------------------------------------------------------------------- #

def test_checkpoint_elastic_restore_and_refusal(tmp_path, capsys):
    mgr = CheckpointManager(str(tmp_path), keep=3)
    saved = _worker_state(4, seed=3)
    mgr.save(0, saved, {"m": 1.0}, topology=_topo(4))
    assert mgr.saved_topology() == _topo(4)

    template = _worker_state(2, seed=9)
    # without elastic: explicit fail-fast that points at the flag
    with pytest.raises(RuntimeError, match=r"elastic=True \(--elastic\)"):
        mgr.restore(template, topology=_topo(2))
    # with elastic: restored at world 2 with summed residuals
    out = mgr.restore(template, topology=_topo(2), elastic=True)
    assert out is not None
    state, epoch, meters = out
    assert meters["_elastic"] == {"from_world": 4, "to_world": 2,
                                  "from_process_count": 1,
                                  "to_process_count": 1}
    assert "_topology" not in meters
    old = np.asarray(saved.memory["momentums"]["a"], np.float64)
    new = np.asarray(state.memory["momentums"]["a"], np.float64)
    np.testing.assert_allclose(new[0], old[0] + old[1], rtol=1e-6)
    np.testing.assert_allclose(new[1], old[2] + old[3], rtol=1e-6)
    assert "[elastic] merging 4 workers -> 2" in capsys.readouterr().out


def test_pre_topology_checkpoint_restores_with_warning(tmp_path, capsys):
    """Checkpoints written before the _topology record exist in the wild:
    they must restore as "current topology, non-elastic" with a logged
    warning — both with and without elastic=True (satellite 2)."""
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(0, _worker_state(2, seed=1), {"m": 2.0})   # no topology=
    assert mgr.saved_topology() is None
    template = _worker_state(2, seed=9)
    for elastic_flag in (False, True):
        out = mgr.restore(template, topology=_topo(2),
                          elastic=elastic_flag)
        assert out is not None
        _, _, meters = out
        assert "_elastic" not in meters
        captured = capsys.readouterr().out
        assert "no _topology record" in captured
        assert "current topology" in captured


# --------------------------------------------------------------------- #
# supervised relaunch smoke (scripts/supervise.py)
# --------------------------------------------------------------------- #

def test_supervisor_relaunch_smoke(tmp_path):
    """End-to-end restart loop: launch 1 trains to step 3, SIGTERMs
    itself (DGC_FAULTS=kill@3), emergency-saves with the topology record,
    and exits 75; the supervisor counts the save as progress, relaunches,
    and launch 2 resumes at step 4 and completes with exit 0."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    supervise = os.path.join(root, "scripts", "supervise.py")
    worker = os.path.join(root, "tests", "elastic_worker.py")
    events = tmp_path / "events.jsonl"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "DGC_FAULTS")}
    env["DGC_FAULTS"] = "kill@3"
    proc = subprocess.run(
        [sys.executable, supervise, "--retries", "3", "--backoff", "0.2",
         "--watch", str(tmp_path / "ckpt_sup"),
         "--events", str(events), "--",
         sys.executable, worker, "supervised", "2", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=420)
    assert proc.returncode == 0, \
        f"supervisor failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-4000:]}"

    lines = [json.loads(l) for l in
             (tmp_path / "results.jsonl").read_text().splitlines()]
    assert len(lines) == 2, lines
    first, second = lines
    assert first["start"] == 0 and first["completed"] is False
    assert first["preempt_at"] == 2          # last completed step index
    assert second["start"] == 3 and second["completed"] is True
    assert all(np.isfinite(first["losses"] + second["losses"]))

    ev = [json.loads(l) for l in events.read_text().splitlines()]
    kinds = [e["event"] for e in ev]
    assert kinds.count("launch") == 2
    assert "relaunch" in kinds and kinds[-1] == "done"
    relaunch = ev[kinds.index("relaunch")]
    assert relaunch["rc"] == 75
    # the emergency save counted as progress: the retry budget reset
    assert relaunch["progressed"] is True and relaunch["failures"] == 0

    # the emergency checkpoint carries the topology record (satellite 3)
    meters = json.loads(
        (tmp_path / "ckpt_sup" / "e0" / "meters.json").read_text())
    assert meters["_topology"] == {"process_count": 1, "world": 2,
                                   "num_local_workers": 1}
