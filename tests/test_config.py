"""Config engine parity (C9/C12, reference train.py:34-35 + configs/**)."""

import os
import subprocess
import sys

import pytest

from dgc_tpu.utils.config import Config, configs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_configs():
    Config.reset()
    yield
    Config.reset()


def test_attribute_access_and_nesting():
    configs.train = Config()
    configs.train.lr = 0.1
    assert configs.train.lr == 0.1
    assert configs["train"]["lr"] == 0.1
    assert "train" in configs
    assert "seed" not in configs
    assert configs.get("missing", 5) == 5


def test_callable_node_instantiation():
    class Thing:
        def __init__(self, a, b=2, c=3):
            self.a, self.b, self.c = a, b, c

    node = Config(Thing)
    node.b = 20
    obj = node(1, c=30)
    assert (obj.a, obj.b, obj.c) == (1, 20, 30)


def test_items_hide_callable():
    node = Config(dict)
    node.x = 1
    assert dict(node.items()) == {"x": 1}
    assert list(node.keys()) == ["x"]
    assert len(node) == 1


def test_update_from_arguments():
    configs.train = Config()
    configs.train.num_epochs = 200
    Config.update_from_arguments("--train.num_epochs", "500",
                                 "--train.tag", "hello",
                                 "--train.lr", "0.05")
    assert configs.train.num_epochs == 500
    assert configs.train.tag == "hello"
    assert configs.train.lr == 0.05


def test_update_from_modules_composes(monkeypatch):
    monkeypatch.chdir(REPO)
    Config.update_from_modules("configs/cifar/resnet20.py",
                               "configs/dgc/wm5.py")
    # base config ran
    assert configs.seed == 42
    # cifar group ran
    assert configs.train.num_epochs == 200
    assert configs.dataset.num_classes == 10
    # model leaf ran
    assert configs.model.callable.__name__ == "resnet20"
    # dgc group ran + flag module
    assert configs.train.dgc is True
    assert configs.train.compression.compress_ratio == 0.001
    assert configs.train.compression.warmup_epochs == 5
    # optimizer swapped to dgc_sgd, old fields carried over
    assert configs.train.optimizer.callable.__name__ == "dgc_sgd"
    assert configs.train.optimizer.momentum == 0.9
    assert configs.train.compression.memory.momentum == 0.9


def test_update_from_modules_dgc_flags(monkeypatch):
    monkeypatch.chdir(REPO)
    Config.update_from_modules("configs/cifar/resnet110.py",
                               "configs/dgc/wm5o.py",
                               "configs/dgc/fp16.py",
                               "configs/dgc/int32.py",
                               "configs/dgc/nm.py")
    assert configs.model.callable.__name__ == "resnet110"
    assert configs.train.compression.warmup_coeff == [1, 1, 1, 1, 1]
    assert configs.train.compression.fp16_values is True
    assert configs.train.compression.int32_indices is True
    assert configs.train.compression.memory.momentum_masking is False


def test_imagenet_configs(monkeypatch):
    monkeypatch.chdir(REPO)
    Config.update_from_modules("configs/imagenet/resnet50.py",
                               "configs/imagenet/cosine.py",
                               "configs/dgc/wm0.py")
    assert configs.train.num_epochs == 90
    assert configs.train.optimizer.nesterov is True
    assert configs.train.optimize_bn_separately is True
    assert configs.model.zero_init_residual is True
    assert configs.train.scheduler.callable.__name__ == "cosine_schedule"
    assert configs.train.compression.warmup_epochs == 0


def test_get_save_path():
    sys.path.insert(0, REPO)
    from train import get_save_path
    p = get_save_path("configs/cifar/resnet20.py", "configs/dgc/wm5.py")
    assert p == os.path.join("runs", "cifar.resnet20+dgc.wm5")
    assert "[" not in p  # tensorstore-globbing-safe
    p2 = get_save_path("configs/imagenet/resnet50.py")
    assert p2 == os.path.join("runs", "imagenet.resnet50")
