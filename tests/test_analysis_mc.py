"""dgcmc (layer 4, dynamic half): sandbox crash semantics, the
protocol suite green at HEAD, and the seeded-mutation red tests.

The sandbox is the checker's filesystem model: these tests pin its
semantics (crash-before-op vs mid-write tears, fsync durability,
rename-atomicity, the write-once ledger, thread/root confinement)
independently of any scenario, then run the real suite both ways."""

import builtins
import json
import os
import tempfile

import pytest

from dgc_tpu.analysis.mc import (MUTATIONS, Crash, Sandbox, explore,
                                 run_mc_suite, scenarios)
from dgc_tpu.analysis.protospec import (APPEND_TAIL_TORN, PROTOCOLS,
                                        PROTOCOLS_BY_NAME, RENAME_ATOMIC,
                                        WRITE_ONCE)

_SILENT = lambda s: None  # noqa: E731


# --------------------------------------------------------------------- #
# protospec sanity                                                       #
# --------------------------------------------------------------------- #

def test_protocol_specs_well_formed():
    classes = {RENAME_ATOMIC, WRITE_ONCE, APPEND_TAIL_TORN}
    assert len(PROTOCOLS) == 8
    for spec in PROTOCOLS:
        assert spec.files, spec.name
        assert spec.invariants, spec.name
        for fs in spec.files:
            assert fs.atomicity in classes, (spec.name, fs.pattern)
            assert fs.writer and fs.readers, (spec.name, fs.pattern)
        assert PROTOCOLS_BY_NAME[spec.name] is spec


def test_every_protocol_has_a_scenario():
    assert ({s.name for s in scenarios()}
            == {p.name for p in PROTOCOLS})
    # fast mode drops exactly the orbax-heavy checkpoint scenario
    assert ({s.name for s in scenarios(fast=True)}
            == {p.name for p in PROTOCOLS} - {"checkpoint-epoch"})


# --------------------------------------------------------------------- #
# sandbox semantics                                                      #
# --------------------------------------------------------------------- #

def test_crash_is_not_an_exception():
    # `except Exception` recovery in code under test must NOT swallow a
    # simulated kill
    assert issubclass(Crash, BaseException)
    assert not issubclass(Crash, Exception)


def test_crash_fires_before_nonwrite_op(tmp_path):
    sb = Sandbox(str(tmp_path), crash_at=0)
    with sb:
        with pytest.raises(Crash):
            open(tmp_path / "a.txt", "w")
    assert not (tmp_path / "a.txt").exists()
    assert sb.ops == [("create", "a.txt")]


def test_mid_write_tear_leaves_half(tmp_path):
    sb = Sandbox(str(tmp_path), crash_at=1)   # op 0 = create, op 1 = write
    with sb:
        with pytest.raises(Crash):
            with open(tmp_path / "a.txt", "w") as f:
                f.write("0123456789")
    data = (tmp_path / "a.txt").read_bytes()
    assert data == b"01234"                   # half of the torn write
    torn = sb.apply_crash_effects()
    # nothing was fsynced: half of the surviving suffix tears away too
    assert (tmp_path / "a.txt").read_bytes() == b"012"
    assert torn and "a.txt" in torn[0]


def test_fsynced_bytes_survive_crash_effects(tmp_path):
    from dgc_tpu.serving.protocol import write_json_atomic
    sb = Sandbox(str(tmp_path))
    with sb:
        write_json_atomic(str(tmp_path / "x.json"), {"v": 1})
    assert sb.apply_crash_effects() == []     # mkstemp+fsync+replace
    with open(tmp_path / "x.json") as f:
        assert json.load(f) == {"v": 1}
    kinds = [k for k, _ in sb.ops]
    assert "fsync" in kinds and "replace" in kinds


def test_unsynced_replace_carries_risk(tmp_path):
    # publish WITHOUT fsync: the rename lands, but the bytes are not
    # durable — crash effects must tear the published file
    sb = Sandbox(str(tmp_path))
    with sb:
        with open(tmp_path / "t.tmp", "w") as f:
            f.write("0123456789abcdef")
        os.replace(tmp_path / "t.tmp", tmp_path / "final.txt")
    torn = sb.apply_crash_effects()
    assert torn and "final.txt" in torn[0]
    assert len((tmp_path / "final.txt").read_bytes()) < 16


def test_write_once_ledger_flags_republish(tmp_path):
    sb = Sandbox(str(tmp_path), write_once=("delta_*.npz",))
    with sb:
        for content in ("AAAA", "BBBB"):
            with open(tmp_path / "d.tmp", "w") as f:
                f.write(content)
            os.replace(tmp_path / "d.tmp", tmp_path / "delta_1.npz")
    assert len(sb.violations) == 1
    assert "delta_1.npz" in sb.violations[0]
    assert "step" in sb.violations[0]


def test_write_once_identical_republish_is_legal(tmp_path):
    sb = Sandbox(str(tmp_path), write_once=("delta_*.npz",))
    with sb:
        for _ in range(2):
            with open(tmp_path / "d.tmp", "w") as f:
                f.write("AAAA")
            os.replace(tmp_path / "d.tmp", tmp_path / "delta_1.npz")
    assert sb.violations == []


def test_sandbox_confined_to_root(tmp_path):
    inside = tmp_path / "in"
    inside.mkdir()
    sb = Sandbox(str(inside))
    with sb:
        with open(tmp_path / "outside.txt", "w") as f:
            f.write("x")
        with open(inside / "inside.txt", "w") as f:
            f.write("y")
    assert [rel for _, rel in sb.ops] == ["inside.txt", "inside.txt"]
    assert (tmp_path / "outside.txt").read_text() == "x"


def test_sandbox_restores_syscalls(tmp_path):
    before = (builtins.open, os.replace, os.fsync, tempfile.mkstemp)
    with Sandbox(str(tmp_path)):
        assert builtins.open is not before[0]
    assert (builtins.open, os.replace, os.fsync,
            tempfile.mkstemp) == before


# --------------------------------------------------------------------- #
# the suite: green at HEAD, red under every seeded mutation              #
# --------------------------------------------------------------------- #

def test_suite_green_at_head():
    results = run_mc_suite(log=_SILENT)
    assert {n for n, _ in results} == {p.name for p in PROTOCOLS}
    for name, violations in results:
        assert violations == [], (name, violations[:3])


@pytest.mark.parametrize("mutation", MUTATIONS)
def test_mutation_turns_suite_red_naming_protocol_and_step(mutation):
    results = run_mc_suite(log=_SILENT, mutate=mutation, fast=True)
    red = [(n, v) for n, v in results if v]
    assert red, f"mutation {mutation} left the suite green"
    for name, violations in red:
        assert name in PROTOCOLS_BY_NAME
        # every violation names its protocol and a concrete step
        assert all(v.startswith(f"{name} @ ") for v in violations)
        assert any("step" in v for v in violations), violations[:3]


def test_unknown_mutation_rejected():
    with pytest.raises(ValueError, match="unknown mc mutation"):
        run_mc_suite(log=_SILENT, mutate="no_such_bug")


def test_env_var_seeds_mutation(monkeypatch):
    monkeypatch.setenv("DGC_MC_MUTATE", "torn_tail")
    results = run_mc_suite(log=_SILENT, fast=True)
    assert any(v for n, v in results if n == "telemetry-stream")


def test_torn_tail_reds_scheduler_ledger():
    # the gang scheduler's grant ledger is append-tail-torn: swapping in
    # a strict line reader must turn the gate red NAMING the protocol
    # (scoped to the one scenario — the full-suite mutation sweep is
    # already pinned per-mutation above; re-running all 8 here would
    # only re-prove that at ~6s of tier-1 budget)
    scn = [s for s in scenarios(mutate="torn_tail", fast=True)
           if s.name == "scheduler-ledger"][0]
    viols = explore(scn, log=_SILENT, mutate="torn_tail")
    assert viols
    assert all(v.startswith("scheduler-ledger @ ") for v in viols)
    assert any("LEDGER-TAIL-PREFIX" in v for v in viols)


def test_explore_reports_crash_context():
    # a scenario red under mutation carries "crash at step K (kind path)"
    # context strings — the step-naming contract of the checker
    scn = [s for s in scenarios(mutate="torn_tail", fast=True)
           if s.name == "telemetry-stream"][0]
    viols = explore(scn, log=_SILENT, mutate="torn_tail")
    assert viols
    assert any("crash at step" in v for v in viols)
