"""dgcver layer 3: jaxpr traversal + the four dataflow passes.

Toy traced programs pin each pass's detection logic in isolation; the
seeded-mutation tests prove the passes stay wired to the *real* engine
(`DGC_VERIFY_MUTATE` flips a hostile edit into flat.py at trace time and
the right pass must go red, naming the source line); the suite test pins
the whole gate green on every pinned config."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dgc_tpu.analysis import jaxpr as jxa
from dgc_tpu.analysis import verify
from dgc_tpu.analysis.rules import Allowlist, load_allowlist
from dgc_tpu.analysis.verify import (AxisPolicy, check_collective_axes,
                                     check_donation_liveness,
                                     check_dtype_flow,
                                     check_ef_conservation,
                                     run_verify_suite)
from dgc_tpu.ops import kernels
from dgc_tpu.utils.compat import shard_map

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _prog(fn, *args):
    return jxa.flatten(jax.make_jaxpr(fn)(*args))


# --------------------------------------------------------------------- #
# traversal layer                                                        #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_flatten_recurses_into_pjit_and_scan():
    def inner(x):
        return x * 2.0

    def f(x):
        y = jax.jit(inner)(x)

        def body(c, _):
            return c + y, None

        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    prog = _prog(f, jnp.ones((4,)))
    prims = {e.prim for e in prog.eqns}
    # the mul inside pjit and the add inside scan are both visible flat
    assert "mul" in prims and "add" in prims
    assert all(e.source for e in prog.eqns if e.prim == "mul")


@pytest.mark.fast
def test_collectives_extract_axis_names(mesh8):
    def worker(x):
        return jax.lax.psum(x, "data")

    f = shard_map(worker, mesh=mesh8, in_specs=(P("data"),),
                  out_specs=P("data"), check_vma=False)
    prog = _prog(f, jnp.ones((8, 4)))
    sites = jxa.collectives(prog)
    assert sites and all("data" in s.axes for s in sites)


@pytest.mark.fast
def test_tags_and_forward_taint():
    def f(x):
        y = kernels.vtag(x * 2.0, "dgcver.src.test")
        z = y + 1.0
        w = x - 3.0          # independent of the tagged value
        return z, w

    prog = _prog(f, jnp.ones((4,)))
    tag_map = jxa.tags(prog)
    assert "dgcver.src.test" in tag_map
    seeds = {v for e in tag_map["dgcver.src.test"] for v in e.outvars}
    tainted = jxa.forward_taint(prog, seeds)
    z_var, w_var = prog.outvars[0], prog.outvars[1]
    assert z_var in tainted
    assert w_var not in tainted


@pytest.mark.fast
def test_peak_live_bytes_positive():
    def f(x):
        return (x * 2.0).sum()

    prog = _prog(f, jnp.ones((128,)))
    peak = jxa.peak_live_bytes(prog)
    assert peak >= 128 * 4


# --------------------------------------------------------------------- #
# pass 1: collective-axis                                                #
# --------------------------------------------------------------------- #

def _psum_prog(mesh8):
    def worker(x):
        return jax.lax.psum(x, "data")

    f = shard_map(worker, mesh=mesh8, in_specs=(P("data"),),
                  out_specs=P("data"), check_vma=False)
    return _prog(f, jnp.ones((8, 4)))


@pytest.mark.fast
def test_collective_axis_allowed(mesh8):
    prog = _psum_prog(mesh8)
    pol = AxisPolicy(allowed=frozenset({"data"}), budgets={"data": 4})
    assert check_collective_axes(prog, pol, REPO_ROOT) == []


@pytest.mark.fast
def test_collective_axis_undeclared_axis_flagged(mesh8):
    prog = _psum_prog(mesh8)
    pol = AxisPolicy(allowed=frozenset({"model"}), budgets={})
    findings = check_collective_axes(prog, pol, REPO_ROOT)
    assert findings and "undeclared axis 'data'" in findings[0].message


@pytest.mark.fast
def test_collective_axis_budget_enforced(mesh8):
    prog = _psum_prog(mesh8)
    pol = AxisPolicy(allowed=frozenset({"data"}), budgets={"data": 0})
    findings = check_collective_axes(prog, pol, REPO_ROOT)
    assert any("over its budget" in f.message for f in findings)


# --------------------------------------------------------------------- #
# pass 2: dtype-flow                                                     #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_dtype_flow_flags_onchip_bf16_roundtrip():
    def f(x):
        r = kernels.vtag(x, "dgcver.src.residual")
        return r.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    findings = check_dtype_flow(_prog(f, jnp.ones((8,))), REPO_ROOT)
    assert findings and "truncating cast" in findings[0].message


@pytest.mark.fast
def test_dtype_flow_allows_wire_lane_narrowing(mesh8):
    def worker(x):
        r = kernels.vtag(x, "dgcver.src.residual")
        q = r.astype(jnp.bfloat16)          # narrow...
        g = jax.lax.all_gather(q, "data")   # ...but it IS the wire
        return g.astype(jnp.float32).sum()

    f = shard_map(worker, mesh=mesh8, in_specs=(P("data"),),
                  out_specs=P(), check_vma=False)
    assert check_dtype_flow(_prog(f, jnp.ones((8, 4))), REPO_ROOT) == []


@pytest.mark.fast
def test_dtype_flow_ignores_untainted_casts():
    def f(x):
        kernels.vtag(x + 2.0, "dgcver.src.residual")  # tainted lane unused
        return (x * 3.0).astype(jnp.bfloat16)

    assert check_dtype_flow(_prog(f, jnp.ones((8,))), REPO_ROOT) == []


# --------------------------------------------------------------------- #
# pass 3: donation / liveness                                            #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_donation_liveness_on_donated_toy():
    def f(state, x):
        return state + x, (state * x).sum()

    state, x = jnp.ones((16,)), jnp.ones((16,))
    prog = _prog(f, state, x)
    text = (jax.jit(f, donate_argnums=(0,))
            .lower(state, x).compile().as_text())
    metrics, findings = check_donation_liveness(
        prog, text, n_state_leaves=1, declared_donate=True, root=REPO_ROOT)
    assert metrics["alias_coverage"] == 1.0
    assert metrics["peak_live_bytes"] > 0
    assert findings == []


@pytest.mark.fast
def test_donation_liveness_flags_empty_alias_header():
    def f(state, x):
        return state + x, (state * x).sum()

    state, x = jnp.ones((16,)), jnp.ones((16,))
    prog = _prog(f, state, x)
    text = jax.jit(f).lower(state, x).compile().as_text()  # no donation
    metrics, findings = check_donation_liveness(
        prog, text, n_state_leaves=1, declared_donate=True, root=REPO_ROOT)
    assert metrics["alias_coverage"] == 0.0
    assert findings  # dead-after-read state arg and/or empty alias header


# --------------------------------------------------------------------- #
# pass 4: ef-conservation (+ the Plan descriptor hook)                   #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_ef_conservation_dense_program_trivially_ok():
    status, findings = check_ef_conservation(
        _prog(lambda x: x * 2.0, jnp.ones((4,))), REPO_ROOT)
    assert status == "dense" and findings == []


@pytest.mark.fast
def test_ef_conservation_descriptor_rejects_dense_under_sparse_plan():
    desc = {"conservation": "sparse", "eager_foldback": False}
    status, findings = check_ef_conservation(
        _prog(lambda x: x * 2.0, jnp.ones((4,))), REPO_ROOT,
        descriptor=desc)
    assert status == "broken"
    assert "promises a sparse selection" in findings[0].message


@pytest.mark.fast
def test_plan_verify_descriptor_per_regime():
    from dgc_tpu.compression.planner import Plan

    def desc(reg):
        return Plan([reg], fabric="32x25GbE", world=8).verify_descriptor()

    d = desc("fp32")
    assert (d["gather_lanes"], d["eager_foldback"],
            d["packed_words"]) == (2, False, False)
    assert desc("int8") == {
        "gather_lanes": 3, "conservation": "sparse",
        "value_kinds": ("i8",), "packed_words": False,
        "eager_foldback": True, "gossip": None}
    assert desc("int4_packed")["packed_words"] is True
    assert desc("int8_delta_idx")["gather_lanes"] == 3
    assert desc("gossip_ring")["gossip"] == "ring"
    assert desc("gossip_ring")["eager_foldback"] is False
    dd = desc("dense")
    assert dd["conservation"] == "dense" and dd["gather_lanes"] == 0


# --------------------------------------------------------------------- #
# seeded mutations: the passes stay wired to the real engine             #
# --------------------------------------------------------------------- #

def _fixture_prog(mesh8):
    from dgc_tpu.analysis.suite import build_fixture
    state, step, _, (images, labels, key) = build_fixture(
        mesh8, donate=False, telemetry=False)
    return jxa.flatten(jax.make_jaxpr(step)(state, images, labels, key))


def test_mutation_cast_bf16_turns_dtype_flow_red(mesh8, monkeypatch):
    monkeypatch.setenv("DGC_VERIFY_MUTATE", "cast_bf16")
    findings = check_dtype_flow(_fixture_prog(mesh8), REPO_ROOT)
    assert findings, "seeded bf16 truncation not detected"
    assert any(f.path.endswith("compression/flat.py") and f.line > 0
               for f in findings)
    assert "truncating cast" in findings[0].message


def test_mutation_drop_foldback_turns_conservation_red(mesh8, monkeypatch):
    monkeypatch.setenv("DGC_VERIFY_MUTATE", "drop_foldback")
    status, findings = check_ef_conservation(_fixture_prog(mesh8),
                                             REPO_ROOT)
    assert status == "broken"
    assert any("C3 broken" in f.message for f in findings)
    assert any(f.path.endswith("compression/flat.py") and f.line > 0
               for f in findings)


def test_unmutated_fixture_is_conserving_and_clean(mesh8, monkeypatch):
    monkeypatch.delenv("DGC_VERIFY_MUTATE", raising=False)
    prog = _fixture_prog(mesh8)
    assert check_dtype_flow(prog, REPO_ROOT) == []
    status, findings = check_ef_conservation(prog, REPO_ROOT)
    assert status == "ok" and findings == []


# --------------------------------------------------------------------- #
# the suite + waivers + regress gating                                   #
# --------------------------------------------------------------------- #

def test_verify_suite_green_on_all_pinned_configs(mesh8, tmp_path):
    results = run_verify_suite(
        mesh8, root=REPO_ROOT, fast=True, allowlist=load_allowlist())
    bad = [(n, v) for n, v in results if v]
    assert not bad, bad
    # fast mode traces every config through the first three passes
    names = {n.split("].")[0] + "]" for n, _ in results}
    assert len(names) == len(verify.VERIFY_CONFIGS)


@pytest.mark.fast
def test_inline_dgcver_waiver_syntax():
    line = "q = v.astype(jnp.int8)  # dgcver: ok[dtype-flow]"
    assert Allowlist.inline_waiver(line, "dtype-flow", tool="dgcver")
    assert not Allowlist.inline_waiver(line, "ef-conservation",
                                       tool="dgcver")
    # dgclint waivers do not leak into the dgcver namespace
    assert not Allowlist.inline_waiver(
        "x = 1  # dgclint: ok[dtype-flow]", "dtype-flow", tool="dgcver")


@pytest.mark.fast
def test_allowlist_matches_verify_findings():
    from dgc_tpu.analysis.rules import Finding
    al = Allowlist([{"rule": "ef-conservation", "file": "dgc_tpu/*",
                     "reason": "test entry"}])
    f = Finding(rule="ef-conservation", path="dgc_tpu/compression/flat.py",
                line=1, col=0, snippet="x = 1", message="m")
    assert al.match(f) == "test entry"
    f2 = Finding(rule="dtype-flow", path="dgc_tpu/compression/flat.py",
                 line=1, col=0, snippet="x = 1", message="m")
    assert al.match(f2) is None


@pytest.mark.fast
def test_regress_gates_analysis_report(tmp_path):
    from dgc_tpu.telemetry.regress import compare, load_summary
    base = {"schema": "dgc-analysis-report-v1", "alias_coverage": 1.0,
            "peak_live_bytes": 100000.0, "configs": {}}
    worse = dict(base, alias_coverage=0.5, peak_live_bytes=250000.0)
    pb, pn = tmp_path / "base.json", tmp_path / "new.json"
    pb.write_text(json.dumps(base))
    pn.write_text(json.dumps(worse))
    rows = compare(load_summary(str(pb)), load_summary(str(pn)), tol=0.10)
    by = {r["metric"]: r for r in rows}
    assert by["alias_coverage"]["regressed"]        # higher is better
    assert by["peak_live_bytes"]["regressed"]       # lower is better
    # self-compare passes
    rows = compare(load_summary(str(pb)), load_summary(str(pb)), tol=0.10)
    assert not any(r["regressed"] for r in rows)
