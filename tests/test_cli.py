"""The harness CLI end-to-end (reference train.py usage, README.md:107-115):
fresh run, checkpoint resume, and --evaluate — as real subprocesses on the
fake 8-device CPU mesh. This is the only coverage of train.py's __main__
path (argument parsing, config composition, save-path naming, the epoch
loop, resume arithmetic)."""

import glob
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def run_dir():
    suffix = f".clitest{os.getpid()}"
    d = os.path.join(REPO, "runs", f"cifar.resnet20+dgc.wm5{suffix}.np8")
    yield suffix, d
    shutil.rmtree(d, ignore_errors=True)


def _run(*extra, suffix):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    cmd = [sys.executable, "train.py",
           "--configs", "configs/cifar/resnet20.py", "configs/dgc/wm5.py",
           "--cpu_mesh", "8", "--suffix", suffix,
           "--dataset.synthetic_size", "128", "--train.batch_size", "2",
           *extra]
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=900)


def test_cli_train_resume_evaluate(run_dir):
    suffix, d = run_dir

    # fresh 1-epoch run: trains, evaluates, checkpoints
    r = _run("--train.num_epochs", "1", suffix=suffix)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "==> train from scratch" in r.stdout
    assert "[loss]" in r.stdout and "acc/test_top1" in r.stdout
    assert os.path.isdir(os.path.join(d, "checkpoints", "e0"))
    assert os.path.exists(os.path.join(d, "metrics.jsonl"))

    # resume: same command with num_epochs 2 picks up after epoch 0
    r = _run("--train.num_epochs", "2", suffix=suffix)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "[resumed] epoch 0" in r.stdout
    assert "training epoch 1/2" in r.stdout
    assert "training epoch 0/2" not in r.stdout
    assert os.path.isdir(os.path.join(d, "checkpoints", "e1"))

    # --evaluate: loads best checkpoint, prints metrics, does not train
    r = _run("--evaluate", suffix=suffix)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "acc/test_top1" in r.stdout
    assert "training epoch" not in r.stdout


def test_cli_autotune_two_epoch_replan():
    """The AUTOTUNE_SMOKE gate (scripts/t1.sh): a 2-epoch --autotune run
    must refit at every epoch boundary, record an autotune_replan event
    in the telemetry stream, and leave a valid provenance-stamped
    fabric.json in the save path."""
    suffix = f".atsmoke{os.getpid()}"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    cmd = [sys.executable, "train.py",
           "--configs", "configs/cifar/resnet20.py", "configs/dgc/wm5.py",
           "configs/telemetry.py",
           "--cpu_mesh", "8", "--suffix", suffix,
           "--dataset.synthetic_size", "128", "--train.batch_size", "2",
           "--train.num_epochs", "2", "--autotune"]
    r = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=900)
    dirs = glob.glob(os.path.join(REPO, "runs", f"*{suffix}*"))
    try:
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        assert "[autotune] fabric autotuned-" in r.stdout
        assert "[autotune] refit" in r.stdout
        assert len(dirs) == 1, dirs

        # the refreshed fabric.json round-trips through the planner
        sys.path.insert(0, REPO)
        from dgc_tpu.compression.planner import load_fabric
        fpath = os.path.join(dirs[0], "fabric.json")
        fab = load_fabric(fpath)
        assert fab.name.startswith("autotuned-")
        assert fab.measured and fab.gbps > 0
        with open(fpath) as fh:
            prov = json.load(fh)["provenance"]
        assert prov["source"] == "autotune"
        assert prov["refit"] >= 1 and prov["points"] >= 2

        # the replan event rode the telemetry stream (one per refit)
        events = []
        for p in glob.glob(os.path.join(dirs[0], "telemetry", "*.jsonl")):
            with open(p) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("event") == "autotune_replan":
                        events.append(rec)
        assert events, "no autotune_replan event in the telemetry stream"
        for rec in events:
            assert rec["points"] >= 2
            assert rec["gbps"] > 0
            assert isinstance(rec["regimes"], dict)
            assert rec["rebuilt"] in (True, False)
    finally:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)
