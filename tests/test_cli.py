"""The harness CLI end-to-end (reference train.py usage, README.md:107-115):
fresh run, checkpoint resume, and --evaluate — as real subprocesses on the
fake 8-device CPU mesh. This is the only coverage of train.py's __main__
path (argument parsing, config composition, save-path naming, the epoch
loop, resume arithmetic)."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def run_dir():
    suffix = f".clitest{os.getpid()}"
    d = os.path.join(REPO, "runs", f"cifar.resnet20+dgc.wm5{suffix}.np8")
    yield suffix, d
    shutil.rmtree(d, ignore_errors=True)


def _run(*extra, suffix):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    cmd = [sys.executable, "train.py",
           "--configs", "configs/cifar/resnet20.py", "configs/dgc/wm5.py",
           "--cpu_mesh", "8", "--suffix", suffix,
           "--dataset.synthetic_size", "128", "--train.batch_size", "2",
           *extra]
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=900)


def test_cli_train_resume_evaluate(run_dir):
    suffix, d = run_dir

    # fresh 1-epoch run: trains, evaluates, checkpoints
    r = _run("--train.num_epochs", "1", suffix=suffix)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "==> train from scratch" in r.stdout
    assert "[loss]" in r.stdout and "acc/test_top1" in r.stdout
    assert os.path.isdir(os.path.join(d, "checkpoints", "e0"))
    assert os.path.exists(os.path.join(d, "metrics.jsonl"))

    # resume: same command with num_epochs 2 picks up after epoch 0
    r = _run("--train.num_epochs", "2", suffix=suffix)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "[resumed] epoch 0" in r.stdout
    assert "training epoch 1/2" in r.stdout
    assert "training epoch 0/2" not in r.stdout
    assert os.path.isdir(os.path.join(d, "checkpoints", "e1"))

    # --evaluate: loads best checkpoint, prints metrics, does not train
    r = _run("--evaluate", suffix=suffix)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "acc/test_top1" in r.stdout
    assert "training epoch" not in r.stdout
