"""Worker program for the 2-process fleet-observability drill
(tests/test_multiprocess.py::test_fleet_two_process_straggler).

One 2-process ``jax.distributed`` launch over a 2-host x 4-device mesh:
build the FLEET train step (telemetry=True, fleet=True — the packed
all_gather replaces the telemetry pmean), stamp the real host prep
interval into the clock input each step, and write every record through a
per-host :class:`TelemetrySink` shard (``<run>/telemetry/host<i>/``) —
exactly the layout train.py produces with configs/fleet.py.

The parent arms ``DGC_FAULTS=slow:ms=...`` on process 1 only, so that
process sleeps before every dispatch: its workers' dispatch intervals
stretch and the fleet view must name one of them the straggler. Prints one
``RESULT:`` JSON line per process with the in-graph straggler verdicts.

With ``adaptive`` as a 6th argv (the straggler-adaptive drill,
tests/test_multiprocess.py::test_fleet_two_process_adaptive), the step is
built with ``resilience.adaptive.AdaptiveConfig()`` and the RESULT line
additionally carries the per-step ``w_eff_ratio`` / ``w_sent_ratio``
columns — the parent asserts the straggler's effective send fraction
drops while the healthy workers' stays at 1. A windowed fault
(``slow:ms=M@K-L``) makes it the transient-straggler drill: the policy
must engage inside the window and release after it.
"""

import json
import os
import sys
import time

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")
if "jax_cpu_collectives_implementation" in jax.config.values:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

STEPS = 14


def main():
    proc_id = int(sys.argv[1])
    num_procs = int(sys.argv[2])
    coord = sys.argv[3]
    workdir = sys.argv[4]
    adaptive_on = len(sys.argv) > 5 and sys.argv[5] == "adaptive"

    from dgc_tpu.parallel.multihost import (host_local_to_global,
                                            initialize_multihost)

    import getpass
    import tempfile
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(tempfile.gettempdir(),
                                   f"dgc_tpu_test_jax_cache_"
                                   f"{getpass.getuser()}"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    os.environ["JAX_COORDINATOR_ADDRESS"] = coord
    os.environ["JAX_NUM_PROCESSES"] = str(num_procs)
    os.environ["JAX_PROCESS_ID"] = str(proc_id)
    assert initialize_multihost(initialization_timeout=600,
                                heartbeat_timeout_seconds=600,
                                shutdown_timeout_seconds=1200) is True
    assert jax.process_count() == num_procs

    import jax.numpy as jnp  # noqa: F401  (kept for parity with sibling)
    import numpy as np
    from flax import linen as nn
    from jax.sharding import Mesh

    from dgc_tpu import (DGCCompressor, DGCSGDMemory, DistributedOptimizer,
                         dgc_sgd)
    from dgc_tpu.resilience import faults
    from dgc_tpu.telemetry import fleet
    from dgc_tpu.telemetry.sink import TelemetrySink
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)
    from dgc_tpu.utils.pytree import named_flatten

    W = len(jax.devices())
    assert W == 2 * 4
    mesh = Mesh(np.array(jax.devices()), ("data",))

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(8, (3, 3))(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x.mean(axis=(1, 2)))

    model = M()
    v = dict(model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3))))

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        if mutable:
            return model.apply(variables, x, train=train, mutable=mutable,
                               rngs=rngs)
        return model.apply(variables, x, train=train)

    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    acfg = None
    if adaptive_on:
        from dgc_tpu.resilience.adaptive import AdaptiveConfig
        acfg = AdaptiveConfig()
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W, adaptive=acfg),
                        mesh, dist_opt=dist)
    step_fn = build_train_step(apply_fn, dist, mesh, donate=False,
                               flat=setup, telemetry=True, fleet=True,
                               adaptive=acfg)

    run_dir = os.path.join(workdir, "fleetrun")
    sink = TelemetrySink(
        os.path.join(run_dir, "telemetry", f"host{proc_id}"),
        static=dict(setup.engine.telemetry_static(), world=W,
                    process_index=proc_id, num_processes=num_procs),
        fleet=True)
    sink.write_record({"event": "fleet_drill_start", "proc": proc_id})

    bs = 4

    def batch(i):
        rng = np.random.RandomState(2000 + i)
        im = rng.randn(W * bs, 16, 16, 3).astype(np.float32)
        lb = rng.randint(0, 10, W * bs).astype(np.int32)
        return (host_local_to_global(im, mesh),
                host_local_to_global(lb, mesh))

    prev = None
    kept = []
    for i in range(STEPS):
        if faults.armed():
            faults.maybe_slow(i)         # the injected straggler drill
                                         # (step-gated for @K-L windows)
        im, lb = batch(i)
        # w_clock lane: host PREP time only — previous dispatch RETURN to
        # this dispatch START. The dispatch call itself is excluded: it
        # can block on the cohort collective, and that wait is the same
        # on every host (equalized), so including it would erase the
        # straggler's signature. Only its own sleep/data work stretch
        # ITS stamps.
        now = time.perf_counter()
        dt_ms = (now - prev) * 1000.0 if prev is not None else 0.0
        state, m = step_fn(state, im, lb, jax.random.PRNGKey(i),
                           fleet.make_clock(dt_ms, mesh, W))
        prev = time.perf_counter()
        sink.write(i, {**m["telemetry"], **m["fleet"], "loss": m["loss"]})
        kept.append(m["fleet"])
    jax.block_until_ready(state)
    sink.close()

    # convert after the loop: one host sync per recorded scalar, all of
    # them long since computed
    stragglers = [int(float(f["straggler"])) for f in kept]
    gaps = [float(f["straggler_gap"]) for f in kept]
    out = {"proc": proc_id,
           "stragglers": stragglers,
           "gaps": [round(g, 3) for g in gaps],
           "sink": sink.path or ""}
    if adaptive_on:
        out["eff"] = [[round(float(x), 4) for x in np.asarray(f["w_eff_ratio"])]
                      for f in kept]
        out["sent"] = [[round(float(x), 5)
                        for x in np.asarray(f["w_sent_ratio"])]
                       for f in kept]
        out["engaged"] = [float(f["adaptive_engaged"]) for f in kept]
    print("RESULT:" + json.dumps(out), flush=True)

    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("fleet_drill_done")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
