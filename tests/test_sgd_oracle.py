"""DGCSGD / SGD vs a torch oracle (SURVEY.md §2.9, reference sgd.py:30-70).

torch (CPU) is available in this environment; the optimizers must match
torch.optim.SGD / the reference's DGCSGD step-for-step.
"""

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from dgc_tpu.optim import dgc_sgd, sgd


def _run_jax(opt, p0, grads):
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    for g in grads:
        updates, state = opt.update({"w": jnp.asarray(g)}, state, params)
        params = {"w": params["w"] + updates["w"]}
    return np.asarray(params["w"])


def _run_torch_sgd(p0, grads, lr, momentum, weight_decay, nesterov):
    p = torch.nn.Parameter(torch.tensor(p0))
    opt = torch.optim.SGD([p], lr=lr, momentum=momentum,
                          weight_decay=weight_decay, nesterov=nesterov)
    for g in grads:
        opt.zero_grad()
        p.grad = torch.tensor(g)
        opt.step()
    return p.detach().numpy()


def _run_torch_dgc_sgd(p0, grads, lr, momentum, weight_decay, nesterov):
    """The reference DGCSGD recurrence (sgd.py:48-68), executed with torch:
    momentum applies to the weight-decay term only; grad added raw."""
    p = torch.tensor(p0)
    buf = None
    for g in grads:
        g = torch.tensor(g)
        if weight_decay != 0:
            d_p = weight_decay * p
            if momentum != 0:
                if buf is None:
                    buf = d_p.clone()
                else:
                    buf.mul_(momentum).add_(d_p)
                d_p = d_p.add(buf, alpha=momentum) if nesterov else buf
            d_p = d_p.add(g)
        else:
            d_p = g
        p = p.add(d_p, alpha=-lr)
    return p.numpy()


@pytest.mark.parametrize("momentum,wd,nesterov", [
    (0.9, 1e-4, False),
    (0.9, 1e-4, True),
    (0.0, 1e-4, False),
    (0.9, 0.0, False),
])
def test_sgd_matches_torch(momentum, wd, nesterov):
    rng = np.random.RandomState(0)
    p0 = rng.randn(10).astype(np.float32)
    grads = [rng.randn(10).astype(np.float32) for _ in range(5)]
    ours = _run_jax(sgd(0.1, momentum=momentum, weight_decay=wd,
                        nesterov=nesterov), p0, grads)
    theirs = _run_torch_sgd(p0, grads, 0.1, momentum, wd, nesterov)
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("momentum,wd,nesterov", [
    (0.9, 1e-4, False),
    (0.9, 1e-4, True),
    (0.9, 0.0, False),
    (0.0, 5e-5, False),
])
def test_dgc_sgd_matches_reference_recurrence(momentum, wd, nesterov):
    rng = np.random.RandomState(1)
    p0 = rng.randn(10).astype(np.float32)
    grads = [rng.randn(10).astype(np.float32) for _ in range(5)]
    ours = _run_jax(dgc_sgd(0.05, momentum=momentum, weight_decay=wd,
                            nesterov=nesterov), p0, grads)
    theirs = _run_torch_dgc_sgd(p0, grads, 0.05, momentum, wd, nesterov)
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)


def test_dgc_sgd_differs_from_plain_sgd():
    # sanity: the DGC split is NOT stock SGD when momentum is on
    rng = np.random.RandomState(2)
    p0 = rng.randn(10).astype(np.float32)
    grads = [rng.randn(10).astype(np.float32) for _ in range(3)]
    a = _run_jax(dgc_sgd(0.1, momentum=0.9, weight_decay=1e-4), p0, grads)
    b = _run_jax(sgd(0.1, momentum=0.9, weight_decay=1e-4), p0, grads)
    assert not np.allclose(a, b)


def test_weight_decay_mask():
    p0 = np.ones(4, np.float32)
    grads = [np.zeros(4, np.float32)]
    opt = dgc_sgd(1.0, momentum=0.0, weight_decay=0.5,
                  weight_decay_mask={"w": False})
    out = _run_jax(opt, p0, grads)
    np.testing.assert_allclose(out, p0)  # masked => pure grad (zero) step


def test_lr_schedule_callable():
    lrs = []

    def sched(count):
        lrs.append(1)
        return 0.1 * (count + 1)

    opt = sgd(sched, momentum=0.0)
    p0 = np.zeros(2, np.float32)
    out = _run_jax(opt, p0, [np.ones(2, np.float32)] * 2)
    # step1 lr=0.1, step2 lr=0.2 → p = -0.3
    np.testing.assert_allclose(out, -0.3, rtol=1e-6)
