"""Tests for cohort surgery (ISSUE 15; docs/RESILIENCE.md §"Cohort
surgery"): the fault-plan hang/exit tokens, the order / exit-record file
protocol, the widened (preempt, verdict, target) agreement lane with its
hang-safe deadline tier, the supervisor's exit-76 surgery handling and
heartbeat hang escalation, the device-pool ledger, the excise/readmit
detectors and actions, the monitor's COHORT surface — and the 3-process
drill: ``DGC_FAULTS=hang@5-5`` on worker 2, supervisor SIGKILLs the hung
process, survivors exit 76 with an atomic emergency checkpoint and
relaunch as W=2 under the published shrunk spec, worker 2 passes the
re-init probe, the device pool frees its slot, and a rule-driven readmit
grows the cohort back to W=3 — every transition an audited
``control_action``.

Everything here is host-only (subprocesses + files + threads, no jax),
so the whole file is ``fast``-marked (scripts/t1.sh SURGERY_SMOKE).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dgc_tpu.control import actions, rules
from dgc_tpu.control.plane import ControlPlane, DevicePool, RunSpec
from dgc_tpu.control.rules import Rule
from dgc_tpu.control.supervisor import Supervisor, parse_env_file
from dgc_tpu.resilience import faults, surgery
from dgc_tpu.telemetry import monitor, registry

from test_fleet import _write_run

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "surgery_worker.py")


# --------------------------------------------------------------------- #
# fault plan: hang / exit tokens                                         #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_fault_plan_hang_exit_tokens(monkeypatch):
    p = faults.plan("hang@5")
    assert p.hang_window == (5, None) and p.hang_secs is None
    p = faults.plan("hang:secs=2@5-8")
    assert p.hang_window == (5, 8) and p.hang_secs == 2
    p = faults.plan("hang@5-5")
    assert p.hang_window == (5, 5)
    p = faults.plan("exit:code=76@7")
    assert p.exit_code == 76 and p.exit_window == (7, None)
    p = faults.plan("exit@3")
    assert p.exit_code == 1 and p.exit_window == (3, None)
    # composes with the existing grammar
    p = faults.plan("slow:ms=40@2-9,hang:secs=1@5-5,exit:code=9@20")
    assert p.slow_ms == 40 and p.slow_window == (2, 9)
    assert p.hang_window == (5, 5) and p.exit_code == 9
    with pytest.raises(ValueError):
        faults.plan("hangg@5")

    # unset -> byte-identical plan: every hook is an identity
    monkeypatch.delenv(faults.ENV, raising=False)
    assert faults.plan() == faults.FaultPlan()

    # windowed hang only fires inside the window (and never without a
    # step); a bounded stall returns
    monkeypatch.setenv(faults.ENV, "hang:secs=0@5-5")
    t0 = time.time()
    faults.maybe_hang(None)
    faults.maybe_hang(4)
    faults.maybe_hang(6)
    faults.maybe_hang(5)        # secs=0: stalls zero seconds, returns
    assert time.time() - t0 < 1.0
    monkeypatch.setenv(faults.ENV, "exit:code=42@7")
    faults.maybe_exit(6)        # out of window: no exit
    # the exit itself, in a subprocess (os._exit bypasses everything)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, sys.argv[1]);"
         "from dgc_tpu.resilience import faults; faults.maybe_exit(7)",
         ROOT],
        env=dict(os.environ, DGC_FAULTS="exit:code=42@7"), timeout=60)
    assert proc.returncode == 42


# --------------------------------------------------------------------- #
# order / exit-record files                                              #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_order_file_protocol(tmp_path):
    path = str(tmp_path / surgery.ORDER_FILE)
    assert surgery.read_order(path) is None          # absent
    surgery.publish_order(path, "desync", 2, step=30,
                          extra={"rule_fired": 3})
    rec = surgery.read_order(path)
    assert rec["verdict"] == "desync" and rec["target"] == 2
    assert rec["step"] == 30 and rec["rule_fired"] == 3 and rec["t"] > 0

    with pytest.raises(ValueError):
        surgery.publish_order(path, "none", 1)
    with pytest.raises(ValueError):
        surgery.publish_order(path, "bogus", 1)

    # torn / malformed degrade to "no order", never crash a step
    with open(path, "w") as f:
        f.write('{"verdict": "des')
    assert surgery.read_order(path) is None
    with open(path, "w") as f:
        json.dump({"verdict": "desync"}, f)          # no target
    assert surgery.read_order(path) is None
    with open(path, "w") as f:
        json.dump(["not", "a", "dict"], f)
    assert surgery.read_order(path) is None

    surgery.clear_order(path)
    surgery.clear_order(path)                        # idempotent
    assert surgery.read_order(path) is None
    # atomic writes leave no temp litter
    assert not [p for p in os.listdir(tmp_path) if p.startswith(".surgery")]


@pytest.mark.fast
def test_exit_record_roundtrip(tmp_path):
    path = str(tmp_path / surgery.EXIT_RECORD)
    assert surgery.read_exit_record(path) is None
    ag = surgery.Agreement(excise=True, target=1, verdict="hang", lost=True)
    surgery.write_exit_record(path, ag, world=3, process_index=0, step=17)
    rec = surgery.read_exit_record(path)
    assert rec["verdict"] == "hang" and rec["target"] == 1
    assert rec["lost"] is True and rec["world"] == 3
    assert rec["process_index"] == 0 and rec["step"] == 17


# --------------------------------------------------------------------- #
# the agreement lane                                                     #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_lanes_encode_decode():
    row = surgery.encode_lanes(False, None)
    assert row.tolist() == [0.0, 0.0, 0.0] and row.dtype == np.float32
    row = surgery.encode_lanes(True, {"verdict": "desync", "target": 0})
    assert row.tolist() == [1.0, 1.0, 1.0]           # target+1 offset

    none = surgery.encode_lanes(False, None)
    ag = surgery.decode_lanes(np.stack([none, none, none]))
    assert ag == surgery.Agreement()                 # quiet boundary

    ag = surgery.decode_lanes(np.stack([
        surgery.encode_lanes(True, None),            # one saw SIGTERM
        none,
        surgery.encode_lanes(False, {"verdict": "desync", "target": 2}),
    ]))
    assert ag.preempt and ag.excise and ag.target == 2
    assert ag.verdict == "desync" and not ag.lost

    # disagreement: the highest verdict code wins deterministically
    ag = surgery.decode_lanes(np.stack([
        surgery.encode_lanes(False, {"verdict": "desync", "target": 1}),
        surgery.encode_lanes(False, {"verdict": "hang", "target": 2}),
    ]))
    assert ag.verdict == "hang" and ag.target == 2

    # a verdict with no target is not an excise
    ag = surgery.decode_lanes(np.asarray([[0.0, 4.0, 0.0]], np.float32))
    assert not ag.excise and ag.target == -1 and ag.verdict == "none"


@pytest.mark.fast
def test_coordinator_agreement_paths(tmp_path):
    order_path = str(tmp_path / surgery.ORDER_FILE)

    def cohort_gather(payload):
        # two quiet peers ride along
        quiet = surgery.encode_lanes(False, None)
        return np.stack([payload, quiet, quiet])

    coord = surgery.SurgeryCoordinator(
        order_path, boundary_timeout=5.0, retries=1, backoff=0.05,
        process_index=0, process_count=3, allgather=cohort_gather,
        log=lambda m: None)
    assert coord.agree(False) == surgery.Agreement()
    surgery.publish_order(order_path, "straggler", 1)
    ag = coord.agree(True)
    assert ag.preempt and ag.excise and ag.target == 1
    assert ag.verdict == "straggler"
    assert not coord.excised(ag)
    assert coord.excised(surgery.Agreement(excise=True, target=0))

    # hang tier: the gather never completes -> bounded budget -> lost
    stuck = surgery.SurgeryCoordinator(
        order_path, boundary_timeout=0.05, retries=2, backoff=0.05,
        process_index=0, process_count=3,
        allgather=lambda p: time.sleep(30), log=lambda m: None)
    t0 = time.time()
    ag = stuck.agree(False)
    assert ag.lost and ag.verdict == "hang" and not ag.excise
    assert time.time() - t0 < 5.0                    # bounded, not 30s

    # a SIGKILLed peer surfaces as a collective error -> same lost path
    def boom(payload):
        raise RuntimeError("connection reset by peer")
    dead = surgery.SurgeryCoordinator(
        order_path, boundary_timeout=1.0, retries=0, backoff=0.05,
        process_index=0, process_count=3, allgather=boom,
        log=lambda m: None)
    assert dead.agree(False).lost

    # late arrival INSIDE the backoff budget: the same in-flight gather
    # completes, no agreement is lost
    def late(payload):
        time.sleep(0.3)
        return cohort_gather(payload)
    slowpoke = surgery.SurgeryCoordinator(
        order_path, boundary_timeout=0.1, retries=3, backoff=0.15,
        process_index=0, process_count=3, allgather=late,
        log=lambda m: None)
    ag = slowpoke.agree(False)
    assert not ag.lost and ag.excise and ag.target == 1

    # single-process short circuit: the order is honored with NO
    # communication at all
    def forbidden(payload):
        raise AssertionError("single-process agree must not communicate")
    solo = surgery.SurgeryCoordinator(
        order_path, process_index=0, process_count=1, allgather=forbidden)
    ag = solo.agree(False)
    assert ag.excise and ag.target == 1 and ag.verdict == "straggler"
    surgery.clear_order(order_path)
    assert solo.agree(True) == surgery.Agreement(preempt=True)


@pytest.mark.fast
def test_shrink_and_remap():
    assert surgery.shrink_updates(3, 2) == {"JAX_NUM_PROCESSES": "2"}
    assert surgery.shrink_updates(2, 0) == {"JAX_NUM_PROCESSES": "1"}
    assert surgery.shrink_updates(1, 0) is None      # nothing to shrink to
    assert surgery.shrink_updates(4, -1) is None     # unknown target
    assert surgery.shrink_updates(4, 4) is None      # out of range

    assert surgery.remap_process_id(2, 2) is None    # self-excision
    assert surgery.remap_process_id(3, 2) == 2       # above the hole
    assert surgery.remap_process_id(1, 2) == 1       # below: unchanged


@pytest.mark.fast
def test_probe_checksum_deterministic():
    a = np.arange(64, dtype=np.float32)
    b = np.ones((4, 4), np.int32)
    assert surgery.probe_checksum([a, b]) == surgery.probe_checksum(
        [a.copy(), b.copy()])
    assert surgery.probe_checksum([a]) != surgery.probe_checksum([a + 1])
    # shape/dtype are part of the identity, not just the bytes
    assert surgery.probe_checksum([a]) != surgery.probe_checksum(
        [a.reshape(8, 8)])


# --------------------------------------------------------------------- #
# supervisor: exit 76, hang escalation                                   #
# --------------------------------------------------------------------- #

_SURGERY_CHILD = """\
import json, os, sys
sys.path.insert(0, sys.argv[2])
run = sys.argv[1]
ck = os.path.join(run, "checkpoints"); os.makedirs(ck, exist_ok=True)
marker = os.path.join(run, "ran")
if os.path.exists(marker):
    sys.exit(0)
open(marker, "w").write("1")
with open(os.path.join(ck, "latest.json"), "w") as f:
    json.dump({"epoch": 1}, f)
from dgc_tpu.resilience import surgery
surgery.write_exit_record(
    os.path.join(ck, surgery.EXIT_RECORD),
    surgery.Agreement(excise=True, target=int(os.environ["TGT"]),
                      verdict="hang", lost=True),
    world=3, process_index=int(os.environ["JAX_PROCESS_ID"]), step=5)
sys.exit(76)
"""


def _surgery_sup(tmp_path, pid, target):
    run = tmp_path / "run"
    run.mkdir(exist_ok=True)
    script = tmp_path / "child.py"
    script.write_text(_SURGERY_CHILD)
    envf = tmp_path / "cohort.env"
    envf.write_text("JAX_NUM_PROCESSES=3\n")
    return Supervisor(
        [sys.executable, str(script), str(run), ROOT],
        retries=0, backoff=0.05, env_file=str(envf),
        watch=str(run / "checkpoints"),
        events=str(tmp_path / "ev.jsonl"),
        extra_env={"JAX_PROCESS_ID": str(pid), "TGT": str(target)})


@pytest.mark.fast
def test_supervisor_exit_76_survivor_relaunch(tmp_path):
    # survivor (pid 1, target 2): apply record, publish shrunk spec,
    # relaunch immediately with the failure budget reset (retries=0!)
    sup = _surgery_sup(tmp_path, pid=1, target=2)
    rc = sup.run(install_signals=False)
    assert rc == 0 and sup.launches == 2 and sup.state == "done"
    assert sup.quarantined is None
    assert parse_env_file(str(tmp_path / "cohort.env")) == {
        "JAX_NUM_PROCESSES": "2"}
    assert sup.extra_env["JAX_PROCESS_ID"] == "1"    # below the hole
    evs = [json.loads(l) for l in (tmp_path / "ev.jsonl").read_text()
           .splitlines()]
    assert [e["event"] for e in evs] == ["launch", "surgery", "launch",
                                         "done"]
    s = evs[1]
    assert s["rc"] == 76 and s["verdict"] == "hang" and s["target"] == 2
    assert s["lost"] is True and s["world"] == 2
    assert s["published"] == {"JAX_NUM_PROCESSES": "2"}
    # the relaunch ran under the published spec
    assert evs[2]["cohort"]["JAX_NUM_PROCESSES"] == "2"

    # the record is applied exactly once per publish
    assert sup._apply_surgery(76) == {}


@pytest.mark.fast
def test_supervisor_exit_76_self_excision_quarantines(tmp_path):
    # pid 2 IS the target: the shrunk spec has no seat -> quarantined
    # for the readmit probe, NOT relaunched into a dead slot
    sup = _surgery_sup(tmp_path, pid=2, target=2)
    rc = sup.run(install_signals=False)
    assert rc == 76 and sup.launches == 1
    assert sup.state == "quarantined"
    assert sup.quarantined == "excised:hang"
    evs = [json.loads(l) for l in (tmp_path / "ev.jsonl").read_text()
           .splitlines()]
    assert [e["event"] for e in evs] == ["launch", "quarantined"]
    assert evs[1]["reason"] == "excised:hang"


@pytest.mark.fast
def test_supervisor_hang_escalation_sigkills_stale_heartbeat(tmp_path):
    hb = tmp_path / "heartbeat"
    sup = Supervisor(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        retries=0, backoff=0.05, events=str(tmp_path / "ev.jsonl"),
        hang_timeout=0.6, heartbeat=str(hb))
    t0 = time.time()
    rc = sup.run(install_signals=False)
    assert time.time() - t0 < 30.0                   # not the 60s sleep
    assert rc != 0 and sup.state == "quarantined"
    assert sup.quarantined.startswith("hang:no heartbeat")
    evs = [json.loads(l) for l in (tmp_path / "ev.jsonl").read_text()
           .splitlines()]
    assert [e["event"] for e in evs] == ["launch", "hang_kill",
                                         "quarantined"]
    assert evs[2]["reason"].startswith("hang:")

    # a child that beats the heartbeat is never escalated
    beat = ("import os, time\n"
            "for _ in range(20):\n"
            "    open(os.environ['DGC_HEARTBEAT'], 'a').close()\n"
            "    os.utime(os.environ['DGC_HEARTBEAT'])\n"
            "    time.sleep(0.05)\n")
    sup2 = Supervisor([sys.executable, "-c", beat], retries=0,
                      backoff=0.05, events=str(tmp_path / "ev2.jsonl"),
                      hang_timeout=0.6, heartbeat=str(tmp_path / "hb2"))
    assert sup2.run(install_signals=False) == 0
    assert sup2.state == "done" and sup2.quarantined is None


# --------------------------------------------------------------------- #
# device-pool ledger                                                     #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_device_pool_one_way_idempotent():
    pool = DevicePool({"a": 4, "b": 2, "c": 1})
    assert pool.free == 0
    assert pool.snapshot()["total"] == 7 and pool.snapshot()["active"] == 7

    pool.quarantine("b")
    pool.quarantine("b")                             # idempotent
    assert pool.snapshot()["quarantined"] == ["b"]
    assert pool.free == 0                            # held, not free

    pool.release("a")                                # active: not releasable
    assert pool.free == 0
    pool.release("b")                                # quarantined -> freed
    pool.release("b")
    assert pool.free == 2
    snap = pool.snapshot()
    assert snap["freed"] == ["b"] and snap["active"] == 5

    pool.quarantine("b")                             # freed: one-way, no-op
    assert pool.free == 2
    pool.activate("b")                               # readmit
    assert pool.free == 0 and pool.snapshot()["active"] == 7
    pool.activate("nope")                            # unknown run ignored
    assert pool.snapshot()["total"] == 7


# --------------------------------------------------------------------- #
# detectors + actions + registry                                         #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_surgery_detectors_on_synthetic_snapshots():
    assert rules.detect_excise({}) is None
    assert rules.detect_excise({"last_supervise": {
        "event": "quarantined", "reason": "exit:70"}}) is None
    ev = rules.detect_excise({"last_supervise": {
        "event": "hang_kill", "reason": "no heartbeat for 2.1s",
        "cohort": {"JAX_PROCESS_ID": "2", "JAX_NUM_PROCESSES": "3"}}})
    assert ev["kind"] == "hang" and ev["worker"] == 2 and ev["world"] == 3
    # the FROM-world comes from the event's launch-time cohort stamp,
    # NOT the live (already-shrunk) spec
    ev = rules.detect_excise({
        "last_supervise": {"event": "quarantined", "reason": "hang:stale",
                           "cohort": {"JAX_PROCESS_ID": "1",
                                      "JAX_NUM_PROCESSES": "3"}},
        "cohort": {"spec_world": 2}})
    assert ev["world"] == 3
    ev = rules.detect_excise({"last_supervise": {
        "event": "hang_kill", "reason": "x", "cohort": {}},
        "cohort": {"spec_world": 4}})
    assert ev["world"] == 4                          # fallback

    assert rules.detect_readmit({}) is None
    assert rules.detect_readmit({"cohort": {
        "probe": {"passed": True}, "pool_free": 0}}) is None
    assert rules.detect_readmit({"cohort": {
        "probe": {"passed": False, "rc": 1}, "pool_free": 2}}) is None
    ev = rules.detect_readmit({"cohort": {
        "probe": {"passed": True, "rc": 0, "checksum": "abc"},
        "pool_free": 2, "spec_world": 2}})
    assert ev == {"kind": "readmit", "pool_free": 2, "probe_rc": 0,
                  "checksum": "abc", "target_world": 3}


@pytest.mark.fast
def test_act_excise_and_readmit(tmp_path):
    watch = tmp_path / "checkpoints"
    watch.mkdir()
    envf = tmp_path / "cohort.env"
    envf.write_text("JAX_NUM_PROCESSES=3\n")
    sup = Supervisor([sys.executable, "-c", "pass"], env_file=str(envf),
                     watch=str(watch))

    # a non-hang excise publishes order + spec but quarantines nothing
    # (the workers take the orderly exit-76 path themselves)
    res = actions.act_excise(
        sup, {"kind": "desync", "worker": 1, "world": 3, "hits": 2},
        env_updates={"JAX_NUM_PROCESSES": "2"})
    order = surgery.read_order(str(watch / surgery.ORDER_FILE))
    assert order["verdict"] == "desync" and order["target"] == 1
    assert order["rule_fired"] == 2
    assert res["published"] == {"JAX_NUM_PROCESSES": "2"}
    assert res["order"]["target"] == 1
    assert sup.quarantined is None

    # a hang excise also quarantines (the corpse is already SIGKILLed)
    res = actions.act_excise(sup, {"kind": "hang", "worker": 2},
                             env_updates={})
    assert sup.quarantined == "excised:hang"
    assert res["quarantined"] == "excised:hang" and res["already"] is False

    # an unknown verdict kind degrades to "manual", never raises
    sup2 = Supervisor([sys.executable, "-c", "pass"], watch=str(watch))
    actions.act_excise(sup2, {"kind": "weird", "worker": 0})
    assert surgery.read_order(
        str(watch / surgery.ORDER_FILE))["verdict"] == "manual"

    # readmit: stale order + exit record cleared, grown spec published,
    # plane-provided relaunch + cohort restart executed and audited
    surgery.write_exit_record(
        str(watch / surgery.EXIT_RECORD),
        surgery.Agreement(excise=True, target=2, verdict="hang"),
        world=3, process_index=0)
    res = actions.act_readmit(
        sup2, {"kind": "readmit", "target_world": 3},
        env_updates={"JAX_NUM_PROCESSES": "3"},
        relauncher=lambda: True, cohort_restart=lambda: ["w0", "w1"])
    assert not os.path.exists(watch / surgery.ORDER_FILE)
    assert not os.path.exists(watch / surgery.EXIT_RECORD)
    assert res["relaunched"] is True
    assert res["cohort_restarted"] == ["w0", "w1"]
    assert parse_env_file(str(envf)) == {"JAX_NUM_PROCESSES": "2"}

    # registry: both are first-class audited control actions
    assert "excise" in registry.control_action_names()
    assert "readmit" in registry.control_action_names()
    assert "excise" in actions.ACTIONS and "readmit" in actions.ACTIONS
    registry.validate_control_action({
        "event": "control_action", "run": "w2", "run_id": "w2-x",
        "rule": "hang-excise", "action": "excise",
        "evidence": {"kind": "hang", "worker": 2}, "result": res,
        "t": time.time()})


@pytest.mark.fast
def test_monitor_cohort_line_and_gauges(tmp_path):
    run = str(tmp_path / "run")
    _write_run(run, hosts=1, world=4, steps=6)
    with open(os.path.join(run, "cohort.json"), "w") as f:
        json.dump({"total": 3, "active": 2, "pool_free": 1,
                   "quarantined": ["w2"], "freed": ["w2"],
                   "spec_world": 3, "t": time.time(),
                   "probe": {"passed": True, "rc": 0}}, f)
    snap = monitor.collect(run)
    assert snap["cohort"]["spec_world"] == 3

    status = monitor.render_status(snap)
    assert "COHORT:" in status
    assert "world 2/3" in status
    assert "quarantined=[w2]" in status
    assert "pool free 1" in status and "probe passed" in status

    om = monitor.render_openmetrics(snap)
    size_lines = [l for l in om.splitlines()
                  if l.startswith("dgc_cohort_size{")]
    assert size_lines and size_lines[0].endswith(" 3")
    assert "dgc_pool_free{" in om

    # a torn cohort.json degrades to "no COHORT surface", not an error
    with open(os.path.join(run, "cohort.json"), "w") as f:
        f.write('{"total": 3, "act')
    snap = monitor.collect(run)
    assert "cohort" not in snap
    assert "COHORT:" not in monitor.render_status(snap)
    assert "dgc_cohort_size" not in monitor.render_openmetrics(snap)


# --------------------------------------------------------------------- #
# the 3-process excise/readmit drill                                     #
# --------------------------------------------------------------------- #

def _surgery_rules():
    # the shipped detectors and action mapping, tuned tick-fast: readmit
    # holds back long enough for the survivors to run a stretch at W=2
    return (
        Rule("hang-excise", rules.detect_excise, "excise",
             min_hits=1, debounce_s=60.0, budget=1),
        Rule("probe-readmit", rules.detect_readmit, "readmit",
             min_hits=14, debounce_s=60.0, budget=1),
    )


@pytest.mark.fast
def test_cohort_surgery_drill(tmp_path):
    root = str(tmp_path)
    cohort_dir = os.path.join(root, "cohort")
    env_file = os.path.join(root, "cohort.env")
    with open(env_file, "w") as f:
        f.write("JAX_NUM_PROCESSES=3\n")

    def spec(i, **kw):
        run_dir = os.path.join(root, f"w{i}")
        env = {"JAX_PROCESS_ID": str(i), "DGC_BOUNDARY_TIMEOUT": "3.5"}
        env.update(kw.pop("env", {}))
        return RunSpec(
            f"w{i}",
            [sys.executable, WORKER, run_dir, "--cohort", cohort_dir,
             "--steps", "140", "--step-ms", "30"],
            run_dir=run_dir, env_file=env_file, env=env, backoff=0.1,
            **kw)

    specs = [
        spec(0), spec(1),
        # worker 2 hangs at step 5 (exactly once: the readmitted life
        # resumes past the window); its supervisor escalates via the
        # stale heartbeat, and its probe re-earns the slot
        spec(2, env={"DGC_FAULTS": "hang@5-5"}, hang_timeout=1.5,
             probe_cmd=[sys.executable, WORKER,
                        os.path.join(root, "w2"), "--cohort", cohort_dir,
                        "--probe"]),
    ]
    plane = ControlPlane(specs, root, rules=_surgery_rules(),
                         interval=0.25)
    final = plane.run(max_ticks=400)

    # every run completed: the cohort went 3 -> 2 -> 3 and finished
    for name in ("w0", "w1", "w2"):
        assert final[name]["rc"] == 0, (name, final[name])
        assert final[name]["state"] == "done"
    # w2's first life was SIGKILLed + quarantined; its readmitted life
    # runs under a FRESH supervisor (one launch)
    assert final["w2"]["launches"] == 1
    # survivors: initial launch + exit-76 surgery relaunch + readmit
    # cohort restart
    assert final["w0"]["launches"] >= 3
    assert final["w1"]["launches"] >= 3

    # exactly two audited remediations, both on w2, in surgery order
    assert [(a["run"], a["action"]) for a in plane.actions] == \
        [("w2", "excise"), ("w2", "readmit")]
    exc, adm = plane.actions
    assert exc["evidence"]["kind"] == "hang"
    assert exc["evidence"]["worker"] == 2
    assert exc["evidence"]["world"] == 3             # FROM-world
    assert exc["result"]["published"] == {"JAX_NUM_PROCESSES": "2"}
    # the hang escalation quarantined the run BEFORE the audit: the
    # action records that it was already held, with the hang reason
    assert exc["result"]["already"] is True
    assert exc["result"]["quarantined"].startswith("hang:")
    assert adm["evidence"]["kind"] == "readmit"
    assert adm["evidence"]["pool_free"] == 1
    assert adm["evidence"]["target_world"] == 3
    assert "checksum" in adm["evidence"]             # the probe's output
    assert adm["result"]["published"] == {"JAX_NUM_PROCESSES": "3"}
    assert adm["result"]["relaunched"] is True
    assert set(adm["result"]["cohort_restarted"]) == {"w0", "w1"}

    # the grown spec is what the fleet ends on
    assert parse_env_file(env_file) == {"JAX_NUM_PROCESSES": "3"}

    # survivors took the exit-76 path with an atomic emergency
    # checkpoint and an exit record naming the hung member
    for name in ("w0", "w1"):
        rec = surgery.read_exit_record(
            os.path.join(root, name, "checkpoints", surgery.EXIT_RECORD))
        assert rec is not None, name
        assert rec["target"] == 2 and rec["world"] == 3
        assert rec["verdict"] == "hang" and rec["lost"] is True
        evs = [json.loads(l) for l in open(
            os.path.join(root, name, "supervise_events.jsonl"))]
        surgeries = [e for e in evs if e["event"] == "surgery"]
        assert len(surgeries) == 1 and surgeries[0]["rc"] == 76
        assert surgeries[0]["world"] == 2
        # launch cohort specs walked 3 -> 2 -> 3
        worlds = [e["cohort"].get("JAX_NUM_PROCESSES") for e in evs
                  if e["event"] == "launch"]
        assert worlds[0] == "3" and "2" in worlds and worlds[-1] == "3"

    # the hung worker: hang_kill then quarantined with the hang reason
    evs = [json.loads(l) for l in open(
        os.path.join(root, "w2", "supervise_events.jsonl"))]
    kinds = [e["event"] for e in evs]
    assert "hang_kill" in kinds
    q = next(e for e in evs if e["event"] == "quarantined")
    assert q["reason"].startswith("hang:")
    # ... and its readmit clears the stale exit record
    assert surgery.read_exit_record(os.path.join(
        root, "w2", "checkpoints", surgery.EXIT_RECORD)) is None

    # every member finished all 140 steps; progress is cohort-wide
    for name in ("w0", "w1", "w2"):
        with open(os.path.join(root, name, "checkpoints",
                               "latest.json")) as f:
            assert json.load(f)["epoch"] == 140, name
    with open(os.path.join(cohort_dir, "progress.json")) as f:
        assert json.load(f)["step"] == 140

    # the fleet event stream is the audit trail: probe + every action
    events = [json.loads(l) for l in open(
        os.path.join(root, "control_events.jsonl"))]
    probes = [e for e in events if e["event"] == "probe"]
    assert probes and probes[0]["run"] == "w2"
    assert probes[0]["passed"] is True and "checksum" in probes[0]
    action_evs = [e for e in events if e["event"] == "control_action"]
    assert len(action_evs) == 2
    for e in action_evs:
        registry.validate_control_action(e)

    # the ledger surface: cohort.json per run + fleet root, COHORT line
    # and gauges on the monitor
    with open(os.path.join(root, "cohort.json")) as f:
        fleet_cohort = json.load(f)
    assert fleet_cohort["total"] == 3 and fleet_cohort["free"] == 0
    assert fleet_cohort["runs"]["w2"] == "active"    # readmitted
    snap = monitor.collect(os.path.join(root, "w2"))
    assert snap["cohort"]["spec_world"] == 3
    assert "COHORT:" in monitor.render_status(snap)
    om = monitor.render_openmetrics(snap)
    assert "dgc_cohort_size" in om and "dgc_pool_free" in om
    # the readmitted worker's final life recorded the grown world
    assert snap["static"]["num_processes"] == 3
