"""Convergence parity (SURVEY.md §4 "convergence-as-test"): the reference's
headline claim is that 99.9%-sparse exchange with momentum-corrected error
feedback matches dense training (README.md:117-128 accuracy tables). On a
learnable synthetic task over the 8-way mesh:

* DGC at aggressive sparsity must track the dense baseline's loss curve;
* removing the error-feedback memory at the same sparsity must be WORSE —
  the memory is what makes sparsity safe (the paper's central mechanism).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from dgc_tpu import (
    Compression,
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    Memory,
    dgc_sgd,
    sgd,
)
from dgc_tpu.training import (
    build_train_step,
    make_flat_setup,
    make_flat_state,
    shard_state,
)
from dgc_tpu.utils.pytree import named_flatten

W = 8
BS = 8          # per-worker
CLASSES = 10
STEPS = 120


class TinyCNN(nn.Module):
    """Small BN-free conv net — fast on the CPU mesh, dim>1 kernels so DGC
    compresses the bulk of the parameters."""

    @nn.compact
    def __call__(self, x, train=True):
        x = nn.Conv(16, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(32, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(64)(x)
        x = nn.relu(x)
        return nn.Dense(CLASSES)(x)


@pytest.fixture(scope="module")
def task():
    """Learnable task: class prototypes + noise, a POOL of samples from
    which each step draws a fresh batch (varying batches are the realistic
    regime — error feedback must average over the stream, not memorize one
    batch)."""
    rng = np.random.RandomState(0)
    protos = rng.randn(CLASSES, 16, 16, 3).astype(np.float32)
    n = 1024
    labels = rng.randint(0, CLASSES, n).astype(np.int32)
    images = (protos[labels]
              + 0.3 * rng.randn(n, 16, 16, 3)).astype(np.float32)
    return jnp.asarray(images), jnp.asarray(labels)


def _train(memory, compress_ratio, task, mesh, dense=False, steps=STEPS):
    images, labels = task
    model = TinyCNN()
    v = {"params": model.init(jax.random.PRNGKey(7),
                              jnp.zeros((1, 16, 16, 3)))["params"],
         "batch_stats": {}}

    if dense:
        dist = DistributedOptimizer(
            sgd(0.05, momentum=0.9, weight_decay=1e-4), Compression.none(),
            world_size=W)
    else:
        comp = DGCCompressor(compress_ratio, memory=memory)
        named, _ = named_flatten(v["params"])
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(
            dgc_sgd(0.05, momentum=0.9, weight_decay=1e-4), comp,
            world_size=W)

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        out = model.apply({"params": variables["params"]}, x, train=train)
        if mutable:
            return out, {"batch_stats": {}}
        return out

    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh,
                        dist_opt=dist)
    step = build_train_step(apply_fn, dist, mesh, flat=setup)
    losses = []
    npr = np.random.RandomState(99)   # same batch stream for every config
    for i in range(steps):
        idx = jnp.asarray(npr.randint(0, images.shape[0], W * BS))
        state, m = step(state, images[idx], labels[idx],
                        jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    return losses


def _train_warmup(task, mesh, epochs=5, steps_per_epoch=60):
    """DGC at the FLAGSHIP ratio 0.001 with a warm-up schedule, driving the
    per-epoch engine rebuild exactly like the harness (train.py rebuild)."""
    images, labels = task
    model = TinyCNN()
    v = {"params": model.init(jax.random.PRNGKey(7),
                              jnp.zeros((1, 16, 16, 3)))["params"],
         "batch_stats": {}}
    comp = DGCCompressor(0.001, memory=DGCSGDMemory(momentum=0.9),
                         warmup_epochs=3, warmup_coeff=[0.1, 0.02, 0.004])
    named, _ = named_flatten(v["params"])
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(
        dgc_sgd(0.05, momentum=0.9, weight_decay=1e-4), comp, world_size=W)

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        out = model.apply({"params": variables["params"]}, x, train=train)
        if mutable:
            return out, {"batch_stats": {}}
        return out

    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh,
                        dist_opt=dist)
    step = build_train_step(apply_fn, dist, mesh, donate=False, flat=setup)
    losses = []
    npr = np.random.RandomState(99)
    for epoch in range(epochs):
        if comp.warmup_compress_ratio(epoch):
            setup = make_flat_setup(v, dist)
            step = build_train_step(apply_fn, dist, mesh, donate=False,
                                    flat=setup)
        for i in range(steps_per_epoch):
            idx = jnp.asarray(npr.randint(0, images.shape[0], W * BS))
            state, m = step(state, images[idx], labels[idx],
                            jax.random.PRNGKey(epoch * 1000 + i))
            losses.append(float(m["loss"]))
    assert comp.compress_ratio == 0.001
    return losses


def test_dgc_flagship_ratio_converges(mesh8, task):
    """CI-runnable shortened variant of the flagship operating point
    (VERDICT round-1 item 1): DGC at ratio 0.001 (NOT 0.01) with a warm-up
    schedule must track the dense loss curve on the learnable task. The
    full-scale evidence is scripts/accuracy_parity.py (ResNet-20, 8-worker
    topology, 120 epochs on the TPU — docs/RESULTS.md table); this is its
    fast regression guard."""
    dense = _train(None, None, task, mesh8, dense=True, steps=300)
    dgc = _train_warmup(task, mesh8)
    assert all(np.isfinite(dgc))
    # both learn; DGC's final loss within 1.5x of dense's at the same step
    # count (the loss-curve form of the accuracy-parity claim)
    assert dense[-1] < 0.35 * dense[0]
    assert dgc[-1] < max(1.5 * dense[-1], 0.35 * dgc[0]), (
        dense[-1], dgc[-1])


def test_dgc_parity_and_memory_ablation(mesh8, task):
    dense = _train(None, None, task, mesh8, dense=True)
    dgc = _train(DGCSGDMemory(momentum=0.9), 0.01, task, mesh8)
    nomem = _train(Memory(), 0.01, task, mesh8)

    assert all(np.isfinite(dense)) and all(np.isfinite(dgc))
    # both learn the task
    assert dense[-1] < 0.35 * dense[0], (dense[0], dense[-1])
    # parity: DGC's final loss within 1.5x of dense (the reference's
    # accuracy-parity claim, in loss-curve form)
    assert dgc[-1] < max(1.5 * dense[-1], 0.35 * dgc[0]), (
        dense[-1], dgc[-1])
    # ablation: stripping the error-feedback memory at 1% sparsity must be
    # clearly worse than DGC with memory — the momentum-corrected local
    # accumulation is the mechanism (reference memory.py:50-77)
    assert nomem[-1] > 1.2 * dgc[-1], (nomem[-1], dgc[-1])
