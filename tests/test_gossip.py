"""Gossip sparse exchange with in-graph bounded staleness (ISSUE 20,
dgc_tpu.compression.gossip).

Covers the schedule algebra (config validation, neighborhood symmetry,
mixing-column mass conservation, the traced/NumPy twin agreement), the
engine-level gossip exchange against a full NumPy error-feedback oracle
over real multi-round runs (ring + hypercube, with and without an
injected ``droplink`` fault), the step-exact staleness-breach ->
forced-full-sync drill, the fleet ``w_staleness`` lane on the full
train step, and the elastic gossip-state reshard. The 2-process gloo
gossip run lives in tests/test_multiprocess.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu import (DGCCompressor, DGCSGDMemory, DistributedOptimizer,
                     dgc_sgd)
from dgc_tpu.compression import gossip, planner
from dgc_tpu.compression.flat import FlatDGCEngine
from dgc_tpu.ops import kernels
from dgc_tpu.resilience import faults
from dgc_tpu.utils.compat import shard_map
from dgc_tpu.utils.pytree import named_flatten

W = 8


# --------------------------------------------------------------------- #
# schedule units                                                         #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_make_config_validation():
    cfg = gossip.make_config("ring", W)
    assert cfg.sync_every == gossip.default_sync_every(W) == 4
    assert cfg.max_staleness == gossip.default_max_staleness(W) == 8
    with pytest.raises(ValueError, match="unknown gossip topology"):
        gossip.make_config("mesh", W)
    with pytest.raises(ValueError, match="world >= 2"):
        gossip.make_config("ring", 1)
    with pytest.raises(ValueError, match="power-of-two"):
        gossip.make_config("hcube", 6)
    gossip.make_config("ring", 6)       # non-pow2 ring is fine
    with pytest.raises(ValueError, match="below sync_every"):
        gossip.make_config("ring", W, sync_every=4, max_staleness=3)


@pytest.mark.fast
def test_neighborhoods_symmetric_and_covering():
    for topo in gossip.TOPOLOGIES:
        cfg = gossip.make_config(topo, W)
        seen = {w: set() for w in range(W)}
        for clock in range(W):
            for w in range(W):
                outs = gossip.out_neighbors(cfg, clock, w)
                assert w not in outs
                seen[w].update(outs)
                # symmetric: in-neighborhood == out-neighborhood
                for p in outs:
                    assert w in gossip.out_neighbors(cfg, clock, p)
        # the rotation reaches every other worker eventually
        for w in range(W):
            assert seen[w] == set(range(W)) - {w}
    # hcube matching is an involution every round
    cfg = gossip.make_config("hcube", W)
    for clock in range(W):
        for w in range(W):
            (p,) = gossip.out_neighbors(cfg, clock, w)
            assert gossip.out_neighbors(cfg, clock, p) == (w,)


@pytest.mark.fast
def test_mixing_columns_sum_to_one():
    # sum over receivers of each sender's weight == 1 every round: the
    # gossip mixing matrix is column-stochastic -> signed mass conserved
    for topo in gossip.TOPOLOGIES:
        cfg = gossip.make_config(topo, W)
        for clock in range(2 * W):
            mix = np.stack([gossip.recv_weights_np(cfg, clock, r)
                            for r in range(W)])
            np.testing.assert_allclose(mix.sum(axis=0), 1.0, atol=1e-7)


@pytest.mark.fast
def test_round_state_np_schedule():
    cfg = gossip.make_config("ring", W, sync_every=4, max_staleness=8)
    age = np.zeros((W,), np.int32)
    for clock in range(9):
        full, forced, age = gossip.round_state_np(cfg, clock, age)
        assert full == (clock % 4 == 0)
        assert not forced                   # no fault: breaches never fire
        want = 0 if clock % 4 == 0 else clock % 4
        np.testing.assert_array_equal(age, want)


@pytest.mark.fast
def test_traced_round_state_matches_numpy():
    rng = np.random.RandomState(0)
    for topo in gossip.TOPOLOGIES:
        cfg = gossip.make_config(topo, W, sync_every=3, max_staleness=5)
        for clock in range(7):
            age = rng.randint(0, 5, W).astype(np.int32)
            dropped = (rng.rand(W) < 0.3)
            for d in (None, dropped):
                f_np, fo_np, a_np = gossip.round_state_np(
                    cfg, clock, age, d)
                f_t, fo_t, a_t = gossip.round_state(
                    cfg, jnp.asarray(clock, jnp.int32), jnp.asarray(age),
                    None if d is None else jnp.asarray(d))
                assert bool(f_t) == f_np and bool(fo_t) == fo_np
                np.testing.assert_array_equal(np.asarray(a_t), a_np)
                for w in range(W):
                    rw_np = gossip.row_weights_np(cfg, clock, w, f_np, d)
                    rw_t = gossip.row_weights(
                        cfg, jnp.asarray(clock, jnp.int32),
                        jnp.asarray(w, jnp.int32), f_t,
                        None if d is None else jnp.asarray(d))
                    np.testing.assert_allclose(np.asarray(rw_t), rw_np,
                                               atol=1e-7)


# --------------------------------------------------------------------- #
# planner: gossip regimes are a valid, opt-in plan family                #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_gossip_plan_is_opt_in():
    # default candidate sweeps never pick gossip; forcing the candidate
    # yields a plan carrying the validated schedule config in its key
    assert not any(r.startswith("gossip")
                   for r in planner.REGIMES)
    geoms = [planner.BucketGeom(numel=4096, payload=205, rows=16,
                                index_bits=12.0)]
    plain = planner.plan_buckets(geoms, fabric="32x25GbE", world=W)
    assert plain.gossip is None
    for topo in gossip.TOPOLOGIES:
        plan = planner.plan_buckets(geoms, fabric="32x25GbE", world=W,
                                    candidates=("gossip_" + topo,))
        assert plan.gossip is not None
        assert plan.gossip.topology == topo
        assert plan.key()[-1] == plan.gossip
        assert plan.verify_descriptor()["gossip"] == topo
    with pytest.raises(ValueError, match="power-of-two"):
        planner.plan_buckets(geoms, fabric="32x25GbE", world=6,
                             candidates=("gossip_hcube",))


# --------------------------------------------------------------------- #
# engine: the gossip exchange vs the NumPy oracle                        #
# --------------------------------------------------------------------- #

def _params():
    rng = np.random.RandomState(0)
    return {
        "conv1": {"kernel": jnp.asarray(rng.randn(3, 3, 4, 8), jnp.float32)},
        "conv2": {"kernel": jnp.asarray(rng.randn(3, 3, 8, 8), jnp.float32)},
        "dense": {"kernel": jnp.asarray(rng.randn(32, 10), jnp.float32),
                  "bias": jnp.asarray(rng.randn(10), jnp.float32)},
    }


def _engine(topology="ring", sync_every=4, max_staleness=8):
    params = _params()
    named, _ = named_flatten(params)
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    layout, engine = dist.make_flat(params)
    plan = planner.plan_buckets(
        [planner.bucket_geometry(b) for b in engine.buckets],
        fabric="32x25GbE", world=W, candidates=("gossip_" + topology,),
        gossip_sync_every=sync_every, gossip_max_staleness=max_staleness)
    return comp, layout, FlatDGCEngine(comp, layout, plan=plan)


def _grads(layout, rng):
    g = np.zeros((W, layout.total), np.float32)
    for n in layout.names:
        o, s = layout.offsets[n], layout.sizes[n]
        g[:, o:o + s] = rng.randn(W, s)
    return g


def _exchange_fn(engine, mesh):
    def worker(fg, mem, key):
        fg = fg[0]
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = engine.exchange(fg, mem, key, "data", W, op="average")
        return out[None], jax.tree.map(lambda x: x[None], mem)

    return jax.jit(shard_map(
        worker, mesh=mesh,
        in_specs=(P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))


def _init_mem(engine):
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
        engine.init_memory())


def _run_oracle(mesh, topology, steps=6, droplink=None):
    """Drive the gossip engine ``steps`` rounds against the full NumPy
    oracle: velocity recurrence (inbox fold included), wire output,
    inbox contents, ages, clock, forced counter, and global signed +
    absolute mass conservation. ``droplink`` is a per-round [W] bool
    predicate (round -> dropped vector) mirroring the armed fault."""
    comp, layout, engine = _engine(topology)
    T = engine.T
    cfg = engine._gossip
    f = _exchange_fn(engine, mesh)
    mem = _init_mem(engine)
    rng = np.random.RandomState(3)

    mom = comp.memory.momentum
    v_np = np.zeros((W, T), np.float32)
    m_np = np.zeros((W, T), np.float32)
    inbox_np = np.zeros((W, T), np.float32)
    keep_prev = np.ones((W, T), np.float32)
    age_np = np.zeros((W,), np.int32)
    forced_total = 0
    saw_gossip = saw_full = False

    for step in range(steps):
        g = _grads(layout, rng)
        out, mem = f(jnp.asarray(g), mem, jax.random.PRNGKey(step))
        out0 = np.asarray(out)[0]
        dropped = droplink(step) if droplink is not None else None
        bits = np.asarray(mem["sent_bits"])
        keep_new = np.stack([
            np.asarray(kernels.keep_from_bits(jnp.asarray(bits[w]), T))
            for w in range(W)])
        sent_new = 1.0 - keep_new
        if dropped is not None:
            # the fault voids the dropped sender's transmit record: its
            # mass must stay home in full
            for p in np.nonzero(dropped)[0]:
                np.testing.assert_array_equal(keep_new[p], 1.0)

        full, forced, age_np = gossip.round_state_np(
            cfg, step, age_np, dropped)
        forced_total += int(forced)
        # oracle recurrence: previous round's deferred mask first, THEN
        # the inbox fold (received mass can never be wiped by the
        # receiver's own record)
        m_np = mom * (m_np * keep_prev) + g[:, :T]
        v_np = v_np * keep_prev + m_np + inbox_np

        vc = np.asarray(mem["velocities_c"])
        np.testing.assert_allclose(vc, v_np, rtol=1e-5, atol=1e-5)

        transmitted = v_np * sent_new
        if full:
            saw_full = True
            live = (np.ones(W) if dropped is None
                    else 1.0 - dropped.astype(np.float32))
            np.testing.assert_allclose(
                out0[:T], (transmitted * live[:, None]).sum(0) / W,
                rtol=1e-5, atol=1e-5)
            inbox_np = np.zeros((W, T), np.float32)
        else:
            saw_gossip = True
            assert np.allclose(out0[:T], 0.0)
            inbox_np = np.stack([
                gossip.recv_weights_np(cfg, step, w) @ transmitted
                for w in range(W)])
        np.testing.assert_allclose(np.asarray(mem["gossip_inbox"]),
                                   inbox_np, rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(
            np.asarray(mem["gossip_age"])[0], age_np)
        assert int(np.asarray(mem["gossip_clock"])[0]) == step + 1
        assert int(np.asarray(mem["gossip_forced"])[0]) == forced_total
        # the bound holds by construction, fault or no fault
        assert int(age_np.max()) <= cfg.max_staleness
        # global ABSOLUTE mass: everything accumulated is either kept
        # (residual) or on the wire — nothing invented, nothing lost
        raw = np.abs(v_np.astype(np.float64)).sum()
        keep_mass = np.abs((v_np * keep_new).astype(np.float64)).sum()
        tx_mass = np.abs(transmitted.astype(np.float64)).sum()
        assert abs((keep_mass + tx_mass) - raw) <= 1e-6 * max(raw, 1e-12)
        keep_prev = keep_new
    assert saw_gossip and saw_full   # the run exercised both round kinds
    return engine, mem, forced_total


@pytest.mark.parametrize("topology", gossip.TOPOLOGIES)
def test_gossip_mass_conservation_oracle(mesh8, topology):
    """>= 3 real gossip rounds (plus full-sync rounds) at W=8 against
    the NumPy oracle: velocities, wire, inbox, ages, clock, and global
    mass conservation to 1e-6 relative."""
    _, _, forced = _run_oracle(mesh8, topology, steps=6)
    assert forced == 0                   # no fault, no forced syncs


def test_gossip_droplink_mass_survives(mesh8, monkeypatch):
    """A ``droplink`` round: the dropped worker's contribution is
    suppressed on every receiver AND voided from its own transmit
    record, so the mass-conservation oracle holds straight through the
    fault — and the unset fault stays byte-free (covered by the
    gossip-off contract)."""
    monkeypatch.setenv(faults.ENV, "droplink:peer=3@1-1")

    def droplink(rnd):
        if rnd == 1:
            d = np.zeros((W,), bool)
            d[3] = True
            return d
        return None

    _, mem, forced = _run_oracle(mesh8, "ring", steps=4,
                                 droplink=droplink)
    assert forced == 0       # one dropped round never breaches ms=8
    # the dropped round fed worker 3's receivers zero: their inboxes at
    # round 1 excluded its mass (already asserted inside the oracle via
    # transmitted[3] == 0); by round 4 everything is flowing again
    assert int(np.asarray(mem["gossip_clock"])[0]) == 4


def test_staleness_breach_forces_sync_step_exact(mesh8, monkeypatch):
    """The degradation ladder, pinned step-exact: a droplink on worker 3
    over gossip rounds 1..5 with ``max_staleness == sync_every == 4``
    forces full syncs at exactly rounds 5 (still dropped: age would hit
    5 > 4) and 6 (first live round: the stale view flushes and resets),
    then the schedule resumes — and no age ever exceeds the bound."""
    monkeypatch.setenv(faults.ENV, "droplink:peer=3@1-5")
    comp, layout, engine = _engine("ring", sync_every=4, max_staleness=4)
    cfg = engine._gossip
    f = _exchange_fn(engine, mesh8)
    mem = _init_mem(engine)
    rng = np.random.RandomState(5)

    want_forced = [0, 0, 0, 0, 0, 1, 2, 2]
    want_age3 = [0, 1, 2, 3, 4, 4, 0, 1]    # worker 3's age, clamped at 4
    for step in range(8):
        g = _grads(layout, rng)
        out, mem = f(jnp.asarray(g), mem, jax.random.PRNGKey(step))
        age = np.asarray(mem["gossip_age"])[0]
        assert int(np.asarray(mem["gossip_forced"])[0]) \
            == want_forced[step], step
        assert int(age[3]) == want_age3[step], step
        assert int(age.max()) <= cfg.max_staleness
        # forced and scheduled rounds apply globally (nonzero sparse
        # out); pure gossip rounds keep the params untouched
        is_full = (step % 4 == 0) or step in (5, 6)
        sparse_out = np.abs(np.asarray(out)[0][:engine.T]).sum()
        assert (sparse_out > 0) == is_full, step


def test_gossip_memory_roundtrip_keeps_round_state(mesh8):
    """Checkpoint semantics at the engine level: the canonical
    memory_full view folds the in-flight inbox into velocities (mass-
    conserving), and a state-dict roundtrip preserves clock/age/forced
    bitwise with a zeroed inbox."""
    _, mem, _ = _run_oracle(mesh8, "ring", steps=3)
    comp, layout, engine = _engine("ring")
    mem0 = jax.tree.map(lambda x: jnp.asarray(x[0]), mem)
    full = engine.memory_full(mem0)
    keep = np.asarray(kernels.keep_from_bits(mem0["sent_bits"], engine.T))
    want_v = (np.asarray(mem0["velocities_c"]) * keep
              + np.asarray(mem0["gossip_inbox"]))
    np.testing.assert_allclose(np.asarray(full["velocities"])[:engine.T],
                               want_v, rtol=1e-6, atol=1e-6)
    saved = engine.memory_state_dict(mem0)
    restored = engine.load_memory_state_dict(mem0, saved)
    for k in ("gossip_clock", "gossip_age", "gossip_forced"):
        np.testing.assert_array_equal(np.asarray(restored[k]),
                                      np.asarray(mem0[k]))
    np.testing.assert_array_equal(np.asarray(restored["gossip_inbox"]), 0)
    # and the restored velocities carry the folded inbox mass
    np.testing.assert_allclose(
        np.asarray(restored["velocities_c"]), want_v, rtol=1e-6, atol=1e-6)


# --------------------------------------------------------------------- #
# full train step: the w_staleness lane rides the fleet gather           #
# --------------------------------------------------------------------- #

def test_step_fleet_staleness_lane(mesh8):
    """The fleet step under a gossip plan: w_staleness is a real
    per-worker column tracking the gossip ages, max_staleness_seen /
    gossip_forced_syncs ride along, and a non-gossip fleet build keeps
    the same schema with constant-zero values."""
    from dgc_tpu.analysis.suite import build_fixture

    g_plan = planner.plan_buckets([], fabric="32x25GbE", world=W,
                                  candidates=("gossip_ring",),
                                  gossip_sync_every=4)
    state, step, setup, (images, labels, key) = build_fixture(
        mesh8, donate=False, telemetry=True, fleet=True, plan=g_plan)
    sh = NamedSharding(mesh8, P(tuple(mesh8.axis_names)))
    clock = jax.device_put(np.full((W,), 10.0, np.float32), sh)

    ages = []
    for i in range(3):
        state, metrics = step(state, images, labels, key, clock)
        flt = metrics["fleet"]
        col = np.asarray(flt["w_staleness"])
        assert col.shape == (W,)
        ages.append(col)
        assert float(flt["max_staleness_seen"]) == col.max()
        assert float(flt["gossip_forced_syncs"]) == 0.0
    # round 0 is the warm full sync (ages 0); rounds 1..2 are gossip
    # rounds, every worker's age ticking up in lockstep
    np.testing.assert_allclose(ages[0], 0.0)
    np.testing.assert_allclose(ages[1], 1.0)
    np.testing.assert_allclose(ages[2], 2.0)

    # gossip off: identical schema, constant-zero gossip lanes
    state_p, step_p, _, (im, lb, k) = build_fixture(
        mesh8, donate=False, telemetry=True, fleet=True)
    _, metrics_p = step_p(state_p, im, lb, k, clock)
    np.testing.assert_allclose(
        np.asarray(metrics_p["fleet"]["w_staleness"]), 0.0)
    assert float(metrics_p["fleet"]["max_staleness_seen"]) == 0.0
    assert float(metrics_p["fleet"]["gossip_forced_syncs"]) == 0.0


# --------------------------------------------------------------------- #
# faults: droplink parsing                                               #
# --------------------------------------------------------------------- #

@pytest.mark.fast
def test_droplink_parsing():
    p = faults.plan("droplink:peer=3@2-5")
    assert p.droplink_peer == 3 and p.droplink_window == (2, 5)
    assert faults.plan("droplink:peer=1").droplink_window == (0, None)
    assert faults.plan("droplink:peer=1@7").droplink_window == (7, None)
    with pytest.raises(ValueError, match="peer"):
        faults.plan("droplink@2-5")
    # unarmed: the injector is Python-static None (zero HLO)
    assert faults.gossip_dropped(W, jnp.zeros((), jnp.int32)) is None \
        or faults.plan().droplink_peer is None


@pytest.mark.fast
def test_droplink_window_counts_gossip_rounds():
    import os
    old = os.environ.get(faults.ENV)
    os.environ[faults.ENV] = "droplink:peer=2@3-4"
    try:
        for clock, inside in ((2, False), (3, True), (4, True), (5, False)):
            d = np.asarray(faults.gossip_dropped(
                W, jnp.asarray(clock, jnp.int32)))
            assert d[2] == inside and d.sum() == int(inside)
    finally:
        if old is None:
            os.environ.pop(faults.ENV, None)
        else:
            os.environ[faults.ENV] = old
