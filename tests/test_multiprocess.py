"""Two-process ``jax.distributed`` execution of the multi-host path
(VERDICT round-1 item: the reference ran 8-256 real MPI ranks,
/root/reference/train.py:99-100,244-264 — this exercises process-group
init, ``host_local_to_global`` batch assembly, a sharded flat DGC train
step over a 2-process x 4-device mesh, collective checkpoint save with
coordinator-only bookkeeping, and restore-then-train)."""

import json
import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_train_save_resume(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    # log to FILES, not PIPEs: sequential communicate() would deadlock if
    # the other process fills its 64KB pipe while both sit at a collective
    # barrier
    logs = [open(tmp_path / f"worker{i}.log", "w+") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", coord, str(tmp_path)],
            stdout=logs[i], stderr=subprocess.STDOUT, text=True, env=env)
        for i in range(2)
    ]
    outs = []
    for p, lf in zip(procs, logs):
        # generous: a cold compilation cache means several multi-minute
        # XLA compiles per process on a loaded 1-core host (warm: ~30 s);
        # the workers' own coordination timeouts are raised to match
        p.wait(timeout=1500)
        lf.seek(0)
        outs.append(lf.read())
        lf.close()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-4000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT:"):
                r = json.loads(line[len("RESULT:"):])
                results[r["proc"]] = r
    assert set(results) == {0, 1}
    # single-controller semantics: both processes observe identical losses
    assert results[0]["losses"] == results[1]["losses"]
    # two-tier hierarchical exchange over the real process boundary agrees
    assert results[0]["tt_losses"] == results[1]["tt_losses"]
    # first-step losses match: before any exchange reaches the params, the
    # two runs share params and data, so forward losses are near-identical
    assert abs(results[0]["losses"][0] - results[0]["tt_losses"][0]) < 1e-4
    assert results[0]["coordinator"] and not results[1]["coordinator"]
    # coordinator-only file bookkeeping
    assert (tmp_path / "logs" / "metrics.jsonl").exists()
    assert (tmp_path / "ckpt" / "latest.json").exists()
    assert (tmp_path / "ckpt" / "best").exists()

    # --- 4-host x 2-local two-tier mesh: sparse axis crosses the process
    # boundary (rows 0-1 proc 0, rows 2-3 proc 1) ---
    assert results[0]["t4_losses"] == results[1]["t4_losses"]
    assert all(l == l and abs(l) < 1e6 for l in results[0]["t4_losses"])
    # per-node memory semantics: the local (dense) tier psums the gradient
    # before compression, so both devices of a host row hold bitwise-
    # identical error-feedback memory at every step...
    assert results[0]["t4_mem_pair_dev"] == [0.0, 0.0], \
        f"per-node memory diverged: {results[0]['t4_mem_pair_dev']}"
    # ...and the property survives a collective save/resume cycle
    assert results[0]["t4_restore_diff"] == 0.0
    assert results[0]["t4_restored_pair_dev"] == 0.0
    assert results[0]["t4_resumed_pair_dev"] == 0.0
    # telemetry taps ran inside the cross-process program and agree
    assert results[0]["t4_payload"] == results[1]["t4_payload"] > 0
    assert (tmp_path / "ckpt_tt" / "latest.json").exists()


def _run_pair(worker, tmp_path, phase, extra_env=None):
    """Launch one 2-process phase of the preempt worker; return the parsed
    per-process RESULT dicts."""
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "DGC_FAULTS")}
    logs = [open(tmp_path / f"{phase}_w{i}.log", "w+") for i in range(2)]
    procs = []
    for i in range(2):
        e = dict(env)
        if extra_env and i in extra_env:
            e.update(extra_env[i])
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(i), "2", coord, str(tmp_path),
             phase],
            stdout=logs[i], stderr=subprocess.STDOUT, text=True, env=e))
    outs = []
    for p, lf in zip(procs, logs):
        p.wait(timeout=1500)
        lf.seek(0)
        outs.append(lf.read())
        lf.close()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{phase} proc {i} failed:\n{out[-4000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT:"):
                r = json.loads(line[len("RESULT:"):])
                results[r["proc"]] = r
    assert set(results) == {0, 1}, f"{phase}: missing RESULT lines"
    return results


def test_kill_and_resume_bitwise_memory(tmp_path):
    """Resilience drill (docs/RESILIENCE.md): SIGTERM one worker of a
    2-process run mid-training; both processes must agree on the same step
    boundary, write one collective emergency checkpoint, and exit cleanly.
    A fresh launch must restore it and continue with BITWISE-identical
    per-worker compressor memory and the exact loss trajectory of an
    uninterrupted run."""
    import signal

    worker = os.path.join(os.path.dirname(__file__), "preempt_worker.py")
    base = _run_pair(worker, tmp_path, "baseline")
    run = _run_pair(worker, tmp_path, "run",
                    extra_env={1: {"DGC_FAULTS": "kill@3"}})
    res = _run_pair(worker, tmp_path, "resume")
    for p in (0, 1):
        # both processes broke on the same boundary, after exactly 3 steps
        assert run[p]["preempt_at"] == 2
        assert run[p]["losses"] == base[p]["losses"][:3]
        # the emergency checkpoint holds the exact 3-step memory: saved,
        # restored, and baseline fingerprints all bitwise-identical
        assert (res[p]["mem_restored"] == run[p]["mem_saved"]
                == base[p]["mem_at_kill"])
        # post-resume trajectory matches the uninterrupted run exactly
        assert res[p]["start"] == 3
        assert res[p]["losses"] == base[p]["losses"][3:]
        assert res[p]["mem_final"] == base[p]["mem_final"]
    # only the faulted process saw the signal; the save was atomic (no
    # .tmp staging dir left behind, latest pointer published)
    assert run[1]["signum"] == int(signal.SIGTERM)
    assert not (tmp_path / "ckpt_preempt" / "e0.tmp").exists()
    assert (tmp_path / "ckpt_preempt" / "latest.json").exists()
    # the emergency path stamps the topology record, so an elastic
    # relaunch on a different slice shape can reshard this checkpoint
    meters = json.loads(
        (tmp_path / "ckpt_preempt" / "e0" / "meters.json").read_text())
    assert meters["_topology"] == {"process_count": 2, "world": 8,
                                   "num_local_workers": 1}


def test_gossip_two_process_save_resume(tmp_path):
    """Gossip drill over a real process boundary (docs/RESILIENCE.md
    §Gossip exchange): run the fleet train step under a ``gossip_ring``
    plan across 2 gloo processes with ``droplink:peer=3@1-5`` armed on
    BOTH (the injector is traced into the shared program). The staleness
    ladder must replay the step-exact single-process arithmetic — worker
    3's age climbs to the bound, forced full-syncs fire at exactly
    clocks 5 and 6 — the ``w_staleness`` lane and forced-sync counter
    must reach the fleet sink, and a mid-drill collective checkpoint
    must round-trip the gossip clock state BITWISE: the resumed run's
    losses and final gossip fingerprint match the uninterrupted run
    exactly."""
    worker = os.path.join(os.path.dirname(__file__), "gossip_worker.py")
    fault = {i: {"DGC_FAULTS": "droplink:peer=3@1-5"} for i in (0, 1)}
    run = _run_pair(worker, tmp_path, "run", extra_env=fault)
    res = _run_pair(worker, tmp_path, "resume", extra_env=fault)

    # replicated verdicts: both processes observe identical lanes
    for key in ("losses", "w_staleness", "forced", "max_seen"):
        assert run[0][key] == run[1][key], key
    # the step-exact degradation ladder (tests/test_gossip.py::
    # test_staleness_breach_forces_sync_step_exact, now cross-process)
    assert run[0]["forced"] == [0, 0, 0, 0, 0, 1, 2, 2]
    age3 = [col[3] for col in run[0]["w_staleness"]]
    assert age3 == [0, 1, 2, 3, 4, 4, 0, 1]
    assert run[0]["max_seen"] == [0, 1, 2, 3, 4, 4, 0, 1]
    # the bound holds for every worker at every step
    assert max(x for col in run[0]["w_staleness"] for x in col) <= 4

    # bitwise save/resume of the gossip clock state, per process shard
    for p in (0, 1):
        assert res[p]["start"] == 5
        assert res[p]["gossip_restored"] == run[p]["gossip_saved"]
        # the resumed trajectory IS the uninterrupted one
        assert res[p]["losses"] == run[p]["losses"][5:]
        assert res[p]["forced"] == run[p]["forced"][5:]
        assert res[p]["w_staleness"] == run[p]["w_staleness"][5:]
        assert res[p]["gossip_final"] == run[p]["gossip_final"]
        assert res[p]["mem_final"] == run[p]["mem_final"]

    # the staleness gauges reached the per-host sink shards
    from dgc_tpu.telemetry import fleet, monitor

    view = fleet.load_view(str(tmp_path / "gossiprun"))
    assert sorted(view.hosts) == ["host0", "host1"]
    assert view.world == 8
    series = dict(fleet.worker_series(view, "w_staleness"))
    assert [s[3] for s in (series[i] for i in range(8))] \
        == [0, 1, 2, 3, 4, 4, 0, 1]

    snap = monitor.collect(str(tmp_path / "gossiprun"))
    om = monitor.render_openmetrics(snap)
    assert "dgc_worker_staleness" in om
    assert "dgc_gossip_forced_syncs" in om
    status = monitor.render_status(snap)
    assert "GOSSIP:" in status and "FORCED SYNCS 2" in status


def _run_elastic_phase(tmp_path, phase, world, *extra):
    """One single-process launch of tests/elastic_worker.py at a fake
    world size; returns the parsed RESULT dict."""
    worker = os.path.join(os.path.dirname(__file__), "elastic_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "DGC_FAULTS")}
    proc = subprocess.run(
        [sys.executable, worker, phase, str(world), str(tmp_path),
         *map(str, extra)],
        capture_output=True, text=True, env=env, timeout=900)
    assert proc.returncode == 0, (
        f"elastic {phase}@W={world} failed:\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-4000:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("RESULT:"):
            return json.loads(line[len("RESULT:"):])
    raise AssertionError(f"no RESULT line from {phase}@W={world}")


def test_elastic_cross_topology_resume(tmp_path):
    """Elastic restart drill (docs/RESILIENCE.md §"Elastic restart"):
    save a checkpoint at W=4, resume at W=2 (2:1 merge) and W=1 (full
    collapse). The worker asserts per-parameter residual+momentum
    gradient mass against an independent NumPy oracle and that merged BN
    rows are parent-group means; here we additionally pin that the mass
    the save phase computed from the LIVE state matches what the resume
    phases recovered from disk, and that the resumed runs keep learning
    on the same global-batch schedule."""
    base = _run_elastic_phase(tmp_path, "baseline", 4)
    save = _run_elastic_phase(tmp_path, "save", 4)
    res2 = _run_elastic_phase(tmp_path, "resume", 2, 4)
    res1 = _run_elastic_phase(tmp_path, "resume", 1, 4)

    # the first 10 steps of the save phase ARE the baseline's: same
    # data, same topology, same seeds
    assert save["losses"] == base["losses"][:10]

    for res in (res2, res1):
        assert res["start"] == 10
        # worker-side oracle verdict, re-pinned here
        assert res["mass_rel"] < 1e-5
        # per-parameter mass from the live pre-save state equals the
        # mass recovered from disk after the reshard (two independent
        # computations: different arrays, different world sizes)
        for name, (m_saved, v_saved) in save["mass"].items():
            m_new, v_new = res["mass"][name]
            for a, b in ((m_saved, m_new), (v_saved, v_new)):
                assert abs(a - b) <= 1e-5 * max(abs(a), abs(b), 1e-6), \
                    f"{name}: {a} vs {b}"
        losses = res["losses"]
        assert all(l == l and abs(l) < 1e6 for l in losses)
        # resumed training still converges (the test_convergence
        # tolerance: the reshard perturbs the trajectory, not the fate)
        assert losses[-1] < max(1.5 * base["losses"][-1],
                                0.35 * base["losses"][0]), \
            f"resumed run diverged: {losses}"
    # the synthetic task genuinely learns, so the bound above has teeth
    first6 = sum(base["losses"][:6]) / 6
    last6 = sum(base["losses"][-6:]) / 6
    assert last6 < first6


def test_elastic_grow_resume(tmp_path):
    """Elastic GROW drill (docs/RESILIENCE.md §"Cohort surgery" readmit
    path): save at W=1, resume at W=2 — the 1:k split. The worker
    asserts the split semantics directly on the restored arrays: child
    c%k==0 inherits its parent's rows BITWISE (sent_bits included),
    siblings start zeroed, BN rows are copied; here we re-pin that the
    per-parameter residual+momentum gradient mass recovered from disk
    equals what the save phase computed from the live state — growth
    must conserve mass exactly, not just shrinkage."""
    save = _run_elastic_phase(tmp_path, "save", 1)
    res = _run_elastic_phase(tmp_path, "resume", 2, 1)
    assert res["start"] == 10
    assert res["mass_rel"] < 1e-5
    for name, (m_saved, v_saved) in save["mass"].items():
        m_new, v_new = res["mass"][name]
        for a, b in ((m_saved, m_new), (v_saved, v_new)):
            assert abs(a - b) <= 1e-5 * max(abs(a), abs(b), 1e-6), \
                f"{name}: {a} vs {b}"
    losses = res["losses"]
    assert all(l == l and abs(l) < 1e6 for l in losses)
    # the grown run keeps learning on the same global-batch schedule
    assert losses[-1] < max(1.5 * save["losses"][-1],
                            0.35 * save["losses"][0]), \
        f"grown run diverged: {losses}"


def test_fleet_two_process_straggler(tmp_path):
    """Fleet observability drill (docs/TELEMETRY.md §Fleet monitoring):
    run the fleet train step across 2 real processes with
    ``DGC_FAULTS=slow:ms=350`` armed on process 1 only. The injected
    host-side sleep stretches only that process's dispatch intervals, so
    the in-graph straggler verdict, the merged host-shard fleet view, and
    the monitor's straggler table must all name one of process 1's
    workers (4-7) — while the desync detector stays quiet on the healthy
    residual-mass cohort, and fires once we corrupt one worker's recorded
    residual-mass column."""
    worker = os.path.join(os.path.dirname(__file__), "fleet_worker.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "DGC_FAULTS")}
    logs = [open(tmp_path / f"fleet_w{i}.log", "w+") for i in range(2)]
    procs = []
    for i in range(2):
        e = dict(env)
        if i == 1:
            e["DGC_FAULTS"] = "slow:ms=350"
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(i), "2", coord, str(tmp_path)],
            stdout=logs[i], stderr=subprocess.STDOUT, text=True, env=e))
    outs = []
    for p, lf in zip(procs, logs):
        p.wait(timeout=1500)
        lf.seek(0)
        outs.append(lf.read())
        lf.close()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"fleet proc {i} failed:\n{out[-4000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT:"):
                r = json.loads(line[len("RESULT:"):])
                results[r["proc"]] = r
    assert set(results) == {0, 1}

    # in-graph verdict is replicated: both processes saw the same columns
    assert results[0]["stragglers"] == results[1]["stragglers"]
    # steady state (skip warmup: step 0 stamps dt=0, step 1 absorbs the
    # compile): the straggler is one of process 1's workers (4-7)
    tail = results[0]["stragglers"][2:]
    slow_hits = sum(1 for s in tail if s >= 4)
    assert slow_hits >= len(tail) - 1, \
        f"straggler verdicts did not name process 1: {results[0]}"

    # --- host-side: merge the per-host shards into the fleet view ---
    from dgc_tpu.telemetry import fleet, monitor

    run_dir = str(tmp_path / "fleetrun")
    view = fleet.load_view(run_dir)
    assert sorted(view.hosts) == ["host0", "host1"]
    assert view.world == 8 and view.skipped == 0
    assert len(view.steps) >= 10

    table = fleet.straggler_table(view)
    assert len(table) == 8
    assert table[0]["worker"] >= 4, f"straggler table: {table[:2]}"
    assert table[0]["share"] > 1.0

    summary = fleet.fleet_summary(view)
    assert summary["straggler"] >= 4
    assert summary["straggler_gap"] > 100.0      # ms: the injected sleep
    # healthy run: the residual/grad-mass desync detector stays quiet
    assert summary["desync_alerts"] == 0, summary

    # --- monitor renders both projections from the recorded run ---
    snap = monitor.collect(run_dir)
    om = monitor.render_openmetrics(snap)
    assert om.endswith("# EOF\n")
    assert 'dgc_worker_clock_ms{run="fleetrun",worker="7"}' in om
    assert "dgc_straggler_gap_ms" in om and "dgc_worker_skew" in om
    status = monitor.render_status(snap)
    assert "straggler" in status and "desync: quiet" in status

    # --- corrupted-residual drill: rewrite ONE worker's recorded
    # residual-mass column with a multiplicative walk-away; the detector
    # must fire and name that worker ---
    bad = 5
    corrupt = tmp_path / "fleetrun_corrupt"
    for host, files in fleet.discover_shards(run_dir).items():
        hd = corrupt / "telemetry" / host
        hd.mkdir(parents=True)
        for f in files:
            out_lines = []
            for ln in open(f):
                rec = json.loads(ln)
                col = rec.get("w_residual_mass")
                if isinstance(col, list) and "step" in rec:
                    drift = 1.0 + 0.9 * max(0, int(rec["step"]) - 4)
                    col[bad] = col[bad] * drift
                out_lines.append(json.dumps(rec))
            (hd / os.path.basename(f)).write_text(
                "\n".join(out_lines) + "\n")
    cview = fleet.load_view(str(corrupt))
    alerts = fleet.detect_desync(
        fleet.worker_series(cview, "w_residual_mass"))
    assert alerts, "corrupted residual column must trip the detector"
    assert {a.worker for a in alerts} == {bad}
    csummary = fleet.fleet_summary(cview)
    assert csummary["desync_alerts"] > 0
    assert csummary["desync_workers"] == [bad]


def test_fleet_two_process_adaptive(tmp_path):
    """Straggler-adaptive drill (docs/RESILIENCE.md §Adaptive exchange):
    the fleet step with the adaptive policy on, across 2 real processes,
    with a WINDOWED fault (``slow:ms=350@3-8``) armed on process 1 only.
    The policy must engage one step after the window opens (one-step
    verdict feedback), degrade ONLY process 1's workers — their effective
    send fraction and actual wire sent-ratio drop while the healthy
    workers' stay at full quota — and release to full send after the
    window closes (memoryless policy). Verdicts are replicated: both
    processes must report identical columns."""
    worker = os.path.join(os.path.dirname(__file__), "fleet_worker.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "DGC_FAULTS")}
    logs = [open(tmp_path / f"adapt_w{i}.log", "w+") for i in range(2)]
    procs = []
    for i in range(2):
        e = dict(env)
        if i == 1:
            e["DGC_FAULTS"] = "slow:ms=350@3-8"
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(i), "2", coord, str(tmp_path),
             "adaptive"],
            stdout=logs[i], stderr=subprocess.STDOUT, text=True, env=e))
    outs = []
    for p, lf in zip(procs, logs):
        p.wait(timeout=1500)
        lf.seek(0)
        outs.append(lf.read())
        lf.close()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"adaptive proc {i} failed:\n{out[-4000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT:"):
                r = json.loads(line[len("RESULT:"):])
                results[r["proc"]] = r
    assert set(results) == {0, 1}

    # the verdict is a pure function of gathered (replicated) columns
    assert results[0]["eff"] == results[1]["eff"]
    eff = results[0]["eff"]
    engaged = results[0]["engaged"]

    # before the fault window (+1 step of verdict lag): nobody degraded
    for step in range(0, 4):
        assert all(x == 1.0 for x in eff[step]), (step, eff[step])
    # engaged mid-window: process 1's workers (4-7) degraded, the healthy
    # half untouched — steps 5..9 (the sleep stamps clocks at steps 3-8,
    # each verdict lands one step later; skip the boundary steps)
    mid = range(5, 9)
    for step in mid:
        assert engaged[step] == 1.0, (step, engaged)
        assert all(x == 1.0 for x in eff[step][:4]), (step, eff[step])
        assert any(x < 0.999 for x in eff[step][4:]), (step, eff[step])
    # released after the window: memoryless policy back to full send
    for step in range(11, len(eff)):
        assert engaged[step] == 0.0, (step, engaged)
        assert all(x == 1.0 for x in eff[step]), (step, eff[step])

    # the degradation reached the WIRE, not just the policy output: the
    # straggler half's actual transmitted ratio drops mid-window
    sent = results[0]["sent"]
    for step in mid:
        slow = sum(sent[step][4:]) / 4
        healthy = sum(sent[step][:4]) / 4
        assert slow < 0.95 * healthy, (step, sent[step])
    # outside the window both halves transmit the same quota
    last = len(sent) - 1
    assert abs(sum(sent[last][4:]) - sum(sent[last][:4])) <= \
        0.05 * sum(sent[last][:4])

    # merged host shards carry the new lanes end to end
    from dgc_tpu.telemetry import fleet, monitor

    view = fleet.load_view(str(tmp_path / "fleetrun"))
    series = {step: vals
              for step, vals in fleet.worker_series(view, "w_eff_ratio")}
    for step in mid:
        assert min(series[step][4:]) < 0.999
        assert all(x == 1.0 for x in series[step][:4])

    om = monitor.render_openmetrics(monitor.collect(
        str(tmp_path / "fleetrun")))
    assert "dgc_worker_eff_ratio" in om
    assert "dgc_adaptive_engaged" in om
