"""Online exchange replanning (dgc_tpu.compression.autotune): the
epoch-boundary refit loop, its zero-recompile plan identity, and the
provenance-stamped fabric.json persistence.

Everything here is host-side (engine construction + planning is NumPy);
no mesh, no compiled exchange — the compile-pinning side lives in
dgc_tpu/analysis/suite.py as contracts.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu import DGCCompressor, DGCSGDMemory, DistributedOptimizer, dgc_sgd
from dgc_tpu.compression.autotune import Autotuner, regime_histogram
from dgc_tpu.compression.planner import (
    BUILTIN_FABRICS,
    Fabric,
    load_fabric,
)
from dgc_tpu.utils.pytree import named_flatten

W = 8


class _ListSink:
    def __init__(self):
        self.records = []

    def write_record(self, rec):
        self.records.append(rec)


def _engine(ratio=0.05):
    """A two-bucket engine (one large, one small tensor) whose plan
    flips between sparse and dense regimes as the modeled link speed
    changes — the replan trigger geometry."""
    rng = np.random.RandomState(0)
    params = {
        "big": {"kernel": jnp.asarray(rng.randn(600, 600), jnp.float32)},
        "small": {"kernel": jnp.asarray(rng.randn(40, 50), jnp.float32)},
        "bias": {"b": jnp.asarray(rng.randn(16), jnp.float32)},
    }
    named, _ = named_flatten(params)
    comp = DGCCompressor(ratio, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    _, engine = dist.make_flat(params)
    return engine


def _selfconsistent_points(fabric, sizes):
    """Per-hop (bytes, ms) points exactly on the fabric's own line —
    a refit from these recovers (alpha_ms, gbps) and the plan key
    cannot change."""
    return [(b, fabric.alpha_ms + b / (fabric.gbps * 1e6)) for b in sizes]


def test_regime_histogram():
    assert regime_histogram(()) == {}
    assert regime_histogram(("int8", "dense", "int8", "int4_packed")) == {
        "dense": 1, "int4_packed": 1, "int8": 2}
    # stable (sorted) key order for JSON diffing
    assert list(regime_histogram(("fp32", "dense"))) == ["dense", "fp32"]


def test_autotuner_stable_name_and_gating():
    """The fabric renames to autotuned-<base> ONCE, so Plan.key() moves
    only with the regimes; below min_points epoch_end is a no-op."""
    tuner = Autotuner(fabric="32x25GbE", world=W, min_points=3)
    assert tuner.fabric.name == "autotuned-32x25GbE"
    assert tuner.base_name == "32x25GbE"
    assert tuner.world == W
    # renaming is idempotent: an already-autotuned fabric keeps its name
    again = Autotuner(fabric=tuner.fabric, world=W)
    assert again.fabric.name == "autotuned-32x25GbE"

    engine = _engine()
    plan = tuner.plan_for(engine)
    assert plan.fabric.name == "autotuned-32x25GbE"
    assert tuner.plan is plan

    # 2 points < min_points=3: no fit, no event, compiled step untouched
    tuner.sink = _ListSink()
    tuner.record_step(1.0, 10_000)
    tuner.record_step(1.1, 10_000)
    assert tuner.epoch_end(engine, epoch=0) is None
    assert tuner.refit_count == 0 and tuner.replan_count == 0
    assert tuner.sink.records == []
    # non-positive samples never enter the pool
    tuner.record_step(0.0, 10_000)
    tuner.record_step(1.0, 0)
    assert len(tuner.points) == 2


def test_autotuner_refit_same_key_keeps_plan():
    """Self-consistent points: the refit recovers the fabric it already
    had, the plan key is unchanged, epoch_end returns None (the
    caller's do-not-rebuild signal) — but the refit IS recorded."""
    tuner = Autotuner(fabric="32x25GbE", world=W, min_points=2,
                      sink=_ListSink())
    engine = _engine()
    plan0 = tuner.plan_for(engine)
    for b, t in _selfconsistent_points(tuner.fabric,
                                       (1e4, 1e5, 1e6, 5e6)):
        tuner.record_step(t, int(b))
    assert tuner.epoch_end(engine, epoch=1) is None
    assert tuner.refit_count == 1
    assert tuner.replan_count == 0
    assert tuner.plan is plan0
    assert tuner.fabric.measured
    assert tuner.fabric.gbps == pytest.approx(
        BUILTIN_FABRICS["32x25GbE"].gbps, rel=1e-6)
    (rec,) = tuner.sink.records
    assert rec["event"] == "autotune_replan"
    assert rec["rebuilt"] is False
    assert rec["epoch"] == 1
    assert rec["regimes"] == regime_histogram(plan0.regimes)


def test_autotuner_replans_when_fabric_drifts():
    """Start on the fast ICI fabric (all-dense plan), then feed points
    from a link ~1000x slower: the refit must change the regimes, and
    epoch_end returns the new plan exactly once."""
    tuner = Autotuner(fabric="ici_v5e8", world=W, min_points=2,
                      sink=_ListSink())
    engine = _engine()
    plan0 = tuner.plan_for(engine)
    assert plan0.all_dense, plan0.regimes
    slow = Fabric("slow", W, gbps=0.05, alpha_ms=5.0)
    for b, t in _selfconsistent_points(slow, (1e4, 1e5, 1e6, 5e6)):
        tuner.record_step(t, int(b))
    new = tuner.epoch_end(engine, epoch=2)
    assert new is not None and not new.all_dense
    assert tuner.replan_count == 1
    assert tuner.plan is new
    # the key moved through the regimes, never the name
    assert new.fabric.name == "autotuned-ici_v5e8"
    assert new.key() != plan0.key()
    (rec,) = tuner.sink.records
    assert rec["rebuilt"] is True
    # a second epoch on the same points: same decisions, no rebuild
    assert tuner.epoch_end(engine, epoch=3) is None
    assert tuner.refit_count == 2 and tuner.replan_count == 1


def test_autotuner_writes_provenance_stamped_fabric(tmp_path):
    """fabric.json round-trips through planner.load_fabric (schema,
    name, workers, fit) and carries the autotune provenance block."""
    out = tmp_path / "runs" / "fabric.json"
    tuner = Autotuner(fabric="32x25GbE", world=W, min_points=2,
                      fabric_out=str(out))
    engine = _engine()
    tuner.plan_for(engine)
    for b, t in _selfconsistent_points(tuner.fabric, (1e5, 1e6, 4e6)):
        tuner.record_step(t, int(b))
    tuner.epoch_end(engine, epoch=5)
    fab = load_fabric(str(out))
    assert fab.name == "autotuned-32x25GbE"
    assert fab.workers == W
    assert fab.measured
    assert fab.gbps == pytest.approx(tuner.fabric.gbps)
    assert fab.alpha_ms == pytest.approx(tuner.fabric.alpha_ms)
    prov = json.loads(out.read_text())["provenance"]
    assert prov["source"] == "autotune"
    assert prov["base"] == "32x25GbE"
    assert prov["refit"] == 1
    assert prov["epoch"] == 5
    assert prov["points"] == 3
    assert prov["distinct_sizes"] == 3
    assert prov["geometry_bytes"] == [100_000, 1_000_000, 4_000_000]
    # self-consistent points lie exactly on the fit line
    assert prov["fit_residual_ms"] == pytest.approx(0.0, abs=1e-9)
    assert "written_at" in prov


def test_autotuner_ingests_attrib_profile():
    """Per-bucket allgather ms from an attrib profile dict become
    (bucket wire bytes, ms) points — the sharp multi-size input."""
    tuner = Autotuner(fabric="32x25GbE", world=W, min_points=2)
    engine = _engine()
    tuner.plan_for(engine)
    wire = engine.bucket_wire_bytes()
    assert len(wire) == 2 and all(b > 0 for b in wire)
    profile = {"dgc": {"buckets": {
        "b0": {"allgather": 1.5, "select": 0.3},
        "b1": {"allgather": 0.2},
        "b7": {"allgather": 9.9},      # no such bucket: ignored
    }}}
    assert tuner.add_profile(profile, engine) == 2
    assert sorted(tuner.points) == sorted(
        [(float(wire[0]), 1.5), (float(wire[1]), 0.2)])
    assert tuner.add_profile(None, engine) == 0
    assert tuner.add_profile({}, engine) == 0
    # epoch_end ingests the profile= kwarg the same way
    tuner2 = Autotuner(fabric="32x25GbE", world=W, min_points=2)
    tuner2.plan_for(engine)
    tuner2.epoch_end(engine, epoch=0, profile=profile)
    assert tuner2.refit_count == 1


def test_autotuner_point_pool_is_bounded():
    tuner = Autotuner(fabric="32x25GbE", world=W, max_points=10)
    for i in range(25):
        tuner.record_step(1.0 + i, 1000 + i)
    assert len(tuner.points) == 10
    # newest kept
    assert tuner.points[-1] == (1024.0, 25.0)
    assert tuner.points[0] == (1015.0, 16.0)
