"""Regime-aware exchange planner (dgc_tpu.compression.planner): cost-model
decision boundaries, plan identity/replan semantics, fabric.json round-trip,
and the planner's integration with the flat engine (including the fused
select/pack path the planner's pipeline rides on).

Everything here is host-side and fast except the RecompileGuard pin, which
lowers the exchange once on the 8-fake-device CPU mesh.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu import DGCCompressor, DGCSGDMemory, DistributedOptimizer, dgc_sgd
from dgc_tpu.compression.planner import (
    BUILTIN_FABRICS,
    BucketGeom,
    CostModel,
    FABRIC_SCHEMA,
    FABRIC_VERSION,
    Fabric,
    Plan,
    bucket_ms_from_profile,
    fit_link_model,
    load_fabric,
    plan_buckets,
    plan_engine,
    resolve_fabric,
)
from dgc_tpu.utils.pytree import named_flatten

W = 8

#: a geometry where sparse wire wins big on slow fabrics (ResNet-20-ish:
#: 272k params, 0.1% payload) and a tiny one where the fixed sparse
#: overhead can never pay for itself
BIG = BucketGeom(numel=272_474, payload=283, rows=20, index_bits=14.0)
TINY = BucketGeom(numel=2_000, payload=4, rows=2, index_bits=11.0)


def _two_bucket_setup(ratio=0.05, **comp_kw):
    """Params whose compressed tensors land in two engine buckets (the
    mixed-plan geometry: one large, one small)."""
    rng = np.random.RandomState(0)
    params = {
        "big": {"kernel": jnp.asarray(rng.randn(600, 600), jnp.float32)},
        "small": {"kernel": jnp.asarray(rng.randn(40, 50), jnp.float32)},
        "bias": {"b": jnp.asarray(rng.randn(16), jnp.float32)},
    }
    named, _ = named_flatten(params)
    comp = DGCCompressor(ratio, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0, **comp_kw)
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    return params, comp, dist


# ------------------------------------------------------------------ #
# cost model / decisions                                             #
# ------------------------------------------------------------------ #

@pytest.mark.fast
def test_decision_boundaries_by_fabric():
    """Fast fabric -> dense (the sparse pipeline's fixed compute dwarfs
    a near-free psum); slow fabric -> a sparse wire for the big bucket
    (wire dominates) but still dense for the tiny one (fixed overhead
    never amortizes)."""
    ici = plan_buckets([BIG, TINY], fabric="ici_v5e8", world=8)
    assert ici.regimes == ("dense", "dense")
    assert ici.all_dense and ici.num_gathers == 0

    eth = plan_buckets([BIG, TINY], fabric="32x25GbE", world=32)
    assert eth.regimes[0] != "dense"      # wire win must be taken
    assert eth.regimes[1] == "dense"      # 2k params: psum is ~free
    # the headline 32x25GbE claim: the chosen wire beats dense >= 5x on
    # the dominant bucket by the model
    c0 = eth.bucket_costs[0]
    assert c0["dense"] / c0[eth.regimes[0]] >= 5.0


@pytest.mark.fast
def test_packed_indices_win_when_wire_dominates():
    """With compute coefficients zeroed, only bytes matter: packed
    indices carry fewer bits than int32, so int8_packed must win over
    the PR-7 menu on any finite-bandwidth link — and with the full menu
    the low-bit codecs (4-bit values / Elias-Fano indices) must go
    strictly below int8_packed's byte count."""
    free = CostModel(fixed_ms_per_bucket=0.0, select_ms_per_elem=0.0,
                     quant_ms_per_elem=0.0, pack_ms_per_elem=0.0,
                     apply_ms_per_elem=0.0)
    pr7 = ("dense", "fp32", "int8", "int8_packed")
    plan = plan_buckets([BIG], fabric="32x25GbE", world=32, cost=free,
                        candidates=pr7)
    assert plan.regimes == ("int8_packed",)
    full = plan_buckets([BIG], fabric="32x25GbE", world=32, cost=free)
    assert full.regimes[0] in ("int4_packed", "int8_delta_idx")
    tab = full.bucket_costs[0]
    assert tab[full.regimes[0]] < tab["int8_packed"]


@pytest.mark.fast
def test_tie_breaks_toward_dense():
    """Exact cost tie -> the earlier candidate (dense, the never-lose
    direction). numel = payload * W makes dense and fp32 wire bytes
    equal when compute is free."""
    free = CostModel(fixed_ms_per_bucket=0.0, select_ms_per_elem=0.0,
                     quant_ms_per_elem=0.0, pack_ms_per_elem=0.0,
                     apply_ms_per_elem=0.0)
    g = BucketGeom(numel=8_192, payload=1_024, rows=1, index_bits=32.0)
    plan = plan_buckets([g], fabric="32x25GbE", world=8, cost=free,
                        candidates=("dense", "fp32"))
    tab = plan.bucket_costs[0]
    assert tab["dense"] == pytest.approx(tab["fp32"])
    assert plan.regimes == ("dense",)


@pytest.mark.fast
def test_never_lose_by_model():
    """Because dense is always a candidate, the planned mix can never be
    modeled slower than all-dense — on any fabric."""
    geoms = [BIG, TINY,
             BucketGeom(numel=50_000, payload=50, rows=5, index_bits=12.0)]
    for fab in BUILTIN_FABRICS.values():
        plan = plan_buckets(geoms, fabric=fab)
        pred = plan.predicted_ms()
        assert pred["ratio"] >= 1.0
        assert pred["planned_ms"] <= pred["dense_ms"] * (1 + 1e-12)


@pytest.mark.fast
def test_measured_bucket_ms_overrides_coefficients():
    """A measured per-bucket profile replaces the coefficient compute
    model: an enormous measured cost must push a bucket to dense even on
    the slow fabric."""
    plan = plan_buckets([BIG], fabric="32x25GbE", world=32,
                        bucket_ms=[1e6])
    assert plan.regimes == ("dense",)


@pytest.mark.fast
def test_bucket_ms_from_profile():
    prof = {"dgc": {"buckets": {"b0": {"select": 0.03, "pack": 0.01},
                                "b1": {"select": 0.002}}}}
    assert bucket_ms_from_profile(prof, 2) == [0.04, 0.002]
    assert bucket_ms_from_profile(prof, 3) is None    # count mismatch
    assert bucket_ms_from_profile(None, 2) is None


# ------------------------------------------------------------------ #
# plan identity / replan                                             #
# ------------------------------------------------------------------ #

@pytest.mark.fast
def test_plan_key_equality_and_collectives():
    p1 = Plan(("fp32", "dense"), BUILTIN_FABRICS["32x25GbE"], 8)
    p2 = Plan(("fp32", "dense"), BUILTIN_FABRICS["32x25GbE"], 8)
    p3 = Plan(("int8", "dense"), BUILTIN_FABRICS["32x25GbE"], 8)
    assert p1 == p2 and hash(p1) == hash(p2)
    assert p1 != p3
    # lane counting: fp32 = f32 + plain idx; int8 adds the q lane;
    # int8_packed swaps plain idx for packed words
    assert p1.collectives() == {"all-gather": 2, "all-reduce": 1}
    assert Plan(("int8",), BUILTIN_FABRICS["32x25GbE"], 8).num_gathers == 3
    assert Plan(("int8_packed",), BUILTIN_FABRICS["32x25GbE"],
                8).num_gathers == 3
    assert Plan(("dense",), BUILTIN_FABRICS["32x25GbE"], 8).num_gathers == 0
    with pytest.raises(ValueError):
        Plan(("quantum",), BUILTIN_FABRICS["32x25GbE"], 8)


@pytest.mark.fast
def test_replan_is_stable_on_unchanged_geometry():
    """replan over the same buckets -> identical key (the caller skips
    the engine rebuild, so a no-op warmup step recompiles nothing)."""
    params, comp, dist = _two_bucket_setup()
    _, engine = dist.make_flat(params)
    plan = plan_engine(engine, fabric="32x25GbE")
    again = plan.replan(engine)
    assert again.key() == plan.key()
    # single-candidate plans survive replan with the forced regime
    forced = plan_buckets([], fabric="32x25GbE", world=W,
                          candidates=("int8",))
    refit = forced.replan(engine)
    assert refit.regimes == ("int8",) * len(engine.buckets)


@pytest.mark.fast
def test_replan_tracks_payload_geometry(mesh8):
    """A warm-up ratio change reshapes payloads; replan must re-decide
    from the new geometry, and an unchanged key must cost zero
    recompiles of the lowered exchange."""
    from dgc_tpu.analysis.contracts import RecompileGuard
    from tests.test_flat import _flat_exchange_fn

    params, comp, dist = _two_bucket_setup(ratio=0.05)
    layout, engine = dist.make_flat(params)
    plan = plan_engine(engine, fabric="32x25GbE")

    # a geometry change (tighter ratio -> smaller payload) feeds replan
    _, _, dist2 = _two_bucket_setup(ratio=0.01)
    _, engine2 = dist2.make_flat(params)
    replanned = plan.replan(engine2)
    assert len(replanned.regimes) == len(engine2.buckets)

    # unchanged key -> the caller keeps the compiled exchange: two calls
    # through one jitted fn trace exactly once
    if replanned.key() == plan.key():
        fn = _flat_exchange_fn(dist, engine, mesh8)
        rng = np.random.RandomState(0)
        fg = jnp.asarray(rng.randn(W, layout.total), jnp.float32)
        mem = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
            engine.init_memory())
        with RecompileGuard(fn, expect=1, name="planned-exchange"):
            _, mem = fn(fg, mem, jax.random.PRNGKey(0))
            fn(fg, mem, jax.random.PRNGKey(1))


# ------------------------------------------------------------------ #
# fabric resolution                                                  #
# ------------------------------------------------------------------ #

@pytest.mark.fast
def test_fit_link_model_recovers_synthetic_link():
    alpha, gbps = 0.25, 10.0
    pts = [(b, alpha + b / (gbps * 1e6))
           for b in (1e4, 1e5, 1e6, 5e6)]
    a, g = fit_link_model(pts)
    assert a == pytest.approx(alpha, rel=1e-6)
    assert g == pytest.approx(gbps, rel=1e-6)
    # clamps: a fit that would go negative on alpha floors at 0
    a2, _ = fit_link_model([(1e6, 0.1), (2e6, 0.3), (3e6, 0.5)])
    assert a2 >= 0.0
    with pytest.raises(ValueError):
        fit_link_model([(0, 0.0)])


@pytest.mark.fast
def test_fit_link_model_degenerate_uses_prior():
    """<2 distinct byte sizes: the two-parameter fit is underdetermined.
    With a prior fabric (the autotuner's refit path) alpha pins to the
    prior's intercept and only bandwidth re-solves from the cluster;
    without one, the historical single-point behavior holds."""
    prior = Fabric("autotuned-32x25GbE", 8, gbps=3.125, alpha_ms=0.2)
    # identical-size cluster around a 2 GB/s link: alpha stays pinned,
    # bandwidth comes from the cluster mean with the intercept removed
    t = 0.2 + 1e6 / (2.0 * 1e6)
    a, g = fit_link_model([(1e6, t)] * 5, prior=prior)
    assert a == pytest.approx(0.2)
    assert g == pytest.approx(2.0, rel=1e-6)
    # a measurement faster than the intercept alone cannot produce a
    # physical slope: keep the prior's bandwidth, never invent one
    a2, g2 = fit_link_model([(1e6, 0.1)], prior=prior)
    assert a2 == pytest.approx(0.2)
    assert g2 == pytest.approx(prior.gbps)
    # no prior, one distinct size: alpha 0, bandwidth from the point
    a3, g3 = fit_link_model([(1e6, 0.5)])
    assert a3 == 0.0
    assert g3 == pytest.approx(1e6 / (0.5 * 1e6))
    # two distinct sizes: the full lstsq runs and the prior is ignored
    pts = [(b, 0.25 + b / (10.0 * 1e6)) for b in (1e5, 1e6)]
    a4, g4 = fit_link_model(pts, prior=prior)
    assert a4 == pytest.approx(0.25, rel=1e-5)
    assert g4 == pytest.approx(10.0, rel=1e-5)


@pytest.mark.fast
def test_low_bit_menu_cuts_modeled_wire_15pct():
    """ISSUE 11 acceptance: on the 32x25GbE fabric the widened menu's
    planned modeled wire bytes improve >= 15% over the int8_packed-only
    menu on the repo's ResNet/VGG bucket geometries — via the
    Elias-Fano index stream at warm-up payloads (dense rows, shallow
    deltas) and via int4 values at the final sparse ratio."""
    import math

    def geom(rows, cols, ratio):
        numel = rows * cols
        p = max(1, int(numel * ratio))
        s = max(0, (max(numel // p, 1)).bit_length() - 1)
        delta = (p * s + p + (numel >> s) + 1) / p
        return BucketGeom(numel, p, rows,
                          float(max(1, math.ceil(math.log2(cols)))), delta)

    def modeled_wire(g, regime):
        return {"dense": 0.0, "fp32": g.payload * 8.0,
                "int8": g.payload * 5.0 + 4 * g.rows,
                "int8_packed":
                    g.payload * (1 + g.index_bits / 8) + 4 * g.rows,
                "int4_packed":
                    g.payload * (0.5 + g.index_bits / 8) + 4,
                "int8_delta_idx":
                    g.payload * (1 + g.delta_bits / 8) + 4 * g.rows,
                }[regime]

    old_menu = ("dense", "fp32", "int8", "int8_packed")
    # (bucket geometry, expected winning regime family)
    cases = [
        # VGG-16 fc6 at the wm5 epoch-3 warm-up ratio: payload-dense
        # rows make the per-index delta budget ~log2(U/p)+2 << the
        # positional ceil(log2 cols) width
        (geom(4096, 25088, 0.04), "int8_delta_idx"),
        # VGG-16 conv5 block at the final north-star ratio: the value
        # lane dominates and int4 halves it
        (geom(512, 4608, 0.001), "int4_packed"),
    ]
    for g, want in cases:
        full = plan_buckets([g], fabric="32x25GbE", world=32)
        old = plan_buckets([g], fabric="32x25GbE", world=32,
                           candidates=old_menu)
        assert full.regimes[0] == want, (full.regimes, want)
        wb_full = modeled_wire(g, full.regimes[0])
        wb_old = modeled_wire(g, old.regimes[0])
        assert wb_old > 0
        assert wb_full <= 0.85 * wb_old, (
            f"{want}: {wb_full:.0f} vs {wb_old:.0f} "
            f"({100 * (1 - wb_full / wb_old):.1f}% < 15%)")


@pytest.mark.fast
def test_fabric_json_roundtrip_and_schema_errors(tmp_path):
    path = tmp_path / "fabric.json"
    path.write_text(json.dumps({
        "schema": FABRIC_SCHEMA, "version": FABRIC_VERSION,
        "name": "measured-8w-gloo", "workers": 8,
        "rows": [], "fit": {"alpha_ms": 0.12, "gbps": 3.4},
    }))
    fab = load_fabric(str(path))
    assert fab == Fabric("measured-8w-gloo", 8, 3.4, 0.12, measured=True)
    # resolve_fabric accepts the path directly and via DGC_FABRIC
    assert resolve_fabric(str(path)) == fab

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": "something-else", "version": 1}))
    with pytest.raises(ValueError, match="schema"):
        load_fabric(str(bad))
    old = tmp_path / "old.json"
    old.write_text(json.dumps({"schema": FABRIC_SCHEMA, "version": 999,
                               "fit": {}, "workers": 8}))
    with pytest.raises(ValueError, match="version"):
        load_fabric(str(old))


@pytest.mark.fast
def test_resolve_fabric_fallbacks(tmp_path, monkeypatch):
    # builtin name and Fabric passthrough
    assert resolve_fabric("ici_v5e8") is BUILTIN_FABRICS["ici_v5e8"]
    fab = Fabric("custom", 4, 1.0)
    assert resolve_fabric(fab) is fab
    # env var wins over the builtin default
    path = tmp_path / "fabric.json"
    path.write_text(json.dumps({
        "schema": FABRIC_SCHEMA, "version": FABRIC_VERSION,
        "name": "envfab", "workers": 2, "rows": [],
        "fit": {"alpha_ms": 0.0, "gbps": 1.0}}))
    monkeypatch.setenv("DGC_FABRIC", str(path))
    assert resolve_fabric(None).name == "envfab"
    monkeypatch.delenv("DGC_FABRIC")
    # no env, no runs/fabric.json -> the documented modeled default
    assert (resolve_fabric(None, runs_dir=str(tmp_path / "nope"))
            is BUILTIN_FABRICS["32x25GbE"])
    with pytest.raises(ValueError, match="unknown fabric"):
        resolve_fabric("no-such-fabric")


# ------------------------------------------------------------------ #
# engine integration                                                 #
# ------------------------------------------------------------------ #

@pytest.mark.fast
def test_plan_engine_over_real_buckets():
    """plan_engine reads the engine's bucket geometry: the ICI plan goes
    all-dense (never lose), the Ethernet plan keeps a sparse wire on the
    big bucket, and the engine built from the plan reports matching
    per-bucket wire bytes (0 for dense-planned buckets)."""
    from dgc_tpu.compression.flat import FlatDGCEngine

    # the north-star 0.1% ratio: a 5% payload would (correctly) lose to
    # dense even on 25GbE at W=32 — the planner is ratio-aware
    params, comp, dist = _two_bucket_setup(ratio=0.001)
    layout, engine = dist.make_flat(params)
    assert len(engine.buckets) == 2

    ici = plan_engine(engine, fabric="ici_v5e8")
    assert ici.all_dense

    eth = plan_engine(engine, fabric="32x25GbE", world=32)
    assert eth.regimes[0] != "dense" and eth.regimes[1] == "dense"

    planned = FlatDGCEngine(comp, layout, plan=eth)
    per_bucket = planned.bucket_wire_bytes()
    assert per_bucket[1] == 0                      # dense rides the psum
    assert per_bucket[0] > 0
    # per-bucket byte-ceil vs the engine's single word-pad of the shared
    # packed stream: sub-word rounding slack either way (see
    # bucket_wire_bytes) — bounded by the packed-bucket count below and
    # the 4-byte word above. int8_delta_idx / int4_packed account
    # per-bucket word-exactly, so their slack is exactly 0.
    n_packed = sum(1 for r in planned.regimes if r.endswith("_packed"))
    slack = planned.wire_bytes_per_worker() - sum(per_bucket)
    assert -n_packed <= slack < 4
    assert planned.plan.key() == eth.key()

    # all-packed plan: both buckets byte-ceil their bit widths, so the
    # per-bucket sum may OVERSHOOT the word-padded stream (negative
    # slack) — the case a dense-planned bucket can't exercise
    allp = Plan(("int8_packed", "int8_packed"), eth.fabric, eth.world)
    packed_eng = FlatDGCEngine(comp, layout, plan=allp)
    pb = packed_eng.bucket_wire_bytes()
    assert all(w > 0 for w in pb)
    slack2 = packed_eng.wire_bytes_per_worker() - sum(pb)
    assert -2 < slack2 < 4


@pytest.mark.fast
def test_fused_select_pack_bitwise_parity():
    """The fused Pallas threshold->select->pack pass is plan-compatible:
    an engine with fused_select=True must produce the exact sparsify
    wire (values AND indices) of the unfused engine."""
    params, _, _ = _two_bucket_setup()
    named, _ = named_flatten(params)

    def build(fused):
        comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, fused_select=fused)
        comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        return dist.make_flat(params)

    layout_f, eng_fused = build(True)
    layout_u, eng_plain = build(False)
    assert any(eng_fused._use_fused_select(b) for b in eng_fused.buckets)

    rng = np.random.RandomState(7)
    vec = np.zeros((layout_f.t_compressed,), np.float32)
    vec[:layout_f.t_data] = rng.randn(layout_f.t_data)
    vec = jnp.asarray(vec)
    v_f, i_f = jax.jit(eng_fused.sparsify)(vec, jax.random.PRNGKey(0))
    v_u, i_u = jax.jit(eng_plain.sparsify)(vec, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(v_f), np.asarray(v_u))
    np.testing.assert_array_equal(np.asarray(i_f), np.asarray(i_u))
