"""Resilience layer (docs/RESILIENCE.md): in-graph step guards, exchange
integrity, fault injection, preemption handling, and checkpoint fallback.

Every guard is asserted against the injector that triggers it
(``DGC_FAULTS``) — behavior, not hope. Faults parse at trace time, so
tests arm the env var (monkeypatch) BEFORE the first step call.
"""

import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.resilience import GuardConfig, faults, guard, integrity, preempt


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def _updating_state(s):
    return (s.params, s.opt_state, s.memory, s.batch_stats)


# ---------------------------------------------------------------------- #
# fault plan parsing                                                     #
# ---------------------------------------------------------------------- #

def test_fault_plan_grammar():
    p = faults.plan("nan@2, bitflip:elem=3:bit=7, kill@5, init_fail@2, "
                    "badidx:elem=1:set=-4")
    assert p.nan_step == 2 and p.kill_step == 5 and p.init_failures == 2
    assert p.bitflip == {"elem": 3, "bit": 7}
    assert p.badidx == {"elem": 1, "set": -4}
    assert faults.plan("") == faults.FaultPlan()
    with pytest.raises(ValueError, match="unknown fault token"):
        faults.plan("tyop@3")


def test_armed_tracks_env(monkeypatch):
    monkeypatch.delenv(faults.ENV, raising=False)
    assert not faults.armed()
    monkeypatch.setenv(faults.ENV, "nan@0")
    assert faults.armed()


# ---------------------------------------------------------------------- #
# guard matrix: nonfinite skip, spike breaker                            #
# ---------------------------------------------------------------------- #

def test_nan_guard_skips_exactly_one_update(mesh8, monkeypatch):
    """NaN gradients at step 1 must skip that update ATOMICALLY — params,
    optimizer state, compressor memory, and BN stats all bitwise-unchanged
    — while the step counter advances and training resumes next step."""
    monkeypatch.setenv(faults.ENV, "nan@1")
    from dgc_tpu.analysis.suite import build_fixture
    state, step, _, (im, lb, key) = build_fixture(
        mesh8, donate=False, guards=GuardConfig())

    state1, m1 = step(state, im, lb, key)          # step 0: clean
    assert float(m1["guards"]["skipped_steps"]) == 0.0
    pre = jax.device_get(_updating_state(state1))

    state2, m2 = step(state1, im, lb, key)         # step 1: poisoned
    post = jax.device_get(_updating_state(state2))
    assert _tree_equal(pre, post), "skip must revert the update bitwise"
    assert int(state2.step) == 2, "the step counter still advances"
    assert float(m2["guards"]["skipped_steps"]) == 1.0
    assert float(m2["guards"]["nonfinite_rate"]) == pytest.approx(0.5)

    state3, m3 = step(state2, im, lb, key)         # step 2: clean again
    assert not _tree_equal(jax.device_get(state2.params),
                           jax.device_get(state3.params))
    assert float(m3["guards"]["skipped_steps"]) == 1.0
    assert float(m3["guards"]["nonfinite_rate"]) == pytest.approx(1 / 3)
    assert np.isfinite(np.asarray(jax.device_get(state3.params)).sum())


def test_guards_off_step_has_no_guard_metrics(mesh8, monkeypatch):
    monkeypatch.delenv(faults.ENV, raising=False)
    from dgc_tpu.analysis.suite import build_fixture
    state, step, _, (im, lb, key) = build_fixture(mesh8, donate=False)
    _, m = step(state, im, lb, key)
    assert "guards" not in m
    assert state.guards is None


def test_spike_breaker_window_semantics():
    """The circuit breaker arms only once the window is full, trips on
    loss > factor x window-mean, and spiked losses still enter the window
    (a persistent level shift re-arms the baseline instead of skipping
    forever). Nonfinite losses never pollute the window."""
    cfg = GuardConfig(nonfinite=False, spike_window=2, spike_factor=2.0)
    gs = guard.init_state(cfg)
    zero = jnp.zeros(())

    def run(losses):
        nonlocal gs
        skips = []
        for v in losses:
            skip, gs, _ = guard.apply(cfg, gs, bad_count=zero,
                                      mean_loss=jnp.asarray(float(v)))
            skips.append(bool(skip))
        return skips

    # warm-up (not armed), then a 10x spike trips, then recovery passes
    assert run([1.0, 1.0, 10.0, 1.0]) == [False, False, True, False]
    # the spike pushed into the window: mean is now (10+1)/2, so a
    # persistent level shift to ~5 no longer trips once absorbed
    assert run([5.0]) == [False]
    # nonfinite loss: no skip from the breaker (nonfinite=False here) and
    # no window pollution
    before = np.asarray(gs["loss_window"]).copy()
    assert run([float("nan")]) == [False]
    np.testing.assert_array_equal(np.asarray(gs["loss_window"]), before)


def test_nonfinite_guard_counts_bad_workers():
    cfg = GuardConfig(nonfinite=True)
    gs = guard.init_state(cfg)
    skip, gs, m = guard.apply(cfg, gs, bad_count=jnp.asarray(1.0),
                              mean_loss=jnp.asarray(1.0))
    assert bool(skip) and float(m["skipped_steps"]) == 1.0
    skip, gs, m = guard.apply(cfg, gs, bad_count=jnp.asarray(0.0),
                              mean_loss=jnp.asarray(1.0))
    assert not bool(skip) and float(m["skipped_steps"]) == 1.0


def test_guard_config_validation():
    with pytest.raises(ValueError):
        GuardConfig(spike_window=-1)
    with pytest.raises(ValueError):
        GuardConfig(spike_window=4, spike_factor=1.0)


# ---------------------------------------------------------------------- #
# exchange integrity: index clamp + payload checksum                     #
# ---------------------------------------------------------------------- #

def test_scatter_add_wraps_negative_indices():
    """The hazard the clamp exists for: JAX scatter-add DROPS indices >= T
    but WRAPS negative ones — a corrupt negative index silently writes
    into a live parameter slot."""
    acc = jnp.zeros((4,), jnp.float32).at[jnp.asarray([-1])].add(
        jnp.asarray([1.0]))
    assert float(acc[3]) == 1.0          # wrote param slot 3, silently
    acc = jnp.zeros((4,), jnp.float32).at[jnp.asarray([99])].add(
        jnp.asarray([1.0]))
    assert float(np.asarray(acc).sum()) == 0.0   # >=T at least drops


def test_clamp_indices_matches_numpy_oracle():
    total, sentinel = 100, 7
    idx = jnp.asarray([-3, 0, 5, 99, 100, 10**6, -1], jnp.int32)
    got = np.asarray(integrity.clamp_indices(idx, total, sentinel))
    arr = np.asarray(idx)
    want = np.where((arr >= 0) & (arr < total), arr, sentinel)
    np.testing.assert_array_equal(got, want)


def test_clamp_indices_per_slot_bounds():
    # codec layout: 4 payload slots, two owning rows [0,4) and [4,10);
    # bounds arrays are per PAYLOAD slot, broadcast over the last axis
    slot_off = np.asarray([0, 0, 4, 4], np.int32)
    slot_numel = np.asarray([4, 4, 6, 6], np.int32)
    idx = jnp.asarray([3, 5, 5, 12], jnp.int32)
    got = np.asarray(integrity.clamp_indices(idx, 10, 0,
                                             slot_off, slot_numel))
    # 3 in [0,4) ok; 5 escapes row 0 -> sentinel; 5 in [4,10) ok;
    # 12 past row 1 -> sentinel
    np.testing.assert_array_equal(got, [3, 0, 5, 0])


def test_payload_checksum_roundtrip_and_detection():
    rng = np.random.RandomState(0)
    nb, per = 3, 8
    seg = np.repeat(np.arange(nb, dtype=np.int32), per)
    vals = jnp.asarray(rng.randn(nb * per).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, 1000, nb * per).astype(np.int32))
    chk = integrity.payload_checksum(vals, idx, seg, nb)
    # symmetric recompute: zero mismatches on an intact payload
    g_vals, g_idx = vals[None], idx[None]
    g_chk = chk[None]
    assert float(integrity.count_mismatches(
        g_vals, g_idx, g_chk, seg, nb)) == 0.0
    # one flipped mantissa bit in one value -> exactly one bucket flags
    bad = np.asarray(vals).copy()
    bad[5] = np.frombuffer(
        (np.asarray(bad[5]).view(np.int32) ^ (1 << 18)).tobytes(),
        np.float32)[0]
    assert float(integrity.count_mismatches(
        jnp.asarray(bad)[None], g_idx, g_chk, seg, nb)) == 1.0
    # a corrupted index flags too (the checksum covers both words)
    bad_idx = np.asarray(idx).copy()
    bad_idx[9] += 1
    assert float(integrity.count_mismatches(
        g_vals, jnp.asarray(bad_idx)[None], g_chk, seg, nb)) == 1.0


def test_checksum_refused_with_int8_values():
    from dgc_tpu.analysis.suite import build_fixture
    with pytest.raises(ValueError, match="int8"):
        build_fixture(None, donate=False, guards=GuardConfig(),
                      compressor_kwargs={"checksum": True,
                                         "int8_values": True})


def test_checksum_requires_guards(mesh8):
    from dgc_tpu.analysis.suite import build_fixture
    with pytest.raises(ValueError, match="guards"):
        build_fixture(mesh8, donate=False,
                      compressor_kwargs={"checksum": True})


def test_checksum_counts_injected_bitflip(mesh8, monkeypatch):
    monkeypatch.setenv(faults.ENV, "bitflip:elem=0:bit=18")
    from dgc_tpu.analysis.suite import build_fixture
    state, step, _, (im, lb, key) = build_fixture(
        mesh8, donate=False, guards=GuardConfig(),
        compressor_kwargs={"checksum": True})
    state, m = step(state, im, lb, key)
    assert float(m["guards"]["checksum_failures"]) >= 1.0
    state, m = step(state, im, lb, key)    # cumulative counter
    assert float(m["guards"]["checksum_failures"]) >= 2.0


def test_checksum_clean_run_counts_zero(mesh8, monkeypatch):
    monkeypatch.delenv(faults.ENV, raising=False)
    from dgc_tpu.analysis.suite import build_fixture
    state, step, _, (im, lb, key) = build_fixture(
        mesh8, donate=False, guards=GuardConfig(),
        compressor_kwargs={"checksum": True})
    for i in range(2):
        state, m = step(state, im, lb, jax.random.fold_in(key, i))
        assert float(m["guards"]["checksum_failures"]) == 0.0
    assert np.isfinite(float(m["loss"]))


def test_bad_index_clamped_not_crashing(mesh8, monkeypatch):
    """A corrupt (negative) gathered index routes to the structural-zero
    sentinel instead of wrapping into a live parameter slot: training
    stays finite and the checksum reports the corruption."""
    monkeypatch.setenv(faults.ENV, "badidx:elem=0:set=-5")
    from dgc_tpu.analysis.suite import build_fixture
    state, step, _, (im, lb, key) = build_fixture(
        mesh8, donate=False, guards=GuardConfig(),
        compressor_kwargs={"checksum": True})
    for i in range(2):
        state, m = step(state, im, lb, jax.random.fold_in(key, i))
    assert np.isfinite(np.asarray(jax.device_get(state.params)).sum())
    assert float(m["guards"]["checksum_failures"]) >= 1.0


# ---------------------------------------------------------------------- #
# checkpoint: atomic publish + corrupt-latest fallback                   #
# ---------------------------------------------------------------------- #

def _ckpt_state(value: float):
    from dgc_tpu.training import TrainState
    return TrainState(
        step=jnp.asarray(int(value), jnp.int32),
        params={"w": jnp.full((4,), value)},
        opt_state=(jnp.zeros(()),),
        memory={"momentums": {"a/b": jnp.full((3,), value)}},
        batch_stats={})


def test_atomic_save_leaves_no_tmp(tmp_path):
    from dgc_tpu.training.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), keep=3)
    # a stale staging dir from a crashed run must not block the save
    os.makedirs(tmp_path / "e0.tmp")
    mgr.save(0, _ckpt_state(1.0), {"m": 1.0})
    assert not (tmp_path / "e0.tmp").exists()
    # meters.json published atomically WITH the state
    assert (tmp_path / "e0" / "meters.json").exists()


def test_restore_falls_back_past_corrupt_latest(tmp_path, capsys):
    from dgc_tpu.training.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(0, _ckpt_state(1.0), {"m": 0.5})
    mgr.save(1, _ckpt_state(2.0), {"m": 1.5})
    # corrupt the newest checkpoint: keep the dir, gut the array data
    for name in os.listdir(tmp_path / "e1"):
        if name != "meters.json":
            p = tmp_path / "e1" / name
            if p.is_dir():
                import shutil
                shutil.rmtree(p)
            else:
                p.unlink()
    out = mgr.restore(_ckpt_state(0.0))
    assert out is not None, "must fall back to the previous kept epoch"
    state, epoch, meters = out
    assert epoch == 0 and meters["m"] == 0.5
    np.testing.assert_allclose(np.asarray(state.params["w"]), 1.0)
    assert "falling back" in capsys.readouterr().out


def test_restore_falls_back_when_latest_dir_deleted(tmp_path):
    import shutil
    from dgc_tpu.training.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(0, _ckpt_state(1.0), {})
    mgr.save(1, _ckpt_state(2.0), {})
    shutil.rmtree(tmp_path / "e1")      # latest.json still points at e1
    out = mgr.restore(_ckpt_state(0.0))
    assert out is not None
    assert out[1] == 0


def test_restore_survives_corrupt_latest_pointer(tmp_path):
    from dgc_tpu.training.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(0, _ckpt_state(3.0), {})
    with open(tmp_path / "latest.json", "w") as f:
        f.write("{torn wr")           # crash mid-write
    assert mgr.latest_epoch() is None
    out = mgr.restore(_ckpt_state(0.0))
    assert out is not None and out[1] == 0
    np.testing.assert_allclose(np.asarray(out[0].params["w"]), 3.0)


def test_restore_resets_guards_for_pre_resilience_checkpoint(tmp_path,
                                                            capsys):
    from dgc_tpu.training.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(0, _ckpt_state(1.0), {})          # saved WITHOUT guard state
    template = _ckpt_state(0.0).replace(
        guards=guard.init_state(GuardConfig()))
    out = mgr.restore(template)
    assert out is not None, "old checkpoints must restore under guards"
    assert out[0].guards is None               # caller re-seeds fresh
    np.testing.assert_allclose(np.asarray(out[0].params["w"]), 1.0)
    assert "guard" in capsys.readouterr().out


# ---------------------------------------------------------------------- #
# multihost: partial-triple fail-fast + bounded init retry               #
# ---------------------------------------------------------------------- #

def _clear_multihost_env(monkeypatch):
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID", "SLURM_NTASKS", "SLURM_PROCID",
              "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(k, raising=False)


def test_partial_env_triple_fails_fast(monkeypatch):
    from dgc_tpu.parallel.multihost import initialize_multihost
    _clear_multihost_env(monkeypatch)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1234")
    with pytest.raises(RuntimeError, match="JAX_NUM_PROCESSES"):
        initialize_multihost()
    # num/id without a coordinator would silently come up single-process
    _clear_multihost_env(monkeypatch)
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    with pytest.raises(RuntimeError, match="JAX_COORDINATOR_ADDRESS"):
        initialize_multihost()


def test_full_triple_passes_failfast_and_single_host_skips(monkeypatch):
    from dgc_tpu.parallel.multihost import initialize_multihost
    _clear_multihost_env(monkeypatch)
    assert initialize_multihost() is False     # nothing set: single host


def test_init_retry_recovers_from_transient_failures(monkeypatch):
    from dgc_tpu.parallel import multihost
    _clear_multihost_env(monkeypatch)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    monkeypatch.setenv(faults.ENV, "init_fail@2")   # first 2 attempts die
    calls = []

    def stub(coordinator_address=None, num_processes=None, process_id=None,
             **kw):
        calls.append((coordinator_address, num_processes, process_id))

    monkeypatch.setattr(jax.distributed, "initialize", stub)
    assert multihost.initialize_multihost(
        init_retries=3, init_backoff=0.0) is True
    assert calls == [("127.0.0.1:1234", 1, 0)]      # 3rd attempt landed


def test_init_retry_exhaustion_raises(monkeypatch):
    from dgc_tpu.parallel import multihost
    _clear_multihost_env(monkeypatch)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    monkeypatch.setenv(faults.ENV, "init_fail@9")
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    with pytest.raises(RuntimeError, match="injected init failure"):
        multihost.initialize_multihost(init_retries=2, init_backoff=0.0)
    assert calls == []


# ---------------------------------------------------------------------- #
# preemption handler + watchdog (host-side)                              #
# ---------------------------------------------------------------------- #

def test_preemption_handler_sets_flag_and_restores():
    prev = signal.getsignal(signal.SIGUSR1)
    h = preempt.PreemptionHandler(signals=(signal.SIGUSR1,))
    assert not h.requested
    os.kill(os.getpid(), signal.SIGUSR1)
    assert h.requested and h.signum == signal.SIGUSR1
    h.uninstall()
    assert signal.getsignal(signal.SIGUSR1) is prev


def test_agree_preempt_single_process_short_circuits():
    assert preempt.agree_preempt(True) is True
    assert preempt.agree_preempt(False) is False


def test_watchdog_detects_stall_and_flushes(tmp_path):
    events = []

    class FakeSink:
        def flush(self):
            events.append("flush")

    with open(tmp_path / "wd.log", "w") as fh:
        wd = preempt.Watchdog(0.3, sink=FakeSink(),
                              on_stall=lambda: events.append("stall"),
                              interval=0.05, stream=fh)
        time.sleep(1.0)              # no beats: at least one stall fires
        wd.stop()
    assert wd.stalls >= 1
    assert "stall" in events and "flush" in events
    with open(tmp_path / "wd.log") as fh:
        assert "no step progress" in fh.read()


def test_watchdog_quiet_while_beating(tmp_path):
    with open(tmp_path / "wd.log", "w") as fh:
        wd = preempt.Watchdog(0.5, interval=0.05, stream=fh)
        for _ in range(10):
            wd.beat()
            time.sleep(0.05)
        wd.stop()
    assert wd.stalls == 0


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        preempt.Watchdog(0.0)


# ---------------------------------------------------------------------- #
# fast end-to-end smoke (scripts/t1.sh RESILIENCE_SMOKE)                 #
# ---------------------------------------------------------------------- #

@pytest.mark.fast
def test_resilience_smoke_guarded_faulted_run(mesh8, monkeypatch,
                                              tmp_path):
    """One guarded+checksummed fixture under simultaneous NaN and bit-flip
    injection: the NaN step skips atomically, the checksum counts every
    corrupted exchange, training stays finite throughout, and an
    emergency-style save/restore resumes with the guard counters (and the
    rest of the state) bitwise intact."""
    monkeypatch.setenv(faults.ENV, "nan@2,bitflip:elem=0:bit=18")
    from dgc_tpu.analysis.suite import build_fixture
    from dgc_tpu.training.checkpoint import CheckpointManager
    state, step, _, (im, lb, key) = build_fixture(
        mesh8, donate=False, guards=GuardConfig(),
        compressor_kwargs={"checksum": True})
    m = None
    for i in range(4):
        state, m = step(state, im, lb, jax.random.fold_in(key, i))
    g = {k: float(v) for k, v in m["guards"].items()}
    assert g["skipped_steps"] == 1.0           # exactly the nan@2 step
    assert g["checksum_failures"] >= 4.0       # every exchange corrupted
    assert g["nonfinite_rate"] == pytest.approx(0.25)
    assert np.isfinite(float(m["loss"]))
    assert np.isfinite(np.asarray(jax.device_get(state.params)).sum())

    # emergency checkpoint + resume: the batch cursor round-trips and the
    # restored state (guard counters included) is bitwise the saved one
    ckpt = CheckpointManager(str(tmp_path), keep=2)
    ckpt.save(0, state, {"preempt_batch": 3})
    out = ckpt.restore(state)
    assert out is not None and int(out[2]["preempt_batch"]) == 3
    r_state = out[0]
    assert _tree_equal(jax.device_get((state.params, state.memory,
                                       state.guards)),
                       jax.device_get((r_state.params, r_state.memory,
                                       r_state.guards)))
    r_state, m = step(r_state, im, lb, jax.random.fold_in(key, 4))
    assert np.isfinite(float(m["loss"]))
    assert float(m["guards"]["skipped_steps"]) == 1.0
