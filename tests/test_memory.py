"""Momentum-correction memory contract (SURVEY.md §2.3-2.4,
reference memory.py:50-77)."""

import jax.numpy as jnp
import numpy as np

from dgc_tpu.compression import DGCSGDMemory, Memory


def _init(mem, shapes):
    return mem.init([(n, np.zeros(s, np.float32)) for n, s in shapes.items()])


def test_noop_memory_is_identity():
    mem = Memory()
    state = mem.init([("w", np.zeros(4))])
    g = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    out, state2 = mem.compensate(state, "w", g)
    assert np.allclose(out, g)
    assert mem.update(state2, "w", None, None) == state2


def test_momentum_correction_recurrence():
    m = 0.9
    mem = DGCSGDMemory(momentum=m)
    state = _init(mem, {"w": (3,)})
    g1 = jnp.asarray([1.0, 2.0, 3.0])
    g2 = jnp.asarray([0.5, 0.5, 0.5])

    out1, state = mem.compensate(state, "w", g1)
    # mmt1 = g1; vec1 = g1
    assert np.allclose(out1, g1)
    out2, state = mem.compensate(state, "w", g2)
    # mmt2 = 0.9*g1 + g2 ; vec2 = vec1 + mmt2
    mmt2 = m * np.asarray(g1) + np.asarray(g2)
    assert np.allclose(out2, np.asarray(g1) + mmt2)


def test_nesterov_variant():
    m = 0.9
    mem = DGCSGDMemory(momentum=m, nesterov=True)
    state = _init(mem, {"w": (2,)})
    g = jnp.asarray([1.0, -1.0])
    out, state = mem.compensate(state, "w", g)
    # mmt = (0 + g)*m ; vec = 0 + mmt + g
    assert np.allclose(out, m * np.asarray(g) + np.asarray(g))


def test_non_accumulate_dense_path():
    m = 0.9
    mem = DGCSGDMemory(momentum=m)
    state = _init(mem, {"b": (2,)})
    g = jnp.asarray([2.0, 4.0])
    out, state = mem.compensate(state, "b", g, accumulate=False)
    assert np.allclose(out, g)  # mmt = 0*m + g
    # velocities untouched on the dense path
    assert np.allclose(state["velocities"]["b"], 0.0)
    out2, state = mem.compensate(state, "b", g, accumulate=False)
    assert np.allclose(out2, m * np.asarray(g) + np.asarray(g))


def test_update_masks_transmitted_coordinates():
    mem = DGCSGDMemory(momentum=0.9, momentum_masking=True)
    state = _init(mem, {"w": (6,)})
    g = jnp.arange(1.0, 7.0)
    _, state = mem.compensate(state, "w", g)
    idx = jnp.asarray([1, 4, 0], jnp.int32)
    valid = jnp.asarray([True, True, False])  # padded slot points at 0
    state = mem.update(state, "w", idx, valid)
    vel = np.asarray(state["velocities"]["w"])
    mmt = np.asarray(state["momentums"]["w"])
    assert vel[1] == 0 and vel[4] == 0
    assert mmt[1] == 0 and mmt[4] == 0
    # coordinate 0 was only referenced by a padded slot: must survive
    assert vel[0] == 1.0 and mmt[0] == 1.0
    assert vel[2] == 3.0 and vel[3] == 4.0 and vel[5] == 6.0


def test_momentum_masking_toggle():
    mem = DGCSGDMemory(momentum=0.9, momentum_masking=False)
    state = _init(mem, {"w": (4,)})
    _, state = mem.compensate(state, "w", jnp.ones(4))
    state = mem.update(state, "w", jnp.asarray([2], jnp.int32),
                       jnp.asarray([True]))
    assert np.asarray(state["velocities"]["w"])[2] == 0
    assert np.asarray(state["momentums"]["w"])[2] == 1.0  # mm off: kept


def test_gradient_clipping_hook():
    calls = []

    def clip(g):
        calls.append(1)
        return g * 0.5

    mem = DGCSGDMemory(momentum=0.0, gradient_clipping=clip)
    state = _init(mem, {"w": (2,)})
    out, _ = mem.compensate(state, "w", jnp.asarray([2.0, 2.0]))
    assert calls and np.allclose(out, [1.0, 1.0])


def test_state_dict_roundtrip():
    mem = DGCSGDMemory(momentum=0.9)
    state = _init(mem, {"w": (3,), "b": (2,)})
    _, state = mem.compensate(state, "w", jnp.ones(3))
    saved = mem.state_dict(state)
    fresh = _init(mem, {"w": (3,), "b": (2,)})
    restored = mem.load_state_dict(fresh, saved)
    assert np.allclose(restored["momentums"]["w"], 1.0)
    assert np.allclose(restored["velocities"]["w"], 1.0)
