"""Engine construction at ImageNet scale: geometry, buckets, payload, and a
single exchange for ResNet-18/50 and VGG-16-BN shapes (the BASELINE.json
config rows beyond CIFAR). Host-side-heavy, device ops on the 1-device CPU
mesh — catches bucket/padding/overflow issues at real parameter counts
without a TPU pod."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dgc_tpu import DGCCompressor, DGCSGDMemory, DistributedOptimizer, dgc_sgd
from dgc_tpu.parallel import make_mesh
from dgc_tpu.utils.pytree import named_flatten
from dgc_tpu.utils.compat import shard_map


def _build(model_fn, num_classes=1000, ratio=0.001, image_size=32):
    model = model_fn(num_classes=num_classes)
    v = model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, image_size, image_size, 3)), train=True)
    named, _ = named_flatten(v["params"])
    comp = DGCCompressor(ratio, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize((n, p) for n, p in named.items() if p.ndim > 1)
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=1)
    layout, engine = dist.make_flat(v["params"])
    return comp, dist, layout, engine


@pytest.mark.parametrize("name", ["resnet18", "resnet50", "vgg16_bn"])
def test_engine_builds_at_imagenet_scale(name):
    from dgc_tpu import models as M
    # VGG's classifier head needs the real 224 spatial extent
    comp, dist, layout, engine = _build(
        getattr(M, name), image_size=224 if name == "vgg16_bn" else 32)
    # wire volume within the padded-payload gate's documented bound: the
    # round-5 identity-tight fast path (flat._PAD_PAYLOAD_MAX_FRAC) may
    # inflate the payload by <= 2% over the reference's sum of per-tensor
    # num_selects, never shrink it
    ref_wire = sum(a.num_selects for a in comp.attributes.values())
    assert ref_wire <= engine.payload_size <= 1.02 * ref_wire
    # every compressed tensor is in one bucket row, except giant tensors
    # (> _SPLIT_COLS) which split into segment rows with the SAME total
    # quota (stratified selection; wire volume asserted above)
    from dgc_tpu.compression.flat import _SPLIT_COLS
    from dgc_tpu.ops.kernels import ladder_cols
    split_tensors = sum(
        1 for a in comp.attributes.values()
        if ladder_cols(a.numel) > _SPLIT_COLS and a.num_selects >= 2)
    rows = sum(b.rows for b in engine.buckets)
    assert rows >= len(comp.attributes)
    if split_tensors == 0:
        assert rows == len(comp.attributes)
    else:
        assert rows > len(comp.attributes)
        # split buckets (more rows than layout names): segment quotas sum
        # EXACTLY to the tensor's num_selects and segment numels cover the
        # tensor exactly — the quota/coverage invariant of _segment_rows
        lay_by_base = {g.base: g for g in layout.buckets}
        found_split = 0
        for b in engine.buckets:
            g = lay_by_base[b.base]
            if b.rows == len(g.names):
                continue
            [tname] = g.names
            a = comp.attributes[tname]
            found_split += 1
            assert int(b.num_selects.sum()) == a.num_selects, tname
            assert int(b.numels.sum()) == a.numel, tname
            assert (b.num_selects >= 1).all()
        assert found_split == split_tensors
    # bucket padding bounded by the build factor (split buckets: per-row
    # width is the segment width, numels fill it except the last row)
    for b in engine.buckets:
        real = b.numels[:b.rows]
        assert b.cols < 2 * max(int(real.max()), 128) + 128 * 1024
    # ~0.1% of params on the wire
    assert engine.payload_size < 0.002 * layout.num_params
    assert layout.num_params > 10_000_000  # genuinely ImageNet scale


def test_resnet50_exchange_one_step():
    """One full exchange at 25M params on the 1-device mesh: compiles, runs,
    produces finite output of the right shape, and the error-feedback
    invariant holds (untransmitted coordinates accumulate)."""
    from dgc_tpu.models import resnet50
    comp, dist, layout, engine = _build(resnet50)
    mesh = make_mesh(1)
    g = jnp.asarray(
        np.random.RandomState(0).randn(layout.total).astype(np.float32))
    mem = engine.init_memory()

    def worker(fg, m, key):
        out, m = engine.exchange(fg, m, key, "data", 1)
        return out, m

    f = jax.jit(shard_map(
        worker, mesh=mesh, in_specs=(P(), P(), P()), out_specs=(P(), P()),
        check_vma=False))
    out, mem = f(g, mem, jax.random.PRNGKey(0))
    out = np.asarray(out)
    assert out.shape == (layout.total,)
    assert np.isfinite(out).all()
    # at 0.1% ratio the exchanged compressed block is sparse
    nz = np.count_nonzero(out[:layout.t_data])
    assert 0 < nz <= 2 * engine.payload_size
    # residual accumulated for untransmitted coords
    assert np.abs(np.asarray(mem["velocities_c"])[:layout.t_data]).sum() > 0


def test_approx_recall_knob():
    """approx_recall defaults to 0.90 (measured recall 0.966-0.975 at the
    ResNet-50 buckets, -0.62 ms/step paired vs 0.95 — flat._select_topk)
    and None forces exact top-k — on CPU approx_max_k lowers to exact, so
    both settings must select identically (the gate itself only changes
    the op choice at num_selects > 128)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dgc_tpu import DGCCompressor, DGCSGDMemory, DistributedOptimizer, dgc_sgd

    assert DGCCompressor(0.01).approx_recall == 0.90
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(600, 600), jnp.float32)}

    def run(recall):
        comp = DGCCompressor(0.5, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, approx_recall=recall)
        comp.initialize([("w", params["w"])])
        assert comp.attributes["w"].num_selects > 128  # approx gate engages
        dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=1)
        _, engine = dist.make_flat(params)
        vec = jnp.zeros((engine.layout.t_compressed,), jnp.float32)
        vec = vec.at[:360000].set(jnp.asarray(rng.randn(360000), jnp.float32))
        return jax.jit(engine.sparsify)(vec, jax.random.PRNGKey(0))

    rng = np.random.RandomState(0)
    va, ia = run(0.95)
    rng = np.random.RandomState(0)
    ve, ie = run(None)
    np.testing.assert_array_equal(np.asarray(ia), np.asarray(ie))
    np.testing.assert_array_equal(np.asarray(va), np.asarray(ve))
