"""Adasum delta-optimizer (C5 parity), the torch DLPack bridge, and the
profiling/multihost helpers."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from dgc_tpu.utils.compat import shard_map

from dgc_tpu import (
    Compression,
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    dgc_sgd,
    sgd,
)
from dgc_tpu.optim.adasum import (
    AdasumDistributedOptimizer,
    adasum_pair,
    adasum_reduce,
)

W = 8


def test_adasum_pair_identities():
    a = jnp.asarray(np.random.RandomState(0).randn(64), jnp.float32)
    # identical vectors: adasum(a, a) == a (scale invariance)
    np.testing.assert_allclose(np.asarray(adasum_pair(a, a)),
                               np.asarray(a), rtol=1e-6)
    # orthogonal vectors add
    b = jnp.zeros((64,)).at[0].set(3.0)
    c = jnp.zeros((64,)).at[1].set(4.0)
    np.testing.assert_allclose(np.asarray(adasum_pair(b, c)),
                               np.asarray(b + c), rtol=1e-6)
    # zero operand: identity
    np.testing.assert_allclose(np.asarray(adasum_pair(a, jnp.zeros((64,)))),
                               np.asarray(a), rtol=1e-6)


def test_adasum_reduce_identical_and_orthogonal():
    a = jnp.asarray(np.random.RandomState(1).randn(32), jnp.float32)
    stacked = jnp.broadcast_to(a[None], (W,) + a.shape)
    np.testing.assert_allclose(np.asarray(adasum_reduce(stacked)),
                               np.asarray(a), rtol=1e-5)
    # pairwise-disjoint supports: full sum survives
    rows = jnp.zeros((W, W)).at[jnp.arange(W), jnp.arange(W)].set(1.0)
    np.testing.assert_allclose(np.asarray(adasum_reduce(rows)),
                               np.ones((W,)), rtol=1e-5)


def test_adasum_distributed_optimizer_flat(mesh8):
    """All workers with identical grads: the reduced delta equals the local
    delta (not x W, not / W) — the Adasum fixed point."""
    params = {"w": jnp.asarray(np.random.RandomState(2).randn(16, 16),
                               jnp.float32),
              "b": jnp.zeros((16,), jnp.float32)}
    comp = Compression.none()
    dist = AdasumDistributedOptimizer(sgd(0.1), comp, world_size=W)
    layout, engine = dist.make_flat(params)
    flat_p = layout.flatten(params)
    opt_state = dist.init(flat_p)
    g = jnp.asarray(np.random.RandomState(3).randn(layout.total),
                    jnp.float32)

    def worker(fg, fp, key):
        upd, _, _ = dist.update_flat(fg[0], opt_state, fp, {}, key, engine)
        return upd[None]

    f = jax.jit(shard_map(
        worker, mesh=mesh8, in_specs=(P("data"), P(), P()),
        out_specs=P("data"), check_vma=False))
    upd = f(jnp.broadcast_to(g[None], (W,) + g.shape), flat_p,
            jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(upd[0]), np.asarray(-0.1 * g),
                               rtol=1e-4, atol=1e-6)


def test_adasum_per_tensor_dense_matches_reduce_oracle(mesh8):
    """The per-tensor path (AdasumDistributedOptimizer.update, the C5
    parity route the reference works on per-tensor,
    optimizer.py:197-367): DISTINCT per-worker gradients, dense
    compressor — every tensor's reduced delta equals the pairwise
    adasum_reduce of the per-worker local deltas."""
    params = {"w": jnp.asarray(np.random.RandomState(6).randn(8, 8),
                               jnp.float32),
              "b": jnp.zeros((8,), jnp.float32)}
    dist = AdasumDistributedOptimizer(sgd(0.1), Compression.none(),
                                      world_size=W)
    opt_state = dist.init(params)
    rng = np.random.RandomState(7)
    grads_w = {"w": jnp.asarray(rng.randn(W, 8, 8), jnp.float32),
               "b": jnp.asarray(rng.randn(W, 8), jnp.float32)}

    def worker(gw, p, key):
        g = jax.tree.map(lambda x: x[0], gw)
        upd, _, _ = dist.update(g, opt_state, p, {},
                                jax.random.fold_in(
                                    key, jax.lax.axis_index("data")))
        return jax.tree.map(lambda x: x[None], upd)

    f = jax.jit(shard_map(
        worker, mesh=mesh8, in_specs=(P("data"), P(), P()),
        out_specs=P("data"), check_vma=False))
    upd = f(grads_w, params, jax.random.PRNGKey(0))
    for name in ("w", "b"):
        # local sgd(0.1) delta is -0.1 * g; oracle = pairwise Adasum tree
        deltas = jnp.asarray(-0.1 * np.asarray(grads_w[name])).reshape(W, -1)
        oracle = np.asarray(adasum_reduce(deltas)).reshape(
            grads_w[name].shape[1:])
        np.testing.assert_allclose(np.asarray(upd[name][0]), oracle,
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_adasum_per_tensor_with_dgc(mesh8):
    """Per-tensor Adasum + DGC: compressed deltas scatter-add SUM (no /W),
    dense-fallback deltas adasum + non-accumulating correction — identical
    workers give W x delta at the selected coords and delta on the bias."""
    params = {"w": jnp.asarray(np.random.RandomState(8).randn(40, 40),
                               jnp.float32),
              "b": jnp.zeros((8,), jnp.float32)}
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize([("w", params["w"])])
    dist = AdasumDistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                      world_size=W)
    opt_state = dist.init(params)
    mem = dist.init_memory(params)
    rng = np.random.RandomState(9)
    g = {"w": jnp.asarray(rng.randn(40, 40), jnp.float32),
         "b": jnp.asarray(rng.randn(8), jnp.float32)}

    def worker(p, m, key):
        m = jax.tree.map(lambda x: x[0], m)
        upd, _, m = dist.update(g, opt_state, p, m,
                                jax.random.fold_in(
                                    key, jax.lax.axis_index("data")))
        return (jax.tree.map(lambda x: x[None], upd),
                jax.tree.map(lambda x: x[None], m))

    f = jax.jit(shard_map(
        worker, mesh=mesh8, in_specs=(P(), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))
    mem_w = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         mem)
    upd, mem2 = f(params, mem_w, jax.random.PRNGKey(0))
    uw = np.asarray(upd["w"][0]).reshape(-1)
    delta = -0.1 * np.asarray(g["w"]).reshape(-1)
    a = comp.attributes["w"]
    top = np.argsort(-np.abs(delta))[:a.num_selects]
    expect = np.zeros_like(delta)
    expect[top] = W * delta[top]  # SUM semantics, reference :192-193
    np.testing.assert_allclose(uw, expect, rtol=1e-4, atol=1e-6)
    # dense fallback: identical deltas -> adasum fixed point, then the
    # non-accumulating correction on zero momentum returns the delta
    np.testing.assert_allclose(np.asarray(upd["b"][0]),
                               -0.1 * np.asarray(g["b"]),
                               rtol=1e-5, atol=1e-6)
    # transmitted coords zeroed in the per-worker velocity (memory.update)
    vel = np.asarray(mem2["velocities"]["w"][0])
    assert (vel[top] == 0).all()


def test_adasum_with_dgc_compression(mesh8):
    """Adasum + DGC: compressed payloads are scatter-add summed (no /W,
    reference compression.py:192-193) and the step runs end to end."""
    params = {"w": jnp.asarray(np.random.RandomState(4).randn(64, 64),
                               jnp.float32)}
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize([("w", params["w"])])
    dist = AdasumDistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                      world_size=W)
    layout, engine = dist.make_flat(params)
    flat_p = layout.flatten(params)
    opt_state = dist.init(flat_p)
    mem = engine.init_memory()
    g = jnp.asarray(np.random.RandomState(5).randn(layout.total),
                    jnp.float32)

    def worker(fg, fp, m, key):
        m = jax.tree.map(lambda x: x[0], m)
        upd, _, m = dist.update_flat(fg[0], opt_state, fp, m, key, engine)
        return upd[None], jax.tree.map(lambda x: x[None], m)

    f = jax.jit(shard_map(
        worker, mesh=mesh8, in_specs=(P("data"), P(), P("data"), P()),
        out_specs=(P("data"), P("data")), check_vma=False))
    mem_w = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (W,) + x.shape),
                         mem)
    upd, mem2 = f(jnp.broadcast_to(g[None], (W,) + g.shape), flat_p, mem_w,
                  jax.random.PRNGKey(0))
    u = np.asarray(upd[0])
    assert np.isfinite(u).all()
    # identical sparse payloads from all workers sum to W * delta at the
    # selected coordinates
    nz = np.flatnonzero(u[:layout.t_data])
    assert nz.size > 0


def test_adasum_allreduce_matches_gathered_reduce(mesh8):
    """ppermute recursive doubling == the gathered binary-tree reduce."""
    from dgc_tpu.optim.adasum import adasum_allreduce
    rng = np.random.RandomState(6)
    xs = jnp.asarray(rng.randn(W, 48), jnp.float32)

    def worker(x):
        return adasum_allreduce(x[0], "data", W)[None]

    f = jax.jit(shard_map(worker, mesh=mesh8, in_specs=(P("data"),),
                              out_specs=P("data"), check_vma=False))
    got = np.asarray(f(xs))
    want = np.asarray(adasum_reduce(xs))
    for w in range(W):
        np.testing.assert_allclose(got[w], want, rtol=1e-4, atol=1e-6)


def test_torch_bridge_multiworker_average(mesh8):
    """W=8 bridge with distinct per-worker grads: dense fallback averages
    across workers (the actual cross-worker exchange, not a replicated
    no-op)."""
    torch = pytest.importorskip("torch")
    shapes = {"b": (16,)}
    dist = DistributedOptimizer(sgd(0.1), Compression.none(), world_size=W)
    from dgc_tpu.interop import TorchDGCBridge
    bridge = TorchDGCBridge(dist, shapes, mesh=mesh8)
    g = torch.randn(W, 16)
    out = bridge.exchange({"b": g})
    np.testing.assert_allclose(out["b"].numpy(), g.numpy().mean(0),
                               rtol=1e-5)


def test_torch_bridge_roundtrip():
    """Torch grads through the JAX flat engine: dense average on W=1 with a
    None compressor is the identity; DGC path sparsifies + keeps memory."""
    torch = pytest.importorskip("torch")

    shapes = {"w": (8, 16), "b": (16,)}
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize([("w", jnp.zeros(shapes["w"]))])
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=1)
    from dgc_tpu.interop import TorchDGCBridge
    from dgc_tpu.parallel import make_mesh
    bridge = TorchDGCBridge(dist, shapes, mesh=make_mesh(1))

    gw = torch.randn(8, 16)
    gb = torch.randn(16)
    out = bridge.exchange({"w": gw, "b": gb})
    assert set(out) == {"w", "b"}
    assert tuple(out["w"].shape) == (8, 16)
    # dense fallback ('b') on W=1: average == momentum-corrected value with
    # zero memory == the gradient itself
    np.testing.assert_allclose(out["b"].numpy(), gb.numpy(), rtol=1e-5)
    # compressed 'w': at most num_selects nonzero entries, each equal to
    # the original gradient value there (W=1 average)
    a = comp.attributes["w"]
    w_out = out["w"].numpy().reshape(-1)
    nz = np.flatnonzero(w_out)
    assert 0 < nz.size <= a.num_selects
    np.testing.assert_allclose(w_out[nz], gw.numpy().reshape(-1)[nz],
                               rtol=1e-5)
    # error feedback: untransmitted residual accumulated in velocities
    sd = bridge.state_dict()
    assert np.abs(sd["velocities"]["w"]).sum() > 0
    # second step runs (memory threading)
    out2 = bridge.exchange({"w": gw, "b": gb})
    assert np.isfinite(out2["w"].numpy()).all()


def test_adasum_train_step_per_worker_opt_state(mesh8):
    """Full flat train step with Adasum: the local base-optimizer state is
    per-worker ([world] leading axis, sharded on the data axis) and
    genuinely diverges across workers on distinct data — a replicated spec
    would silently keep only shard 0 on host materialization."""
    from flax import linen as nn
    from dgc_tpu.training import (build_train_step, make_flat_setup,
                                  make_flat_state, shard_state)

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    model = M()
    v = {"params": model.init(jax.random.PRNGKey(0),
                              jnp.zeros((1, 8)))["params"],
         "batch_stats": {}}

    def apply_fn(variables, x, train=True, mutable=None, rngs=None):
        out = model.apply({"params": variables["params"]}, x, train=train)
        return (out, {"batch_stats": {}}) if mutable else out

    dist = AdasumDistributedOptimizer(
        sgd(0.05, momentum=0.9), Compression.none(), world_size=W)
    assert dist.per_worker_opt_state
    setup = make_flat_setup(v, dist)
    state = shard_state(make_flat_state(v, dist, setup, W), mesh8,
                        dist_opt=dist)
    assert state.opt_state.momentum_buffer.shape[0] == W
    step = build_train_step(apply_fn, dist, mesh8, flat=setup)

    rng = np.random.RandomState(8)
    images = jnp.asarray(rng.randn(W * 4, 8), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 4, W * 4), jnp.int32)
    for i in range(2):
        state, m = step(state, images, labels, jax.random.PRNGKey(i))
    assert np.isfinite(float(m["loss"]))
    buf = np.asarray(jax.device_get(state.opt_state.momentum_buffer))
    # distinct per-worker data -> distinct local momentum buffers survive
    # the round trip to host
    assert not np.allclose(buf[0], buf[1])


def test_torch_bridge_state_dict_roundtrip(mesh8):
    torch = pytest.importorskip("torch")
    shapes = {"w": (8, 16)}
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9),
                         sample_ratio=1.0)
    comp.initialize([("w", jnp.zeros(shapes["w"]))])
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    from dgc_tpu.interop import TorchDGCBridge
    bridge = TorchDGCBridge(dist, shapes, mesh=mesh8)
    bridge.exchange({"w": torch.randn(W, 8, 16)})
    sd = bridge.state_dict()
    assert sd["velocities"]["w"].shape[0] == W
    assert np.abs(sd["velocities"]["w"]).sum() > 0

    bridge2 = TorchDGCBridge(dist, shapes, mesh=mesh8)
    bridge2.load_state_dict(sd)
    sd2 = bridge2.state_dict()
    for k in sd:
        np.testing.assert_allclose(sd2[k]["w"], sd[k]["w"], rtol=1e-6)


def test_torch_bridge_fp16_wire(mesh8):
    """fp16 wire format through the bridge (reference compression.py:168-171
    wire casts): compressed values cross the wire as fp16 and are restored
    to fp32; the result matches the fp32 wire to fp16 precision, and the
    returned tensors are writable (no UB from read-only numpy views)."""
    torch = pytest.importorskip("torch")
    import warnings
    from dgc_tpu.interop import TorchDGCBridge

    shapes = {"w": (16, 32), "b": (32,)}

    def make(fp16):
        comp = DGCCompressor(0.1, memory=DGCSGDMemory(momentum=0.9),
                             sample_ratio=1.0, fp16_values=fp16)
        comp.initialize([("w", jnp.zeros(shapes["w"]))])
        dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                    world_size=W)
        return TorchDGCBridge(dist, shapes, mesh=mesh8)

    torch.manual_seed(0)
    grads = {"w": torch.randn(W, 16, 32), "b": torch.randn(W, 32)}
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the non-writable-numpy warning
        out16 = make(True).exchange({k: v.clone() for k, v in grads.items()})
        out32 = make(False).exchange({k: v.clone() for k, v in grads.items()})
    for n in shapes:
        assert out16[n].dtype == torch.float32
        np.testing.assert_allclose(out16[n].numpy(), out32[n].numpy(),
                                   rtol=2e-3, atol=2e-3)
        out16[n].add_(1.0)  # writable round-trip
    # fp16 wire genuinely quantized something (paths are not identical)
    assert not np.array_equal(out16["w"].numpy() - 1.0, out32["w"].numpy())


def test_multihost_helpers_single_process():
    from dgc_tpu.parallel.multihost import (
        initialize_multihost, is_coordinator, local_batch_slice)
    assert initialize_multihost() is False  # no coordinator env => no-op
    assert is_coordinator()
    assert local_batch_slice(64) == slice(0, 64)


def test_profiling_helpers(tmp_path):
    from dgc_tpu.utils.profiling import exchange_report, step_timer, trace

    f = jax.jit(lambda x: x * 2)
    stats = step_timer(f, jnp.ones((128,)), warmup=1, iters=3)
    assert stats["median_ms"] > 0

    rep = exchange_report(dgc_ms=0.25, dense_ms=0.2, payload_elems=283,
                          num_params=272474, workers=32, fabric_gbps=3.125)
    assert rep["speedup"] > 1
    assert rep["wire_reduction"] > 10

    with trace(str(tmp_path / "prof")):
        jax.block_until_ready(f(jnp.ones((128,))))
    assert any((tmp_path / "prof").rglob("*"))


def test_torch_training_through_bridge_converges():
    """The north-star compatibility path end-to-end: a real torch training
    loop (torch model, autograd, SGD) with gradients routed through the
    JAX DGC engine each step (examples/torch_train.py). Loss must collapse
    on the structured task."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples"))
    try:
        from torch_train import train
    finally:
        sys.path.pop(0)
    losses = train(steps=60, verbose=False)
    assert losses[-1] < 0.1 * losses[0], (losses[0], losses[-1])
