"""LR schedule recipe (SURVEY.md §2.10, reference train.py:335-352)."""

import numpy as np
import pytest

from dgc_tpu.training import cosine_schedule, make_lr_schedule, multistep_schedule


def test_warmup_ramp():
    # base 0.1, world 8, nbps 1 → scaled 0.8; warmup 5 epochs of 10 steps
    sched = make_lr_schedule(scaled_lr=0.8, world_size=8,
                             num_steps_per_epoch=10, warmup_lr_epochs=5)
    # step 0: factor = 1/8 → lr = base_lr = 0.1
    assert float(sched(0)) == pytest.approx(0.1)
    # mid-warmup epoch 2.5: factor = (2.5*7/5+1)/8 = 0.5625
    assert float(sched(25)) == pytest.approx(0.8 * 0.5625)
    # end of warmup: full scaled lr
    assert float(sched(50)) == pytest.approx(0.8)


def test_cosine_after_warmup():
    decay = cosine_schedule(t_max=195)
    sched = make_lr_schedule(scaled_lr=0.8, world_size=8,
                             num_steps_per_epoch=10, warmup_lr_epochs=5,
                             decay=decay)
    # first post-warmup epoch: t=0 → full lr
    assert float(sched(50)) == pytest.approx(0.8)
    # halfway: t=97.5 epochs... use epoch 102 (t=97): cos curve in (0,1)
    mid = float(sched(1020))
    assert 0.0 < mid < 0.8
    # per-epoch stepping: constant within an epoch
    assert float(sched(1020)) == float(sched(1029))
    # end: ~0
    assert float(sched(10 * 200)) == pytest.approx(0.0, abs=1e-3)


def test_multistep():
    decay = multistep_schedule(milestones=[25, 55, 75], gamma=0.1)
    sched = make_lr_schedule(scaled_lr=1.0, world_size=8,
                             num_steps_per_epoch=1, warmup_lr_epochs=5,
                             decay=decay)
    # epochs after warmup: e-5; milestones hit at real epochs 30, 60, 80
    assert float(sched(10)) == pytest.approx(1.0)
    assert float(sched(30)) == pytest.approx(0.1)
    assert float(sched(60)) == pytest.approx(0.01)
    assert float(sched(85)) == pytest.approx(0.001)


def test_no_warmup():
    sched = make_lr_schedule(scaled_lr=0.4, world_size=4,
                             num_steps_per_epoch=10, warmup_lr_epochs=0)
    assert float(sched(0)) == pytest.approx(0.4)
