"""Subprocess worker for the train-to-serve drill (tests/test_serving.py).

Roles (argv[1]):

* ``trainer <serving_dir> <target_version> <target_seq>`` — walks a
  seeded toy parameter set and publishes the serving stream every tick
  (``Exporter``); the injected dropped-delta fault rides the
  ``DGC_SERVE_DROP`` env var set by the test. Stops once the stream head
  reaches ``(target_version, target_seq)`` — i.e. after the control
  plane's resync rebase landed and the post-resync stream advanced.
* ``replica <serving_dir> <name> <target_version> <target_seq>`` —
  follows the stream (``Replica``, ``auto_resync=False``: the CONTROL
  PLANE must drive the resync, that is the drill), publishes its status
  file for the fleet monitor every poll, and exits once it serves
  exactly the target head.

Prints ``RESULT:<json>`` as the last line; everything else is progress
logging for the drill's log files (pipes deadlock at 64 KB — the parent
reads files, tests/test_multiprocess.py pattern).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_params(step: int):
    """The trainer's deterministic toy model state at ``step``: both ends
    of the drill can name any step's exact params, so parity failures are
    attributable. Mixed shapes on purpose (matrix / vector / scalar)."""
    rng = np.random.RandomState(1234)
    w = rng.randn(48, 32).astype(np.float32)
    b = rng.randn(48).astype(np.float32)
    s = np.float32(0.5)
    for i in range(step):
        upd = np.random.RandomState(10_000 + i)
        w = w + 0.01 * upd.randn(48, 32).astype(np.float32)
        b = b + 0.01 * upd.randn(48).astype(np.float32)
        s = np.float32(s + 0.001)
    return {"w": w, "b": b, "s": s}


def run_trainer(serving_dir: str, target_version: int,
                target_seq: int) -> dict:
    from dgc_tpu.serving import Exporter
    exp = Exporter(serving_dir, make_params(0), ratio=0.05, max_lag=3,
                   lineage={"epoch": 0, "step": 0})
    step, published = 0, 0
    deadline = time.monotonic() + 90.0
    while time.monotonic() < deadline:
        step += 1
        rec = exp.publish(make_params(step), step=step)
        published += 1
        print(f"published {rec['kind']} v{rec['base_version']}:"
              f"{rec['delta_seq']}"
              + (" DROPPED" if rec.get("dropped") else ""), flush=True)
        if (exp.base_version >= target_version
                and exp.delta_seq >= target_seq):
            break
        time.sleep(0.15)
    key = f"{exp.base_version}:{exp.delta_seq}"
    return {"role": "trainer", "base_version": exp.base_version,
            "latest_seq": exp.delta_seq, "digest": exp.digests[key],
            "published": published,
            "wire_bytes_per_update": exp.spec.wire_bytes_per_update(),
            "full_checkpoint_bytes": exp.spec.full_checkpoint_bytes()}


def run_replica(serving_dir: str, name: str, target_version: int,
                target_seq: int) -> dict:
    from dgc_tpu.serving import Replica
    from dgc_tpu.telemetry import registry
    rep = Replica(serving_dir, name=name, auto_resync=False)
    max_ok_staleness = 0
    deadline = time.monotonic() + 90.0
    st = rep.status(latest_seq=-1, max_lag=0)
    while time.monotonic() < deadline:
        st = rep.poll()
        registry.validate_replica_status(st)
        rep.write_status(serving_dir, latest_seq=st["latest_seq"],
                         max_lag=st["max_lag"])
        if st["health"] == "ok":
            max_ok_staleness = max(max_ok_staleness, st["staleness"])
        if (st["health"] == "ok"
                and st["base_version"] == target_version
                and st["delta_seq"] == target_seq
                and st["latest_seq"] == target_seq):
            break
        time.sleep(0.1)
    # bitwise apply parity is checked by the parent against the trainer's
    # digest for the same (base_version, delta_seq)
    out = dict(st, role="replica", digest=rep.digest(),
               max_ok_staleness=max_ok_staleness)
    # the served params reshape losslessly out of the flat state
    params = rep.params()
    out["param_names"] = sorted(params)
    return out


def main(argv) -> int:
    role = argv[1]
    if role == "trainer":
        result = run_trainer(argv[2], int(argv[3]), int(argv[4]))
    elif role == "replica":
        result = run_replica(argv[2], argv[3], int(argv[4]), int(argv[5]))
    else:
        raise SystemExit(f"unknown role {role!r}")
    print("RESULT:" + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
