"""Sparsification numerics contract (SURVEY.md §2.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgc_tpu.compression import DGCCompressor, DGCSGDMemory
from dgc_tpu.ops import (
    adapt_threshold,
    scatter_add_dense,
    select_by_threshold,
    strided_sample,
    topk_threshold,
    transmitted_mask,
)


def test_topk_threshold_is_kth_largest():
    x = jnp.asarray([5.0, 1.0, 3.0, 9.0, 7.0])
    assert float(topk_threshold(x, 3)) == 5.0
    assert float(topk_threshold(x, 1)) == 9.0


def test_strided_sample_phase_in_range():
    imp = jnp.arange(100.0)
    s = strided_sample(imp, num_samples=9, stride=11, key=jax.random.PRNGKey(0))
    assert s.shape == (9,)
    # all sampled values come from the tensor and respect the stride pattern
    vals = np.asarray(s)
    phase = vals[0]
    assert np.allclose(np.diff(vals), 11)
    assert 0 <= phase < 11


def test_select_fixed_size_and_padding():
    flat = jnp.asarray([0.1, -5.0, 0.2, 4.0, -0.3, 3.0])
    imp = jnp.abs(flat)
    vals, idx, valid = select_by_threshold(flat, imp, jnp.float32(3.0), 4)
    # 3 elements pass (|−5|, |4|, |3|); slot 4 is padded
    assert vals.shape == (4,) and idx.shape == (4,) and valid.shape == (4,)
    assert bool(valid[0]) and bool(valid[1]) and bool(valid[2])
    assert not bool(valid[3])
    assert float(vals[3]) == 0.0 and int(idx[3]) == 0
    # selected (value, index) pairs are the top-3 by importance, signed values
    got = {(int(i), float(v)) for i, v in zip(idx[:3], vals[:3])}
    assert got == {(1, -5.0), (3, 4.0), (5, 3.0)}


def test_select_truncates_to_topk_on_overflow():
    flat = jnp.arange(1.0, 11.0)          # importance 1..10
    vals, idx, valid = select_by_threshold(flat, jnp.abs(flat),
                                           jnp.float32(2.0), 3)
    assert bool(valid.all())
    assert set(np.asarray(idx).tolist()) == {9, 8, 7}   # top-3 by importance


def test_adapt_threshold_lowers_when_too_few():
    # threshold passes only 1 element but target is 10 => must lower
    imp = jnp.concatenate([jnp.full((1,), 100.0), jnp.full((99,), 1.0)])
    thr = adapt_threshold(imp, jnp.float32(50.0), num_selects=10,
                          lower_bound=0.8, upper_bound=1.3, max_iters=50,
                          resample=True)
    count = int(jnp.sum(imp >= thr))
    assert count >= 0.8 * 10


def test_adapt_threshold_raises_when_too_many_noresample():
    imp = jnp.full((1000,), 1.0).at[:5].set(10.0)
    # threshold passes everything; without resample it must raise
    thr = adapt_threshold(imp, jnp.float32(0.5), num_selects=5,
                          lower_bound=0.8, upper_bound=1.3, max_iters=50,
                          resample=False)
    assert float(thr) > 0.5


def test_adapt_threshold_zero_grad_terminates():
    imp = jnp.zeros((1000,))
    thr = adapt_threshold(imp, jnp.float32(0.0), num_selects=10,
                          lower_bound=0.8, upper_bound=1.3, max_iters=10,
                          resample=True)
    assert float(thr) == 0.0  # bounded loop, no hang, no NaN


def test_scatter_add_duplicates_accumulate():
    idx = jnp.asarray([0, 2, 2, 5], jnp.int32)
    vals = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    out = scatter_add_dense(6, idx, vals)
    assert np.allclose(out, [1.0, 0.0, 5.0, 0.0, 0.0, 4.0])


def test_transmitted_mask_guards_padded_zero():
    idx = jnp.asarray([3, 0, 0], jnp.int32)
    valid = jnp.asarray([True, False, False])
    mask = np.asarray(transmitted_mask(6, idx, valid))
    assert mask.tolist() == [False, False, False, True, False, False]
    # but a genuine index-0 transmission is recorded
    mask2 = np.asarray(transmitted_mask(6, jnp.asarray([0], jnp.int32),
                                        jnp.asarray([True])))
    assert mask2[0]


@pytest.mark.parametrize("resample", [True, False])
@pytest.mark.parametrize("strided", [True, False])
def test_compressor_sparsify_end_to_end(resample, strided):
    comp = DGCCompressor(0.01, sample_ratio=0.05, resample=resample,
                         strided_sample=strided)
    numel = 10000
    comp.initialize([("w", (numel, (100, 100)))])
    g = jax.random.normal(jax.random.PRNGKey(1), (100, 100))
    vals, idx, valid = jax.jit(
        lambda g, k: comp.sparsify(g, "w", k))(g, jax.random.PRNGKey(2))
    ns = comp.attributes["w"].num_selects
    assert vals.shape == (ns,) and idx.shape == (ns,)
    flat = np.asarray(g).reshape(-1)
    v, i, m = np.asarray(vals), np.asarray(idx), np.asarray(valid)
    # transmitted values must be the tensor's values at those indices
    assert np.allclose(v[m], flat[i[m]])
    # selected elements are important: all |selected| >= max(|unselected|) is
    # too strong under sampling; check they are above the median importance
    if m.sum() > 0:
        assert np.abs(v[m]).min() >= np.median(np.abs(flat))


def test_sparsify_deterministic_under_same_key():
    comp = DGCCompressor(0.01, sample_ratio=0.05)
    comp.initialize([("w", (5000, (5000,)))])
    g = jax.random.normal(jax.random.PRNGKey(3), (5000,))
    f = jax.jit(lambda g, k: comp.sparsify(g, "w", k))
    a = f(g, jax.random.PRNGKey(7))
    b = f(g, jax.random.PRNGKey(7))
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))
