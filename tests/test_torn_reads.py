"""Torn-read property tests: every tolerant reader in the coordination
protocols, against truncation at EVERY byte boundary.

The crash model (dgcmc, docs/ANALYSIS.md §Layer 4) says a reader of a
rename-atomic artifact can only ever see a complete old or complete new
file — but readers must ALSO survive the states a non-atomic writer or
a torn filesystem could leave, because that is exactly the regression
the model checker exists to catch. Contract per reader: a proper prefix
of a valid artifact yields None (or the documented fallback), never an
exception and never a partial payload."""

import json
import os

import numpy as np
import pytest

from dgc_tpu.resilience import surgery
from dgc_tpu.serving import protocol
from dgc_tpu.telemetry import sink


def _assert_none_at_every_truncation(path, reader, full_value):
    """reader(path) must be None for every proper prefix of the file and
    ``full_value`` for the complete file."""
    data = open(path, "rb").read()
    assert len(data) > 2
    for k in range(len(data)):
        with open(path, "wb") as f:
            f.write(data[:k])
        got = reader()
        assert got is None, f"truncation at byte {k}/{len(data)}: {got!r}"
    with open(path, "wb") as f:
        f.write(data)
    assert reader() == full_value


# --------------------------------------------------------------------- #
# serving/protocol.py                                                    #
# --------------------------------------------------------------------- #

def test_read_json_none_at_every_truncation(tmp_path):
    payload = {"base_version": 3, "latest_seq": 7, "digests": {"3:7": "d"}}
    path = str(tmp_path / "x.json")
    protocol.write_json_atomic(path, payload)
    _assert_none_at_every_truncation(
        path, lambda: protocol.read_json(path), payload)


def test_read_manifest_none_at_every_truncation(tmp_path):
    payload = {"spec": {"ratio": 0.5}, "base_version": 1, "latest_seq": 0}
    protocol.write_json_atomic(
        os.path.join(str(tmp_path), protocol.MANIFEST), payload)
    _assert_none_at_every_truncation(
        os.path.join(str(tmp_path), protocol.MANIFEST),
        lambda: protocol.read_manifest(str(tmp_path)), payload)


def test_read_resync_request_none_at_every_truncation(tmp_path):
    req = protocol.request_resync(str(tmp_path), "stale_replica",
                                  replicas=["a", "b"])
    path = os.path.join(str(tmp_path), protocol.RESYNC_REQUEST)
    _assert_none_at_every_truncation(
        path, lambda: protocol.read_resync_request(str(tmp_path)), req)


def test_load_npz_none_at_every_truncation(tmp_path):
    path = str(tmp_path / "delta.npz")
    arrays = {"values": np.arange(6, dtype=np.float32),
              "idx": np.array([1, 3, 5], np.int32)}
    protocol.save_npz_atomic(path, arrays)
    data = open(path, "rb").read()
    for k in range(len(data)):
        with open(path, "wb") as f:
            f.write(data[:k])
        assert protocol.load_npz(path) is None, f"byte {k}/{len(data)}"
    with open(path, "wb") as f:
        f.write(data)
    out = protocol.load_npz(path)
    assert out is not None
    np.testing.assert_array_equal(out["values"], arrays["values"])
    np.testing.assert_array_equal(out["idx"], arrays["idx"])


def test_load_npz_missing_is_gap_not_error(tmp_path):
    assert protocol.load_npz(str(tmp_path / "absent.npz")) is None


# --------------------------------------------------------------------- #
# resilience/surgery.py                                                  #
# --------------------------------------------------------------------- #

def test_read_order_none_at_every_truncation(tmp_path):
    path = str(tmp_path / surgery.ORDER_FILE)
    surgery.publish_order(path, "straggler", 2, step=11)
    full = surgery.read_order(path)
    assert full and full["verdict"] == "straggler" and full["target"] == 2
    _assert_none_at_every_truncation(
        path, lambda: surgery.read_order(path), full)


def test_read_exit_record_none_at_every_truncation(tmp_path):
    path = str(tmp_path / surgery.EXIT_RECORD)
    agreement = surgery.Agreement(excise=True, target=1,
                                  verdict="straggler")
    surgery.write_exit_record(path, agreement, world=4, process_index=0,
                              step=9)
    full = surgery.read_exit_record(path)
    assert full and full["world"] == 4 and full["target"] == 1
    _assert_none_at_every_truncation(
        path, lambda: surgery.read_exit_record(path), full)


# --------------------------------------------------------------------- #
# telemetry/sink.py — append-tail-torn: prefix survives, never partial   #
# --------------------------------------------------------------------- #

def test_read_run_tolerant_prefix_at_every_truncation(tmp_path):
    path = str(tmp_path / "run.jsonl")
    app = sink.JsonlAppender(path)
    from dgc_tpu.telemetry import registry
    app.write({"schema": registry.SCHEMA,
               "version": registry.SCHEMA_VERSION, "run": "t"})
    for i in (1, 2, 3):
        app.write({"kind": "step", "i": i})
    app.close()
    data = open(path, "rb").read()
    header_len = data.index(b"\n") + 1
    for k in range(len(data) + 1):
        with open(path, "wb") as f:
            f.write(data[:k])
        if k < header_len - 1:
            # a torn header is an unreadable FILE by contract — a typed
            # error, never a misparse (k == header_len - 1 only drops
            # the newline: the header json is complete and readable)
            with pytest.raises(ValueError):
                sink.read_run_tolerant(path)
            continue
        header, records, skipped = sink.read_run_tolerant(path)
        ids = [r["i"] for r in records]
        # complete-record prefix only; the torn tail is counted, not
        # surfaced, and never parsed into a partial record
        assert ids == [1, 2, 3][:len(ids)], f"byte {k}: {ids}"
        assert all(set(r) == {"kind", "i"} for r in records)
    header, records, skipped = sink.read_run_tolerant(path)
    assert [r["i"] for r in records] == [1, 2, 3] and skipped == 0


# --------------------------------------------------------------------- #
# training/checkpoint.py — pointer torn at any byte => scan fallback     #
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def saved_manager(tmp_path_factory):
    from dgc_tpu.training.checkpoint import CheckpointManager
    d = str(tmp_path_factory.mktemp("ckpt"))
    mgr = CheckpointManager(d, keep=3)
    for epoch in (0, 1):
        state = {"w": np.arange(4, dtype=np.float32) + epoch,
                 "m": np.full((3,), float(epoch), np.float32)}
        mgr.save(epoch, state, {"acc": 0.5 + epoch})
    return mgr


def test_latest_epoch_none_at_every_truncation(saved_manager):
    mgr = saved_manager
    meta = mgr._meta_path()
    data = open(meta, "rb").read()
    assert mgr.latest_epoch() == 1
    for k in range(len(data)):
        with open(meta, "wb") as f:
            f.write(data[:k])
        assert mgr.latest_epoch() is None, f"byte {k}/{len(data)}"
    with open(meta, "wb") as f:
        f.write(data)
    assert mgr.latest_epoch() == 1


def test_restore_falls_back_past_torn_pointer(saved_manager):
    mgr = saved_manager
    meta = mgr._meta_path()
    data = open(meta, "rb").read()
    template = {"w": np.zeros(4, np.float32), "m": np.zeros(3, np.float32)}
    with open(meta, "wb") as f:
        f.write(data[:len(data) // 2])   # torn pointer
    try:
        out = mgr.restore(template)
        assert out is not None
        state, epoch, meters = out
        # the kept-epoch scan still finds the newest COMPLETE epoch
        assert epoch == 1
        np.testing.assert_array_equal(
            np.asarray(state["w"]), np.arange(4, dtype=np.float32) + 1)
    finally:
        with open(meta, "wb") as f:
            f.write(data)


def test_restore_falls_back_past_torn_meters(saved_manager):
    mgr = saved_manager
    meters_path = os.path.join(mgr.directory, "e1", "meters.json")
    data = open(meters_path, "rb").read()
    template = {"w": np.zeros(4, np.float32), "m": np.zeros(3, np.float32)}
    with open(meters_path, "wb") as f:
        f.write(data[:len(data) // 2])   # torn meters in the newest epoch
    try:
        out = mgr.restore(template)
        assert out is not None
        _state, epoch, _meters = out
        assert epoch == 0                # fell back, did not raise
    finally:
        with open(meters_path, "wb") as f:
            f.write(data)
