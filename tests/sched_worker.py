"""Fake gang member for the scheduler drill (tests/test_scheduler.py).

Gang members form a W-wide cohort under one ControlPlane + GangScheduler,
lock-stepped through a file barrier in a shared ``--cohort`` dir — no
jax, millisecond steps — so the full priority-inversion cycle of
docs/RESILIENCE.md §Scheduler runs in seconds:

* every step each member accumulates a deterministic per-(step, seat)
  residual contribution into an f32 accumulator (``res.<seat>.json``),
  mirroring DGC error feedback, alongside an exact f64 oracle trail
  (``mass_in``) of everything ever added — the drill's conservation
  check is |Σ res − Σ mass_in| ≤ 1e-6 across the cohort. Contributions
  are dyadic rationals (exact in f32) so a lost seat shows up as ~1e-1,
  never as accumulated rounding;
* a published surgery order (the scheduler's preempt-to-grant) is
  consumed at the step boundary: EVERY member writes its residual state
  (the excised seat marks it ``final``), writes a ``surgery_exit.json``
  record naming the target, and exits 76 — the supervisors apply the
  shrunk spec, quarantine the excised seat, and relaunch survivors;
* a stale order (``target >= W`` after the shrink already applied) is
  ignored, so survivors self-stabilize without a cleanup pass;
* seat 0 folds the final residual of any seat outside the current world
  into its own accumulator (f32 add — the drill's stand-in for the
  elastic merge) and zeroes the orphan, so the excised seat's mass
  survives the shrink;
* SIGTERM (the grow-path cohort restart) is deferred to the next
  checkpoint — the handler only sets a flag, so the res/mass_in pair is
  never torn — then takes the emergency-save path: persist state,
  exit 75;
* progress is shared (``progress.json``) and barrier markers persist,
  so members relaunched under a re-published spec (survivors at W-1, a
  grown cohort at W+1) resume at the cohort's step.

Telemetry is the fleet schema so the plane's monitor.collect sees a
real-looking run every tick — the autoscale detector reads its
throughput lane.
"""

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from dgc_tpu.resilience import surgery  # noqa: E402
from dgc_tpu.telemetry import registry  # noqa: E402


def _atomic_json(path, payload):
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _read_json(path):
    try:
        with open(path) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) else None
    except (OSError, ValueError):
        return None


def _read_step(path, default=0):
    try:
        with open(path) as f:
            return int(json.load(f).get("step", default))
    except (OSError, ValueError):
        return default


def contrib(step, seat):
    """Per-(step, seat) residual contribution: a dyadic rational, so f32
    accumulation is EXACT and the mass oracle isolates lost seats from
    rounding."""
    return (seat + 1) / 1024.0 + (step % 8) / 8192.0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("run_dir")
    ap.add_argument("--cohort", required=True,
                    help="shared dir: barriers, progress, residual state")
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--step-ms", type=float, default=25.0)
    ap.add_argument("--world", type=int, default=2,
                    help="telemetry lane width (fixed across phases)")
    args = ap.parse_args(argv)

    run_dir = os.path.abspath(args.run_dir)
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    cohort_dir = os.path.abspath(args.cohort)
    bar_dir = os.path.join(cohort_dir, "barriers")
    for d in (ckpt_dir, bar_dir):
        os.makedirs(d, exist_ok=True)
    shard_dir = os.path.join(run_dir, "telemetry", "host0")
    os.makedirs(shard_dir, exist_ok=True)

    W = int(os.environ.get("JAX_NUM_PROCESSES") or 1)
    seat = int(os.environ.get("JAX_PROCESS_ID") or 0)
    hb_path = os.environ.get("DGC_HEARTBEAT")
    boundary_timeout = float(os.environ.get("DGC_BOUNDARY_TIMEOUT") or 10.0)
    progress_path = os.path.join(cohort_dir, "progress.json")
    order_path = os.path.join(ckpt_dir, surgery.ORDER_FILE)
    res_path = os.path.join(cohort_dir, "res.%d.json" % seat)

    static = {"world": args.world, "num_params": 1000, "payload_elems": 50,
              "num_processes": W, "process_id": seat}
    run_id = os.environ.get("DGC_RUN_ID")
    if run_id:
        static["run_id"] = run_id

    def beat():
        if not hb_path:
            return
        try:
            with open(hb_path, "a"):
                pass
            os.utime(hb_path, None)
        except OSError:
            pass

    def save(completed):
        _atomic_json(os.path.join(ckpt_dir, "latest.json"),
                     {"epoch": int(completed)})

    fh = open(os.path.join(shard_dir, "telemetry.jsonl"), "w")

    def emit(rec):
        fh.write(json.dumps(rec) + "\n")
        fh.flush()

    emit(registry.make_header(static, guards=True, fleet=True))

    # residual state: f32 accumulator + exact f64 oracle trail, resumed
    # from the seat's own atomic file across relaunches
    st = _read_json(res_path) or {}
    res = np.float32(st.get("res", 0.0))
    mass_in = float(st.get("mass_in", 0.0))
    folded = list(st.get("folded", []))

    # cohort-wide resume point: all members of a (re)formed cohort start
    # at the same shared step
    step = max(_read_step(progress_path),
               _read_step(os.path.join(ckpt_dir, "latest.json"), 0))
    state = {"step": step}

    def save_res(final=False):
        _atomic_json(res_path, {
            "seat": seat, "step": state["step"], "res": float(res),
            "mass_in": mass_in, "folded": folded, "final": bool(final)})

    # SIGTERM/SIGINT are deferred to the next checkpoint: the handler
    # only raises a flag, so res and mass_in (updated as a pair) can
    # never be persisted torn
    term = {"flag": False}

    def on_term(signum, frame):
        term["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    def emergency_exit():
        save(state["step"])
        save_res()
        fh.flush()
        os._exit(75)

    def fold_orphans():
        """Seat 0 folds the final residual of seats outside the current
        world into its own accumulator (the elastic-merge stand-in):
        own state first (crash between the writes double-counts — the
        'folded' list dedups on resume — instead of losing mass)."""
        nonlocal res
        if seat != 0:
            return
        for j in range(W, 16):
            if j in folded:
                continue
            p = os.path.join(cohort_dir, "res.%d.json" % j)
            rec = _read_json(p)
            if not rec or not rec.get("final"):
                continue
            res = np.float32(res + np.float32(rec.get("res", 0.0)))
            folded.append(j)
            save_res()
            _atomic_json(p, dict(rec, res=0.0, folded_into=seat))
            emit({"event": "residual_fold", "t_host": round(time.time(), 3),
                  "from_seat": j, "into_seat": seat,
                  "mass": rec.get("res", 0.0)})

    def barrier(s):
        """Write own marker, wait for all W peers'. Markers persist, so
        a resuming member fast-forwards through past steps. Returns the
        missing member ids on deadline."""
        own = os.path.join(bar_dir, "b%d.%d" % (s, seat))
        with open(own, "w") as f:
            f.write(str(time.time()))
        deadline = time.time() + boundary_timeout
        while True:
            missing = [q for q in range(W)
                       if not os.path.exists(
                           os.path.join(bar_dir, "b%d.%d" % (s, q)))]
            if not missing:
                return []
            beat()      # blocked at the boundary is not hung
            if term["flag"]:
                emergency_exit()
            if time.time() > deadline:
                return missing
            time.sleep(0.015)

    def surgery_exit(target, verdict, s, lost):
        save(s)
        save_res(final=(seat == target))
        ag = surgery.Agreement(excise=True, target=target,
                               verdict=verdict, lost=lost)
        surgery.write_exit_record(
            os.path.join(ckpt_dir, surgery.EXIT_RECORD), ag,
            world=W, process_index=seat, step=s)
        emit({"event": "surgery_exit", "t_host": round(time.time(), 3),
              "step": s, "target": target, "verdict": verdict})
        fh.flush()
        os._exit(surgery.EXIT_SURGERY)

    while state["step"] < args.steps:
        s = state["step"]
        beat()
        if term["flag"]:
            emergency_exit()
        # consume a published excise order at the boundary; a stale one
        # (target outside the already-shrunk world) is ignored
        order = surgery.read_order(order_path)
        if order is not None and int(order["target"]) < W:
            surgery_exit(int(order["target"]), order["verdict"], s,
                         lost=False)
        fold_orphans()
        missing = barrier(s)
        if missing:
            # a peer left the cohort at the boundary (its order arrived
            # first): same exit-76 path, naming the missing member
            surgery_exit(max(missing), "hang", s, lost=True)
        res = np.float32(res + np.float32(contrib(s, seat)))
        mass_in += contrib(s, seat)
        time.sleep(args.step_ms / 1000.0)
        state["step"] = s + 1
        save(s + 1)
        save_res()
        _atomic_json(progress_path, {"step": s + 1})
        emit({
            "step": s, "t_host": round(time.time(), 3),
            "loss": round(2.0 - 0.01 * s, 4),
            "grad_norm": 1.0, "payload_elems": 50.0,
            "w_clock": [10.0] * args.world,
            "w_grad_norm": [1.0] * args.world,
            "w_residual_mass": [100.0] * args.world,
            "w_sent_ratio": [0.05] * args.world,
            "straggler": 0.0, "straggler_gap": 0.0, "worker_skew": 0.1,
        })

    fold_orphans()      # catch a late-landing orphan before finishing
    save_res()
    emit({"event": "run_done", "t_host": round(time.time(), 3),
          "steps": args.steps, "world": W})
    fh.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
