"""Multi-worker exchange semantics on the fake 8-device CPU mesh
(SURVEY.md §2.5, §5 backend notes)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dgc_tpu import (
    Compression,
    DGCCompressor,
    DGCSGDMemory,
    DistributedOptimizer,
    dgc_sgd,
    sgd,
)
from dgc_tpu.training import with_leading_axis
from dgc_tpu.utils.compat import shard_map

W = 8


def _exchange_fn(dist, mesh):
    def worker(grads, mem, key):
        grads = jax.tree.map(lambda x: x[0], grads)
        mem = jax.tree.map(lambda x: x[0], mem)
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        out, mem = dist.exchange(grads, mem, key)
        return (jax.tree.map(lambda x: x[None], out),
                jax.tree.map(lambda x: x[None], mem))

    return jax.jit(shard_map(
        worker, mesh=mesh,
        in_specs=(P("data"), P("data"), P()),
        out_specs=(P("data"), P("data")),
        check_vma=False))


def test_dense_none_compressor_is_psum_average(mesh8):
    dist = DistributedOptimizer(sgd(0.1), Compression.none(), world_size=W)
    rng = np.random.RandomState(0)
    g = rng.randn(W, 32).astype(np.float32)
    f = _exchange_fn(dist, mesh8)
    out, _ = f({"w": jnp.asarray(g)}, {}, jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(out["w"][0]), g.mean(0), rtol=1e-5)


def test_fp16_compressor_roundtrip(mesh8):
    dist = DistributedOptimizer(sgd(0.1), Compression.fp16(), world_size=W)
    g = np.full((W, 16), 0.5, np.float32)
    f = _exchange_fn(dist, mesh8)
    out, _ = f({"w": jnp.asarray(g)}, {}, jax.random.PRNGKey(0))
    assert out["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["w"][0]), 0.5)


def test_dgc_exchange_matches_manual_oracle(mesh8):
    """decompress(all_gather(compress(g))) == average of per-worker sparse
    contributions, reconstructed from the velocity mask side-channel."""
    comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9))
    numel = 2304
    comp.initialize([("conv", (numel, (3, 3, 16, 16)))])
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    rng = np.random.RandomState(1)
    g = rng.randn(W, 3, 3, 16, 16).astype(np.float32)
    mem = with_leading_axis(
        comp.memory.init([("conv", np.zeros((3, 3, 16, 16), np.float32))]), W)

    f = _exchange_fn(dist, mesh8)
    out, mem1 = f({"conv": jnp.asarray(g)}, mem, jax.random.PRNGKey(0))

    # every worker's decompressed gradient is identical
    for w in range(1, W):
        np.testing.assert_array_equal(np.asarray(out["conv"][0]),
                                      np.asarray(out["conv"][w]))

    # oracle: step-1 velocity == grad; transmitted coords are those whose
    # velocity was zeroed; the exchanged grad is their sum / W
    vec = g.reshape(W, -1)
    expected = np.zeros(numel, np.float32)
    ns = comp.attributes["conv"].num_selects
    for w in range(W):
        sent = np.asarray(mem1["velocities"]["conv"][w]) == 0
        assert sent.sum() <= ns
        expected[sent] += vec[w][sent]
    expected /= W
    np.testing.assert_allclose(np.asarray(out["conv"][0]).reshape(-1),
                               expected, atol=1e-6)


def test_dgc_mixed_dense_and_sparse(mesh8):
    """dim>1 params go sparse; 1-D params dense with post-average momentum
    correction (reference train.py:136-140, compression.py:198)."""
    comp = DGCCompressor(0.01, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize([("w", (4096, (64, 64)))])  # bias NOT registered
    dist = DistributedOptimizer(dgc_sgd(0.1, momentum=0.9), comp,
                                world_size=W)
    g_w = np.random.RandomState(2).randn(W, 64, 64).astype(np.float32)
    g_b = np.ones((W, 64), np.float32) * 2.0
    mem = with_leading_axis(comp.memory.init(
        [("w", np.zeros((64, 64), np.float32)),
         ("b", np.zeros((64,), np.float32))]), W)
    f = _exchange_fn(dist, mesh8)
    out, mem1 = f({"w": jnp.asarray(g_w), "b": jnp.asarray(g_b)}, mem,
                  jax.random.PRNGKey(0))
    # dense: average (=2) then mmt = 0*m + 2 → 2
    np.testing.assert_allclose(np.asarray(out["b"][0]), 2.0, rtol=1e-6)
    # dense-path momentum advanced in memory
    np.testing.assert_allclose(np.asarray(mem1["momentums"]["b"][0]), 2.0,
                               rtol=1e-6)
    # sparse side produced a (mostly) sparse result
    nz = np.count_nonzero(np.asarray(out["w"][0]))
    assert nz <= W * comp.attributes["w"].num_selects


def test_fused_vs_unfused_identical(mesh8):
    comp = DGCCompressor(0.05, memory=DGCSGDMemory(momentum=0.9))
    comp.initialize([("a", (1024, (32, 32))), ("c", (2048, (2, 32, 32)))])
    rng = np.random.RandomState(3)
    g = {"a": jnp.asarray(rng.randn(W, 32, 32), jnp.float32),
         "c": jnp.asarray(rng.randn(W, 2, 32, 32), jnp.float32)}

    def run(fuse):
        dist = DistributedOptimizer(dgc_sgd(0.1), comp, world_size=W,
                                    fuse_payloads=fuse)
        mem = with_leading_axis(comp.memory.init(
            [("a", np.zeros((32, 32), np.float32)),
             ("c", np.zeros((2, 32, 32), np.float32))]), W)
        f = _exchange_fn(dist, mesh8)
        out, _ = f(g, mem, jax.random.PRNGKey(0))
        return out

    fused, unfused = run(True), run(False)
    for k in fused:
        np.testing.assert_array_equal(np.asarray(fused[k]),
                                      np.asarray(unfused[k]))


def test_global_clip_helpers(mesh8):
    from dgc_tpu.utils.clip_grad import (
        clip_grad_norm_2_by_global,
        clip_grad_value_by_global_norm,
    )

    def worker(g):
        g = g[0]
        out1 = clip_grad_norm_2_by_global(g, 1.0, axis_name="data")
        out2 = clip_grad_value_by_global_norm(g, axis_name="data")
        return out1[None], out2[None]

    f = jax.jit(shard_map(worker, mesh=mesh8, in_specs=(P("data"),),
                              out_specs=(P("data"), P("data")),
                              check_vma=False))
    g = np.full((W, 4), 2.0, np.float32)
    out1, out2 = f(jnp.asarray(g))
    # global sq-sum per worker = 16, mean = 16, norm = 4 → scaled by 1/4
    np.testing.assert_allclose(np.asarray(out1[0]), 0.5, rtol=1e-5)
    # clip value = 4 → unchanged
    np.testing.assert_allclose(np.asarray(out2[0]), 2.0, rtol=1e-5)
