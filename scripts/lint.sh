#!/usr/bin/env bash
# Fast AST-only dgclint pass (no jax import, milliseconds) — the
# edit-loop companion to the full `python -m dgc_tpu.analysis --gate`
# wired into scripts/t1.sh. Extra args pass through, e.g.:
#   scripts/lint.sh --show-allowed
#   scripts/lint.sh bench.py scripts   # lint beyond the default roots
set -e
cd "$(dirname "$0")/.."
exec python -m dgc_tpu.analysis --lint "$@"
