#!/usr/bin/env bash
# Fast AST-only dgclint pass (no jax import, milliseconds) — the
# edit-loop companion to the full `python -m dgc_tpu.analysis --gate
# --verify` wired into scripts/t1.sh. Extra args pass through, e.g.:
#   scripts/lint.sh --show-allowed
#   scripts/lint.sh bench.py scripts   # lint beyond the default roots
#   scripts/lint.sh --fast             # lint + race lint + trace-only
#                                      # dgcver passes (skips the
#                                      # compile-needing donation pass;
#                                      # a few seconds)
set -e
cd "$(dirname "$0")/.."
if [[ "$1" == "--fast" ]]; then
    shift
    exec env JAX_PLATFORMS=cpu python -m dgc_tpu.analysis \
        --lint --race --verify --fast "$@"
fi
exec python -m dgc_tpu.analysis --lint --race "$@"
